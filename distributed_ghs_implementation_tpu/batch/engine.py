"""BatchEngine: the queue + supervised execution behind batched solves.

Two entry points share one execution core:

* :meth:`BatchEngine.solve_many` — synchronous: a whole request list is
  formed into batches immediately (no waiting) and solved; the serving
  scheduler's ``solve_batch`` miss path and the public
  ``minimum_spanning_forest_batch`` both land here.
* :meth:`BatchEngine.submit` — asynchronous: one graph joins the forming
  queue and waits up to ``policy.max_wait_s`` for same-bucket lane-mates
  (a full bucket dispatches immediately); concurrent cache-miss ``solve``
  requests coalesce into device batches this way.

Execution is supervised in the round-6 spirit but batch-shaped: a formed
batch retries on *transient* failure (same classification and backoff as
``utils.resilience``), and when retries exhaust it degrades to per-lane
single-graph solves under the full supervisor ladder — so one poisoned
lane (or one injected ``batch.attempt`` fault) never fails its lane-mates,
and every lane's incidents stay separately attributable. Non-transient
errors raise immediately (programming errors must not be papered over).

``solve_many`` is **pipelined** (``policy.pipeline_depth``, default
double-buffered): a background former thread stacks batch *k+1*'s host
arrays while batch *k* executes on the device, handing off through a
bounded queue — the device never waits on host-side padding/stacking.
Results, retries, and incident handling are identical to the synchronous
path: execution still runs through the same supervised core, and the
former thread touches no device state.

Telemetry (``batch.*`` on the obs bus — docs/OBSERVABILITY.md):
``batch.solve`` spans; ``batch.batches.formed`` / ``batch.lanes.formed`` /
``batch.bypass`` / ``batch.retry`` / ``batch.lane.fallback`` /
``batch.compile.hit|miss`` / ``batch.pipeline.batches`` counters;
``batch.fill_ratio``, ``batch.queue.wait_s``, ``batch.form_s``, and
``batch.pipeline.stall_s`` histograms; ``batch.queue.depth`` samples.
"""

from __future__ import annotations

import queue as queue_mod
import threading
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from distributed_ghs_implementation_tpu.api import (
    MSTResult,
    minimum_spanning_forest,
)
from distributed_ghs_implementation_tpu.batch.lanes import (
    StackedBatch,
    bucket_key,
    execute_stacked,
    stack_lanes,
)
from distributed_ghs_implementation_tpu.batch.policy import BatchPolicy
from distributed_ghs_implementation_tpu.graphs.edgelist import Graph
from distributed_ghs_implementation_tpu.obs.events import BUS
from distributed_ghs_implementation_tpu.obs.slo import (
    current_class,
    current_kind,
)
from distributed_ghs_implementation_tpu.utils.resilience import (
    FAULTS,
    IncidentLog,
    Supervisor,
    SupervisorConfig,
    is_transient,
)

# Ceiling on distinct per-class queue-wait histogram names one engine will
# create (each histogram is permanent process state on the global bus).
_CLS_HIST_CAP = 16


class PendingSolve:
    """One submitted solve; ``wait()`` blocks until its batch lands.

    ``cls`` snapshots the submitting request's SLO class tag
    (``obs.slo.current_class``) — the worker thread that eventually forms
    the batch has no request context of its own, so queue-wait telemetry
    is attributed from the tag captured here at submit time. ``kind``
    snapshots the analytics query kind the same way (``None`` == mst):
    batch forming keys on it, so lanes stay kind-homogeneous.
    """

    __slots__ = ("graph", "event", "result", "error", "enqueued_at", "cls",
                 "kind")

    def __init__(self, graph: Graph):
        self.graph = graph
        self.event = threading.Event()
        self.result: Optional[MSTResult] = None
        self.error: Optional[BaseException] = None
        self.enqueued_at = time.monotonic()
        self.cls = current_class()
        self.kind = current_kind()

    def wait(self, timeout: Optional[float] = None) -> MSTResult:
        if not self.event.wait(timeout):
            raise TimeoutError("batched solve did not complete in time")
        if self.error is not None:
            raise self.error
        assert self.result is not None
        return self.result


class BatchEngine:
    """Forms, supervises, and unpacks multi-graph device batches."""

    def __init__(
        self,
        *,
        policy: Optional[BatchPolicy] = None,
        supervisor_config: Optional[SupervisorConfig] = None,
        clock=time.monotonic,
        sleep=time.sleep,
    ):
        self.policy = policy or BatchPolicy()
        self.config = supervisor_config or SupervisorConfig()
        self._clock = clock
        self._sleep = sleep
        self._dispatch = threading.Lock()  # one device batch in flight
        self._cv = threading.Condition()
        self._cls_seen: set = set()  # distinct per-class histogram labels
        self._queue: List[PendingSolve] = []
        self._worker: Optional[threading.Thread] = None
        self._closed = False

    # ------------------------------------------------------------------
    # Synchronous entry
    # ------------------------------------------------------------------
    def solve_many(self, graphs: Sequence[Graph]) -> List[MSTResult]:
        """Solve a request list; results in input order.

        Forms batches immediately (the caller already holds the whole
        list, so there is nothing to wait for); non-admitted graphs bypass
        to supervised single-graph solves. With ``policy.pipeline_depth >=
        2`` and more than one formed batch, forming is pipelined: batch
        *k+1* stacks on a background thread while batch *k* executes.
        """
        graphs = list(graphs)
        results: List[Optional[MSTResult]] = [None] * len(graphs)
        batches, bypass = self.policy.form(graphs)
        if (
            self.policy.pipeline_depth >= 2
            and len(batches) >= 2
            and self._pipeline_worthwhile(batches)
        ):
            self._solve_batches_pipelined(graphs, batches, results)
        else:
            for fb in batches:
                members = [graphs[i] for i in fb.indices]
                for i, result in zip(fb.indices, self._solve_formed(members)):
                    results[i] = result
        for i in bypass:
            BUS.count("batch.bypass")
            results[i] = self._solve_single(graphs[i])
        return results  # type: ignore[return-value]

    def _pipeline_worthwhile(self, batches) -> bool:
        """Is there enough host stacking per batch to hide behind device
        execution? A batch's stacked arrays hold ``8 * lanes * m_pad``
        int32 elements (3 edge-slot arrays of ``2 * m_pad`` + 2 rank
        arrays of ``m_pad``, all times ``lanes``); below the policy floor
        the former thread's handoff overhead outweighs the overlap
        (docs/BENCH_NOTES.md "Round 10" has the measurements)."""
        lanes = self.policy.max_lanes
        return any(
            8 * lanes * fb.key[1] >= self.policy.pipeline_min_stack_elems
            for fb in batches
        )

    def _solve_batches_pipelined(
        self, graphs: List[Graph], batches, results: List[Optional[MSTResult]]
    ) -> None:
        """Double-buffered dispatch: one background former thread stacks
        upcoming batches' host arrays into a bounded handoff queue
        (capacity ``pipeline_depth - 1``) while this thread executes.

        The former does pure host work (``stack_lanes`` touches no device
        state and no shared caches), so overlap is safe; execution itself
        still runs through :meth:`_solve_formed`'s retry/fallback ladder,
        keeping results and incidents identical to the synchronous path. A
        forming error is delivered as a ``None`` stack and reproduced by
        re-stacking on this thread — stacking is deterministic, so the
        error surfaces with exactly the synchronous path's classification
        and incident records.
        """
        handoff: "queue_mod.Queue" = queue_mod.Queue(
            maxsize=max(1, self.policy.pipeline_depth - 1)
        )
        stop = threading.Event()

        def former() -> None:
            for fb in batches:
                # The WHOLE per-batch body is guarded: an unexpected error
                # (bad indices from a broken policy, an obs exporter blowing
                # up) must reach the dispatcher as an item, never kill this
                # thread silently — a dead former would hang the timeout-
                # less handoff.get() forever.
                try:
                    members = [graphs[i] for i in fb.indices]
                    t0 = self._clock()
                    try:
                        stacked = stack_lanes(
                            members, lanes=self.policy.max_lanes,
                            mode=self.policy.mode,
                        )
                    except BaseException:  # noqa: BLE001 — redone at dispatch
                        stacked = None  # deterministic: re-raised by re-stack
                    BUS.record("batch.form_s", self._clock() - t0)
                    item: object = (fb, members, stacked)
                except BaseException as e:  # noqa: BLE001 — raised at dispatch
                    item = e
                while not stop.is_set():
                    try:
                        handoff.put(item, timeout=0.05)
                        break
                    except queue_mod.Full:
                        continue
                if stop.is_set():
                    return

        thread = threading.Thread(target=former, name="batch-former", daemon=True)
        thread.start()
        try:
            for _ in range(len(batches)):
                t0 = self._clock()
                got = handoff.get()
                if isinstance(got, BaseException):
                    raise got  # the sync path would have raised it here too
                fb, members, stacked = got
                BUS.record("batch.pipeline.stall_s", self._clock() - t0)
                BUS.count("batch.pipeline.batches")
                for i, result in zip(
                    fb.indices, self._solve_formed(members, stacked=stacked)
                ):
                    results[i] = result
        finally:
            stop.set()
            while thread.is_alive():
                try:  # unblock a former stuck on a full handoff queue
                    handoff.get_nowait()
                except queue_mod.Empty:
                    pass
                thread.join(timeout=0.05)

    # ------------------------------------------------------------------
    # Asynchronous entry (the scheduler's per-request miss path)
    # ------------------------------------------------------------------
    def submit(self, graph: Graph) -> PendingSolve:
        """Queue one solve for lane-forming; returns a waitable handle.

        Non-admitted graphs solve inline in the calling thread (there is
        no batch to wait for) and return an already-completed handle.
        """
        pending = PendingSolve(graph)
        pending.enqueued_at = self._clock()  # queue timing honors the
        if not self.policy.admits(graph):    # injectable clock throughout
            BUS.count("batch.bypass")
            try:
                pending.result = self._solve_single(graph)
            except BaseException as e:  # noqa: BLE001 — delivered via wait()
                pending.error = e
            pending.event.set()
            return pending
        with self._cv:
            if self._closed:
                raise RuntimeError("BatchEngine is closed")
            self._queue.append(pending)
            BUS.sample("batch.queue.depth", len(self._queue))
            if self._worker is None:
                self._worker = threading.Thread(
                    target=self._worker_loop, name="batch-engine", daemon=True
                )
                self._worker.start()
            self._cv.notify_all()
        return pending

    def close(self) -> None:
        """Stop accepting submissions and drain the queue."""
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        if self._worker is not None:
            self._worker.join(timeout=30)

    # ------------------------------------------------------------------
    # Worker: the forming window
    # ------------------------------------------------------------------
    def _take_batch(self) -> Optional[List[PendingSolve]]:
        """Under the lock: pop a full bucket, or the oldest item's bucket
        once its wait expires. ``None`` means keep waiting.

        The forming key is ``(kind, shape bucket)``: every admitted solve
        is a plain MSF solve regardless of query kind (components submits
        its index-weighted twin), so mixing kinds would be *numerically*
        fine — homogeneity is kept so one lane-mate's failure, retry, or
        supervision incident never blurs across kinds in the per-kind SLO
        and incident telemetry (docs/ANALYTICS.md).
        """
        if not self._queue:
            return None
        by_bucket: Dict[tuple, List[PendingSolve]] = {}
        for p in self._queue:
            by_bucket.setdefault(
                (p.kind, bucket_key(p.graph)), []
            ).append(p)
        for members in by_bucket.values():
            if len(members) >= self.policy.max_lanes:
                return members[: self.policy.max_lanes]
        oldest = self._queue[0]
        if self._clock() - oldest.enqueued_at >= self.policy.max_wait_s:
            members = by_bucket[(oldest.kind, bucket_key(oldest.graph))]
            return members[: self.policy.max_lanes]
        return None

    def _worker_loop(self) -> None:
        while True:
            with self._cv:
                batch = self._take_batch()
                while batch is None:
                    if self._closed and not self._queue:
                        return
                    if self._queue:
                        headroom = self.policy.max_wait_s - (
                            self._clock() - self._queue[0].enqueued_at
                        )
                        self._cv.wait(timeout=max(headroom, 0.0005))
                    else:
                        self._cv.wait()
                    batch = self._take_batch()
                for p in batch:
                    self._queue.remove(p)
                BUS.sample("batch.queue.depth", len(self._queue))
            now = self._clock()
            for p in batch:
                wait_s = now - p.enqueued_at
                BUS.record("batch.queue.wait_s", wait_s)
                if p.cls is not None and (
                    p.cls in self._cls_seen
                    or len(self._cls_seen) < _CLS_HIST_CAP
                ):
                    # Per-class forming-queue wait: histograms survive ring
                    # overflow, so obs.slo can attach this to each class's
                    # report even on long drills (obs/slo.py joins on the
                    # "batch.queue.wait_s.<cls>" name). Distinct labels are
                    # capped — histograms live forever in the process-global
                    # bus, and the label ultimately comes from request JSON.
                    self._cls_seen.add(p.cls)
                    BUS.record(f"batch.queue.wait_s.{p.cls}", wait_s)
            try:
                results = self._solve_formed([p.graph for p in batch])
                for p, result in zip(batch, results):
                    p.result = result
            except BaseException as e:  # noqa: BLE001 — delivered via wait()
                for p in batch:
                    p.error = e
            finally:
                for p in batch:
                    p.event.set()

    # ------------------------------------------------------------------
    # Execution core
    # ------------------------------------------------------------------
    def _solve_formed(
        self,
        graphs: List[Graph],
        stacked: Optional[StackedBatch] = None,
    ) -> List[MSTResult]:
        """One same-bucket batch: lane solve with retry, then per-lane
        fallback isolation. Results in input order.

        ``stacked`` carries pre-formed host arrays from the pipelined
        former; when absent (synchronous path, or a former that failed)
        the stack is built here, inside the attempt's error classification.
        A retry re-dispatches the same immutable stack without re-forming.
        """
        lanes = self.policy.max_lanes
        n_pad, m_pad = bucket_key(graphs[0])
        BUS.count("batch.batches.formed")
        BUS.count("batch.lanes.formed", len(graphs))
        BUS.record("batch.fill_ratio", len(graphs) / lanes)
        log = IncidentLog()
        with BUS.span(
            "batch.solve", cat="batch",
            bucket_n=n_pad, bucket_m=m_pad, lanes=len(graphs), max_lanes=lanes,
        ) as span:
            for attempt in range(1, self.config.retries_per_rung + 2):
                t0 = self._clock()
                try:
                    FAULTS.fire("batch.attempt")
                    if stacked is None:
                        stacked = stack_lanes(
                            graphs, lanes=lanes, mode=self.policy.mode
                        )
                    with self._dispatch:
                        solved = execute_stacked(stacked)
                except Exception as e:  # noqa: BLE001 — classified below
                    if not is_transient(e):
                        log.add(
                            rung="batch", attempt=attempt, outcome="fatal",
                            error=repr(e), elapsed_s=self._clock() - t0,
                            site="batch.attempt",
                        )
                        raise
                    retrying = attempt <= self.config.retries_per_rung
                    backoff = 0.0
                    if retrying:
                        backoff = min(
                            self.config.backoff_base_s * (2 ** (attempt - 1)),
                            self.config.backoff_cap_s,
                        )
                    log.add(
                        rung="batch", attempt=attempt, outcome="transient",
                        error=repr(e), elapsed_s=self._clock() - t0,
                        backoff_s=backoff, site="batch.attempt",
                    )
                    BUS.count("batch.retry")
                    if retrying and backoff > 0:
                        self._sleep(backoff)
                    continue
                wall = self._clock() - t0
                log.add(
                    rung="batch", attempt=attempt, outcome="ok", elapsed_s=wall
                )
                span.set(attempts=attempt, outcome="ok")
                incidents = log if len(log) > 1 else None
                return [
                    self._lane_result(g, *out, wall=wall, incidents=incidents)
                    for g, out in zip(graphs, solved)
                ]
            # Retries exhausted: isolate lanes — each graph gets its own
            # supervised solve so one bad lane cannot fail its lane-mates.
            span.set(outcome="lane_fallback")
            return [self._fallback_lane(g, log) for g in graphs]

    def _lane_result(
        self, graph, edge_ids, fragment, levels, *, wall, incidents
    ) -> MSTResult:
        num_components = (
            int(np.unique(fragment).size) if graph.num_nodes else 0
        )
        return MSTResult(
            graph=graph,
            edge_ids=edge_ids,
            num_levels=levels,
            wall_time_s=wall,
            backend=f"batch/{self.policy.mode}",
            num_components=num_components,
            incidents=incidents,
        )

    def _fallback_lane(self, graph: Graph, batch_log: IncidentLog) -> MSTResult:
        """One isolated lane after batch retries exhausted. The lane's
        incident log keeps the batch-level failure records in front of its
        own supervised attempts (records are already on the bus — they are
        re-linked here, not re-emitted), so a degraded response tells the
        whole story: the batch failed first, then this lane solved alone."""
        BUS.count("batch.lane.fallback")
        result = self._solve_single(graph)
        merged = IncidentLog()
        merged.records = list(batch_log.records)
        if result.incidents is not None:
            merged.records.extend(result.incidents.records)
        result.incidents = merged
        return result

    def _solve_single(self, graph: Graph) -> MSTResult:
        """The bypass/fallback path: one supervised single-graph solve."""
        return minimum_spanning_forest(
            graph, supervised=True, supervisor=Supervisor(self.config)
        )
