"""Raw op throughput on the chip: dispatch overhead, gather, scatter,
segment_min, pointer_jump — the numbers the kernel design trades on."""

from __future__ import annotations

import _bootstrap  # noqa: F401 — repo-root sys.path setup

import time

import jax
import jax.numpy as jnp
import numpy as np


def _sync(out):
    for leaf in jax.tree_util.tree_leaves(out):
        if hasattr(leaf, "ravel") and getattr(leaf, "size", 0):
            np.asarray(leaf.ravel()[0])


def timeit(fn, *args, repeats=5, **kw):
    out = fn(*args, **kw)
    _sync(out)
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        out = fn(*args, **kw)
        _sync(out)
        best = min(best, time.perf_counter() - t0)
    return best


def main():
    rng = np.random.default_rng(0)
    n = 1 << 20

    trivial = jax.jit(lambda x: x + 1)
    t = timeit(trivial, jnp.zeros((), jnp.int32))
    print(f"dispatch overhead (scalar +1)      : {t * 1e3:8.2f} ms")

    table = jnp.asarray(rng.integers(0, n, n, dtype=np.int32))
    for e in (20, 24, 26):
        idx = jnp.asarray(rng.integers(0, n, 1 << e, dtype=np.int32))
        gather = jax.jit(lambda t_, i_: t_[i_])
        t = timeit(gather, table, idx)
        print(f"gather  {1 << e:>11,} from 1M        : {t * 1e3:8.2f} ms  "
              f"({t / (1 << e) * 1e9:5.2f} ns/elem)")

    for e in (20, 24):
        sz = 1 << e
        idx = jnp.asarray(rng.integers(0, sz, sz, dtype=np.int32))
        vals = jnp.asarray(rng.integers(0, 1 << 30, sz, dtype=np.int32))
        sset = jax.jit(lambda i_, v_, s=sz: jnp.zeros(s, jnp.int32).at[i_].set(v_, mode="drop"))
        t = timeit(sset, idx, vals)
        print(f"scatter-set {sz:>11,} -> {sz:>11,}  : {t * 1e3:8.2f} ms  "
              f"({t / sz * 1e9:5.2f} ns/elem)")
        smin = jax.jit(lambda i_, v_, s=sz: jnp.full(s, 2**31 - 1, jnp.int32).at[i_].min(v_))
        t = timeit(smin, idx, vals)
        print(f"scatter-min {sz:>11,} -> {sz:>11,}  : {t * 1e3:8.2f} ms  "
              f"({t / sz * 1e9:5.2f} ns/elem)")

    # segment_min at edge scale into 1M segments (the flat kernel's core).
    for e in (24, 25):
        sz = 1 << e
        seg = jnp.asarray(rng.integers(0, n, sz, dtype=np.int32))
        vals = jnp.asarray(rng.integers(0, 1 << 30, sz, dtype=np.int32))
        f = jax.jit(lambda v_, s_: jax.ops.segment_min(v_, s_, num_segments=n))
        t = timeit(f, vals, seg)
        print(f"segment_min {sz:>11,} -> 1M         : {t * 1e3:8.2f} ms  "
              f"({t / sz * 1e9:5.2f} ns/elem)")
    # sorted-segment variant (CSR order)
    seg_sorted = jnp.sort(seg)
    f2 = jax.jit(
        lambda v_, s_: jax.ops.segment_min(
            v_, s_, num_segments=n, indices_are_sorted=True
        )
    )
    t = timeit(f2, vals, seg_sorted)
    print(f"segment_min sorted {1 << 25:>11,} -> 1M  : {t * 1e3:8.2f} ms")

    # pointer_jump fixed iteration counts on 1M
    parent = jnp.asarray(rng.integers(0, n, n, dtype=np.int32))
    for k in (1, 2, 4, 8):
        f3 = jax.jit(
            lambda p_, k=k: jax.lax.fori_loop(0, k, lambda _, q: q[q], p_)
        )
        t = timeit(f3, parent)
        print(f"pointer jump x{k} on 1M             : {t * 1e3:8.2f} ms")

    # cumsum + compare at 16M (compaction building blocks)
    big = jnp.asarray(rng.integers(0, 2, 1 << 24, dtype=np.int32))
    f4 = jax.jit(lambda b_: jnp.cumsum(b_))
    t = timeit(f4, big)
    print(f"cumsum 16M                         : {t * 1e3:8.2f} ms")


if __name__ == "__main__":
    main()
