"""The offline kernel autotuner (round 23, ``tune/`` + docs/KERNELS.md
"Autotuning"): geometry promotion, search-space validity, record
persistence/staleness, the deterministic CPU-pinned dry search, and the
selector precedence ladder with measured winners installed.

Everything here runs on the CPU backend (conftest pins it), where the
search deterministically pins ``xla`` winners — the same contract CI's
``gate-tune-v1`` byte-checks — so the suite needs no hardware and no
tolerance knobs.
"""

import dataclasses
import json

import pytest

from distributed_ghs_implementation_tpu.batch import lanes as lanes_mod
from distributed_ghs_implementation_tpu.graphs.generators import gnm_random_graph
from distributed_ghs_implementation_tpu.obs.events import BUS
from distributed_ghs_implementation_tpu.ops import pallas_kernels as pk
from distributed_ghs_implementation_tpu.tune import measure as tune_measure
from distributed_ghs_implementation_tpu.tune import record as tune_record
from distributed_ghs_implementation_tpu.tune import space as tune_space
from distributed_ghs_implementation_tpu.tune.measure import mesh_bucket, search
from distributed_ghs_implementation_tpu.tune.record import (
    TuningRecordError,
    install_record,
    load_and_install,
    load_record,
    parse_bucket_key,
    save_record,
)


@pytest.fixture(autouse=True)
def _clean_kernel_state(monkeypatch):
    """Round-15 shield: no ambient GHS_KERNEL, no sticky fallback, no
    leftover tuned state or geometry from another test."""
    monkeypatch.delenv("GHS_KERNEL", raising=False)
    pk._reset_for_tests()
    yield
    pk._reset_for_tests()


@pytest.fixture()
def bus():
    BUS.enable()
    BUS.clear()
    yield BUS
    BUS.enable()
    BUS.clear()


# ----------------------------------------------------------------------
# Satellite 1: KernelGeometry promotion + boundary validation
# ----------------------------------------------------------------------
def test_geometry_defaults_match_promoted_constants():
    g = pk.DEFAULT_GEOMETRY
    assert g.table_max_elems == 1 << 20
    assert g.hook_max_nodes == 1 << 19
    assert g.ell_block_elems == 1 << 15
    assert g.flat_block_rows == 256


def test_geometry_json_round_trip():
    g = pk.KernelGeometry(flat_block_rows=512)
    assert pk.KernelGeometry.from_json(g.to_json()) == g


def test_geometry_from_json_rejects_unknown_fields():
    with pytest.raises((TypeError, ValueError)):
        pk.KernelGeometry.from_json({"flat_block_rows": 256, "bogus": 1})


@pytest.mark.parametrize("field", [
    "table_max_elems", "hook_max_nodes", "ell_block_elems",
    "flat_block_rows",
])
def test_geometry_rejects_non_power_of_two_and_nonpositive(field):
    for bad in (0, -8, 3, 1000):
        with pytest.raises(ValueError):
            pk.KernelGeometry(**{field: bad})
    with pytest.raises((TypeError, ValueError)):
        pk.KernelGeometry(**{field: True})


@pytest.mark.parametrize("field,ceiling", [
    ("table_max_elems", 1 << 22),
    ("hook_max_nodes", 1 << 20),
    ("ell_block_elems", 1 << 18),
    ("flat_block_rows", 1 << 12),
])
def test_geometry_ceilings_are_inclusive_boundaries(field, ceiling):
    # Exactly at the VMEM ceiling is valid; one power-of-two past is not.
    pk.KernelGeometry(**{field: ceiling})
    with pytest.raises(ValueError):
        pk.KernelGeometry(**{field: ceiling * 2})


def test_set_geometry_rejects_wrong_type_and_scope_restores():
    with pytest.raises(TypeError):
        pk.set_geometry({"flat_block_rows": 256})
    custom = pk.KernelGeometry(hook_max_nodes=1 << 18)
    with pk.geometry_scope(custom):
        assert pk.geometry() is custom
        assert not pk.hook_shape_ok((1 << 18) + 1)
    assert pk.geometry() == pk.DEFAULT_GEOMETRY
    assert pk.hook_shape_ok((1 << 18) + 1)


def test_shape_guards_at_divisibility_and_vmem_edges():
    g = pk.KernelGeometry()
    # Table ceiling is inclusive; the flat guard also demands whole lanes.
    assert pk.ell_shape_ok(g.table_max_elems, 8, 8, geom=g)
    assert not pk.ell_shape_ok(g.table_max_elems + 1, 8, 8, geom=g)
    assert pk.flat_shape_ok(64, 128, geom=g)
    assert not pk.flat_shape_ok(64, 127, geom=g)  # not lane-divisible
    assert not pk.flat_shape_ok(64, 0, geom=g)
    assert pk.hook_shape_ok(g.hook_max_nodes, geom=g)
    assert not pk.hook_shape_ok(g.hook_max_nodes + 1, geom=g)


def test_explicit_geom_beats_installed_geometry():
    small = pk.KernelGeometry(table_max_elems=1 << 10)
    pk.set_geometry(small)
    try:
        assert not pk.flat_shape_ok(1 << 12, 1 << 13)  # installed: too big
        assert pk.flat_shape_ok(1 << 12, 1 << 13, geom=pk.DEFAULT_GEOMETRY)
    finally:
        pk.set_geometry(None)


# ----------------------------------------------------------------------
# tune/space.py: candidate enumeration
# ----------------------------------------------------------------------
def test_enumerate_candidates_xla_first_deterministic_and_valid():
    a = tune_space.enumerate_candidates(256, 1024, 4, "fused")
    b = tune_space.enumerate_candidates(256, 1024, 4, "fused")
    assert a == b
    assert a[0].kernel == "xla"
    assert all(c.kernel == "pallas" for c in a[1:])
    assert len(a) <= tune_space.raw_space_size("fused")
    assert len({c.label() for c in a}) == len(a)


def test_invalid_geometries_are_filtered_not_scored():
    # A bucket bigger than the smallest table ceiling in the grid would
    # admit fewer candidates than the raw grid; validity is a hard gate.
    small = tune_space.enumerate_candidates(64, 256, 2, "fused")
    assert all(
        tune_space.candidate_valid(c.geometry, 64, 256, 2, "fused")
        for c in small
    )
    with pytest.raises(ValueError):
        tune_measure.normalize_buckets([(64, 256, 2, "warp")])


def test_normalize_buckets_dedupes_and_sorts():
    out = tune_measure.normalize_buckets(
        [(256, 1024, 4, "fused"), (64, 256, 0, "fused"),
         (256, 1024, 4, "fused")]
    )
    assert out == [(64, 256, 0, "fused"), (256, 1024, 4, "fused")]


def test_mesh_bucket_mirrors_lane_padding():
    from distributed_ghs_implementation_tpu.models.boruvka import _bucket_size

    b = mesh_bucket(70_000, 140_000, 8)
    n_pad, m_pad, n_dev, mode = b
    assert (n_dev, mode) == (8, "mesh")
    assert n_pad == _bucket_size(70_000)
    assert m_pad >= _bucket_size(140_000) and m_pad % (8 * 8) == 0


# ----------------------------------------------------------------------
# tune/measure.py: the dry (pinned) search
# ----------------------------------------------------------------------
BUCKETS = [(64, 256, 2, "fused"), (64, 256, 0, "fused")]


def test_dry_search_is_deterministic_and_cpu_pins_xla(bus):
    rec_a = search(BUCKETS, dry=True)
    rec_b = search(BUCKETS, dry=True)
    assert rec_a == rec_b
    assert rec_a["pinned"] is True
    for key, entry in rec_a["entries"].items():
        assert entry["kernel"] == "xla", key
        assert entry["source"] == "cpu-pin"
        assert entry["parity"] in ("ok", "skipped")
    counters = bus.counters()
    assert counters.get("tune.search.candidate", 0) > 0


def test_search_scores_bad_candidate_dead_without_global_fallback(bus,
                                                                  monkeypatch):
    # A candidate that explodes at compile time must be rejected in place
    # — never tripping the process-wide sticky disable_pallas.
    real = tune_measure._make_runner

    def bomb(bucket, candidate, graph):
        if candidate.kernel == "pallas":
            raise RuntimeError("mosaic says no")
        return real(bucket, candidate, graph)

    monkeypatch.setattr(tune_measure, "_make_runner", bomb)
    rec = search([(64, 256, 2, "fused")], dry=True)
    entry = next(iter(rec["entries"].values()))
    assert entry["kernel"] == "xla"
    assert bus.counters().get("tune.search.rejected", 0) >= 1
    assert pk.kernel_choice("pallas") == "pallas"  # still not disabled


def test_unreachable_bucket_reports_probe_heuristic():
    # Padded edge count beyond C(n,2): no simple graph can land there.
    rec = search([(4, 1024, 0, "fused")], dry=True)
    entry = rec["entries"]["4x1024x0xfused"]
    assert entry["source"] == "unreachable"
    assert entry["kernel"] in ("pallas", "xla")


# ----------------------------------------------------------------------
# tune/record.py: persistence, staleness, integrity
# ----------------------------------------------------------------------
def test_record_save_is_byte_deterministic_and_round_trips(tmp_path, bus):
    rec = search(BUCKETS, dry=True)
    p1, p2 = str(tmp_path / "a.json"), str(tmp_path / "b.json")
    save_record(rec, p1)
    save_record(rec, p2)
    assert (tmp_path / "a.json").read_bytes() == (tmp_path / "b.json").read_bytes()
    assert (tmp_path / "a.json.sha256").exists()
    loaded = load_record(p1)
    assert loaded == rec
    assert bus.counters().get("tune.record.hit", 0) >= 1


def test_missing_record_is_a_miss_not_an_error(tmp_path, bus):
    assert load_record(str(tmp_path / "nope.json")) is None
    assert bus.counters().get("tune.record.miss", 0) == 1


@pytest.mark.parametrize("field,value", [
    ("fingerprint", "other-machine-0000"),
    ("jax_version", "0.0.1"),
    ("backend", "tpu"),
    ("probe_ok", None),
])
def test_stale_records_degrade_to_none(tmp_path, bus, field, value):
    rec = search(BUCKETS, dry=True)
    if field == "probe_ok":
        rec["probe_ok"] = not rec["probe_ok"]
    else:
        rec[field] = value
    path = str(tmp_path / "stale.json")
    save_record(rec, path)
    assert load_record(path) is None
    assert bus.counters().get("tune.record.stale", 0) == 1
    assert load_and_install(path) == 0
    assert pk.tuned_summary() is None or not pk.tuned_summary()


def test_corrupt_record_quarantines(tmp_path, bus):
    rec = search(BUCKETS, dry=True)
    path = str(tmp_path / "rot.json")
    save_record(rec, path)
    raw = bytearray((tmp_path / "rot.json").read_bytes())
    raw[len(raw) // 2] ^= 0x40  # bit rot inside the payload
    (tmp_path / "rot.json").write_bytes(bytes(raw))
    assert load_record(path) is None
    assert bus.counters().get("tune.record.quarantined", 0) == 1


def test_malformed_record_raises_typed_error(tmp_path):
    path = tmp_path / "bad.json"
    path.write_text(json.dumps({"schema": "ghs-tuning-v1", "entries": {
        "64x256x2xfused": {"kernel": "cuda"},
    }}))
    with pytest.raises(TuningRecordError):
        load_record(str(path))
    path2 = tmp_path / "worse.json"
    path2.write_text(json.dumps({"schema": "something-else"}))
    with pytest.raises(TuningRecordError):
        load_record(str(path2))


def test_bucket_key_round_trip_and_rejection():
    b = (256, 1024, 4, "fused")
    assert parse_bucket_key(tune_record.bucket_key_str(b)) == b
    for bad in ("256x1024", "axbxcxd", "1x2x3xwarp"):
        with pytest.raises(TuningRecordError):
            parse_bucket_key(bad)


def test_install_record_applies_consensus_geometry_only():
    geom = tune_space.Candidate(
        kernel="pallas",
        geometry=pk.KernelGeometry(flat_block_rows=512),
    ).geometry
    entries = {
        (64, 256, 2, "fused"): {
            "kernel": "pallas", "source": "measured",
            "geometry": geom.to_json(),
        },
        (64, 256, 0, "fused"): {
            "kernel": "xla", "source": "measured",
            "geometry": pk.DEFAULT_GEOMETRY.to_json(),
        },
    }
    rec = tune_record.new_record(entries, pinned=False)
    assert install_record(rec) == 2
    assert pk.geometry().flat_block_rows == 512  # single pallas consensus
    pk._reset_for_tests()

    split = dict(entries)
    split[(128, 512, 2, "fused")] = {
        "kernel": "pallas", "source": "measured",
        "geometry": pk.KernelGeometry(flat_block_rows=128).to_json(),
    }
    install_record(tune_record.new_record(split, pinned=False))
    assert pk.geometry() == pk.DEFAULT_GEOMETRY  # split verdict: default


# ----------------------------------------------------------------------
# Satellite 3: selector precedence with a TuningRecord installed
# ----------------------------------------------------------------------
BUCKET = (64, 256, 2, "fused")


def _install(winner="xla"):
    pk.set_tuned_kernels({BUCKET: winner}, source={"test": True})


def test_measured_tier_needs_bucket_and_record(bus):
    _install("xla")
    assert pk.kernel_choice(None) == pk.kernel_choice()  # no bucket: probe
    assert pk.kernel_choice(None, bucket=BUCKET) == "xla"
    assert bus.counters().get("kernel.selected.measured", 0) == 1
    assert pk.kernel_choice(None, bucket=(1, 2, 3, "fused")) == \
        pk.kernel_choice()  # unknown bucket: probe heuristic


def test_per_solve_override_beats_measured():
    _install("xla")
    assert pk.kernel_choice("pallas", bucket=BUCKET) == "pallas"


def test_set_default_kernel_beats_measured():
    _install("xla")
    pk.set_default_kernel("pallas")
    assert pk.kernel_choice(None, bucket=BUCKET) == "pallas"


def test_env_var_beats_measured(monkeypatch):
    _install("pallas")
    monkeypatch.setenv("GHS_KERNEL", "xla")
    assert pk.kernel_choice(None, bucket=BUCKET) == "xla"


def test_sticky_disable_pallas_overrides_measured_pallas_winner(bus):
    _install("pallas")
    assert pk.kernel_choice(None, bucket=BUCKET) == "pallas"
    pk.disable_pallas("test: mosaic fault")
    assert pk.kernel_choice(None, bucket=BUCKET) == "xla"
    # Measurements steer; they never un-break a disabled process.
    assert bus.counters().get("kernel.selected.measured", 0) == 1


def test_measured_tier_is_load_bearing_through_solve_lanes(tmp_path, bus):
    rec = search(BUCKETS, dry=True)
    path = str(tmp_path / "t.json")
    save_record(rec, path)
    assert load_and_install(path) == len(BUCKETS)
    g = gnm_random_graph(60, 200, seed=3)
    before = bus.counters().get("kernel.selected.measured", 0)
    ids_tuned = [r[0] for r in lanes_mod.solve_lanes([g, g], lanes=2)]
    assert bus.counters().get("kernel.selected.measured", 0) > before
    ids_xla = [r[0] for r in lanes_mod.solve_lanes([g, g], lanes=2,
                                                   kernel="xla")]
    for a, b in zip(ids_tuned, ids_xla):
        assert (a == b).all()


def test_kernel_report_carries_tuned_and_geometry_stanzas():
    _install("xla")
    rep = pk.kernel_report()
    assert rep["tuned"]["entries"] == 1
    assert rep["geometry"] == pk.DEFAULT_GEOMETRY.to_json()
