#!/usr/bin/env python
"""Serve drill: drive the MST query service and check every answer.

Three modes:

* ``--smoke`` — the CI gate: start ``ghs serve`` as a subprocess, drive the
  JSONL protocol over its pipes (solve -> update -> repeat the original
  solve), and assert the repeat is answered from cache — both via the
  response's ``cached`` flag and via the ``serve.store.hit`` counter in the
  ``stats`` op (the obs-bus proof that no solver ran).
* ``--warmup-smoke`` — the warm-path gate: start ``ghs serve`` with
  ``--batch-lanes`` and ``--warmup-buckets`` covering the drill's graph
  shape, drive two distinct solves on that bucket, and assert via the
  ``compile.*`` counters in ``stats`` that the warmup compiled
  (``compile.warmup >= 1``) and the query phase compiled NOTHING
  (no ``compile.miss``) — the "zero request-time XLA compiles" acceptance
  from docs/SERVING.md. The report carries the compile counters (CI
  uploads it as the compile-cache stats artifact).
* ``--sharded-smoke`` — the oversize-path gate (8-device dryrun in CI):
  start ``ghs serve --sharded-lane --warmup-mesh-buckets`` covering the
  drill's OVERSIZE shape, drive an oversize deck (miss -> repeat ->
  distinct miss -> incremental update -> repeat) and assert the solves
  executed on the mesh (``backend == "sharded_lane"``), repeats were
  store hits, the update rode the donated-buffer residency path
  (``lane.update.donated``), and the query phase compiled NOTHING
  (``compile.miss == 0`` — the same zero-request-time-compiles property,
  now on the oversize path; docs/SHARDED_LANE.md).
* default — an in-process replay: a seeded random graph, then ``--updates``
  random insert/delete/reweight requests through :class:`MSTService`, every
  response's MST weight checked against the SciPy oracle on an
  independently-maintained mirror of the edge set. ``--chaos`` arms
  ``GHS_FAULT_*``-style faults first (supervisor retries on the miss path,
  torn cache writes when ``--disk-cache`` is set), so the drill doubles as
  the serving layer's game-day. Armed ``GHS_FAULT_*`` environment variables
  are honored in both modes.

Exit code 0 iff every check passed. ``--output`` writes a JSON report.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

import numpy as np

try:
    import _bootstrap  # noqa: F401 — repo-root sys.path setup
except ImportError:  # loaded by file path: tools/ is not sys.path[0] then
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _seed_graph(nodes: int, edges: int, seed: int):
    from distributed_ghs_implementation_tpu.graphs.generators import (
        gnm_random_graph,
    )

    return gnm_random_graph(nodes, edges, seed=seed)


def _slo_section(stats, wall_s: float, stats_response: dict = None) -> dict:
    """The drill's per-class summary — the SAME ``ghs-slo-summary-v1``
    schema the load drill reports, so all drills compare field-for-field.
    Subprocess modes measure client-side (the server's bus lives across
    the pipes); ``events_dropped`` rides in from the ``stats`` op."""
    from distributed_ghs_implementation_tpu.obs import slo

    dropped = int((stats_response or {}).get("events_dropped", 0))
    return slo.assemble(stats, wall_s=wall_s, events_dropped=dropped)


def _graph_edges(g):
    return [[int(a), int(b), int(c)] for a, b, c in zip(g.u, g.v, g.w)]


def run_smoke(args) -> dict:
    """solve -> update -> repeat-solve over the real CLI pipes."""
    from distributed_ghs_implementation_tpu.obs import slo

    g = _seed_graph(args.nodes, args.edges, args.seed)
    edges = _graph_edges(g)
    requests = [
        {"op": "solve", "num_nodes": g.num_nodes, "edges": edges,
         "slo_class": "miss"},
        {"op": "update", "digest": None, "updates": [],
         "slo_class": "update"},  # digest patched below
        {"op": "solve", "num_nodes": g.num_nodes, "edges": edges,
         "slo_class": "hit"},
        {"op": "stats"},
        {"op": "shutdown"},
    ]
    proc = subprocess.Popen(
        [sys.executable, "-m", "distributed_ghs_implementation_tpu", "serve"],
        stdin=subprocess.PIPE,
        stdout=subprocess.PIPE,
        text=True,
        env={**os.environ, "JAX_PLATFORMS": os.environ.get("JAX_PLATFORMS", "cpu")},
    )

    acct = slo.ClassStats()

    def roundtrip(request):
        t0 = time.perf_counter()
        proc.stdin.write(json.dumps(request) + "\n")
        proc.stdin.flush()
        line = proc.stdout.readline()
        if not line:
            raise RuntimeError("serve process closed its pipe early")
        response = json.loads(line)
        if request.get("slo_class"):
            acct.observe(
                request["slo_class"],
                time.perf_counter() - t0,
                ok=bool(response.get("ok")),
            )
        return response

    checks = []
    stats = {}
    t_run = time.perf_counter()
    try:
        first = roundtrip(requests[0])
        checks.append(("first solve ok", bool(first.get("ok"))))
        checks.append(("first solve is a miss", first.get("source") == "solved"))
        requests[1]["digest"] = first.get("digest")
        requests[1]["updates"] = [
            {"kind": "insert", "u": 0, "v": g.num_nodes - 1, "w": 1}
        ]
        update = roundtrip(requests[1])
        checks.append(("update ok", bool(update.get("ok"))))
        checks.append(("update incremental", update.get("mode") == "incremental"))
        repeat = roundtrip(requests[2])
        checks.append(("repeat solve ok", bool(repeat.get("ok"))))
        checks.append(("repeat is a cache hit", repeat.get("cached") is True))
        checks.append(
            ("repeat weight stable",
             repeat.get("total_weight") == first.get("total_weight"))
        )
        stats = roundtrip(requests[3])
        hits = stats.get("counters", {}).get("serve.store.hit", 0)
        checks.append(("obs counter saw the hit", hits >= 1))
        roundtrip(requests[4])
    finally:
        proc.stdin.close()
        proc.wait(timeout=60)
    slo_summary = _slo_section(acct, time.perf_counter() - t_run, stats)
    return {
        "mode": "smoke",
        "checks": [{"name": n, "ok": bool(ok)} for n, ok in checks],
        "slo": slo_summary,
        "events_dropped": slo_summary["events_dropped"],
        "dropped_warning": slo_summary["dropped_warning"],
        "ok": all(ok for _, ok in checks),
    }


def run_warmup_smoke(args) -> dict:
    """Warmup serve, query the pre-declared bucket, assert zero
    request-time compiles (``compile.miss``) via the stats op."""
    from distributed_ghs_implementation_tpu.obs import slo

    g1 = _seed_graph(args.nodes, args.edges, args.seed)
    g2 = _seed_graph(args.nodes, args.edges, args.seed + 1)
    cache_dir = args.compile_cache_dir or "serve_compile_cache"
    argv = [
        sys.executable, "-m", "distributed_ghs_implementation_tpu",
        "serve",
        "--batch-lanes", "4",
        "--warmup-buckets", f"{args.nodes}x{args.edges}",
        "--compile-cache-dir", cache_dir,
    ]
    if args.kernel:
        # Kernel-variant warmup coverage: the warmed buckets must be the
        # variant the queries resolve (compile.miss == 0 either way).
        argv += ["--kernel", args.kernel]
    proc = subprocess.Popen(
        argv,
        stdin=subprocess.PIPE,
        stdout=subprocess.PIPE,
        text=True,
        env={**os.environ, "JAX_PLATFORMS": os.environ.get("JAX_PLATFORMS", "cpu")},
    )

    def roundtrip(request):
        proc.stdin.write(json.dumps(request) + "\n")
        proc.stdin.flush()
        line = proc.stdout.readline()
        if not line:
            raise RuntimeError("serve process closed its pipe early")
        return json.loads(line)

    checks = []
    counters = {}
    warmup_report = None
    stats = {}
    acct = slo.ClassStats()
    t_run = time.perf_counter()
    try:
        # A throwaway stats roundtrip absorbs subprocess boot + the warmup
        # phase, so the timed solves below measure warm QUERY latency, not
        # interpreter startup.
        boot = roundtrip({"op": "stats"})
        checks.append(("serve booted", bool(boot.get("ok"))))
        t_run = time.perf_counter()
        for i, g in enumerate((g1, g2), 1):
            t0 = time.perf_counter()
            response = roundtrip(
                {"op": "solve", "num_nodes": g.num_nodes,
                 "edges": _graph_edges(g), "slo_class": "miss"}
            )
            acct.observe(
                "miss", time.perf_counter() - t0, ok=bool(response.get("ok"))
            )
            checks.append((f"solve {i} ok", bool(response.get("ok"))))
            checks.append((f"solve {i} is a miss", response.get("source") == "solved"))
            checks.append(
                (f"solve {i} rode the lane engine",
                 str(response.get("backend", "")).startswith("batch/"))
            )
        stats = roundtrip({"op": "stats"})
        counters = stats.get("counters", {})
        warmup_report = stats.get("warmup")
        wall_s = time.perf_counter() - t_run
        checks.append(("warmup ran", bool(warmup_report)))
        checks.append(
            ("warmup compiled the bucket",
             counters.get("compile.warmup", 0) >= 1)
        )
        checks.append(
            ("zero request-time compiles (compile.miss)",
             counters.get("compile.miss", 0) == 0)
        )
        checks.append(
            ("queries hit the precompiled solver",
             counters.get("batch.compile.hit", 0) >= 2)
        )
        roundtrip({"op": "shutdown"})
    finally:
        proc.stdin.close()
        proc.wait(timeout=120)
    slo_summary = _slo_section(acct, wall_s, stats)
    return {
        "mode": "warmup-smoke",
        "kernel": args.kernel or "auto",
        "checks": [{"name": n, "ok": bool(ok)} for n, ok in checks],
        "slo": slo_summary,
        "events_dropped": slo_summary["events_dropped"],
        "dropped_warning": slo_summary["dropped_warning"],
        "warmup": warmup_report,
        "compile_counters": {
            k: v for k, v in counters.items() if k.startswith("compile.")
        },
        "compile_cache_dir": cache_dir,
        "ok": all(ok for _, ok in checks),
    }


def run_sharded_smoke(args) -> dict:
    """Oversize deck through ``serve --sharded-lane`` over its JSONL pipes:
    mesh execution, store hits on repeats, donated-update residency, and
    zero request-time compiles — all asserted via the ``stats`` op."""
    from distributed_ghs_implementation_tpu.obs import slo

    nodes, edges_n = args.oversize_nodes, args.oversize_edges
    g1 = _seed_graph(nodes, edges_n, args.seed)
    g2 = _seed_graph(nodes, edges_n, args.seed + 1)
    # The donated-update step needs a TRUE insert (an existing pair would
    # be a reweight — a wide rank shift that legitimately restages) with a
    # top weight (new last rank: a one-slot delta).
    existing = {(int(a), int(b)) for a, b in zip(g2.u, g2.v)}
    ins_v = next(x for x in range(1, nodes) if (0, x) not in existing)
    ins = [0, ins_v, 10_000]
    env = {
        **os.environ,
        "JAX_PLATFORMS": os.environ.get("JAX_PLATFORMS", "cpu"),
    }
    if "xla_force_host_platform_device_count" not in env.get("XLA_FLAGS", ""):
        # The dryrun mesh: 8 virtual CPU devices, as in tests/conftest.py.
        env["XLA_FLAGS"] = (
            env.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8"
        ).strip()
    argv = [
        sys.executable, "-m", "distributed_ghs_implementation_tpu", "serve",
        "--sharded-lane",
        # The REAL generated edge count (ensure_connected can exceed the
        # requested size) — the warm bucket must be the traffic's bucket.
        "--warmup-mesh-buckets", f"{g1.num_nodes}x{g1.num_edges}",
    ]
    if args.compile_cache_dir:
        argv += ["--compile-cache-dir", args.compile_cache_dir]
    proc = subprocess.Popen(
        argv, stdin=subprocess.PIPE, stdout=subprocess.PIPE, text=True,
        env=env,
    )

    acct = slo.ClassStats()

    def roundtrip(request, cls=None):
        t0 = time.perf_counter()
        proc.stdin.write(json.dumps(request) + "\n")
        proc.stdin.flush()
        line = proc.stdout.readline()
        if not line:
            raise RuntimeError("serve process closed its pipe early")
        response = json.loads(line)
        if cls:
            acct.observe(
                cls, time.perf_counter() - t0, ok=bool(response.get("ok"))
            )
        return response

    checks = []
    counters = {}
    warmup_report = None
    stats = {}
    wall_s = 0.0
    try:
        boot = roundtrip({"op": "stats"})  # absorbs boot + mesh warmup
        checks.append(("serve booted", bool(boot.get("ok"))))
        warmup_report = boot.get("warmup")
        checks.append(
            ("mesh bucket warmed",
             bool(warmup_report) and warmup_report.get("mesh_warmed", 0) >= 1)
        )
        t_run = time.perf_counter()
        solve1 = {"op": "solve", "num_nodes": g1.num_nodes,
                  "edges": _graph_edges(g1), "slo_class": "oversize"}
        first = roundtrip(solve1, "oversize")
        checks.append(("oversize solve ok", bool(first.get("ok"))))
        checks.append(
            ("oversize solve ran on the mesh",
             first.get("backend") == "sharded_lane")
        )
        repeat = roundtrip(solve1, "oversize")
        checks.append(("repeat is a store hit", repeat.get("cached") is True))
        second = roundtrip(
            {"op": "solve", "num_nodes": g2.num_nodes,
             "edges": _graph_edges(g2), "slo_class": "oversize"},
            "oversize",
        )
        checks.append(
            ("second oversize solve on the mesh",
             bool(second.get("ok"))
             and second.get("backend") == "sharded_lane")
        )
        # A top-weight true insert: a one-slot rank delta, i.e. the
        # donated residency-refresh regime.
        update = roundtrip(
            {"op": "update", "digest": second.get("digest"),
             "updates": [{"kind": "insert",
                          "u": ins[0], "v": ins[1], "w": ins[2]}],
             "slo_class": "update"},
            "update",
        )
        checks.append(("update ok", bool(update.get("ok"))))
        re_solve = roundtrip(
            {"op": "solve", "num_nodes": g2.num_nodes,
             "edges": _graph_edges(g2) + [ins],
             "slo_class": "oversize"},
            "oversize",
        )
        checks.append(
            ("updated graph answered from the store",
             re_solve.get("cached") is True
             and re_solve.get("digest") == update.get("digest"))
        )
        stats = roundtrip({"op": "stats"})
        counters = stats.get("counters", {})
        wall_s = time.perf_counter() - t_run
        checks.append(
            ("update rode the donated residency path",
             counters.get("lane.update.donated", 0) >= 1)
        )
        checks.append(
            ("oversize routed (serve.route.sharded_lane)",
             counters.get("serve.route.sharded_lane", 0) >= 2)
        )
        checks.append(
            ("zero request-time compiles on the oversize path",
             counters.get("compile.miss", 0) == 0)
        )
        checks.append(
            ("warmup compiled the mesh programs",
             counters.get("compile.warmup", 0) >= 1)
        )
        roundtrip({"op": "shutdown"})
    finally:
        proc.stdin.close()
        proc.wait(timeout=180)
    slo_summary = _slo_section(acct, wall_s, stats)
    return {
        "mode": "sharded-smoke",
        "checks": [{"name": n, "ok": bool(ok)} for n, ok in checks],
        "slo": slo_summary,
        "events_dropped": slo_summary["events_dropped"],
        "dropped_warning": slo_summary["dropped_warning"],
        "warmup": warmup_report,
        "compile_counters": {
            k: v for k, v in counters.items() if k.startswith("compile.")
        },
        "lane_counters": {
            k: v for k, v in counters.items()
            if k.startswith(("lane.", "serve.route."))
        },
        "ok": all(ok for _, ok in checks),
    }


def run_replay(args) -> dict:
    """In-process update-stream replay, every step checked vs the oracle."""
    from distributed_ghs_implementation_tpu.graphs.edgelist import Graph
    from distributed_ghs_implementation_tpu.obs import slo
    from distributed_ghs_implementation_tpu.obs.events import BUS
    from distributed_ghs_implementation_tpu.serve.service import MSTService
    from distributed_ghs_implementation_tpu.utils.resilience import FAULTS
    from distributed_ghs_implementation_tpu.utils.verify import scipy_mst_weight

    BUS.enable()
    BUS.clear()
    if args.chaos:
        # The miss path must survive transient device failures (supervisor
        # retry), and the persistent cache a torn write mid-save.
        FAULTS.arm("resilience.attempt.device", times=1)
        if args.disk_cache:
            FAULTS.arm("serve.store.save", times=1, kind="torn")

    service = MSTService(disk_dir=args.disk_cache)
    g = _seed_graph(args.nodes, args.edges, args.seed)
    mirror = {
        (int(a), int(b)): int(c) for a, b, c in zip(g.u, g.v, g.w)
    }
    t_run = time.perf_counter()
    response = service.handle(
        {"op": "solve", "num_nodes": g.num_nodes, "edges": _graph_edges(g),
         "slo_class": "miss"}
    )
    if not response.get("ok"):
        return {"mode": "replay", "ok": False, "error": response.get("error")}
    digest = response["digest"]

    rng = np.random.default_rng(args.seed + 1)
    steps = []
    ok = True
    for step in range(args.updates):
        kind = str(rng.choice(["insert", "delete", "reweight"]))
        if kind == "delete" and mirror:
            a, b = list(mirror)[int(rng.integers(0, len(mirror)))]
            upd = {"kind": "delete", "u": a, "v": b}
            del mirror[(a, b)]
        elif kind == "reweight" and mirror:
            a, b = list(mirror)[int(rng.integers(0, len(mirror)))]
            w = int(rng.integers(1, 100))
            upd = {"kind": "reweight", "u": a, "v": b, "w": w}
            mirror[(a, b)] = w
        else:
            a, b = sorted(int(x) for x in rng.integers(0, g.num_nodes, 2))
            if a == b:
                continue
            w = int(rng.integers(1, 100))
            upd = {"kind": "insert", "u": a, "v": b, "w": w}
            mirror[(a, b)] = w  # insert of an existing edge is a reweight
        response = service.handle(
            {"op": "update", "digest": digest, "updates": [upd],
             "slo_class": "update"}
        )
        if not response.get("ok"):
            steps.append({"step": step, "update": upd,
                          "error": response.get("error")})
            ok = False
            break
        digest = response["digest"]
        pairs = np.asarray(list(mirror), dtype=np.int64).reshape(-1, 2)
        oracle_graph = Graph.from_arrays(
            g.num_nodes, pairs[:, 0], pairs[:, 1],
            np.asarray(list(mirror.values()), dtype=np.int64),
        )
        expect = scipy_mst_weight(oracle_graph) if mirror else 0.0
        good = abs(float(response["total_weight"]) - float(expect)) < 1e-6
        ok = ok and good
        steps.append(
            {"step": step, "update": upd, "mode": response.get("mode"),
             "weight": response["total_weight"], "oracle": expect, "ok": good}
        )
    stats = service.handle({"op": "stats"})
    # In-process: per-class accounting joins the REAL bus events (the same
    # obs.slo join the load drill gates on), not client stopwatches.
    slo_summary = slo.summarize_bus(BUS, wall_s=time.perf_counter() - t_run)
    return {
        "mode": "replay",
        "chaos": bool(args.chaos),
        "ok": ok,
        "steps_run": len(steps),
        "slo": slo_summary,
        "events_dropped": slo_summary["events_dropped"],
        "dropped_warning": slo_summary["dropped_warning"],
        "counters": stats.get("counters", {}),
        "failures": [s for s in steps if not s.get("ok", True)],
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="serve_drill", description=__doc__)
    p.add_argument("--smoke", action="store_true",
                   help="CI smoke: subprocess + JSONL pipes + cache-hit assert")
    p.add_argument("--warmup-smoke", action="store_true",
                   help="CI warm-path smoke: serve --warmup-buckets, assert "
                   "zero request-time compiles via compile.* counters")
    p.add_argument("--sharded-smoke", action="store_true",
                   help="CI oversize-path smoke: serve --sharded-lane over "
                   "the 8-device dryrun; oversize deck, store hits, donated "
                   "update, zero request-time compiles")
    p.add_argument("--oversize-nodes", type=int, default=70_000,
                   help="oversize deck shape for --sharded-smoke (node "
                   "bucket past the lane-admission ceiling)")
    p.add_argument("--oversize-edges", type=int, default=3_000)
    p.add_argument(
        "--kernel", choices=["auto", "pallas", "xla"], default=None,
        help="pass this level-kernel variant to the serve child "
        "(--warmup-smoke: asserts zero request-time compiles with the "
        "variant's warmed buckets; docs/KERNELS.md)",
    )
    p.add_argument("--compile-cache-dir",
                   help="persistent compile-cache dir for --warmup-smoke")
    p.add_argument("--chaos", action="store_true",
                   help="arm fault sites before the replay")
    p.add_argument("--nodes", type=int, default=300)
    p.add_argument("--edges", type=int, default=1200)
    p.add_argument("--seed", type=int, default=17)
    p.add_argument("--updates", type=int, default=25)
    p.add_argument("--disk-cache", help="persistent cache dir for the replay")
    p.add_argument("--output", help="write the JSON report here")
    args = p.parse_args(argv)

    if args.smoke:
        report = run_smoke(args)
    elif args.warmup_smoke:
        report = run_warmup_smoke(args)
    elif args.sharded_smoke:
        report = run_sharded_smoke(args)
    else:
        report = run_replay(args)
    if args.output:
        with open(args.output, "w") as f:
            json.dump(report, f, indent=2)
            f.write("\n")
    print(json.dumps({
        k: v for k, v in report.items() if k != "counters"
    } if report["mode"] == "replay" else report, indent=2))
    print(f"serve drill: {'PASS' if report['ok'] else 'FAIL'}")
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
