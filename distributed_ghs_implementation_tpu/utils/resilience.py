"""Self-healing solve supervision + the fault-injection registry.

The solver stack is fast but brittle by construction: a transient
``XlaRuntimeError`` (device OOM spike, preemption, interconnect hiccup)
aborts a multi-minute RMAT-24 solve, and nothing above ``_solve`` knows how
to try again. This module adds the production discipline the reference never
had:

* :class:`FaultRegistry` — named injection sites armed via the
  ``GHS_FAULT_*`` environment or the :meth:`FaultRegistry.inject` context
  manager, so tests (and operators doing game-days) can induce solver
  exceptions, slow chunks, and torn checkpoint writes deterministically.
* :class:`Supervisor` — wraps the solve in a watchdog deadline (checked
  cooperatively at chunk/level boundaries — no thread can interrupt a
  running XLA dispatch), bounded retry with capped exponential backoff on
  *transient* errors, and a degradation ladder
  ``sharded -> device -> stepped -> host`` that trades speed for simplicity
  one rung at a time. Every attempt lands in a structured
  :class:`IncidentLog` so a degraded run is diagnosable after the fact.

Exposed as ``api.minimum_spanning_forest(..., supervised=True)`` and
``run --supervised`` on the CLI. The chaos drill
(``tools/chaos_drill.py``) exercises the whole matrix against the oracle.
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import os
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from distributed_ghs_implementation_tpu.graphs.edgelist import Graph
from distributed_ghs_implementation_tpu.obs.events import BUS


# ----------------------------------------------------------------------
# Error vocabulary
# ----------------------------------------------------------------------
class InjectedFault(RuntimeError):
    """Raised at an armed injection site (always classified transient)."""


class TransientDeviceError(RuntimeError):
    """Explicitly-transient wrapper for callers surfacing retryable errors."""


class WatchdogTimeout(RuntimeError):
    """An attempt exceeded the supervisor deadline at a chunk boundary."""


class SupervisorExhausted(RuntimeError):
    """Every rung of the degradation ladder failed; carries the incident log."""

    def __init__(self, message: str, incidents: "IncidentLog"):
        super().__init__(message)
        self.incidents = incidents


# jaxlib surfaces device failures under this name (it subclasses RuntimeError,
# so we match by name rather than importing jaxlib here).
_TRANSIENT_TYPE_NAMES = {"XlaRuntimeError"}


def is_transient(exc: BaseException) -> bool:
    """Should the supervisor retry/degrade (True) or re-raise (False)?

    Transient: injected faults, watchdog timeouts, explicit
    :class:`TransientDeviceError`, OS/timeout/connection errors, and
    ``XlaRuntimeError`` (device runtime failures). Everything else — e.g.
    ``ValueError`` from malformed input — is a programming error the ladder
    must not paper over.
    """
    if isinstance(
        exc,
        (
            InjectedFault,
            TransientDeviceError,
            WatchdogTimeout,
            TimeoutError,
            ConnectionError,
            OSError,
        ),
    ):
        return True
    return type(exc).__name__ in _TRANSIENT_TYPE_NAMES


# ----------------------------------------------------------------------
# Fault-injection registry
# ----------------------------------------------------------------------
@dataclasses.dataclass
class _ArmedFault:
    remaining: int
    kind: str = "raise"  # "raise" | "slow" | "torn"
    value: float = 0.0  # seconds for kind="slow"


class FaultRegistry:
    """Process-global registry of induced faults at named sites.

    Site names are dotted, underscore-free identifiers
    (``resilience.attempt.device``, ``checkpoint.save``). Arm a site either
    programmatically::

        with FAULTS.inject("resilience.attempt.device", times=2):
            ...

    or from the environment, mapping ``GHS_FAULT_<SITE>`` with dots as
    underscores and a ``times[:kind[:value]]`` value::

        GHS_FAULT_RESILIENCE_ATTEMPT_DEVICE=2
        GHS_FAULT_CHECKPOINT_SAVE=1:torn
        GHS_FAULT_RESILIENCE_SLOW_STEPPED=1:slow:3600

    Kinds: ``raise`` makes the site raise :class:`InjectedFault`; ``slow``
    advances the supervisor's virtual clock by ``value`` seconds at the next
    chunk boundary (a deterministic stand-in for a stalled dispatch — no
    sleeps); ``torn`` makes ``save_checkpoint`` leave a truncated file and
    raise, simulating a crash mid-write on a non-atomic filesystem.
    """

    ENV_PREFIX = "GHS_FAULT_"

    def __init__(self):
        self._sites: Dict[str, _ArmedFault] = {}
        self._env_loaded = False

    # -- configuration -------------------------------------------------
    def arm(
        self, site: str, *, times: int = 1, kind: str = "raise", value: float = 0.0
    ) -> None:
        if kind not in ("raise", "slow", "torn"):
            raise ValueError(f"unknown fault kind {kind!r}")
        if "_" in site:
            raise ValueError(
                f"site {site!r} may not contain '_' (reserved for the env mapping)"
            )
        self._sites[site] = _ArmedFault(remaining=times, kind=kind, value=value)

    def disarm(self, site: str) -> None:
        self._sites.pop(site, None)

    def reset(self) -> None:
        """Forget every armed site AND any env-derived state (test isolation)."""
        self._sites.clear()
        self._env_loaded = True  # do not re-read the env behind the reset

    def reload_env(self) -> None:
        """(Re-)parse ``GHS_FAULT_*`` from the current environment."""
        self._env_loaded = True
        for key, raw in os.environ.items():
            if not key.startswith(self.ENV_PREFIX) or not raw:
                continue
            site = key[len(self.ENV_PREFIX):].lower().replace("_", ".")
            parts = raw.split(":")
            try:
                times = int(parts[0])
                kind = parts[1] if len(parts) > 1 else "raise"
                value = float(parts[2]) if len(parts) > 2 else 0.0
            except ValueError as e:
                raise ValueError(
                    f"bad {key}={raw!r}; expected times[:kind[:value]]"
                ) from e
            self.arm(site, times=times, kind=kind, value=value)

    @contextlib.contextmanager
    def inject(
        self, site: str, *, times: int = 1, kind: str = "raise", value: float = 0.0
    ):
        """Arm ``site`` for the duration of the block, disarming on exit."""
        self.arm(site, times=times, kind=kind, value=value)
        try:
            yield self
        finally:
            self.disarm(site)

    # -- firing --------------------------------------------------------
    def armed(self, site: str) -> bool:
        """Is ``site`` armed? (peek — does not consume a shot)."""
        if not self._env_loaded:
            self.reload_env()
        return site in self._sites

    def pop(self, site: str) -> Optional[_ArmedFault]:
        """Consume one shot at ``site``; returns the armed spec or ``None``."""
        if not self._env_loaded:
            self.reload_env()
        armed = self._sites.get(site)
        if armed is None or armed.remaining <= 0:
            return None
        armed.remaining -= 1
        if armed.remaining == 0:
            del self._sites[site]
        return armed

    def fire(self, site: str) -> None:
        """Raise :class:`InjectedFault` if ``site`` is armed (kind ``raise``)."""
        armed = self.pop(site)
        if armed is not None and armed.kind == "raise":
            raise InjectedFault(f"injected fault at {site}")


FAULTS = FaultRegistry()


# ----------------------------------------------------------------------
# Incident log
# ----------------------------------------------------------------------
@dataclasses.dataclass
class Incident:
    rung: str
    attempt: int  # 1-based within the rung
    outcome: str  # "ok" | "transient" | "timeout" | "unavailable" | "fatal"
    error: Optional[str] = None
    elapsed_s: float = 0.0
    backoff_s: float = 0.0
    # The fault-registry site implicated in a failed attempt (the attempt
    # site for transient/fatal errors, the slow site for timeouts); None on
    # success/unavailable. Structured so dashboards and tests can key on it.
    site: Optional[str] = None


class IncidentLog:
    """Structured record of every supervised attempt, in order.

    Every record is mirrored onto the event bus as a ``resilience.attempt``
    span-event (duration = the attempt's elapsed time), so traces show the
    retry/degrade ladder inline with solver and protocol activity — the
    structured replacement for grepping formatted attempt strings.
    """

    def __init__(self):
        self.records: List[Incident] = []

    def add(self, **kwargs) -> Incident:
        rec = Incident(**kwargs)
        self.records.append(rec)
        BUS.complete(
            "resilience.attempt",
            rec.elapsed_s,
            cat="resilience",
            rung=rec.rung,
            attempt=rec.attempt,
            outcome=rec.outcome,
            error=rec.error,
            backoff_s=rec.backoff_s,
            site=rec.site,
        )
        return rec

    @property
    def final_rung(self) -> Optional[str]:
        for rec in reversed(self.records):
            if rec.outcome == "ok":
                return rec.rung
        return None

    def to_dicts(self) -> List[dict]:
        return [dataclasses.asdict(r) for r in self.records]

    def to_json(self, **kwargs) -> str:
        return json.dumps(self.to_dicts(), **kwargs)

    def summary(self) -> str:
        """One line per attempt, e.g. ``device#1 transient(InjectedFault)``."""
        parts = []
        for r in self.records:
            detail = "" if r.error is None else f"({r.error.split('(')[0]})"
            parts.append(f"{r.rung}#{r.attempt} {r.outcome}{detail}")
        return " -> ".join(parts)

    def __len__(self) -> int:
        return len(self.records)


# ----------------------------------------------------------------------
# Degradation ladder rungs — all share _solve's (edge_ids, fragment, levels)
# contract. `tick` (when not None) is called at chunk/level boundaries; the
# supervisor uses it for cooperative watchdog checks.
# ----------------------------------------------------------------------
def _mask_to_ids(graph: Graph, mst_ranks, fragment, levels):
    ranks = np.nonzero(np.asarray(mst_ranks))[0]
    edge_ids = np.sort(graph.edge_id_of_rank(ranks))
    return edge_ids, np.asarray(fragment)[: graph.num_nodes], int(levels)


def _rung_sharded(graph: Graph, tick):
    try:
        from distributed_ghs_implementation_tpu.parallel.sharded import (
            solve_graph_sharded,
        )
    except ImportError as e:
        raise NotImplementedError("sharded backend unavailable") from e
    return solve_graph_sharded(graph)


def _rung_device(graph: Graph, tick):
    from distributed_ghs_implementation_tpu.models.rank_solver import (
        make_production_solver,
    )

    solve = make_production_solver(graph)
    on_chunk = None if tick is None else (lambda level, frag, mst, count: tick())
    mst, fragment, levels = solve(on_chunk=on_chunk)
    return _mask_to_ids(graph, mst, fragment, levels)


def _rung_stepped(graph: Graph, tick):
    from distributed_ghs_implementation_tpu.models.boruvka import (
        prepare_device_arrays,
        solve_arrays_stepped,
    )

    args = prepare_device_arrays(graph)
    on_level = (
        None if tick is None else (lambda level, f, m, has, count, dt: tick())
    )
    mst, fragment, levels = solve_arrays_stepped(
        *args, stepped_levels=None, on_level=on_level
    )
    return _mask_to_ids(graph, mst, fragment, levels)


def _rung_host(graph: Graph, tick):
    from distributed_ghs_implementation_tpu.models.rank_solver import (
        solve_graph_kruskal_host,
    )

    # Raises NotImplementedError (rung unavailable) on float weights or a
    # missing native toolchain — the supervisor records it and degrades.
    return solve_graph_kruskal_host(graph)


_RUNGS = {
    "sharded": _rung_sharded,
    "device": _rung_device,
    "stepped": _rung_stepped,
    "host": _rung_host,
}

#: Degradation order: multi-chip -> single-device production routing ->
#: host-stepped kernel (simplest device path, per-level sync) -> host
#: Kruskal (no accelerator at all). Each rung trades speed for fewer moving
#: parts; all compute the identical forest (rank order makes the MSF unique).
LADDER: Tuple[str, ...] = ("sharded", "device", "stepped", "host")


# ----------------------------------------------------------------------
# Supervisor
# ----------------------------------------------------------------------
@dataclasses.dataclass(frozen=True)
class SupervisorConfig:
    """Retry/degrade policy.

    ``retries_per_rung`` is the number of *re*-tries after the first attempt
    (so a rung sees at most ``retries_per_rung + 1`` attempts).
    ``deadline_s`` arms the cooperative watchdog: attempts are aborted with
    :class:`WatchdogTimeout` at the first chunk/level boundary past the
    deadline (rungs without boundary hooks — sharded, host — run unguarded).
    """

    retries_per_rung: int = 1
    backoff_base_s: float = 0.5
    backoff_cap_s: float = 30.0
    deadline_s: Optional[float] = None
    ladder: Tuple[str, ...] = LADDER


class Supervisor:
    """Retry, degrade, and log around any ladder rung.

    ``clock``/``sleep`` are injectable for deterministic tests (the armed
    ``resilience.slow.<rung>`` site advances a virtual skew on top of
    ``clock``, so a "slow chunk" is simulated without wall-clock sleeps).
    """

    def __init__(
        self,
        config: Optional[SupervisorConfig] = None,
        *,
        clock=time.monotonic,
        sleep=time.sleep,
    ):
        self.config = config or SupervisorConfig()
        self._clock = clock
        self._sleep = sleep
        bad = [r for r in self.config.ladder if r not in _RUNGS]
        if bad:
            raise ValueError(f"unknown ladder rungs {bad}; known: {sorted(_RUNGS)}")

    def solve(self, graph: Graph, *, entry: str = "device"):
        """Run the ladder from ``entry`` down; returns
        ``(edge_ids, fragment, levels, incident_log)``.

        ``entry`` outside the ladder (e.g. ``"protocol"``) starts at
        ``"device"``. Raises :class:`SupervisorExhausted` when every rung
        fails, non-transient errors immediately (after logging them).
        """
        cfg = self.config
        log = IncidentLog()
        if graph.num_nodes == 0 or graph.num_edges == 0:
            return (
                np.zeros(0, dtype=np.int64),
                np.arange(graph.num_nodes, dtype=np.int32),
                0,
                log,
            )
        ladder = cfg.ladder
        if entry in ladder:
            start = ladder.index(entry)
        elif "device" in ladder:
            start = ladder.index("device")
        else:
            start = 0
        with BUS.span(
            "resilience.solve", cat="resilience", entry=ladder[start],
            nodes=graph.num_nodes, edges=graph.num_edges,
        ) as span:
            remaining = ladder[start:]
            for i, rung in enumerate(remaining):
                outcome = self._attempt_rung(rung, graph, log)
                if outcome is not None:
                    span.set(final_rung=rung, attempts=len(log))
                    return outcome + (log,)
                if i + 1 < len(remaining):
                    BUS.instant(
                        "resilience.degrade",
                        cat="resilience",
                        from_rung=rung,
                        to_rung=remaining[i + 1],
                    )
            span.set(final_rung=None, attempts=len(log))
        raise SupervisorExhausted(
            f"every rung failed: {log.summary()}", log
        )

    # ------------------------------------------------------------------
    def _attempt_rung(self, rung: str, graph: Graph, log: IncidentLog):
        """All attempts of one rung; result tuple on success, None to degrade."""
        cfg = self.config
        for attempt in range(1, cfg.retries_per_rung + 2):
            skew = [0.0]
            t0 = self._clock()

            def tick():
                armed = FAULTS.pop(f"resilience.slow.{rung}")
                if armed is not None:
                    if armed.kind == "slow":
                        skew[0] += armed.value
                    else:
                        raise InjectedFault(f"injected fault at resilience.slow.{rung}")
                elapsed = (self._clock() - t0) + skew[0]
                if cfg.deadline_s is not None and elapsed > cfg.deadline_s:
                    raise WatchdogTimeout(
                        f"{rung} attempt {attempt}: {elapsed:.1f}s elapsed "
                        f"exceeds the {cfg.deadline_s}s deadline"
                    )

            # Boundary hooks change solver routing slightly (chunked vs
            # speculative dispatch), so only guard when the watchdog has a
            # deadline to enforce — or a slow site is armed, which must be
            # consumed here rather than leak into an unrelated later solve.
            guard = (
                tick
                if cfg.deadline_s is not None
                or FAULTS.armed(f"resilience.slow.{rung}")
                else None
            )
            try:
                FAULTS.fire(f"resilience.attempt.{rung}")
                result = _RUNGS[rung](graph, guard)
            except NotImplementedError as e:
                log.add(
                    rung=rung,
                    attempt=attempt,
                    outcome="unavailable",
                    error=str(e),
                    elapsed_s=(self._clock() - t0) + skew[0],
                )
                return None  # this rung can never work here: degrade
            except Exception as e:  # noqa: BLE001 — classification below
                elapsed = (self._clock() - t0) + skew[0]
                if not is_transient(e):
                    log.add(
                        rung=rung,
                        attempt=attempt,
                        outcome="fatal",
                        error=repr(e),
                        elapsed_s=elapsed,
                        site=f"resilience.attempt.{rung}",
                    )
                    raise
                retrying = attempt <= cfg.retries_per_rung
                backoff = 0.0
                if retrying:
                    backoff = min(
                        cfg.backoff_base_s * (2 ** (attempt - 1)),
                        cfg.backoff_cap_s,
                    )
                timed_out = isinstance(e, WatchdogTimeout)
                log.add(
                    rung=rung,
                    attempt=attempt,
                    outcome="timeout" if timed_out else "transient",
                    error=repr(e),
                    elapsed_s=elapsed,
                    backoff_s=backoff,
                    site=(
                        f"resilience.slow.{rung}"
                        if timed_out
                        else f"resilience.attempt.{rung}"
                    ),
                )
                if retrying and backoff > 0:
                    self._sleep(backoff)
                continue
            log.add(
                rung=rung,
                attempt=attempt,
                outcome="ok",
                elapsed_s=(self._clock() - t0) + skew[0],
            )
            return result
        return None  # retries exhausted: degrade to the next rung


def supervised_solve(
    graph: Graph,
    *,
    entry: str = "device",
    config: Optional[SupervisorConfig] = None,
    clock=time.monotonic,
    sleep=time.sleep,
):
    """Convenience wrapper: ``Supervisor(config).solve(graph, entry=entry)``."""
    return Supervisor(config, clock=clock, sleep=sleep).solve(graph, entry=entry)
