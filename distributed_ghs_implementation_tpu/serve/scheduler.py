"""Micro-batching solve scheduler: single-flight coalescing + admission bound.

Request handling for the serve path, in order:

1. **Cache probe** — ``ResultStore.get`` by content key; a hit never touches
   the solver (zero ``solver.*`` spans — the warm-path guarantee tests
   assert on bus events).
2. **Single-flight** — concurrent requests for the same key join the one
   in-flight solve instead of duplicating it (``serve.scheduler.coalesced``
   counts the joins). This is what keeps a thundering herd of identical
   queries at exactly one kernel dispatch.
3. **Admission bound** — distinct misses solve under a semaphore
   (``max_concurrent``); excess requests queue. ``serve.queue.depth`` is
   sampled on every transition so traces show pressure over time. With a
   batch engine attached the engine's own forming queue + serialized
   dispatch is the capacity bound instead (holding the semaphore while
   waiting for lane-mates would forbid the very coalescing the engine is
   for).
4. **Supervised solve** — every miss runs through the round-6 resilience
   supervisor (watchdog, bounded retry, the sharded->device->stepped->host
   degradation ladder), so one flaky device never fails a request that a
   degraded rung can still answer exactly. With a batch engine, device
   misses instead run the engine's batch-shaped supervision (batch retry,
   then per-lane ladder fallback — ``batch/engine.py``).

``solve_batch`` is the micro-batching entry: it dedups a whole request list
by key, registers ONE flight per distinct missed digest *before any solving
starts* (duplicates inside the batch — and concurrent ``solve`` callers —
join that flight instead of racing it), then solves the distinct misses as
a group: through the batch engine when attached (same-bucket misses share
device dispatches), else sequentially.
"""

from __future__ import annotations

import threading
from typing import List, Optional, Sequence, Tuple

from distributed_ghs_implementation_tpu.api import MSTResult, minimum_spanning_forest
from distributed_ghs_implementation_tpu.graphs.edgelist import Graph
from distributed_ghs_implementation_tpu.obs.events import BUS
from distributed_ghs_implementation_tpu.obs.slo import current_class
from distributed_ghs_implementation_tpu.serve.store import ResultStore, solve_cache_key


def _cls_args() -> dict:
    """The SLO class tag of the current request context, as span args —
    stamping it on ``serve.solve`` lets ``obs.slo`` decompose each class's
    end-to-end latency into solve time vs everything else."""
    cls = current_class()
    return {"cls": cls} if cls is not None else {}


class _Flight:
    """One in-flight solve; joiners block on ``event`` and read the outcome."""

    __slots__ = ("event", "result", "error")

    def __init__(self):
        self.event = threading.Event()
        self.result: Optional[MSTResult] = None
        self.error: Optional[BaseException] = None


class SolveScheduler:
    """Cache-fronted, single-flight, capacity-bounded solve dispatch."""

    def __init__(
        self,
        store: Optional[ResultStore] = None,
        *,
        backend: str = "device",
        max_concurrent: int = 2,
        supervisor_config=None,
        batch_engine=None,
    ):
        if max_concurrent < 1:
            raise ValueError(f"max_concurrent must be >= 1, got {max_concurrent}")
        self.store = store if store is not None else ResultStore()
        self.backend = backend
        self.batch_engine = batch_engine
        self._supervisor_config = supervisor_config
        self._sem = threading.BoundedSemaphore(max_concurrent)
        self._flights: dict = {}
        self._lock = threading.Lock()

    def solve(
        self, graph: Graph, *, backend: Optional[str] = None
    ) -> Tuple[MSTResult, str]:
        """Answer one solve request; returns ``(result, source)`` where
        ``source`` is ``"cache"`` / ``"coalesced"`` / ``"solved"``."""
        backend = backend or self.backend
        key = solve_cache_key(graph, backend=backend)
        cached = self.store.get(key, graph=graph)
        if cached is not None:
            return cached, "cache"

        flight, leader = self._join_or_lead(key)
        if not leader:
            BUS.count("serve.scheduler.coalesced")
            flight.event.wait()
            if flight.error is not None:
                raise flight.error
            return flight.result, "coalesced"

        try:
            # Double-check after winning leadership: a previous leader may
            # have published between our cache probe and the flight insert —
            # without this, that race re-solves an already-cached graph.
            cached = self.store.get(key, graph=graph, record_miss=False)
            if cached is not None:
                flight.result = cached
                return cached, "cache"
            flight.result = self._solve_miss(graph, backend)
            self.store.put(key, flight.result)
        except BaseException as e:
            flight.error = e
            raise
        finally:
            self._land(key, flight)
        return flight.result, "solved"

    def solve_batch(
        self, graphs: Sequence[Graph], *, backend: Optional[str] = None
    ) -> List[Tuple[MSTResult, str]]:
        """Solve a batch, deduplicating by content key first: duplicates
        inside the batch resolve against one flight (never race), and the
        distinct misses solve as a group (coalescing into device batches
        when the batch engine is attached)."""
        backend = backend or self.backend
        keys: List[str] = []
        unique: dict = {}
        for g in graphs:
            key = solve_cache_key(g, backend=backend)
            keys.append(key)
            if key in unique:
                BUS.count("serve.scheduler.coalesced")
            else:
                unique[key] = g

        outcome: dict = {}
        leaders: list = []  # (key, graph, flight)
        joiners: list = []  # (key, flight)
        for key, g in unique.items():
            cached = self.store.get(key, graph=g)
            if cached is not None:
                outcome[key] = (cached, "cache")
                continue
            flight, leader = self._join_or_lead(key)
            if leader:
                # Leadership double-check, as in solve().
                cached = self.store.get(key, graph=g, record_miss=False)
                if cached is not None:
                    flight.result = cached
                    self._land(key, flight)
                    outcome[key] = (cached, "cache")
                else:
                    leaders.append((key, g, flight))
            else:
                joiners.append((key, flight))

        if leaders:
            try:
                results = self._solve_misses(
                    [g for _, g, _ in leaders], backend
                )
            except BaseException as e:
                for key, _, flight in leaders:
                    flight.error = e
                    self._land(key, flight)
                raise
            try:
                for (key, _, flight), result in zip(leaders, results):
                    flight.result = result
                    self.store.put(key, result)
                    self._land(key, flight)
                    outcome[key] = (result, "solved")
            except BaseException as e:
                # A raise mid-publish (e.g. KeyboardInterrupt) must not
                # leak the remaining flights — a leaked flight blocks its
                # joiners forever. Land every unlanded leader (with its
                # result when the solve already succeeded).
                for key, _, flight in leaders:
                    if not flight.event.is_set():
                        if flight.result is None:
                            flight.error = e
                        self._land(key, flight)
                raise

        for key, flight in joiners:
            BUS.count("serve.scheduler.coalesced")
            flight.event.wait()
            if flight.error is not None:
                raise flight.error
            outcome[key] = (flight.result, "coalesced")

        out: List[Tuple[MSTResult, str]] = []
        first = set()
        for key in keys:
            result, source = outcome[key]
            out.append((result, source) if key not in first else (result, "coalesced"))
            first.add(key)
        return out

    # ------------------------------------------------------------------
    def _join_or_lead(self, key: str) -> Tuple[_Flight, bool]:
        """Atomically join the in-flight solve for ``key`` or become its
        leader; returns ``(flight, is_leader)``."""
        with self._lock:
            flight = self._flights.get(key)
            if flight is not None:
                return flight, False
            flight = self._flights[key] = _Flight()
            BUS.sample("serve.queue.depth", len(self._flights))
            return flight, True

    def _land(self, key: str, flight: _Flight) -> None:
        """Retire a flight and wake its joiners."""
        with self._lock:
            del self._flights[key]
            BUS.sample("serve.queue.depth", len(self._flights))
        flight.event.set()

    def _solve_miss(self, graph: Graph, backend: str) -> MSTResult:
        """One cache miss: batch-engine submission (device backend) or a
        semaphore-bounded supervised solve. Graphs the engine's policy
        would bypass anyway (oversize) stay on the semaphore path — the
        engine only replaces the admission bound for solves it actually
        queues and serializes."""
        if (
            self.batch_engine is not None
            and backend == "device"
            and self.batch_engine.policy.admits(graph)
        ):
            with BUS.span(
                "serve.solve", cat="serve", backend="batch",
                nodes=graph.num_nodes, edges=graph.num_edges, **_cls_args(),
            ):
                return self.batch_engine.submit(graph).wait()
        with self._sem:
            with BUS.span(
                "serve.solve", cat="serve", backend=backend,
                nodes=graph.num_nodes, edges=graph.num_edges, **_cls_args(),
            ):
                return minimum_spanning_forest(
                    graph, backend=backend, supervised=True,
                    supervisor=self._make_supervisor(),
                )

    def _solve_misses(
        self, graphs: List[Graph], backend: str
    ) -> List[MSTResult]:
        """The distinct misses of one batch, as a group."""
        if self.batch_engine is not None and backend == "device":
            with BUS.span(
                "serve.solve", cat="serve", backend="batch",
                misses=len(graphs), **_cls_args(),
            ):
                return self.batch_engine.solve_many(graphs)
        return [self._solve_miss(g, backend) for g in graphs]

    # ------------------------------------------------------------------
    def _make_supervisor(self):
        from distributed_ghs_implementation_tpu.utils.resilience import Supervisor

        return Supervisor(self._supervisor_config)
