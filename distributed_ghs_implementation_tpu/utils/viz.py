"""Visualization: side-by-side original/MST PNGs for small graphs.

Parity with the reference's matplotlib output
(``/root/reference/ghs_implementation.py:643-699`` and
``ghs_implementation_mpi.py:824-879``, input render at
``create_graph_files.py:97-124``): spring layout, edge-weight labels, MST
edges highlighted. Degrades to a no-op with a warning above ``max_nodes``
(the reference would happily hang rendering a million-node graph).
"""

from __future__ import annotations

import sys
from typing import Optional

from distributed_ghs_implementation_tpu.graphs.edgelist import Graph

DEFAULT_MAX_NODES = 500


def visualize_graph(
    graph: Graph,
    output_path: str,
    *,
    seed: int = 42,
    max_nodes: int = DEFAULT_MAX_NODES,
) -> Optional[str]:
    """Render the input graph alone (``create_graph_files.py:97-124`` parity)."""
    if graph.num_nodes > max_nodes:
        print(
            f"viz skipped: {graph.num_nodes} nodes > max_nodes={max_nodes}",
            file=sys.stderr,
        )
        return None
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt
    import networkx as nx

    g = graph.to_networkx()
    pos = nx.spring_layout(g, seed=seed)
    fig, ax = plt.subplots(figsize=(10, 8))
    nx.draw_networkx(g, pos, ax=ax, node_color="lightblue", node_size=500)
    nx.draw_networkx_edge_labels(
        g, pos, ax=ax, edge_labels={(a, b): w for a, b, w in graph.edge_triples()}
    )
    ax.set_title(f"Input graph: {graph.num_nodes} nodes, {graph.num_edges} edges")
    ax.axis("off")
    fig.tight_layout()
    fig.savefig(output_path, dpi=110)
    plt.close(fig)
    return output_path


def visualize_mst(
    result,
    output_path: str,
    *,
    seed: int = 42,
    max_nodes: int = DEFAULT_MAX_NODES,
) -> Optional[str]:
    """Side-by-side original vs MST (``ghs_implementation.py:643-699`` parity).

    ``result`` is an :class:`~distributed_ghs_implementation_tpu.api.MSTResult`.
    """
    graph: Graph = result.graph
    if graph.num_nodes > max_nodes:
        print(
            f"viz skipped: {graph.num_nodes} nodes > max_nodes={max_nodes}",
            file=sys.stderr,
        )
        return None
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt
    import networkx as nx

    g = graph.to_networkx()
    pos = nx.spring_layout(g, seed=seed)
    mst_edges = set(result.edges)
    fig, (ax1, ax2) = plt.subplots(1, 2, figsize=(16, 7))

    nx.draw_networkx(g, pos, ax=ax1, node_color="lightblue", node_size=450)
    nx.draw_networkx_edge_labels(
        g, pos, ax=ax1, edge_labels={(a, b): w for a, b, w in graph.edge_triples()}
    )
    ax1.set_title(f"Original: {graph.num_nodes} nodes, {graph.num_edges} edges")
    ax1.axis("off")

    nx.draw_networkx_nodes(g, pos, ax=ax2, node_color="lightgreen", node_size=450)
    nx.draw_networkx_labels(g, pos, ax=ax2)
    nx.draw_networkx_edges(
        g,
        pos,
        ax=ax2,
        edgelist=[e for e in g.edges() if (min(e), max(e)) in mst_edges],
        width=2.5,
        edge_color="darkgreen",
    )
    nx.draw_networkx_edge_labels(
        g,
        pos,
        ax=ax2,
        edge_labels={
            (a, b): w for a, b, w in result.weighted_edges
        },
    )
    ax2.set_title(
        f"MST: {result.num_edges} edges, total weight {result.total_weight} "
        f"({result.backend} backend, {result.num_levels} levels)"
    )
    ax2.axis("off")
    fig.tight_layout()
    fig.savefig(output_path, dpi=110)
    plt.close(fig)
    return output_path
