"""Per-level timing breakdown of the solver hot path — the kernel receipt.

Two workloads, one report schema (``ghs-level-profile-v1``):

* ``--workload rmat`` (default): the ELL kernel per level on one big graph
  (RMAT by default; ``--gnm NODESxEDGES`` swaps in the G(n,m) generator,
  whose NumPy RNG stream is identical on every host — the CI-gateable
  variant). Reports per-level ms + alive-fragment counts (the shrink
  profile), the stepped total, and the fused while_loop total; the gate
  metric is ``edges_per_sec`` over the fused loop.
* ``--workload batch``: the 16-lane serving workload — K same-bucket
  graphs stacked block-diagonally (``batch/lanes.py``) and solved in one
  dispatch, plus a host-stepped per-level breakdown of the same stacked
  solve. The gate metric is ``graphs_per_sec``.

``--kernel pallas|xla|auto`` selects the level-kernel variant
(``ops/pallas_kernels.py``); ``--compare-kernels`` times the XLA path AND
the resolved kernel back to back and reports ``level_kernel_speedup`` —
the number ``gate-kernel-v1`` enforces (``tools/bench_gate.py`` accepts
these reports directly: the embedded ``gate_metrics`` block is the
``ghs-bench-metrics-v1`` payload). On a host where Pallas auto-falls back
(no TPU), the speedup pins at ~1.0 by construction — the gate then passes
on the XLA path, which is exactly the fallback contract.

Usage:
  python tools/profile_levels.py [--scale 20] [--edge-factor 16]
  python tools/profile_levels.py --workload batch --lanes 16 \
      --compare-kernels --json receipt.json
"""

from __future__ import annotations

import _bootstrap  # noqa: F401 — repo-root sys.path setup

import argparse
import functools
import json
import time

import jax
import jax.numpy as jnp
import numpy as np

SCHEMA = "ghs-level-profile-v1"


@functools.partial(jax.jit, static_argnames=("nbuckets", "kernel"))
def _one_level(fragment, mst_ranks, *flat, nbuckets: int, kernel: str = "xla"):
    from distributed_ghs_implementation_tpu.models.boruvka import _ell_level

    buckets = tuple(
        (flat[3 * i], flat[3 * i + 1], flat[3 * i + 2]) for i in range(nbuckets)
    )
    ra, rb = flat[3 * nbuckets], flat[3 * nbuckets + 1]
    f2, m2, has = _ell_level(fragment, mst_ranks, buckets, ra, rb, kernel=kernel)
    # fragment entries are root ids and roots map to themselves, so the
    # distinct count is the number of self-mapped vertices (no sort needed).
    ids = jnp.arange(f2.shape[0], dtype=f2.dtype)
    return f2, m2, has, jnp.sum(f2 == ids)


def _parse_gnm(spec: str):
    parts = spec.lower().split("x")
    if len(parts) != 2:
        raise SystemExit(f"bad --gnm {spec!r}; expected NODESxEDGES")
    return int(parts[0]), int(parts[1])


def profile_rmat(args, kernel: str) -> dict:
    """Per-level + fused ELL profile of one big graph at ``kernel``."""
    from distributed_ghs_implementation_tpu.graphs.generators import (
        gnm_random_graph,
        rmat_graph,
    )
    from distributed_ghs_implementation_tpu.models.boruvka import (
        _solve_ell,
        prepare_ell_arrays,
    )

    t0 = time.perf_counter()
    if args.gnm:
        n, m = _parse_gnm(args.gnm)
        g = gnm_random_graph(n, m, seed=24)
        workload = f"gnm({n},{m})-seed24"
    else:
        g = rmat_graph(args.scale, args.edge_factor, seed=24)
        workload = f"rmat-{args.scale}x{args.edge_factor}-seed24"
    t_gen = time.perf_counter() - t0
    t0 = time.perf_counter()
    buckets, ra, rb, n_pad = prepare_ell_arrays(g)
    t_prep = time.perf_counter() - t0
    slot_total = sum(int(b[1].size) for b in buckets)
    print(
        f"{workload}: n={g.num_nodes:,} m={g.num_edges:,} "
        f"gen={t_gen:.1f}s prep={t_prep:.1f}s kernel={kernel} "
        f"buckets={len(buckets)} padded_slots={slot_total:,} "
        f"(directed={2 * g.num_edges:,})"
    )
    for verts, dstb, rankb in buckets:
        print(
            f"  bucket W={dstb.shape[1]:>6}  rows={dstb.shape[0]:>9,}  "
            f"slots={dstb.size:>11,}"
        )

    flat = []
    for b in buckets:
        flat.extend(b)
    flat.extend([ra, rb])
    nb = len(buckets)

    fragment = jnp.arange(n_pad, dtype=jnp.int32)
    mst_ranks = jnp.zeros(ra.shape[0], dtype=bool)
    # warm compile (int() forces a real sync; block_until_ready does not
    # block on the axon remote backend)
    f2, m2, has, nf = _one_level(fragment, mst_ranks, *flat, nbuckets=nb,
                                 kernel=kernel)
    _ = int(nf)

    fragment = jnp.arange(n_pad, dtype=jnp.int32)
    mst_ranks = jnp.zeros(ra.shape[0], dtype=bool)
    level = 0
    total = 0.0
    levels = []
    while True:
        t0 = time.perf_counter()
        fragment, mst_ranks, has, nfrag = _one_level(
            fragment, mst_ranks, *flat, nbuckets=nb, kernel=kernel
        )
        nfrag_i = int(nfrag)  # syncs the whole level
        dt = time.perf_counter() - t0
        total += dt
        level += 1
        levels.append({"level": level, "ms": round(dt * 1e3, 3),
                       "fragments": nfrag_i})
        print(f"level {level:2d}: {dt * 1e3:8.2f} ms  fragments={nfrag_i:,}")
        if not bool(has) or level > 40:
            break
    print(f"stepped total: {total:.3f} s")

    buckets_j = tuple(buckets)
    out = _solve_ell(buckets_j, ra, rb, num_nodes=n_pad, kernel=kernel)
    _ = int(out[2])
    times = []
    for _ in range(args.repeats):
        t0 = time.perf_counter()
        out = _solve_ell(buckets_j, ra, rb, num_nodes=n_pad, kernel=kernel)
        _ = int(out[2])
        times.append(time.perf_counter() - t0)
    fused_s = min(times)
    print(f"fused while_loop: best {fused_s:.3f} s, levels={int(out[2])}")

    if args.trace_dir:
        with jax.profiler.trace(args.trace_dir):
            out = _solve_ell(buckets_j, ra, rb, num_nodes=n_pad, kernel=kernel)
            jax.block_until_ready(out[0])
        print(f"trace written to {args.trace_dir}")

    ranks = np.nonzero(np.asarray(out[0]))[0]
    edge_ids = g.edge_id_of_rank(ranks)
    return {
        "workload": workload,
        "nodes": g.num_nodes,
        "edges": g.num_edges,
        "levels": levels,
        "stepped_s": total,
        "fused_s": fused_s,
        "level_count": int(out[2]),
        "mst_weight": int(g.w[edge_ids].sum()),
        "edges_per_sec": g.num_edges / fused_s,
    }


def profile_batch(args, kernel: str) -> dict:
    """The 16-lane batch workload: one-dispatch stacked solve + a
    host-stepped per-level breakdown of the same stack, at ``kernel``."""
    from distributed_ghs_implementation_tpu.batch.lanes import (
        execute_stacked,
        stack_lanes,
    )
    from distributed_ghs_implementation_tpu.graphs.generators import (
        gnm_random_graph,
    )
    from distributed_ghs_implementation_tpu.models.boruvka import (
        solve_arrays_stepped,
    )

    graphs = [
        gnm_random_graph(args.batch_nodes, args.batch_edges, seed=24_000 + i)
        for i in range(args.lanes)
    ]
    stacked = stack_lanes(graphs, lanes=args.lanes)
    workload = (
        f"batch-gnm({args.batch_nodes},{args.batch_edges})x{args.lanes}lanes"
    )
    print(f"{workload}: bucket ({stacked.n_pad}, {stacked.m_pad}) "
          f"kernel={kernel}")

    # One-dispatch stacked solve (the serving hot path).
    results = execute_stacked(stacked, kernel=kernel)  # warm: compile
    times = []
    for _ in range(max(args.repeats, 3)):
        t0 = time.perf_counter()
        results = execute_stacked(stacked, kernel=kernel)
        times.append(time.perf_counter() - t0)
    fused_s = min(times)
    gps = len(graphs) / fused_s
    print(f"one-dispatch stacked solve: best {fused_s * 1e3:.2f} ms "
          f"({gps:.1f} graphs/s)")

    # Host-stepped per-level breakdown of the SAME stacked arrays.
    src, dst, rank, ra, rb = (jnp.asarray(a) for a in stacked.arrays)
    n_total = stacked.lanes * stacked.n_pad
    fragment0 = jnp.arange(n_total, dtype=jnp.int32)
    levels = []

    def on_level(level, fragment, mst_ranks, has_np, count_np, wall_s):
        frags = int(np.sum(np.asarray(fragment) == np.arange(n_total)))
        levels.append({"level": level, "ms": round(wall_s * 1e3, 3),
                       "fragments": frags})
        print(f"level {level:2d}: {wall_s * 1e3:8.2f} ms  fragments={frags:,}")

    # Warm the stepped kernels outside the per-level clocks.
    solve_arrays_stepped(fragment0, src, dst, rank, ra, rb,
                         stepped_levels=None, kernel=kernel)
    _mst_ranks, _, level_count = solve_arrays_stepped(
        fragment0, src, dst, rank, ra, rb, stepped_levels=None,
        on_level=on_level, kernel=kernel,
    )
    stepped_s = sum(lv["ms"] for lv in levels) / 1e3
    print(f"stepped total: {stepped_s:.3f} s")

    total_weight = 0
    for g, (edge_ids, _frag, _lv) in zip(graphs, results):
        total_weight += int(g.w[edge_ids].sum())
    return {
        "workload": workload,
        "nodes": args.batch_nodes,
        "edges": args.batch_edges,
        "lanes": args.lanes,
        "levels": levels,
        "stepped_s": stepped_s,
        "fused_s": fused_s,
        "level_count": int(level_count),
        "mst_weight": total_weight,
        "graphs_per_sec": gps,
    }


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--workload", choices=["rmat", "batch"], default="rmat")
    p.add_argument("--scale", type=int, default=20)
    p.add_argument("--edge-factor", type=int, default=16)
    p.add_argument(
        "--gnm", metavar="NODESxEDGES",
        help="profile a seeded G(n,m) graph instead of RMAT (NumPy RNG — "
        "bit-identical on every host, the CI-gateable generator)",
    )
    p.add_argument("--lanes", type=int, default=16,
                   help="lane count for --workload batch")
    p.add_argument("--batch-nodes", type=int, default=128)
    p.add_argument("--batch-edges", type=int, default=480)
    p.add_argument("--repeats", type=int, default=3)
    p.add_argument("--kernel", choices=["auto", "pallas", "xla"], default=None)
    p.add_argument(
        "--compare-kernels", action="store_true",
        help="profile the XLA path AND the resolved kernel; report "
        "level_kernel_speedup (the gate-kernel-v1 metric)",
    )
    p.add_argument("--json", help="write the ghs-level-profile-v1 report here")
    p.add_argument("--trace-dir", default=None,
                   help="write a jax profiler trace here (rmat workload)")
    p.add_argument(
        "--tune-record", default=None, metavar="PATH",
        help="install this ghs-tuning-v1 record (written by `ghs tune`) "
        "before kernel resolution, so the profiled variant is the "
        "measured per-bucket winner; the receipt embeds the tuning "
        "summary",
    )
    args = p.parse_args()

    from distributed_ghs_implementation_tpu.ops.pallas_kernels import (
        kernel_choice,
        kernel_report,
        tuned_summary,
    )

    if args.tune_record:
        from distributed_ghs_implementation_tpu.tune import load_and_install

        installed = load_and_install(args.tune_record)
        print(f"tune record: {installed} bucket(s) installed")

    resolved = kernel_choice(args.kernel)
    profile = profile_rmat if args.workload == "rmat" else profile_batch

    compare = None
    if args.compare_kernels and resolved != "xla":
        print("--- kernel=xla (baseline) ---")
        compare = profile(args, "xla")
        print(f"--- kernel={resolved} ---")
    report = profile(args, resolved)
    if args.compare_kernels and compare is None:
        # Resolved already IS xla (fallback or explicit): the comparison
        # pair is the same path twice — skip the re-run and pin the
        # speedup at exactly 1.0 rather than publishing two-run noise as
        # if it were a kernel effect.
        compare = dict(report)

    throughput_key = (
        "edges_per_sec" if args.workload == "rmat" else "graphs_per_sec"
    )
    metrics = {
        throughput_key: report[throughput_key],
        "fused_s": report["fused_s"],
        "stepped_s": report["stepped_s"],
        "levels": report["level_count"],
        "mst_weight": report["mst_weight"],
    }
    if compare is not None:
        speedup = (
            1.0 if compare is report or compare == report
            else compare["fused_s"] / report["fused_s"]
        )
        metrics["level_kernel_speedup"] = speedup
        print(f"level_kernel_speedup ({resolved} vs xla): {speedup:.3f}x")

    out = {
        "schema": SCHEMA,
        "workload": args.workload,
        "kernel": {"requested": args.kernel or "auto", "resolved": resolved,
                   "report": kernel_report()},
        "tuning": tuned_summary(),
        "config": {"workload": report["workload"]},
        "levels": report["levels"],
        "stepped_s": report["stepped_s"],
        "fused_s": report["fused_s"],
        "xla_fused_s": (compare or report)["fused_s"],
        "gate_metrics": {
            "schema": "ghs-bench-metrics-v1",
            "config": {"workload": report["workload"]},
            "metrics": metrics,
        },
    }
    if args.json:
        with open(args.json, "w") as f:
            json.dump(out, f, indent=2)
            f.write("\n")
        print(f"report written to {args.json}")


if __name__ == "__main__":
    main()
