"""Exporters for the event bus: Chrome-trace/Perfetto JSON, JSONL, stats.

Three views of the same ring buffer:

* :func:`write_chrome_trace` — the Chrome ``traceEvents`` JSON format, which
  Perfetto (https://ui.perfetto.dev) and ``chrome://tracing`` both load
  directly: spans become ``"X"`` slices that nest by time per thread track,
  counters become ``"C"`` timeline tracks.
* :func:`write_events_jsonl` — one JSON object per line (stream-appendable,
  grep-able), with a trailing ``"M"`` metadata line carrying the counter
  totals and histogram summaries so a log file is self-contained.
* :func:`render_stats` — the plain-text summary behind the ``stats``
  subcommand, computed from a live bus or a parsed JSONL file.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Tuple

from distributed_ghs_implementation_tpu.obs.events import (
    PH_COMPLETE,
    PH_COUNTER,
    PH_INSTANT,
    EventBus,
    aggregate_span_stats,
)


def _jsonable(value: Any) -> Any:
    """Lazy serialization boundary: coerce arbitrary arg values to JSON."""
    if value is None or isinstance(value, (bool, int, float, str)):
        return value
    if isinstance(value, dict):
        return {str(k): _jsonable(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [_jsonable(v) for v in value]
    try:  # numpy scalars expose item()
        return value.item()
    except AttributeError:
        return repr(value)


def _tid_map(events) -> Dict[int, int]:
    """Stable small-int thread ids (raw idents are unreadable in a trace)."""
    mapping: Dict[int, int] = {}
    for rec in events:
        mapping.setdefault(rec[5], len(mapping))
    return mapping


def chrome_trace_events(bus: EventBus) -> List[dict]:
    """Bus records as Chrome ``traceEvents`` dicts (timestamps in µs)."""
    events = bus.events()
    tids = _tid_map(events)
    pid = os.getpid()
    out: List[dict] = []
    for ph, name, cat, ts_ns, dur_ns, tid, args in events:
        ev: Dict[str, Any] = {
            "name": name,
            "cat": cat,
            "ph": ph,
            "ts": ts_ns / 1000.0,
            "pid": pid,
            "tid": tids[tid],
        }
        if ph == PH_COMPLETE:
            ev["dur"] = dur_ns / 1000.0
        if ph == PH_COUNTER:
            ev["args"] = {"value": _jsonable((args or {}).get("value", 0))}
        elif args:
            ev["args"] = _jsonable(args)
        if ph == PH_INSTANT:
            ev["s"] = "t"  # thread-scoped instant marker
        out.append(ev)
    # Counter totals as a final sample each, so every counter has a track
    # even if no timeline samples were taken during the run.
    end_ts = max((e["ts"] + e.get("dur", 0.0) for e in out), default=0.0)
    for name, value in sorted(bus.counters().items()):
        out.append(
            {
                "name": name,
                "cat": "counter",
                "ph": PH_COUNTER,
                "ts": end_ts,
                "pid": pid,
                "tid": 0,
                "args": {"value": _jsonable(value)},
            }
        )
    return out


def to_chrome_trace(bus: EventBus) -> dict:
    return {
        "traceEvents": chrome_trace_events(bus),
        "displayTimeUnit": "ms",
        "otherData": {
            "generator": "distributed_ghs_implementation_tpu.obs",
            "events_dropped": bus.dropped,
        },
    }


def write_chrome_trace(bus: EventBus, path: str) -> str:
    with open(path, "w") as f:
        json.dump(to_chrome_trace(bus), f)
        f.write("\n")
    return path


def write_events_jsonl(bus: EventBus, path: str) -> str:
    """Events one-per-line, bracketed by metadata: a LEADING header line
    (ring capacity + dropped count at export time) and a TRAILING line with
    the counter/histogram totals. The header exists so a log truncated
    mid-write — the normal state of a file another process is tailing —
    still tells the reader whether the ring overflowed; a measurement that
    dropped events must be flagged, never silently under-counted."""
    with open(path, "w") as f:
        f.write(
            json.dumps(
                {
                    "ph": "M",
                    "kind": "header",
                    "schema": "ghs-obs-jsonl-v1",
                    "capacity": bus.capacity,
                    "events_dropped": bus.dropped,
                }
            )
            + "\n"
        )
        for ph, name, cat, ts_ns, dur_ns, tid, args in bus.events():
            rec = {
                "ph": ph,
                "name": name,
                "cat": cat,
                "ts_us": ts_ns / 1000.0,
            }
            if ph == PH_COMPLETE:
                rec["dur_us"] = dur_ns / 1000.0
            if args:
                rec["args"] = _jsonable(args)
            f.write(json.dumps(rec) + "\n")
        f.write(
            json.dumps(
                {
                    "ph": "M",
                    "counters": _jsonable(bus.counters()),
                    "histograms": _jsonable(bus.histograms()),
                    "events_dropped": bus.dropped,
                }
            )
            + "\n"
        )
    return path


def read_events_jsonl(path: str) -> Tuple[List[dict], dict]:
    """Parse a JSONL event log; returns ``(event_dicts, metadata)``.

    Tolerant of files still being written (or truncated by a crash): a
    line that fails to parse — typically the torn final line of a
    concurrent writer — is *skipped and counted* (``lines_skipped`` in the
    metadata), never raised. Metadata merges the leading header under the
    trailing totals line, so a log cut off before its trailing ``"M"``
    line still reports the header's ``events_dropped``.
    """
    events: List[dict] = []
    header: dict = {}
    meta: dict = {}
    skipped = 0
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                skipped += 1
                continue
            if not isinstance(rec, dict):
                skipped += 1
                continue
            if rec.get("ph") == "M":
                if rec.get("kind") == "header":
                    header = rec
                else:
                    meta = rec
            else:
                events.append(rec)
    merged = {**header, **meta}
    merged.pop("kind", None)
    if skipped:
        merged["lines_skipped"] = skipped
    return events, merged


def snapshot_from_jsonl(path: str) -> dict:
    """Rebuild a :meth:`EventBus.snapshot`-shaped dict from a JSONL log."""
    events, meta = read_events_jsonl(path)
    spans, instants = aggregate_span_stats(
        (rec.get("ph"), rec.get("name"), rec.get("dur_us", 0.0) / 1e6)
        for rec in events
    )
    snap = {
        "schema": "ghs-obs-snapshot-v1",
        "spans": spans,
        "instants": instants,
        "counters": meta.get("counters", {}),
        "histograms": meta.get("histograms", {}),
        "events_retained": len(events),
        "events_dropped": meta.get("events_dropped", 0),
    }
    if meta.get("lines_skipped"):
        snap["lines_skipped"] = meta["lines_skipped"]
    return snap


def _fmt_s(seconds: float) -> str:
    if seconds >= 1.0:
        return f"{seconds:8.3f}s "
    if seconds >= 1e-3:
        return f"{seconds * 1e3:8.3f}ms"
    return f"{seconds * 1e6:8.1f}µs"


def render_stats(snapshot: dict) -> str:
    """Human-readable summary of a snapshot (live bus or JSONL-derived)."""
    lines: List[str] = []
    spans = snapshot.get("spans", {})
    if spans:
        lines.append("spans (by total time):")
        lines.append(
            f"  {'name':<32} {'count':>7} {'total':>10} {'mean':>10} {'max':>10}"
        )
        for name, agg in sorted(
            spans.items(), key=lambda kv: -kv[1]["total_s"]
        ):
            lines.append(
                f"  {name:<32} {agg['count']:>7} {_fmt_s(agg['total_s']):>10}"
                f" {_fmt_s(agg['mean_s']):>10} {_fmt_s(agg['max_s']):>10}"
            )
    instants = snapshot.get("instants", {})
    if instants:
        lines.append("instants:")
        for name, count in sorted(instants.items()):
            lines.append(f"  {name:<32} {count:>7}")
    counters = snapshot.get("counters", {})
    if counters:
        lines.append("counters:")
        for name, value in sorted(counters.items()):
            value = int(value) if float(value).is_integer() else value
            lines.append(f"  {name:<40} {value:>12}")
    hists = snapshot.get("histograms", {})
    if hists:
        lines.append("histograms:")
        for name, h in sorted(hists.items()):
            if not h.get("count"):
                continue
            lines.append(
                f"  {name:<32} count={h['count']} mean={h['mean']:.2f} "
                f"p50={h['p50']:.2f} p90={h['p90']:.2f} p99={h['p99']:.2f} "
                f"max={h['max']:.2f}"
            )
    dropped = snapshot.get("events_dropped", 0)
    lines.append(
        f"events: {snapshot.get('events_retained', 0)} retained, "
        f"{dropped} dropped (ring overflow)"
    )
    if dropped:
        lines.append(
            f"WARNING: ring overflow dropped {dropped} events — span tables "
            "above under-count; counters/histograms are still complete"
        )
    if snapshot.get("lines_skipped"):
        lines.append(
            f"WARNING: {snapshot['lines_skipped']} unparseable JSONL "
            "line(s) skipped (torn write?)"
        )
    return "\n".join(lines)


def save_snapshot(bus: EventBus, path: str) -> str:
    with open(path, "w") as f:
        json.dump(bus.snapshot(), f, indent=2)
        f.write("\n")
    return path


def load_snapshot(path: str) -> Optional[dict]:
    with open(path) as f:
        return json.load(f)
