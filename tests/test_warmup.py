"""Warm-path executor (round 10): AOT bucket precompilation, warmup
record/replay, the persistent compile cache plumbing, and pipelined
batch dispatch.

Gates: a warmup-precompiled bucket serves its first query with ZERO
request-time lane-solver compiles (``compile.miss`` stays 0 and the
request counts ``batch.compile.hit`` / ``compile.hit`` — never a fresh
compile); record -> restart -> replay round-trips to the same guarantee;
and the pipelined ``solve_many`` is result- and incident-identical to the
synchronous path.
"""

import os

import numpy as np
import pytest

from distributed_ghs_implementation_tpu.api import minimum_spanning_forest
from distributed_ghs_implementation_tpu.batch.engine import BatchEngine
from distributed_ghs_implementation_tpu.batch.lanes import (
    bucket_key,
    clear_solver_cache,
    compiled_bucket_keys,
    precompile_bucket,
)
from distributed_ghs_implementation_tpu.batch.policy import BatchPolicy
from distributed_ghs_implementation_tpu.batch.warmup import (
    WarmupPlan,
    bucket_of,
    default_ladder,
    load_bucket_record,
    merge_plans,
    parse_bucket_list,
    run_warmup,
    save_bucket_record,
)
from distributed_ghs_implementation_tpu.graphs.generators import gnm_random_graph
from distributed_ghs_implementation_tpu.obs.events import BUS
from distributed_ghs_implementation_tpu.utils.resilience import (
    FAULTS,
    SupervisorConfig,
)


@pytest.fixture(autouse=True)
def _clean_global_bus():
    BUS.enable()
    BUS.clear()
    yield
    BUS.enable()
    BUS.clear()


def _fast_config():
    return SupervisorConfig(retries_per_rung=1, backoff_base_s=0.0)


def _counter(name: str) -> float:
    return BUS.counters().get(name, 0)


# ----------------------------------------------------------------------
# Plans: parsing, ladders, merging, record files
# ----------------------------------------------------------------------
def test_parse_bucket_list_buckets_raw_sizes():
    # Raw workload sizes bucket exactly like requests do, duplicates collapse.
    assert parse_bucket_list("128x512,300x1200") == [(128, 512), (512, 2048)]
    assert parse_bucket_list("100x300, 128x512") == [(128, 512)]
    assert parse_bucket_list("") == []


def test_parse_bucket_list_auto_is_the_ladder():
    ladder = parse_bucket_list("auto")
    assert ladder == default_ladder()
    assert ladder
    for n, m in ladder:
        assert n & (n - 1) == 0 and m & (m - 1) == 0  # padded shapes


def test_parse_bucket_list_rejects_garbage():
    with pytest.raises(ValueError, match="bucket spec"):
        parse_bucket_list("128")
    with pytest.raises(ValueError):
        parse_bucket_list("ax b")
    with pytest.raises(ValueError, match="positive"):
        parse_bucket_list("0x8")


def test_merge_plans_unions_and_keeps_lane_geometry():
    a = WarmupPlan(buckets=((128, 512),), lanes=4)
    b = WarmupPlan(buckets=((128, 512), (256, 1024)), keys=((64, 256, 8, "fused"),))
    merged = merge_plans(a, b)
    assert merged.buckets == ((128, 512), (256, 1024))
    assert merged.keys == ((64, 256, 8, "fused"),)
    assert merged.lanes == 4


def test_bucket_record_round_trip(tmp_path):
    clear_solver_cache()
    precompile_bucket(64, 256, 4, "fused")
    path = str(tmp_path / "buckets.json")
    assert save_bucket_record(path) == 1
    plan = load_bucket_record(path)
    assert plan.keys == ((64, 256, 4, "fused"),)


def test_load_bucket_record_rejects_bad_schema(tmp_path):
    path = tmp_path / "bad.json"
    path.write_text('{"schema": "something-else", "buckets": []}')
    with pytest.raises(ValueError, match="schema"):
        load_bucket_record(str(path))


@pytest.mark.parametrize("entry,why", [
    ([64, 256, 4], "arity"),                 # 3-wide, not 4
    ([64, "lots", 4, "fused"], "int"),       # non-int edge count
    ([64.5, 256, 4, "fused"], "int"),        # float nodes
    ([True, 256, 4, "fused"], "int"),        # bool is not an int here
    ([-64, 256, 4, "fused"], "positive"),    # negative size
    ([64, 0, 4, "fused"], "positive"),       # zero size
    ([64, 256, -1, "fused"], "lanes"),       # negative lanes
    ([64, 256, 4, "warp"], "mode"),          # unknown mode string
], ids=["arity", "str-edges", "float-nodes", "bool-nodes", "neg-nodes",
        "zero-edges", "neg-lanes", "bad-mode"])
def test_load_bucket_record_names_the_malformed_entry(tmp_path, entry, why):
    """Satellite (round 23): a hand-edited record with ONE bad entry
    raises a typed WarmupRecordError naming that entry — never a bare
    unpacking/astype traceback mid-boot."""
    import json as _json

    from distributed_ghs_implementation_tpu.batch.warmup import (
        RECORD_SCHEMA,
        WarmupRecordError,
    )

    path = tmp_path / "record.json"
    path.write_text(_json.dumps({
        "schema": RECORD_SCHEMA,
        "buckets": [[64, 256, 4, "fused"], entry],
    }))
    with pytest.raises(WarmupRecordError) as exc:
        load_bucket_record(str(path))
    msg = str(exc.value)
    assert "#1" in msg            # names WHICH entry
    assert repr(entry) in msg     # and shows it verbatim
    assert isinstance(exc.value, ValueError)  # old handlers keep working


def test_load_bucket_record_rejects_non_list_buckets(tmp_path):
    from distributed_ghs_implementation_tpu.batch.warmup import (
        RECORD_SCHEMA,
        WarmupRecordError,
    )

    path = tmp_path / "record.json"
    path.write_text('{"schema": "%s", "buckets": {"a": 1}}' % RECORD_SCHEMA)
    with pytest.raises(WarmupRecordError, match="list"):
        load_bucket_record(str(path))


def test_plan_from_flags_threads_tuning_and_merge_carries_it(tmp_path):
    from distributed_ghs_implementation_tpu.batch.warmup import plan_from_flags

    plan = plan_from_flags(buckets="64x256", lanes=2, tuning="/tmp/t.json")
    assert plan.tuning == "/tmp/t.json"
    merged = merge_plans(WarmupPlan(buckets=((64, 256),)), plan)
    assert merged.tuning == "/tmp/t.json"


def test_run_warmup_installs_plan_tuning_record(tmp_path):
    """WarmupPlan.tuning is installed BEFORE any precompile, so warmed
    buckets compile the measured variant (round 23)."""
    from distributed_ghs_implementation_tpu.ops import pallas_kernels as pk
    from distributed_ghs_implementation_tpu.tune.measure import search
    from distributed_ghs_implementation_tpu.tune.record import save_record

    pk._reset_for_tests()
    try:
        rec = search([(64, 256, 2, "fused")], dry=True)
        path = str(tmp_path / "tuning.json")
        save_record(rec, path)
        clear_solver_cache()
        report = run_warmup(
            WarmupPlan(buckets=((64, 256),), lanes=2, tuning=path)
        )
        assert report["tuned_entries"] == 1
        summary = pk.tuned_summary()
        assert summary and summary["entries"] == 1
    finally:
        pk._reset_for_tests()


# ----------------------------------------------------------------------
# AOT precompilation: zero request-time compiles
# ----------------------------------------------------------------------
def test_precompiled_bucket_serves_first_query_without_compiling():
    """The tentpole guarantee: after warmup covers a bucket, the first
    request on it is a compile-cache HIT — ``compile.miss`` stays zero."""
    clear_solver_cache()
    graphs = [gnm_random_graph(50, 150, seed=s) for s in range(3)]
    n_pad, m_pad = bucket_key(graphs[0])
    assert precompile_bucket(n_pad, m_pad, 4, "fused") is True
    assert _counter("compile.warmup") == 1
    assert _counter("compile.miss") == 0
    # Idempotent: a second precompile is a cache hit, not a recompile.
    assert precompile_bucket(n_pad, m_pad, 4, "fused") is False

    engine = BatchEngine(policy=BatchPolicy(max_lanes=4))
    results = engine.solve_many(graphs)
    assert _counter("compile.miss") == 0
    assert _counter("compile.hit") >= 1
    assert _counter("batch.compile.hit") >= 1
    for g, r in zip(graphs, results):
        assert np.array_equal(r.edge_ids, minimum_spanning_forest(g).edge_ids)


def test_run_warmup_reports_compiled_vs_cached(monkeypatch):
    # The report's "kernel" key resolves through kernel_choice: shield the
    # exact-dict assertion below from an ambient GHS_KERNEL in the shell.
    monkeypatch.delenv("GHS_KERNEL", raising=False)
    clear_solver_cache()
    plan = WarmupPlan(buckets=((64, 256),), lanes=4)
    first = run_warmup(plan)
    assert first["compiled"] == 1 and first["cached"] == 0
    assert first["single_warmed"] == 1
    again = run_warmup(plan)
    assert again["compiled"] == 0 and again["cached"] == 1
    assert run_warmup(WarmupPlan()) == {
        "buckets": 0, "compiled": 0, "cached": 0, "skipped": 0,
        "single_warmed": 0, "mesh_warmed": 0, "mesh_skipped": 0,
        "stream_warmed": 0, "stream_sharded_warmed": 0,
        "kernel": "xla", "tuned_entries": 0, "wall_s": 0.0,
    }


def test_warmup_replay_round_trip_restart_compiles_nothing_at_request_time():
    """Record buckets from live traffic -> 'restart' (solver cache
    cleared) -> replay -> the query phase performs zero request-time
    compiles (the satellite-4 acceptance)."""
    clear_solver_cache()
    graphs = [gnm_random_graph(40, 100, seed=s) for s in range(4)]
    engine = BatchEngine(policy=BatchPolicy(max_lanes=4))
    engine.solve_many(graphs)  # cold process: this pays a request-time compile
    assert _counter("compile.miss") >= 1
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        record = os.path.join(d, "buckets.json")
        assert save_bucket_record(record) >= 1

        clear_solver_cache()  # simulated restart
        BUS.clear()
        run_warmup(load_bucket_record(record))
        assert _counter("compile.warmup") >= 1
        assert _counter("compile.miss") == 0

        engine2 = BatchEngine(policy=BatchPolicy(max_lanes=4))
        results = engine2.solve_many(graphs)
        assert _counter("compile.miss") == 0  # zero request-time compiles
        assert _counter("batch.compile.hit") >= 1
        for g, r in zip(graphs, results):
            assert np.array_equal(
                r.edge_ids, minimum_spanning_forest(g).edge_ids
            )


def test_scheduler_solve_batch_after_warmup_is_a_compile_hit():
    """The satellite-3 fix: a warmup-precompiled bucket reached through
    ``solve_batch`` counts as a compile-cache hit, never a fresh compile."""
    from distributed_ghs_implementation_tpu.serve.scheduler import SolveScheduler

    clear_solver_cache()
    g1 = gnm_random_graph(50, 150, seed=31)
    g2 = gnm_random_graph(50, 150, seed=32)
    n_pad, m_pad = bucket_key(g1)
    precompile_bucket(n_pad, m_pad, 4, "fused")
    misses_after_warmup = _counter("batch.compile.miss")
    engine = BatchEngine(
        policy=BatchPolicy(max_lanes=4), supervisor_config=_fast_config()
    )
    sched = SolveScheduler(batch_engine=engine)
    out = sched.solve_batch([g1, g2])
    assert [s for _, s in out] == ["solved", "solved"]
    assert _counter("batch.compile.miss") == misses_after_warmup  # no new ones
    assert _counter("batch.compile.hit") >= 1
    assert _counter("compile.miss") == 0


def test_service_warmup_phase(tmp_path):
    from distributed_ghs_implementation_tpu.serve.service import MSTService

    clear_solver_cache()
    svc = MSTService(
        batch_lanes=2,
        warmup=WarmupPlan(buckets=(bucket_of(60, 180),)),
    )
    assert svc.warmup_report is not None
    assert svc.warmup_report["compiled"] >= 1
    # The service filled in its own lane geometry (lanes=2).
    assert (64, 256, 2, "fused") in compiled_bucket_keys()
    g = gnm_random_graph(60, 180, seed=11)
    edges = [[int(a), int(b), int(c)] for a, b, c in zip(g.u, g.v, g.w)]
    first = svc.handle({"op": "solve", "num_nodes": 60, "edges": edges})
    assert first["ok"] and first["backend"] == "batch/fused"
    stats = svc.handle({"op": "stats"})
    assert stats["warmup"]["compiled"] >= 1
    assert stats["counters"].get("compile.warmup", 0) >= 1
    assert stats["counters"].get("compile.miss", 0) == 0  # warm first query
    assert stats["counters"].get("compile.hit", 0) >= 1


def test_service_rejects_non_plan_warmup():
    from distributed_ghs_implementation_tpu.serve.service import MSTService

    with pytest.raises(TypeError, match="WarmupPlan"):
        MSTService(warmup={"buckets": [(64, 256)]})


# ----------------------------------------------------------------------
# Persistent compile cache
# ----------------------------------------------------------------------
def test_persistent_cache_enable_and_stats(tmp_path):
    import jax

    from distributed_ghs_implementation_tpu.utils import compile_cache as cc

    d = str(tmp_path / "xla-cache")
    try:
        assert cc.enable_persistent_cache(d) == os.path.abspath(d)
        assert os.path.isdir(d)
        # Compile something novel so an entry lands on disk.
        fn = jax.jit(lambda x: x * 3 + 7)
        np.asarray(fn(np.arange(16, dtype=np.int32)))
        stats = cc.cache_stats()
        assert stats["enabled"] and stats["dir"] == os.path.abspath(d)
        assert stats["entries"] >= 1
        assert stats["bytes"] > 0
    finally:
        cc.disable_persistent_cache()
    assert cc.cache_stats()["enabled"] is False


# ----------------------------------------------------------------------
# Pipelined dispatch
# ----------------------------------------------------------------------
def test_pipelined_solve_many_parity_and_counters():
    graphs = [gnm_random_graph(60, 150, seed=s) for s in range(12)]
    engine = BatchEngine(
        policy=BatchPolicy(
            max_lanes=4, pipeline_depth=2, pipeline_min_stack_elems=0
        ),
        supervisor_config=_fast_config(),
    )
    results = engine.solve_many(graphs)
    counters = BUS.counters()
    assert counters["batch.batches.formed"] == 3
    assert counters["batch.pipeline.batches"] == 3
    assert counters["batch.lanes.formed"] == 12
    hists = BUS.histograms()
    assert hists["batch.form_s"]["count"] == 3
    assert hists["batch.pipeline.stall_s"]["count"] == 3
    for g, r in zip(graphs, results):
        seq = minimum_spanning_forest(g)
        assert np.array_equal(r.edge_ids, seq.edge_ids)
        assert r.backend == "batch/fused"


def test_pipeline_depth_one_is_fully_synchronous():
    graphs = [gnm_random_graph(60, 150, seed=s) for s in range(8)]
    engine = BatchEngine(
        policy=BatchPolicy(max_lanes=4, pipeline_depth=1),
        supervisor_config=_fast_config(),
    )
    results = engine.solve_many(graphs)
    counters = BUS.counters()
    assert counters["batch.batches.formed"] == 2
    assert "batch.pipeline.batches" not in counters
    for g, r in zip(graphs, results):
        assert np.array_equal(r.edge_ids, minimum_spanning_forest(g).edge_ids)


def test_single_batch_skips_the_pipeline():
    graphs = [gnm_random_graph(60, 150, seed=s) for s in range(3)]
    engine = BatchEngine(
        policy=BatchPolicy(
            max_lanes=4, pipeline_depth=2, pipeline_min_stack_elems=0
        )
    )
    engine.solve_many(graphs)
    assert "batch.pipeline.batches" not in BUS.counters()


def test_pipelined_retry_and_fallback_identical_to_sync():
    """Injected batch faults behave exactly as on the synchronous path:
    every batch degrades to per-lane supervised solves, results stay
    correct, incidents stay per-lane."""
    graphs = [gnm_random_graph(40, 100, seed=s) for s in range(8)]
    engine = BatchEngine(
        policy=BatchPolicy(
            max_lanes=4, pipeline_depth=2, pipeline_min_stack_elems=0
        ),
        supervisor_config=_fast_config(),
    )
    with FAULTS.inject("batch.attempt", times=100):
        results = engine.solve_many(graphs)
    counters = BUS.counters()
    assert counters["batch.pipeline.batches"] == 2
    assert counters["batch.lane.fallback"] == 8
    for g, r in zip(graphs, results):
        assert np.array_equal(r.edge_ids, minimum_spanning_forest(g).edge_ids)
        assert r.backend.startswith("supervised/")
        assert r.incidents is not None
        assert [rec.rung for rec in r.incidents.records][:2] == ["batch", "batch"]


def test_pipelined_forming_error_propagates_like_sync():
    """A former-thread stacking failure surfaces as the same exception the
    synchronous path raises (re-stacked on the dispatch thread), and the
    former shuts down instead of leaking."""
    import distributed_ghs_implementation_tpu.batch.engine as eng_mod

    graphs = [gnm_random_graph(60, 150, seed=s) for s in range(8)]
    engine = BatchEngine(
        policy=BatchPolicy(
            max_lanes=4, pipeline_depth=2, pipeline_min_stack_elems=0
        ),
        supervisor_config=_fast_config(),
    )

    def boom(*a, **k):
        raise ValueError("stacking exploded")

    orig = eng_mod.stack_lanes
    eng_mod.stack_lanes = boom
    try:
        with pytest.raises(ValueError, match="stacking exploded"):
            engine.solve_many(graphs)
    finally:
        eng_mod.stack_lanes = orig


def test_policy_rejects_bad_pipeline_depth():
    with pytest.raises(ValueError, match="pipeline_depth"):
        BatchPolicy(pipeline_depth=0)


def test_small_stacks_stay_synchronous_by_default():
    """The default ``pipeline_min_stack_elems`` floor: tiny per-batch
    stacks (where handoff overhead beats the overlap) run synchronously
    even at pipeline_depth=2."""
    graphs = [gnm_random_graph(60, 150, seed=s) for s in range(8)]  # 2 batches
    engine = BatchEngine(policy=BatchPolicy(max_lanes=4))
    results = engine.solve_many(graphs)
    counters = BUS.counters()
    assert counters["batch.batches.formed"] == 2
    assert "batch.pipeline.batches" not in counters
    for g, r in zip(graphs, results):
        assert np.array_equal(r.edge_ids, minimum_spanning_forest(g).edge_ids)


def test_shape_only_record_entries_warm_single_graph_kernel(tmp_path):
    """A serve without the lane engine records traffic shapes with
    ``lanes=0``; replay warms the single-graph kernel for them and
    precompiles no lane solver."""
    clear_solver_cache()
    path = str(tmp_path / "rec.json")
    assert save_bucket_record(path, shape_buckets=[(128, 4)]) == 1
    plan = load_bucket_record(path)
    assert plan.keys == ((128, 4, 0, "fused"),)
    report = run_warmup(plan)
    assert report["buckets"] == 0 and report["compiled"] == 0
    assert report["single_warmed"] == 1
    assert compiled_bucket_keys() == []  # no lane solver materialized


def test_concurrent_get_solver_compiles_once():
    """Two threads racing a cold bucket: one leads the compile (outside
    the cache lock), the other waits and reads the published entry —
    exactly one ``batch.compile.miss``, and a hit on an UNRELATED warm
    bucket is never blocked behind it."""
    import threading

    from distributed_ghs_implementation_tpu.batch.lanes import _get_solver

    clear_solver_cache()
    results = []

    def worker():
        results.append(_get_solver(32, 64, 3, "fused"))

    threads = [threading.Thread(target=worker) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert len(results) == 4
    assert all(r is results[0] for r in results)  # one shared executable
    assert _counter("batch.compile.miss") == 1
    assert _counter("batch.compile.hit") == 3


def test_oversize_buckets_are_not_single_warmed():
    """Buckets the solver routes to the rank solver must never be warmed
    through the fused kernel (a replay would otherwise pay boot-time
    compiles no request ever hits) — and the service must not record
    them."""
    from distributed_ghs_implementation_tpu.batch.warmup import (
        MAX_SINGLE_WARM_EDGES,
        warmable_single,
    )

    assert warmable_single(64, 256)
    assert not warmable_single(64, 2 * MAX_SINGLE_WARM_EDGES)
    report = run_warmup(
        WarmupPlan(buckets=((64, 2 * MAX_SINGLE_WARM_EDGES),), lanes=0)
    )
    assert report["single_warmed"] == 0


def test_service_records_seen_buckets_for_warmup_record():
    from distributed_ghs_implementation_tpu.serve.service import MSTService

    svc = MSTService()
    g = gnm_random_graph(60, 180, seed=21)
    edges = [[int(a), int(b), int(c)] for a, b, c in zip(g.u, g.v, g.w)]
    svc.handle({"op": "solve", "num_nodes": 60, "edges": edges})
    assert list(svc.seen_buckets) == [(64, 256)]


def test_precompile_bucket_rejects_request_unreachable_geometry():
    with pytest.raises(ValueError, match="int32 id space"):
        precompile_bucket(1 << 30, 1 << 20, 16, "fused")
    with pytest.raises(ValueError, match="lanes"):
        precompile_bucket(64, 256, 0, "fused")


def test_run_warmup_skips_buckets_past_the_admission_ceiling():
    """A typo'd spec must not stall boot compiling a lane solver the
    request path's admission check would never route to."""
    from distributed_ghs_implementation_tpu.batch.warmup import (
        MAX_SINGLE_WARM_EDGES,
    )

    clear_solver_cache()
    report = run_warmup(
        WarmupPlan(buckets=((64, 4 * MAX_SINGLE_WARM_EDGES),), lanes=4)
    )
    assert report["skipped"] == 1
    assert report["compiled"] == 0 and report["buckets"] == 0
    assert compiled_bucket_keys() == []


def test_service_normalizes_replayed_lane_geometry():
    """A record taken at --batch-lanes 16 replayed into --batch-lanes 2
    must warm THIS process's solvers — zero request-time compiles."""
    from distributed_ghs_implementation_tpu.serve.service import MSTService

    clear_solver_cache()
    plan = WarmupPlan(keys=((64, 256, 16, "fused"),))  # recorded elsewhere
    svc = MSTService(batch_lanes=2, warmup=plan)
    assert (64, 256, 2, "fused") in compiled_bucket_keys()  # normalized
    assert (64, 256, 16, "fused") not in compiled_bucket_keys()
    g = gnm_random_graph(60, 180, seed=12)
    edges = [[int(a), int(b), int(c)] for a, b, c in zip(g.u, g.v, g.w)]
    first = svc.handle({"op": "solve", "num_nodes": 60, "edges": edges})
    assert first["ok"] and first["backend"] == "batch/fused"
    stats = svc.handle({"op": "stats"})
    assert stats["counters"].get("compile.miss", 0) == 0


def test_traffic_only_record_excludes_warmup_ladder(tmp_path):
    """serve-style records converge to traffic: a compiled ladder bucket
    is NOT recorded unless traffic hit its shape."""
    clear_solver_cache()
    precompile_bucket(512, 2048, 4, "fused")  # a ladder compile, no traffic
    path = str(tmp_path / "rec.json")
    assert save_bucket_record(
        path, shape_buckets=[(64, 256)], include_compiled=False
    ) == 1
    assert load_bucket_record(path).keys == ((64, 256, 0, "fused"),)


def test_pipelined_former_unexpected_error_raises_not_hangs():
    """An error OUTSIDE stack_lanes in the former (e.g. a broken policy
    emitting out-of-range indices) must surface as the exception the
    synchronous path would raise — never a dead thread + eternal
    handoff.get()."""
    from distributed_ghs_implementation_tpu.batch.policy import FormedBatch

    engine = BatchEngine(
        policy=BatchPolicy(
            max_lanes=4, pipeline_depth=2, pipeline_min_stack_elems=0
        ),
        supervisor_config=_fast_config(),
    )
    graphs = [gnm_random_graph(60, 150, seed=s) for s in range(4)]
    bad = [
        FormedBatch(key=(64, 256), indices=(0, 99)),  # 99 out of range
        FormedBatch(key=(64, 256), indices=(1, 2)),
    ]
    results = [None] * len(graphs)
    with pytest.raises(IndexError):
        engine._solve_batches_pipelined(graphs, bad, results)


def test_bench_gate_throughput_floor_is_multiplicative():
    """At CI's loose --time-tolerance 5.0 an additive floor would be
    negative (gating nothing); the multiplicative floor still fails a
    broken-pipeline-style collapse."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "bench_gate_for_test",
        os.path.join(os.path.dirname(__file__), "..", "tools", "bench_gate.py"),
    )
    bg = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bg)
    base = {"schema": bg.SCHEMA, "metrics": {"batch_graphs_per_sec": 1000.0}}
    collapsed = {"schema": bg.SCHEMA, "metrics": {"batch_graphs_per_sec": 50.0}}
    ok, lines = bg.compare(base, collapsed, time_tolerance=5.0)
    assert not ok and any("FAIL" in line for line in lines)
    fine = {"schema": bg.SCHEMA, "metrics": {"batch_graphs_per_sec": 400.0}}
    ok, _ = bg.compare(base, fine, time_tolerance=5.0)
    assert ok
