"""Admission + batch-forming policy: which solves batch, with whom, when.

The policy is pure decision logic (no device work, no threads) so the
engine's queueing and the API's one-shot batching share one rule set:

* **Admission** — a graph batches only if its shape bucket is small enough
  that lane-stacking wins; oversize graphs *bypass* to the existing
  single-graph path (which routes big graphs to the rank solver anyway —
  batching is a small-graph throughput play, and one RMAT-20 lane would
  stall 15 small ones).
* **Forming** — admitted graphs group by :func:`lanes.bucket_key` and chunk
  into at most ``max_lanes`` lanes, preserving arrival order. Every formed
  batch solves at exactly ``max_lanes`` lanes (unfilled lanes are inert
  padding), so each bucket costs ONE compiled shape no matter how batches
  fill — the fill ratio is telemetry (``batch.fill_ratio``), not a compile
  key.
* **Waiting** — ``max_wait_s`` bounds how long the engine's queue holds a
  lone request open for lane-mates before dispatching it anyway.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Sequence, Tuple

from distributed_ghs_implementation_tpu.batch.lanes import BucketKey, bucket_key
from distributed_ghs_implementation_tpu.graphs.edgelist import Graph
from distributed_ghs_implementation_tpu.models.boruvka import (
    ELL_AUTO_EDGE_THRESHOLD,
)


@dataclasses.dataclass(frozen=True)
class FormedBatch:
    """One dispatchable batch: same-bucket input positions, arrival order."""

    key: BucketKey
    indices: Tuple[int, ...]


@dataclasses.dataclass(frozen=True)
class BatchPolicy:
    """Batching knobs (docs/BATCHING.md has the tuning guidance).

    ``max_lanes`` — lanes per device batch (and the compiled lane count).
    ``max_wait_s`` — queue hold time for an unfilled batch (engine only).
    ``max_bucket_edges`` / ``max_bucket_nodes`` — admission ceiling; graphs
    padding past either bypass to the single-graph path (the default edge
    ceiling is the solver's own small-graph routing threshold, below which
    the flat bucketed kernel — the one lanes stack — is the fast path).
    ``mode`` — lane execution: ``"fused"`` block-diagonal or ``"vmap"``.
    ``pipeline_depth`` — how many batches the engine's forming stage may
    run ahead of device execution in ``solve_many`` (2 = double-buffered:
    batch *k+1* stacks on a background thread while batch *k* executes;
    1 = fully synchronous, forming and execution strictly alternate).
    ``pipeline_min_stack_elems`` — smallest per-batch stacked array size
    (elements: ``8 * max_lanes * m_pad``) worth pipelining; below it the
    former thread's handoff overhead beats the overlap win (measured on
    CPU: 4x128-vertex lanes lose ~10% pipelined, 16 lanes win ~1.8x on
    run medians — docs/BENCH_NOTES.md) and ``solve_many`` stays
    synchronous. 0 forces pipelining whenever there are >= 2 batches.
    """

    max_lanes: int = 16
    max_wait_s: float = 0.002
    max_bucket_edges: int = ELL_AUTO_EDGE_THRESHOLD
    max_bucket_nodes: int = 1 << 16
    mode: str = "fused"
    pipeline_depth: int = 2
    pipeline_min_stack_elems: int = 32768

    def __post_init__(self):
        if self.max_lanes < 1:
            raise ValueError(f"max_lanes must be >= 1, got {self.max_lanes}")
        if self.max_wait_s < 0:
            raise ValueError(f"max_wait_s must be >= 0, got {self.max_wait_s}")
        if self.mode not in ("fused", "vmap"):
            raise ValueError(f"unknown lane mode {self.mode!r}")
        if self.pipeline_depth < 1:
            raise ValueError(
                f"pipeline_depth must be >= 1, got {self.pipeline_depth}"
            )
        if self.pipeline_min_stack_elems < 0:
            raise ValueError(
                f"pipeline_min_stack_elems must be >= 0, got "
                f"{self.pipeline_min_stack_elems}"
            )

    def admits(self, graph: Graph) -> bool:
        """Can this graph ride a lane (vs bypassing to the single path)?"""
        n_pad, m_pad = bucket_key(graph)
        return n_pad <= self.max_bucket_nodes and m_pad <= self.max_bucket_edges

    def route(self, graph: Graph, *, sharded_available: bool = False) -> str:
        """Where one solve goes, as a routing label: ``"lane"`` (admitted —
        the bucketed lane engine / small-graph path), ``"sharded_lane"``
        (oversize with a mesh lane attached — ``parallel/lane.py``), or
        ``"bypass"`` (oversize, no sharded lane: the legacy single-graph
        supervised path). The ONE encoding of the oversize decision — the
        serving scheduler stamps the label on its ``serve.solve`` spans so
        load/SLO summaries can tell the two oversize paths apart."""
        if self.admits(graph):
            return "lane"
        return "sharded_lane" if sharded_available else "bypass"

    def form(
        self, graphs: Sequence[Graph]
    ) -> Tuple[List[FormedBatch], List[int]]:
        """Partition a request list into formed batches + bypass positions.

        Returns ``(batches, bypass)`` where each :class:`FormedBatch` holds
        input positions of one same-bucket chunk (at most ``max_lanes``)
        and ``bypass`` holds positions of non-admitted graphs. Together
        they cover every input exactly once.
        """
        groups: Dict[BucketKey, List[int]] = {}
        bypass: List[int] = []
        for i, g in enumerate(graphs):
            if self.admits(g):
                groups.setdefault(bucket_key(g), []).append(i)
            else:
                bypass.append(i)
        batches: List[FormedBatch] = []
        for key, members in groups.items():
            for at in range(0, len(members), self.max_lanes):
                batches.append(
                    FormedBatch(key=key, indices=tuple(members[at:at + self.max_lanes]))
                )
        return batches, bypass
