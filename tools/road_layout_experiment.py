"""Road-family memory-layout experiment (VERDICT r3 item 3).

The road head (levels 1-2 at full width) sits at ~9 s of the 16.7 s
USA-road-size grid solve, all in gathers/segment-min at the measured
~9 ns/elem. The round-3 bisection (git fdc50ce) called that intrinsic for
*this layout*; the untried lever was a locality-aware vertex relabeling at
ingestion. This tool measures it directly: solve the same 23.9M-node grid
under (a) the generator's row-major labels, (b) BFS/wavefront order
(sort by i+j — the breadth order from a corner on a grid), and
(c) Hilbert-curve order, with per-phase timers on every jitted kernel.

The gather-table argument says labels should NOT matter: the index
streams are rank-ordered (weight order — a random permutation of edges),
so accesses into the n-sized parent/fragment tables are uniformly random
whatever the vertex numbering; relabeling permutes table VALUES, not the
randomness of the access sequence. A >=1.3x head win would falsify that;
a flat result records the negative with numbers.

Usage: python tools/road_layout_experiment.py [rows] [cols] [seed]
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def hilbert_order(rows: int, cols: int) -> np.ndarray:
    """Permutation old-id -> new-id by Hilbert curve index over the grid."""
    side = 1 << max(rows - 1, cols - 1, 1).bit_length()
    r = np.repeat(np.arange(rows, dtype=np.int64), cols)
    c = np.tile(np.arange(cols, dtype=np.int64), rows)
    x, y = c.copy(), r.copy()
    d = np.zeros(rows * cols, dtype=np.int64)
    s = side >> 1
    while s > 0:
        rx = ((x & s) > 0).astype(np.int64)
        ry = ((y & s) > 0).astype(np.int64)
        d += s * s * ((3 * rx) ^ ry)
        cond = ry == 0
        flip = cond & (rx == 1)
        xf = np.where(flip, s - 1 - x, x)
        yf = np.where(flip, s - 1 - y, y)
        x = np.where(cond, yf, xf)
        y = np.where(cond, xf, yf)
        s >>= 1
    perm = np.argsort(d, kind="stable")
    pi = np.empty(rows * cols, dtype=np.int64)
    pi[perm] = np.arange(rows * cols, dtype=np.int64)
    return pi


def wavefront_order(rows: int, cols: int) -> np.ndarray:
    """BFS-from-corner order on a grid == antidiagonal wavefronts."""
    r = np.repeat(np.arange(rows, dtype=np.int64), cols)
    c = np.tile(np.arange(cols, dtype=np.int64), rows)
    perm = np.lexsort((r, r + c))  # by wavefront, then row within it
    pi = np.empty(rows * cols, dtype=np.int64)
    pi[perm] = np.arange(rows * cols, dtype=np.int64)
    return pi


def relabel(graph, pi):
    from distributed_ghs_implementation_tpu.graphs.edgelist import Graph

    return Graph.from_arrays(
        graph.num_nodes, pi[graph.u], pi[graph.v], graph.w
    )


def solve_instrumented(g, label):
    import jax

    from distributed_ghs_implementation_tpu.models import rank_solver as rs

    t0 = time.perf_counter()
    vmin0, ra, rb, parent1 = rs.prepare_rank_arrays_full(g)
    jax.block_until_ready((vmin0, ra, rb, parent1))
    prep = time.perf_counter() - t0

    record = []

    def timed(name, fn):
        def w(*a, **k):
            t0 = time.perf_counter()
            out = fn(*a, **k)
            jax.block_until_ready(out)
            record.append((name, time.perf_counter() - t0))
            return out
        return w

    names = ["_rank_head", "_compact_and_mark", "_shrink_and_run",
             "_run_levels", "_finish_chunk"]
    saved = {n: getattr(rs, n) for n in names}
    best = None
    lv = 0
    try:
        for n in names:
            setattr(rs, n, timed(n, saved[n]))
        for i in range(3):
            record.clear()
            t0 = time.perf_counter()
            mst, frag, lv = rs.solve_rank_staged(
                vmin0, ra, rb, **rs._family_params(rs._pick_family(g)),
                parent1=parent1,
            )
            jax.block_until_ready((mst, frag))
            dt = time.perf_counter() - t0
            if best is None or dt < best[0]:
                best = (dt, list(record))
    finally:
        for n in names:
            setattr(rs, n, saved[n])

    by = {}
    for name, dt in best[1]:
        by.setdefault(name, [0.0, 0])
        by[name][0] += dt
        by[name][1] += 1
    log(f"[{label}] prep {prep:.1f}s best solve {best[0]:.2f}s levels={lv}")
    for name, (dt, cnt) in sorted(by.items(), key=lambda kv: -kv[1][0]):
        log(f"    {name:18s} {dt:6.2f}s x{cnt}")
    ids = rs.fetch_mst_edge_ids(g, mst)
    weight = int(g.w[ids].sum())
    # Drop the staged-array cache so the next labeling doesn't pin HBM.
    g.__dict__.pop("_rank_device_cache", None)
    return {
        "label": label, "prep_s": round(prep, 1),
        "solve_best_s": round(best[0], 2), "levels": int(lv),
        "phases": {k: [round(v[0], 2), v[1]] for k, v in by.items()},
        "weight": weight,
    }


def main():
    from distributed_ghs_implementation_tpu.graphs.generators import (
        road_grid_graph,
    )

    rows = int(sys.argv[1]) if len(sys.argv) > 1 else 4864
    cols = int(sys.argv[2]) if len(sys.argv) > 2 else 4912
    seed = int(sys.argv[3]) if len(sys.argv) > 3 else 8
    t0 = time.perf_counter()
    g = road_grid_graph(rows, cols, seed=seed)
    log(f"grid {rows}x{cols}: {g.num_nodes:,} nodes {g.num_edges:,} edges "
        f"in {time.perf_counter()-t0:.1f}s")

    results = [solve_instrumented(g, "row-major")]
    t0 = time.perf_counter()
    pi_w = wavefront_order(rows, cols)
    log(f"wavefront order in {time.perf_counter()-t0:.1f}s")
    results.append(solve_instrumented(relabel(g, pi_w), "bfs-wavefront"))
    del pi_w
    t0 = time.perf_counter()
    pi_h = hilbert_order(rows, cols)
    log(f"hilbert order in {time.perf_counter()-t0:.1f}s")
    results.append(solve_instrumented(relabel(g, pi_h), "hilbert"))

    weights = {r["weight"] for r in results}
    out = {
        "tool": "road_layout_experiment",
        "grid": [rows, cols, seed],
        "results": results,
        "weights_agree": len(weights) == 1,
    }
    print(json.dumps(out), flush=True)
    assert len(weights) == 1, weights


if __name__ == "__main__":
    main()
