"""Per-class SLO accounting (obs/slo.py) + the open-loop load drill.

Coverage contract from the issue: the class tag must travel request ->
``serve.request``/``serve.solve`` span args -> per-class summary (from a
live bus AND from a JSONL export), the summary schema must flatten into
bench-gate metrics that actually gate p99/goodput regressions, and a
miniature load-drill deck must run open-loop against a real service with
zero lost accepted queries.
"""

import json
import os
import sys

import pytest

from distributed_ghs_implementation_tpu.obs import slo
from distributed_ghs_implementation_tpu.obs.events import BUS
from distributed_ghs_implementation_tpu.obs.export import write_events_jsonl

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))


@pytest.fixture(autouse=True)
def _clean_global_bus():
    BUS.enable()
    BUS.clear()
    yield
    BUS.enable()
    BUS.clear()


# ----------------------------------------------------------------------
# ClassStats + assembly
# ----------------------------------------------------------------------
def test_class_stats_counts_and_goodput():
    stats = slo.ClassStats()
    for i in range(10):
        stats.observe("hit", 0.001 * (i + 1))
    stats.observe("miss", 0.5)
    stats.observe("miss", 0.7, ok=False)
    stats.observe("miss", shed=True)
    summary = slo.assemble(stats, wall_s=2.0)
    assert summary["schema"] == "ghs-slo-summary-v1"
    hit = summary["classes"]["hit"]
    assert hit["sent"] == 10 and hit["ok"] == 10
    assert hit["goodput_per_sec"] == pytest.approx(5.0)
    assert hit["latency_s"]["p50"] == pytest.approx(0.005, abs=1e-3)
    miss = summary["classes"]["miss"]
    assert (miss["sent"], miss["ok"], miss["errors"], miss["shed"]) == (3, 1, 1, 1)
    totals = summary["totals"]
    assert totals["sent"] == 13 and totals["errors"] == 1 and totals["shed"] == 1
    assert not summary["dropped_warning"]


def test_dropped_events_flag_the_summary():
    stats = slo.ClassStats()
    stats.observe("hit", 0.01)
    summary = slo.assemble(stats, wall_s=1.0, events_dropped=7)
    assert summary["events_dropped"] == 7
    assert summary["dropped_warning"] is True


def test_tagged_class_is_scoped():
    assert slo.current_class() is None
    with slo.tagged_class("miss"):
        assert slo.current_class() == "miss"
        with slo.tagged_class("hit"):
            assert slo.current_class() == "hit"
        assert slo.current_class() == "miss"
    assert slo.current_class() is None
    with slo.tagged_class(None):  # no-op, never raises
        assert slo.current_class() is None


# ----------------------------------------------------------------------
# The event-stream join (live bus and JSONL round trip)
# ----------------------------------------------------------------------
def _drive_tagged_service():
    from distributed_ghs_implementation_tpu.graphs.generators import (
        gnm_random_graph,
    )
    from distributed_ghs_implementation_tpu.serve.service import MSTService

    service = MSTService()
    g = gnm_random_graph(48, 120, seed=3)
    edges = [[int(a), int(b), int(c)] for a, b, c in zip(g.u, g.v, g.w)]
    r1 = service.handle(
        {"op": "solve", "num_nodes": 48, "edges": edges, "slo_class": "miss"}
    )
    r2 = service.handle(
        {"op": "solve", "num_nodes": 48, "edges": edges, "slo_class": "hit"}
    )
    r3 = service.handle(
        {"op": "update", "digest": r1["digest"], "slo_class": "update",
         "updates": [{"kind": "insert", "u": 0, "v": 47, "w": 1}]}
    )
    bad = service.handle(
        {"op": "update", "digest": "nope", "slo_class": "update",
         "updates": []}
    )
    assert r1["ok"] and r2["ok"] and r3["ok"] and not bad["ok"]
    assert r1["source"] == "solved" and r2["source"] == "cache"
    assert r2["slo_class"] == "hit"  # the tag echoes on the response


def test_bus_join_builds_per_class_report():
    _drive_tagged_service()
    summary = slo.summarize_bus(BUS, wall_s=1.0)
    classes = summary["classes"]
    assert set(classes) == {"hit", "miss", "update"}
    assert classes["miss"]["sent"] == 1 and classes["miss"]["ok"] == 1
    # The miss decomposes: its serve.solve span landed under the same class.
    assert classes["miss"]["solve_s"]["count"] == 1
    assert classes["miss"]["solve_s"]["p99"] <= classes["miss"]["latency_s"]["p99"]
    # Cache hits never touch the solver: no solve_s section at all.
    assert "solve_s" not in classes["hit"]
    # The failed update is an error, not a silent omission.
    assert classes["update"]["sent"] == 2
    assert classes["update"]["errors"] == 1
    assert summary["totals"]["sent"] == 4


def test_jsonl_join_matches_live_bus(tmp_path):
    _drive_tagged_service()
    live = slo.summarize_bus(BUS, wall_s=1.0)
    path = str(tmp_path / "events.jsonl")
    write_events_jsonl(BUS, path)
    offline = slo.summarize_jsonl(path, wall_s=1.0)
    for cls in live["classes"]:
        for key in ("sent", "ok", "errors", "shed"):
            assert offline["classes"][cls][key] == live["classes"][cls][key]
        assert offline["classes"][cls]["latency_s"]["p99"] == pytest.approx(
            live["classes"][cls]["latency_s"]["p99"], rel=1e-6
        )


def test_hostile_class_labels_are_sanitized():
    """slo_class comes from untrusted request JSON and ends up in bus
    histogram names — it must be reduced to a short identifier token."""
    from distributed_ghs_implementation_tpu.graphs.generators import (
        gnm_random_graph,
    )
    from distributed_ghs_implementation_tpu.serve.service import MSTService

    service = MSTService()
    g = gnm_random_graph(48, 120, seed=5)
    edges = [[int(a), int(b), int(c)] for a, b, c in zip(g.u, g.v, g.w)]
    response = service.handle(
        {"op": "solve", "num_nodes": 48, "edges": edges,
         "slo_class": "a/b.c " + "x" * 100}
    )
    assert response["ok"]
    (cls,) = slo.summarize_bus(BUS)["classes"]
    assert len(cls) <= 32
    assert all(ch.isalnum() or ch in "_-" for ch in cls)
    assert cls.startswith("a_b_c_x")


def test_untagged_traffic_stays_out_of_class_reports():
    from distributed_ghs_implementation_tpu.graphs.generators import (
        gnm_random_graph,
    )
    from distributed_ghs_implementation_tpu.serve.service import MSTService

    service = MSTService()
    g = gnm_random_graph(48, 120, seed=4)
    edges = [[int(a), int(b), int(c)] for a, b, c in zip(g.u, g.v, g.w)]
    response = service.handle(
        {"op": "solve", "num_nodes": 48, "edges": edges}
    )
    assert response["ok"] and "slo_class" not in response
    assert slo.summarize_bus(BUS)["classes"] == {}


# ----------------------------------------------------------------------
# Gate metrics + bench_gate integration
# ----------------------------------------------------------------------
def _toy_summary():
    stats = slo.ClassStats()
    for _ in range(20):
        stats.observe("hit", 0.002)
    for _ in range(5):
        stats.observe("miss", 0.08)
    return slo.assemble(stats, wall_s=2.0)


def test_gate_metrics_flatten_and_classify():
    import bench_gate

    doc = slo.gate_metrics(
        _toy_summary(),
        workload="gate-load-v1",
        extra_metrics={"lost_accepted": 0},
    )
    assert doc["schema"] == "ghs-bench-metrics-v1"
    metrics = doc["metrics"]
    assert metrics["hit_p99_s"] == pytest.approx(0.002)
    assert metrics["hit_goodput_per_sec"] == pytest.approx(10.0)
    assert metrics["queries_sent"] == 25
    # Suffix classification routes each key to the right regression rule.
    assert bench_gate.metric_kind("hit_p99_s") == "time"
    assert bench_gate.metric_kind("hit_goodput_per_sec") == "throughput"
    assert bench_gate.metric_kind("hit_errors") == "count"
    assert bench_gate.metric_kind("lost_accepted") == "exact"


def test_gate_fails_p99_goodput_loss_and_lost_query():
    import bench_gate

    base = slo.gate_metrics(
        _toy_summary(), workload="gate-load-v1",
        extra_metrics={"lost_accepted": 0},
    )
    same = json.loads(json.dumps(base))
    ok, _ = bench_gate.compare(base, same)
    assert ok

    slow = json.loads(json.dumps(base))
    slow["metrics"]["miss_p99_s"] *= 10
    ok, lines = bench_gate.compare(base, slow)
    assert not ok and any("miss_p99_s" in ln for ln in lines if "FAIL" in ln)

    slower = json.loads(json.dumps(base))
    slower["metrics"]["hit_goodput_per_sec"] /= 10
    ok, _ = bench_gate.compare(base, slower)
    assert not ok

    errs = json.loads(json.dumps(base))
    errs["metrics"]["hit_errors"] = 2  # ANY error against a zero baseline
    ok, _ = bench_gate.compare(base, errs)
    assert not ok

    lost = json.loads(json.dumps(base))
    lost["metrics"]["lost_accepted"] = 1  # exact: one lost query fails
    ok, lines = bench_gate.compare(base, lost)
    assert not ok and any(
        "lost_accepted" in ln and "exact" in ln for ln in lines if "FAIL" in ln
    )


def test_bench_gate_cli_accepts_load_report(tmp_path):
    """--metrics with a ghs-load-report-v1 file gates its embedded
    gate_metrics (the CI wiring for gate-load-v1)."""
    import bench_gate

    gate = slo.gate_metrics(
        _toy_summary(), workload="gate-load-v1",
        extra_metrics={"lost_accepted": 0},
    )
    report = {"schema": "ghs-load-report-v1", "gate_metrics": gate}
    baseline = str(tmp_path / "baseline.json")
    with open(baseline, "w") as f:
        json.dump(gate, f)
    fresh = str(tmp_path / "report.json")
    with open(fresh, "w") as f:
        json.dump(report, f)
    assert bench_gate.main(["--baseline", baseline, "--metrics", fresh]) == 0

    report["gate_metrics"] = json.loads(json.dumps(gate))
    report["gate_metrics"]["metrics"]["lost_accepted"] = 3
    with open(fresh, "w") as f:
        json.dump(report, f)
    assert bench_gate.main(["--baseline", baseline, "--metrics", fresh]) == 1


def test_committed_load_baseline_is_gateable():
    """The committed gate-load-v1 baseline has the SLO shape: per-class
    p99 + goodput + errors/shed for the acceptance classes, exact-gated
    lost_accepted, and passes against itself."""
    import bench_gate

    path = os.path.join(
        os.path.dirname(__file__), "..", "docs", "BENCH_BASELINE_LOAD.json"
    )
    with open(path) as f:
        baseline = json.load(f)
    assert baseline["schema"] == "ghs-bench-metrics-v1"
    assert baseline["config"]["workload"] == "gate-load-v1"
    metrics = baseline["metrics"]
    for cls in ("hit", "miss", "batch", "update", "oversize", "dup"):
        assert f"{cls}_p99_s" in metrics
        assert f"{cls}_goodput_per_sec" in metrics
        assert metrics[f"{cls}_errors"] == 0
    assert metrics["lost_accepted"] == 0
    ok, lines = bench_gate.compare(baseline, json.loads(json.dumps(baseline)))
    assert ok, lines


# ----------------------------------------------------------------------
# The load drill itself (miniature deck; the full smoke runs in CI)
# ----------------------------------------------------------------------
def test_load_drill_window_counter_delta_survives_worker_restart():
    import load_drill

    pre = {("0", 1): {"serve.scheduler.fresh_solve": 5, "serve.hits": 2}}
    post = {
        ("0", 2): {"serve.scheduler.fresh_solve": 3},  # killed + restarted
        ("1", 1): {"serve.hits": 7},
    }
    delta = load_drill._window_counter_delta(pre, post)
    # The victim's vanished pre-kill counters must NOT cancel the
    # restarted incarnation's fresh solves — that is the hole that let
    # the kill drill's "zero fresh solves" gate pass vacuously.
    assert delta["serve.scheduler.fresh_solve"] == 3
    assert delta["serve.hits"] == 7  # the dead incarnation's base is gone
    same = {("1", 1): {"serve.hits": 4}}
    assert load_drill._window_counter_delta(same, post)["serve.hits"] == 3


def test_load_drill_arrival_models_are_seeded_and_bounded():
    import numpy as np

    import load_drill

    for model in ("poisson", "bursty", "ramp"):
        a = load_drill.arrival_times(50, 4.0, model, np.random.default_rng(7))
        b = load_drill.arrival_times(50, 4.0, model, np.random.default_rng(7))
        assert np.array_equal(a, b), model  # seeded => identical schedules
        assert len(a) == 50
        assert float(a.min()) >= 0.0 and float(a.max()) <= 4.0 + 1e-9
    assert len(load_drill.arrival_times(0, 4.0, "poisson",
                                        np.random.default_rng(7))) == 0
    with pytest.raises(ValueError, match="arrival"):
        load_drill.arrival_times(5, 4.0, "square-wave",
                                 np.random.default_rng(7))


@pytest.mark.slow
def test_load_drill_micro_deck_end_to_end(tmp_path):
    """A tiny open-loop deck against a real service: every class reported
    from bus events, zero lost accepted queries, chaos absorbed."""
    import load_drill

    out = str(tmp_path / "report.json")
    rc = load_drill.main([
        "--duration", "3", "--rate", "4", "--oversize", "0",
        "--lanes", "2", "--seed", "5", "--output", out,
    ])
    with open(out) as f:
        report = json.load(f)
    assert rc == 0, [c for c in report["checks"] if not c["ok"]]
    assert report["schema"] == "ghs-load-report-v1"
    for cls in ("hit", "miss", "batch", "update", "dup"):
        c = report["slo"]["classes"][cls]
        assert c["sent"] >= 1
        for p in ("p50", "p95", "p99"):
            assert c["latency_s"][p] >= 0.0
    assert report["chaos"]["lost_accepted"] == 0
    assert report["gate_metrics"]["metrics"]["queries_sent"] == \
        report["slo"]["totals"]["sent"]


# ----------------------------------------------------------------------
# Fleet: fleet.request spans join like serve.request + per-worker breakdown
# ----------------------------------------------------------------------
def test_fleet_request_spans_join_with_worker_breakdown():
    with BUS.span("fleet.request", cat="fleet", op="solve", cls="hit",
                  ok=True, worker=0):
        pass
    with BUS.span("fleet.request", cat="fleet", op="solve", cls="hit",
                  ok=True, worker=1):
        pass
    with BUS.span("fleet.request", cat="fleet", op="solve", cls="miss",
                  ok=False, worker=1):
        pass
    with BUS.span("fleet.request", cat="fleet", op="solve", cls="shed-me",
                  ok=False, shed=True):
        pass  # shed before dispatch: no worker attribution
    summary = slo.summarize_bus(BUS, wall_s=1.0)
    assert summary["classes"]["hit"]["sent"] == 2
    assert summary["classes"]["miss"]["errors"] == 1
    assert summary["classes"]["shed-me"]["shed"] == 1
    workers = summary["workers"]
    assert set(workers) == {"0", "1"}
    assert workers["0"]["classes"]["hit"]["sent"] == 1
    assert workers["1"]["classes"]["hit"]["sent"] == 1
    assert workers["1"]["classes"]["miss"]["errors"] == 1
    assert workers["1"]["totals"]["sent"] == 2


def test_single_process_summary_has_no_worker_section():
    with BUS.span("serve.request", cat="serve", op="solve", cls="hit",
                  ok=True):
        pass
    summary = slo.summarize_bus(BUS, wall_s=1.0)
    assert "workers" not in summary


def test_sanitize_class_normalizes_hostile_labels():
    assert slo.sanitize_class(None) is None
    assert slo.sanitize_class("hit") == "hit"
    assert slo.sanitize_class("a.b c/d") == "a_b_c_d"
    assert slo.sanitize_class("x" * 99) == "x" * 32
    assert slo.sanitize_class("!!!") == "___"
    assert slo.sanitize_class("") == "untagged"
