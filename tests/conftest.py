"""Test environment: force CPU JAX with 8 virtual devices.

Multi-chip sharding is validated on a virtual device mesh (the driver
separately dry-runs ``__graft_entry__.dryrun_multichip``); the real TPU chip
is exercised by ``bench.py``, not the unit suite.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# The axon sitecustomize force-registers the TPU backend and overrides
# jax_platforms at interpreter startup; an explicit config update (before any
# backend initialization) wins over both it and the env var.
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(0)


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: large-scale tests (RMAT-16+, multi-process)"
    )
