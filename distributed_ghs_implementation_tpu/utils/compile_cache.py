"""Persistent XLA compile cache: restarts reuse compiled executables.

Without it, every process restart re-pays XLA compilation for every kernel
shape it touches — for a serving process that is the cold-start tax the
warmup phase (``batch/warmup.py``) then multiplies by the bucket count.
:func:`enable_persistent_cache` turns on JAX's on-disk compilation cache
(thresholds zeroed so even small kernels persist), which drops repeat
compiles to a disk read. ``ghs serve`` enables it by default
(``--no-compile-cache`` opts out, ``--compile-cache-dir`` relocates it);
the default directory is ``$GHS_COMPILE_CACHE_DIR`` or
``~/.cache/ghs-xla``, with a per-machine-type subdirectory
(:func:`_platform_fingerprint`) so a shared home directory across a
heterogeneous fleet can never reload another CPU's AOT executables.

The module also bridges JAX's internal cache telemetry onto the obs bus
(``compile.*`` taxonomy, docs/OBSERVABILITY.md) so cold vs warm is visible
in traces and ``stats``:

* counters ``compile.persistent.hit`` / ``compile.persistent.miss`` — the
  on-disk cache's own hit/miss stream (a "miss" here still populates the
  disk for the next restart);
* histograms ``compile.backend_s`` (actual XLA backend compiles) and
  ``compile.cache_retrieval_s`` (deserializing a cached executable) — the
  two durations whose gap IS the cache's value.

Relationship to the package ``__init__``: that hook enables the same JAX
cache for *accelerator* sessions at import time (where a cold compile
costs ~10 s/shape) and deliberately skips CPU. ``enable_persistent_cache``
is the explicit, serving-grade version: any platform, thresholds zeroed
(serve's lane solvers are many small kernels), and when the import-time
hook already configured a directory this function reuses it rather than
repointing — one cache per deployment, whoever enabled it first.

Everything degrades gracefully: on a JAX build without the config knobs or
monitoring hooks the functions no-op and return ``None``/``False`` — the
solver stack never depends on the cache existing.
"""

from __future__ import annotations

import os
import threading
from typing import Optional

from distributed_ghs_implementation_tpu.obs.events import BUS

#: Monitoring-event suffix -> obs counter name (anything else under the
#: compilation-cache prefix lands as ``compile.persistent.<suffix>``).
_EVENT_COUNTERS = {
    "cache_hits": "compile.persistent.hit",
    "cache_misses": "compile.persistent.miss",
}
_CACHE_EVENT_PREFIX = "/jax/compilation_cache/"
_DURATION_HISTS = {
    "/jax/core/compile/backend_compile_duration": "compile.backend_s",
    "/jax/compilation_cache/cache_retrieval_time_sec": "compile.cache_retrieval_s",
}

_state = {"dir": None, "bridge_installed": False}
_lock = threading.Lock()


def default_cache_dir() -> str:
    # GHS_TPU_COMPILE_CACHE is the package __init__'s knob for the same
    # cache — honoring it keeps one directory per deployment.
    return (
        os.environ.get("GHS_COMPILE_CACHE_DIR")
        or os.environ.get("GHS_TPU_COMPILE_CACHE")
        or os.path.join(os.path.expanduser("~"), ".cache", "ghs-xla")
    )


def _platform_fingerprint() -> str:
    """A cache-namespace token for the executing hardware.

    Cached CPU executables embed ISA-feature assumptions (the package
    ``__init__`` documents observed "+prefer-no-scatter ... SIGILL"
    loader warnings from cross-machine reloads), so the DEFAULT cache
    directory is namespaced per backend + CPU feature set: a shared home
    directory across a heterogeneous fleet gets one subcache per distinct
    machine type instead of one poisoned pool. Accelerators namespace by
    device kind (their executables are device-bound anyway).
    """
    import hashlib
    import platform as plat

    import jax

    backend = jax.default_backend()
    if backend != "cpu":
        try:
            kind = jax.devices()[0].device_kind.replace(" ", "-")
        except Exception:
            kind = backend
        return f"{backend}-{kind}"
    features = ""
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith(("flags", "Features")):
                    features = line.strip()
                    break
    except OSError:
        pass
    token = f"{plat.machine()}|{features}"
    return f"cpu-{plat.machine()}-{hashlib.sha256(token.encode()).hexdigest()[:12]}"


def _on_event(event: str, **kw) -> None:
    if event.startswith(_CACHE_EVENT_PREFIX):
        suffix = event[len(_CACHE_EVENT_PREFIX):]
        BUS.count(_EVENT_COUNTERS.get(suffix, f"compile.persistent.{suffix}"))


def _on_duration(event: str, duration_s: float, **kw) -> None:
    hist = _DURATION_HISTS.get(event)
    if hist is not None:
        BUS.record(hist, duration_s)


def _install_monitoring_bridge() -> bool:
    """Route JAX's cache/compile telemetry onto the obs bus (idempotent)."""
    with _lock:
        if _state["bridge_installed"]:
            return True
        try:
            from jax import monitoring

            monitoring.register_event_listener(_on_event)
            monitoring.register_event_duration_secs_listener(_on_duration)
        except Exception:  # pragma: no cover — older/renamed monitoring API
            return False
        _state["bridge_installed"] = True
        return True


def enable_persistent_cache(cache_dir: Optional[str] = None) -> Optional[str]:
    """Enable JAX's on-disk compilation cache; returns the directory in use.

    Idempotent (re-enabling with a different directory repoints the
    cache). Thresholds are zeroed so every compile persists — this repo's
    kernels are small and numerous, exactly the population the default
    min-compile-time filter would skip. Returns ``None`` when the JAX
    build doesn't support the cache config (the caller proceeds uncached).
    """
    import jax

    if cache_dir is None:
        # The package __init__ may have configured the cache already (TPU
        # sessions); reuse its directory instead of splitting the cache.
        try:
            configured = jax.config.jax_compilation_cache_dir
        except Exception:
            configured = None
        if configured:
            cache_dir = configured
        else:
            # Default location: namespace per machine type so reloading
            # another CPU's AOT executables (SIGILL risk) is impossible
            # by construction. An explicit cache_dir is the operator's
            # exact path — no namespacing.
            cache_dir = os.path.join(default_cache_dir(), _platform_fingerprint())
    path = os.path.abspath(cache_dir)
    try:
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception:
        BUS.instant("compile.cache.unavailable", cat="compile")
        return None
    try:
        # A process that already compiled something has a lazily-initialized
        # cache bound to the OLD dir (or to none); rebind it. Best-effort —
        # on a JAX without this internal the config alone covers the common
        # enable-before-first-compile case (serve does exactly that).
        from jax._src import compilation_cache as _jax_cc

        _jax_cc.reset_cache()
    except Exception:  # pragma: no cover
        pass
    _install_monitoring_bridge()
    with _lock:
        _state["dir"] = path
    BUS.instant("compile.cache.enabled", cat="compile", dir=path)
    return path


def disable_persistent_cache() -> None:
    """Turn the on-disk cache back off (tests restore global state)."""
    import jax

    try:
        jax.config.update("jax_compilation_cache_dir", None)
    except Exception:
        return
    try:
        from jax._src import compilation_cache as _jax_cc

        _jax_cc.reset_cache()
    except Exception:  # pragma: no cover
        pass
    with _lock:
        _state["dir"] = None


def cache_stats() -> dict:
    """Disk-side view of the persistent cache (for stats/drill artifacts)."""
    path = _state["dir"]
    stats = {
        "enabled": path is not None,
        "dir": path,
        "entries": 0,
        "bytes": 0,
    }
    if path and os.path.isdir(path):
        for name in os.listdir(path):
            if name.endswith("-cache"):
                stats["entries"] += 1
            try:
                stats["bytes"] += os.path.getsize(os.path.join(path, name))
            except OSError:
                continue
    return stats
