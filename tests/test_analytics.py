"""Analytics front door (round 22, docs/ANALYTICS.md): the kind registry,
per-kind solvers vs their NetworkX oracles, per-kind store isolation, the
kind-aware serve protocol and probe derivation rules, the verify adapters,
batch kind-homogeneity, and the promoted public helpers."""

import os

import numpy as np
import pytest

from distributed_ghs_implementation_tpu import analytics
from distributed_ghs_implementation_tpu.analytics import solvers as asolvers
from distributed_ghs_implementation_tpu.api import minimum_spanning_forest
from distributed_ghs_implementation_tpu.graphs.edgelist import Graph
from distributed_ghs_implementation_tpu.graphs.generators import gnm_random_graph
from distributed_ghs_implementation_tpu.obs.events import BUS
from distributed_ghs_implementation_tpu.serve.service import MSTService
from distributed_ghs_implementation_tpu.serve.store import (
    ResultStore,
    cache_key_for_digest,
    solve_cache_key,
)


@pytest.fixture(autouse=True)
def _clean_global_bus():
    BUS.enable()
    BUS.clear()
    yield
    BUS.enable()
    BUS.clear()


def _edges(g):
    return [[int(a), int(b), int(c)] for a, b, c in zip(g.u, g.v, g.w)]


def _host_solve(g):
    return minimum_spanning_forest(g, backend="host"), "solved"


def _ragged_graph(seed: int) -> Graph:
    """Two random blocks plus isolated tail nodes — multi-component on
    purpose, so partition/k-forest edge cases are exercised."""
    a = gnm_random_graph(30, 70, seed=seed)
    b = gnm_random_graph(20, 45, seed=seed + 1)
    u = np.concatenate([a.u, b.u + a.num_nodes])
    v = np.concatenate([a.v, b.v + a.num_nodes])
    w = np.concatenate([a.w, b.w])
    return Graph.from_arrays(a.num_nodes + b.num_nodes + 2, u, v, w)


# ----------------------------------------------------------------------
# Registry
# ----------------------------------------------------------------------
def test_registry_kinds_and_unknown_error():
    assert analytics.known() == (
        "mst", "components", "k_msf", "bottleneck", "path_max"
    )
    assert analytics.get(None).name == "mst"  # the historical default
    with pytest.raises(ValueError, match="unknown kind"):
        analytics.get("diameter")
    # Registry rows resolve to real callables without eager jax imports.
    spec = analytics.get("components")
    assert spec.solver is asolvers.solve_components
    assert spec.oracle is asolvers.oracle_components
    assert spec.slo_class == "components"
    assert analytics.get("mst").slo_class is None  # telemetry back-compat


def test_cache_tokens_and_param_validation():
    assert analytics.cache_token("mst") == "mst"
    assert analytics.cache_token("components") == "components"
    assert analytics.cache_token("k_msf", k=4) == "k_msf4"
    assert analytics.cache_token("path_max") is None  # never store-cached
    assert analytics.parse_params("k_msf", {"k": "3"}) == {"k": 3}
    with pytest.raises(ValueError, match="integer 'k'"):
        analytics.parse_params("k_msf", {})
    with pytest.raises(ValueError, match="k must be >= 1"):
        analytics.parse_params("k_msf", {"k": 0})
    with pytest.raises(ValueError, match="'u' and 'v'"):
        analytics.parse_params("path_max", {"u": 1})
    # Kind tokens become disk filenames: non-filename-safe tokens refuse.
    with pytest.raises(ValueError, match="bad cache kind token"):
        cache_key_for_digest("d" * 8, kind="k-msf:4")


# ----------------------------------------------------------------------
# Solvers vs NetworkX oracles
# ----------------------------------------------------------------------
@pytest.mark.parametrize("seed", [0, 7])
def test_components_solver_matches_networkx(seed):
    g = _ragged_graph(seed)
    result, _src = asolvers.solve_components(g, _host_solve)
    assert result.graph is g  # kind entries digest-validate as the original
    served = asolvers.partition_from_labels(asolvers.labels_for_forest(result))
    assert served == asolvers.oracle_components(g)
    # The forest is a complete certificate of its own partition.
    from distributed_ghs_implementation_tpu.verify.certify import (
        certify_components,
    )

    cert = certify_components(
        g, result.edge_ids, expect_components=result.num_components
    )
    assert cert.ok, cert.detail


@pytest.mark.parametrize("k", [1, 2, 3, 10, 52])
def test_k_msf_solver_matches_oracle(k):
    g = _ragged_graph(3)
    trimmed, _src, full = asolvers.solve_k_msf(g, _host_solve, k)
    want = asolvers.oracle_k_msf_weight(g, k)
    assert int(g.w[trimmed.edge_ids].sum()) == want
    # k' = min(n, max(k, c)): never fewer parts than the graph has.
    assert trimmed.num_components == min(
        g.num_nodes, max(k, full.num_components)
    )


def test_k_msf_early_exit_counterexample():
    # Borůvka's level 1 adds MOEs {1, 2, 10} and reaches exactly 3
    # fragments with weight 13 — but the optimal 3-forest weighs 8 (the
    # lightest 3 of the 4 MSF edges). Trimming must find 8, proving the
    # early-exit shortcut is not what ships.
    g = Graph.from_edges(
        6, [(0, 1, 1), (2, 3, 2), (0, 2, 5), (4, 5, 10)]
    )
    trimmed, _src, _full = asolvers.solve_k_msf(g, _host_solve, 3)
    total = int(g.w[trimmed.edge_ids].sum())
    assert total == asolvers.oracle_k_msf_weight(g, 3) == 8
    assert total != 13


def test_bottleneck_and_path_max_match_oracle():
    g = _ragged_graph(11)
    _res, _src, bn = asolvers.solve_bottleneck(g, _host_solve)
    assert bn is not None and bn[0] == asolvers.oracle_bottleneck(g)

    result, _src2, _ = asolvers.solve_path_max(g, _host_solve, 0, 0)
    rng = np.random.default_rng(5)
    pairs = [(0, 1), (0, g.num_nodes - 1), (2, 2)] + [
        tuple(int(x) for x in rng.integers(0, g.num_nodes, 2))
        for _ in range(6)
    ]
    for u, v in pairs:
        got = asolvers.path_max_of(result, u, v)
        want = asolvers.oracle_path_max(g, u, v)
        assert got["connected"] == want["connected"], (u, v)
        assert got["weight"] == want["weight"], (u, v)
    with pytest.raises(ValueError, match="out of range"):
        asolvers.path_max_of(result, 0, g.num_nodes)


# ----------------------------------------------------------------------
# Per-kind store isolation (satellite: keys must not collide)
# ----------------------------------------------------------------------
def test_store_per_kind_entries_and_disk_files(tmp_path):
    g = gnm_random_graph(40, 90, seed=5)
    mst = minimum_spanning_forest(g, backend="host")
    comp, _src = asolvers.solve_components(g, _host_solve)
    k2, _src2, _full = asolvers.solve_k_msf(g, _host_solve, 2)

    store = ResultStore(capacity=8, disk_dir=str(tmp_path))
    mst_key = solve_cache_key(g, backend="host")
    comp_key = solve_cache_key(g, backend="host", kind="components")
    k2_key = solve_cache_key(g, backend="host", kind="k_msf2")
    assert len({mst_key, comp_key, k2_key}) == 3
    assert comp_key == mst_key + ":components"  # mst keeps the 2-segment key

    store.put(mst_key, mst)
    store.put(comp_key, comp)
    store.put(k2_key, k2)
    assert len(store) == 3
    # One npz + integrity sidecar per kind on disk.
    for key in (mst_key, comp_key, k2_key):
        path = os.path.join(str(tmp_path), key.replace(":", "_") + ".npz")
        assert os.path.exists(path), key
        assert os.path.exists(path + ".sha256"), key

    # Each key round-trips ITS OWN edge set through a cold store.
    cold = ResultStore(capacity=8, disk_dir=str(tmp_path))
    for key, put in ((mst_key, mst), (comp_key, comp), (k2_key, k2)):
        got = cold.get(key, g)
        assert got is not None and np.array_equal(got.edge_ids, put.edge_ids)

    # evict_chain on the base key drops the kind siblings with it.
    assert store.evict_chain(mst_key)
    assert len(store) == 0
    assert BUS.counters().get("serve.store.chain_evicted", 0) == 3

    # Quarantining one kind's entry leaves the other kinds servable.
    assert store.invalidate(comp_key, reason="test poison")
    fresh = ResultStore(capacity=8, disk_dir=str(tmp_path))
    assert fresh.get(comp_key, g) is None
    assert fresh.get(mst_key, g) is not None
    assert fresh.get(k2_key, g) is not None


# ----------------------------------------------------------------------
# Service protocol: kinds end to end
# ----------------------------------------------------------------------
def test_service_answers_every_kind_oracle_exact():
    svc = MSTService()
    g = _ragged_graph(21)
    base = {"op": "solve", "num_nodes": g.num_nodes, "edges": _edges(g)}

    comp = svc.handle({**base, "kind": "components", "labels_out": True})
    assert comp["ok"] and comp["kind"] == "components"
    assert comp["slo_class"] == "components"  # the kind's default class
    assert (
        asolvers.partition_from_labels(comp["labels"])
        == asolvers.oracle_components(g)
    )
    assert comp["num_components"] == len(asolvers.oracle_components(g))

    kf = svc.handle({**base, "kind": "k_msf", "k": 3})
    assert kf["ok"] and kf["k"] == 3 and kf["slo_class"] == "k_msf"
    assert kf["total_weight"] == asolvers.oracle_k_msf_weight(g, 3)

    bn = svc.handle({**base, "kind": "bottleneck"})
    assert bn["ok"] and bn["slo_class"] == "bottleneck"
    assert bn["bottleneck_weight"] == asolvers.oracle_bottleneck(g)

    pm = svc.handle({**base, "kind": "path_max", "u": 0, "v": g.num_nodes - 1})
    want = asolvers.oracle_path_max(g, 0, g.num_nodes - 1)
    assert pm["ok"] and pm["slo_class"] == "path_max"
    assert pm["connected"] == want["connected"]
    assert pm["path_max_weight"] == want["weight"]

    # Untagged mst stays untagged; an explicit class beats the default.
    mst = svc.handle(dict(base))
    assert mst["ok"] and "slo_class" not in mst
    gold = svc.handle({**base, "kind": "components", "slo_class": "gold"})
    assert gold["slo_class"] == "gold"

    counters = BUS.counters()
    for kind in ("components", "k_msf", "bottleneck", "path_max"):
        assert counters.get(f"serve.kind.{kind}", 0) >= 1, kind
    assert counters.get("serve.kind.mst", 0) == 1


def test_service_unknown_kind_and_unknown_op():
    svc = MSTService()
    g = gnm_random_graph(10, 20, seed=1)
    bad = svc.handle({
        "op": "solve", "kind": "diameter",
        "num_nodes": g.num_nodes, "edges": _edges(g),
    })
    assert not bad["ok"] and "unknown kind" in bad["error"]
    assert "path_max" in bad["error"]  # the full accepted list is named
    nop = svc.handle({"op": "solv"})
    assert not nop["ok"] and "unknown op" in nop["error"]
    assert "solve" in nop["error"] and "update" in nop["error"]
    # Malformed kind params error client-side, before any solving.
    fresh = BUS.counters().get("serve.scheduler.fresh_solve", 0)
    nok = svc.handle({
        "op": "solve", "kind": "k_msf",
        "num_nodes": g.num_nodes, "edges": _edges(g),
    })
    assert not nok["ok"] and "integer 'k'" in nok["error"]
    assert BUS.counters().get("serve.scheduler.fresh_solve", 0) == fresh


def test_service_kind_cache_keys_do_not_collide():
    svc = MSTService()
    g = gnm_random_graph(50, 130, seed=9)
    base = {"op": "solve", "num_nodes": g.num_nodes, "edges": _edges(g)}
    first = svc.handle({**base, "kind": "components"})
    assert first["ok"] and not first["cached"]
    # Same digest, different kind: MUST miss the components entry.
    mst = svc.handle(dict(base))
    assert mst["ok"] and not mst["cached"]
    again = svc.handle({**base, "kind": "components"})
    assert again["ok"] and again["cached"]
    assert again["num_components"] == first["num_components"]


def test_service_kind_probe_derivation_rules():
    svc = MSTService()
    g = gnm_random_graph(45, 120, seed=14)
    solved = svc.handle(
        {"op": "solve", "num_nodes": g.num_nodes, "edges": _edges(g)}
    )
    digest = solved["digest"]

    def probe(kind, **extra):
        return svc.handle({
            "op": "solve", "cached_only": True, "digest": digest,
            "kind": kind, **extra,
        })

    fresh = BUS.counters().get("serve.scheduler.fresh_solve", 0)
    # Derived kinds answer from the cached mst entry without solving...
    bn = probe("bottleneck")
    assert bn["ok"] and bn["bottleneck_weight"] == asolvers.oracle_bottleneck(g)
    pm = probe("path_max", u=0, v=g.num_nodes - 1)
    assert pm["ok"]
    assert pm["path_max_weight"] == asolvers.oracle_path_max(
        g, 0, g.num_nodes - 1
    )["weight"]
    kf = probe("k_msf", k=2)
    assert kf["ok"] and kf["total_weight"] == asolvers.oracle_k_msf_weight(g, 2)
    # ... components never derives: its canonical entry is a different
    # edge set, so an mst-only digest is a kind miss, not a wrong answer.
    cp = probe("components")
    assert not cp["ok"] and cp.get("cache_miss")
    counters = BUS.counters()
    assert counters.get("serve.probe.hit", 0) == 3
    assert counters.get("serve.probe.miss", 0) == 1
    assert counters.get("serve.scheduler.fresh_solve", 0) == fresh  # no solves

    # After a full components solve the kind probe hits its own key.
    svc.handle({
        "op": "solve", "kind": "components",
        "num_nodes": g.num_nodes, "edges": _edges(g),
    })
    cp2 = probe("components")
    assert cp2["ok"] and cp2["cached"]


# ----------------------------------------------------------------------
# Verify adapters
# ----------------------------------------------------------------------
def test_certify_components_failure_modes():
    from distributed_ghs_implementation_tpu.verify.certify import (
        certify_components,
    )

    g = Graph.from_edges(3, [(0, 1, 1), (1, 2, 2)])
    # A valid but NON-MAXIMAL forest: {0-1} leaves graph edge 1-2
    # crossing two claimed components.
    cert = certify_components(g, np.array([0]))
    assert not cert.ok and cert.reason == "cross_edge"
    # Metadata disagreeing with the certified count fails too.
    cert = certify_components(g, np.array([0, 1]), expect_components=2)
    assert not cert.ok and cert.reason == "metadata_mismatch"
    cert = certify_components(g, np.array([0, 1]), expect_components=1)
    assert cert.ok


def test_certify_k_forest_failure_modes():
    from distributed_ghs_implementation_tpu.verify.certify import (
        certify_k_forest,
    )

    g = Graph.from_edges(4, [(0, 1, 1), (1, 2, 2), (2, 3, 3), (0, 3, 4)])
    # Canonical (u, v)-sorted ids: 0=(0,1,w1) 1=(0,3,w4) 2=(1,2,w2)
    # 3=(2,3,w3). MSF = {w1, w2, w3}; the optimal 2-forest is {w1, w2}.
    good = certify_k_forest(g, np.array([0, 2]), 2)
    assert good.ok, good.detail
    wrong_size = certify_k_forest(g, np.array([0]), 2)
    assert not wrong_size.ok and wrong_size.reason == "not_spanning"
    # Right size, wrong edges: {w1, w3} is not the rank-prefix MSF.
    not_optimal = certify_k_forest(g, np.array([0, 3]), 2)
    assert not not_optimal.ok
    assert "k_msf prefix subgraph" in not_optimal.detail


def test_certify_bottleneck_scalar_mismatch():
    from distributed_ghs_implementation_tpu.verify.certify import (
        certify_bottleneck,
    )

    g = Graph.from_edges(3, [(0, 1, 1), (1, 2, 5), (0, 2, 7)])
    ids = minimum_spanning_forest(g, backend="host").edge_ids
    assert certify_bottleneck(g, ids, bottleneck_weight=5).ok
    bad = certify_bottleneck(g, ids, bottleneck_weight=7)
    assert not bad.ok and bad.reason == "weight_mismatch"


def test_certify_claim_kind_dispatch():
    from distributed_ghs_implementation_tpu.verify.certify import certify_claim

    g = _ragged_graph(31)
    comp, _src = asolvers.solve_components(g, _host_solve)
    pairs = [
        [int(a), int(b)]
        for a, b in zip(g.u[comp.edge_ids], g.v[comp.edge_ids])
    ]
    cert = certify_claim(
        g.num_nodes, _edges(g), pairs,
        kind="components", num_components=comp.num_components,
    )
    assert cert.ok, cert.detail
    lying = certify_claim(
        g.num_nodes, _edges(g), pairs,
        kind="components", num_components=comp.num_components + 1,
    )
    assert not lying.ok
    missing_k = certify_claim(g.num_nodes, _edges(g), pairs, kind="k_msf")
    assert not missing_k.ok and missing_k.reason == "malformed_claim"


# ----------------------------------------------------------------------
# Batch lanes stay kind-homogeneous
# ----------------------------------------------------------------------
def test_batch_forming_splits_lanes_by_kind():
    from distributed_ghs_implementation_tpu.batch.engine import (
        BatchEngine,
        BatchPolicy,
        PendingSolve,
    )
    from distributed_ghs_implementation_tpu.obs.slo import tagged_kind

    engine = BatchEngine(policy=BatchPolicy(max_lanes=2))
    graphs = [gnm_random_graph(60, 150, seed=s) for s in range(4)]
    pending = []
    for i, g in enumerate(graphs):  # interleave mst / components submits
        with tagged_kind(None if i % 2 == 0 else "components"):
            pending.append(PendingSolve(g))
    engine._queue = list(pending)
    batch = engine._take_batch()
    # Four same-bucket solves are queued, but a lane never mixes kinds.
    assert batch is not None and len(batch) == 2
    assert len({p.kind for p in batch}) == 1


# ----------------------------------------------------------------------
# Promoted public helpers (satellite 1)
# ----------------------------------------------------------------------
def test_promoted_helpers_are_public_with_aliases():
    from distributed_ghs_implementation_tpu import serve
    from distributed_ghs_implementation_tpu.serve import dynamic

    assert serve.components_via_unionfind is dynamic.components_via_unionfind
    assert serve.tree_path_max is dynamic.tree_path_max
    # The historical private names stay importable as exact aliases.
    assert dynamic._components_via_unionfind is dynamic.components_via_unionfind
    assert dynamic._tree_path_max is dynamic.tree_path_max

    labels = serve.components_via_unionfind(
        5, np.array([0, 2]), np.array([1, 3])
    )
    assert labels.shape == (5,)
    assert labels[0] == labels[1] and labels[2] == labels[3]
    assert len({int(labels[0]), int(labels[2]), int(labels[4])}) == 3

    tu = np.array([0, 1])
    tv = np.array([1, 2])
    tw = np.array([5, 3])
    assert serve.tree_path_max(3, tu, tv, tw, 0, 2) == 0  # w=5 edge
    assert serve.tree_path_max(3, tu, tv, tw, 1, 1) is None
    assert serve.tree_path_max(4, tu, tv, tw, 0, 3) is None  # disconnected
