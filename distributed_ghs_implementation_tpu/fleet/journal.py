"""Durable router journal: the accepted-work ledger that survives a crash.

Rounds 12–17 made every *worker* disposable — kill drills prove zero lost
accepted queries across pipe and TCP fleets, elastic churn, and
WAL-replayed stream failover — but the router's own state (the
accepted-but-unanswered ledger, session pins, the forwarding affinity
LRU, ring membership, the last scale decision) lived only in process
memory: a router crash silently lost every accepted query. This module
closes that last gap with a flock'd, fsync'd append-only journal
(``<journal_dir>/journal.jsonl``, schema ``ghs-router-journal-v1``) built
on the same hardened WAL core the stream log uses
(:class:`utils.wal.JsonlWal`: torn-tail seal, tolerant reads, atomic
rewrite) plus a **sequence-contiguity chain**: every record carries
``seq``; replay accepts the longest contiguous prefix and drops anything
past a gap (``fleet.router.journal.chain_broken``) — a skipped corrupt
line *is* a gap, so corruption can never splice unrelated history
together.

Record kinds (field ``t``):

* ``accept`` — one accepted request: journal id, the full request, its
  routing key/class/lane bits. **Appended before dispatch**: the router
  only acknowledges work whose accept is durable, so a crash can never
  lose an acknowledged query.
* ``answer`` — the matching outcome (journal id, ok, serving worker, the
  result digest — which is also how replay rebuilds the forwarding
  affinity LRU). An accept without an answer is an *orphan*: the
  restarted router re-queues it by digest, the same idempotent
  content-addressed re-queue worker failover uses.
* ``pin`` — an update/stream session digest moved (or renamed along its
  chain) to a worker.
* ``ring`` — a membership change (``add`` / ``remove`` / ``retire``,
  with the dial address for remote workers), so a restarted router knows
  the pool the autoscaler had grown it to — and does not double-scale.
* ``scale`` — the autoscaler's latest decision (with a wall-clock stamp
  the restarted cooldown derives from).
* ``checkpoint`` — a compaction point: the full mirrored state in one
  record, followed only by records after it. Written every
  ``checkpoint_every`` appends (the WAL-compaction-on-snapshot idiom).

The journal is also a state machine: it mirrors pins/affinity/membership
as records append (bounded LRUs, matching the router's own caps), so
compaction needs no caller-supplied snapshot and :meth:`load` hands the
restarted router everything re-adoption needs in one object.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional, Tuple

from distributed_ghs_implementation_tpu.obs.events import BUS
from distributed_ghs_implementation_tpu.utils.wal import JsonlWal

JOURNAL_SCHEMA = "ghs-router-journal-v1"
COUNTER_PREFIX = "fleet.router.journal"

#: Mirror caps, matching the router's in-memory LRUs — the journal must
#: not remember more affinity than the router it restores.
_PIN_CAP = 4096
_SERVED_CAP = 4096

_KINDS = ("accept", "answer", "pin", "ring", "scale", "checkpoint")


def _entry(rec: dict) -> dict:
    """Schema-checked record -> validated entry (raising marks the line
    unparsable, exactly like non-JSON bytes)."""
    kind = rec["t"]
    if kind not in _KINDS:
        raise ValueError(f"unknown journal record kind {kind!r}")
    rec["seq"] = int(rec["seq"])
    if kind in ("accept", "answer"):
        rec["jid"] = int(rec["jid"])
    return rec


class JournalState:
    """Everything a restarted router re-adopts, replayed from the
    journal's longest valid prefix."""

    def __init__(self):
        self.had_state = False  # any parsable record at all
        self.next_jid = 1
        self.next_seq = 1
        #: jid -> the accept record (request/key/cls/lane) with no answer.
        self.unanswered: "Dict[int, dict]" = {}
        #: session digest -> worker id (insertion-ordered LRU mirror).
        self.pins: "Dict[str, int]" = {}
        #: digest -> worker that last answered it ok (forwarding affinity).
        self.served: "Dict[str, int]" = {}
        #: worker id -> {"addr": str|None, "retired": bool} — the pool as
        #: the crashed router knew it (scale-ups included).
        self.members: "Dict[int, dict]" = {}
        self.last_scale: Optional[dict] = None
        self.dropped = 0  # entries past a chain break (never replayed)

    # -- the replay state machine (shared by load() and the live mirror) --
    def apply(self, rec: dict) -> None:
        kind = rec["t"]
        if kind == "checkpoint":
            self.next_jid = int(rec.get("next_jid", self.next_jid))
            self.unanswered = {
                int(a["jid"]): a for a in rec.get("unanswered", [])
            }
            self.pins = {d: int(w) for d, w in (rec.get("pins") or {}).items()}
            self.served = {
                d: int(w) for d, w in (rec.get("served") or {}).items()
            }
            self.members = {
                int(k): dict(v) for k, v in (rec.get("members") or {}).items()
            }
            self.last_scale = rec.get("scale")
        elif kind == "accept":
            self.unanswered[rec["jid"]] = rec
            self.next_jid = max(self.next_jid, rec["jid"] + 1)
        elif kind == "answer":
            self.unanswered.pop(rec["jid"], None)
            if rec.get("ok") and rec.get("digest") is not None:
                worker = rec.get("worker")
                if worker is not None:
                    self.served[str(rec["digest"])] = int(worker)
                    while len(self.served) > _SERVED_CAP:
                        self.served.pop(next(iter(self.served)))
        elif kind == "pin":
            prev = rec.get("prev")
            if prev:
                self.pins.pop(prev, None)
            self.pins[str(rec["digest"])] = int(rec["worker"])
            while len(self.pins) > _PIN_CAP:
                self.pins.pop(next(iter(self.pins)))
        elif kind == "ring":
            wid = int(rec["worker"])
            action = rec.get("action")
            member = self.members.setdefault(
                wid, {"addr": None, "retired": False}
            )
            if rec.get("addr") is not None:
                member["addr"] = rec["addr"]
            if rec.get("lane") is not None:
                # The oversize-lane subring is capability-derived (a
                # dialed standby declares it in its hello), so restart
                # cannot reconstruct it from config alone — it rides the
                # ring record.
                member["lane"] = bool(rec["lane"])
            if action == "retire":
                member["retired"] = True
                self._drop_worker(wid)
            elif action == "remove":
                # Mirrors _on_death: the dead worker's pins and warm
                # copies die with the incarnation.
                self._drop_worker(wid)
            elif action == "add":
                member["retired"] = False
        elif kind == "scale":
            self.last_scale = rec.get("decision")

    def _drop_worker(self, wid: int) -> None:
        for d in [d for d, w in self.pins.items() if w == wid]:
            del self.pins[d]
        for d in [d for d, w in self.served.items() if w == wid]:
            del self.served[d]

    def checkpoint_record(self, seq: int) -> dict:
        return {
            "t": "checkpoint",
            "seq": seq,
            "next_jid": self.next_jid,
            "unanswered": list(self.unanswered.values()),
            "pins": dict(self.pins),
            "served": dict(self.served),
            "members": {str(k): v for k, v in self.members.items()},
            "scale": self.last_scale,
        }


class RouterJournal:
    """The router's durable ledger: one :class:`JsonlWal` under
    ``journal_dir``, a live state mirror, and checkpoint compaction.

    Thread-safe: the router appends from request threads, reader threads,
    and the heartbeat loop concurrently. Every append is durable (flock +
    fsync) before it returns — that is the whole point.
    """

    def __init__(self, root: str, *, checkpoint_every: int = 512):
        self.root = root
        self.path = os.path.join(root, "journal.jsonl")
        self.checkpoint_every = max(2, int(checkpoint_every))
        self._wal = JsonlWal(
            self.path,
            schema=JOURNAL_SCHEMA,
            counter_prefix=COUNTER_PREFIX,
            validate=_entry,
        )
        self._lock = threading.Lock()
        self.state = JournalState()
        self._since_checkpoint = 0
        self._closed = False

    def close(self) -> None:
        """Stop accepting appends, synchronously: taken under the same
        lock every append holds, so an in-flight append completes (and is
        durable — its owner gets a real ack) before this returns, and any
        append after it raises instead of racing a successor router that
        has already loaded the file (a late append would collide with the
        successor's sequence numbers and read as a chain break on the
        NEXT restart). ``FleetRouter.crash()`` calls this first — a dead
        process appends nothing."""
        with self._lock:
            self._closed = True

    # -- boot ----------------------------------------------------------
    def load(self) -> JournalState:
        """Replay the journal into a fresh state: the longest prefix of
        contiguous sequence numbers (a skipped corrupt line is a gap —
        everything past it is dropped and counted, never spliced)."""
        entries, _torn = self._wal.read()
        state = JournalState()
        expected: Optional[int] = None
        kept = 0
        for i, rec in enumerate(entries):
            if expected is not None and rec["seq"] != expected:
                BUS.count(f"{COUNTER_PREFIX}.chain_broken")
                state.dropped = len(entries) - i
                break
            state.apply(rec)
            state.had_state = True
            state.next_seq = rec["seq"] + 1
            expected = rec["seq"] + 1
            kept += 1
        BUS.count(f"{COUNTER_PREFIX}.replayed", kept)
        with self._lock:
            self.state = state
            self._since_checkpoint = 0
        return state

    # -- appends (all durable before returning) ------------------------
    def _append(self, rec: dict) -> None:
        """Must be called with ``self._lock`` held; assigns ``seq``,
        mirrors into the live state, and checkpoints on cadence."""
        if self._closed:
            raise OSError("journal closed (router crashed)")
        rec = dict(rec)
        rec["seq"] = self.state.next_seq
        self._wal.append(rec)
        self.state.next_seq += 1
        self.state.apply(rec)
        self._since_checkpoint += 1
        if (
            self._since_checkpoint >= self.checkpoint_every
            and rec["t"] != "checkpoint"
        ):
            self._checkpoint_locked()

    def accept(
        self,
        request: dict,
        *,
        key: Optional[str],
        cls: Optional[str],
        lane: bool = False,
        trace: Optional[dict] = None,
    ) -> int:
        """Durably record one accepted request; returns its journal id.
        The caller dispatches only after this returns — the accept ack is
        gated on the durable append. ``trace`` is the request's wire trace
        context (obs/tracing.py): it rides the accept record — and any
        checkpoint that carries it forward — so a successor router's
        orphan replay re-dispatches under the ORIGINAL trace_id."""
        with self._lock:
            jid = self.state.next_jid
            rec = {
                "t": "accept",
                "jid": jid,
                "req": request,
                "key": key,
                "cls": cls,
                "lane": bool(lane),
            }
            if trace is not None:
                rec["trace"] = trace
            self._append(rec)
        return jid

    def answer(
        self,
        jid: int,
        *,
        ok: bool,
        worker: Optional[int] = None,
        digest: Optional[str] = None,
    ) -> None:
        with self._lock:
            self._append({
                "t": "answer",
                "jid": int(jid),
                "ok": bool(ok),
                "worker": worker,
                "digest": digest,
            })

    def pin(
        self, digest: str, worker: int, prev: Optional[str] = None
    ) -> None:
        with self._lock:
            self._append({
                "t": "pin", "digest": digest, "worker": int(worker),
                "prev": prev,
            })

    def ring(
        self, action: str, worker: int, addr: Optional[str] = None,
        lane: Optional[bool] = None,
    ) -> None:
        with self._lock:
            self._append({
                "t": "ring", "action": action, "worker": int(worker),
                "addr": addr, "lane": lane,
            })

    def scale(self, decision: dict) -> None:
        with self._lock:
            self._append({"t": "scale", "decision": dict(decision)})

    # -- compaction ------------------------------------------------------
    def checkpoint(self) -> None:
        """Compact: rewrite the journal as one checkpoint record holding
        the mirrored state (unanswered accepts ride inside it)."""
        with self._lock:
            self._checkpoint_locked()

    def _checkpoint_locked(self) -> None:
        rec = self.state.checkpoint_record(self.state.next_seq)
        self._wal.rewrite([rec])
        self.state.next_seq += 1
        self._since_checkpoint = 0
        BUS.count(f"{COUNTER_PREFIX}.compact")

    # -- introspection (drills + the stats op) -------------------------
    def status(self) -> Tuple[int, int]:
        """``(unanswered, next_jid)`` of the live mirror."""
        with self._lock:
            return len(self.state.unanswered), self.state.next_jid

    def unanswered_entries(self) -> List[dict]:
        with self._lock:
            return list(self.state.unanswered.values())
