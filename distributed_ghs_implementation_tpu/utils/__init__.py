"""Utilities: verification oracles, resilience, visualization, reporting."""

from distributed_ghs_implementation_tpu.utils.compile_cache import (
    cache_stats,
    enable_persistent_cache,
)
from distributed_ghs_implementation_tpu.utils.resilience import (
    FAULTS,
    Supervisor,
    SupervisorConfig,
    supervised_solve,
)
from distributed_ghs_implementation_tpu.utils.verify import (
    networkx_mst_weight,
    scipy_mst_weight,
    verify_result,
)

__all__ = [
    "FAULTS",
    "Supervisor",
    "SupervisorConfig",
    "cache_stats",
    "enable_persistent_cache",
    "networkx_mst_weight",
    "scipy_mst_weight",
    "supervised_solve",
    "verify_result",
]
