// Native graph ingestion: RMAT generation, dedup, DIMACS parsing, CSR build.
//
// The reference's only native layer is the MPI library behind mpi4py
// (/root/reference/ghs_implementation_mpi.py:6). Here the native layer owns
// the data path instead: host-side graph construction at RMAT-24 scale, where
// NumPy is the bottleneck (vectorized Python RMAT-20 takes ~60 s; this does
// it in ~1 s). Exposed through a C ABI for ctypes — no pybind11 dependency.
//
// Build: g++ -O3 -march=native -fopenmp -shared -fPIC graph_native.cpp -o libgraph_native.so
// (distributed_ghs_implementation_tpu/graphs/native.py compiles on demand and
// falls back to NumPy when no toolchain is present.)

#include <algorithm>
#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <vector>

#ifdef _OPENMP
#include <omp.h>
#endif

namespace {

// splitmix64: tiny, high-quality, seedable per-edge generator so results are
// independent of thread count (deterministic parallel generation).
inline uint64_t splitmix64(uint64_t& x) {
  x += 0x9e3779b97f4a7c15ULL;
  uint64_t z = x;
  z = (z ^ (z >> 30)) * 0xbf58476d1ce4e5b9ULL;
  z = (z ^ (z >> 27)) * 0x94d049bb133111ebULL;
  return z ^ (z >> 31);
}

inline double u01(uint64_t& s) {
  return (splitmix64(s) >> 11) * 0x1.0p-53;
}

}  // namespace

extern "C" {

// Graph500-style RMAT: fills u/v/w (caller-allocated, length m).
// Deterministic in (seed); parallel over edges.
void rmat_generate(int scale, int64_t m, uint64_t seed, double a, double b,
                   double c, int64_t wlow, int64_t whigh, int64_t* u,
                   int64_t* v, int64_t* w) {
  const double d = 1.0 - a - b - c;
  const double p_src = a + b;  // P(src bit = 0)
  const double p_dst_given_src0 = (a + b) > 0 ? b / (a + b) : 0.0;
  const double p_dst_given_src1 = (c + d) > 0 ? d / (c + d) : 0.0;
  const int64_t wspan = whigh - wlow + 1;
#pragma omp parallel for schedule(static)
  for (int64_t e = 0; e < m; ++e) {
    uint64_t s = seed * 0x9e3779b97f4a7c15ULL + (uint64_t)e * 0xda942042e4dd58b5ULL;
    splitmix64(s);  // warm up
    int64_t uu = 0, vv = 0;
    for (int bit = 0; bit < scale; ++bit) {
      const bool src_bit = u01(s) >= p_src;
      const double p_dst = src_bit ? p_dst_given_src1 : p_dst_given_src0;
      const bool dst_bit = u01(s) < p_dst;
      uu = (uu << 1) | (int64_t)src_bit;
      vv = (vv << 1) | (int64_t)dst_bit;
    }
    u[e] = uu;
    v[e] = vv;
    w[e] = wlow + (int64_t)(splitmix64(s) % (uint64_t)wspan);
  }
}

// Canonicalize (lo, hi), drop self-loops, dedup keeping the min weight per
// pair. In-place; returns the new edge count.
int64_t dedup_edges(int64_t m, int64_t n, int64_t* u, int64_t* v, int64_t* w) {
  struct Rec {
    int64_t code;
    int64_t w;
  };
  std::vector<Rec> recs;
  recs.reserve((size_t)m);
  for (int64_t e = 0; e < m; ++e) {
    const int64_t lo = u[e] < v[e] ? u[e] : v[e];
    const int64_t hi = u[e] < v[e] ? v[e] : u[e];
    if (lo == hi) continue;  // self-loop
    recs.push_back({lo * n + hi, w[e]});
  }
  std::sort(recs.begin(), recs.end(), [](const Rec& x, const Rec& y) {
    return x.code < y.code || (x.code == y.code && x.w < y.w);
  });
  int64_t out = 0;
  for (size_t i = 0; i < recs.size(); ++i) {
    if (i == 0 || recs[i].code != recs[i - 1].code) {
      u[out] = recs[i].code / n;
      v[out] = recs[i].code % n;
      w[out] = recs[i].w;
      ++out;
    }
  }
  return out;
}

// DIMACS .gr parser ("p sp N M" header, "a u v w" arcs, 1-indexed).
// Two-phase via cap: pass cap=0 to get the arc count (and n via n_out),
// then call again with arrays of that capacity. Returns arcs written (or
// total arcs if cap==0); -1 on I/O error.
int64_t dimacs_parse(const char* path, int64_t* n_out, int64_t* u, int64_t* v,
                     int64_t* w, int64_t cap) {
  FILE* f = std::fopen(path, "r");
  if (!f) return -1;
  char line[256];
  int64_t count = 0;
  *n_out = 0;
  while (std::fgets(line, sizeof line, f)) {
    // A line longer than the buffer would leave its tail to be misread as a
    // fresh record (desyncing the two-phase count/fill passes); consume the
    // remainder so each physical line is parsed exactly once.
    if (!std::strchr(line, '\n') && !std::feof(f)) {
      int ch;
      while ((ch = std::fgetc(f)) != EOF && ch != '\n') {
      }
    }
    if (line[0] == 'p') {
      long long n = 0, m = 0;
      std::sscanf(line, "p %*s %lld %lld", &n, &m);
      *n_out = (int64_t)n;
    } else if (line[0] == 'a') {
      long long aa, bb, ww;
      if (std::sscanf(line, "a %lld %lld %lld", &aa, &bb, &ww) == 3) {
        if (cap > 0) {
          if (count >= cap) break;
          u[count] = (int64_t)aa - 1;
          v[count] = (int64_t)bb - 1;
          w[count] = (int64_t)ww;
        }
        ++count;
      }
    }
  }
  std::fclose(f);
  return count;
}

// Rank-sorted CSR over directed slots: like build_csr but each row is sorted
// ascending by the per-edge rank (the kernel's total order), carrying the
// rank instead of the weight. Counting sort by src then per-row std::sort —
// O(E + sum_v d_v log d_v). Feeds Graph.ell_buckets at RMAT-22+ scale where
// the NumPy lexsort path takes minutes.
void build_rank_csr(int64_t n, int64_t m, const int64_t* u, const int64_t* v,
                    const int64_t* rank, int64_t* indptr, int64_t* adj_dst,
                    int64_t* adj_rank) {
  std::memset(indptr, 0, sizeof(int64_t) * (size_t)(n + 1));
  for (int64_t e = 0; e < m; ++e) {
    ++indptr[u[e] + 1];
    ++indptr[v[e] + 1];
  }
  for (int64_t i = 0; i < n; ++i) indptr[i + 1] += indptr[i];
  std::vector<int64_t> cursor(indptr, indptr + n);
  for (int64_t e = 0; e < m; ++e) {
    int64_t cu = cursor[u[e]]++;
    adj_dst[cu] = v[e];
    adj_rank[cu] = rank[e];
    int64_t cv = cursor[v[e]]++;
    adj_dst[cv] = u[e];
    adj_rank[cv] = rank[e];
  }
  struct Pair {
    int64_t rank, dst;
  };
#pragma omp parallel for schedule(dynamic, 1024)
  for (int64_t vtx = 0; vtx < n; ++vtx) {
    const int64_t s = indptr[vtx], e = indptr[vtx + 1];
    if (e - s < 2) continue;
    std::vector<Pair> row((size_t)(e - s));
    for (int64_t i = s; i < e; ++i) row[(size_t)(i - s)] = {adj_rank[i], adj_dst[i]};
    std::sort(row.begin(), row.end(),
              [](const Pair& a, const Pair& b) { return a.rank < b.rank; });
    for (int64_t i = s; i < e; ++i) {
      adj_rank[i] = row[(size_t)(i - s)].rank;
      adj_dst[i] = row[(size_t)(i - s)].dst;
    }
  }
}

// Per-vertex minimum incident rank: one O(m) pass over rank-ordered endpoint
// arrays (ra[r], rb[r] = endpoints of the rank-r edge). out has n entries,
// INT32_MAX sentinel for isolated vertices. This IS Boruvka level 1 (every
// incident edge is outgoing at level 0), done on the host for free.
void first_rank(int64_t n, int64_t m, const int64_t* ra, const int64_t* rb,
                int32_t* out) {
  const int32_t kMax = 0x7fffffff;
  for (int64_t v = 0; v < n; ++v) out[v] = kMax;
  for (int64_t r = 0; r < m; ++r) {
    if (out[ra[r]] == kMax) out[ra[r]] = (int32_t)r;
    if (out[rb[r]] == kMax) out[rb[r]] = (int32_t)r;
  }
}

// int64-rank variant for the sharded rank64 path (rank spaces past 2^31;
// the int32 first_rank's (int32_t)r cast would overflow there).
void first_rank64(int64_t n, int64_t m, const int64_t* ra, const int64_t* rb,
                  int64_t* out) {
  const int64_t kMax = 0x7fffffffffffffffLL;
  for (int64_t v = 0; v < n; ++v) out[v] = kMax;
  for (int64_t r = 0; r < m; ++r) {
    if (out[ra[r]] == kMax) out[ra[r]] = r;
    if (out[rb[r]] == kMax) out[rb[r]] = r;
  }
}

// int32 variant over already-built rank endpoints (the prep fast path reuses
// the padded ra/rb it just produced instead of re-gathering from u/v).
void first_rank_i32(int64_t n, int64_t m, const int32_t* ra, const int32_t* rb,
                    int32_t* out) {
  const int32_t kMax = 0x7fffffff;
  for (int64_t v = 0; v < n; ++v) out[v] = kMax;
  for (int64_t r = 0; r < m; ++r) {
    if (out[ra[r]] == kMax) out[ra[r]] = (int32_t)r;
    if (out[rb[r]] == kMax) out[rb[r]] = (int32_t)r;
  }
}

// int32 endpoints, int64 rank output: the rank64 staging path reuses the
// padded int32 ra/rb it just built (ranks exceed int32 there, vertex ids
// never do) instead of re-gathering int64 endpoints from u/v.
void first_rank_i32e64(int64_t n, int64_t m, const int32_t* ra,
                       const int32_t* rb, int64_t* out) {
  const int64_t kMax = 0x7fffffffffffffffLL;
  for (int64_t v = 0; v < n; ++v) out[v] = kMax;
  for (int64_t r = 0; r < m; ++r) {
    if (out[ra[r]] == kMax) out[ra[r]] = r;
    if (out[rb[r]] == kMax) out[rb[r]] = r;
  }
}

// Level-2 MOE on the host: per-FRAGMENT first cross rank, fused with the
// fragment relabel (fa = parent1[ra]) so the m-sized relabeled arrays never
// materialize. One O(m) pass over rank-ascending endpoints.
void first_cross_rank(int64_t n, int64_t m, const int32_t* ra,
                      const int32_t* rb, const int32_t* parent1,
                      int32_t* out) {
  const int32_t kMax = 0x7fffffff;
  for (int64_t v = 0; v < n; ++v) out[v] = kMax;
  for (int64_t r = 0; r < m; ++r) {
    const int32_t fa = parent1[ra[r]];
    const int32_t fb = parent1[rb[r]];
    if (fa == fb) continue;
    if (out[fa] == kMax) out[fa] = (int32_t)r;
    if (out[fb] == kMax) out[fb] = (int32_t)r;
  }
}

// Fused rank-endpoint build: ra[r] = (int32)u[order[r]], rb likewise, with the
// tail zero-padded to size_pad. One pass, int32 writes — replaces two int64
// NumPy fancy-gathers plus casts plus pad copies (the pre-transfer critical
// path of prep: the ra/rb staging cannot start before these arrays exist).
void rank_endpoints_i32(int64_t m, int64_t size_pad, const int64_t* order,
                        const int64_t* u, const int64_t* v, int32_t* ra,
                        int32_t* rb) {
#pragma omp parallel for schedule(static)
  for (int64_t r = 0; r < m; ++r) {
    const int64_t e = order[r];
    ra[r] = (int32_t)u[e];
    rb[r] = (int32_t)v[e];
  }
  if (size_pad > m) {
    std::memset(ra + m, 0, (size_t)(size_pad - m) * sizeof(int32_t));
    std::memset(rb + m, 0, (size_t)(size_pad - m) * sizeof(int32_t));
  }
}

// rank_endpoints_i32 fused with the 24-bit planar wire packing: one pass
// emits the int32 endpoint arrays (the host levels consume them) AND the
// six little-endian byte-planes of the packed transfer buffer
// (planes[k*size_pad + r] = byte k of ra[r] for k<3, of rb[r] for k>=3) —
// replacing a separate strided re-read/re-write of both arrays on prep's
// pre-transfer critical path. Caller guarantees endpoint ids < 2^24.
void rank_endpoints_i32_planes(int64_t m, int64_t size_pad,
                               const int64_t* order, const int64_t* u,
                               const int64_t* v, int32_t* ra, int32_t* rb,
                               uint8_t* planes) {
#pragma omp parallel for schedule(static)
  for (int64_t r = 0; r < m; ++r) {
    const int64_t e = order[r];
    const uint32_t a = (uint32_t)u[e];
    const uint32_t b = (uint32_t)v[e];
    ra[r] = (int32_t)a;
    rb[r] = (int32_t)b;
    planes[r] = (uint8_t)(a & 0xff);
    planes[size_pad + r] = (uint8_t)((a >> 8) & 0xff);
    planes[2 * size_pad + r] = (uint8_t)((a >> 16) & 0xff);
    planes[3 * size_pad + r] = (uint8_t)(b & 0xff);
    planes[4 * size_pad + r] = (uint8_t)((b >> 8) & 0xff);
    planes[5 * size_pad + r] = (uint8_t)((b >> 16) & 0xff);
  }
  if (size_pad > m) {
    const size_t pad = (size_t)(size_pad - m);
    std::memset(ra + m, 0, pad * sizeof(int32_t));
    std::memset(rb + m, 0, pad * sizeof(int32_t));
    for (int k = 0; k < 6; ++k)
      std::memset(planes + (size_t)k * size_pad + m, 0, pad);
  }
}

// Kruskal MSF over edges in ascending (weight, edge id) order — the oracle
// fast path: the rank order already exists natively, so one union-find pass
// verifies a solve at C speed (SciPy's csgraph oracle costs ~890 s at
// RMAT-24; this is O(m alpha(n))). Writes [total_weight, edge_count] to out.
static int64_t uf_find(int64_t* p, int64_t x) {
  while (p[x] != x) {
    p[x] = p[p[x]];  // path halving
    x = p[x];
  }
  return x;
}

// Full Kruskal SOLVE over edges in the given (weight, edge id) order:
// emits the chosen edge ids (ascending rank order) and the final
// per-vertex component label (fully path-compressed). Validates `order`
// instead of trusting it: the solver under test consumes the SAME
// precomputed order, so an independent check must prove (a) the order is
// a permutation of [0, m) and (b) weights are non-decreasing along it —
// given both, the result is the true unique MSF regardless of how ties
// were broken. Returns the MSF edge count, or -1 on a corrupt order.
int64_t kruskal_msf_solve(int64_t n, int64_t m, const int64_t* order,
                          const int64_t* u, const int64_t* v,
                          const int64_t* w, int64_t* out_edges,
                          int64_t* labels) {
  std::vector<int64_t> parent((size_t)n);
  for (int64_t i = 0; i < n; ++i) parent[i] = i;
  std::vector<uint8_t> seen((size_t)m, 0);
  int64_t count = 0, prev_w = 0;
  for (int64_t r = 0; r < m; ++r) {
    const int64_t e = order[r];
    if (e < 0 || e >= m || seen[e] || (r > 0 && w[e] < prev_w)) return -1;
    seen[e] = 1;
    prev_w = w[e];
    const int64_t ru = uf_find(parent.data(), u[e]);
    const int64_t rv = uf_find(parent.data(), v[e]);
    if (ru == rv) continue;
    parent[ru] = rv;
    out_edges[count++] = e;
  }
  for (int64_t i = 0; i < n; ++i) labels[i] = uf_find(parent.data(), i);
  return count;
}

// Weight-only oracle form: one body with kruskal_msf_solve (a divergence
// between the oracle and the host solve would be the quiet kind of bug —
// share the loop). Writes [total_weight, edge_count]; edge_count = -1 on
// a corrupt order (caller falls back to the independently-sorted SciPy
// oracle).
void kruskal_msf(int64_t n, int64_t m, const int64_t* order, const int64_t* u,
                 const int64_t* v, const int64_t* w, int64_t* out) {
  std::vector<int64_t> edges((size_t)(n > 0 ? n : 1));
  std::vector<int64_t> labels((size_t)(n > 0 ? n : 1));
  const int64_t count =
      kruskal_msf_solve(n, m, order, u, v, w, edges.data(), labels.data());
  if (count < 0) {
    out[0] = 0;
    out[1] = -1;
    return;
  }
  int64_t total = 0;
  for (int64_t i = 0; i < count; ++i) total += w[edges[i]];
  out[0] = total;
  out[1] = count;
}

// Stable counting sort of edge ids by integer weight (ranks ascending by
// (weight, edge id)) for small weight ranges — the lexsort that dominates
// host prep at RMAT-24 scale becomes O(m + range).
// Returns 1 on success, 0 when the range is too large (caller falls back).
int rank_order_counting(int64_t m, const int64_t* w, int64_t wlow,
                        int64_t whigh, int64_t* order) {
  const int64_t range = whigh - wlow + 1;
  if (range <= 0 || range > (1 << 22)) return 0;
  std::vector<int64_t> count((size_t)range + 1, 0);
  for (int64_t e = 0; e < m; ++e) ++count[w[e] - wlow + 1];
  for (int64_t i = 0; i < range; ++i) count[i + 1] += count[i];
  for (int64_t e = 0; e < m; ++e) order[count[w[e] - wlow]++] = e;
  return 1;
}

// CSR over directed slots from undirected edges: indptr has n+1 entries;
// adj_dst/adj_w have 2m entries. Counting sort, O(n + m).
void build_csr(int64_t n, int64_t m, const int64_t* u, const int64_t* v,
               const int64_t* w, int64_t* indptr, int64_t* adj_dst,
               int64_t* adj_w) {
  std::memset(indptr, 0, sizeof(int64_t) * (size_t)(n + 1));
  for (int64_t e = 0; e < m; ++e) {
    ++indptr[u[e] + 1];
    ++indptr[v[e] + 1];
  }
  for (int64_t i = 0; i < n; ++i) indptr[i + 1] += indptr[i];
  std::vector<int64_t> cursor(indptr, indptr + n);
  for (int64_t e = 0; e < m; ++e) {
    int64_t cu = cursor[u[e]]++;
    adj_dst[cu] = v[e];
    adj_w[cu] = w[e];
    int64_t cv = cursor[v[e]]++;
    adj_dst[cv] = u[e];
    adj_w[cv] = w[e];
  }
}

}  // extern "C"
