"""Host-L2 road experiment under honest end-to-end accounting (VERDICT r4
item 7).

The round-4 road bisection put ~9 s of the 23.9M-node grid's 14.5 s solve
in the L1+L2 head, and round 4's winning move was computing L1 on the host.
This experiment moves LEVEL 2 to the host too (``host_level2``: native
fused relabel + first-cross-rank scan + hook/compress), then starts the
device program at the level-3 relabel. Both clocks are reported — host
prep (including the new pass and the extra ``parent12``/``l2_ranks``
staging) AND the device solve — so a win must survive end-to-end
accounting, not cost-shifting (VERDICT r4 weak #1).

Prints one JSON line with baseline and host-L2 numbers; byte-compares the
MSTs and verifies the weight against the SciPy oracle.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def sync(x):
    _ = np.asarray(x.ravel()[:1])


def main() -> int:
    import jax
    import jax.numpy as jnp

    from distributed_ghs_implementation_tpu.graphs.generators import (
        road_grid_graph,
    )
    from distributed_ghs_implementation_tpu.models import rank_solver as rs

    rows, cols = 4864, 4912  # the r4 USA-road-size grid (23.9M nodes)
    t0 = time.perf_counter()
    g = road_grid_graph(rows, cols, seed=8)
    gen_s = time.perf_counter() - t0
    print(f"gen: {gen_s:.1f}s m={g.num_edges:,}", file=sys.stderr)
    fam = rs._pick_family(g)

    # ---------------- baseline: production staged path ----------------
    t0 = time.perf_counter()
    vmin0, ra, rb, parent1 = rs.prepare_rank_arrays_full(g)
    prep_base_s = time.perf_counter() - t0
    mst, frag, lv = rs.solve_rank_auto(vmin0, ra, rb, family=fam, parent1=parent1)
    sync(mst)
    solves = []
    for _ in range(2):
        t0 = time.perf_counter()
        mst, frag, lv = rs.solve_rank_auto(
            vmin0, ra, rb, family=fam, parent1=parent1
        )
        sync(mst)
        solves.append(time.perf_counter() - t0)
    base_solve = min(solves)
    base_ids = np.sort(g.edge_id_of_rank(np.nonzero(np.asarray(mst))[0]))

    # ---------------- host-L2 variant ----------------
    n_pad = vmin0.shape[0]
    m_pad = ra.shape[0]
    params = rs._family_params(fam)

    t0 = time.perf_counter()
    ra_h, rb_h = g.rank_endpoints(pad_to=m_pad)
    parent1_h = np.asarray(parent1)
    parent12_np, l2_ranks_np = rs.host_level2(
        parent1_h, ra_h, rb_h, g.num_edges
    )
    host_l2_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    parent12 = jax.device_put(parent12_np)
    l2_pad = int(rs._bucket_size(max(l2_ranks_np.size, 1024)))
    l2_ranks = jax.device_put(
        np.pad(l2_ranks_np, (0, l2_pad - l2_ranks_np.size),
               constant_values=l2_ranks_np[0] if l2_ranks_np.size else 0)
    )
    sync(parent12); sync(l2_ranks)
    stage_l2_s = time.perf_counter() - t0
    print(f"host_level2: {host_l2_s:.2f}s (+{stage_l2_s:.2f}s staging, "
          f"{l2_ranks_np.size:,} l2 marks)", file=sys.stderr)

    @jax.jit
    def head2(vmin0, ra, rb, parent12, l2_ranks):
        # Level-3 entry: relabel by the host 2-level partition, mark L1+L2.
        mp = ra.shape[0]
        fa = parent12[ra]
        fb = parent12[rb]
        has1 = vmin0 < rs.INT32_MAX
        safe1 = jnp.where(has1, vmin0, 0)
        mst = jnp.zeros(mp, dtype=bool).at[safe1].max(has1)
        mst = mst.at[l2_ranks].set(True)
        count = jnp.sum((fa != fb).astype(jnp.int32))
        return mst, fa, fb, count

    def solve_host_l2():
        mst, fa, fb, count_d = head2(vmin0, ra, rb, parent12, l2_ranks)
        count = int(jax.device_get(count_d))
        mst, fragment, lv = rs._finish_to_fixpoint(
            parent12, mst, fa, fb, jnp.arange(m_pad, dtype=jnp.int32),
            lv=2, count=count, space=n_pad,
            max_levels=2 + rs._max_levels(n_pad),
            chunk_levels=params["chunk_levels"],
            compact_space=True,
        )
        return mst, fragment, lv

    mst2, frag2, lv2 = solve_host_l2()
    sync(mst2)
    solves2 = []
    for _ in range(2):
        t0 = time.perf_counter()
        mst2, frag2, lv2 = solve_host_l2()
        sync(mst2)
        solves2.append(time.perf_counter() - t0)
    l2_solve = min(solves2)
    l2_ids = np.sort(g.edge_id_of_rank(np.nonzero(np.asarray(mst2))[0]))

    same = bool(np.array_equal(base_ids, l2_ids))
    w = int(g.w[l2_ids].sum())
    t0 = time.perf_counter()
    from distributed_ghs_implementation_tpu.utils.verify import scipy_mst_weight

    oracle = int(scipy_mst_weight(g))
    oracle_s = time.perf_counter() - t0

    out = {
        "config": "host-L2 road experiment (r5)",
        "round": 5,
        "nodes": g.num_nodes, "edges": g.num_edges, "family": fam,
        "baseline": {"prep_s": round(prep_base_s, 2),
                     "solve_s": round(base_solve, 2),
                     "e2e_s": round(prep_base_s + base_solve, 2)},
        "host_l2": {"extra_host_s": round(host_l2_s, 2),
                    "extra_staging_s": round(stage_l2_s, 2),
                    "solve_s": round(l2_solve, 2),
                    "e2e_s": round(prep_base_s + host_l2_s + stage_l2_s
                                   + l2_solve, 2)},
        "mst_byte_identical": same,
        "weight": w, "oracle": oracle, "verified": bool(w == oracle),
        "oracle_s": round(oracle_s, 1),
    }
    print(json.dumps(out))
    return 0 if (same and w == oracle) else 1


if __name__ == "__main__":
    sys.exit(main())
