"""Fleet transport layer: framed channels over OS pipes or TCP sockets.

Round 12's fleet hard-wired the framing (``fleet/framing.py``) to a worker
subprocess's stdin/stdout — a single-host ceiling. This module abstracts
the channel so the router addresses a worker the same way whether it is a
child process on this machine or a pod-slice-owning process on another
one:

* :class:`PipeTransport` — the round-12 medium unchanged: locked,
  immediately-flushed frame writes on a subprocess pipe pair.
* :class:`SocketTransport` — frames over a connected TCP socket with
  **write coalescing / pipelined frame I/O**: ``send()`` enqueues the
  encoded frame and a dedicated flusher thread drains *everything* queued
  into one ``sendall`` — under concurrent dispatch the router pays one
  syscall (and one TCP segment, Nagle off) for a whole burst of frames
  instead of one per request. ``transport.writes`` / ``transport.frames``
  expose the coalescing ratio.

**Registration protocol.** A worker introduces itself with one *hello
frame* — the same frame on pipes (where round 12 called it the ready
frame) and sockets (where it doubles as dial-in registration)::

    {"ready": true, "proto": 1, "worker": K, "pid": ...,
     "caps": {"lane": bool, "stream": bool, "kernel": "auto",
              "warmed": bool},
     "token": "<spawn token>", "lease_s": ...}

``proto`` is the fleet protocol version — :func:`check_hello` rejects a
mismatch with a clear error instead of letting two incompatible processes
mis-parse each other's frames. ``caps`` carries the worker's capability
flags in ONE place (round 13 grew an ad-hoc ``lane`` key; round 14 would
have added ``stream``; this is where all of them live now). ``warmed`` is
the elastic fleet's warm-handoff gate: a worker only sends its hello
*after* its service is built and its warmup ladder has run, so a truthful
``warmed: true`` means "route traffic at me and you will not see a cold
p99" — :meth:`fleet.router.FleetRouter.add_worker` refuses ring entry to
a hello without it (``docs/FLEET.md`` "Elasticity"). ``token``
authenticates a spawned TCP worker's dial-in to its slot + incarnation, so
a stale worker from a previous incarnation cannot hijack a restarted
slot's connection.

Death detection composes with the router's existing machinery: a closed
connection surfaces as ``recv() -> None`` exactly like pipe EOF, and the
heartbeat loop's silence threshold acts as the **lease** — a socket that
stays connected while its worker stops answering pings expires after
``lease_s`` and is declared dead the same way a wedged pipe worker is.
"""

from __future__ import annotations

import os
import socket
import threading
import uuid
from typing import IO, Callable, Optional, Tuple

from distributed_ghs_implementation_tpu.fleet.framing import (
    FrameError,
    encode_bframe,
    encode_frame,
    fold_sections,
    frame_sections,
    read_frame,
)

#: The fleet wire-protocol version. Bump on any frame-shape change the
#: other side cannot ignore; the hello exchange rejects mismatches.
PROTO_VERSION = 1

#: Test hook: lets a drill spawn a worker that ADVERTISES a different
#: protocol version, to prove the router's rejection path end to end.
_PROTO_ENV = "GHS_FLEET_PROTO"

#: The hello frame is a few hundred bytes; anything bigger is not a hello.
_MAX_HELLO_BYTES = 64 * 1024


class HelloError(ValueError):
    """A malformed or incompatible hello frame (version mismatch, missing
    identity). The connection is rejected with this message."""


def build_hello(
    worker_id: int,
    *,
    caps: Optional[dict] = None,
    token: Optional[str] = None,
    lease_s: Optional[float] = None,
    warmed: Optional[bool] = None,
) -> dict:
    """The worker's registration frame (pipes call it the ready frame).

    ``warmed`` lands in ``caps`` — it is a capability like the others, but
    it carries a *timing* promise (the warmup ladder already ran), so it
    gets a first-class parameter rather than riding in an ad-hoc dict.
    """
    proto = int(os.environ.get(_PROTO_ENV, PROTO_VERSION))
    hello = {
        "ready": True,
        "proto": proto,
        "worker": int(worker_id),
        "pid": os.getpid(),
        "caps": dict(caps or {}),
    }
    # Frame-checksum capability (round 19): this build parses the
    # checksummed header form, so the router may enable CRC toward us.
    # The hello itself always goes out in the legacy form — it is the
    # message that NEGOTIATES the capability (GHS_FLEET_CRC=0 opts a
    # worker out, for mixed-build compatibility drills).
    hello["caps"].setdefault(
        "crc", os.environ.get("GHS_FLEET_CRC", "1") != "0"
    )
    # Trace-propagation capability: this build understands an optional
    # ``trace`` field on request frames (obs/tracing.py) and will
    # re-establish the router's trace context before dispatch. Same
    # opt-in shape as CRC — a legacy worker without the cap just gets
    # untraced frames (GHS_FLEET_TRACE=0 simulates one in drills).
    hello["caps"].setdefault(
        "trace", os.environ.get("GHS_FLEET_TRACE", "1") != "0"
    )
    # Binary-wire capability: this build parses B-frames (raw array
    # sections behind a JSON header, ``fleet/framing.py``), so the router
    # may pass section-bearing payloads through opaquely instead of
    # folding them to JSON. Same opt-in shape as CRC — a legacy worker
    # without the cap gets classic JSON frames, per connection
    # (GHS_FLEET_WIRE=0 simulates one in the mixed-build drills).
    hello["caps"].setdefault(
        "wire", os.environ.get("GHS_FLEET_WIRE", "1") != "0"
    )
    if warmed is not None:
        hello["caps"]["warmed"] = bool(warmed)
    if token is not None:
        hello["token"] = token
    if lease_s is not None:
        hello["lease_s"] = float(lease_s)
    return hello


def check_hello(frame: dict) -> dict:
    """Validate a hello frame; returns it with ``caps`` normalized.

    Raises :class:`HelloError` with an actionable message on a protocol
    version mismatch (the one failure an operator mixing fleet builds
    across hosts will actually hit) or a hello without a worker identity.
    """
    if not frame.get("ready"):
        raise HelloError(f"not a hello frame: {sorted(frame)[:8]}")
    proto = frame.get("proto")
    if proto != PROTO_VERSION:
        raise HelloError(
            f"fleet protocol version mismatch: worker speaks proto "
            f"{proto!r}, this router speaks {PROTO_VERSION} — upgrade the "
            f"older side (worker pid {frame.get('pid')}, id "
            f"{frame.get('worker')})"
        )
    if frame.get("worker") is None:
        raise HelloError("hello frame carries no worker id")
    caps = frame.get("caps")
    frame["caps"] = dict(caps) if isinstance(caps, dict) else {}
    return frame


def new_conn_token() -> str:
    """An unguessable per-incarnation dial-in token."""
    return uuid.uuid4().hex


def parse_hostport(addr: str, *, default_host: str = "127.0.0.1") -> Tuple[str, int]:
    """``"host:port"`` (or bare ``"port"``) -> ``(host, port)``."""
    host, _, port = addr.rpartition(":")
    if not port.isdigit():
        raise ValueError(f"bad address {addr!r}: expected HOST:PORT")
    return (host or default_host, int(port))


class TransportClosed(OSError):
    """Raised by ``send`` on a transport already known to be dead — the
    synchronous signal the dispatch path turns into failover."""


class Transport:
    """One framed channel to a peer. ``send`` may buffer (socket
    coalescing); ``recv`` blocks for one frame and returns ``None`` when
    the channel is gone — a garbled frame also ends the channel (the
    stream is no longer frame-aligned), after counting it.

    **CRC negotiation**: ``enable_crc()`` switches outbound frames to the
    checksummed header form (``fleet/framing.py``). The router calls it
    for workers whose hello advertised the ``crc`` capability; a worker —
    which never sees a router hello — enables it by *echo-on-receipt*:
    the first inbound frame carrying a checksum proves the peer both
    emits and (being the same build) parses the form. Either way, no
    checksummed frame is ever sent at a peer that might not parse it.

    **Binary-wire negotiation** rides the identical machinery one rung
    up: ``enable_wire()`` (router side, from hello ``caps.wire``) or the
    first inbound B-frame (worker side, echo-on-receipt) switches
    section-bearing payloads to the binary form. A payload that carries
    a :class:`~..fleet.framing.WireSections` toward a peer WITHOUT the
    capability is folded to classic JSON at the send boundary
    (``fold_sections``) — per-connection degradation, never an error.
    """

    kind = "abstract"
    crc_out = False  # emit checksummed frames (set via enable_crc)
    wire_out = False  # emit binary B-frames (set via enable_wire)

    def enable_crc(self) -> None:
        self.crc_out = True

    def enable_wire(self) -> None:
        self.wire_out = True

    def _note_recv_meta(self, meta: dict) -> None:
        if meta.get("crc") and not self.crc_out:
            self.crc_out = True  # peer speaks checksummed frames: echo it
        if meta.get("wire") and not self.wire_out:
            self.wire_out = True  # peer speaks B-frames: echo it

    def encode_for_peer(self, obj: dict) -> bytes:
        """``obj`` in the richest form this peer negotiated: B-frame for
        section-bearing payloads toward ``caps.wire`` peers, folded JSON
        toward legacy peers, plain (CRC'd where negotiated) JSON for
        everything else."""
        if frame_sections(obj) is not None:
            if self.wire_out:
                return encode_bframe(obj)
            return encode_frame(fold_sections(obj), crc=self.crc_out)
        return encode_frame(obj, crc=self.crc_out)

    def send(self, obj: dict) -> None:
        raise NotImplementedError

    def recv(self) -> Optional[dict]:
        raise NotImplementedError

    def close(self, *, flush: bool = True) -> None:
        """Tear down the channel. ``flush=True`` (graceful paths: drain,
        worker exit) waits briefly for queued frames to reach the wire;
        ``flush=False`` (death paths: lease expiry, kill, partition
        simulation) tears down immediately — waiting on a wedged peer
        there would stall failover."""
        raise NotImplementedError

    @property
    def closed(self) -> bool:
        raise NotImplementedError


class PipeTransport(Transport):
    """The round-12 medium behind the Transport interface: immediate
    locked frame writes, blocking frame reads, on a pipe pair."""

    kind = "pipe"

    def __init__(self, write_stream: IO[bytes], read_stream: IO[bytes]):
        self._w = write_stream
        self._r = read_stream
        self._lock = threading.Lock()
        self._closed = False
        self.writes = 0
        self.frames = 0

    def send(self, obj: dict) -> None:
        self.send_bytes(self.encode_for_peer(obj))

    def send_bytes(self, data: bytes) -> None:
        with self._lock:
            if self._closed:
                raise TransportClosed("pipe transport closed")
            self._w.write(data)
            self._w.flush()
            self.writes += 1
            self.frames += 1

    def recv(self) -> Optional[dict]:
        meta: dict = {}
        try:
            frame = read_frame(self._r, meta=meta)
        except (FrameError, OSError, ValueError):
            return None
        self._note_recv_meta(meta)
        return frame

    def close(self, *, flush: bool = True) -> None:
        # Pipe writes are immediate (send flushes), so there is nothing
        # queued to wait for either way.
        with self._lock:
            if self._closed:
                return
            self._closed = True
            for stream in (self._w, self._r):
                try:
                    stream.close()
                except OSError:
                    pass

    @property
    def closed(self) -> bool:
        return self._closed


class SocketTransport(Transport):
    """Frames over one connected TCP socket, writes coalesced.

    ``send()`` never blocks on the network: it appends the encoded frame
    to the outbound queue and wakes the flusher, which drains the WHOLE
    queue into a single ``sendall``. Concurrent senders (the router's
    request threads, the worker's response pool) therefore share syscalls
    instead of serializing on them — the pipelined frame I/O the
    round-16 transport exists for. ``pipelined=False`` degrades to a
    direct locked ``sendall`` per frame (the comparison baseline).

    A send error (peer gone) closes the socket, which pops the blocking
    ``recv`` with ``None`` — one death signal, the same one pipe EOF
    gives, so the router's failover path needs no new cases.
    """

    kind = "tcp"

    def __init__(
        self, sock: socket.socket, *, pipelined: bool = True, rfile=None
    ):
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock = sock
        # The hello exchange reads from a buffered file over this socket
        # BEFORE the transport exists; reuse that exact object — a fresh
        # makefile would silently drop whatever the first one buffered
        # past the hello frame.
        self._rfile = rfile if rfile is not None else sock.makefile("rb")
        self._lock = threading.Lock()
        self._pending: list = []
        self._inflight = False  # flusher holds a popped batch mid-sendall
        self._wake = threading.Condition(self._lock)
        self._closed = False
        self._pipelined = pipelined
        self.writes = 0
        self.frames = 0
        self.peer = None
        try:
            self.peer = "%s:%d" % sock.getpeername()[:2]
        except OSError:
            pass
        self._flusher = None
        if pipelined:
            self._flusher = threading.Thread(
                target=self._flush_loop, name="fleet-tcp-flush", daemon=True
            )
            self._flusher.start()

    # -- writing -------------------------------------------------------
    def send(self, obj: dict) -> None:
        self.send_bytes(self.encode_for_peer(obj))

    def send_bytes(self, data: bytes) -> None:
        if self._pipelined:
            with self._wake:
                if self._closed:
                    raise TransportClosed("tcp transport closed")
                self._pending.append(data)
                self.frames += 1
                self._wake.notify()
            return
        with self._lock:
            if self._closed:
                raise TransportClosed("tcp transport closed")
            try:
                self._sock.sendall(data)
            except OSError:
                self._teardown_locked()
                raise
            self.writes += 1
            self.frames += 1

    def _flush_loop(self) -> None:
        while True:
            with self._wake:
                self._inflight = False
                while not self._pending and not self._closed:
                    self._wake.wait()
                if self._closed and not self._pending:
                    return
                batch, self._pending = self._pending, []
                self._inflight = True
            data = b"".join(batch)
            try:
                self._sock.sendall(data)
            except OSError:
                self.close(flush=False)
                return
            self.writes += 1

    def flush(self, timeout_s: float = 5.0) -> None:
        """Best-effort wait for the outbound queue AND any batch the
        flusher already popped to reach ``sendall`` completion (drain
        frames and final responses must leave before teardown)."""
        import time

        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._lock:
                if self._closed or (not self._pending
                                    and not self._inflight):
                    return
            time.sleep(0.002)

    # -- reading -------------------------------------------------------
    def recv(self) -> Optional[dict]:
        meta: dict = {}
        try:
            frame = read_frame(self._rfile, meta=meta)
        except (FrameError, OSError, ValueError):
            return None
        self._note_recv_meta(meta)
        return frame

    # -- teardown ------------------------------------------------------
    def _teardown_locked(self) -> None:
        self._closed = True
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        try:
            self._rfile.close()
        except OSError:
            pass

    def close(self, *, flush: bool = True) -> None:
        if flush and self._pipelined:
            self.flush()
        with self._wake:
            if self._closed:
                return
            self._teardown_locked()
            self._wake.notify_all()

    @property
    def closed(self) -> bool:
        return self._closed


# ----------------------------------------------------------------------
# Chaos layer (round 18): dirty-link fault injection
# ----------------------------------------------------------------------
#: Fault-registry sites (``utils.resilience.FAULTS`` / ``GHS_FAULT_*``)
#: the chaos wrapper consults per frame. All existing drills inject only
#: CLEAN failures (os._exit, socket close); these are the dirty ones real
#: cross-host links produce:
#:
#: * ``fleet.chaos.drop``    — drop the next N outbound frames (kind
#:   ``raise``; ``GHS_FAULT_FLEET_CHAOS_DROP=N``) — a transient blackhole.
#: * ``fleet.chaos.corrupt`` — corrupt the next N outbound frames' bytes
#:   (kind ``torn``; the peer's framing raises ``FrameError`` and drops
#:   the channel — the corrupt-prefix-must-not-size-an-allocation path).
#: * ``fleet.chaos.delay``   — add ``value`` seconds to the next N sends
#:   (kind ``slow``) — a latency spike.
#: * ``fleet.chaos.payload`` — corrupt the next N inbound RESULT payloads
#:   *past framing* (kind ``torn``): the frame decodes cleanly (length ok,
#:   checksum ok — the corruption model is a bad worker/cache, not a bad
#:   wire), but the decoded solve response carries a mutated edge set and
#:   weight. Only the verification layer (``verify/``) can catch this one
#:   — which is exactly what the corruption drill proves it does.
CHAOS_DROP_SITE = "fleet.chaos.drop"
CHAOS_CORRUPT_SITE = "fleet.chaos.corrupt"
CHAOS_DELAY_SITE = "fleet.chaos.delay"
CHAOS_PAYLOAD_SITE = "fleet.chaos.payload"


def corrupt_result_payload(frame: dict) -> dict:
    """Deterministically mutate a decoded solve-response payload the way
    ``fleet.chaos.payload`` models it: the first claimed MST edge becomes
    a self-loop (an edge the input graph cannot contain) and the claimed
    total weight shifts by one — both plausible-looking to every layer
    below verification. Mutates (a copy of) the inner response dict."""
    resp = frame.get("resp")
    target = resp if isinstance(resp, dict) else frame
    target = dict(target)
    if target.get("mst_edges"):
        edges = [list(e) for e in target["mst_edges"]]
        edges[0] = [edges[0][0], edges[0][0]]
        target["mst_edges"] = edges
    if "total_weight" in target:
        target["total_weight"] = target["total_weight"] + 1
    out = dict(frame)
    if isinstance(resp, dict):
        out["resp"] = target
    else:
        out = target
    return out


class ChaosState:
    """One worker's standing fault flags, OWNED BY THE ROUTER and shared
    across that worker's transport incarnations — a partition outlives a
    re-dial (the new connection is just as partitioned), which is what
    makes the partition drill's flap-until-healed behavior honest.

    ``drop_send`` alone is a **one-way partition** (router→worker frames
    vanish; the worker's responses still arrive, so the router sees a
    live-but-unreachable peer until its in-flight work drains and the
    lease expires). ``drop_recv`` too makes it **symmetric**. Latency and
    jitter model a congested link; jitter is deterministic under
    ``seed`` (same drill, same delays)."""

    def __init__(self, *, seed: int = 0, name: str = ""):
        import random

        self.drop_send = False
        self.drop_recv = False
        self.latency_s = 0.0
        self.jitter_s = 0.0
        self._rng = random.Random(f"{seed}:{name}")

    @property
    def partitioned(self) -> bool:
        return self.drop_send or self.drop_recv

    def delay(self) -> float:
        if self.latency_s <= 0 and self.jitter_s <= 0:
            return 0.0
        return self.latency_s + self.jitter_s * self._rng.random()

    def corrupt(self, data: bytes) -> bytes:
        """Deterministically mangle a frame's bytes. The length prefix is
        always hit (digit ^ 0x5A = letter): the peer must refuse the
        header outright — a flip that only grew the declared length would
        instead wedge its reader waiting for bytes that never come, which
        is the lease's job to catch, not framing's — plus seeded interior
        flips so payload-level garbage is exercised too."""
        if not data:
            return data
        buf = bytearray(data)
        buf[0] ^= 0x5A
        if len(buf) > 1:
            # Interior flips start at 1: a flip landing back on byte 0
            # would XOR-revert the mandatory prefix mangle and ship a
            # byte-identical "corrupted" frame.
            for _ in range(max(1, len(buf) // 16)):
                i = self._rng.randrange(1, len(buf))
                buf[i] ^= 0x5A
        return bytes(buf)


class ChaosTransport(Transport):
    """A fault-injectable wrapper around any :class:`Transport`.

    Every outbound frame consults the standing :class:`ChaosState` flags
    plus the ``fleet.chaos.*`` fault-registry sites; inbound frames honor
    the symmetric-partition flag by being read and discarded (from the
    protocol's point of view, identical to the network never delivering
    them). Dropping is *silent* — exactly like a real partition: the
    sender learns nothing until silence expires the lease.
    """

    def __init__(self, inner: Transport, state: ChaosState):
        self._inner = inner
        self.state = state

    @property
    def kind(self) -> str:  # the router keys lease accounting off this
        return self._inner.kind

    @property
    def crc_out(self) -> bool:
        return self._inner.crc_out

    def enable_crc(self) -> None:
        self._inner.enable_crc()

    @property
    def wire_out(self) -> bool:
        return self._inner.wire_out

    def enable_wire(self) -> None:
        self._inner.enable_wire()

    @property
    def writes(self) -> int:
        return self._inner.writes

    @property
    def frames(self) -> int:
        return self._inner.frames

    @property
    def peer(self):
        return getattr(self._inner, "peer", None)

    def send(self, obj: dict) -> None:
        from distributed_ghs_implementation_tpu.utils.resilience import FAULTS

        data = self._inner.encode_for_peer(obj)
        state = self.state
        armed_delay = FAULTS.pop(CHAOS_DELAY_SITE)
        delay = state.delay() + (
            armed_delay.value if armed_delay is not None else 0.0
        )
        if delay > 0:
            import time

            from distributed_ghs_implementation_tpu.obs.events import BUS

            BUS.record("fleet.chaos.delay_s", delay)
            time.sleep(delay)
        # Pop the one-shot drop AND corrupt sites BEFORE the standing-
        # partition return: short-circuiting would leave an armed shot
        # unconsumed behind a partition and fire it on the first
        # post-heal frame instead (a "healed" link that immediately
        # drops or corrupts would read as a failed warm rejoin).
        drop_shot = FAULTS.pop(CHAOS_DROP_SITE)
        corrupt_shot = FAULTS.pop(CHAOS_CORRUPT_SITE)
        if state.drop_send or drop_shot is not None:
            from distributed_ghs_implementation_tpu.obs.events import BUS

            BUS.count("fleet.chaos.dropped")
            return  # a partitioned link swallows the frame silently
        if corrupt_shot is not None:
            from distributed_ghs_implementation_tpu.obs.events import BUS

            BUS.count("fleet.chaos.corrupted")
            data = state.corrupt(data)
        self._inner.send_bytes(data)

    def recv(self) -> Optional[dict]:
        from distributed_ghs_implementation_tpu.utils.resilience import FAULTS

        while True:
            frame = self._inner.recv()
            if frame is None:
                return None
            if not self.state.drop_recv:
                # Payload corruption fires PAST framing, on decoded solve
                # responses that actually carry a result edge set — the
                # shot is consumed only by a corruptible frame, so an
                # armed count maps 1:1 onto corrupted results (exact
                # drill counters). Length and checksum were both valid:
                # nothing below the verification layer can object.
                resp = frame.get("resp") if isinstance(
                    frame.get("resp"), dict
                ) else frame
                if resp.get("mst_edges") and FAULTS.pop(
                    CHAOS_PAYLOAD_SITE
                ) is not None:
                    from distributed_ghs_implementation_tpu.obs.events import (
                        BUS,
                    )

                    BUS.count("fleet.chaos.payload_corrupted")
                    frame = corrupt_result_payload(frame)
                return frame
            from distributed_ghs_implementation_tpu.obs.events import BUS

            BUS.count("fleet.chaos.dropped")  # symmetric partition: eat it

    def flush(self, timeout_s: float = 5.0) -> None:
        inner_flush = getattr(self._inner, "flush", None)
        if inner_flush is not None:
            inner_flush(timeout_s)

    def close(self, *, flush: bool = True) -> None:
        self._inner.close(flush=flush)

    @property
    def closed(self) -> bool:
        return self._inner.closed


# ----------------------------------------------------------------------
# Connection establishment
# ----------------------------------------------------------------------
class WorkerListener:
    """The router's dial-in rendezvous: spawned (or externally started)
    TCP workers connect here and send their hello frame; each validated
    hello is handed to ``on_hello(hello, transport)``. Rejections
    (version mismatch, garbage) close the connection and are reported via
    ``on_reject(reason)`` so the router can surface them instead of
    timing out mutely."""

    def __init__(
        self,
        on_hello: Callable[[dict, SocketTransport], None],
        *,
        host: str = "127.0.0.1",
        on_reject: Optional[Callable[[str], None]] = None,
        pipelined: bool = True,
    ):
        self._on_hello = on_hello
        self._on_reject = on_reject or (lambda reason: None)
        self._pipelined = pipelined
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, 0))
        self._sock.listen(64)
        self.host, self.port = self._sock.getsockname()[:2]
        self._closed = False
        self._thread = threading.Thread(
            target=self._accept_loop, name="fleet-listener", daemon=True
        )
        self._thread.start()

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def _accept_loop(self) -> None:
        while not self._closed:
            try:
                conn, _addr = self._sock.accept()
            except OSError:
                return  # listener closed
            threading.Thread(
                target=self._register, args=(conn,),
                name="fleet-hello", daemon=True,
            ).start()

    def _register(self, conn: socket.socket) -> None:
        conn.settimeout(10.0)  # a dialer that never says hello can't wedge us
        rfile = conn.makefile("rb")
        try:
            hello = read_frame(rfile, max_bytes=_MAX_HELLO_BYTES)
            if hello is None:
                raise HelloError("connection closed before hello")
            hello = check_hello(hello)
        except (HelloError, FrameError, OSError, ValueError) as e:
            self._on_reject(f"{type(e).__name__}: {e}")
            try:
                conn.close()
            except OSError:
                pass
            return
        conn.settimeout(None)
        transport = SocketTransport(conn, pipelined=self._pipelined, rfile=rfile)
        try:
            self._on_hello(hello, transport)
        except Exception as e:  # noqa: BLE001 — a bad hello must not kill accept
            self._on_reject(f"{type(e).__name__}: {e}")
            transport.close()

    def close(self) -> None:
        self._closed = True
        try:
            self._sock.close()
        except OSError:
            pass


def connect_to_worker(
    addr: str, *, timeout_s: float = 10.0, pipelined: bool = True
) -> Tuple[dict, SocketTransport]:
    """Dial an externally started worker listening on ``addr``
    (``fleet.worker --listen``); the worker sends its hello on accept.
    Returns ``(hello, transport)`` or raises ``OSError`` /
    :class:`HelloError`."""
    host, port = parse_hostport(addr)
    sock = socket.create_connection((host, port), timeout=timeout_s)
    sock.settimeout(timeout_s)
    rfile = sock.makefile("rb")
    try:
        hello = read_frame(rfile, max_bytes=_MAX_HELLO_BYTES)
        if hello is None:
            raise HelloError(f"worker at {addr} closed before hello")
        hello = check_hello(hello)
    except (FrameError, HelloError, OSError):
        sock.close()
        raise
    sock.settimeout(None)
    return hello, SocketTransport(sock, pipelined=pipelined, rfile=rfile)
