"""Horizontal serving fleet: digest-routed worker processes with failover.

The single-process serving stack (``serve/`` + ``batch/``) caps out at one
Python process and loses every in-flight query when it crashes. ``fleet/``
lifts it horizontal: N worker processes (``fleet/worker.py``), each a
full :class:`serve.service.MSTService`, behind a consistent-hash router
(``fleet/router.py``) with health-checked failover, re-queue of accepted
requests, restart-with-backoff, admission control, and graceful drain.
Workers speak length-prefixed frames (``fleet/framing.py``) over either
subprocess pipes or TCP sockets (``fleet/transport.py`` — coalesced
pipelined writes, dial-in hello registration, host:port addressing), so
the fleet is no longer bound to one machine; cross-host cache misses
forward to the digest-owner worker before solving locally. The pool is
elastic (``fleet/autoscaler.py``): an obs-driven control loop grows it
with warm-handoff joins and shrinks it with drain-aware retires. And the
router itself is crash-survivable (``fleet/journal.py``): a durable
accepted-work journal gates every accept on an fsynced append, so a
restarted router re-adopts live workers warm and replays orphaned work;
a transport chaos layer (``ChaosTransport``) drills the dirty-link
failures — partitions, latency, frame corruption — clean kills never
exercised. Round 19 adds the trust layer: frames carry crc32 payload
checksums (version-gated via the hello ``crc`` capability), and the
router CERTIFIES cross-host forwarded payloads — and, in
``verify_responses`` mode, every verifiable solve response — against the
``verify/`` MST certificate before serving them (``docs/FLEET.md``,
``docs/VERIFICATION.md``).
"""

from distributed_ghs_implementation_tpu.fleet.autoscaler import (
    Autoscaler,
    ElasticPolicy,
)
from distributed_ghs_implementation_tpu.fleet.hashing import HashRing
from distributed_ghs_implementation_tpu.fleet.journal import (
    RouterJournal,
)
from distributed_ghs_implementation_tpu.fleet.router import (
    FleetConfig,
    FleetRouter,
)
from distributed_ghs_implementation_tpu.fleet.transport import (
    PROTO_VERSION,
    ChaosState,
    ChaosTransport,
    HelloError,
    PipeTransport,
    SocketTransport,
    Transport,
    build_hello,
    check_hello,
)

__all__ = [
    "Autoscaler",
    "ElasticPolicy",
    "FleetConfig",
    "FleetRouter",
    "HashRing",
    "RouterJournal",
    "PROTO_VERSION",
    "ChaosState",
    "ChaosTransport",
    "HelloError",
    "PipeTransport",
    "SocketTransport",
    "Transport",
    "build_hello",
    "check_hello",
]
