"""Thin per-kind solver wrappers over the scheduler's MSF solve.

Every kind here is derived from the same GHS/Borůvka level loop the MST
path runs (``models/boruvka.py`` via the injected ``solve`` callable — in
the service that is ``SolveScheduler.solve``, so analytics traffic rides
single-flight dedup, admission control, the batch engine, the sharded
oversize lane, and supervision for free):

* ``components`` — the *weight-free* instantiation: solve the graph's
  index-weighted twin (rank = edge position; any all-distinct rank yields
  the same connectivity), producing a connectivity forest whose labels are
  the component answer.
* ``k_msf`` — full MSF, then trim to the lightest ``n - max(k, c)`` tree
  edges by solver rank. The ISSUE's suggested early-exit-at-``k``-fragments
  short cut is **unsound** and deliberately not used: with edges
  ``(0,1,w=1) (2,3,w=2) (0,2,w=5) (4,5,w=10)`` on 6 nodes, Borůvka's first
  level adds MOEs ``{1, 2, 10}`` and reaches exactly 3 fragments with total
  13, while the optimal 3-forest drops the heaviest MST edge (``w=10``)
  from the 4-edge MSF for total 8. Cut-property trimming is exact (the
  k-forest matroid optimum is the lightest ``n - k'`` MST edges); early
  exit commits to whole levels and cannot shed the heavy MOE a later level
  would have made droppable.
* ``bottleneck`` — the max-tree-edge reduction over the MSF (the minimum
  bottleneck spanning value; unique across MSTs since all MSTs share one
  sorted weight sequence).
* ``path_max`` — :func:`serve.dynamic.tree_path_max` over the MSF's tree
  arrays: the minimax (bottleneck-optimal) edge between two nodes.

``solve`` contract: ``solve(graph) -> (MSTResult, source_str)``.
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import numpy as np

from distributed_ghs_implementation_tpu.api import MSTResult
from distributed_ghs_implementation_tpu.graphs.edgelist import (
    Graph,
    component_labels,
)

SolveFn = Callable[[Graph], Tuple[MSTResult, str]]


# -- shared plumbing ---------------------------------------------------------

def connectivity_graph(graph: Graph) -> Graph:
    """The index-weighted twin used by the ``components`` kind.

    Built with the **direct** :class:`Graph` constructor, not
    ``from_arrays``: ``graph`` is already canonical (sorted, deduped,
    ``u < v``), so reusing its endpoint arrays guarantees 1:1 edge-id
    alignment — edge ``i`` of the twin IS edge ``i`` of the original, and
    the twin's MSF edge ids can be read back against the original graph.
    ``from_arrays`` would re-canonicalize and could in principle re-dedup,
    breaking that alignment.
    """
    m = graph.num_edges
    return Graph(
        graph.num_nodes,
        graph.u,
        graph.v,
        np.arange(m, dtype=np.int32),
    )


def edge_ranks(graph: Graph) -> np.ndarray:
    """Each edge's position in the solver's ``(w, edge id)`` total order."""
    order = np.argsort(graph.w, kind="stable")
    ranks = np.empty(graph.num_edges, dtype=np.int64)
    ranks[order] = np.arange(graph.num_edges)
    return ranks


def labels_for_forest(result: MSTResult) -> np.ndarray:
    """Component labels (``0..k-1``, scipy ordering) implied by a forest
    result — exact for any maximal spanning forest, MSF included."""
    g = result.graph
    ids = result.edge_ids
    return component_labels(g.num_nodes, g.u[ids], g.v[ids])


def partition_from_labels(labels) -> frozenset:
    """Label array → canonical partition (frozenset of node frozensets),
    the representation both oracle and served labels are compared in —
    label *values* are arbitrary, the grouping is the answer."""
    groups: dict = {}
    for node, lab in enumerate(np.asarray(labels).tolist()):
        groups.setdefault(lab, []).append(node)
    return frozenset(frozenset(g) for g in groups.values())


# -- per-kind solvers --------------------------------------------------------

def solve_components(
    graph: Graph, solve: SolveFn
) -> Tuple[MSTResult, str]:
    """Connectivity forest of ``graph`` via the weight-free level loop.

    Returns an :class:`MSTResult` whose ``graph`` is the **original** graph
    (so store digest validation and disk round trips under the kind key
    work unchanged) and whose ``edge_ids`` form a maximal spanning forest —
    a complete connectivity certificate. Labels are derived on demand by
    :func:`labels_for_forest`.
    """
    inner, source = solve(connectivity_graph(graph))
    return (
        MSTResult(
            graph=graph,
            edge_ids=np.asarray(inner.edge_ids).copy(),
            num_levels=inner.num_levels,
            wall_time_s=inner.wall_time_s,
            backend=inner.backend,
            num_components=inner.num_components,
        ),
        source,
    )


def trim_to_k_forest(result: MSTResult, k: int) -> MSTResult:
    """The optimal ``k``-forest derived from a full MSF result: keep the
    lightest ``n - k'`` tree edges by solver rank, ``k' = min(n, max(k,
    c))`` (``c`` = the graph's component count — fewer than ``c`` parts is
    infeasible, hence the *relaxed* spanning predicate)."""
    g = result.graph
    n = g.num_nodes
    k_eff = min(n, max(int(k), int(result.num_components)))
    keep = max(0, n - k_eff)
    ids = np.asarray(result.edge_ids)
    ranks = edge_ranks(g)[ids]
    trimmed = ids[np.argsort(ranks, kind="stable")][:keep]
    return MSTResult(
        graph=g,
        edge_ids=np.sort(trimmed),
        num_levels=result.num_levels,
        wall_time_s=result.wall_time_s,
        backend=result.backend,
        num_components=k_eff,
    )


def solve_k_msf(
    graph: Graph, solve: SolveFn, k: int
) -> Tuple[MSTResult, str, MSTResult]:
    """Optimal ``k``-forest: full MSF (shared with the ``mst`` cache entry),
    then :func:`trim_to_k_forest`. Returns ``(trimmed, source, full_msf)``
    — the caller caches the trimmed answer under the kind key and may park
    the full MSF as the digest's session seed."""
    inner, source = solve(graph)
    return trim_to_k_forest(inner, k), source, inner


def bottleneck_of(result: MSTResult) -> Optional[tuple]:
    """The max tree edge by the solver's ``(w, u, v)`` order: ``(weight, u,
    v)``, or ``None`` for an edgeless forest. Its weight is the minimum
    bottleneck spanning value of the graph."""
    ids = np.asarray(result.edge_ids)
    if ids.size == 0:
        return None
    g = result.graph
    u, v, w = g.u[ids], g.v[ids], g.w[ids]
    top = int(np.lexsort((v, u, w))[-1])
    cast = int if g.is_integer_weighted else float
    return (cast(w[top]), int(u[top]), int(v[top]))


def solve_bottleneck(
    graph: Graph, solve: SolveFn
) -> Tuple[MSTResult, str, Optional[tuple]]:
    """Minimum bottleneck spanning value: the MSF plus its max-tree-edge
    reduction. Returns ``(mst_result, source, (weight, u, v) | None)``."""
    inner, source = solve(graph)
    return inner, source, bottleneck_of(inner)


def path_max_of(result: MSTResult, u: int, v: int) -> dict:
    """Minimax edge between ``u`` and ``v`` over the forest: ``{"connected",
    "weight", "edge"}``. ``u == v`` is trivially connected with no edge;
    different fragments report ``connected: False``."""
    from distributed_ghs_implementation_tpu.serve.dynamic import tree_path_max

    g = result.graph
    n = g.num_nodes
    u, v = int(u), int(v)
    if not (0 <= u < n and 0 <= v < n):
        raise ValueError(f"path_max endpoints out of range: ({u}, {v}), n={n}")
    if u == v:
        return {"connected": True, "weight": None, "edge": None}
    ids = np.asarray(result.edge_ids)
    rel = tree_path_max(n, g.u[ids], g.v[ids], g.w[ids], u, v)
    if rel is None:
        return {"connected": False, "weight": None, "edge": None}
    idx = int(ids[rel])
    cast = int if g.is_integer_weighted else float
    return {
        "connected": True,
        "weight": cast(g.w[idx]),
        "edge": (int(g.u[idx]), int(g.v[idx])),
    }


def solve_path_max(
    graph: Graph, solve: SolveFn, u: int, v: int
) -> Tuple[MSTResult, str, dict]:
    """Minimax path query: MSF (cache-shared with ``mst``) +
    :func:`path_max_of`. Returns ``(mst_result, source, answer_dict)``."""
    inner, source = solve(graph)
    return inner, source, path_max_of(inner, u, v)


# -- NetworkX oracles --------------------------------------------------------
#
# The exactness contracts gate-analytics-v1 compares against. Each oracle
# answers in a tie-independent representation: partitions for components,
# total weight for k-MSF (the sorted MSF weight multiset is unique across
# tie-breaks), the bottleneck scalar, and the minimax path value.

def oracle_components(graph: Graph) -> frozenset:
    """Canonical partition via ``networkx.connected_components``."""
    import networkx as nx

    comps = [frozenset(c) for c in nx.connected_components(graph.to_networkx())]
    return frozenset(comps)


def oracle_k_msf_weight(graph: Graph, k: int):
    """Total weight of the optimal ``k``-forest: lightest ``n - max(k, c)``
    MSF edges by weight (tie-independent — all MSFs share one sorted
    weight sequence)."""
    import networkx as nx

    g = graph.to_networkx()
    msf = nx.minimum_spanning_tree(g)  # spanning forest when disconnected
    weights = sorted(d["weight"] for _, _, d in msf.edges(data=True))
    n = graph.num_nodes
    c = n - len(weights)
    keep = max(0, n - min(n, max(int(k), c)))
    total = sum(weights[:keep])
    return int(total) if graph.is_integer_weighted else float(total)


def oracle_bottleneck(graph: Graph):
    """Max edge weight of the NetworkX MSF (``None`` when edgeless)."""
    import networkx as nx

    msf = nx.minimum_spanning_tree(graph.to_networkx())
    weights = [d["weight"] for _, _, d in msf.edges(data=True)]
    if not weights:
        return None
    top = max(weights)
    return int(top) if graph.is_integer_weighted else float(top)


def oracle_path_max(graph: Graph, u: int, v: int) -> dict:
    """Minimax path value between ``u`` and ``v``: max edge weight on the
    NetworkX-MSF path (the optimum over all graph paths, and unique)."""
    import networkx as nx

    u, v = int(u), int(v)
    if u == v:
        return {"connected": True, "weight": None}
    msf = nx.minimum_spanning_tree(graph.to_networkx())
    if u not in msf or v not in msf or not nx.has_path(msf, u, v):
        return {"connected": False, "weight": None}
    path = nx.shortest_path(msf, u, v)
    top = max(
        msf[a][b]["weight"] for a, b in zip(path[:-1], path[1:])
    )
    return {
        "connected": True,
        "weight": int(top) if graph.is_integer_weighted else float(top),
    }
