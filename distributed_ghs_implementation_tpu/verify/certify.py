"""MST certificates: prove a claimed forest IS the minimum spanning forest.

A certificate check costs O(m α + m log n) — union-find over the claimed
tree edges plus one batch of tree path-max queries — against the O(m log n)
*per level* of re-solving, and runs through an entirely independent code
path: no Borůvka kernel, no Pallas, no fragment arrays. That independence
is the point. The solver stack routes through fused kernels, donated
device buffers, disk caches, WAL replay, and cross-host forwarding; any of
those can hand back a *plausible* wrong answer (the reference
implementation served weight-57 "MSTs" whose true weight was 53 and never
noticed). The certificate re-derives correctness from first principles:

1. **Forest validity** — the claimed edge ids are in range and distinct,
   and union-find over them finds no cycle (``bad_edge_ids`` / ``cycle``).
2. **Spanning parity** — the claimed forest has exactly as many components
   as the input graph: dropping a component (or splitting one) is caught
   by comparing component counts (``not_spanning``).
3. **Cycle property** — every non-tree edge is heavier than every tree
   edge on the path between its endpoints (``not_minimal``). Weights are
   compared as *ranks* in the total order ``(weight, edge id)`` — the same
   tie-breaking contract the whole repo solves under — so the MSF is
   unique and conditions 1–3 are necessary AND sufficient: a passing
   certificate means the claimed forest is edge-for-edge THE minimum
   spanning forest, not merely one of equal weight.

The path-max queries use binary lifting over the rooted claimed forest
(ancestor tables ``up[k][v]`` and max-edge-rank tables ``mx[k][v]``,
``k ≤ log2(depth)``), answered for all non-tree edges at once. Two
engines share the host-built tables:

* ``engine="np"`` — pure NumPy, importable without jax (the fleet router
  certifies forwarded payloads with this one).
* ``engine="xla"`` — the query loop under ``jax.jit``, deliberately plain
  XLA (never Pallas), so a Pallas-routed solve is cross-checked by a code
  path that shares nothing with the kernel under suspicion.

``engine="auto"`` picks XLA when jax is importable, NumPy otherwise. Both
engines are bit-identical (tests pin it).
"""

from __future__ import annotations

import dataclasses
import time
from typing import List, Optional, Sequence, Tuple

import numpy as np

from distributed_ghs_implementation_tpu.graphs.edgelist import Graph
from distributed_ghs_implementation_tpu.obs.events import BUS

#: Failure reasons, in check order. ``None`` reason == certificate passed.
REASONS = (
    "bad_edge_ids",   # out of range / duplicate claimed edge ids
    "cycle",          # claimed edges close a cycle (not a forest)
    "not_spanning",   # component count differs from the input graph
    "cross_edge",     # components claim: a graph edge crosses two claimed
                      # components (the forest is not maximal)
    "not_minimal",    # a non-tree edge beats a tree edge on its path
    "unknown_edge",   # a claimed (u, v) pair is not an input edge
    "weight_mismatch",  # claimed total weight != recomputed edge sum
    "metadata_mismatch",  # claimed component count != certified count
    "malformed_claim",  # the claim could not even be parsed as edges
)


@dataclasses.dataclass
class Certificate:
    """One verification verdict. ``bool(cert)`` is ``cert.ok``."""

    ok: bool
    reason: Optional[str]  # one of REASONS, None when ok
    detail: str = ""
    num_tree_edges: int = 0
    expected_edges: int = 0
    num_components: int = 0       # of the certified forest
    graph_components: int = 0     # of the input graph
    violations: int = 0           # offending non-tree edges (not_minimal)
    engine: str = "np"
    check_s: float = 0.0

    def __bool__(self) -> bool:
        return self.ok

    def summary(self) -> dict:
        out = {"ok": self.ok, "engine": self.engine,
               "check_s": round(self.check_s, 6)}
        if not self.ok:
            out["reason"] = self.reason
            out["detail"] = self.detail
        return out


def _fail(reason: str, detail: str, **fields) -> Certificate:
    return Certificate(ok=False, reason=reason, detail=detail, **fields)


def _edge_ranks(graph: Graph) -> np.ndarray:
    """Rank of each edge in the total order ``(weight, edge id)`` —
    re-derived here with a plain stable argsort (never the graph's cached
    native-sorted order: the certificate must not trust inputs it can
    cheaply recompute)."""
    order = np.argsort(graph.w, kind="stable")
    rank = np.empty(graph.num_edges, dtype=np.int64)
    rank[order] = np.arange(graph.num_edges, dtype=np.int64)
    return rank


def _components(num_nodes: int, u: np.ndarray, v: np.ndarray) -> np.ndarray:
    """Component label per vertex (C-speed scipy union-find equivalent)."""
    from distributed_ghs_implementation_tpu.graphs.edgelist import (
        component_labels,
    )

    return component_labels(num_nodes, u, v)


def _root_forest(
    num_nodes: int, tu: np.ndarray, tv: np.ndarray, tranks: np.ndarray
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Root the claimed forest: ``(parent, depth, parent_edge_rank)``.

    BFS with predecessors via scipy (C speed, depth-independent — a road
    network MST is a few vertices wide and tens of thousands deep, where a
    level-synchronous NumPy BFS would crawl). Roots carry ``parent ==
    self`` and ``parent_edge_rank == -1`` (the neutral element under max).
    """
    from scipy.sparse import coo_matrix
    from scipy.sparse.csgraph import breadth_first_order

    parent = np.arange(num_nodes, dtype=np.int64)
    depth = np.zeros(num_nodes, dtype=np.int64)
    perank = np.full(num_nodes, -1, dtype=np.int64)
    if tu.size == 0:
        return parent, depth, perank
    adj = coo_matrix(
        (np.ones(2 * tu.size, dtype=np.int8),
         (np.concatenate([tu, tv]), np.concatenate([tv, tu]))),
        shape=(num_nodes, num_nodes),
    ).tocsr()
    labels = _components(num_nodes, tu, tv)
    # One BFS per NON-TRIVIAL tree component, from its first vertex.
    # Singleton components (isolated vertices — RMAT graphs have tens of
    # thousands) are already correct as self-parented roots; a scipy BFS
    # call per singleton turned an RMAT-17 certificate into minutes.
    uniq, first = np.unique(labels, return_index=True)
    sizes = np.bincount(labels, minlength=uniq.max() + 1 if uniq.size else 0)
    first = first[sizes[uniq] >= 2]
    seen = np.zeros(num_nodes, dtype=bool)
    for root in first:
        if seen[root]:
            continue
        order, pred = breadth_first_order(
            adj, int(root), directed=False, return_predecessors=True
        )
        seen[order] = True
        pred = pred[order]
        has_parent = order != root
        kids = order[has_parent]
        parent[kids] = pred[has_parent]
    # Parent-edge ranks by packed-key binary search over both orientations
    # of the tree edges (child-side key -> the connecting edge's rank).
    src = np.concatenate([tu, tv]).astype(np.int64)
    dst = np.concatenate([tv, tu]).astype(np.int64)
    ranks2 = np.concatenate([tranks, tranks]).astype(np.int64)
    key = src * num_nodes + dst
    korder = np.argsort(key)
    key, ranks2 = key[korder], ranks2[korder]
    child = np.nonzero(parent != np.arange(num_nodes, dtype=np.int64))[0]
    want = parent[child] * num_nodes + child
    perank[child] = ranks2[np.searchsorted(key, want)]
    # Depth by pointer doubling: after k rounds, cnt(v) = min(depth(v),
    # 2^k) — converges in log2(max depth) vectorized passes, so a
    # 10^5-deep road-network MST costs ~17 array ops, not 10^5.
    idx = np.arange(num_nodes, dtype=np.int64)
    anc = parent.copy()
    depth = (anc != idx).astype(np.int64)
    while True:
        nxt = anc[anc]
        if np.array_equal(nxt, anc):
            break
        depth = depth + depth[anc]
        anc = nxt
    return parent, depth, perank


def _lift_tables(
    parent: np.ndarray, perank: np.ndarray, depth: np.ndarray
) -> Tuple[np.ndarray, np.ndarray]:
    """Binary-lifting tables ``(up[K, n], mx[K, n])``: ``up[k][v]`` is
    ``v``'s ``2^k``-th ancestor, ``mx[k][v]`` the max tree-edge rank on
    that ancestor path (-1 past the root)."""
    n = parent.shape[0]
    levels = max(1, int(depth.max()).bit_length()) if n else 1
    # Rounded up so the XLA engine sees fewer distinct table shapes (the
    # recurrence is closed past the root: up saturates at the root,
    # mx at -1 — extra levels are semantically inert).
    levels = -(-levels // 4) * 4
    up = np.empty((levels, n), dtype=np.int64)
    mx = np.empty((levels, n), dtype=np.int64)
    up[0] = parent
    mx[0] = perank
    for k in range(1, levels):
        up[k] = up[k - 1][up[k - 1]]
        mx[k] = np.maximum(mx[k - 1], mx[k - 1][up[k - 1]])
    return up, mx


def _path_max_np(
    up: np.ndarray, mx: np.ndarray, depth: np.ndarray,
    a: np.ndarray, b: np.ndarray,
) -> np.ndarray:
    """Max tree-edge rank on the tree path ``a[i] .. b[i]``, vectorized
    over all queries at once (the NumPy engine)."""
    K = up.shape[0]
    a = a.copy()
    b = b.copy()
    best = np.full(a.shape[0], -1, dtype=np.int64)
    # Lift the deeper endpoint up to the shallower one's depth.
    diff = depth[a] - depth[b]
    swap = diff < 0
    a[swap], b[swap] = b[swap], a[swap]
    diff = np.abs(diff)
    for k in range(K):
        take = (diff >> k) & 1 == 1
        best[take] = np.maximum(best[take], mx[k][a[take]])
        a[take] = up[k][a[take]]
    # Lift both while their 2^k ancestors differ; afterwards both sit one
    # step below the LCA.
    meet = a == b
    for k in range(K - 1, -1, -1):
        split = ~meet & (up[k][a] != up[k][b])
        best[split] = np.maximum(
            best[split], np.maximum(mx[k][a[split]], mx[k][b[split]])
        )
        a[split] = up[k][a[split]]
        b[split] = up[k][b[split]]
    final = ~meet
    best[final] = np.maximum(
        best[final], np.maximum(mx[0][a[final]], mx[0][b[final]])
    )
    return best


#: The jitted XLA query, built once (lazily — this module must import
#: without jax). A per-call ``@jax.jit`` would defeat jax's compile cache
#: entirely: the cache keys on the wrapped FUNCTION OBJECT plus shapes.
_XLA_QUERY = None


def _get_xla_query():
    global _XLA_QUERY
    if _XLA_QUERY is not None:
        return _XLA_QUERY
    import jax
    import jax.numpy as jnp

    @jax.jit
    def query(up_j, mx_j, depth_j, aq, bq):
        K = up_j.shape[0]
        da, db = depth_j[aq], depth_j[bq]
        swap = da - db < 0
        aq, bq = jnp.where(swap, bq, aq), jnp.where(swap, aq, bq)
        diff = jnp.abs(da - db)
        best = jnp.full(aq.shape, -1, dtype=jnp.int32)

        def lift(k, carry):
            aq, best = carry
            take = (diff >> k) & 1 == 1
            best = jnp.where(take, jnp.maximum(best, mx_j[k][aq]), best)
            aq = jnp.where(take, up_j[k][aq], aq)
            return aq, best

        aq, best = jax.lax.fori_loop(0, K, lift, (aq, best))
        meet = aq == bq

        def descend(i, carry):
            aq, bq, best = carry
            k = K - 1 - i
            split = ~meet & (up_j[k][aq] != up_j[k][bq])
            cand = jnp.maximum(mx_j[k][aq], mx_j[k][bq])
            best = jnp.where(split, jnp.maximum(best, cand), best)
            aq = jnp.where(split, up_j[k][aq], aq)
            bq = jnp.where(split, up_j[k][bq], bq)
            return aq, bq, best

        aq, bq, best = jax.lax.fori_loop(0, K, descend, (aq, bq, best))
        last = jnp.maximum(mx_j[0][aq], mx_j[0][bq])
        return jnp.where(meet, best, jnp.maximum(best, last))

    _XLA_QUERY = query
    return query


def _path_max_xla(
    up: np.ndarray, mx: np.ndarray, depth: np.ndarray,
    a: np.ndarray, b: np.ndarray,
) -> np.ndarray:
    """The same query batch under ``jax.jit`` — plain XLA ops only (no
    Pallas anywhere on this path), padded to a power of two so repeat
    certifications of same-scale graphs reuse the compiled executable
    (one compile per distinct ``(levels, n, padded queries)`` shape)."""
    # int32 everywhere: vertex ids and edge ranks both fit (the certify
    # entry points bound m below 2^31), and x64-disabled jax would
    # silently truncate int64 anyway — better to cast deliberately.
    up = up.astype(np.int32)
    mx = mx.astype(np.int32)
    depth = depth.astype(np.int32)
    nq = a.shape[0]
    pad = 1 << max(0, int(nq - 1).bit_length())
    a_p = np.zeros(pad, dtype=np.int32)
    b_p = np.zeros(pad, dtype=np.int32)
    a_p[:nq] = a
    b_p[:nq] = b  # pads query (0, 0): path max -1, inert
    out = np.asarray(_get_xla_query()(up, mx, depth, a_p, b_p))
    return out[:nq]


def _resolve_engine(engine: str) -> str:
    if engine == "auto":
        try:
            import jax  # noqa: F401

            return "xla"
        except Exception:  # noqa: BLE001 — no jax: numpy engine
            return "np"
    if engine not in ("np", "xla"):
        raise ValueError(f"unknown certificate engine {engine!r}")
    return engine


def certify_edge_ids(
    graph: Graph,
    edge_ids: np.ndarray,
    *,
    engine: str = "auto",
    expect_components: Optional[int] = None,
) -> Certificate:
    """Certify that ``edge_ids`` (indices into ``graph.u/v/w``) are THE
    minimum spanning forest of ``graph``. See the module docstring for
    what a passing certificate proves."""
    t0 = time.perf_counter()
    engine = _resolve_engine(engine)
    n, m = graph.num_nodes, graph.num_edges
    if engine == "xla" and max(n, m) >= 2**31:
        engine = "np"  # the XLA engine is int32; host ints are unbounded

    def done(cert: Certificate) -> Certificate:
        cert.engine = engine
        cert.check_s = time.perf_counter() - t0
        BUS.count("verify.checks")
        BUS.record("verify.check_s", cert.check_s)
        return cert

    ids = np.asarray(edge_ids, dtype=np.int64).ravel()
    if ids.size and (ids.min() < 0 or ids.max() >= m):
        return done(_fail(
            "bad_edge_ids",
            f"edge id out of range [0, {m}): "
            f"[{ids.min()}, {ids.max()}]",
        ))
    if np.unique(ids).size != ids.size:
        return done(_fail(
            "bad_edge_ids",
            f"{ids.size - np.unique(ids).size} duplicate edge ids claimed",
        ))

    tu, tv = graph.u[ids], graph.v[ids]
    tree_labels = _components(n, tu, tv)
    c_tree = int(np.unique(tree_labels).size) if n else 0
    if ids.size != n - c_tree:
        # More claimed edges than a forest on these components can hold ==
        # at least one cycle (self-loops/duplicates were already rejected).
        return done(_fail(
            "cycle",
            f"{ids.size} claimed edges over {c_tree} components "
            f"(a forest has exactly {n - c_tree})",
            num_tree_edges=int(ids.size), num_components=c_tree,
        ))
    c_graph = (
        int(np.unique(_components(n, graph.u, graph.v)).size) if n else 0
    )
    if c_tree != c_graph:
        return done(_fail(
            "not_spanning",
            f"claimed forest has {c_tree} components, the input graph "
            f"has {c_graph} — a component was dropped or split",
            num_tree_edges=int(ids.size),
            num_components=c_tree, graph_components=c_graph,
            expected_edges=n - c_graph,
        ))
    if expect_components is not None and int(expect_components) != c_graph:
        return done(_fail(
            "metadata_mismatch",
            f"result metadata claims {expect_components} components, "
            f"certificate finds {c_graph}",
            num_tree_edges=int(ids.size),
            num_components=c_tree, graph_components=c_graph,
        ))

    # Cycle property over ranks: every non-tree edge must out-rank every
    # tree edge on the path between its endpoints.
    if m and ids.size:
        rank = _edge_ranks(graph)
        in_tree = np.zeros(m, dtype=bool)
        in_tree[ids] = True
        parent, depth, perank = _root_forest(n, tu, tv, rank[ids])
        up, mx = _lift_tables(parent, perank, depth)
        nt = np.nonzero(~in_tree)[0]
        if nt.size:
            path_max = (_path_max_xla if engine == "xla" else _path_max_np)(
                up, mx, depth, graph.u[nt], graph.v[nt]
            )
            bad = rank[nt] < path_max
            if bad.any():
                worst = nt[bad][:4]
                return done(_fail(
                    "not_minimal",
                    f"{int(bad.sum())} non-tree edges are lighter than a "
                    f"tree edge on their path (e.g. edge ids "
                    f"{worst.tolist()})",
                    num_tree_edges=int(ids.size),
                    num_components=c_tree, graph_components=c_graph,
                    expected_edges=n - c_graph,
                    violations=int(bad.sum()),
                ))
    return done(Certificate(
        ok=True, reason=None,
        num_tree_edges=int(ids.size), expected_edges=n - c_graph,
        num_components=c_tree, graph_components=c_graph,
    ))


def certify_result(result, *, engine: str = "auto") -> Certificate:
    """Certify an :class:`api.MSTResult` — the serve-side entry point.

    Checks the result's ``num_components`` metadata against the certified
    count too: a deserialized cache entry can corrupt metadata and arrays
    independently."""
    return certify_edge_ids(
        result.graph,
        result.edge_ids,
        engine=engine,
        expect_components=result.num_components,
    )


# -- analytics kind adapters -------------------------------------------------
#
# Per-kind certificates for the analytics front door (``analytics/``). Each
# certifies a *served answer*, not a recompute: the components adapter
# proves partition exactness from forest validity + the cross-edge check,
# and the k-forest adapter reduces to the rank-order MSF certificate on the
# rank-prefix subgraph (the "relaxed spanning predicate").


def certify_components(
    graph: Graph,
    edge_ids: np.ndarray,
    *,
    engine: str = "auto",
    expect_components: Optional[int] = None,
) -> Certificate:
    """Certify a connectivity answer: ``edge_ids`` must be a *maximal*
    spanning forest of ``graph``.

    Two checks, jointly exact: (1) the claimed edges form a forest
    (``bad_edge_ids`` / ``cycle``), so the claimed partition can only
    *refine* the graph's true partition (tree edges are graph edges); and
    (2) no graph edge crosses two claimed components (``cross_edge``), so
    the true partition also refines the claimed one. Refinement both ways
    is equality — a passing certificate proves the served labels are THE
    component partition, with no oracle in the loop.
    """
    t0 = time.perf_counter()
    engine = _resolve_engine(engine)
    n, m = graph.num_nodes, graph.num_edges

    def done(cert: Certificate) -> Certificate:
        cert.engine = engine
        cert.check_s = time.perf_counter() - t0
        BUS.count("verify.checks")
        BUS.record("verify.check_s", cert.check_s)
        return cert

    ids = np.asarray(edge_ids, dtype=np.int64).ravel()
    if ids.size and (ids.min() < 0 or ids.max() >= m):
        return done(_fail(
            "bad_edge_ids",
            f"edge id out of range [0, {m}): [{ids.min()}, {ids.max()}]",
        ))
    if np.unique(ids).size != ids.size:
        return done(_fail(
            "bad_edge_ids",
            f"{ids.size - np.unique(ids).size} duplicate edge ids claimed",
        ))
    tu, tv = graph.u[ids], graph.v[ids]
    tree_labels = _components(n, tu, tv)
    c_tree = int(np.unique(tree_labels).size) if n else 0
    if ids.size != n - c_tree:
        return done(_fail(
            "cycle",
            f"{ids.size} claimed edges over {c_tree} components "
            f"(a forest has exactly {n - c_tree})",
            num_tree_edges=int(ids.size), num_components=c_tree,
        ))
    if m:
        cross = tree_labels[graph.u] != tree_labels[graph.v]
        if cross.any():
            worst = np.nonzero(cross)[0][:4]
            return done(_fail(
                "cross_edge",
                f"{int(cross.sum())} graph edges cross claimed components "
                f"(e.g. edge ids {worst.tolist()}) — forest not maximal",
                num_tree_edges=int(ids.size), num_components=c_tree,
                violations=int(cross.sum()),
            ))
    if expect_components is not None and int(expect_components) != c_tree:
        return done(_fail(
            "metadata_mismatch",
            f"result metadata claims {expect_components} components, "
            f"certificate finds {c_tree}",
            num_tree_edges=int(ids.size),
            num_components=c_tree, graph_components=c_tree,
        ))
    return done(Certificate(
        ok=True, reason=None,
        num_tree_edges=int(ids.size), expected_edges=n - c_tree,
        num_components=c_tree, graph_components=c_tree,
    ))


def certify_k_forest(
    graph: Graph,
    edge_ids: np.ndarray,
    k: int,
    *,
    engine: str = "auto",
) -> Certificate:
    """Certify an optimal-``k``-forest answer (the ``k_msf`` kind).

    The target size is ``n - k'`` with ``k' = min(n, max(k, c_graph))`` —
    the *relaxed spanning predicate* (fewer than ``c_graph`` parts is
    infeasible, more than ``n`` is meaningless). Optimality reduces to the
    rank-order MSF certificate on a subgraph: with ``r* = max`` solver
    rank over the claimed edges, the claim is the optimal ``k'``-forest
    iff it is THE MSF of the rank-prefix subgraph ``{edges with rank <=
    r*}`` and has exactly ``n - k'`` edges (Kruskal's partial forest after
    processing rank ``r*`` is precisely the prefix subgraph's MSF). The
    heavy lifting is the existing :func:`certify_edge_ids` cycle
    certificate, run on that subgraph.
    """
    t0 = time.perf_counter()
    engine = _resolve_engine(engine)
    n, m = graph.num_nodes, graph.num_edges

    def done(cert: Certificate) -> Certificate:
        cert.engine = engine
        cert.check_s = time.perf_counter() - t0
        return cert

    ids = np.asarray(edge_ids, dtype=np.int64).ravel()
    if ids.size and (ids.min() < 0 or ids.max() >= m):
        BUS.count("verify.checks")
        return done(_fail(
            "bad_edge_ids",
            f"edge id out of range [0, {m}): [{ids.min()}, {ids.max()}]",
        ))
    if np.unique(ids).size != ids.size:
        BUS.count("verify.checks")
        return done(_fail(
            "bad_edge_ids",
            f"{ids.size - np.unique(ids).size} duplicate edge ids claimed",
        ))
    c_graph = (
        int(np.unique(_components(n, graph.u, graph.v)).size) if n else 0
    )
    k_eff = min(n, max(int(k), c_graph))
    want = n - k_eff
    if ids.size != want:
        BUS.count("verify.checks")
        return done(_fail(
            "not_spanning",
            f"k-forest claim has {ids.size} edges; k={k} over a "
            f"{c_graph}-component graph requires exactly {want} "
            f"(relaxed k' = {k_eff})",
            num_tree_edges=int(ids.size), expected_edges=want,
            graph_components=c_graph,
        ))
    if want == 0:
        BUS.count("verify.checks")
        return done(Certificate(
            ok=True, reason=None, num_tree_edges=0, expected_edges=0,
            num_components=k_eff, graph_components=c_graph,
        ))
    rank = _edge_ranks(graph)
    r_star = int(rank[ids].max())
    mask = rank <= r_star
    # Direct constructor: the masked arrays keep the canonical sorted
    # order, and positions in the subgraph map back via cumsum.
    sub = Graph(n, graph.u[mask], graph.v[mask], graph.w[mask])
    sub_pos = np.cumsum(mask) - 1
    inner = certify_edge_ids(sub, sub_pos[ids], engine=engine)
    if not inner.ok:
        inner.detail = (
            f"[k_msf prefix subgraph, rank <= {r_star}] " + inner.detail
        )
        return done(inner)
    return done(Certificate(
        ok=True, reason=None,
        num_tree_edges=int(ids.size), expected_edges=want,
        num_components=k_eff, graph_components=c_graph,
    ))


def certify_bottleneck(
    graph: Graph,
    edge_ids: np.ndarray,
    *,
    bottleneck_weight=None,
    engine: str = "auto",
    expect_components: Optional[int] = None,
    atol: float = 1e-6,
) -> Certificate:
    """Certify a bottleneck answer: the full MSF certificate plus the
    claimed scalar against the recomputed max-tree-edge weight (the MSF's
    max edge weight is the graph's minimum bottleneck spanning value, and
    identical across all MSTs)."""
    cert = certify_edge_ids(
        graph, edge_ids, engine=engine, expect_components=expect_components,
    )
    if not cert.ok:
        return cert
    ids = np.asarray(edge_ids, dtype=np.int64).ravel()
    actual = float(graph.w[ids].max()) if ids.size else None
    if bottleneck_weight is not None and (
        actual is None or abs(actual - float(bottleneck_weight)) > atol
    ):
        return _fail(
            "weight_mismatch",
            f"claimed bottleneck weight {bottleneck_weight} != recomputed "
            f"{actual}",
            num_tree_edges=cert.num_tree_edges,
            num_components=cert.num_components,
            graph_components=cert.graph_components,
            engine=cert.engine,
        )
    return cert


def certify_claim(
    num_nodes: int,
    edges: Sequence,
    mst_edges: Sequence,
    *,
    total_weight=None,
    engine: str = "np",
    atol: float = 1e-6,
    kind: str = "mst",
    k: Optional[int] = None,
    num_components: Optional[int] = None,
    bottleneck_weight=None,
) -> Certificate:
    """Certify a *payload-shaped* claim: the request's raw edge list plus
    a response's ``mst_edges`` pairs (and optional claimed total weight).

    This is the fleet router's form — it holds the original request (the
    graph) and a forwarded response (the claim) as plain JSON, never as
    repo objects, and must verify WITHOUT jax on its import path (the
    default engine here is ``"np"``). A claimed pair that is not an input
    edge fails ``unknown_edge``; a claimed weight that disagrees with the
    recomputed edge sum fails ``weight_mismatch`` even when the edge set
    itself is plausible (the corruption a bit-flipped weight field is).

    ``kind`` selects the analytics adapter for forwarded non-MST answers:
    ``components`` (against the claimed ``num_components``), ``k_msf``
    (requires ``k``), ``bottleneck`` (against the claimed
    ``bottleneck_weight``); the default certifies an MST claim. All kinds
    share the edge-mapping and total-weight checks above.
    """
    t0 = time.perf_counter()

    def done(cert: Certificate) -> Certificate:
        cert.check_s = time.perf_counter() - t0
        return cert

    try:
        graph = Graph.from_edges(int(num_nodes), edges)
        pairs = np.asarray(list(mst_edges), dtype=np.int64).reshape(-1, 2)
    except Exception as e:  # noqa: BLE001 — adversarial input IS the job
        # A ragged/non-numeric claim (a buggy, older-build, or lying
        # peer) must FAIL its certificate, not crash the verifier — the
        # caller's rejection path is the same either way.
        BUS.count("verify.checks")
        return done(_fail(
            "malformed_claim", f"{type(e).__name__}: {e}", engine=engine,
        ))
    lo = np.minimum(pairs[:, 0], pairs[:, 1])
    hi = np.maximum(pairs[:, 0], pairs[:, 1])

    if pairs.size and (
        graph.num_edges == 0
        or lo.min() < 0 or hi.max() >= graph.num_nodes
    ):
        BUS.count("verify.checks")
        return done(_fail(
            "unknown_edge",
            "claimed edges against an edgeless graph" if
            graph.num_edges == 0 else "claimed edge endpoint out of range",
            engine=engine,
        ))
    # Graph arrays are lexsorted by (u, v) after canonicalization: claimed
    # pairs map to edge ids by binary search on the packed key.
    key = graph.u.astype(np.int64) * graph.num_nodes + graph.v
    want = lo * graph.num_nodes + hi
    pos = np.searchsorted(key, want)
    ok_pos = (pos < key.size) & (key[np.minimum(pos, key.size - 1)] == want)
    if pairs.size and not ok_pos.all():
        missing = pairs[~ok_pos][:4]
        BUS.count("verify.checks")
        return done(_fail(
            "unknown_edge",
            f"claimed edges are not input edges: {missing.tolist()}",
            engine=engine,
        ))
    ids = pos.astype(np.int64)
    if total_weight is not None and pairs.size:
        recomputed = graph.w[ids].sum()
        if abs(float(recomputed) - float(total_weight)) > atol:
            BUS.count("verify.checks")
            return done(_fail(
                "weight_mismatch",
                f"claimed total weight {total_weight} != recomputed "
                f"{recomputed}",
                engine=engine,
            ))
    if kind == "components":
        return done(certify_components(
            graph, ids, engine=engine, expect_components=num_components,
        ))
    if kind == "k_msf":
        if k is None:
            BUS.count("verify.checks")
            return done(_fail(
                "malformed_claim", "k_msf claim without k", engine=engine,
            ))
        return done(certify_k_forest(graph, ids, int(k), engine=engine))
    if kind == "bottleneck":
        return done(certify_bottleneck(
            graph, ids, bottleneck_weight=bottleneck_weight, engine=engine,
        ))
    return done(certify_edge_ids(graph, ids, engine=engine))


def describe_violations(cert: Certificate) -> List[str]:
    """Human-readable failure rows for incident logs and drill reports."""
    if cert.ok:
        return []
    return [f"{cert.reason}: {cert.detail}"]
