"""Durable update log: snapshot every K windows + a JSONL delta WAL.

A maintained forest used to live only in a worker's memory — a restart
threw away every windowed session and the first post-restart update paid a
full fresh solve. This module gives each stream a directory under the
(fleet-shared) stream root:

* ``snapshot.npz`` — the session's whole state (``u/v/w/in_tree`` +
  window sequence + head digest) written through
  :func:`utils.checkpoint.atomic_write_npz`: tmp-file + rename with one
  retained ``.bak`` generation, so a crash mid-snapshot costs at most one
  snapshot interval (the ``stream.log.save`` fault site tears writes in
  tests).
* ``wal.jsonl`` — one JSON line per committed window
  (``ghs-stream-wal-v1``: seq, prev/new digest, the raw updates). Appends
  are flushed + fsynced and serialized across processes by the same
  advisory per-path flock the shared result store uses
  (``utils/locking.py``) — the two-process hammer test drives exactly
  that interleaving. The append/seal/read/compact mechanics live in the
  reusable :class:`utils.wal.JsonlWal` core (factored out in round 18 so
  the router's accepted-work journal shares them); this module keeps the
  stream-specific *chain* semantics on top.

**Replay** (:meth:`UpdateLog.load`) is snapshot-then-deltas: the newest
loadable snapshot generation (primary, else ``.bak``) plus every WAL entry
with a later sequence number, in order. A torn tail — a crash mid-append
leaves a partial last line — is skipped and counted
(``stream.log.torn_skipped``), never fatal; so is an unparsable *mid*-log
line (``stream.log.corrupt_line`` — a retried append seals the torn
record of its failed predecessor in place, leaving garbage between two
good lines). A real chain break (sequence gap, or a ``prev`` digest that
does not follow from the snapshot — the snapshot/log-disagreement case)
stops replay at the break with ``stream.log.chain_broken``: everything
before the break is still recovered, and the caller decides whether the
shortened head is acceptable. After each snapshot the WAL is compacted (entries at or below
the snapshot's sequence dropped via tmp + rename); a crash between
snapshot and compaction just leaves already-covered entries that replay
skips by sequence number.
"""

from __future__ import annotations

import json
import os
from typing import List, Optional, Tuple

import numpy as np

from distributed_ghs_implementation_tpu.obs.events import BUS
from distributed_ghs_implementation_tpu.utils.checkpoint import (
    atomic_write_npz,
)
from distributed_ghs_implementation_tpu.utils.locking import flocked as _flocked
from distributed_ghs_implementation_tpu.utils.wal import JsonlWal

WAL_SCHEMA = "ghs-stream-wal-v1"
FAULT_SITE = "stream.log.save"


class ChainBreak(ValueError):
    """The WAL does not follow from the snapshot (gap or digest mismatch),
    or an append would not follow from the durable tail (a fork). Carries
    the durable head when known so the caller can re-sync the client."""

    def __init__(
        self,
        msg: str,
        *,
        seq: Optional[int] = None,
        digest: Optional[str] = None,
    ):
        super().__init__(msg)
        self.seq = seq
        self.digest = digest


def _wal_entry(rec: dict) -> dict:
    """One schema-checked WAL record -> the replay entry shape (raising
    marks the line unparsable, exactly like non-JSON bytes)."""
    entry = {
        "seq": int(rec["seq"]),
        "prev": rec["prev"],
        "digest": rec["digest"],
        "updates": rec["updates"],
    }
    if "trace" in rec:
        # The publisher's trace context (obs/tracing.py): replay re-runs
        # the window under the ORIGINAL trace_id, so a recovery shows up
        # in the merged fleet trace as a child of the publish that
        # committed the window.
        entry["trace"] = rec["trace"]
    return entry


def stream_dir(root: str, stream_id: str) -> str:
    return os.path.join(root, stream_id)


def list_streams(root: str) -> List[str]:
    """Stream ids with a recoverable directory under ``root``."""
    if not os.path.isdir(root):
        return []
    return sorted(
        e.name for e in os.scandir(root)
        if e.is_dir() and (
            os.path.exists(os.path.join(e.path, "snapshot.npz"))
            or os.path.exists(os.path.join(e.path, "snapshot.npz.bak"))
        )
    )


class UpdateLog:
    """One stream's durable layer: ``<root>/<stream_id>/{snapshot.npz,wal.jsonl}``."""

    def __init__(self, root: str, stream_id: str):
        self.dir = stream_dir(root, stream_id)
        self.snap_path = os.path.join(self.dir, "snapshot.npz")
        self.wal_path = os.path.join(self.dir, "wal.jsonl")
        # The shared append/seal/read/compact mechanics (utils/wal.py);
        # chain semantics — what makes an entry FOLLOW its predecessor —
        # stay here.
        self._wal = JsonlWal(
            self.wal_path,
            schema=WAL_SCHEMA,
            counter_prefix="stream.log",
            validate=_wal_entry,
        )

    # -- writing -------------------------------------------------------
    def append(
        self,
        *,
        seq: int,
        prev_digest: str,
        digest: str,
        updates: list,
        trace: Optional[dict] = None,
    ) -> None:
        """Append one committed window (flushed + fsynced, flock-serialized).

        The durable chain is validated under the same flock before the
        write: an append must extend the on-disk tail (last WAL entry,
        else the snapshot head). A mismatch raises :class:`ChainBreak`
        carrying the durable head instead of forking the log — the
        fleet-shared-``stream_dir`` race where a worker holding a stale
        resident copy of a stream accepts a publish (its *in-memory* head
        matched) after another worker already committed past it.
        """
        os.makedirs(self.dir, exist_ok=True)
        with self._wal.lock():
            tail = self._durable_head()
            if tail is not None and (
                int(seq) != tail[0] + 1 or prev_digest != tail[1]
            ):
                BUS.count("stream.log.fork_refused")
                raise ChainBreak(
                    f"append seq {seq} (prev {prev_digest[:12]}...) does "
                    f"not extend the durable tail seq {tail[0]} "
                    f"({tail[1][:12]}...)",
                    seq=tail[0],
                    digest=tail[1],
                )
            # The core seals any torn tail before the write, so a crashed
            # predecessor cannot make this (durably committed) record
            # unparsable on replay.
            rec = {
                "seq": int(seq),
                "prev": prev_digest,
                "digest": digest,
                "updates": updates,
            }
            if trace is not None:
                rec["trace"] = trace
            self._wal.append(rec, locked=True)

    def snapshot(
        self,
        state: dict,
        *,
        seq: int,
        digest: str,
        notifications: Optional[list] = None,
    ) -> None:
        """Persist the session state (``WindowedMST.state_arrays``) and
        compact the WAL down to entries the snapshot does not cover.

        ``notifications`` rides along (JSON-encoded) so a recovered
        stream's ring reaches BACK past the snapshot point — a subscriber
        whose cursor predates the snapshot still drains gap-free after a
        failover, instead of hitting ``truncated``.

        Extra ``state`` keys persist as-is — the sharded-residency marker
        (``"sharded"``, stream/session.py) rides the same npz under the
        same sha256 sidecar + ``.bak`` integrity net as the arrays."""
        os.makedirs(self.dir, exist_ok=True)
        arrays = dict(state)
        arrays["seq"] = np.asarray(int(seq))
        arrays["digest"] = np.asarray(digest)
        arrays["notifications"] = np.asarray(
            json.dumps(list(notifications or []))
        )
        with _flocked(self.snap_path):
            atomic_write_npz(self.snap_path, arrays, fault_site=FAULT_SITE)
        BUS.count("stream.log.snapshot")
        self._compact(seq)

    def _compact(self, covered_seq: int) -> None:
        """Drop WAL entries the snapshot already covers (tmp + rename; a
        crash anywhere leaves entries replay skips by sequence number)."""
        try:
            with self._wal.lock():
                entries, _torn = self._read_wal()
                keep = [e for e in entries if e["seq"] > covered_seq]
                if len(keep) == len(entries):
                    return
                self._wal.rewrite(keep, locked=True)
            BUS.count("stream.log.compact")
        except (OSError, TimeoutError):
            pass  # compaction is best-effort; replay skips covered entries

    def _durable_head(self) -> Optional[Tuple[int, str]]:
        """``(seq, digest)`` of the durable chain tail — the last WAL
        append, else the newest loadable snapshot head; ``None`` when
        neither exists (a bare log). Callers hold the WAL flock; reads
        here must not re-enter it."""
        tail = self._wal.tail()
        if tail is not None:
            return tail["seq"], tail["digest"]
        for candidate in (self.snap_path, self.snap_path + ".bak"):
            if self._quarantine_if_corrupt(candidate):
                continue
            try:
                with np.load(candidate) as data:
                    return int(data["seq"]), str(data["digest"])
            except Exception:  # missing/torn: fall through
                continue
        return None

    @staticmethod
    def _quarantine_if_corrupt(candidate: str) -> bool:
        """Checksum-verify one snapshot generation before ``np.load``
        touches it; a sidecar mismatch quarantines the file
        (``stream.log.quarantined``) and reports True — the caller falls
        to the next generation, exactly like a torn write."""
        from distributed_ghs_implementation_tpu.utils.integrity import (
            IntegrityError,
            check_file,
            quarantine,
        )

        try:
            check_file(candidate)
        except FileNotFoundError:
            return False  # the load below reports it as missing
        except IntegrityError as e:
            quarantine(
                candidate, reason=str(e), counter="stream.log.quarantined"
            )
            return True
        return False

    # -- reading -------------------------------------------------------
    def _read_wal(self, count: bool = True) -> Tuple[List[dict], int]:
        """Parse the WAL; returns ``(entries, torn_skipped)`` — the core's
        tolerant read (torn tail and unparsable mid-log lines skipped);
        whether the log is still usable past a skip is decided by
        :meth:`load`'s chain validation, which stops at any real gap."""
        return self._wal.read(count=count)

    def load_snapshot(self) -> Tuple[Optional[dict], List[Tuple[str, str]]]:
        """Newest loadable snapshot generation (primary, else ``.bak``);
        returns ``(state_or_None, notes)`` in the checkpoint-recovery
        shape (why each skipped candidate was rejected)."""
        notes: List[Tuple[str, str]] = []
        for candidate in (self.snap_path, self.snap_path + ".bak"):
            if not os.path.exists(candidate):
                notes.append((candidate, "missing"))
                continue
            if self._quarantine_if_corrupt(candidate):
                notes.append((candidate, "quarantined: checksum mismatch"))
                continue
            try:
                with np.load(candidate) as data:
                    state = {
                        "num_nodes": int(data["num_nodes"]),
                        "u": np.asarray(data["u"]),
                        "v": np.asarray(data["v"]),
                        "w": np.asarray(data["w"]),
                        "in_tree": np.asarray(data["in_tree"], dtype=bool),
                        "seq": int(data["seq"]),
                        "digest": str(data["digest"]),
                        "notifications": (
                            json.loads(str(data["notifications"]))
                            if "notifications" in data.files else []
                        ),
                        # Residency marker (absent on pre-sharded-stream
                        # snapshots): this stream's head lived device-
                        # resident on the mesh lane, so a recovering
                        # worker re-stages BEFORE replaying and the
                        # replayed windows re-scatter into the slots —
                        # zero fresh solves on the rebuild path
                        # (stream/session.py recover()).
                        "sharded": (
                            bool(data["sharded"])
                            if "sharded" in data.files else False
                        ),
                    }
            except Exception as e:  # torn/corrupt: fall to the next generation
                notes.append((candidate, f"{type(e).__name__}: {e}"))
                continue
            if candidate.endswith(".bak"):
                BUS.count("stream.log.snap_fallback")
            return state, notes
        return None, notes

    def load(self) -> Tuple[Optional[dict], List[dict], List[Tuple[str, str]]]:
        """Replay input: ``(snapshot_state_or_None, chained_entries, notes)``.

        ``chained_entries`` are the WAL windows that verifiably follow the
        snapshot: contiguous sequence numbers starting at ``seq + 1`` whose
        ``prev`` digests chain from the snapshot digest. The first entry
        breaking the chain stops the list (``stream.log.chain_broken``) —
        the snapshot/log-disagreement degraded path.
        """
        state, notes = self.load_snapshot()
        entries, _torn = self._read_wal()
        if state is None:
            return None, [], notes
        chained: List[dict] = []
        seq, head = state["seq"], state["digest"]
        broken = False
        for entry in entries:
            if entry["seq"] <= seq:
                continue  # covered by the snapshot (compaction raced a crash)
            if entry["seq"] != seq + 1 or entry["prev"] != head:
                BUS.count("stream.log.chain_broken")
                notes.append((
                    self.wal_path,
                    f"chain break at seq {entry['seq']} "
                    f"(expected {seq + 1} following {head[:12]}...)",
                ))
                broken = True
                break
            chained.append(entry)
            seq, head = entry["seq"], entry["digest"]
        if broken:
            self._truncate_to_chain()
        return state, chained, notes

    def _truncate_to_chain(self) -> None:
        """Repair a mid-log chain break: rewrite the WAL down to the
        prefix that chains from the snapshot. Entries past the break are
        unreachable by replay, but ``append`` validates against the LAST
        parsable line — leaving them in place refuses every publish from
        the recovered head forever (the client adopts the dead tail
        digest, the session keeps recovering to the chained head: a
        re-sync livelock). The chain is re-derived from the freshest
        snapshot generation INSIDE the flock, so a concurrent writer that
        just advanced the snapshot (making the tail chain again) is never
        clobbered. Best-effort like compaction: a failed rewrite leaves
        the pre-repair state."""
        try:
            with self._wal.lock():
                state, _notes = self.load_snapshot()
                if state is None:
                    return
                entries, _torn = self._read_wal(count=False)
                keep: List[dict] = []
                seq, head = state["seq"], state["digest"]
                for entry in entries:
                    if entry["seq"] <= seq:
                        continue  # covered: compaction's job either way
                    if entry["seq"] != seq + 1 or entry["prev"] != head:
                        break
                    keep.append(entry)
                    seq, head = entry["seq"], entry["digest"]
                if len(keep) == len(entries):
                    return
                self._wal.rewrite(keep, locked=True)
            BUS.count("stream.log.chain_truncated")
        except (OSError, TimeoutError):
            pass
