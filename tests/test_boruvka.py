"""Core solver correctness: the NetworkX oracle gate, upgraded to pytest.

The reference verifies by ad-hoc comparison in its experiment loop
(``/root/reference/ghs_implementation.py:746-756``); here the same oracle is an
automated gate across fixtures, the reference's own 6 experiment configs, seed
sweeps, determinism, and structural edge cases the reference cannot handle
(disconnected graphs, single vertices, ties).
"""

import numpy as np
import pytest

from distributed_ghs_implementation_tpu.api import (
    GHSAlgorithm,
    minimum_spanning_forest,
    minimum_spanning_tree,
)
from distributed_ghs_implementation_tpu.graphs.edgelist import Graph
from distributed_ghs_implementation_tpu.graphs.generators import (
    erdos_renyi_graph,
    gnm_random_graph,
    line_graph,
    readme_sample_graph,
    reference_random_graph,
    rmat_graph,
    simple_test_graph,
)
from distributed_ghs_implementation_tpu.utils.verify import (
    networkx_mst_edges,
    verify_result,
)


def test_readme_sample_exact_edges():
    """The documented 6-node sample (README.md:43-64): unique MST, exact match."""
    r = minimum_spanning_tree(readme_sample_graph())
    assert r.total_weight == 20
    assert sorted(r.edges) == [(0, 1), (1, 2), (2, 3), (3, 4), (3, 5)]
    assert r.is_spanning_tree


def test_simple_fixture():
    """The reference's 3-node fixture (create_simple_test.py:9-50)."""
    r = minimum_spanning_tree(simple_test_graph())
    assert r.total_weight == 3
    assert sorted(r.edges) == [(0, 1), (1, 2)]


@pytest.mark.parametrize(
    "num_nodes,edge_probability,seed",
    [
        (5, 0.5, 42),
        (6, 0.4, 100),
        (7, 0.6, 200),
        (6, 0.7, 300),
        (10, 0.8, 400),
        (20, 0.3, 500),
    ],
)
def test_reference_experiment_configs(num_nodes, edge_probability, seed):
    """The reference's own 6 configs (ghs_implementation.py:787-794), on the
    *same graphs* it generates — including the 20-node one it gets wrong."""
    g = reference_random_graph(num_nodes, edge_probability, seed)
    r = minimum_spanning_tree(g)
    assert verify_result(r, oracle="networkx").ok


@pytest.mark.parametrize("seed", range(10))
@pytest.mark.parametrize("n,p", [(30, 0.15), (100, 0.08), (300, 0.03)])
def test_er_sweep_weight_parity(n, p, seed):
    g = erdos_renyi_graph(n, p, seed=seed)
    r = minimum_spanning_forest(g)
    assert verify_result(r, oracle="networkx").ok


def test_gnm_baseline_config():
    """BASELINE config 2: gnm_random_graph(1024, 8192)."""
    g = gnm_random_graph(1024, 8192, seed=7)
    r = minimum_spanning_forest(g)
    assert verify_result(r).ok


def test_unique_mst_exact_edge_set():
    """With distinct weights the MST is unique: require exact edge equality."""
    rng = np.random.default_rng(3)
    n = 40
    iu, iv = np.triu_indices(n, k=1)
    keep = rng.random(iu.size) < 0.3
    u, v = iu[keep], iv[keep]
    w = rng.permutation(u.size) + 1  # all-distinct weights
    g = Graph.from_arrays(n, u, v, w)
    r = minimum_spanning_forest(g)
    assert {tuple(e) for e in r.edges} == networkx_mst_edges(g)


def test_heavy_ties():
    """All-equal weights: any spanning tree is minimal; check count + weight."""
    g = erdos_renyi_graph(60, 0.2, seed=9, weight_low=5, weight_high=5)
    r = minimum_spanning_forest(g)
    assert verify_result(r).ok


def test_determinism():
    """Same graph -> byte-identical MST (the reference is nondeterministic;
    SURVEY.md measured 2/3 wrong runs at 20 nodes)."""
    g = erdos_renyi_graph(80, 0.1, seed=12)
    r1 = minimum_spanning_forest(g)
    r2 = minimum_spanning_forest(g)
    assert np.array_equal(r1.edge_ids, r2.edge_ids)


def test_high_diameter_line():
    """Path graph: worst-case diameter, still <= ceil(log2 n)+1 levels."""
    n = 513
    r = minimum_spanning_tree(line_graph(n))
    assert r.num_edges == n - 1
    assert r.num_levels <= 11


def test_disconnected_forest():
    """Two components: the reference deadlocks; we return a spanning forest."""
    edges = [(0, 1, 1), (1, 2, 2), (3, 4, 1), (4, 5, 5), (3, 5, 2)]
    g = Graph.from_edges(6, edges)
    r = minimum_spanning_forest(g)
    assert r.num_components == 2
    assert r.num_edges == 4
    assert r.total_weight == 1 + 2 + 1 + 2
    with pytest.raises(ValueError):
        minimum_spanning_tree(g)


def test_trivial_graphs():
    r = minimum_spanning_forest(Graph.from_edges(1, []))
    assert r.num_edges == 0 and r.num_components == 1
    r = minimum_spanning_forest(Graph.from_edges(2, [(0, 1, 7)]))
    assert r.total_weight == 7


def test_parallel_edges_and_self_loops():
    g = Graph.from_edges(3, [(0, 1, 5), (1, 0, 2), (1, 2, 3), (2, 2, 1)])
    assert g.num_edges == 2  # dedup kept min weight, loop dropped
    r = minimum_spanning_forest(g)
    assert r.total_weight == 5


def test_float_weights():
    rng = np.random.default_rng(5)
    n = 50
    iu, iv = np.triu_indices(n, k=1)
    keep = rng.random(iu.size) < 0.2
    g = Graph.from_arrays(n, iu[keep], iv[keep], rng.random(int(keep.sum())))
    r = minimum_spanning_forest(g)
    assert verify_result(r, atol=1e-4).ok


def test_rmat_small_scipy_parity():
    """RMAT scale-10 against the SciPy oracle (big-graph verification path)."""
    g = rmat_graph(10, 8, seed=2)
    r = minimum_spanning_forest(g)
    assert verify_result(r, oracle="scipy").ok


def test_solve_from_arbitrary_partition():
    """boruvka_solve must be correct for non-identity starting partitions
    (checkpoint-resume path): pre-merging vertices may not produce extra
    MST edges."""
    import jax.numpy as jnp

    from distributed_ghs_implementation_tpu.models.boruvka import (
        boruvka_solve,
        prepare_device_arrays,
    )

    g = erdos_renyi_graph(30, 0.2, seed=17)
    frag0, src, dst, rank, ra, rb = prepare_device_arrays(g, bucket_shapes=False)
    # Pre-merge vertex 1 into fragment 0.
    frag0 = frag0.at[1].set(0)
    mst_ranks, fragment, _ = boruvka_solve(frag0, src, dst, rank, ra, rb)
    num_components = int(np.unique(np.asarray(fragment)[: g.num_nodes]).size)
    # 29 fragments to merge -> at most 28 edges chosen.
    assert int(np.asarray(mst_ranks).sum()) == g.num_nodes - 1 - num_components


def test_all_strategies_agree():
    from distributed_ghs_implementation_tpu.models.boruvka import solve_graph

    g = erdos_renyi_graph(120, 0.08, seed=21)
    results = {
        s: solve_graph(g, strategy=s)[0] for s in ["ell", "stepped", "fused", "rank"]
    }
    assert np.array_equal(results["ell"], results["fused"])
    assert np.array_equal(results["stepped"], results["fused"])
    assert np.array_equal(results["rank"], results["fused"])


@pytest.mark.parametrize("seed", range(4))
def test_ell_strategy_oracle(seed):
    """The ELL strategy against the *external* oracle (not just the fused
    kernel — a shared bug must not pass) on skewed-degree graphs."""
    from distributed_ghs_implementation_tpu.models.boruvka import solve_graph
    from distributed_ghs_implementation_tpu.utils.verify import scipy_mst_weight

    g = rmat_graph(9, 8, seed=seed, use_native=False)  # power-law degrees
    edge_ids, fragment, _ = solve_graph(g, strategy="ell")
    assert float(g.w[edge_ids].sum()) == pytest.approx(scipy_mst_weight(g))
    assert len(edge_ids) == g.num_nodes - np.unique(fragment).size
    fused_ids, _, _ = solve_graph(g, strategy="fused")
    assert np.array_equal(edge_ids, fused_ids)


def test_ghs_algorithm_api():
    """The reference driver surface: GHSAlgorithm(n, edges).run() -> pairs."""
    edges = [(0, 1, 1), (0, 2, 4), (1, 2, 2), (1, 3, 5), (2, 3, 3)]
    ghs = GHSAlgorithm(4, edges)
    mst = ghs.run(timeout=15)  # timeout accepted for parity, unused
    assert sorted(mst) == [(0, 1), (1, 2), (2, 3)]
    assert ghs.get_mst_weight() == 6


@pytest.mark.parametrize("seed", range(4))
def test_rank_strategy_oracle(seed):
    """The rank-space solver against the external oracle on skewed-degree
    graphs, plus byte-identical agreement with the fused kernel."""
    from distributed_ghs_implementation_tpu.models.boruvka import solve_graph
    from distributed_ghs_implementation_tpu.utils.verify import scipy_mst_weight

    g = rmat_graph(9, 8, seed=seed, use_native=False)
    edge_ids, fragment, _ = solve_graph(g, strategy="rank")
    assert float(g.w[edge_ids].sum()) == pytest.approx(scipy_mst_weight(g))
    assert len(edge_ids) == g.num_nodes - np.unique(fragment).size
    fused_ids, fused_frag, _ = solve_graph(g, strategy="fused")
    assert np.array_equal(edge_ids, fused_ids)
    assert np.array_equal(fragment, fused_frag)


def test_rank_strategy_edge_cases():
    from distributed_ghs_implementation_tpu.models.boruvka import solve_graph
    from distributed_ghs_implementation_tpu.models.rank_solver import (
        solve_graph_rank,
    )

    # Disconnected forest, high diameter, floats, ties.
    for g in [
        line_graph(700),
        Graph.from_edges(7, [(0, 1, 5), (2, 3, 1), (3, 4, 1), (5, 6, 2)]),
        Graph.from_edges(4, [(0, 1, 0.5), (1, 2, 0.25), (2, 3, 0.75), (0, 3, 0.1)]),
        Graph.from_edges(5, [(0, 1, 1), (1, 2, 1), (2, 3, 1), (3, 4, 1), (0, 4, 1)]),
    ]:
        ids_r, frag_r, _ = solve_graph_rank(g)
        ids_f, frag_f, _ = solve_graph(g, strategy="fused")
        assert np.array_equal(ids_r, ids_f)
        assert np.array_equal(frag_r, frag_f)


def test_first_ranks_native_matches_numpy():
    """Graph.first_ranks: native O(m) pass == NumPy unique fallback."""
    from distributed_ghs_implementation_tpu.graphs import native

    g = rmat_graph(10, 8, seed=3, use_native=False)
    got = g.first_ranks
    m = g.num_edges
    order = g._rank_order
    ra, rb = g.u[order], g.v[order]
    expect = np.full(g.num_nodes, np.iinfo(np.int32).max, dtype=np.int32)
    for r in range(m - 1, -1, -1):
        expect[ra[r]] = r
        expect[rb[r]] = r
    assert np.array_equal(got, expect)
    if native.native_available():
        assert np.array_equal(
            native.first_rank_native(g.num_nodes, ra, rb), expect
        )


def test_rank_order_counting_matches_lexsort():
    """The native counting-sort rank order is the exact lexsort order."""
    from distributed_ghs_implementation_tpu.graphs import native

    rng = np.random.default_rng(9)
    w = rng.integers(1, 50, size=5000).astype(np.int64)
    expect = np.lexsort((np.arange(w.size), w))
    got = native.rank_order_counting_native(w)
    if got is not None:
        assert np.array_equal(got, expect)


def test_speculative_rank_misprediction_falls_back():
    """solve_rank_speculative must return None (not corrupt results) when the
    predicted survivor width is too small, and solve_rank_auto must still
    produce the exact MST through the staged fallback."""
    from distributed_ghs_implementation_tpu.models import rank_solver as rs

    g = gnm_random_graph(400, 3000, seed=9)
    vmin0, ra, rb = rs.prepare_rank_arrays(g)
    # Absurdly small prediction: guaranteed overflow unless the head already
    # finished the graph (it does not at this density).
    r = rs.solve_rank_speculative(vmin0, ra, rb, out_size=2)
    ref_ids, _, _ = solve_graph_for_test(g)
    if r is not None:  # accepted only if the head truly converged
        mst, fragment, levels = r
        ranks = np.nonzero(np.asarray(mst))[0]
        ids = np.sort(g.edge_id_of_rank(ranks))
        assert np.array_equal(ids, ref_ids)
    mst, fragment, levels = rs.solve_rank_auto(vmin0, ra, rb, family="dense")
    ranks = np.nonzero(np.asarray(mst))[0]
    ids = np.sort(g.edge_id_of_rank(ranks))
    assert np.array_equal(ids, ref_ids)


def solve_graph_for_test(g):
    from distributed_ghs_implementation_tpu.models.boruvka import solve_graph

    return solve_graph(g, strategy="fused")


@pytest.mark.parametrize(
    "graph_fn",
    [
        lambda: rmat_graph(10, 8, seed=3),
        lambda: rmat_graph(12, 16, seed=7),
        lambda: gnm_random_graph(400, 3000, seed=9),
        # Heavy ties: every weight equal — rank order is pure edge-id order.
        lambda: Graph.from_arrays(
            300,
            np.random.default_rng(1).integers(0, 300, 4000),
            np.random.default_rng(2).integers(0, 300, 4000),
            np.ones(4000, dtype=np.int64),
        ),
        # Float weights (skips the native counting sort).
        lambda: Graph.from_arrays(
            500,
            np.random.default_rng(3).integers(0, 500, 6000),
            np.random.default_rng(4).integers(0, 500, 6000),
            np.random.default_rng(5).random(6000),
        ),
        # Disconnected: two dense halves, no bridge.
        lambda: Graph.from_arrays(
            400,
            np.concatenate([
                np.random.default_rng(6).integers(0, 200, 2500),
                np.random.default_rng(7).integers(200, 400, 2500),
            ]),
            np.concatenate([
                np.random.default_rng(8).integers(0, 200, 2500),
                np.random.default_rng(9).integers(200, 400, 2500),
            ]),
            np.random.default_rng(10).integers(1, 1000, 5000),
        ),
    ],
)
def test_filtered_rank_solver_bit_identical(graph_fn):
    """solve_rank_filtered == solve_rank_staged, bit for bit (the filtered
    path computes the same unique rank-order MST — see the exactness proof
    in models/rank_solver.py)."""
    from distributed_ghs_implementation_tpu.models import rank_solver as rs

    g = graph_fn()
    vmin0, ra, rb = rs.prepare_rank_arrays(g)
    m_s, f_s, _ = rs.solve_rank_staged(vmin0, ra, rb)
    m_f, f_f, _ = rs.solve_rank_filtered(vmin0, ra, rb)
    assert np.array_equal(np.asarray(m_s), np.asarray(m_f))
    # Same partition (root ids may differ between merge orders).
    assert np.array_equal(
        canonical_partition(np.asarray(f_s)), canonical_partition(np.asarray(f_f))
    )


def canonical_partition(f: np.ndarray) -> np.ndarray:
    """Relabel a partition by first occurrence, making equality checks
    insensitive to which member each class uses as its root id."""
    _, first_idx, inv = np.unique(f, return_index=True, return_inverse=True)
    order = np.argsort(np.argsort(first_idx))
    return order[inv]


@pytest.mark.parametrize(
    "graph_fn",
    [
        lambda: rmat_graph(12, 16, seed=7),
        lambda: gnm_random_graph(400, 3000, seed=9),
        lambda: rmat_graph(10, 8, seed=3),
    ],
)
def test_filtered_speculative_bit_identical(graph_fn):
    """The one-dispatch speculative filtered solve matches the staged path
    bit for bit when its predictions hold. Small CPU graphs retire less in
    the head than the at-scale ratios the default widths assume, so the
    acceptance case passes generous explicit widths; the default-width call
    must either accept with identical results or cleanly return None."""
    from distributed_ghs_implementation_tpu.models import rank_solver as rs

    g = graph_fn()
    vmin0, ra, rb = rs.prepare_rank_arrays(g)
    m_s, f_s, _ = rs.solve_rank_staged(vmin0, ra, rb)
    prefix = rs._prefix_size(vmin0.shape[0], ra.shape[0])
    r = rs.solve_rank_filtered_speculative(
        vmin0, ra, rb, prefix_out=prefix, out_size=ra.shape[0]
    )
    assert r is not None
    m_f, f_f, _ = r
    assert np.array_equal(np.asarray(m_s), np.asarray(m_f))
    assert np.array_equal(
        canonical_partition(np.asarray(f_s)), canonical_partition(np.asarray(f_f))
    )
    r2 = rs.solve_rank_filtered_speculative(vmin0, ra, rb)
    if r2 is not None:
        assert np.array_equal(np.asarray(m_s), np.asarray(r2[0]))


def test_filtered_speculative_misprediction_falls_back():
    """An absurdly small survivor-width prediction must return None (never
    corrupt results), and solve_rank_auto must still produce the exact MST
    through the fallback chain."""
    from distributed_ghs_implementation_tpu.models import rank_solver as rs

    g = gnm_random_graph(300, 4000, seed=13)
    vmin0, ra, rb = rs.prepare_rank_arrays(g)
    ref_ids, _, _ = solve_graph_for_test(g)
    # Overflow each speculative width separately: the survivor width and the
    # prefix width (the check standing between silent _compact_slots
    # truncation and a corrupt accepted result).
    for kw in ({"out_size": 2}, {"prefix_out": 2}):
        r = rs.solve_rank_filtered_speculative(vmin0, ra, rb, **kw)
        if r is not None:  # accepted only if the true count really fit
            mst, _, _ = r
            ids = np.sort(g.edge_id_of_rank(np.nonzero(np.asarray(mst))[0]))
            assert np.array_equal(ids, ref_ids), kw
    mst, fragment, _ = rs.solve_rank_auto(vmin0, ra, rb, family="dense")
    ids = np.sort(g.edge_id_of_rank(np.nonzero(np.asarray(mst))[0]))
    assert np.array_equal(ids, ref_ids)


def test_filtered_chunked_filter_bit_identical(monkeypatch):
    """The chunked suffix filter (forced via tiny thresholds, including a
    non-dividing chunk width that exercises the clamped-overlap path) is
    bit-identical to the single-pass filter."""
    from distributed_ghs_implementation_tpu.models import rank_solver as rs

    g = rmat_graph(12, 16, seed=7)
    vmin0, ra, rb = rs.prepare_rank_arrays(g)
    m_ref, f_ref, _ = rs.solve_rank_filtered(vmin0, ra, rb)
    monkeypatch.setattr(rs, "_FILTER_CHUNK_BYTES", 1)
    for chunk_ranks in (1 << 13, 12345):  # pow2 and a non-dividing width
        monkeypatch.setattr(rs, "_FILTER_CHUNK_RANKS", chunk_ranks)
        m_c, f_c, _ = rs.solve_rank_filtered(vmin0, ra, rb)
        assert np.array_equal(np.asarray(m_ref), np.asarray(m_c)), chunk_ranks
        assert np.array_equal(
            canonical_partition(np.asarray(f_ref)),
            canonical_partition(np.asarray(f_c)),
        )


def test_fetch_mst_edge_ids_chunked_packbits(monkeypatch):
    """The sliced packbits fetch (forced via a tiny threshold) returns the
    same edge ids as the single-program form."""
    from distributed_ghs_implementation_tpu.models import rank_solver as rs

    g = rmat_graph(10, 8, seed=3)
    vmin0, ra, rb = rs.prepare_rank_arrays(g)
    mst, _, _ = rs.solve_rank_staged(vmin0, ra, rb)
    ids_full = rs.fetch_mst_edge_ids(g, mst)
    w = mst.shape[0]
    assert w % 8 == 0
    # A dividing chunk and a non-dividing one (exercises the remainder
    # tail — quarter-step bucket widths need not divide by the chunk).
    for chunk in (w // 4, max(8, (w // 3) & ~7)):
        monkeypatch.setattr(rs, "_PACKBITS_CHUNK", chunk)
        assert w > chunk
        ids_chunked = rs.fetch_mst_edge_ids(g, mst)
        assert np.array_equal(ids_full, ids_chunked), chunk


def test_filtered_rank_solver_prefix_extremes():
    """Degenerate prefix splits: prefix covering the whole graph falls back
    to the staged path; an oversized prefix_mult is clamped to m_pad."""
    from distributed_ghs_implementation_tpu.models import rank_solver as rs

    g = line_graph(600)  # m = n - 1: no room for a suffix
    vmin0, ra, rb = rs.prepare_rank_arrays(g)
    m_s, _, _ = rs.solve_rank_staged(vmin0, ra, rb, compact_after=1)
    m_f, _, _ = rs.solve_rank_filtered(vmin0, ra, rb)
    assert np.array_equal(np.asarray(m_s), np.asarray(m_f))

    g2 = gnm_random_graph(128, 2048, seed=4)
    vmin0, ra, rb = rs.prepare_rank_arrays(g2)
    m_s, _, _ = rs.solve_rank_staged(vmin0, ra, rb)
    for mult in (1, 8):
        m_f, _, _ = rs.solve_rank_filtered(vmin0, ra, rb, prefix_mult=mult)
        assert np.array_equal(np.asarray(m_s), np.asarray(m_f))


def test_filtered_rank_solver_compact_space(monkeypatch):
    """The filtered path with the census/shrink finish (forced small
    thresholds) still matches, exercising the shrink chain across the two
    _finish_to_fixpoint calls."""
    from distributed_ghs_implementation_tpu.models import rank_solver as rs

    g = rmat_graph(11, 12, seed=5)
    vmin0, ra, rb = rs.prepare_rank_arrays(g)
    m_s, f_s, _ = rs.solve_rank_staged(vmin0, ra, rb)

    orig = rs._finish_to_fixpoint

    def forced(*args, **kw):
        kw["compact_space"] = True
        return orig(*args, **kw)

    monkeypatch.setattr(rs, "_SHRINK_MIN_SPACE", 64)
    try:
        rs._finish_to_fixpoint = forced
        m_f, f_f, _ = rs.solve_rank_filtered(vmin0, ra, rb)
    finally:
        rs._finish_to_fixpoint = orig
    assert np.array_equal(np.asarray(m_s), np.asarray(m_f))
    assert np.array_equal(
        canonical_partition(np.asarray(f_s)), canonical_partition(np.asarray(f_f))
    )


def test_baseline_config2_exact():
    """BASELINE.json config 2: gnm_random_graph(1024, 8192), all backends."""
    from distributed_ghs_implementation_tpu.graphs.generators import gnm_random_graph
    from distributed_ghs_implementation_tpu.utils.verify import verify_result

    g = gnm_random_graph(1024, 8192, seed=2)
    r = minimum_spanning_forest(g)
    assert verify_result(r).ok
    ids_fused, _, _ = solve_graph_for_test(g)
    assert np.array_equal(ids_fused, r.edge_ids)
    rs = minimum_spanning_forest(g, backend="sharded")
    assert np.array_equal(rs.edge_ids, r.edge_ids)


def test_random_road_network_non_grid():
    """The non-grid road stand-in for BASELINE config 5 (VERDICT r3 item 6):
    irregular degrees (dead ends through junctions, not the grid's uniform
    4), USA-road average degree, distance-derived weights — and the sparse
    family tuning must route + verify it exactly."""
    from distributed_ghs_implementation_tpu.graphs.generators import (
        random_road_network,
    )
    from distributed_ghs_implementation_tpu.models.rank_solver import _pick_family
    from distributed_ghs_implementation_tpu.utils.verify import verify_result

    g = random_road_network(80, 80, seed=7)
    assert _pick_family(g) == "sparse"
    deg = np.zeros(g.num_nodes, np.int64)
    np.add.at(deg, g.u, 1)
    np.add.at(deg, g.v, 1)
    avg = 2 * g.num_edges / g.num_nodes
    assert 2.1 < avg < 2.7  # USA-road's ~2.4 incident average
    # Genuinely non-grid: the degree histogram is spread, not a spike at 4.
    frac4 = float(np.mean(deg == 4))
    assert frac4 < 0.5
    assert float(np.mean(deg <= 1)) > 0.05  # dead ends exist
    r = minimum_spanning_forest(g, backend="device")
    assert verify_result(r, oracle="networkx").ok
    rp = minimum_spanning_forest(g, backend="sharded")
    assert np.array_equal(r.edge_ids, rp.edge_ids)


@pytest.mark.parametrize("case", [(40, 120, 3), (100, 60, 1), (64, 64, 9)])
def test_host_level1_matches_device(case):
    """The host-side level-1 partition must be element-identical to the
    device computation it replaces (same hook destinations, same mutual
    break, same roots) — the r4 L1 host-precompute's contract."""
    import jax.numpy as jnp

    from distributed_ghs_implementation_tpu.models import rank_solver as rs

    n, m, seed = case
    rng = np.random.default_rng(seed)
    g = Graph.from_arrays(
        n,
        rng.integers(0, n, size=m),
        rng.integers(0, n, size=m),
        rng.integers(1, 6, size=m),
    )
    if g.num_edges == 0:
        pytest.skip("degenerate draw")
    n_pad = rs._bucket_size(g.num_nodes)
    m_pad = rs._bucket_size(g.num_edges)
    vmin0 = np.full(n_pad, np.iinfo(np.int32).max, dtype=np.int32)
    vmin0[: g.num_nodes] = g.first_ranks
    ra, rb = g.rank_endpoints(pad_to=m_pad)
    host = rs.host_level1(vmin0, ra, rb)
    dev = np.asarray(
        rs._device_level1(jnp.asarray(vmin0), jnp.asarray(ra), jnp.asarray(rb))
    )
    assert np.array_equal(host, dev)


# ----------------------------------------------------------------------
# Shape-bucket helpers (round 9): the batch engine keys its lane stacking
# and compile cache on these, so their boundary behavior is load-bearing.
# ----------------------------------------------------------------------
def test_next_pow2_boundaries():
    from distributed_ghs_implementation_tpu.models.boruvka import _next_pow2

    assert _next_pow2(0) == 1
    assert _next_pow2(1) == 1
    assert _next_pow2(2) == 2
    assert _next_pow2(3) == 4
    # Exact powers of two are fixed points; just-over doubles.
    for k in range(1, 24):
        p = 1 << k
        assert _next_pow2(p) == p
        assert _next_pow2(p + 1) == 2 * p
        assert _next_pow2(p - 1) == p if p > 2 else True


def test_bucket_size_boundaries():
    from distributed_ghs_implementation_tpu.models.boruvka import _bucket_size

    # Tiny sizes pass through (no padding below the quarter-step regime).
    assert [_bucket_size(x) for x in range(0, 5)] == [1, 1, 2, 3, 4]
    # Quarter steps: {1, 1.25, 1.5, 1.75} * 2^k.
    assert _bucket_size(5) == 5    # 1.25 * 4
    assert _bucket_size(6) == 6    # 1.5 * 4
    assert _bucket_size(7) == 7    # 1.75 * 4
    assert _bucket_size(8) == 8    # exact power of two is a fixed point
    assert _bucket_size(9) == 10   # 1.25 * 8
    assert _bucket_size(11) == 12
    assert _bucket_size(13) == 14
    assert _bucket_size(15) == 16
    for k in range(3, 24):
        p = 1 << k
        assert _bucket_size(p) == p
        assert _bucket_size(p + 1) == 5 * (p >> 2)  # 1.25x the next pow2's half
    # Contract over a dense range: covers x, wastes at most 25%.
    for x in range(1, 4097):
        b = _bucket_size(x)
        assert x <= b <= max(x + 1, (x * 5 + 3) // 4)


# ----------------------------------------------------------------------
# _compact_kernel padding semantics (the host-stepped shrink path)
# ----------------------------------------------------------------------
def test_compact_kernel_dead_slots_stay_sentinel():
    """Compaction packs alive slots (src_f != dst_f) in order; every dead
    and every pad slot must come out as the inert pattern — vertex-0
    self-edge, rank INT32_MAX — so a later MOE can never pick one. In
    particular a dead slot's REAL rank must not leak into the buffer."""
    from distributed_ghs_implementation_tpu.models.boruvka import _compact_kernel
    from distributed_ghs_implementation_tpu.ops.segment_ops import INT32_MAX

    src_f = np.array([1, 2, 2, 5, 7, 7, 9], np.int32)
    dst_f = np.array([1, 3, 2, 6, 7, 8, 9], np.int32)
    rank = np.array([10, 11, 12, 13, 14, 15, 16], np.int32)
    new_src, new_dst, new_rank = map(
        np.asarray, _compact_kernel(src_f, dst_f, rank, 4)
    )
    assert new_src.tolist() == [2, 5, 7, 0]
    assert new_dst.tolist() == [3, 6, 8, 0]
    assert new_rank.tolist() == [11, 13, 15, INT32_MAX]
    # Dead slots' ranks (10, 12, 14, 16) never appear in the output.
    assert not set(new_rank.tolist()) & {10, 12, 14, 16}


def test_compact_kernel_all_dead_is_all_sentinel():
    from distributed_ghs_implementation_tpu.models.boruvka import _compact_kernel
    from distributed_ghs_implementation_tpu.ops.segment_ops import INT32_MAX

    same = np.array([3, 3, 0, 7], np.int32)
    rank = np.array([1, 2, 3, 4], np.int32)
    new_src, new_dst, new_rank = map(
        np.asarray, _compact_kernel(same, same, rank, 2)
    )
    assert new_src.tolist() == [0, 0]
    assert new_dst.tolist() == [0, 0]
    assert new_rank.tolist() == [INT32_MAX, INT32_MAX]


def test_compact_kernel_undersized_buffer_truncates_safely():
    """``out_size`` below the alive count must not crash or scribble out of
    bounds: the overflow scatters drop, keeping the FIRST ``out_size``
    alive slots in slot order (the callers never request this — they size
    by the alive count — but the kernel's contract is safe truncation)."""
    from distributed_ghs_implementation_tpu.models.boruvka import _compact_kernel

    src_f = np.array([0, 1, 2, 3, 4, 5], np.int32)
    dst_f = np.array([9, 8, 7, 6, 5, 4], np.int32)  # every slot alive
    rank = np.arange(20, 26, dtype=np.int32)
    new_src, new_dst, new_rank = map(
        np.asarray, _compact_kernel(src_f, dst_f, rank, 4)
    )
    assert new_src.shape == (4,)
    assert new_src.tolist() == [0, 1, 2, 3]
    assert new_dst.tolist() == [9, 8, 7, 6]
    assert new_rank.tolist() == [20, 21, 22, 23]
