#!/usr/bin/env python
"""Open-loop load drill: sustained concurrent traffic against the serve stack.

Every other bench in the repo is *closed-loop* (the next request waits for
the last answer), which can never see queueing collapse. This drill is
**open-loop**: a seeded arrival schedule is generated up front (Poisson,
bursty, or ramp — arrivals never wait on completions), then threaded
clients fire a mixed scenario deck at :class:`serve.service.MSTService`:

* ``hit`` — repeats over a pre-solved pool (pure cache path),
* ``miss`` — distinct graphs across several shape buckets (solver path),
* ``batch`` — same-bucket bursts that must share lanes in the batch engine,
* ``dup`` — duplicate-digest storms (single-flight coalescing),
* ``update`` — incremental edge-update streams through ``serve/dynamic.py``
  (digest-chained, serialized per stream),
* ``oversize`` — bucket-ceiling bypasses to the single-graph path,

plus seeded **chaos faults armed mid-flight** (transient device failures,
a failed batch attempt) that the supervisor ladder must absorb: an
accepted query may degrade, it may never be *lost*.

Each request carries an ``slo_class`` tag; per-class goodput and
p50/p95/p99 latency are then **joined from the real ``serve.*`` /
``batch.*`` / ``compile.*`` bus events** by ``obs.slo`` (client-side
stopwatch accounting rides along as a cross-check). The report
(``ghs-load-report-v1``) embeds ``ghs-bench-metrics-v1`` gate metrics;
``tools/bench_gate.py`` compares them against the committed
``docs/BENCH_BASELINE_LOAD.json`` (the ``gate-load-v1`` workload) so p99
and goodput regressions fail CI the way weight parity does. See
``docs/LOAD_TESTING.md``.

    python tools/load_drill.py --smoke --output load_report.json \
        --gate-baseline docs/BENCH_BASELINE_LOAD.json
    python tools/load_drill.py --smoke --update-baseline   # rewrite baseline
    python tools/load_drill.py --chaos --duration 20       # chaos scenario

Exit code 0 iff every check passed (and the gate, when a baseline is given).
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import List, Optional

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPORT_SCHEMA = "ghs-load-report-v1"
WORKLOAD = "gate-load-v1"
DEFAULT_BASELINE = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "docs",
    "BENCH_BASELINE_LOAD.json",
)

# Shape buckets the deck draws from (nodes, edges): hit/miss/batch classes
# stay inside the lane-admission ceiling; oversize deliberately exceeds it.
MISS_SHAPES = ((48, 120), (96, 280), (200, 620))
BATCH_SHAPE = (128, 400)
HIT_SHAPE = (64, 180)
UPDATE_SHAPE = (80, 240)
OVERSIZE_SHAPE = (70_000, 140_000)


@dataclasses.dataclass
class Arrival:
    """One scheduled query: fire at ``at_s`` (relative to window start)."""

    at_s: float
    cls: str
    request: Optional[dict] = None  # None for update-stream arrivals
    stream: Optional[int] = None  # update-stream id (digest chained)
    updates: Optional[list] = None  # the update ops for a stream arrival


def _graph_request(g, cls: str) -> dict:
    return {
        "op": "solve",
        "num_nodes": g.num_nodes,
        "edges": [[int(a), int(b), int(c)] for a, b, c in zip(g.u, g.v, g.w)],
        "slo_class": cls,
    }


# ----------------------------------------------------------------------
# Arrival models (open-loop: schedules are fixed before the first dispatch)
# ----------------------------------------------------------------------
def arrival_times(
    n: int, duration_s: float, model: str, rng: np.random.Generator
) -> np.ndarray:
    """``n`` seeded arrival offsets in ``[0, duration_s)``.

    ``poisson`` — exponential inter-arrival gaps, rescaled to the window
    (open-loop Poisson traffic at the target average rate).
    ``bursty`` — four ON windows separated by silence; arrivals uniform
    inside the ON windows (a thundering-herd shape).
    ``ramp`` — arrival density grows linearly across the window (the
    rate doubles by the end; models a traffic ramp-up).
    """
    if n <= 0:
        return np.empty(0)
    if model == "poisson":
        gaps = rng.exponential(1.0, size=n)
        t = np.cumsum(gaps)
        return t * (duration_s / t[-1])
    if model == "bursty":
        bursts = 4
        on = duration_s / (2 * bursts)
        starts = np.arange(bursts) * (2 * on)
        which = rng.integers(0, bursts, size=n)
        return starts[which] + rng.uniform(0, on, size=n)
    if model == "ramp":
        # Inverse-CDF of a linearly growing rate: t = D * sqrt(u).
        return duration_s * np.sqrt(rng.uniform(0, 1, size=n))
    raise ValueError(f"unknown arrival model {model!r}")


# ----------------------------------------------------------------------
# The scenario deck
# ----------------------------------------------------------------------
def build_deck(args, rng: np.random.Generator):
    """Returns ``(schedule, warm_graphs, stream_seeds, counts)``.

    ``warm_graphs`` are solved before the measured window (cache/bucket
    priming); ``stream_seeds`` seed the update sessions. Every graph is
    seeded from ``args.seed``, so the deck is bit-reproducible.
    """
    from distributed_ghs_implementation_tpu.graphs.generators import (
        gnm_random_graph,
    )

    D = args.duration
    scale = args.rate / 10.0  # --rate 10 is the smoke deck's reference size
    counts = {
        "hit": max(4, int(30 * scale)),
        "miss": max(3, int(24 * scale)),
        "batch": max(4, int(24 * scale)),
        "dup": max(4, int(12 * scale)),
        "update": max(3, int(15 * scale)),
        "oversize": args.oversize,
    }
    schedule: List[Arrival] = []

    # hit: repeats over a small pre-solved pool.
    hit_pool = [
        gnm_random_graph(*HIT_SHAPE, seed=args.seed + 100 + i) for i in range(4)
    ]
    for i, t in enumerate(
        arrival_times(counts["hit"], D, args.arrival, rng)
    ):
        schedule.append(
            Arrival(float(t), "hit", _graph_request(hit_pool[i % 4], "hit"))
        )

    # miss: every query a distinct graph, cycling the shape buckets.
    for i, t in enumerate(
        arrival_times(counts["miss"], D, args.arrival, rng)
    ):
        shape = MISS_SHAPES[i % len(MISS_SHAPES)]
        g = gnm_random_graph(*shape, seed=args.seed + 1000 + i)
        schedule.append(Arrival(float(t), "miss", _graph_request(g, "miss")))

    # batch: same-bucket bursts — distinct digests arriving together so the
    # engine's forming queue actually builds multi-graph lanes.
    n_bursts = max(1, counts["batch"] // 8)
    burst_at = np.linspace(0.15 * D, 0.85 * D, n_bursts)
    for i in range(counts["batch"]):
        g = gnm_random_graph(*BATCH_SHAPE, seed=args.seed + 2000 + i)
        t = float(burst_at[i % n_bursts]) + float(rng.uniform(0, 0.01))
        schedule.append(Arrival(t, "batch", _graph_request(g, "batch")))

    # dup: duplicate-digest storms — each storm is ONE uncached digest
    # fired ~simultaneously; single-flight must answer with one solve.
    n_storms = max(1, counts["dup"] // 6)
    counts["dup"] = n_storms * (counts["dup"] // n_storms)
    storm_at = np.linspace(0.3 * D, 0.7 * D, n_storms)
    for s in range(n_storms):
        g = gnm_random_graph(
            BATCH_SHAPE[0], BATCH_SHAPE[1], seed=args.seed + 3000 + s
        )
        req = _graph_request(g, "dup")
        for k in range(counts["dup"] // n_storms):
            t = float(storm_at[s]) + float(rng.uniform(0, 0.005))
            schedule.append(Arrival(t, "dup", req))

    # update: digest-chained incremental streams (built at dispatch time —
    # each response re-keys the session content-addressed).
    n_streams = 3
    stream_seeds = [
        gnm_random_graph(*UPDATE_SHAPE, seed=args.seed + 4000 + s)
        for s in range(n_streams)
    ]
    for i, t in enumerate(
        arrival_times(counts["update"], D, args.arrival, rng)
    ):
        s = i % n_streams
        n = stream_seeds[s].num_nodes
        a, b = 0, 0
        while a == b:
            a, b = (int(x) for x in rng.integers(0, n, 2))
        kind = "insert" if i % 3 else "reweight"
        upd = {"kind": kind, "u": min(a, b), "v": max(a, b),
               "w": int(rng.integers(1, 100))}
        if kind == "reweight":
            # Reweight an edge that certainly exists: one from the seed.
            j = int(rng.integers(0, stream_seeds[s].num_edges))
            upd["u"] = int(stream_seeds[s].u[j])
            upd["v"] = int(stream_seeds[s].v[j])
        schedule.append(
            Arrival(float(t), "update", stream=s, updates=[upd])
        )

    # oversize: beyond the lane-admission ceiling — must bypass to the
    # single-graph path without stalling small-graph traffic.
    for i, frac in enumerate(np.linspace(0.25, 0.65, counts["oversize"])):
        g = gnm_random_graph(*OVERSIZE_SHAPE, seed=args.seed + 5000 + i)
        schedule.append(
            Arrival(float(frac) * D, "oversize", _graph_request(g, "oversize"))
        )

    schedule.sort(key=lambda a: a.at_s)
    warm_graphs = (
        hit_pool
        + [gnm_random_graph(*s, seed=args.seed + 90) for s in MISS_SHAPES]
        + [gnm_random_graph(*BATCH_SHAPE, seed=args.seed + 91)]
    )
    if counts["oversize"]:  # don't warm a bucket no query will touch
        warm_graphs.append(gnm_random_graph(*OVERSIZE_SHAPE, seed=args.seed + 92))
    return schedule, warm_graphs, stream_seeds, counts


# ----------------------------------------------------------------------
# Execution
# ----------------------------------------------------------------------
class _StreamState:
    __slots__ = ("digest", "lock")

    def __init__(self, digest: str):
        self.digest = digest
        self.lock = threading.Lock()


def run_window(service, schedule, streams, args, chaos_plan):
    """Dispatch the schedule open-loop; returns client-side records + wall.

    Latency is measured from the SCHEDULED arrival instant (not dispatch),
    so client-pool backlog counts against the service — the open-loop
    convention that makes queueing delay visible.
    """
    from distributed_ghs_implementation_tpu.utils.resilience import FAULTS

    records: List[dict] = []
    records_lock = threading.Lock()

    t0 = time.perf_counter()

    def fire(arrival: Arrival) -> None:
        scheduled = t0 + arrival.at_s
        try:
            if arrival.stream is not None:
                state = streams[arrival.stream]
                with state.lock:
                    response = service.handle(
                        {
                            "op": "update",
                            "digest": state.digest,
                            "updates": arrival.updates,
                            "slo_class": arrival.cls,
                        }
                    )
                    if response.get("ok"):
                        state.digest = response["digest"]
            else:
                response = service.handle(arrival.request)
            ok = bool(response.get("ok"))
        except Exception as e:  # noqa: BLE001 — a lost query, recorded
            with records_lock:
                records.append(
                    {"cls": arrival.cls, "ok": False, "lost": True,
                     "error": f"{type(e).__name__}: {e}",
                     "latency_s": time.perf_counter() - scheduled}
                )
            return
        with records_lock:
            records.append(
                {"cls": arrival.cls, "ok": ok, "lost": False,
                 "error": response.get("error"),
                 "latency_s": time.perf_counter() - scheduled}
            )

    chaos_armed: List[dict] = []
    next_chaos = 0
    with ThreadPoolExecutor(max_workers=args.workers) as pool:
        futures = []
        for arrival in schedule:
            while (
                next_chaos < len(chaos_plan)
                and arrival.at_s >= chaos_plan[next_chaos]["at_s"]
            ):
                # Chaos lands MID-FLIGHT, between dispatches: earlier
                # queries are still in the pool when the faults arm.
                plan = chaos_plan[next_chaos]
                for site, times in plan["sites"].items():
                    FAULTS.arm(site, times=times)
                chaos_armed.append(plan)
                next_chaos += 1
            delay = (t0 + arrival.at_s) - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            futures.append(pool.submit(fire, arrival))
        for f in futures:
            f.result()  # fire() never raises; this rejoins the pool
    wall_s = time.perf_counter() - t0
    return records, wall_s, chaos_armed


def client_summary(records, wall_s) -> dict:
    """The stopwatch cross-check: same schema, client-side measurements."""
    from distributed_ghs_implementation_tpu.obs import slo

    stats = slo.ClassStats()
    for rec in records:
        stats.observe(rec["cls"], rec["latency_s"], ok=rec["ok"])
    return slo.assemble(stats, wall_s=wall_s)


# ----------------------------------------------------------------------
# The drill
# ----------------------------------------------------------------------
def run_drill(args) -> dict:
    from distributed_ghs_implementation_tpu.obs import slo
    from distributed_ghs_implementation_tpu.obs.events import BUS
    from distributed_ghs_implementation_tpu.obs.export import write_events_jsonl
    from distributed_ghs_implementation_tpu.serve.service import MSTService
    from distributed_ghs_implementation_tpu.utils.resilience import FAULTS

    BUS.enable()
    rng = np.random.default_rng(args.seed)
    schedule, warm_graphs, stream_seeds, counts = build_deck(args, rng)

    service = MSTService(
        batch_lanes=args.lanes,
        batch_wait_s=args.batch_wait,
        max_sessions=256,  # solve seeds must not LRU-evict update sessions
        store_capacity=max(256, len(schedule)),
    )

    # Warm phase: prime every bucket the deck touches (compiles, rank
    # caches, the hit pool, update sessions) OUTSIDE the measured window —
    # sustained-load numbers should show steady-state serving, and the
    # compile.* counters inside the window then expose any request-time
    # compile as the anomaly it is.
    t_warm = time.perf_counter()
    for g in warm_graphs:
        service.handle(_graph_request(g, "warm"))
    stream_digests = []
    for g in stream_seeds:
        response = service.handle(_graph_request(g, "warm"))
        if not response.get("ok"):
            raise RuntimeError(f"warm solve failed: {response.get('error')}")
        stream_digests.append(response["digest"])
    warm_s = time.perf_counter() - t_warm
    streams = [_StreamState(d) for d in stream_digests]

    # Chaos plan: transient faults armed mid-flight (seeded offsets). The
    # supervisor ladder + batch retry must absorb them — degraded latency
    # is expected, lost accepted queries are not.
    chaos_plan = []
    if not args.no_chaos:
        chaos_plan.append(
            {
                "at_s": 0.5 * args.duration,
                "sites": {"resilience.attempt.device": 2, "batch.attempt": 1},
            }
        )
        if args.chaos:
            chaos_plan.append(
                {
                    "at_s": 0.7 * args.duration,
                    "sites": {"resilience.attempt.device": 4, "batch.attempt": 2},
                }
            )

    BUS.clear()  # the measured window starts here
    try:
        records, wall_s, chaos_armed = run_window(
            service, schedule, streams, args, chaos_plan
        )
    finally:
        FAULTS.reset()

    # Server-side accounting: the per-class join over real bus events.
    summary = slo.summarize_bus(BUS, wall_s=wall_s)
    client = client_summary(records, wall_s)
    compile_counters = {
        k: v for k, v in BUS.counters().items() if k.startswith("compile.")
    }
    serve_counters = {
        k: v
        for k, v in BUS.counters().items()
        if k.startswith(("serve.", "batch."))
    }
    if args.jsonl:
        write_events_jsonl(BUS, args.jsonl)

    lost = sum(1 for rec in records if rec["lost"])
    answered = len(records)
    errors = sum(1 for rec in records if not rec["ok"] and not rec["lost"])
    expected_classes = [c for c, n in counts.items() if n > 0]
    bus_classes = summary["classes"]

    checks = [
        ("every accepted query answered",
         answered == len(schedule) and lost == 0),
        ("zero errors (chaos absorbed by the supervisor)", errors == 0),
        ("all classes present in the bus-joined report",
         all(c in bus_classes for c in expected_classes)),
        ("bus join saw every request span",
         summary["totals"]["sent"] == len(schedule)),
        ("no events dropped during the window (report trustworthy)",
         not summary["dropped_warning"]),
        ("p99 bounded under chaos",
         client["totals"]["latency_s"].get("p99", float("inf"))
         <= args.p99_bound),
        ("duplicate storms coalesced (single-flight)",
         serve_counters.get("serve.scheduler.coalesced", 0) >= 1),
        ("chaos armed mid-flight", len(chaos_armed) == len(chaos_plan)),
        ("cache absorbed the hit class",
         serve_counters.get("serve.store.hit", 0) >= counts["hit"]),
        ("zero request-time compiles in the measured window",
         compile_counters.get("compile.miss", 0) == 0),
    ]
    ok = all(passed for _, passed in checks)

    config = {
        "workload": WORKLOAD,
        "deck": "smoke" if args.smoke else "custom",
        "seed": args.seed,
        "arrival": args.arrival,
        "duration_s": args.duration,
        "rate": args.rate,
        "lanes": args.lanes,
        "counts": counts,
        "chaos": "off" if args.no_chaos else ("heavy" if args.chaos else "mid"),
    }
    gate = slo.gate_metrics(
        summary,
        workload=WORKLOAD,
        config=config,
        extra_metrics={"lost_accepted": lost, "answered": answered},
    )
    return {
        "schema": REPORT_SCHEMA,
        "config": config,
        "wall_s": round(wall_s, 3),
        "warm_s": round(warm_s, 3),
        "slo": summary,
        "client": client,
        "compile_counters": compile_counters,
        "serve_counters": serve_counters,
        "chaos": {
            "armed": chaos_armed,
            "lost_accepted": lost,
            "errors": errors,
        },
        "events_dropped": summary["events_dropped"],
        "dropped_warning": summary["dropped_warning"],
        "checks": [{"name": n, "ok": bool(p)} for n, p in checks],
        "ok": ok,
        "gate_metrics": gate,
    }


def run_gate(report: dict, baseline_path: str, time_tolerance: float):
    """Compare the report's gate metrics against the committed baseline
    (reusing bench_gate's classification); returns ``(ok, lines)``."""
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import bench_gate

    with open(baseline_path) as f:
        baseline = json.load(f)
    return bench_gate.compare(
        baseline, report["gate_metrics"], time_tolerance=time_tolerance
    )


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="load_drill", description=__doc__)
    p.add_argument("--smoke", action="store_true",
                   help="the CI deck: ~10s window, mid-flight chaos, gate-ready")
    p.add_argument("--chaos", action="store_true",
                   help="heavier chaos scenario (second mid-flight arm point)")
    p.add_argument("--no-chaos", action="store_true",
                   help="disable the deck's mid-flight fault arming")
    p.add_argument("--arrival", choices=("poisson", "bursty", "ramp"),
                   default="poisson")
    p.add_argument("--duration", type=float, default=10.0,
                   help="arrival window in seconds (open-loop)")
    p.add_argument("--rate", type=float, default=10.0,
                   help="average arrivals/sec scale (10 = reference deck)")
    p.add_argument("--seed", type=int, default=23)
    p.add_argument("--lanes", type=int, default=4,
                   help="batch lanes for the service under test")
    p.add_argument("--batch-wait", type=float, default=0.02,
                   help="lane-forming window (s); wider than prod default "
                   "so open-loop bursts actually share lanes")
    p.add_argument("--oversize", type=int, default=2,
                   help="oversize-bypass queries in the deck")
    p.add_argument("--workers", type=int, default=16,
                   help="client threads (the open-loop dispatch pool)")
    p.add_argument("--p99-bound", type=float, default=30.0,
                   help="degraded-but-BOUNDED: fail if total p99 exceeds this")
    p.add_argument("--jsonl", help="also export the window's bus events")
    p.add_argument("--output", help="write the JSON report here")
    p.add_argument("--gate-baseline", nargs="?", const=DEFAULT_BASELINE,
                   help="gate the report against this baseline "
                   f"(default {DEFAULT_BASELINE})")
    p.add_argument("--time-tolerance", type=float, default=0.5,
                   help="gate wall-time tolerance (CI uses 5.0)")
    p.add_argument("--update-baseline", nargs="?", const=DEFAULT_BASELINE,
                   help="write the gate baseline from this run and exit")
    args = p.parse_args(argv)

    report = run_drill(args)
    if args.output:
        with open(args.output, "w") as f:
            json.dump(report, f, indent=2)
            f.write("\n")

    brief = {
        k: report[k]
        for k in ("schema", "config", "wall_s", "checks", "ok",
                  "events_dropped", "chaos")
    }
    brief["classes"] = {
        cls: {
            "sent": c["sent"],
            "goodput_per_sec": round(c["goodput_per_sec"] or 0, 2),
            "p50_s": round(c["latency_s"].get("p50", 0), 4),
            "p95_s": round(c["latency_s"].get("p95", 0), 4),
            "p99_s": round(c["latency_s"].get("p99", 0), 4),
        }
        for cls, c in report["slo"]["classes"].items()
    }
    print(json.dumps(brief, indent=2))

    if args.update_baseline:
        with open(args.update_baseline, "w") as f:
            json.dump(report["gate_metrics"], f, indent=2)
            f.write("\n")
        print(f"load baseline written: {args.update_baseline}")
        return 0 if report["ok"] else 1

    gate_ok = True
    if args.gate_baseline:
        gate_ok, lines = run_gate(
            report, args.gate_baseline, args.time_tolerance
        )
        for line in lines:
            print(line)
        print(f"load gate ({WORKLOAD}): {'PASS' if gate_ok else 'FAIL'}")

    print(f"load drill: {'PASS' if report['ok'] and gate_ok else 'FAIL'}")
    return 0 if report["ok"] and gate_ok else 1


if __name__ == "__main__":
    sys.exit(main())
