"""Incremental MSF maintenance: edge insert / delete / reweight on a cached
result, without re-running the solver.

The GHS fragment structure is what makes this cheap (PAPER.md): a single
edge change resolves against the existing tree by the classic exchange
rules —

* **insert** (cycle rule): a new edge ``(a, b, w)`` enters the tree iff it
  beats the maximum edge on the tree path ``a..b`` (which it then evicts);
  endpoints in different components just join their fragments.
* **delete** (cut rule): removing a non-tree edge changes nothing; removing
  a tree edge splits its fragment in two, and the replacement is the
  minimum edge crossing that cut — found here with ONE
  ``ops.segment_ops.fragment_moe`` over the edge list keyed by cut-side
  labels, exactly the solver's per-fragment MOE search. The side labels
  themselves come from a mini-Borůvka connectivity pass over the remaining
  tree edges built on the same ``fragment_moe`` +
  ``ops.union_find.hook_and_compress`` primitives.
* **reweight**: up-weighting a tree edge triggers a cut-rule replacement
  check; down-weighting a non-tree edge triggers a cycle-rule check; the
  other two directions never change the tree.

All comparisons use the lexicographic ``(w, u, v)`` triple — identical to
the solvers' global ``(weight, edge id)`` rank order, because edge ids are
positions in the sorted-``(u, v)`` canonical layout. The maintained forest
is therefore *edge-for-edge* the one a fresh solve would return, not merely
weight-equal (tests assert exact parity).

Fallback: a batch larger than ``resolve_threshold``, or one that leaves the
structure failing the forest check, is answered by a supervised full
re-solve instead (``serve.dynamic.resolve`` vs ``serve.dynamic.incremental``
on the bus tell the two paths apart; the incremental path records zero
``solver.*`` spans).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Iterable, List, Optional, Sequence, Union

import numpy as np

from distributed_ghs_implementation_tpu.api import MSTResult, minimum_spanning_forest
from distributed_ghs_implementation_tpu.graphs.edgelist import Graph
from distributed_ghs_implementation_tpu.obs.events import BUS

_KINDS = ("insert", "delete", "reweight")


@dataclasses.dataclass(frozen=True)
class Update:
    """One edge mutation. ``w`` is required for insert/reweight."""

    kind: str
    u: int
    v: int
    w: Optional[float] = None

    @staticmethod
    def from_dict(d: dict) -> "Update":
        return Update(
            kind=d.get("kind", d.get("op")),
            u=int(d["u"]),
            v=int(d["v"]),
            w=d.get("w"),
        )


def components_via_unionfind(
    num_nodes: int, eu: np.ndarray, ev: np.ndarray
) -> np.ndarray:
    """Connected-component root labels via the solver's own primitives.

    Public API (the analytics ``components`` kind and the stream layer both
    use it): repeated ``fragment_moe`` (per-fragment minimum outgoing edge)
    + ``hook_and_compress`` rounds — Borůvka connectivity, converging in
    ``<= ceil(log2 n)`` rounds. The weight key is the edge *index* (any
    all-distinct rank yields the same connectivity), which is exactly the
    "weight-free" instantiation of the GHS level loop.

    Args:
        num_nodes: node count ``n``; labels are returned for every node.
        eu, ev: endpoint arrays (any integer dtype; orientation and order
            do not matter — both directions are added internally).

    Returns:
        ``int64`` array of length ``n``: each node's fragment root. Two
        nodes are connected iff their labels are equal; isolated nodes
        label themselves.
    """
    import jax.numpy as jnp

    from distributed_ghs_implementation_tpu.ops.segment_ops import fragment_moe
    from distributed_ghs_implementation_tpu.ops.union_find import hook_and_compress

    n = int(num_nodes)
    m = int(eu.size)
    if n == 0:
        return np.zeros(0, dtype=np.int64)
    if m == 0:
        return np.arange(n, dtype=np.int64)
    # For connectivity any all-distinct rank works; edge index is one.
    src = jnp.asarray(np.concatenate([eu, ev]).astype(np.int32))
    dst = jnp.asarray(np.concatenate([ev, eu]).astype(np.int32))
    rank = jnp.asarray(np.concatenate([np.arange(m), np.arange(m)]).astype(np.int32))
    ra = jnp.asarray(eu.astype(np.int32))
    rb = jnp.asarray(ev.astype(np.int32))
    fragment = jnp.arange(n, dtype=jnp.int32)
    for _ in range(max(1, n).bit_length() + 2):
        has, _moe_rank, dstf = fragment_moe(fragment, src, dst, rank, ra, rb)
        if not bool(jnp.any(has)):
            return np.asarray(fragment, dtype=np.int64)
        fragment, _ = hook_and_compress(has, dstf, fragment)
    raise RuntimeError("union-find connectivity did not converge")  # unreachable


#: Historical private name, kept as an alias for in-repo callers and tests
#: that predate the analytics promotion.
_components_via_unionfind = components_via_unionfind


def tree_path_max(
    num_nodes: int,
    tu: np.ndarray,
    tv: np.ndarray,
    tw: np.ndarray,
    a: int,
    b: int,
) -> Optional[int]:
    """Maximum-weight edge on the unique forest path between ``a`` and ``b``.

    Public API (the analytics ``path_max`` kind queries it directly; the
    dynamic-update cycle rule uses it via :meth:`DynamicMST._tree_path_max`).
    Edges are compared by the solver's total order — lexicographic
    ``(w, u, v)`` — so ties break exactly as the MST solver breaks them,
    and for an MST the returned edge is the *minimax* (bottleneck-optimal)
    answer for the pair.

    Args:
        num_nodes: node count the forest spans.
        tu, tv, tw: the forest's edge arrays, ``tu[i] < tv[i]`` per edge
            (any order across edges). Must actually be a forest: each node
            pair connected by at most one path.
        a, b: node ids.

    Returns:
        Index **into the tree arrays** of the maximum-order path edge, or
        ``None`` when ``a == b`` or the nodes are in different fragments.
    """
    from scipy.sparse import coo_matrix
    from scipy.sparse.csgraph import breadth_first_order

    n = int(num_nodes)
    tu = np.asarray(tu)
    tv = np.asarray(tv)
    tw = np.asarray(tw)
    if tu.size == 0 or int(a) == int(b):
        return None
    adj = coo_matrix(
        (np.ones(tu.size, dtype=np.int8), (tu, tv)), shape=(n, n)
    ).tocsr()
    _order, pred = breadth_first_order(
        adj, int(a), directed=False, return_predecessors=True
    )
    if pred[int(b)] < 0:
        return None  # disconnected (scipy sentinel is -9999)
    keys = tu.astype(np.int64) * n + tv.astype(np.int64)
    order = np.argsort(keys, kind="stable")
    skeys = keys[order]
    is_int = tw.dtype.kind in "iu"

    def _triple(i: int):
        w = int(tw[i]) if is_int else float(tw[i])
        return (w, int(tu[i]), int(tv[i]))

    best: Optional[int] = None
    cur = int(b)
    a = int(a)
    while cur != a:
        p = int(pred[cur])
        lo, hi = (p, cur) if p < cur else (cur, p)
        key = lo * n + hi
        pos = int(np.searchsorted(skeys, key))
        if pos >= skeys.size or skeys[pos] != key:
            raise ValueError(f"tree edge ({lo}, {hi}) missing from arrays")
        idx = int(order[pos])
        if best is None or _triple(idx) > _triple(best):
            best = idx
        cur = p
    return best


#: Historical private name for the module-level path-max primitive.
_tree_path_max = tree_path_max


class DynamicMST:
    """A cached solve made updatable.

    Holds the graph as canonical sorted arrays plus an in-tree mask, applies
    update batches by the exchange rules above, and yields a fresh
    :class:`MSTResult` (under a new content digest) per batch.
    """

    def __init__(
        self,
        result: MSTResult,
        *,
        resolve_threshold: Optional[int] = None,
        backend: str = "device",
        supervisor=None,
        solver=None,
        pre_resolve=None,
    ):
        # ``solver`` (graph -> MSTResult) overrides the direct supervised
        # solve in :meth:`_resolve` — the stream layer injects the serving
        # scheduler here so a windowed session's full-re-solve escape hatch
        # is cached, single-flighted, and capacity-bounded like any other
        # miss (stream/session.py). ``pre_resolve`` (graph -> None) runs
        # just before that solve: the stream layer migrates a mesh-resident
        # session's device residency onto the resolve graph here, so an
        # oversize resolve dispatches on already-scattered slots instead of
        # cold-staging mid-publish. Best effort — a hook failure costs a
        # cold stage, never the resolve.
        g = result.graph
        self._n = g.num_nodes
        # Canonical layout: sorted by (u, v), unique. Graph construction
        # guarantees canonical u < v; re-sort defensively (dedup=False
        # callers may have bypassed the sort).
        order = np.lexsort((g.v, g.u))
        self._u = g.u[order].astype(np.int64)
        self._v = g.v[order].astype(np.int64)
        self._w = g.w[order].copy()
        self._k = self._u * self._n + self._v  # sorted lookup keys
        in_tree = np.zeros(g.num_edges, dtype=bool)
        in_tree[result.edge_ids] = True
        self._in_tree = in_tree[order]
        self._backend = backend
        self._supervisor = supervisor
        self._solver = solver
        self._pre_resolve = pre_resolve
        self._threshold = resolve_threshold
        self._last_mode = "seed"
        self._dirty = False

    # -- public state ---------------------------------------------------
    @property
    def num_nodes(self) -> int:
        return self._n

    @property
    def num_tree_edges(self) -> int:
        return int(self._in_tree.sum())

    @property
    def num_components(self) -> int:
        return self._n - self.num_tree_edges  # forest invariant

    @property
    def last_mode(self) -> str:
        """How the previous :meth:`apply` was answered:
        ``"incremental"`` / ``"resolve"`` / ``"seed"``."""
        return self._last_mode

    @property
    def backend(self) -> str:
        """The solver backend this session's results are keyed/re-solved
        under (set at construction from the solve that seeded it)."""
        return self._backend

    @property
    def dirty(self) -> bool:
        """True iff an :meth:`apply` failed after mutation began — the state
        no client has seen; holders should discard the session."""
        return self._dirty

    def result(self, wall_time_s: float = 0.0) -> MSTResult:
        graph = Graph(
            self._n, self._u.copy(), self._v.copy(), self._w.copy()
        )
        return MSTResult(
            graph=graph,
            edge_ids=np.nonzero(self._in_tree)[0],
            num_levels=0,
            wall_time_s=wall_time_s,
            backend=f"serve/{self._last_mode}",
            num_components=self.num_components,
        )

    # -- the batch entry -------------------------------------------------
    def apply(self, updates: Iterable[Union[Update, dict]]) -> MSTResult:
        """Apply one update batch; returns the post-batch result."""
        batch = [
            u if isinstance(u, Update) else Update.from_dict(u) for u in updates
        ]
        self._validate(batch)
        t0 = time.perf_counter()
        threshold = (
            self._threshold
            if self._threshold is not None
            else max(64, self._u.size // 10)
        )
        with BUS.span(
            "serve.dynamic.apply", cat="serve",
            updates=len(batch), nodes=self._n,
        ) as span:
            self._dirty = True  # cleared only when a batch completes
            if len(batch) > threshold:
                span.set(mode="resolve", reason="batch_over_threshold")
                out = self._resolve(batch, t0)
            else:
                for upd in batch:
                    BUS.count(f"serve.dynamic.{upd.kind}")
                    self._apply_one(upd)
                if not self._forest_ok():
                    BUS.count("serve.dynamic.verify_failed")
                    span.set(mode="resolve", reason="verification_failed")
                    out = self._resolve([], t0)
                else:
                    BUS.count("serve.dynamic.incremental")
                    span.set(mode="incremental")
                    self._last_mode = "incremental"
                    out = self.result(time.perf_counter() - t0)
            self._dirty = False
            return out

    # -- single-update rules ---------------------------------------------
    def _apply_one(self, upd: Update) -> None:
        a, b = (upd.u, upd.v) if upd.u < upd.v else (upd.v, upd.u)
        idx = self._find(a, b)
        if upd.kind == "delete":
            if idx < 0:
                return  # deleting an absent edge is a no-op
            self._delete_at(idx)
        elif idx >= 0:  # insert of an existing edge == reweight
            self._reweight_at(idx, upd.w)
        else:
            self._insert(a, b, upd.w)

    def _insert(self, a: int, b: int, w) -> None:
        path_max = self._tree_path_max(a, b)
        idx = self._splice(a, b, w, in_tree=path_max is None)
        if path_max is None:
            return  # different fragments: the new edge joins them
        # Cycle rule: evict the path maximum iff the new edge beats it
        # (the splice shifted indices at/after the insertion point by one).
        mi = path_max if path_max < idx else path_max + 1
        if self._triple(idx) < self._triple(mi):
            self._in_tree[mi] = False
            self._in_tree[idx] = True

    def _delete_at(self, idx: int) -> None:
        was_tree = bool(self._in_tree[idx])
        a, b = int(self._u[idx]), int(self._v[idx])
        self._remove(idx)
        if not was_tree:
            return
        # Cut rule: label the two sides of the broken fragment from the
        # remaining tree edges, then one MOE search for the replacement.
        sides = _components_via_unionfind(
            self._n, self._u[self._in_tree], self._v[self._in_tree]
        )
        repl = self._min_crossing(sides, sides[a], sides[b])
        if repl is not None:
            self._in_tree[repl] = True

    def _reweight_at(self, idx: int, w) -> None:
        old = self._triple(idx)
        self._set_weight(idx, w)
        new = self._triple(idx)
        if self._in_tree[idx] and new > old:
            # A tree edge got heavier: re-run the cut rule across its cut.
            a, b = int(self._u[idx]), int(self._v[idx])
            keep = self._in_tree.copy()
            keep[idx] = False
            sides = _components_via_unionfind(
                self._n, self._u[keep], self._v[keep]
            )
            repl = self._min_crossing(sides, sides[a], sides[b])
            if repl is not None and repl != idx:
                self._in_tree[idx] = False
                self._in_tree[repl] = True
        elif not self._in_tree[idx] and new < old:
            # A non-tree edge got lighter: cycle rule against the tree path.
            a, b = int(self._u[idx]), int(self._v[idx])
            path_max = self._tree_path_max(a, b)
            if path_max is None:
                self._in_tree[idx] = True  # endpoints were disconnected
            elif self._triple(idx) < self._triple(path_max):
                self._in_tree[path_max] = False
                self._in_tree[idx] = True

    # -- searches --------------------------------------------------------
    def _min_crossing(
        self, sides: np.ndarray, root_a, root_b
    ) -> Optional[int]:
        """Minimum-order edge crossing the (root_a | root_b) cut, via the
        solver's ``fragment_moe`` keyed by side labels; ``None`` when the
        cut has no crossing edge (the fragment stays split)."""
        import jax.numpy as jnp

        from distributed_ghs_implementation_tpu.ops.segment_ops import (
            INT32_MAX,
            fragment_moe,
        )

        m = self._u.size
        if m == 0 or root_a == root_b:
            return None
        order = np.lexsort((self._v, self._u, self._w))
        rank_of_edge = np.empty(m, dtype=np.int64)
        rank_of_edge[order] = np.arange(m)
        src = jnp.asarray(np.concatenate([self._u, self._v]).astype(np.int32))
        dst = jnp.asarray(np.concatenate([self._v, self._u]).astype(np.int32))
        rank = jnp.asarray(
            np.concatenate([rank_of_edge, rank_of_edge]).astype(np.int32)
        )
        ra = jnp.asarray(self._u[order].astype(np.int32))
        rb = jnp.asarray(self._v[order].astype(np.int32))
        fragment = jnp.asarray(sides.astype(np.int32))
        _has, moe_rank, _dstf = fragment_moe(fragment, src, dst, rank, ra, rb)
        best = int(min(moe_rank[int(root_a)], moe_rank[int(root_b)]))
        if best >= int(INT32_MAX):
            return None
        return int(order[best])

    def _tree_path_max(self, a: int, b: int) -> Optional[int]:
        """Index (into the *full* edge arrays) of the maximum-order edge on
        the tree path ``a..b``, or ``None`` when ``a`` and ``b`` are in
        different fragments. Thin wrapper over the public module-level
        :func:`tree_path_max`, mapping its tree-relative index back."""
        tree_idx = np.nonzero(self._in_tree)[0]
        rel = tree_path_max(
            self._n,
            self._u[tree_idx],
            self._v[tree_idx],
            self._w[tree_idx],
            a,
            b,
        )
        return None if rel is None else int(tree_idx[rel])

    # -- structural invariants -------------------------------------------
    def _forest_ok(self) -> bool:
        """Structural check: the in-tree mask is a spanning forest of the
        current graph. Two halves, both needed: ``t == n - k_tree`` over the
        *tree* subgraph's own components rejects cycles (a cyclic mask can
        still satisfy the graph-level count), and ``k_tree == k_graph``
        rejects a non-maximal forest (two fragments the graph could
        connect left apart)."""
        from distributed_ghs_implementation_tpu.graphs.edgelist import (
            component_labels,
        )

        t = self.num_tree_edges
        if self._u.size == 0:
            return t == 0
        k_graph = int(np.unique(component_labels(self._n, self._u, self._v)).size)
        k_tree = int(
            np.unique(
                component_labels(
                    self._n, self._u[self._in_tree], self._v[self._in_tree]
                )
            ).size
        )
        return t == self._n - k_tree and k_tree == k_graph

    # -- fallback ---------------------------------------------------------
    def _resolve(self, pending: Sequence[Update], t0: float) -> MSTResult:
        """Apply ``pending`` structurally, then hand the whole graph to a
        supervised full solve (the degradation path for oversized batches
        and failed verification)."""
        for upd in pending:
            BUS.count(f"serve.dynamic.{upd.kind}")
            a, b = (upd.u, upd.v) if upd.u < upd.v else (upd.v, upd.u)
            idx = self._find(a, b)
            if upd.kind == "delete":
                if idx >= 0:
                    self._remove(idx)
            elif idx >= 0:
                self._set_weight(idx, upd.w)
            else:
                self._splice(a, b, upd.w, in_tree=False)
        BUS.count("serve.dynamic.resolve")
        graph = Graph(self._n, self._u.copy(), self._v.copy(), self._w.copy())
        if self._pre_resolve is not None:
            try:
                self._pre_resolve(graph)
            except Exception:  # noqa: BLE001 — residency is best effort
                BUS.count("serve.dynamic.pre_resolve_failed")
        if self._solver is not None:
            solved = self._solver(graph)
        else:
            solved = minimum_spanning_forest(
                graph, backend=self._backend, supervised=True,
                supervisor=self._supervisor,
            )
        in_tree = np.zeros(graph.num_edges, dtype=bool)
        in_tree[solved.edge_ids] = True
        self._in_tree = in_tree
        self._last_mode = "resolve"
        return self.result(time.perf_counter() - t0)

    # -- array plumbing ---------------------------------------------------
    def _key(self, lo: int, hi: int) -> int:
        return lo * self._n + hi

    def _find(self, lo: int, hi: int) -> int:
        """Index of edge ``(lo, hi)`` in the sorted arrays, or -1 — one
        O(log m) bisect over the maintained key array (``_k`` is kept in
        lock-step by ``_splice``/``_remove``; rebuilding it per lookup would
        make a path walk O(path * m))."""
        key = self._key(lo, hi)
        pos = int(np.searchsorted(self._k, key))
        if pos < self._k.size and self._k[pos] == key:
            return pos
        return -1

    def _triple(self, idx: int):
        """The solver's total order on edges: lexicographic ``(w, u, v)``
        (== (weight, edge id), since ids follow the sorted (u, v) layout)."""
        w = self._w[idx]
        w = int(w) if self._w.dtype.kind in "iu" else float(w)
        return (w, int(self._u[idx]), int(self._v[idx]))

    def _splice(self, lo: int, hi: int, w, *, in_tree: bool) -> int:
        if w is None:
            raise ValueError(f"insert ({lo}, {hi}) requires a weight")
        self._promote_weight_dtype(w)
        key = self._key(lo, hi)
        pos = int(np.searchsorted(self._k, key))
        self._u = np.insert(self._u, pos, lo)
        self._v = np.insert(self._v, pos, hi)
        self._w = np.insert(self._w, pos, w)
        self._k = np.insert(self._k, pos, key)
        self._in_tree = np.insert(self._in_tree, pos, in_tree)
        return pos

    def _remove(self, idx: int) -> None:
        self._u = np.delete(self._u, idx)
        self._v = np.delete(self._v, idx)
        self._w = np.delete(self._w, idx)
        self._k = np.delete(self._k, idx)
        self._in_tree = np.delete(self._in_tree, idx)

    def _set_weight(self, idx: int, w) -> None:
        if w is None:
            raise ValueError(
                f"reweight ({self._u[idx]}, {self._v[idx]}) requires a weight"
            )
        self._promote_weight_dtype(w)
        self._w = self._w.copy()  # never mutate arrays shared with a result
        self._w[idx] = w

    def _promote_weight_dtype(self, w) -> None:
        if self._w.dtype.kind in "iu" and float(w) != int(w):
            self._w = self._w.astype(np.float64)

    def _validate(self, batch: List[Update]) -> None:
        for upd in batch:
            if upd.kind not in _KINDS:
                raise ValueError(
                    f"unknown update kind {upd.kind!r}; expected {_KINDS}"
                )
            if not (0 <= upd.u < self._n and 0 <= upd.v < self._n):
                raise ValueError(
                    f"endpoint out of range in {upd} (num_nodes={self._n})"
                )
            if upd.u == upd.v:
                raise ValueError(f"self-loop in {upd}")
            if upd.kind != "delete":
                if upd.w is None:
                    raise ValueError(f"{upd.kind} requires a weight: {upd}")
                import math

                try:  # reject non-numeric weights BEFORE any edge is touched
                    finite = math.isfinite(float(upd.w))
                except (TypeError, ValueError):
                    raise ValueError(f"non-numeric weight in {upd}") from None
                if not finite:  # NaN breaks the total order, inf int-casts
                    raise ValueError(f"non-finite weight in {upd}")
