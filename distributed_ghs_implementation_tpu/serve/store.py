"""Content-addressed solve cache: graph digest + solver config -> MSTResult.

Keying is by *content*, never identity: :func:`solve_cache_key` combines
:meth:`Graph.digest` (stable sha256 over the canonicalized ``u/v/w`` arrays
plus ``num_nodes`` — the same hash checkpoint fingerprints derive from) with
the solver configuration, so two requests describing the same weighted edge
set hit the same entry regardless of edge input order or which client sent
them.

Two layers:

* an in-memory LRU front (``capacity`` entries; an entry is an
  :class:`api.MSTResult`, which pins its graph's arrays — size the capacity
  to the working set, not the request rate), and
* an optional on-disk layer (``disk_dir``) holding one npz per key through
  ``utils.checkpoint.atomic_write_npz`` — the same tmp-file + rename +
  ``.bak``-generation write path checkpoints use, so a crash mid-write never
  leaves a poisoned cache entry (the ``serve.store.save`` fault site tears
  writes in chaos drills). Disk hits are re-validated against the graph's
  digest before they are served and promoted into memory. Writes take an
  advisory per-key ``flock`` (:func:`_flocked`) so fleet workers sharing one
  ``disk_dir`` cannot interleave a publish; reads stay lock-free.

Telemetry (``obs`` bus): ``serve.store.hit`` / ``.miss`` / ``.disk_hit`` /
``.put`` / ``.evict`` counters; all methods are thread-safe (the scheduler
calls in from concurrent request threads).
"""

from __future__ import annotations

import collections
import contextlib
import os
import threading
from typing import Optional

import numpy as np

try:  # advisory write locking (fleet workers share one disk_dir)
    import fcntl
except ImportError:  # pragma: no cover — non-POSIX: single-writer only
    fcntl = None

from distributed_ghs_implementation_tpu.api import MSTResult
from distributed_ghs_implementation_tpu.graphs.edgelist import Graph
from distributed_ghs_implementation_tpu.obs.events import BUS
from distributed_ghs_implementation_tpu.utils.locking import (
    LOCK_TIMEOUT_S,
    flocked,
)


def solve_cache_key(
    graph: Graph, *, backend: str = "device", kind: str = "mst"
) -> str:
    """The cache identity of one solve: content digest + solver config.

    ``backend`` is the *requested* entry (e.g. ``"device"``), not the rung a
    supervised solve eventually lands on — a degraded result is still the
    exact MSF (every rung computes the identical forest), so it may serve
    later requests for the same entry. The same holds for the oversize
    route: a ``"device"`` request the scheduler sends to the mesh-sharded
    lane (``parallel/lane.py``) caches under its requested ``"device"``
    key, so the repeat query is a hit regardless of which path solved it
    (tests/test_lane.py pins the memory and disk round trips).

    ``kind`` is the analytics query-kind token (``"mst"``, ``"components"``,
    ``"k_msf4"``, ...): a components answer for a digest must never collide
    with the MST answer for the same digest, so non-``mst`` kinds append the
    token as a third key segment. ``"mst"`` keeps the historical two-segment
    key so pre-analytics disk caches stay readable in place.
    """
    return cache_key_for_digest(graph.digest(), backend=backend, kind=kind)


def cache_key_for_digest(
    digest: str, *, backend: str = "device", kind: str = "mst"
) -> str:
    """:func:`solve_cache_key` for an already-computed digest — the stream
    layer evicts superseded chain ancestors by digest alone, without
    holding the ancestor graph. Non-``mst`` ``kind`` tokens become a third
    ``:``-separated segment (must be filename-safe: ``[a-z0-9_]``)."""
    base = f"{digest}:{backend}"
    if kind == "mst":
        return base
    token = str(kind)
    if not token or not all(ch.isalnum() or ch == "_" for ch in token):
        raise ValueError(f"bad cache kind token {kind!r}")
    return f"{base}:{token}"


def _disk_path(disk_dir: str, key: str) -> str:
    return os.path.join(disk_dir, key.replace(":", "_") + ".npz")


#: Advisory per-key write locking now lives in ``utils/locking.py`` (the
#: router journal needs it without the serve stack on its import path);
#: ``_flocked`` stays as the public-in-practice alias the stream log and
#: the fleet docs reference, with the historical timeout + counter names.
_LOCK_TIMEOUT_S = LOCK_TIMEOUT_S


def _flocked(path: str, timeout_s: float = _LOCK_TIMEOUT_S):
    """Advisory per-key write lock (see :func:`utils.locking.flocked`)."""
    return flocked(path, timeout_s, counter="serve.store.lock_timeout")


class ResultStore:
    """In-memory LRU + optional on-disk content-addressed result cache."""

    def __init__(
        self,
        capacity: int = 128,
        disk_dir: Optional[str] = None,
        disk_max_entries: int = 512,
    ):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self.disk_dir = disk_dir
        self.disk_max_entries = disk_max_entries
        self._mem: "collections.OrderedDict[str, MSTResult]" = (
            collections.OrderedDict()
        )
        self._lock = threading.Lock()

    def __len__(self) -> int:
        with self._lock:
            return len(self._mem)

    def get(
        self,
        key: str,
        graph: Optional[Graph] = None,
        *,
        record_miss: bool = True,
    ) -> Optional[MSTResult]:
        """Look up ``key``; memory first, then disk (needs ``graph`` to
        rebuild the result — content addressing means the caller has it).
        ``record_miss=False`` keeps a re-probe (the scheduler's single-flight
        double-check) from inflating the miss counter."""
        with self._lock:
            result = self._mem.get(key)
            if result is not None:
                self._mem.move_to_end(key)
                BUS.count("serve.store.hit")
                return result
        if self.disk_dir is not None and graph is not None:
            result = self._disk_get(key, graph)
            if result is not None:
                BUS.count("serve.store.hit")
                BUS.count("serve.store.disk_hit")
                self._mem_put(key, result)
                return result
        if record_miss:
            BUS.count("serve.store.miss")
        return None

    def put(
        self, key: str, result: MSTResult, *, memory_only: bool = False
    ) -> None:
        """Cache ``result``; ``memory_only=True`` skips the disk layer.

        Stream window commits use ``memory_only``: their durability is the
        stream snapshot+WAL (replay rebuilds any head), so a full-graph npz
        write per committed window — for a head the next window supersedes
        — would be pure disk churn on the commit hot path.
        """
        BUS.count("serve.store.put")
        self._mem_put(key, result)
        if self.disk_dir is not None and not memory_only:
            try:
                self._disk_put(key, result)
                self._disk_sweep()
            except Exception:  # noqa: BLE001 — write-behind is best-effort
                # A failed (or torn) cache write must never fail the request
                # that produced the result; the atomic writer left either
                # nothing or a .bak generation behind, and reads re-validate
                # digests, so the worst case is a future miss.
                BUS.count("serve.store.disk_write_failed")

    def invalidate(self, key: str, *, reason: str = "") -> bool:
        """Hard-evict a POISONED entry: memory LRU dropped, every disk
        generation quarantined (never merely unlinked — a failed
        certificate's input is postmortem evidence, and a digest chain
        that re-plants it from disk would serve the same wrong answer
        again). Returns whether anything was removed
        (``serve.store.invalidated``)."""
        removed = False
        with self._lock:
            removed = self._mem.pop(key, None) is not None
        if self.disk_dir is not None:
            from distributed_ghs_implementation_tpu.utils.integrity import (
                quarantine,
            )

            path = _disk_path(self.disk_dir, key)
            for candidate in (path, path + ".bak"):
                if os.path.exists(candidate):
                    removed = bool(quarantine(
                        candidate, reason=reason or "invalidated",
                        counter="serve.store.quarantined",
                    )) or removed
        if removed:
            BUS.count("serve.store.invalidated")
        return removed

    def evict_chain(self, key: str) -> bool:
        """Drop a superseded digest-chain ancestor from the memory LRU.

        A stream commit renames its graph content-addressed every window;
        without this, every window's result lingers in memory until
        capacity pressure — for a long-lived subscribed graph that is the
        whole LRU filled with dead ancestors. Disk entries stay (the
        bounded sweep handles those): a late query for an old chain link
        is still answerable, just not at the cost of memory.

        Kind variants ride along: analytics entries key as
        ``{digest}:{backend}:{kind}`` (see :func:`cache_key_for_digest`), so
        evicting the base ``{digest}:{backend}`` ancestor also drops every
        kind-variant sibling — a superseded graph's components/k-MSF answers
        are exactly as dead as its MST. Returns whether any entry was
        dropped (``serve.store.chain_evicted`` counts each).
        """
        dropped = 0
        prefix = key + ":"
        with self._lock:
            victims = [
                k for k in self._mem if k == key or k.startswith(prefix)
            ]
            for k in victims:
                self._mem.pop(k, None)
                dropped += 1
        for _ in range(dropped):
            BUS.count("serve.store.chain_evicted")
        return dropped > 0

    def stats(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._mem),
                "capacity": self.capacity,
                "disk_dir": self.disk_dir,
            }

    # ------------------------------------------------------------------
    def _mem_put(self, key: str, result: MSTResult) -> None:
        with self._lock:
            self._mem[key] = result
            self._mem.move_to_end(key)
            while len(self._mem) > self.capacity:
                self._mem.popitem(last=False)
                BUS.count("serve.store.evict")

    def _disk_put(self, key: str, result: MSTResult) -> None:
        from distributed_ghs_implementation_tpu.utils.checkpoint import (
            atomic_write_npz,
        )

        path = _disk_path(self.disk_dir, key)
        with _flocked(path):
            atomic_write_npz(
                path,
                {
                    "digest": result.graph.digest_words(),
                    "edge_ids": result.edge_ids,
                    "num_levels": result.num_levels,
                    "num_components": result.num_components,
                    "backend": np.asarray(result.backend),
                },
                fault_site="serve.store.save",
            )

    def _disk_sweep(self) -> None:
        """Bound the disk layer: drop the oldest entries (and their ``.bak``
        generations) past ``disk_max_entries`` — an update stream re-keys to
        a new digest per batch, so without GC the directory grows forever."""
        entries = [
            e for e in os.scandir(self.disk_dir) if e.name.endswith(".npz")
        ]
        if len(entries) <= self.disk_max_entries:
            return
        entries.sort(key=lambda e: e.stat().st_mtime)
        for entry in entries[: len(entries) - self.disk_max_entries]:
            for path in (
                entry.path, entry.path + ".bak",
                entry.path + ".sha256", entry.path + ".bak.sha256",
            ):
                # Concurrent workers sweep the shared directory too — a
                # sibling winning the unlink race is success, not an error.
                with contextlib.suppress(FileNotFoundError):
                    os.unlink(path)
            self._sweep_lock_file(entry.path + ".lock")
            BUS.count("serve.store.disk_evict")

    @staticmethod
    def _sweep_lock_file(lock_path: str) -> None:
        """GC an evicted entry's lock file — but only while HOLDING it.

        Unlinking a lock file someone else holds (or is about to flock)
        would let two writers lock different inodes of the same name and
        interleave a publish; :func:`_flocked` re-validates its inode
        after acquiring, which makes this held-then-unlink safe. A busy
        lock is simply left behind (tiny, retried next sweep)."""
        if fcntl is None:
            return
        try:
            fd = os.open(lock_path, os.O_RDWR)
        except FileNotFoundError:
            return
        try:
            try:
                fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
            except OSError:
                return  # a writer holds it: not ours to reap
            with contextlib.suppress(FileNotFoundError):
                os.unlink(lock_path)
        finally:
            os.close(fd)

    def _disk_get(self, key: str, graph: Graph) -> Optional[MSTResult]:
        """One disk probe, with the failure modes told apart (round 19):

        * **ENOENT** — a plain miss: never counted as corruption.
        * **checksum mismatch** (``utils/integrity.py`` sidecar) — the
          bytes rotted after the commit point: quarantine the file
          (``.quarantine/``, ``serve.store.quarantined``) WITHOUT parsing
          it, try the ``.bak`` generation, degrade to a miss.
        * **torn/corrupt npz** (no sidecar to catch it — a legacy or
          crash-window file) — ``np.load`` failures quarantine the same
          way; they are corruption, not a miss, and must never raise out
          of :meth:`get`.
        * **digest mismatch** — a different graph collided on the
          filename: not corruption, just not our entry.
        """
        from distributed_ghs_implementation_tpu.utils.integrity import (
            IntegrityError,
            check_file,
            quarantine,
        )

        path = _disk_path(self.disk_dir, key)
        for candidate in (path, path + ".bak"):
            if not os.path.exists(candidate):
                continue
            try:
                if check_file(candidate) == "unverified":
                    BUS.count("serve.store.unverified")
            except FileNotFoundError:
                continue  # lost a race with a sweep: a miss, not corruption
            except IntegrityError as e:
                quarantine(
                    candidate, reason=str(e),
                    counter="serve.store.quarantined",
                )
                continue
            try:
                with np.load(candidate) as data:
                    stored = np.asarray(data["digest"])
                    if not np.array_equal(stored, graph.digest_words()):
                        continue  # a different graph collided on the filename
                    return MSTResult(
                        graph=graph,
                        edge_ids=np.asarray(data["edge_ids"]),
                        num_levels=int(data["num_levels"]),
                        wall_time_s=0.0,
                        backend=str(data["backend"]),
                        num_components=int(data["num_components"]),
                    )
            except Exception as e:  # noqa: BLE001 — torn/corrupt npz
                quarantine(
                    candidate, reason=f"{type(e).__name__}: {e}",
                    counter="serve.store.quarantined",
                )
                continue
        return None
