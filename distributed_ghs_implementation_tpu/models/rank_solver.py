"""Rank-space Borůvka solver — the fast single-chip path.

Profiling on the real chip (tools/profile_ops.py, tools/profile_micro.py)
drove every choice here:

  * random gathers cost ~7.6 ns/elem, scatters carry ~90 ms of fixed overhead
    each, and a device dispatch round-trip is ~114 ms on this setup — so the
    design minimizes *edge-sized memory traffic*, *scatter count*, and
    *dispatches*, in that order;
  * on RMAT graphs level 2 retires ~94% of all edges (levels 3+ are nearly
    free if the arrays shrink), while on bounded-degree (road-like) graphs
    level 1 already retires most edges.

Structure:

  * **Level 1 costs nothing on device.** At the identity partition every
    incident edge is outgoing, so each vertex's minimum outgoing edge is its
    minimum-rank incident edge — precomputed on the host in one O(m) native
    pass (``Graph.first_ranks``). The device does only n-sized hooking.
  * **Rank space, not slot space.** State per undirected rank r is its two
    current fragment endpoints ``(fa[r], fb[r])`` — half the directed-slot
    count of the flat kernel, no ELL padding, and the rank index itself is
    the tie-break total order (weights never reach the device).
  * **Both spaces shrink.** Finish chunks stream-compact the surviving
    slots AND (census + dense renumber) the live fragment id space, so late
    levels cost O(alive) instead of O(n); vertex labels come back via one
    replay pass. A ``_pick_family`` policy (sparse/grid/dense by average
    degree) sets head depth and chunk length, and dense graphs take a
    speculative single-round-trip finish with a misprediction fallback.

Protocol parity: each level is one GHS round (TEST/ACCEPT/REJECT + REPORT =
the segment_min; CONNECT/INITIATE/CHANGEROOT = ``hook_and_compress``; BRANCH
marking = the mst scatter) — ``/root/reference/ghs_implementation.py:118-413``,
SURVEY.md §3.4.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from distributed_ghs_implementation_tpu.graphs.edgelist import Graph
from distributed_ghs_implementation_tpu.models.boruvka import (
    _COMPACT_MIN_SLOTS,
    _bucket_size,
    _max_levels,
)
from distributed_ghs_implementation_tpu.ops.segment_ops import INT32_MAX
from distributed_ghs_implementation_tpu.ops.union_find import hook_and_compress


def _moe_over(fa, fb, key, n):
    """Per-fragment min key over both edge directions (one segment_min).

    Measured: one concatenated segment_min beats two half-width ones up to
    RMAT-24 width (39.1 s vs 41.0 s full solve) — the scatter's fixed cost
    outweighs the concatenation temporaries. Above 2^28 slots (RMAT-25
    class) the ~2x slot-sized concat temporaries push a 16 GB chip into
    RESOURCE_EXHAUSTED, so the two-pass form takes over there.
    """
    if fa.shape[0] > (1 << 28):
        return jnp.minimum(
            jax.ops.segment_min(key, fa, num_segments=n),
            jax.ops.segment_min(key, fb, num_segments=n),
        )
    return jax.ops.segment_min(
        jnp.concatenate([key, key]), jnp.concatenate([fa, fb]), num_segments=n
    )


def _level1_hook(vmin0, ra, rb):
    """Level 1 (traced helper shared by both heads): hook every vertex on its
    host-precomputed minimum incident rank. Returns ``(fragment, parent1,
    has1, safe1)``."""
    n = vmin0.shape[0]
    ids = jnp.arange(n, dtype=jnp.int32)
    has1 = vmin0 < INT32_MAX
    safe1 = jnp.where(has1, vmin0, 0)
    a = ra[safe1]
    b = rb[safe1]
    dst1 = jnp.where(has1, jnp.where(a == ids, b, a), ids)
    fragment, parent1 = hook_and_compress(has1, dst1, ids)
    return fragment, parent1, has1, safe1


def host_level1(vmin0: np.ndarray, ra: np.ndarray, rb: np.ndarray) -> np.ndarray:
    """Level-1 partition computed on the HOST during prep — the completion
    of "level 1 costs nothing on device": the hook edges are already the
    host-precomputed ``first_ranks``, so the hook-and-compress union-find
    over them (the r4 bisection's 1.83 s of device pointer-chasing at
    RMAT-24) is a ~1 s numpy pass off the solve clock instead.

    Bit-exact replica of the device semantics (``_level1_hook`` ->
    ``hook_and_compress``): same hook destinations, same mutual-pair break
    (smaller id self-roots), pointer jumping to fixpoint — asserted
    element-identical against the device in tests.
    """
    n = vmin0.shape[0]
    ids = np.arange(n, dtype=np.int32)
    # Sentinel follows the dtype: int32 vmin0 uses INT32_MAX, the sharded
    # rank64 path stages int64 vmin0 with an INT64_MAX sentinel.
    has1 = vmin0 < np.iinfo(vmin0.dtype).max
    safe1 = np.where(has1, vmin0, 0)
    a = ra[safe1]
    b = rb[safe1]
    parent = np.where(has1, np.where(a == ids, b, a), ids).astype(np.int32)
    return _host_break_and_jump(parent, "host_level1")


def _host_break_and_jump(parent: np.ndarray, what: str) -> np.ndarray:
    """Mutual-pair break + bounded pointer jumping — the shared tail of the
    host level passes (bit-exact numpy replica of ``break_symmetric_hooks``
    + ``pointer_jump``). Hook forests with the mutual pair broken converge
    in <= ceil(log2 n)+1 jumps; malformed hook input can produce longer
    cycles — the bound turns a host hang into a loud error. (Cycles whose
    length divides a power of two still collapse silently under squaring:
    this is a hang guard, not full input validation.)"""
    n = parent.shape[0]
    ids = np.arange(n, dtype=np.int32)
    mutual = parent[parent] == ids
    parent = np.where(mutual & (ids < parent), ids, parent)
    for _ in range(max(int(np.ceil(np.log2(max(n, 2)))) + 1, 1)):
        p2 = parent[parent]
        if np.array_equal(p2, parent):
            return parent
        parent = p2
    raise ValueError(
        f"{what} did not converge: hook input is not a true per-vertex/"
        f"per-fragment minimum (hook graph has a cycle longer than 2)"
    )


def host_level2(parent1: np.ndarray, ra: np.ndarray, rb: np.ndarray, m: int):
    """Level-2 partition computed on the HOST — one level deeper than
    :func:`host_level1`, for the road/grid family where the full-width
    device level 2 is the head's dominant cost (r4 bisection: the 23.9M
    road grid spends ~9 s of 14.5 s in the L1+L2 head).

    Replicates the device semantics exactly (``_level_core`` over the
    level-1 fragment space -> ``hook_and_compress``): per-fragment first
    CROSS rank (native fused relabel+scan; numpy fallback), hook with the
    mutual-pair break, bounded pointer jump. Returns ``(parent12,
    l2_ranks)`` — the composed 2-level vertex partition and the sorted
    MST rank ids level 2 chose (for one device scatter into the mask).
    ``m`` is the true (unpadded) edge count."""
    n = parent1.shape[0]
    int32_max = np.iinfo(np.int32).max
    moe2 = None
    try:
        from distributed_ghs_implementation_tpu.graphs import native

        if native.native_available():
            moe2 = native.first_cross_rank_native(
                n, ra[:m], rb[:m], parent1
            )
    except Exception:  # noqa: BLE001 — any native issue -> fallback
        pass
    if moe2 is None:
        fa = parent1[ra[:m]]
        fb = parent1[rb[:m]]
        cross = np.nonzero(fa != fb)[0]
        arr = np.empty(2 * cross.size, dtype=np.int64)
        arr[0::2] = fa[cross]
        arr[1::2] = fb[cross]
        frags, first_pos = np.unique(arr, return_index=True)
        moe2 = np.full(n, int32_max, dtype=np.int32)
        moe2[frags] = cross[first_pos // 2].astype(np.int32)
    has = moe2 < int32_max
    safe = np.where(has, moe2, 0)
    wa = parent1[ra[safe]]
    wb = parent1[rb[safe]]
    ids = np.arange(n, dtype=np.int32)
    parent = np.where(has, np.where(wa == ids, wb, wa), ids).astype(np.int32)
    parent = _host_break_and_jump(parent, "host_level2")
    return parent[parent1], np.unique(moe2[has])


def _pad_l2_ranks(l2r: np.ndarray, m_pad: int) -> np.ndarray:
    """Pad the level-2 mark ranks for staging. The pad value ``m_pad`` is
    load-bearing: every consumer tests ``l2_ranks < width`` and drops the
    rest, so the sentinel must exceed any real rank; the 1024 floor keeps
    tiny graphs off degenerate bucket sizes. One helper so the
    single-chip, sharded, and measurement paths cannot desynchronize."""
    l2_pad = _bucket_size(max(int(l2r.size), 1024))
    out = np.full(l2_pad, m_pad, dtype=np.int32)
    out[: l2r.size] = l2r
    return out


@jax.jit
def _device_level1(vmin0, ra, rb):
    """On-device fallback for callers that stage raw arrays without the
    host-computed level-1 parent (one extra dispatch vs the fused head)."""
    _fragment, parent1, _has1, _safe1 = _level1_hook(vmin0, ra, rb)
    return parent1


def _ensure_parent1(vmin0, ra, rb, parent1):
    if parent1 is None:
        return _device_level1(vmin0, ra, rb)
    return parent1


def _prefix_level2_core(fragment, fa, fb):
    """Level 2 over already-relabeled prefix slots (traced helper shared by
    the single-chip and sharded filtered heads). Returns ``(fragment, fa,
    fb, has2, safe2, count)`` — callers mark ``mst.at[safe2].max(has2)``
    into their own mask width (prefix slot index == global rank)."""
    n = fragment.shape[0]
    slot = jnp.arange(fa.shape[0], dtype=jnp.int32)
    key2 = jnp.where(fa != fb, slot, INT32_MAX)
    fragment, parent2, has2, safe2 = _level_core(fragment, fa, fb, key2, n)
    fa = parent2[fa]
    fb = parent2[fb]
    count = jnp.sum((fa != fb).astype(jnp.int32))
    return fragment, fa, fb, has2, safe2, count


def _level_core(fragment, fa, fb, key_of_slot, n, *, kernel="xla"):
    """MOE + hook for one level; returns (fragment2, parent, has, safe).

    ``kernel`` selects the fused Pallas hook+compress round
    (``ops/pallas_kernels.py``) — a static trace-time choice, identical
    results either way."""
    ids = jnp.arange(n, dtype=jnp.int32)
    moe = _moe_over(fa, fb, key_of_slot, n)
    has = moe < INT32_MAX
    safe = jnp.where(has, moe, 0)
    wa = fa[safe]
    wb = fb[safe]
    dst_frag = jnp.where(has, jnp.where(wa == ids, wb, wa), ids)
    fragment2, parent = hook_and_compress(has, dst_frag, fragment, kernel=kernel)
    return fragment2, parent, has, safe


@functools.partial(jax.jit, static_argnames=("compact_after",))
def _rank_head(vmin0, ra, rb, parent1, *, compact_after: int = 2):
    """Levels 1(+2) at full width, one dispatch. ``parent1`` is the level-1
    partition (host-precomputed in prep, or ``_device_level1``) — the head
    starts at the relabel, not the hook.

    Returns ``(fragment, mst, fa, fb, stats)`` with ``stats = [levels,
    alive_count]`` — the host reads stats in a single fetch and sizes the
    finish chunks exactly (no static budget, no overflow path).
    """
    n = vmin0.shape[0]
    mp = ra.shape[0]
    slot = jnp.arange(mp, dtype=jnp.int32)

    fragment = parent1
    has1 = vmin0 < INT32_MAX
    safe1 = jnp.where(has1, vmin0, 0)
    any1 = jnp.any(has1)

    # Relabel rank endpoints to level-1 fragments — 2 m-sized gathers, the
    # solve's dominant cost together with the level-2 segment_min.
    fa = parent1[ra]
    fb = parent1[rb]

    if compact_after >= 2:
        # ---- Level 2 at full width (RMAT-like graphs: retires ~94%).
        key2 = jnp.where(fa != fb, slot, INT32_MAX)
        fragment, parent2, has2, safe2 = _level_core(fragment, fa, fb, key2, n)
        fa = parent2[fa]
        fb = parent2[fb]
        # One combined MST scatter for levels 1+2.
        mst = (
            jnp.zeros(mp, dtype=bool)
            .at[jnp.concatenate([safe1, safe2])]
            .max(jnp.concatenate([has1, has2]))
        )
        lv = jnp.asarray(1, jnp.int32) + jnp.any(has2).astype(jnp.int32)
    else:
        # Road-like graphs: level 1 already retires most edges.
        mst = jnp.zeros(mp, dtype=bool).at[safe1].max(has1)
        lv = any1.astype(jnp.int32)

    count = jnp.sum((fa != fb).astype(jnp.int32))
    return fragment, mst, fa, fb, jnp.stack([lv, count])


def _compact_slots(fa, fb, rank_of_slot, out_size: int):
    """Order-preserving compaction of alive slots into ``out_size``: one
    scatter of positions, then out_size-sized gathers of the payloads. Dead
    slots scatter out of bounds (dropped); trailing pad slots come out with
    ``cfa == cfb == 0`` (inert). Order preservation keeps the local slot
    index a valid tie-break total order; ``crank`` carries the original rank
    for MST marking. Returns ``(cfa, cfb, crank, valid)``."""
    alive = fa != fb
    pos = jnp.cumsum(alive.astype(jnp.int32)) - 1
    idx = jnp.where(alive, pos, out_size)
    cpos = jnp.zeros(out_size, jnp.int32).at[idx].set(
        jnp.arange(fa.shape[0], dtype=jnp.int32), mode="drop"
    )
    in_count = jnp.sum(alive.astype(jnp.int32))
    valid = jnp.arange(out_size, dtype=jnp.int32) < in_count
    cfa = jnp.where(valid, fa[cpos], 0)
    cfb = jnp.where(valid, fb[cpos], 0)
    crank = rank_of_slot[cpos]
    return cfa, cfb, crank, valid


@functools.partial(
    jax.jit, static_argnames=("out_size", "chunk_levels"), donate_argnums=(1,)
)
def _finish_chunk(
    fragment, mst, fa, fb, rank_of_slot, *, out_size: int, chunk_levels: int = 3
):
    """Compact the surviving slots to ``out_size`` and run up to
    ``chunk_levels`` more levels; one dispatch.

    Chained across calls by the host, which re-sizes ``out_size`` from the
    returned survivor count — so high-diameter graphs (12-14 levels on road
    grids) shed width as they go instead of paying the first compaction's
    width every remaining level. Order-preserving compaction keeps the local
    slot index a valid tie-break total order; ``rank_of_slot`` carries the
    original rank through the chain for MST marking.

    ``mst`` is DONATED (as in ``_shrink_and_run``/``_run_levels``): the
    functional ``.at[].max`` update would otherwise copy the full-width
    mask every chunk (~268 MB at RMAT-24, measured in the r4 bisection);
    callers must treat the passed buffer as consumed and rebind from the
    return, as ``_finish_to_fixpoint`` does.

    Returns ``(fragment, mst, cfa, cfb, crank, stats)`` with ``stats =
    [levels_run, alive_count]``.
    """
    cfa, cfb, crank, valid = _compact_slots(fa, fb, rank_of_slot, out_size)
    fragment, mst, cfa, cfb, stats = _levels_loop(
        fragment, mst, cfa, cfb, crank, chunk_levels=chunk_levels
    )
    return fragment, mst, cfa, cfb, crank, stats


# ---------------------------------------------------------------------------
# Compact fragment space — the high-diameter fix.
#
# After the head, a 4096^2 road grid still has ~13 levels to run, and in the
# original space each costs O(n_pad) — pointer jumps over 33M-entry parent
# arrays and segment-min outputs with 33M segments — even when only a few
# hundred thousand fragments are still merging (measured 84-108 s end to end).
# The fix: number the live roots densely once (census + cumsum), run every
# finish level in that F-sized space, and expand the vertex labels back in one
# n-sized pass at the end. Per-level cost drops from O(n_pad + alive) to
# O(F + alive).
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnames=("n", "out_size"))
def _compact_and_mark(fa, fb, rank_of_slot, *, n: int, out_size: int):
    """Slot compaction plus live-root census, one dispatch.

    Beyond ``_finish_chunk``'s order-preserving slot compaction this marks
    every root appearing on an alive slot and numbers the marked roots densely
    (``newid`` = cumsum of marks). The host reads ``F`` (live-root count) from
    ``stats`` and decides whether a compact-space finish is worth it. Returns
    ``(cfa_o, cfb_o, crank, mark, newid, stats)`` with endpoints still in the
    original root space.
    """
    cfa_o, cfb_o, crank, valid = _compact_slots(fa, fb, rank_of_slot, out_size)
    mark = (
        jnp.zeros(n, bool)
        .at[jnp.where(valid, cfa_o, n)].set(True, mode="drop")
        .at[jnp.where(valid, cfb_o, n)].set(True, mode="drop")
    )
    cums = jnp.cumsum(mark.astype(jnp.int32))
    newid = cums - 1
    stats = jnp.stack([cums[-1], jnp.sum(valid.astype(jnp.int32))])
    return cfa_o, cfb_o, crank, mark, newid, stats


@functools.partial(
    jax.jit, static_argnames=("f_size", "chunk_levels"), donate_argnums=(3,)
)
def _shrink_and_run(
    mark, newid, rep_prev, mst, cfa_o, cfb_o, crank, *, f_size: int, chunk_levels: int
):
    """Relabel alive slots into the dense root space and run the next
    ``chunk_levels`` finish levels there; one dispatch.

    ``rep[f] ->`` ORIGINAL root id of compact id ``f``: the shrink-local
    back-map composed through ``rep_prev`` (the previous space's rep; the
    identity iota at the first shrink). The compact fragment state starts at
    the identity — every compact id is its own root.
    """
    space = mark.shape[0]
    iota_s = jnp.arange(space, dtype=jnp.int32)
    back = jnp.zeros(f_size, jnp.int32).at[jnp.where(mark, newid, f_size)].set(
        iota_s, mode="drop"
    )
    rep = rep_prev[back]
    cfa = newid[cfa_o]
    cfb = newid[cfb_o]
    # Padding slots have cfa_o == cfb_o == 0, so cfa == cfb: inert.
    cfrag = jnp.arange(f_size, dtype=jnp.int32)
    cfrag, mst, cfa, cfb, stats = _levels_loop(
        cfrag, mst, cfa, cfb, crank, chunk_levels=chunk_levels
    )
    return rep, cfrag, mst, cfa, cfb, stats


def _levels_loop(fragment, mst, cfa, cfb, crank, *, chunk_levels: int):
    """Up to ``chunk_levels`` levels over already-compacted slots (traced
    helper shared by ``_finish_chunk`` and ``_shrink_and_run``)."""
    n = fragment.shape[0]
    cslot = jnp.arange(cfa.shape[0], dtype=jnp.int32)
    in_count = jnp.sum((cfa != cfb).astype(jnp.int32))

    def cond(s):
        return s[4] & (s[5] < chunk_levels)

    def body(s):
        fragment, mst, cfa, cfb, _, k = s
        key = jnp.where(cfa != cfb, cslot, INT32_MAX)
        fragment, parent, has, safe = _level_core(fragment, cfa, cfb, key, n)
        mst = mst.at[crank[safe]].max(has)
        return (fragment, mst, parent[cfa], parent[cfb], jnp.any(has), k + 1)

    state = (fragment, mst, cfa, cfb, in_count > 0, jnp.zeros((), jnp.int32))
    fragment, mst, cfa, cfb, _, k = jax.lax.while_loop(cond, body, state)
    count = jnp.sum((cfa != cfb).astype(jnp.int32))
    return fragment, mst, cfa, cfb, jnp.stack([k, count])


@functools.partial(
    jax.jit, static_argnames=("chunk_levels",), donate_argnums=(1,)
)
def _run_levels(fragment, mst, cfa, cfb, crank, *, chunk_levels: int):
    """Levels over already-compacted slots, no re-compaction; one dispatch."""
    return _levels_loop(fragment, mst, cfa, cfb, crank, chunk_levels=chunk_levels)


def _replay_stages(fragment, stages):
    """Final vertex relabel after a shrink chain.

    ``stages`` is one tuple per shrink event, in order:
    ``(mark_k, newid_k, rep_k, cfrag_k_final)`` — the census over the previous
    space, the composed compact->original map, and the compact fragment state
    as of the NEXT shrink (or loop end). The walk runs in the FIRST compact
    space (f1-sized gathers per stage, shrinking), with a single n-sized
    expansion at the end; a root that goes dead at stage k keeps the original
    label it had there. Dispatch count is O(#shrinks), run once per solve.
    """
    if not stages:
        return fragment
    mark1, newid1, rep1, cfrag1 = stages[0]
    cur = cfrag1  # S1 id -> its root in S1 after stage-1 levels
    res = rep1[cur]  # original-space label if it dies here
    alive = jnp.ones(cur.shape[0], bool)
    for mark_k, newid_k, rep_k, cfrag_k in stages[1:]:
        # `alive` guards dead entries whose stale old-space ids could alias a
        # marked id in the newer (denser) space.
        live = alive & mark_k[cur]
        j = cfrag_k[jnp.where(live, newid_k[cur], 0)]
        res = jnp.where(live, rep_k[j], res)
        cur = jnp.where(live, j, cur)
        alive = live
    live1 = mark1[fragment]
    return jnp.where(live1, res[jnp.where(live1, newid1[fragment], 0)], fragment)


# The rank-space int32 envelope. Every device index — rank ids (the
# tie-break total order), vertex ids, compact slot ids — is int32, and
# INT32_MAX itself is the "no edge" sentinel, so padded sizes must stay
# strictly below 2^31. Measured ceiling: RMAT-26 (2^30 padded ranks,
# ~8.6 GB of resident ra/rb on a 16 GB chip, solved in 93.8 s); one more
# scale step leaves the envelope everywhere at once (docs/SCALING.md).
_INT32_RANK_LIMIT = 1 << 31


def check_rank_envelope(n_pad: int, m_pad: int) -> None:
    """Fail fast — at staging, with the ceiling in the message — instead of
    somewhere deep in the level loop with an overflow-corrupted index.

    This guards the SINGLE-CHIP int32 paths. Past 2^31 ranks the sharded
    path lifts the envelope with int64 rank keys
    (``solve_graph_rank_sharded(..., rank64=True)``, auto-enabled at
    2^31 padded ranks) — keys go int64 on n-sized and survivor-sized
    arrays only; the edge-sized ``ra``/``rb`` hold vertex ids and stay
    int32. Per-chip HBM math and the pod ceiling live in docs/SCALING.md
    ("Past int32"). Vertex counts past 2^31 stay unsupported everywhere."""
    if m_pad >= _INT32_RANK_LIMIT or n_pad >= _INT32_RANK_LIMIT:
        raise ValueError(
            f"graph exceeds the int32 rank envelope: padded sizes "
            f"(nodes {n_pad:,}, ranks {m_pad:,}) must stay below 2^31 = "
            f"{_INT32_RANK_LIMIT:,}. The measured single-chip ceiling is "
            f"RMAT-26 (~1.05B edges, 2^30 padded ranks). Past it, use the "
            f"mesh path — solve_graph_rank_sharded enables int64 rank "
            f"keys (rank64) automatically at 2^31 padded ranks; see "
            f"docs/SCALING.md 'Past int32' for the per-chip HBM budget "
            f"and the pod-scale ceiling."
        )


def prepare_rank_arrays(graph: Graph):
    """Host->device staging: ``(vmin0, ra, rb)`` jnp arrays, padded to
    quarter-step bucket sizes (``_bucket_size``).

    Host cost: one native counting sort for ranks, one O(m) native pass for
    ``first_ranks``, and the level-1 union-find (:func:`host_level1`,
    ~1.5 s at RMAT-24) — no CSR, no ELL buckets. This 3-tuple form is the
    raw-array compatibility surface; production entries use
    :func:`prepare_rank_arrays_full`, which also returns the staged level-1
    partition the host pass produced.

    The staged device arrays are cached on the graph (repeat solves skip the
    host->device upload — ~400 MB / ~15 s at 34M edges on a tunneled chip),
    capped at ``_STAGE_CACHE_MAX_RANKS`` so a sequence of huge solves can't
    pin HBM for the lifetime of every Graph a caller keeps a reference to
    (an RMAT-24-scale cache entry would hold ~2 GB of device memory across
    the three rank arrays plus the n-sized ``parent1``).
    """
    return prepare_rank_arrays_full(graph)[:3]


@jax.jit
def _decode_planes24(packed):
    """Six byte-planes (one flat uint8 buffer) -> two int32 arrays on
    device. Planar layout because TPU tiling pads small minor dims: a
    ``(m, 3)`` uint8 reshape would tile to 128 lanes (43x blowup, compile
    OOM); flat 1-D slices at plane boundaries stay dense. (No donation:
    input/output sizes differ, the buffer can't alias — its HBM frees
    when the caller drops the reference after this returns.)"""
    w = packed.shape[0] // 6
    planes = [
        packed[i * w:(i + 1) * w].astype(jnp.int32) for i in range(6)
    ]
    ra = planes[0] | (planes[1] << 8) | (planes[2] << 16)
    rb = planes[3] | (planes[4] << 8) | (planes[5] << 16)
    return ra, rb


def _stage_pair_packed24(ra: np.ndarray, rb: np.ndarray):
    """Host int32 pair (values < 2^24) -> device int32 pair over a 3-byte
    wire format: strip each little-endian int32 to its low 3 bytes, laid
    out as six contiguous byte-planes in ONE uint8 buffer (one transfer —
    chunked puts measured far worse than a single large one), then decode
    on device. The tunnel link (~25 MB/s measured) prices every byte, so
    the 25% cut is ~5 s at RMAT-22, ~20 s at RMAT-24."""
    assert ra.dtype == np.int32 and rb.dtype == np.int32
    w = ra.shape[0]
    packed = np.empty(6 * w, dtype=np.uint8)
    for i, (arr, base) in enumerate(((ra, 0), (rb, 3 * w))):
        bytes_ = arr.view(np.uint8)
        for k in range(3):
            packed[base + k * w:base + (k + 1) * w] = bytes_[k::4]
    return _decode_planes24(jax.device_put(packed))


def _prep_head(graph: Graph):
    """The shared prep head of :func:`prepare_rank_arrays_full` and
    :func:`prepare_rank_arrays_l2`: endpoints built and staged
    transfer-first, ``vmin0`` and the level-1 partition computed UNDER the
    transfers. Returns ``(n, m, n_pad, m_pad, ra, rb, vmin0, parent1,
    sa, sb)`` — host arrays plus the staged (in-flight) endpoint pair.

    Ordering rationale (r5): ``jax.device_put`` is async and the transfer
    is link-bound, not host-CPU-bound, so host compute underneath is ~free
    (measured: 256 MB put returns in 0.3 s, completes in ~12 s, and 10 s
    of host numpy under it costs +0.8 s total). The staged endpoint pair
    is cached on the graph so the full and l2 preps never duplicate the
    expensive edge-sized transfer."""
    n = graph.num_nodes
    m = graph.num_edges
    n_pad = _bucket_size(n)
    m_pad = _bucket_size(m)
    check_rank_envelope(n_pad, m_pad)
    pair = graph.__dict__.get("_rank_endpoint_stage")
    ra = rb = None
    if pair is None and n <= (1 << 24) and m:
        # Endpoint ids fit 24 bits: ship 3 bytes/elem and decode on device
        # — 25% less wire time on the two arrays that dominate prep. The
        # fused native pass emits the int32 endpoints (for the host
        # levels) AND the byte-plane wire buffer in one sweep, skipping a
        # full re-read/re-write of both arrays on the pre-transfer
        # critical path.
        planes = None
        try:
            from distributed_ghs_implementation_tpu.graphs import native

            if native.native_available():
                ra, rb, planes = native.rank_endpoints_i32_planes_native(
                    graph._rank_order, graph.u, graph.v, m_pad
                )
        except Exception:  # noqa: BLE001 — any native issue -> fallback
            ra = rb = planes = None
        if planes is not None:
            # Outside the try: a JAX/device failure here should surface
            # from THIS path (and the valid ra/rb are kept either way),
            # not be masked by a doomed equally-sized retry below.
            pair = _decode_planes24(jax.device_put(planes))
    if ra is None:
        ra, rb = graph.rank_endpoints(pad_to=m_pad)
    if pair is None:
        if n <= (1 << 24):
            pair = _stage_pair_packed24(ra, rb)
        else:
            pair = (jax.device_put(ra), jax.device_put(rb))
    if (
        "_rank_endpoint_stage" not in graph.__dict__
        and m_pad <= _STAGE_CACHE_MAX_RANKS
    ):
        graph.__dict__["_rank_endpoint_stage"] = pair
    sa, sb = pair
    # --- everything below here overlaps the ra/rb transfers ---
    vmin0 = np.full(n_pad, np.iinfo(np.int32).max, dtype=np.int32)
    if "first_ranks" not in graph.__dict__ and m:
        try:
            from distributed_ghs_implementation_tpu.graphs import native

            if native.native_available():
                # Same values as Graph.first_ranks, skipping its re-gather
                # of the endpoints; populate the property cache.
                graph.__dict__["first_ranks"] = native.first_rank_i32_native(
                    n, ra[:m], rb[:m]
                )
        except Exception:  # noqa: BLE001 — any native issue -> fallback
            pass
    vmin0[:n] = graph.first_ranks
    parent1 = host_level1(vmin0, ra, rb)
    return n, m, n_pad, m_pad, ra, rb, vmin0, parent1, sa, sb


def prepare_rank_arrays_full(graph: Graph):
    """:func:`prepare_rank_arrays` plus the host-computed level-1 partition:
    ``(vmin0, ra, rb, parent1)`` staged — see :func:`_prep_head` for the
    transfer-overlap design. The production entries pass ``parent1`` to
    the solvers so the head starts at the relabel (the r4 L1 host
    precompute; :func:`host_level1`). Returns only after a tiny sync fetch
    per array, so a caller's prep clock honestly includes transfer
    completion."""
    cached = graph.__dict__.get("_rank_device_cache")
    if cached is not None:
        return cached
    n, m, n_pad, m_pad, ra, rb, vmin0, parent1, sa, sb = _prep_head(graph)
    sv = jax.device_put(vmin0)
    sp = jax.device_put(parent1)
    staged = (sv, sa, sb, sp)
    for leaf in staged:
        _ = np.asarray(leaf[:1])  # sync: prep ends when the data is resident
    if m_pad <= _STAGE_CACHE_MAX_RANKS:
        # Graph is a frozen dataclass; write the cache the way cached_property
        # does (directly into __dict__, bypassing the frozen __setattr__).
        graph.__dict__["_rank_device_cache"] = staged
    return staged


def prepare_rank_arrays_l2(graph: Graph):
    """:func:`prepare_rank_arrays_full` with HOST LEVEL 2 (the road/grid
    family fast path): ``(vmin0, ra, rb, parent12, l2_ranks)`` staged.

    Same transfer-first overlap as the full prep — the extra host pass
    (:func:`host_level2`) runs underneath the edge-sized stagings, and the
    extra wire traffic vs the full prep is only the compacted level-2 mark
    ranks (``parent12`` replaces ``parent1``, same bytes). Measured on the
    23.9M-node road grid (r5): the device solve drops 14.6 -> 9.7 s
    (byte-identical, oracle-verified) because the head's full-width level-2
    relabel + segment_min never runs on device.

    ``l2_ranks`` is padded with ``m_pad`` (out of range — dropped by the
    head's scatter), so an empty level 2 stays correct."""
    cached = graph.__dict__.get("_rank_device_cache_l2")
    if cached is not None:
        return cached
    n, m, n_pad, m_pad, ra, rb, vmin0, parent1, sa, sb = _prep_head(graph)
    parent12, l2r = host_level2(parent1, ra, rb, m)
    l2_staged = _pad_l2_ranks(l2r, m_pad)
    sv = jax.device_put(vmin0)
    sp = jax.device_put(parent12)
    sl = jax.device_put(l2_staged)
    staged = (sv, sa, sb, sp, sl)
    for leaf in staged:
        _ = np.asarray(leaf[:1])  # sync: prep ends when the data is resident
    if m_pad <= _STAGE_CACHE_MAX_RANKS:
        graph.__dict__["_rank_device_cache_l2"] = staged
    return staged


# Cache staged arrays only below ~0.5 GB of device memory per graph.
_STAGE_CACHE_MAX_RANKS = 1 << 26


def _pick_family(graph: Graph) -> str:
    """Graph-family policy for the staged solver.

    * ``"sparse"`` (avg degree <= 3: paths, trees, real road networks —
      USA-road is ~2.4): level 1 retires most edges; a full-width level 2
      would be a wasted pass. Short finish chunks.
    * ``"grid"`` (3 < avg degree <= 8: grids, meshes): level 2 at full width
      pays off (measured 11.8 s vs 12.6 s on a 4096^2 grid), but survivor
      counts stay too high for the speculative m/8 width. Short chunks.
    * ``"dense"`` (avg degree > 8: RMAT, ER at bench densities): level 2
      retires ~94%; speculative single-round-trip finish when the fragment
      space is under the census threshold.
    """
    avg_degree = 2.0 * graph.num_edges / max(graph.num_nodes, 1)
    if avg_degree <= 3.0:
        return "sparse"
    return "grid" if avg_degree <= 8.0 else "dense"


def _pick_compact_after(graph: Graph) -> int:
    """Head depth for :func:`_pick_family`'s choice."""
    return _family_params(_pick_family(graph))["compact_after"]


def _family_params(family: str) -> dict:
    """Staged-solver knobs for a :func:`_pick_family` choice — the single
    source shared by ``solve_rank_auto``, the checkpoint path, and the
    instrumented-metrics path (measured rationale in ``_pick_family``)."""
    return dict(
        compact_after=1 if family == "sparse" else 2,
        chunk_levels=3 if family == "dense" else 2,
        compact_space=True if family != "dense" else None,
    )


# Below this fragment-space size a shrink buys nothing (level cost is all
# fixed overhead); also the floor for census-worthiness.
_SHRINK_MIN_SPACE = 1 << 15

# Vertex-space size above which the census/compact-space finish pays for
# itself on dense graphs (measured at RMAT-24: plain finish 9.6 s vs census
# 2.8 s + compact finish 1.1 s).
_CENSUS_MIN_SPACE = 1 << 21

# Compacted widths at or below this run all remaining levels in one dispatch
# (the level loop exits early on convergence, so the only cost of a long
# chunk at small width is skipped re-compaction — negligible there).
_ONE_SHOT_MAX_SLOTS = 1 << 22


@jax.jit
def _relabel_slots(fragment, ra, rb):
    """Resume path: rebuild slot endpoints from a restored vertex partition."""
    fa = fragment[ra]
    fb = fragment[rb]
    return fa, fb, jnp.sum((fa != fb).astype(jnp.int32))


def _restore_state_host(initial_state, n_pad: int, m_pad: int):
    """Checkpoint state -> host arrays ``(fragment, mask, lv)`` at the
    current padded sizes. Tolerates a checkpoint written under different
    padding (bucket retune, or another backend's pad unit): pad vertices
    never hook (sentinel ``vmin0``) and pad ranks are never marked, so a
    too-long stored tail is identity/False and truncation is exact; a
    too-short one is re-extended with the identity. Shared by the
    single-chip and sharded resume paths."""
    fragment = np.asarray(initial_state[0], dtype=np.int32)
    if fragment.shape[0] < n_pad:
        fragment = np.concatenate(
            [fragment, np.arange(fragment.shape[0], n_pad, dtype=np.int32)]
        )
    elif fragment.shape[0] > n_pad:
        fragment = fragment[:n_pad]
    mask = np.asarray(initial_state[1], dtype=bool)
    if mask.shape[0] != m_pad:
        fixed = np.zeros(m_pad, dtype=bool)
        fixed[: min(mask.shape[0], m_pad)] = mask[:m_pad]
        mask = fixed
    return fragment, mask, int(initial_state[2])


def _restore_state(initial_state, n_pad: int, m_pad: int):
    """Device-array form of :func:`_restore_state_host`."""
    fragment, mask, lv = _restore_state_host(initial_state, n_pad, m_pad)
    return jnp.asarray(fragment), jnp.asarray(mask), lv


def solve_rank_resume(
    vmin0, ra, rb, initial_state, *, family: str = "dense", on_chunk=None
) -> Tuple[jax.Array, jax.Array, int]:
    """Resume a rank-space solve from checkpoint state (exact from any saved
    partition — the remaining work is Borůvka from that partition).

    Below the chunked-filter capacity regime this is
    :func:`solve_rank_staged`'s ``initial_state`` path (one full-width
    endpoint rebuild). At widths where suffix-size ``fa/fb`` cannot sit next
    to the resident rank arrays (the regime the chunked filter exists for —
    RMAT-26's ra/rb alone are ~8.6 GB on a 16 GB chip), a full-width
    ``_relabel_slots`` would RESOURCE_EXHAUSTED exactly where checkpointing
    matters most; instead the alive slots are rebuilt in rank-ordered chunks
    against the restored partition (reusing the chunked filter machinery — a
    slot is alive iff its endpoints' fragments differ) and the compacted
    survivors feed straight into the finish loop.
    """
    params = _family_params(family)
    n_pad = vmin0.shape[0]
    m_pad = ra.shape[0]
    if 8 * m_pad <= _FILTER_CHUNK_BYTES:
        return solve_rank_staged(
            vmin0, ra, rb, **params,
            initial_state=initial_state, on_chunk=on_chunk,
        )
    fragment, mst, lv = _restore_state(initial_state, n_pad, m_pad)
    cfa, cfb, crank, count = _filter_suffix_chunked(fragment, ra, rb, 0)
    if count == 0:
        return mst, fragment, lv
    compact_space = params["compact_space"]
    if compact_space is None:
        compact_space = n_pad >= _CENSUS_MIN_SPACE
    return _finish_to_fixpoint(
        fragment, mst, cfa, cfb, crank,
        lv=lv, count=count, space=n_pad, max_levels=lv + _max_levels(n_pad),
        chunk_levels=params["chunk_levels"], compact_space=compact_space,
        on_chunk=on_chunk,
    )


def solve_rank_speculative(
    vmin0, ra, rb, *, out_size: int, parent1=None
) -> Tuple[jax.Array, jax.Array, int] | None:
    """RMAT-shape fast path: head + one full finish chunk dispatched
    back-to-back with a *predicted* survivor width, then a single combined
    stats fetch — one host round trip instead of two (~0.12 s each on a
    tunneled chip, ~13% of an RMAT-20 solve).

    On RMAT-like graphs level 2 retires ~94% of edges, so ``out_size ~= m/8``
    is a safe overestimate. If the prediction was too small (slot compaction
    would have dropped survivors) or the chunk did not converge, returns
    ``None`` — caller falls back to the exact staged loop. Results are
    bit-identical to the staged path when accepted.
    """
    n_pad = vmin0.shape[0]
    parent1 = _ensure_parent1(vmin0, ra, rb, parent1)
    fragment, mst, fa, fb, stats = _rank_head(
        vmin0, ra, rb, parent1, compact_after=2
    )
    rank_of_slot = jnp.arange(ra.shape[0], dtype=jnp.int32)
    fragment2, mst2, cfa, cfb, crank, stats2 = _finish_chunk(
        fragment, mst, fa, fb, rank_of_slot,
        out_size=out_size, chunk_levels=_max_levels(n_pad),
    )
    (lv, count), (extra, count2) = (
        tuple(int(x) for x in jax.device_get(s)) for s in (stats, stats2)
    )
    if count <= out_size and count2 == 0:
        return mst2, fragment2, lv + extra
    return None


@jax.jit
def _head_l2(vmin0, ra, rb, parent12, l2_ranks):
    """Level-3 entry for the host-L2 prep: one relabel by the 2-level host
    partition plus the L1+L2 mark scatters — no edge-width segment_min.
    Returns ``(mst, fa, fb, stats)`` with ``stats = [levels, alive]``."""
    mp = ra.shape[0]
    fa = parent12[ra]
    fb = parent12[rb]
    has1 = vmin0 < INT32_MAX
    safe1 = jnp.where(has1, vmin0, 0)
    mst = jnp.zeros(mp, dtype=bool).at[safe1].max(has1)
    has2 = l2_ranks < mp  # pads carry m_pad and are dropped
    mst = mst.at[jnp.where(has2, l2_ranks, mp)].max(has2, mode="drop")
    lv = jnp.any(has1).astype(jnp.int32) + jnp.any(has2).astype(jnp.int32)
    count = jnp.sum((fa != fb).astype(jnp.int32))
    return mst, fa, fb, jnp.stack([lv, count])


def solve_rank_l2(
    vmin0,
    ra,
    rb,
    parent12,
    l2_ranks,
    *,
    chunk_levels: int = 2,
    compact_space: bool = True,
    on_chunk=None,
) -> Tuple[jax.Array, jax.Array, int]:
    """Solve from the host 2-level partition (:func:`prepare_rank_arrays_l2`
    — the road/grid family path). Bit-identical to ``solve_rank_staged``
    (pinned by ``tests/test_aux.py::test_host_level2_matches_device_head``
    and the family parity tests); the head becomes one relabel + two mark
    scatters, and the first full-width segment_min never runs. Same
    ``on_chunk`` checkpoint contract as the staged path; resume goes
    through :func:`solve_rank_resume` (partition-based, path-agnostic)."""
    n_pad = vmin0.shape[0]
    m_pad = ra.shape[0]
    mst, fa, fb, stats = _head_l2(vmin0, ra, rb, parent12, l2_ranks)
    lv, count = (int(x) for x in jax.device_get(stats))
    if on_chunk is not None:
        on_chunk(lv, parent12, mst, count)
    return _finish_to_fixpoint(
        parent12, mst, fa, fb, jnp.arange(m_pad, dtype=jnp.int32),
        lv=lv, count=count, space=n_pad, max_levels=lv + _max_levels(n_pad),
        chunk_levels=chunk_levels, compact_space=compact_space,
        on_chunk=on_chunk,
    )


def solve_rank_staged(
    vmin0,
    ra,
    rb,
    *,
    compact_after: int = 2,
    chunk_levels: int = 3,
    compact_space: bool | None = None,
    initial_state: tuple | None = None,
    on_chunk=None,
    parent1=None,
) -> Tuple[jax.Array, jax.Array, int]:
    """Device-resident solve from staged arrays.

    One head dispatch (levels 1-2 at full width), then finish chunks of
    ``chunk_levels`` levels, each re-compacted to the exact survivor count —
    RMAT-like graphs finish in one chunk; high-diameter road grids shed
    width every chunk instead of paying the first compaction's width for
    all ~12+ remaining levels.

    With ``compact_space`` (default: on for sparse/grid families and for
    large fragment spaces), each chunk boundary additionally censuses the
    live roots and, when the fragment space shrank >= 2x, renumbers it densely
    before running the next levels — so late levels cost O(alive fragments)
    instead of O(n). Vertex labels are restored by one replay pass at the end
    (``_replay_stages``). Returns ``(mst_rank_mask, fragment, levels)``.

    ``initial_state`` is ``(fragment, mst_rank_mask, level)`` from a
    checkpoint: the head is skipped and slot endpoints are rebuilt from the
    restored partition. ``on_chunk(level, vertex_fragment, mst, count)``
    fires after the head and each finish chunk with the *vertex-level*
    fragment (replayed through any shrink stages so far) — the checkpoint
    hook. The hook MUST consume the arrays during the call (``np.asarray``
    / ``device_get``, as the checkpoint writer does): the mask buffer is
    DONATED to the next chunk dispatch, so a reference held past the hook
    reads a deleted buffer on TPU (a loud RuntimeError, not corruption).
    """
    n_pad = vmin0.shape[0]
    if initial_state is not None:
        fragment, mst, lv = _restore_state(initial_state, n_pad, ra.shape[0])
        fa, fb, count_d = _relabel_slots(fragment, ra, rb)
        count = int(jax.device_get(count_d))
    else:
        parent1 = _ensure_parent1(vmin0, ra, rb, parent1)
        fragment, mst, fa, fb, stats = _rank_head(
            vmin0, ra, rb, parent1, compact_after=compact_after
        )
        lv, count = (int(x) for x in jax.device_get(stats))
    rank_of_slot = jnp.arange(ra.shape[0], dtype=jnp.int32)
    if compact_space is None:
        # Road-like graphs always (many levels to amortize); anything else
        # once the fragment space is big enough that finish levels paying
        # O(n_pad) dominate the census cost.
        compact_space = compact_after <= 1 or n_pad >= _CENSUS_MIN_SPACE

    if on_chunk is not None and initial_state is None:
        on_chunk(lv, fragment, mst, count)

    # Budget RELATIVE to the entry level: a resume from a filtered-path
    # checkpoint can arrive with lv already at or past _max_levels(n_pad)
    # (the filtered phases each budget lv + _max_levels); an absolute cap
    # would run zero chunks and silently return the incomplete forest.
    return _finish_to_fixpoint(
        fragment, mst, fa, fb, rank_of_slot,
        lv=lv, count=count, space=n_pad, max_levels=lv + _max_levels(n_pad),
        chunk_levels=chunk_levels, compact_space=compact_space,
        on_chunk=on_chunk,
    )


def _finish_to_fixpoint(
    fragment,
    mst,
    fa,
    fb,
    rank_of_slot,
    *,
    lv: int,
    count: int,
    space: int,
    max_levels: int,
    chunk_levels: int,
    compact_space: bool,
    on_chunk=None,
):
    """Drive finish chunks to fixpoint from an arbitrary mid-solve state.

    ``fragment`` is the vertex-level partition (``space``-sized); ``fa/fb``
    are the alive-slot endpoints in that space with ``rank_of_slot`` carrying
    each slot's original rank for MST marking. Handles slot re-compaction,
    the compact-fragment-space shrink chain, and the final replay back to
    vertex labels. Returns ``(mst, fragment, lv)`` with ``fragment`` in the
    original vertex space. Shared by :func:`solve_rank_staged` and
    :func:`solve_rank_filtered`.
    """
    frag_state = fragment  # vertex-level until the first shrink, cfrag after
    vertex_fragment = fragment  # frozen at first shrink, for the final replay
    rep = None  # current-space -> original-root map (None = original space)
    stages = []  # completed (mark, newid, rep, cfrag_final) per shrink
    pending = None  # (mark, newid, rep) of the last shrink, awaiting cfrag
    census_failures = 0

    def current_vertex_fragment():
        if pending is None:
            return frag_state
        return _replay_stages(vertex_fragment, stages + [(*pending, frag_state)])

    while count > 0 and lv < max_levels:
        out_size = max(_bucket_size(count), _COMPACT_MIN_SLOTS)
        # Once the compacted width is small, per-level cost is negligible and
        # the level loop exits early on convergence — so run ALL remaining
        # levels in one dispatch instead of paying a host round trip
        # (~0.12 s tunneled) every `chunk_levels`. At large widths short
        # chunks still win: they reach the next re-compaction sooner.
        # The one-shot budget is SHAPE-ONLY (not the run-dependent
        # max_levels, which would multiply jit cache entries per graph):
        # fragments still merging <= 2 * alive slots, so
        # _max_levels(2 * out_size) levels always converge.
        eff_levels = (
            _max_levels(2 * out_size)
            if out_size <= _ONE_SHOT_MAX_SLOTS
            else chunk_levels
        )
        did_levels = False
        if compact_space and space > _SHRINK_MIN_SPACE and census_failures < 2:
            cfa_o, cfb_o, crank, mark, newid, cstats = _compact_and_mark(
                fa, fb, rank_of_slot, n=space, out_size=out_size
            )
            f_count, _ = (int(x) for x in jax.device_get(cstats))
            f_size = max(_bucket_size(f_count), _SHRINK_MIN_SPACE // 4)
            if f_size <= space // 2:
                census_failures = 0
                rep_prev = (
                    rep if rep is not None else jnp.arange(space, dtype=jnp.int32)
                )
                if pending is not None:
                    stages.append((*pending, frag_state))
                else:
                    vertex_fragment = frag_state
                rep, frag_state, mst, fa, fb, stats = _shrink_and_run(
                    mark, newid, rep_prev, mst, cfa_o, cfb_o, crank,
                    f_size=f_size, chunk_levels=eff_levels,
                )
                pending = (mark, newid, rep)
                rank_of_slot = crank
                space = f_size
                did_levels = True
            else:
                census_failures += 1
                # Reuse the compacted slots; run the levels without shrink.
                frag_state, mst, fa, fb, stats = _run_levels(
                    frag_state, mst, cfa_o, cfb_o, crank,
                    chunk_levels=eff_levels,
                )
                rank_of_slot = crank
                did_levels = True
        if not did_levels:
            frag_state, mst, fa, fb, rank_of_slot, stats = _finish_chunk(
                frag_state, mst, fa, fb, rank_of_slot,
                out_size=out_size, chunk_levels=eff_levels,
            )
        extra, count = (int(x) for x in jax.device_get(stats))
        lv += extra
        if on_chunk is not None:
            on_chunk(lv, current_vertex_fragment(), mst, count)
        if extra == 0:  # no progress possible (safety valve)
            break

    if pending is not None:
        stages.append((*pending, frag_state))
        fragment = _replay_stages(vertex_fragment, stages)
    else:
        fragment = frag_state
    return mst, fragment, lv


# ---------------------------------------------------------------------------
# Filter-Kruskal path — the dense-graph head killer.
#
# The staged head pays four full-width relabel gathers plus a full-width
# segment_min (RMAT-24: ~20 s of its ~30 s head). But the rank order already
# sorts edges by weight, so the lightest ranks are a prefix of ra/rb. Solve
# Borůvka over that prefix only (levels 2+ restricted to prefix slots), and
# the full edge width is touched exactly twice (one gather per endpoint) by a
# *filter*: a suffix edge whose endpoints the prefix forest already connects
# closes a cycle of known-MST edges and can never be an MST edge — drop it.
# The few survivors (~1-2% on RMAT) finish through the normal chunk loop.
#
# Exactness (no heuristic):
#   * Level 1 hooks every vertex on its globally minimum incident rank
#     (full ``vmin0``) — the textbook Borůvka step; those edges are MST edges
#     for the whole graph.
#   * Prefix levels 2+ pick each fragment's minimum outgoing edge *among
#     prefix slots*. Every suffix rank is strictly heavier than every prefix
#     rank, so whenever a fragment has any outgoing prefix edge that choice
#     equals its global minimum outgoing edge; fragments without one stall
#     (self-hook) — no wrong selection is possible.
#   * The filter drops a suffix edge only when its endpoints are already
#     connected by selected (true MST) edges — the cycle rule, exact under
#     the strict rank total order.
#   * Survivor levels: all prefix edges are intra-fragment by then and every
#     dropped suffix edge is too, so the minimum over survivors is again the
#     global minimum outgoing edge.
# The selected set is therefore exactly the unique rank-order MST — the mask
# is bit-identical to ``solve_rank_staged``'s (asserted in tests).
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnames=("prefix",))
def _filtered_head(vmin0, ra, rb, parent1, *, prefix: int):
    """Level-1 marks + level 2 over prefix slots only; one dispatch.
    ``parent1`` is the level-1 partition (host-precomputed in prep).
    Returns ``(fragment, mst, fa, fb, stats)`` with ``mst`` full-width and
    ``fa/fb`` prefix-width."""
    fragment = parent1
    has1 = vmin0 < INT32_MAX
    safe1 = jnp.where(has1, vmin0, 0)
    mst = jnp.zeros(ra.shape[0], dtype=bool).at[safe1].max(has1)

    # Level 2 restricted to the prefix: relabel only the prefix endpoints.
    fa = parent1[ra[:prefix]]
    fb = parent1[rb[:prefix]]
    fragment, fa, fb, has2, safe2, count = _prefix_level2_core(fragment, fa, fb)
    mst = mst.at[safe2].max(has2)

    lv = jnp.asarray(1, jnp.int32) + jnp.any(has2).astype(jnp.int32)
    return fragment, mst, fa, fb, jnp.stack([lv, count])


@functools.partial(jax.jit, static_argnames=("prefix",))
def _filter_suffix_ends(fragment, ra, rb, *, prefix: int):
    """The one full-width pass: suffix endpoints -> current fragments, plus
    the survivor count. Slicing inside the jit lets XLA fuse it into the
    gather (an eager ``ra[prefix:]`` would materialize two suffix-width HBM
    copies first). Pad slots (``ra == rb == 0``) count as dead."""
    fa = fragment[ra[prefix:]]
    fb = fragment[rb[prefix:]]
    return fa, fb, jnp.sum((fa != fb).astype(jnp.int32))


@functools.partial(jax.jit, static_argnames=("out_size",))
def _filter_compact(fa, fb, prefix, *, out_size: int):
    """Compact the filter survivors; slot ``i`` carries rank ``prefix + i``."""
    rank_of_slot = jnp.arange(fa.shape[0], dtype=jnp.int32) + prefix
    cfa, cfb, crank, _valid = _compact_slots(fa, fb, rank_of_slot, out_size)
    return cfa, cfb, crank


@functools.partial(jax.jit, static_argnames=("prefix", "out_size"))
def _filter_suffix_fused(fragment, ra, rb, *, prefix: int, out_size: int):
    """Filter + compaction in ONE dispatch, with no suffix-width endpoint
    materialization (r4 bisection: the two-step form's ``fa/fb`` cost ~2 GB
    of HBM write+read at RMAT-24 and a second dispatch + stats fetch).

    The alive test consumes the relabel gathers directly (bool out), and
    the survivors' endpoints are RE-gathered at the compacted width
    (``out_size`` << suffix — survivors measure 0.21% of the suffix on
    RMAT, so the speculative m/128 width carries >3x margin). Survivor
    positions come from ``searchsorted`` over the alive cumsum — out_size
    binary searches (~28 * out_size gather-elems) instead of
    ``_compact_slots``'s suffix-wide position scatter, which at the
    measured ~6-11 ns/elem scatter cost was the residual ~1.5 s of the
    r4 bisection's filter+compact phase. Returns ``(cfa, cfb, crank,
    count)``; ``count > out_size`` means the width overflowed and
    survivors were dropped — the caller falls back to the exact two-step
    filter. Bit-identical to it when accepted (searchsorted positions are
    ascending, the same order-preserving compaction; same cycle rule)."""
    alive = fragment[ra[prefix:]] != fragment[rb[prefix:]]
    cum = jnp.cumsum(alive.astype(jnp.int32))  # inclusive count
    count = cum[-1]
    j = jnp.arange(out_size, dtype=jnp.int32)
    # Position of the (j+1)-th survivor: first index with cum == j+1.
    cpos = jnp.searchsorted(cum, j + 1, side="left").astype(jnp.int32)
    valid = j < count
    # Pad slots carry crank 0 with cfa == cfb == 0: inert, never marked
    # (same contract as _compact_slots).
    crank = jnp.where(valid, cpos + prefix, 0)
    cfa = jnp.where(valid, fragment[ra[crank]], 0)
    cfb = jnp.where(valid, fragment[rb[crank]], 0)
    return cfa, cfb, crank, count


@functools.partial(jax.jit, static_argnames=("width",))
def _filter_chunk_ends(fragment, ra, rb, start, *, width: int):
    """One suffix chunk of the filter: relabel ranks ``[start, start+width)``
    and count survivors. Slicing inside the jit keeps only chunk-width
    intermediates live — the point of the chunked filter."""
    ca = jax.lax.dynamic_slice(ra, (start,), (width,))
    cb = jax.lax.dynamic_slice(rb, (start,), (width,))
    fa = fragment[ca]
    fb = fragment[cb]
    return fa, fb, jnp.sum((fa != fb).astype(jnp.int32))


# Suffix bytes above which the filter runs in chunks. This is a CAPACITY
# mechanism, not a speedup: measured at RMAT-25 (3.96 GB suffix, fits
# single-pass) chunking was 47.5 s vs 45.5 s single-pass, so the threshold
# sits just above that — chunking engages only where the single-pass
# suffix-width fa/fb cannot fit next to the resident rank arrays at all
# (RMAT-26: 8.6 GB of ra/rb alone on a 16 GB chip).
_FILTER_CHUNK_BYTES = 1 << 32
# Per-chunk width target (~0.54 GB of fa+fb per chunk).
_FILTER_CHUNK_RANKS = 1 << 26


def _filter_suffix_chunked(fragment, ra, rb, prefix: int):
    """The full-width filter pass in rank-ordered chunks.

    Returns ``(cfa, cfb, crank, count)`` with survivors concatenated in
    ascending-rank order (chunks are processed ascending and each chunk's
    compaction is order-preserving, so the concatenated slot order remains
    the global tie-break order — the same invariant the single-pass filter
    relies on). Peak extra HBM is two chunk-width int32 arrays instead of
    two suffix-width ones.
    """
    m_pad = ra.shape[0]
    suffix = m_pad - prefix
    n_chunks = max(1, -(-suffix // _FILTER_CHUNK_RANKS))
    width = -(-suffix // n_chunks)
    # Both prefix and m_pad are bucket sizes (multiples of large powers of
    # two), so width divides evenly in practice; guard the general case by
    # clamping the last chunk's start and masking the overlap.
    parts = []
    count = 0
    for k in range(n_chunks):
        start = prefix + k * width
        overlap = 0
        if start + width > m_pad:  # re-reads tail ranks already filtered
            overlap = start + width - m_pad
            start = m_pad - width
        fa, fb, cnt_d = _filter_chunk_ends(
            fragment, ra, rb, jnp.asarray(start, jnp.int32), width=width
        )
        if overlap:
            keep = jnp.arange(width, dtype=jnp.int32) >= overlap
            fa = jnp.where(keep, fa, 0)
            fb = jnp.where(keep, fb, 0)
            cnt_d = jnp.sum((fa != fb).astype(jnp.int32))
        cnt = int(jax.device_get(cnt_d))
        if cnt:
            out_c = max(_bucket_size(cnt), _COMPACT_MIN_SLOTS)
            cfa, cfb, crank = _filter_compact(
                fa, fb, jnp.asarray(start, jnp.int32), out_size=out_c
            )
            parts.append((cfa[:cnt], cfb[:cnt], crank[:cnt]))
            count += cnt
        del fa, fb
    if not parts:
        return None, None, None, 0
    out_size = max(_bucket_size(count), _COMPACT_MIN_SLOTS)
    pad = out_size - count
    cfa = jnp.concatenate([p[0] for p in parts] + [jnp.zeros(pad, jnp.int32)])
    cfb = jnp.concatenate([p[1] for p in parts] + [jnp.zeros(pad, jnp.int32)])
    crank = jnp.concatenate(
        [p[2] for p in parts] + [jnp.zeros(pad, jnp.int32)]
    )
    return cfa, cfb, crank, count


def _prefix_size(n_pad: int, m_pad: int, mult: int = 2) -> int:
    """The filter split point: lightest ``mult * n_pad`` ranks, bucketed.
    Measured policy (selected by ``solve_rank_filtered``'s auto-default):
    ``mult=1`` wherever the single-pass filter fits (RMAT-24 12.53 s vs
    13.44 s; a wash at 20/22/25 — the smaller prefix halves the head's
    relabel/segment_min width and the extra survivors are cheap), but
    ``mult=2`` in the chunked-filter capacity regime (RMAT-26 class) and
    on the speculative path — the configurations those results were
    measured under (mult=1 at RMAT-26 hung in compilation and ships
    nowhere unmeasured). The sharded entry follows the mult=1 staged
    choice — its prefix solve is replicated, so the smaller prefix helps
    it at least as much."""
    return _bucket_size(min(mult * n_pad, m_pad))


def _prefix_plan(n_pad: int, m_pad: int) -> Tuple[int, bool]:
    """The filter split decision ``(prefix, force_chunked)`` — extracted so
    prep (:func:`prepare_rank_arrays_filtered`) and the solver
    (:func:`solve_rank_filtered`) cannot disagree on the prefix the host
    level-2 pass was computed for. mult=1 wherever the single-pass filter
    fits; mult=2 in the chunked-filter capacity regime (see
    :func:`_prefix_size` for the measured rationale)."""
    suffix1 = m_pad - _prefix_size(n_pad, m_pad, 1)
    force_chunked = 8 * suffix1 > _FILTER_CHUNK_BYTES
    return _prefix_size(n_pad, m_pad, 2 if force_chunked else 1), force_chunked


@functools.partial(jax.jit, static_argnames=("prefix",))
def _filtered_head_l2(vmin0, ra, rb, parent12, l2_ranks, *, prefix: int):
    """:func:`_filtered_head` with the prefix level 2 host-precomputed
    (:func:`host_level2` over the prefix ranks): one prefix relabel plus
    the L1/L2 mark scatters — the prefix-width segment_min and hook never
    run on device. Same return contract."""
    mp = ra.shape[0]
    has1 = vmin0 < INT32_MAX
    safe1 = jnp.where(has1, vmin0, 0)
    mst = jnp.zeros(mp, dtype=bool).at[safe1].max(has1)
    has2 = l2_ranks < prefix  # pads carry m_pad and are dropped
    mst = mst.at[jnp.where(has2, l2_ranks, mp)].max(has2, mode="drop")
    fa = parent12[ra[:prefix]]
    fb = parent12[rb[:prefix]]
    count = jnp.sum((fa != fb).astype(jnp.int32))
    lv = jnp.asarray(1, jnp.int32) + jnp.any(has2).astype(jnp.int32)
    return parent12, mst, fa, fb, jnp.stack([lv, count])


def prepare_rank_arrays_filtered(graph: Graph):
    """:func:`prepare_rank_arrays_full` plus the host level-2 pass over the
    FILTER PREFIX (the dense-family production prep): ``(vmin0, ra, rb,
    parent1, parent12, l2_ranks, prefix)`` staged.

    Which partitions are staged follows the consuming path:
    * degenerate split / below filter scale: ``parent1`` only (the staged
      fallback runs the device head);
    * speculative regime (``n_pad < _CENSUS_MIN_SPACE``): BOTH —
      ``parent12`` is computed for the speculative program's mult-2
      prefix (the returned ``prefix``), ``parent1`` backs the
      misprediction fallback and any chunked (``on_chunk``) form, which
      use :func:`_prefix_plan`'s prefix and must not see this
      ``parent12``;
    * chunked filtered regime: ``parent12`` only (for
      :func:`_prefix_plan`'s prefix; the L2 head never reads ``parent1``
      on device, so staging it would waste an n-sized transfer).
    The extra host pass (first-cross-rank over the prefix) hides under the
    edge-sized transfers like the rest of prep."""
    cached = graph.__dict__.get("_rank_device_cache_filtered")
    if cached is not None:
        return cached
    n_pad = _bucket_size(graph.num_nodes)
    m_pad = _bucket_size(graph.num_edges)
    prefix, _force_chunked = _prefix_plan(n_pad, m_pad)
    if 2 * prefix > m_pad or not use_filtered_path("dense", m_pad):
        # The consuming path won't run any L2 head (degenerate split or
        # below filter scale): don't pay the host pass/extra transfers.
        full = prepare_rank_arrays_full(graph)
        return full[:4] + (None, None, prefix)
    if n_pad < _CENSUS_MIN_SPACE:
        # Small-dense speculative regime: the single-dispatch program uses
        # the mult-2 prefix (its measured configuration), so the host L2
        # is computed for THAT prefix; parent1 stays staged for the
        # misprediction fallback (which runs the device head).
        prefix_spec = _prefix_size(n_pad, m_pad, 2)
        if 2 * prefix_spec > m_pad:
            full = prepare_rank_arrays_full(graph)
            return full[:4] + (None, None, prefix)
        return _stage_filtered(graph, prefix_spec, include_parent1=True)
    return _stage_filtered(graph, prefix, include_parent1=False)


def _stage_filtered(graph: Graph, prefix: int, *, include_parent1: bool):
    """Shared staging tail of :func:`prepare_rank_arrays_filtered`: host
    level-2 over ``prefix`` ranks (pad slots in ``[m, prefix)`` are
    self-edges with ``ra == rb == 0`` — no cross ranks, so scanning past
    ``m`` is safe) and the device puts. ``include_parent1`` stages the
    fallback partition too (the speculative regime needs it; the chunked
    regime's L2 head never reads it on device, so staging it there would
    waste an n-sized transfer)."""
    n, m, n_pad, m_pad, ra, rb, vmin0, parent1, sa, sb = _prep_head(graph)
    parent12, l2r = host_level2(parent1, ra, rb, prefix)
    l2_staged = _pad_l2_ranks(l2r, m_pad)
    sv = jax.device_put(vmin0)
    sp1 = jax.device_put(parent1) if include_parent1 else None
    sp12 = jax.device_put(parent12)
    sl = jax.device_put(l2_staged)
    staged = (sv, sa, sb, sp1, sp12, sl, prefix)
    for leaf in staged[:6]:
        if leaf is not None:
            _ = np.asarray(leaf[:1])
    if m_pad <= _STAGE_CACHE_MAX_RANKS:
        graph.__dict__["_rank_device_cache_filtered"] = staged
    return staged


def solve_rank_filtered(
    vmin0, ra, rb, *, chunk_levels: int = 3, prefix_mult: int | None = None,
    on_chunk=None, parent1=None, parent12=None, l2_ranks=None,
    l2_prefix: int | None = None,
) -> Tuple[jax.Array, jax.Array, int]:
    """Filter-Kruskal solve: prefix Borůvka, one-pass suffix filter, survivor
    finish. Same contract and bit-identical results as
    :func:`solve_rank_staged`; a large win on dense graphs (the full edge
    width is touched by two gathers and one compaction instead of four
    gathers, a double-width segment_min, an MST scatter, and a compaction).

    ``on_chunk(level, vertex_fragment, mst, count)`` fires after the head
    and each finish chunk with the vertex-level fragment and the full-width
    rank mask — the same checkpoint contract as the staged path (``count``
    is the alive count of the *current phase's* slots), including the
    consume-during-the-call rule (the mask buffer is donated to the next
    chunk dispatch; see :func:`solve_rank_staged`). Resume goes through
    :func:`solve_rank_resume`, exact from any saved partition.

    ``parent12``/``l2_ranks`` (from :func:`prepare_rank_arrays_filtered`)
    carry the host-precomputed PREFIX level 2: the head becomes one prefix
    relabel plus mark scatters (r5). ``l2_prefix`` is the prefix the host
    pass was computed for — REQUIRED with ``parent12`` and verified
    against this call's own prefix, because a mismatched partition would
    silently drop the L2 marks past the smaller prefix (merged but
    unmarked edges -> a wrong forest with no error).
    """
    n_pad = vmin0.shape[0]
    m_pad = ra.shape[0]
    force_chunked = False
    if prefix_mult is None:
        # mult=1 measured best where everything fits (RMAT-24 13.44 ->
        # 12.53 s; wash at 20/22/25); mult=2 in the chunked-filter capacity
        # regime — see _prefix_plan/_prefix_size for the full rationale.
        prefix, force_chunked = _prefix_plan(n_pad, m_pad)
    else:
        prefix = _prefix_size(n_pad, m_pad, prefix_mult)
    if parent12 is not None and l2_prefix != prefix:
        raise ValueError(
            f"parent12/l2_ranks were computed for prefix {l2_prefix} but "
            f"this call runs prefix {prefix}. In the speculative regime "
            f"prep computes them for the mult-2 prefix, which only the "
            f"speculative program may consume — route through "
            f"solve_rank_auto/make_production_solver, or drop parent12 "
            f"and pass parent1."
        )
    if 2 * prefix > m_pad:
        # Not enough suffix to pay for the split — plain staged solve.
        return solve_rank_staged(
            vmin0, ra, rb, chunk_levels=chunk_levels, on_chunk=on_chunk,
            parent1=parent1,
        )

    compact_space = n_pad >= _CENSUS_MIN_SPACE
    if parent12 is not None:
        fragment, mst, fa, fb, stats = _filtered_head_l2(
            vmin0, ra, rb, parent12, l2_ranks, prefix=prefix
        )
    else:
        parent1 = _ensure_parent1(vmin0, ra, rb, parent1)
        fragment, mst, fa, fb, stats = _filtered_head(
            vmin0, ra, rb, parent1, prefix=prefix
        )
    lv, count = (int(x) for x in jax.device_get(stats))
    if on_chunk is not None:
        on_chunk(lv, fragment, mst, count)
    mst, fragment, lv = _finish_to_fixpoint(
        fragment, mst, fa, fb, jnp.arange(prefix, dtype=jnp.int32),
        lv=lv, count=count, space=n_pad, max_levels=lv + _max_levels(n_pad),
        chunk_levels=chunk_levels, compact_space=compact_space,
        on_chunk=on_chunk,
    )

    if force_chunked or 8 * (m_pad - prefix) > _FILTER_CHUNK_BYTES:
        # RMAT-25+ widths: chunk the filter so its intermediates never
        # exceed two chunk-width arrays (the single-pass form's suffix-width
        # fa/fb are the HBM-capacity knee at ~0.5B ranks).
        cfa, cfb, crank, count = _filter_suffix_chunked(fragment, ra, rb, prefix)
    else:
        # Fused filter+compact at a speculative width: one dispatch, no
        # suffix-width endpoint arrays (r4 bisection: 6.3 s -> the alive
        # pass alone). Overflow (count > out_size) falls back to the exact
        # two-step filter sized from the true count.
        out_size = max(_bucket_size(m_pad // 128), _COMPACT_MIN_SLOTS)
        cfa, cfb, crank, count_d = _filter_suffix_fused(
            fragment, ra, rb, prefix=prefix, out_size=out_size
        )
        count = int(jax.device_get(count_d))
        if count > out_size:
            fa_s, fb_s, count_d = _filter_suffix_ends(
                fragment, ra, rb, prefix=prefix
            )
            count = int(jax.device_get(count_d))
            out_size = max(_bucket_size(count), _COMPACT_MIN_SLOTS)
            cfa, cfb, crank = _filter_compact(
                fa_s, fb_s, jnp.asarray(prefix, jnp.int32), out_size=out_size
            )
            del fa_s, fb_s  # free the suffix-width buffers before the finish
    if count > 0:
        mst, fragment, lv = _finish_to_fixpoint(
            fragment, mst, cfa, cfb, crank,
            lv=lv, count=count, space=n_pad, max_levels=lv + _max_levels(n_pad),
            chunk_levels=chunk_levels, compact_space=compact_space,
            on_chunk=on_chunk,
        )
    return mst, fragment, lv


@functools.partial(
    jax.jit, static_argnames=("prefix", "prefix_out", "out_size", "max_levels")
)
def _filtered_speculative_program(
    vmin0, ra, rb, parent1, *, prefix: int, prefix_out: int, out_size: int,
    max_levels: int
):
    """The whole filtered solve as ONE dispatch, for the small-dense regime
    where host round trips (~0.12 s each on a tunneled chip) dominate:

      head -> compact prefix survivors to the predicted ``prefix_out`` ->
      levels to fixpoint there -> suffix filter -> compact to the predicted
      ``out_size`` -> survivor levels to fixpoint.

    Both inner loops run COMPACTED (an uncompacted variant measured 1.86 s
    at RMAT-20 where the adaptive-chunked staged path runs 1.41 s —
    per-level cost at full prefix width costs more than the round trips it
    saves; measured survivor ratios are 5.3% of the prefix and 0.21% of
    the suffix, so the speculative widths carry >2x margin). One combined
    stats fetch validates every speculation; the
    caller falls back to the exact staged sequence on any overflow or
    non-convergence. Results are bit-identical to
    :func:`solve_rank_filtered` when accepted.

    Returns ``(fragment, mst, stats)`` with ``stats = [levels,
    prefix_count, prefix_alive_end, filter_count, survivor_alive_end]``.
    """
    fragment, mst, fa, fb, stats0 = _filtered_head(
        vmin0, ra, rb, parent1, prefix=prefix
    )
    return _speculative_tail(
        fragment, mst, fa, fb, stats0, ra, rb,
        prefix=prefix, prefix_out=prefix_out, out_size=out_size,
        max_levels=max_levels,
    )


@functools.partial(
    jax.jit, static_argnames=("prefix", "prefix_out", "out_size", "max_levels")
)
def _filtered_speculative_program_l2(
    vmin0, ra, rb, parent12, l2_ranks, *, prefix: int, prefix_out: int,
    out_size: int, max_levels: int
):
    """:func:`_filtered_speculative_program` with the prefix level 2
    host-precomputed (``host_level2`` over THIS program's mult-2 prefix):
    the in-dispatch head becomes one prefix relabel + mark scatters
    (measured 0.214 -> 0.097 s at RMAT-20 width). Same contract."""
    fragment, mst, fa, fb, stats0 = _filtered_head_l2(
        vmin0, ra, rb, parent12, l2_ranks, prefix=prefix
    )
    return _speculative_tail(
        fragment, mst, fa, fb, stats0, ra, rb,
        prefix=prefix, prefix_out=prefix_out, out_size=out_size,
        max_levels=max_levels,
    )


def _speculative_tail(
    fragment, mst, fa, fb, stats0, ra, rb, *, prefix: int, prefix_out: int,
    out_size: int, max_levels: int
):
    """The shared post-head body of the speculative programs (compact
    prefix survivors -> levels -> suffix filter -> compact -> levels ->
    combined stats)."""
    prefix_count = stats0[1]
    rank_p = jnp.arange(prefix, dtype=jnp.int32)
    cfa_p, cfb_p, crank_p, _ = _compact_slots(fa, fb, rank_p, prefix_out)
    fragment, mst, cfa_p, cfb_p, stats1 = _levels_loop(
        fragment, mst, cfa_p, cfb_p, crank_p, chunk_levels=max_levels
    )

    fa_s, fb_s, filter_count = _filter_suffix_ends(fragment, ra, rb, prefix=prefix)
    cfa, cfb, crank = _filter_compact(
        fa_s, fb_s, jnp.asarray(prefix, jnp.int32), out_size=out_size
    )
    fragment, mst, cfa, cfb, stats2 = _levels_loop(
        fragment, mst, cfa, cfb, crank, chunk_levels=max_levels
    )

    lv = stats0[0] + stats1[0] + stats2[0]
    return fragment, mst, jnp.stack(
        [lv, prefix_count, stats1[1], filter_count, stats2[1]]
    )


def solve_rank_filtered_speculative(
    vmin0,
    ra,
    rb,
    *,
    prefix_mult: int = 2,
    prefix_out: int | None = None,
    out_size: int | None = None,
    parent1=None,
    parent12=None,
    l2_ranks=None,
    l2_prefix: int | None = None,
) -> Tuple[jax.Array, jax.Array, int] | None:
    """Single-round-trip filtered solve; ``None`` on misprediction (caller
    falls back to :func:`solve_rank_filtered`). Default speculative widths:
    ``prefix/8`` for prefix survivors (measured 5.3% alive after the head)
    and ``m/128`` for filter survivors (measured 0.21% of the suffix).
    ``parent12``/``l2_ranks`` carry the host prefix-L2; ``l2_prefix`` (the
    prefix it was computed for) is REQUIRED with them and verified against
    this program's own prefix — a mismatch would silently drop L2 marks
    past the smaller prefix."""
    n_pad = vmin0.shape[0]
    m_pad = ra.shape[0]
    prefix = _prefix_size(n_pad, m_pad, prefix_mult)
    if 2 * prefix > m_pad:
        return None
    if parent12 is not None and l2_prefix != prefix:
        raise ValueError(
            f"parent12/l2_ranks were computed for prefix {l2_prefix} but "
            f"the speculative program runs prefix {prefix} "
            f"(prefix_mult={prefix_mult}); pass the matching l2_prefix"
        )
    if prefix_out is None:
        prefix_out = max(_bucket_size(prefix // 8), _COMPACT_MIN_SLOTS)
    if out_size is None:
        out_size = max(_bucket_size(m_pad // 128), _COMPACT_MIN_SLOTS)
    max_levels = _max_levels(n_pad)
    if parent12 is not None:
        fragment, mst, stats = _filtered_speculative_program_l2(
            vmin0, ra, rb, parent12, l2_ranks,
            prefix=prefix, prefix_out=prefix_out, out_size=out_size,
            max_levels=max_levels,
        )
    else:
        parent1 = _ensure_parent1(vmin0, ra, rb, parent1)
        fragment, mst, stats = _filtered_speculative_program(
            vmin0, ra, rb, parent1,
            prefix=prefix, prefix_out=prefix_out, out_size=out_size,
            max_levels=max_levels,
        )
    lv, prefix_count, prefix_alive, filter_count, survivor_alive = (
        int(x) for x in jax.device_get(stats)
    )
    if (
        prefix_count <= prefix_out
        and filter_count <= out_size
        and prefix_alive == 0
        and survivor_alive == 0
    ):
        return mst, fragment, lv
    return None


# Dense graphs at or above this rank width route through the filtered path
# (below it, dispatch round-trips outweigh the saved full-width work).
_FILTER_MIN_RANKS = 1 << 23


def use_filtered_path(family: str, num_ranks: int) -> bool:
    """THE routing predicate for the filter-Kruskal path — shared by
    ``solve_rank_auto``, the checkpoint path, and the sharded entry, so a
    retune cannot route checkpointed or sharded runs down a different
    kernel than the benchmarked auto path."""
    return family == "dense" and num_ranks >= _FILTER_MIN_RANKS


def solve_rank_auto(
    vmin0, ra, rb, *, family: str = "dense", parent1=None, parent12=None,
    l2_ranks=None, l2_prefix=None,
):
    """Dispatch policy shared by ``solve_graph_rank`` and ``bench.py`` —
    see :func:`_pick_family` for the per-family rationale. Chunk length 2
    beats 3 on many-level graphs (measured 12.1 s vs 13.2 s on a 4096^2
    grid; 1 loses to dispatch overhead at 14.1 s).
    ``parent12``/``l2_ranks``/``l2_prefix`` (from
    :func:`prepare_rank_arrays_filtered`) route the filtered path through
    the host-precomputed prefix level 2; the consumers verify
    ``l2_prefix`` against their own prefix."""
    n_pad = vmin0.shape[0]
    if use_filtered_path(family, ra.shape[0]):
        if n_pad >= _CENSUS_MIN_SPACE and parent12 is not None:
            # The L2 head never reads parent1 on device — don't force the
            # device-level-1 fallback for an unused array.
            return solve_rank_filtered(
                vmin0, ra, rb, parent1=parent1, parent12=parent12,
                l2_ranks=l2_ranks, l2_prefix=l2_prefix,
            )
        if n_pad < _CENSUS_MIN_SPACE:
            # Small-dense: one dispatch with compacted inner loops beats the
            # staged sequence (RMAT-20: 1.31 s vs 1.41 s staged, same
            # session). parent12 here is computed for the SPECULATIVE
            # (mult-2) prefix and is only valid inside that program (its
            # l2_prefix check enforces it); the misprediction fallback
            # below runs the device head off parent1 (ensured lazily —
            # the accepted L2 speculation never reads it).
            result = solve_rank_filtered_speculative(
                vmin0, ra, rb, parent1=parent1, parent12=parent12,
                l2_ranks=l2_ranks, l2_prefix=l2_prefix,
            )
            if result is not None:
                return result
        parent1 = _ensure_parent1(vmin0, ra, rb, parent1)
        return solve_rank_filtered(vmin0, ra, rb, parent1=parent1)
    parent1 = _ensure_parent1(vmin0, ra, rb, parent1)
    if family == "dense" and n_pad < _CENSUS_MIN_SPACE:
        # Below the census threshold the finish is one chunk and the fetch
        # overhead dominates: speculate the survivor width at m/8 (2x the
        # worst measured RMAT ratio) and fall back on misprediction.
        out_size = max(_bucket_size(ra.shape[0] // 8), _COMPACT_MIN_SLOTS)
        result = solve_rank_speculative(
            vmin0, ra, rb, out_size=out_size, parent1=parent1
        )
        if result is not None:
            return result
    return solve_rank_staged(
        vmin0, ra, rb, **_family_params(family), parent1=parent1
    )


# packbits over masks wider than this runs in slices: the single
# full-width program fails to compile at 2^30 width (observed on the
# tunneled chip's compile helper at RMAT-26). Slice boundaries stay
# byte-aligned — every width above the threshold is a bucket size, i.e. a
# multiple of a large power of two, so both the chunk and any remainder
# tail are multiples of 8 and per-byte bit order is unaffected.
_PACKBITS_CHUNK = 1 << 27


def packed_to_edge_ids(graph: Graph, packed: np.ndarray, count: int) -> np.ndarray:
    """Host decode of a bit-packed rank mask (big-endian bit order, numpy's
    and jnp's shared default) -> sorted edge ids. Shared by the single-chip
    fetch and the sharded multi-process harvest."""
    mask = np.unpackbits(packed, count=count).astype(bool)
    return np.sort(graph.edge_id_of_rank(np.nonzero(mask)[0]))


def fetch_mst_edge_ids(graph: Graph, mst) -> np.ndarray:
    """Device mask -> sorted edge ids, fetched bit-packed (8x less tunnel
    traffic: a 16.8M-node road grid's 42 MB bool mask is ~1.4 s of transfer
    on this setup). Shared by the single-chip and sharded hosts and the
    bench tools."""
    w = mst.shape[0]
    if w > _PACKBITS_CHUNK and w % 8 == 0:
        parts = []
        for s in range(0, w, _PACKBITS_CHUNK):
            size = min(_PACKBITS_CHUNK, w - s)  # tail slice stays byte-aligned
            parts.append(
                np.asarray(
                    jnp.packbits(jax.lax.dynamic_slice(mst, (s,), (size,)))
                )
            )
        packed = np.concatenate(parts)
    else:
        packed = np.asarray(jnp.packbits(mst))
    return packed_to_edge_ids(graph, packed, w)


def use_l2_path(family: str) -> bool:
    """Single routing predicate for the host-L2 (level-3 device entry)
    path — shared by ``solve_graph_rank``, the checkpoint path,
    ``bench.py``, and the instrumented metrics, so a retune cannot route
    production down a different kernel than the one benchmarked. Measured
    r5 (byte-identical, oracle-verified): grid 14.6 -> 9.3 s, sparse
    (config-5 road network) 10.1 -> 4.4 s; dense keeps filter-Kruskal
    (its prefix already does level 2 at ~2n width)."""
    return family in ("grid", "sparse")


def make_production_solver(graph: Graph):
    """Stage the graph's production arrays (prep — the transfer-overlapped
    host passes happen HERE, so callers can clock prep separately) and
    return ``solve(on_chunk=None) -> (mst, fragment, levels)``.

    This is the SINGLE routing source — ``solve_graph_rank``, the
    checkpoint path, ``bench.py``, and the instrumented metrics all call
    it, so a retune cannot route production down a different kernel than
    the ones benchmarked/instrumented. Routing (r5): road families
    (``use_l2_path``) -> host L1+L2 + :func:`solve_rank_l2`; dense at
    filter scale -> host L1 + prefix-L2 + the filter-Kruskal path (the
    speculative single-dispatch variant only when no ``on_chunk`` is
    requested — it has no chunk boundaries); everything else -> the
    staged path."""
    family = _pick_family(graph)
    if use_l2_path(family):
        vmin0, ra, rb, parent12, l2_ranks = prepare_rank_arrays_l2(graph)

        def solve(on_chunk=None):
            return solve_rank_l2(
                vmin0, ra, rb, parent12, l2_ranks, on_chunk=on_chunk
            )
    elif use_filtered_path(family, _bucket_size(graph.num_edges)):
        vmin0, ra, rb, parent1, parent12, l2_ranks, l2_prefix = (
            prepare_rank_arrays_filtered(graph)
        )
        # The chunked filtered form (used whenever on_chunk is requested)
        # runs _prefix_plan's prefix; hand it the host L2 only when prep
        # computed it for exactly that prefix (in the speculative regime
        # it was computed for the mult-2 prefix instead — the prefix
        # comparison, not a re-derived regime predicate, decides).
        plan_prefix, _ = _prefix_plan(
            _bucket_size(graph.num_nodes), _bucket_size(graph.num_edges)
        )
        chunk_p12 = parent12 if l2_prefix == plan_prefix else None
        chunk_l2 = l2_ranks if l2_prefix == plan_prefix else None

        def solve(on_chunk=None):
            if on_chunk is None:
                return solve_rank_auto(
                    vmin0, ra, rb, family=family, parent1=parent1,
                    parent12=parent12, l2_ranks=l2_ranks,
                    l2_prefix=l2_prefix,
                )
            return solve_rank_filtered(
                vmin0, ra, rb, on_chunk=on_chunk, parent1=parent1,
                parent12=chunk_p12, l2_ranks=chunk_l2,
                l2_prefix=l2_prefix if chunk_p12 is not None else None,
            )
    else:
        vmin0, ra, rb, parent1 = prepare_rank_arrays_full(graph)

        def solve(on_chunk=None):
            if on_chunk is None:
                return solve_rank_auto(
                    vmin0, ra, rb, family=family, parent1=parent1
                )
            return solve_rank_staged(
                vmin0, ra, rb, **_family_params(family),
                on_chunk=on_chunk, parent1=parent1,
            )
    return _observed_solver(solve, family)


def _observed_solver(inner, family):
    """Wrap a production solve with event-bus telemetry.

    The overall dispatch is a ``solver.rank.solve`` span; when the caller
    requests chunk boundaries, each one also lands as a ``solver.chunk``
    event. Crucially the wrapper passes ``on_chunk`` through UNCHANGED when
    the caller didn't ask for one — requesting boundaries selects the
    chunked kernel forms, and observability must never reroute production.
    """
    import time as _time

    from distributed_ghs_implementation_tpu.obs.events import BUS

    def solve(on_chunk=None):
        if not BUS.enabled:
            return inner(on_chunk=on_chunk)
        hook = on_chunk
        if hook is not None:
            last = [_time.perf_counter()]

            def on_chunk(level, fragment, mst, count):  # noqa: F811
                now = _time.perf_counter()
                BUS.complete(
                    "solver.chunk",
                    now - last[0],
                    cat="solver",
                    level=int(level),
                    edges_alive=int(count),
                )
                last[0] = now
                hook(level, fragment, mst, count)

        with BUS.span(
            "solver.rank.solve", cat="solver",
            family=str(family), chunked=hook is not None,
        ):
            return inner(on_chunk=on_chunk)

    return solve


def solve_graph_kruskal_host(graph: Graph) -> Tuple[np.ndarray, np.ndarray, int]:
    """Host-native Kruskal over the precomputed rank order (the
    ``backend="host"`` entry): one C union-find pass, byte-identical to
    every device backend (ranks make the weight order total, so the MSF
    is unique). Measured against the device paths (r5,
    docs/BENCH_NOTES.md): the DEVICE wins on every family — RMAT-22
    2.53 s vs 6.46 s host (2.6x), config-5 road network 4.36 vs 4.64 s,
    23.9M road grid 9.28 vs 13.47 s — i.e. after the host-L1/L2 work the
    TPU path beats the single-core Kruskal baseline even on the
    gather-bound road graphs. This entry exists as that measured
    baseline (the reference never had one), as the oracle's solve form,
    and as an escape hatch for CPU-only hosts; production routing stays
    on the device paths, which also own checkpointing, sharding, and the
    instrumented observability. ``levels`` is reported as 0 (no Borůvka
    levels run). Integer weights only (the rank order is the native
    counting sort); float weights raise ``NotImplementedError``."""
    n = graph.num_nodes
    if n == 0 or graph.num_edges == 0:
        return np.zeros(0, dtype=np.int64), np.arange(n, dtype=np.int32), 0
    if not graph.is_integer_weighted:
        raise NotImplementedError("host backend needs integer weights")
    from distributed_ghs_implementation_tpu.graphs import native

    if not native.native_available():
        raise NotImplementedError("host backend needs the native toolchain")
    edge_ids, labels = native.kruskal_msf_solve_native(
        n, graph._rank_order, graph.u, graph.v, graph.w
    )
    return np.sort(edge_ids), labels.astype(np.int32), 0


def solve_graph_rank(graph: Graph) -> Tuple[np.ndarray, np.ndarray, int]:
    """Host entry matching ``models.boruvka.solve_graph``'s contract."""
    n = graph.num_nodes
    if n == 0 or graph.num_edges == 0:
        return np.zeros(0, dtype=np.int64), np.arange(n, dtype=np.int32), 0
    mst, fragment, levels = make_production_solver(graph)()
    return fetch_mst_edge_ids(graph, mst), np.asarray(fragment)[:n], levels
