"""Graph generators: Erdős–Rényi, RMAT, fixtures.

Covers the reference's generator component (C8,
``/root/reference/create_graph_files.py:13-40`` and
``ghs_implementation.py:702-721``) plus the large-scale RMAT generator needed
for the benchmark configs in ``BASELINE.json`` (the reference has nothing at
that scale — its envelope is ~10 vertices).
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

from distributed_ghs_implementation_tpu.graphs.edgelist import Graph


def _connect_components(u: np.ndarray, v: np.ndarray, num_nodes: int) -> Tuple[np.ndarray, np.ndarray]:
    """Append edges linking connected components until the graph is connected.

    One C-speed components pass (``edgelist.component_labels``) chained by
    each component's smallest vertex — the former O(m) interpreted
    union-find loop (VERDICT r3 weak #4) crawled at the bench-scale edge
    counts ``gnm_random_graph`` reaches. Representative choice changed
    with the rewrite; the repair still adds exactly ``n_components - 1``
    edges, so seeded weight streams are unaffected.
    """
    from distributed_ghs_implementation_tpu.graphs.edgelist import (
        component_labels,
    )

    labels = component_labels(num_nodes, u, v)
    if labels.size and labels.max() == 0:
        return u, v
    # First occurrence of each label scanning vertices in ascending order =
    # the smallest vertex of each component, ordered by label.
    _, reps = np.unique(labels, return_index=True)
    u = np.concatenate([u, reps[:-1].astype(u.dtype)])
    v = np.concatenate([v, reps[1:].astype(v.dtype)])
    return u, v


def erdos_renyi_graph(
    num_nodes: int,
    edge_probability: float,
    *,
    seed: int = 0,
    weight_low: int = 1,
    weight_high: int = 10,
    ensure_connected: bool = True,
) -> Graph:
    """G(n, p) with integer weights in ``[weight_low, weight_high]``.

    Vectorized NumPy sampling (the reference loops through NetworkX,
    ``create_graph_files.py:18-34``); connectivity is guaranteed by linking
    leftover components with a union-find sweep rather than resampling.
    Deterministic for a given seed.
    """
    rng = np.random.default_rng(seed)
    n = int(num_nodes)
    if n < 1:
        raise ValueError("num_nodes must be >= 1")
    if n > 32768:
        raise ValueError(
            "erdos_renyi_graph materializes all n(n-1)/2 pairs; "
            "use gnm_random_graph or rmat_graph for large n"
        )
    iu, iv = np.triu_indices(n, k=1)
    mask = rng.random(iu.size) < edge_probability
    u, v = iu[mask].astype(np.int64), iv[mask].astype(np.int64)
    if ensure_connected and n > 1:
        u, v = _connect_components(u, v, n)
    w = rng.integers(weight_low, weight_high + 1, size=u.size, dtype=np.int64)
    return Graph.from_arrays(n, u, v, w)


def gnm_random_graph(
    num_nodes: int,
    num_edges: int,
    *,
    seed: int = 0,
    weight_low: int = 1,
    weight_high: int = 10,
    ensure_connected: bool = True,
) -> Graph:
    """G(n, m): ``num_edges`` distinct edges sampled uniformly (BASELINE config 2)."""
    rng = np.random.default_rng(seed)
    n = int(num_nodes)
    m = int(num_edges)
    if m > n * (n - 1) // 2:
        raise ValueError(f"num_edges={m} exceeds the {n*(n-1)//2} distinct pairs")
    # Oversample pair codes then dedup; retry until we have m distinct pairs.
    want = m
    codes = np.zeros(0, dtype=np.int64)
    while codes.size < want:
        a = rng.integers(0, n, size=2 * (want - codes.size) + 16, dtype=np.int64)
        b = rng.integers(0, n, size=a.size, dtype=np.int64)
        keep = a != b
        lo = np.minimum(a[keep], b[keep])
        hi = np.maximum(a[keep], b[keep])
        codes = np.unique(np.concatenate([codes, lo * n + hi]))
    rng.shuffle(codes)
    codes = codes[:want]
    u, v = codes // n, codes % n
    if ensure_connected and n > 1:
        u, v = _connect_components(u, v, n)
    w = rng.integers(weight_low, weight_high + 1, size=u.size, dtype=np.int64)
    return Graph.from_arrays(n, u, v, w)


def reference_random_graph(
    num_nodes: int = 6, edge_probability: float = 0.5, seed: int = 42
) -> Graph:
    """Reproduce the reference generator's exact sampling behavior.

    Same observable behavior as ``create_graph_files.py:13-40`` /
    ``ghs_implementation.py:702-721``: NetworkX Erdős–Rényi seeded with
    ``seed``, resample with ``random.randint``-derived seeds until connected,
    then ``random.randint(1, 10)`` weights in edge-iteration order. Lets tests
    compare against the reference's own experiment configs
    (``ghs_implementation.py:787-794``) graph-for-graph.
    """
    import random

    import networkx as nx

    random.seed(seed)
    g = nx.erdos_renyi_graph(num_nodes, edge_probability, seed=seed)
    attempts = 0
    while not nx.is_connected(g) and attempts < 100:
        g = nx.erdos_renyi_graph(num_nodes, edge_probability, seed=random.randint(0, 10000))
        attempts += 1
    if not nx.is_connected(g):
        comps = list(nx.connected_components(g))
        for i in range(len(comps) - 1):
            g.add_edge(list(comps[i])[0], list(comps[i + 1])[0])
    for a, b in g.edges():
        g[a][b]["weight"] = random.randint(1, 10)
    return Graph.from_networkx(g)


def rmat_graph(
    scale: int,
    edge_factor: int = 16,
    *,
    seed: int = 1,
    a: float = 0.57,
    b: float = 0.19,
    c: float = 0.19,
    weight_low: int = 1,
    weight_high: int = 255,
    dedup: bool = True,
    use_native: str | bool = "auto",
) -> Graph:
    """Graph500-style RMAT: ``2**scale`` vertices, ``edge_factor * 2**scale`` edges.

    ``use_native="auto"`` routes through the C++ ingestion library when it is
    available and the graph is big enough to care (RMAT-20 drops from ~60 s of
    NumPy to ~1 s); ``False`` forces the vectorized NumPy sampler, ``True``
    requires native. The two paths use different RNG streams, so graphs match
    within a path (per seed) but not across paths.
    """
    native_required = use_native is True
    if native_required and not dedup:
        raise ValueError("native RMAT always dedups; use use_native=False with dedup=False")
    if use_native == "auto":
        use_native = scale >= 16 and dedup
    if use_native:
        from distributed_ghs_implementation_tpu.graphs import native

        if native.native_available():
            u, v, w, n = native.rmat_edges(
                scale,
                edge_factor,
                seed=seed,
                a=a,
                b=b,
                c=c,
                weight_low=weight_low,
                weight_high=weight_high,
            )
            # Already canonical + deduped; skip Graph.from_arrays re-dedup.
            g = Graph(n, u, v, w)
            # Tag which RNG stream produced the graph (frozen dataclass:
            # write the instance __dict__ as the caches do). Consumers with
            # per-stream recorded oracle weights key on this instead of
            # re-deriving the native/NumPy path decision.
            g.__dict__["generator_path"] = "rmat-native"
            return g
        if native_required:
            raise RuntimeError("native RMAT requested but library unavailable")
        # auto + no native toolchain: fall through to the NumPy sampler.

    rng = np.random.default_rng(seed)
    n = 1 << scale
    m = int(edge_factor) << scale
    u = np.zeros(m, dtype=np.int64)
    v = np.zeros(m, dtype=np.int64)
    for level in range(scale):
        r1 = rng.random(m)
        r2 = rng.random(m)
        # First choose src bit with P(a+b of top) etc. Standard RMAT:
        # quadrant probabilities (a, b, c, d) over (src_bit, dst_bit).
        src_bit = r1 >= (a + b)
        # dst bit conditional on src bit: P(dst|src=0) = b/(a+b), P(dst|src=1) = d/(c+d)
        d = 1.0 - a - b - c
        p_dst = np.where(src_bit, d / max(c + d, 1e-12), b / max(a + b, 1e-12))
        dst_bit = r2 < p_dst
        u = (u << 1) | src_bit
        v = (v << 1) | dst_bit
    w = rng.integers(weight_low, weight_high + 1, size=m, dtype=np.int64)
    g = Graph.from_arrays(n, u, v, w, dedup=dedup)
    g.__dict__["generator_path"] = "rmat-numpy"
    return g


def road_grid_graph(
    rows: int,
    cols: int,
    *,
    seed: int = 0,
    diag_prob: float = 0.05,
    weight_low: int = 1,
    weight_high: int = 10_000,
    keep_prob: float = 1.0,
) -> Graph:
    """Synthetic road network: a rows x cols grid with random diagonal
    shortcuts and wide integer weights; ``keep_prob < 1`` thins edges
    toward real road-network density (possibly disconnecting the graph —
    the solver returns the spanning forest).

    The stand-in for BASELINE config 5 (USA-road, 23.9M nodes) in this
    offline environment: bounded degree (~4), diameter ~rows+cols >> log n —
    the regime where the reference's sequential CHANGEROOT walks blow up
    (``/root/reference/README.md:77-80``) and pointer jumping is the answer.
    """
    rng = np.random.default_rng(seed)
    r = np.arange(rows, dtype=np.int64)
    c = np.arange(cols, dtype=np.int64)
    vid = (r[:, None] * cols + c[None, :])
    right_u = vid[:, :-1].ravel()
    right_v = vid[:, 1:].ravel()
    down_u = vid[:-1, :].ravel()
    down_v = vid[1:, :].ravel()
    parts_u = [right_u, down_u]
    parts_v = [right_v, down_v]
    if diag_prob > 0:
        du = vid[:-1, :-1].ravel()
        dv = vid[1:, 1:].ravel()
        keep = rng.random(du.size) < diag_prob
        parts_u.append(du[keep])
        parts_v.append(dv[keep])
    u = np.concatenate(parts_u)
    v = np.concatenate(parts_v)
    if keep_prob < 1.0:
        # Thin the grid toward real road-network density (USA-road averages
        # ~2.4 edges/vertex vs a full grid's ~4); drawn after the diagonal
        # mask so keep_prob=1.0 reproduces historical seeds exactly.
        sel = rng.random(u.size) < keep_prob
        u, v = u[sel], v[sel]
    w = rng.integers(weight_low, weight_high + 1, size=u.size, dtype=np.int64)
    return Graph.from_arrays(int(rows * cols), u, v, w)


def random_road_network(
    rows: int,
    cols: int,
    *,
    seed: int = 0,
    hole_prob: float = 0.08,
    axis_prob: float = 0.53,
    diag_prob: float = 0.12,
    weight_scale: int = 1000,
    dead_end_prob: float = 0.0,
) -> Graph:
    """Random planar-ish road network — the NON-grid stand-in for BASELINE
    config 5 (USA-road; the real DIMACS file is not obtainable offline, the
    reader in ``graphs/io.py`` is tested and ready for it).

    Construction: one jittered intersection point per cell of a
    ``rows x cols`` lattice, with ``hole_prob`` of the cells removed
    (holes force detours and kill the grid's translational regularity);
    independent Bernoulli links to the 4 axis and 4 diagonal neighbors;
    integer weights derived from Euclidean length (like road distances —
    NOT the grid generator's i.i.d. uniform draws, so weight and topology
    correlate the way they do on real roads). Unlike ``road_grid_graph``
    the degree distribution is irregular — dead ends, chains, junctions,
    degrees 0..8 — with incident average
    ``(4*axis_prob + 4*diag_prob) * (1 - hole_prob)`` ~= 2.4 at the
    defaults, matching USA-road's ~2.4 (58.3M directed arcs / 23.9M
    nodes); isolated cells come out as singleton components (the solver
    returns the spanning forest, as for any real disconnected road graph).

    ``dead_end_prob`` marks that fraction of cells as dead ends: a dead
    end keeps only its minimum-weight incident link (an edge survives iff
    BOTH endpoints accept it). Independent Bernoulli links alone cannot
    put real mass on degree 1 at road-like means — actual road graphs are
    full of cul-de-sacs — so this is the knob that lets the histogram
    matcher (``tools/match_usa_road.py``) hit a target degree-1 share,
    not just the mean degree.
    """
    rng = np.random.default_rng(seed)
    # float32 draws throughout: every full-lattice temporary is 91 MB at the
    # 23.9M-cell USA-road size instead of float64's 191 MB.
    alive = rng.random((rows, cols), dtype=np.float32) >= hole_prob
    jx = rng.random((rows, cols), dtype=np.float32)
    jy = rng.random((rows, cols), dtype=np.float32)
    xs = np.arange(cols, dtype=np.float32)[None, :] + jx
    ys = np.arange(rows, dtype=np.float32)[:, None] + jy
    del jx, jy
    newid = np.cumsum(alive.ravel()).reshape(alive.shape).astype(np.int64) - 1
    n = int(alive.sum())

    us, vs, ws = [], [], []
    offsets = [
        (0, 1, axis_prob), (1, 0, axis_prob),
        (1, 1, diag_prob), (1, -1, diag_prob),
    ]
    for dr, dc, p in offsets:
        r0, r1 = (0, rows - dr), (dr, rows)
        if dc >= 0:
            c0, c1 = (0, cols - dc), (dc, cols)
        else:
            c0, c1 = (-dc, cols), (0, cols + dc)
        a_sl = (slice(r0[0], r0[1]), slice(c0[0], c0[1]))
        b_sl = (slice(r1[0], r1[1]), slice(c1[0], c1[1]))
        keep = (
            alive[a_sl] & alive[b_sl]
            & (rng.random(alive[a_sl].shape, dtype=np.float32) < p)
        )
        dx = xs[a_sl][keep] - xs[b_sl][keep]
        dy = ys[a_sl][keep] - ys[b_sl][keep]
        d = np.hypot(dx, dy)
        del dx, dy
        us.append(newid[a_sl][keep])
        vs.append(newid[b_sl][keep])
        ws.append(np.maximum(1, np.round(d * weight_scale)).astype(np.int64))
        del d, keep
    u = np.concatenate(us)
    v = np.concatenate(vs)
    w = np.concatenate(ws)
    if dead_end_prob > 0.0 and u.size:
        dead = rng.random(n, dtype=np.float32) < dead_end_prob
        # Min-weight incident edge per vertex, ties broken by edge id — the
        # (weight, edge id) pair is encoded into one int64 key below so a
        # single order-independent minimum carries both criteria.
        int64_max = np.iinfo(np.int64).max
        best = np.full(n, int64_max, dtype=np.int64)
        eid = np.arange(u.size, dtype=np.int64)
        # Encode (weight, edge id) into one sortable key; weights are
        # bounded by ~sqrt(2)*weight_scale so the shift is safe.
        key = w * (eid.size + 1) + eid
        np.minimum.at(best, u, key)
        np.minimum.at(best, v, key)
        keep_u = ~dead[u] | (key == best[u])
        keep_v = ~dead[v] | (key == best[v])
        sel = keep_u & keep_v
        u, v, w = u[sel], v[sel], w[sel]
    return Graph.from_arrays(n, u, v, w)


def line_graph(num_nodes: int, *, weight: int = 1) -> Graph:
    """Path 0-1-...-(n-1): the high-diameter worst case for level count."""
    n = int(num_nodes)
    u = np.arange(n - 1, dtype=np.int64)
    v = u + 1
    w = np.full(n - 1, weight, dtype=np.int64)
    return Graph.from_arrays(n, u, v, w)


def simple_test_graph() -> Graph:
    """The reference's hand-written fixture: 3-node line, MST weight 3.

    Mirrors ``create_simple_test.py:9-50`` (0-1 weight 1, 1-2 weight 2,
    0-2 weight 3; MST = {(0,1), (1,2)}, total 3).
    """
    return Graph.from_edges(3, [(0, 1, 1), (1, 2, 2), (0, 2, 3)])


def readme_sample_graph() -> Graph:
    """The 6-node/9-edge sample from the reference README (MST weight 20).

    Edges per ``README.md:43-49``; the documented MST is weight 20 with 5
    edges (``README.md:52-61``) — the canonical end-to-end parity fixture.
    """
    edges = [
        (0, 1, 1), (0, 2, 4), (1, 2, 2),
        (1, 3, 5), (2, 3, 3), (2, 4, 7),
        (3, 4, 6), (3, 5, 8), (4, 5, 9),
    ]
    return Graph.from_edges(6, edges)
