"""Aux subsystems: metrics, checkpoint/resume, multihost helpers."""

import os

import numpy as np
import pytest

from distributed_ghs_implementation_tpu.graphs.generators import (
    erdos_renyi_graph,
    line_graph,
)
from distributed_ghs_implementation_tpu.models.boruvka import solve_graph
from distributed_ghs_implementation_tpu.utils.checkpoint import (
    load_checkpoint,
    save_checkpoint,
    solve_graph_checkpointed,
)
from distributed_ghs_implementation_tpu.utils.metrics import (
    solve_graph_instrumented,
)


def test_instrumented_matches_plain():
    g = erdos_renyi_graph(200, 0.05, seed=13)
    (edge_ids, fragment, levels), metrics = solve_graph_instrumented(g)
    ref_ids, ref_frag, _ = solve_graph(g)
    assert np.array_equal(edge_ids, ref_ids)
    assert metrics.num_nodes == 200
    assert len(metrics.levels) == levels
    # Fragment counts must be monotonically non-increasing and end at 1.
    counts = [r.fragments_after for r in metrics.levels]
    assert all(a >= b for a, b in zip(counts, counts[1:]))
    assert counts[-1] == 1
    assert metrics.to_json()  # serializes


def test_checkpoint_roundtrip(tmp_path):
    p = str(tmp_path / "ckpt.npz")
    frag = np.arange(10, dtype=np.int32)
    mst = np.zeros(20, dtype=bool)
    mst[3] = True
    save_checkpoint(p, frag, mst, 2)
    f2, m2, lv = load_checkpoint(p)
    assert np.array_equal(f2, frag) and np.array_equal(m2, mst) and lv == 2


def test_checkpointed_solve_and_resume(tmp_path):
    g = erdos_renyi_graph(150, 0.06, seed=14)
    p = str(tmp_path / "solve.npz")
    edge_ids, fragment, levels = solve_graph_checkpointed(g, p, every=1)
    ref_ids, _, _ = solve_graph(g)
    assert np.array_equal(edge_ids, ref_ids)
    assert os.path.exists(p)

    # Tamper: rewind to the level-1 state by re-solving with a fresh path,
    # stopping early via a partial checkpoint, then resuming.
    frag, mst, lv = load_checkpoint(p)
    assert lv == levels
    # Resume from the final checkpoint: must immediately converge to the same MST.
    edge_ids2, _, _ = solve_graph_checkpointed(g, p, every=1, resume=True)
    assert np.array_equal(edge_ids2, ref_ids)


def test_checkpoint_resume_midway(tmp_path):
    """Simulate preemption: checkpoint after level 1, resume, identical MST."""
    import jax.numpy as jnp

    from distributed_ghs_implementation_tpu.models.boruvka import (
        _level_kernel,
        prepare_device_arrays,
    )

    from distributed_ghs_implementation_tpu.utils.checkpoint import graph_fingerprint

    g = line_graph(130)  # high diameter -> several levels
    frag0, src, dst, rank, ra, rb = prepare_device_arrays(g)
    mst = jnp.zeros(ra.shape[0], dtype=bool)
    frag, mst, src_f, dst_f, has, count = _level_kernel(
        frag0, mst, src, dst, rank, ra, rb
    )
    p = str(tmp_path / "mid.npz")
    save_checkpoint(p, frag, mst, 1, fingerprint=graph_fingerprint(g))

    edge_ids, _, _ = solve_graph_checkpointed(g, p, resume=True)
    ref_ids, _, _ = solve_graph(g)
    assert np.array_equal(edge_ids, ref_ids)


def test_multihost_helpers_single_process():
    from distributed_ghs_implementation_tpu.parallel import multihost

    assert multihost.is_primary()  # single-process run is its own primary


def test_failure_report_schema(tmp_path):
    """The diagnostics dump (reference print_debug_info analog) carries the
    fragment histogram, alive-edge count, and unreachable-node detection."""
    from distributed_ghs_implementation_tpu.api import minimum_spanning_forest
    from distributed_ghs_implementation_tpu.utils.diagnostics import (
        dump_failure_report,
        failure_report,
    )
    from distributed_ghs_implementation_tpu.utils.verify import verify_result

    g = erdos_renyi_graph(50, 0.15, seed=21)
    result = minimum_spanning_forest(g)
    # Simulate a failed run: drop two MST edges, splitting the tree in three.
    import dataclasses

    broken = dataclasses.replace(result, edge_ids=result.edge_ids[:-2])
    v = verify_result(broken)
    assert not v.ok
    report = failure_report(broken, v)
    assert report["schema"] == "ghs-failure-report-v1"
    assert report["fragments"]["count"] == 3
    assert sum(s * c for s, c in report["fragments"]["size_histogram"].items()) == 50
    assert report["edges"]["alive_inter_fragment"] > 0
    assert report["verification"]["ok"] is False
    assert report["unreachable_from_node0"]["count"] > 0

    p = str(tmp_path / "fail.json")
    import json

    assert dump_failure_report(broken, v, path=p) == p
    with open(p) as f:
        assert json.load(f)["schema"] == "ghs-failure-report-v1"


def test_failure_report_protocol_nodes():
    """Per-node protocol state tables ride along when the node map is given."""
    from distributed_ghs_implementation_tpu.api import minimum_spanning_forest
    from distributed_ghs_implementation_tpu.protocol.runner import run_protocol
    from distributed_ghs_implementation_tpu.utils.diagnostics import failure_report

    g = erdos_renyi_graph(12, 0.4, seed=22)
    nodes, _ = run_protocol(g)
    result = minimum_spanning_forest(g, backend="protocol")
    report = failure_report(result, nodes=nodes)
    assert report["protocol"]["edge_state_totals"]["BRANCH"] == 2 * (g.num_nodes - 1)
    assert len(report["protocol"]["nodes"]) == 12
    row = report["protocol"]["nodes"][0]
    assert {"id", "state", "level", "fragment", "edge_states"} <= set(row)


def test_midsolve_interrupt_resume(tmp_path):
    """True mid-solve resume: interrupt after level 1, reload, finish —
    byte-identical MST to the uninterrupted solve."""
    from distributed_ghs_implementation_tpu.models.boruvka import (
        prepare_device_arrays,
        solve_arrays_stepped,
    )
    from distributed_ghs_implementation_tpu.utils.checkpoint import (
        graph_fingerprint,
        load_checkpoint,
        save_checkpoint,
        solve_graph_checkpointed,
    )

    g = erdos_renyi_graph(300, 0.04, seed=15)
    args = prepare_device_arrays(g)
    fp = graph_fingerprint(g)
    p = str(tmp_path / "mid.npz")

    # Run exactly one level, checkpoint, and abandon the run ("interrupt").
    seen = []

    def on_level(level, fragment, mst_ranks, has, count, dt):
        save_checkpoint(p, fragment, mst_ranks, level, fingerprint=fp)
        seen.append(level)

    solve_arrays_stepped(*args, stepped_levels=1, on_level=on_level)
    assert seen == [1]
    _, _, lv = load_checkpoint(p, expect_fingerprint=fp)
    assert lv == 1

    # Resume from the level-1 state and compare to a clean solve.
    edge_ids, fragment, levels = solve_graph_checkpointed(g, p, resume=True)
    ref_ids, ref_frag, _ = solve_graph(g)
    assert np.array_equal(edge_ids, ref_ids)
    assert np.array_equal(fragment, ref_frag)


def test_checkpoint_fingerprint_mismatch(tmp_path):
    """A checkpoint from a different graph is refused, not silently resumed."""
    from distributed_ghs_implementation_tpu.utils.checkpoint import (
        solve_graph_checkpointed,
    )

    g1 = erdos_renyi_graph(100, 0.1, seed=16)
    g2 = erdos_renyi_graph(100, 0.1, seed=17)  # same shapes, different graph
    p = str(tmp_path / "fp.npz")
    solve_graph_checkpointed(g1, p)
    with pytest.raises(ValueError, match="different graph"):
        solve_graph_checkpointed(g2, p, resume=True)


def test_cli_run_checkpoint(tmp_path):
    """`run --checkpoint` is reachable from the CLI and verifies green."""
    from distributed_ghs_implementation_tpu.cli import main as cli_main
    from distributed_ghs_implementation_tpu.graphs import io as gio

    g = erdos_renyi_graph(80, 0.1, seed=18)
    npz = str(tmp_path / "graph.npz")
    gio.write_npz(g, npz)
    ckpt = str(tmp_path / "run.npz")
    rc = cli_main(
        ["run", "--graph-dir", npz, "--checkpoint", ckpt, "--verify"]
    )
    assert rc == 0
    assert os.path.exists(ckpt)

    ckpt2 = str(tmp_path / "run_sharded.npz")
    rc = cli_main(
        ["run", "--graph-dir", npz, "--backend", "sharded",
         "--checkpoint", ckpt2, "--verify"]
    )
    assert rc == 0
    assert os.path.exists(ckpt2)


def test_checkpointed_rank_solve_and_resume(tmp_path):
    """Rank-strategy checkpointing: interrupt at a chunk boundary, resume,
    identical MST — the scale path (chunk-granular, replayed vertex labels)."""
    from distributed_ghs_implementation_tpu.graphs.generators import road_grid_graph
    from distributed_ghs_implementation_tpu.models import rank_solver as rs
    from distributed_ghs_implementation_tpu.utils.checkpoint import (
        graph_fingerprint,
        solve_graph_checkpointed,
    )

    g = road_grid_graph(90, 90, seed=21)  # many levels -> several chunks
    ref_ids, ref_frag, _ = solve_graph(g, strategy="rank")

    p = str(tmp_path / "rank.npz")
    fp = graph_fingerprint(g)

    # Simulate preemption: run the solver with a hook that checkpoints and
    # aborts after the second chunk boundary.
    vmin0, ra, rb = rs.prepare_rank_arrays(g)

    class Stop(Exception):
        pass

    calls = []

    def dying_hook(level, fragment, mst, count):
        calls.append(level)
        save_checkpoint(p, fragment, mst, level, fingerprint=fp)
        if len(calls) == 2 and count > 0:
            raise Stop()

    try:
        rs.solve_rank_staged(
            vmin0, ra, rb,
            compact_after=rs._pick_compact_after(g),
            on_chunk=dying_hook,
        )
    except Stop:
        pass
    assert len(calls) == 2
    _, _, lv_saved = load_checkpoint(p, expect_fingerprint=fp)
    assert 0 < lv_saved

    # Resume from the partial checkpoint; must complete to the same MST.
    edge_ids, fragment, levels = solve_graph_checkpointed(
        g, p, strategy="rank"
    )
    assert np.array_equal(edge_ids, ref_ids)
    assert np.array_equal(np.sort(np.unique(fragment)), np.sort(np.unique(ref_frag)))
    assert levels >= lv_saved


def test_checkpointed_filtered_solve_and_resume(tmp_path, monkeypatch):
    """Filter-Kruskal checkpointing: a checkpoint written mid-filtered-solve
    (prefix phase or survivor phase) resumes through the staged path to the
    identical MST."""
    from distributed_ghs_implementation_tpu.graphs.generators import rmat_graph
    from distributed_ghs_implementation_tpu.models import rank_solver as rs
    from distributed_ghs_implementation_tpu.utils.checkpoint import (
        graph_fingerprint,
        solve_graph_checkpointed,
    )

    g = rmat_graph(11, 16, seed=9)  # dense family
    assert rs._pick_family(g) == "dense"
    ref_ids, ref_frag, _ = solve_graph(g, strategy="rank")

    p = str(tmp_path / "filtered.npz")
    fp = graph_fingerprint(g)
    vmin0, ra, rb = rs.prepare_rank_arrays(g)

    class Stop(Exception):
        pass

    calls = []

    def dying_hook(level, fragment, mst, count):
        calls.append(level)
        save_checkpoint(p, fragment, mst, level, fingerprint=fp)
        if len(calls) == 2 and count > 0:
            raise Stop()

    try:
        rs.solve_rank_filtered(vmin0, ra, rb, on_chunk=dying_hook)
    except Stop:
        pass
    assert len(calls) >= 1
    _, mst_saved, lv_saved = load_checkpoint(p, expect_fingerprint=fp)
    assert 0 < lv_saved
    assert mst_saved.shape[0] == ra.shape[0]  # full-width mask contract

    # Resume (the checkpoint routes through the staged initial_state path);
    # the filtered fresh-solve route is forced on by a tiny threshold so the
    # test also covers checkpoint.py's routing decision on a fresh run.
    monkeypatch.setattr(rs, "_FILTER_MIN_RANKS", 1)
    edge_ids, fragment, levels = solve_graph_checkpointed(g, p, strategy="rank")
    assert np.array_equal(edge_ids, ref_ids)
    assert np.array_equal(np.sort(np.unique(fragment)), np.sort(np.unique(ref_frag)))

    # And a fresh checkpointed run end-to-end through the filtered route.
    p2 = str(tmp_path / "filtered_fresh.npz")
    edge_ids2, _, _ = solve_graph_checkpointed(g, p2, strategy="rank")
    assert np.array_equal(edge_ids2, ref_ids)
    assert os.path.exists(p2)


def test_checkpointed_resume_chunked_rebuild(tmp_path, monkeypatch):
    """Resume at the chunked-filter capacity regime (ADVICE r3): the alive
    slots are rebuilt in rank-ordered chunks against the restored partition
    — never through the full-width ``_relabel_slots``, whose suffix-width
    endpoints would RESOURCE_EXHAUSTED at the scales this regime exists for.
    Thresholds are pinned tiny so a small graph drives the chunked path."""
    from distributed_ghs_implementation_tpu.graphs.generators import rmat_graph
    from distributed_ghs_implementation_tpu.models import rank_solver as rs
    from distributed_ghs_implementation_tpu.utils.checkpoint import (
        graph_fingerprint,
        solve_graph_checkpointed,
    )

    g = rmat_graph(11, 16, seed=9)
    ref_ids, ref_frag, _ = solve_graph(g, strategy="rank")
    p = str(tmp_path / "chunked.npz")
    fp = graph_fingerprint(g)
    vmin0, ra, rb = rs.prepare_rank_arrays(g)

    class Stop(Exception):
        pass

    calls = []

    def dying_hook(level, fragment, mst, count):
        calls.append((level, count))
        save_checkpoint(p, fragment, mst, level, fingerprint=fp)
        if count > 0:
            # Die at the FIRST boundary with work pending: the resume below
            # must then run the chunked rebuild's survivor finish for real.
            raise Stop()

    with pytest.raises(Stop):
        rs.solve_rank_filtered(vmin0, ra, rb, on_chunk=dying_hook)
    assert calls and calls[-1][1] > 0  # interrupted with work pending
    _, _, lv_saved = load_checkpoint(p, expect_fingerprint=fp)
    assert 0 < lv_saved

    # Pin the capacity regime on: several rebuild chunks, and any use of the
    # full-width relabel is an immediate failure.
    monkeypatch.setattr(rs, "_FILTER_CHUNK_BYTES", 1 << 10)
    monkeypatch.setattr(rs, "_FILTER_CHUNK_RANKS", 1 << 10)
    assert 8 * ra.shape[0] > rs._FILTER_CHUNK_BYTES

    def forbid(*a, **k):
        raise AssertionError("full-width relabel used in the capacity regime")

    monkeypatch.setattr(rs, "_relabel_slots", forbid)
    edge_ids, fragment, levels = solve_graph_checkpointed(g, p, strategy="rank")
    assert np.array_equal(edge_ids, ref_ids)
    assert np.array_equal(
        np.sort(np.unique(fragment)), np.sort(np.unique(ref_frag))
    )
    assert levels >= lv_saved


def test_checkpointed_sharded_solve_and_resume(tmp_path):
    """Kill+resume drill on the virtual-mesh sharded solve (VERDICT r3 item
    5): interrupt the sharded filtered solve at a checkpoint boundary with
    work still pending, resume on the mesh, land on the byte-identical MST.
    The same checkpoint also restores through the single-chip path — the
    state contract (vertex partition + full-width rank mask) is
    backend-portable."""
    import shutil

    from distributed_ghs_implementation_tpu.graphs.generators import rmat_graph
    from distributed_ghs_implementation_tpu.parallel.rank_sharded import (
        solve_graph_rank_sharded,
    )
    from distributed_ghs_implementation_tpu.utils.checkpoint import (
        graph_fingerprint,
        solve_graph_checkpointed,
        solve_graph_checkpointed_sharded,
    )

    g = rmat_graph(11, 16, seed=9)  # dense family
    ref_ids, ref_frag, _ = solve_graph(g, strategy="rank")
    p = str(tmp_path / "shard.npz")
    fp = graph_fingerprint(g)

    class Stop(Exception):
        pass

    calls = []

    def dying_hook(level, fragment, mask_fn, count):
        calls.append((level, count))
        save_checkpoint(p, fragment, mask_fn(), level, fingerprint=fp)
        if count > 0:
            raise Stop()

    with pytest.raises(Stop):
        solve_graph_rank_sharded(g, filtered=True, on_chunk=dying_hook)
    assert calls and calls[-1][1] > 0  # interrupted with work pending

    # Resume on the mesh.
    p2 = str(tmp_path / "shard_copy.npz")
    shutil.copy(p, p2)
    edge_ids, fragment, levels = solve_graph_checkpointed_sharded(
        g, p, filtered=True
    )
    assert np.array_equal(edge_ids, ref_ids)
    assert np.array_equal(
        np.sort(np.unique(fragment)), np.sort(np.unique(ref_frag))
    )

    # Cross-backend: the same mid-solve checkpoint restores through the
    # single-chip rank path to the same MST.
    edge_ids2, _, _ = solve_graph_checkpointed(g, p2, strategy="rank")
    assert np.array_equal(edge_ids2, ref_ids)


def test_sharded_resume_capacity_guard(tmp_path, monkeypatch):
    """Resume off an EARLY checkpoint (most ranks still alive) with the
    gather budget pinned tiny: the in-place sharded levels must shrink the
    alive set before the compact/all-gather finish (whose replicated width
    would otherwise blow HBM at the scales checkpointing targets), and the
    result stays byte-identical."""
    from distributed_ghs_implementation_tpu.graphs.generators import rmat_graph
    from distributed_ghs_implementation_tpu.parallel import rank_sharded as rsh
    from distributed_ghs_implementation_tpu.utils.checkpoint import (
        graph_fingerprint,
        load_checkpoint,
    )

    g = rmat_graph(11, 16, seed=9)
    ref_ids, _, _ = solve_graph(g, strategy="rank")
    p = str(tmp_path / "early.npz")
    fp = graph_fingerprint(g)

    class Stop(Exception):
        pass

    def dying_hook(level, fragment, mask_fn, count):
        # Save at the very first boundary — the most-alive state possible.
        save_checkpoint(p, fragment, mask_fn(), level, fingerprint=fp)
        raise Stop()

    with pytest.raises(Stop):
        rsh.solve_graph_rank_sharded(g, filtered=True, on_chunk=dying_hook)

    used = []
    orig = rsh.make_rank_sharded_level

    def spying(mesh, rank64=False, kernel="xla"):
        used.append(1)
        return orig(mesh, rank64, kernel)

    monkeypatch.setattr(rsh, "make_rank_sharded_level", spying)
    monkeypatch.setattr(rsh, "_FINISH_GATHER_MAX_SLOTS", 64)
    state = load_checkpoint(p, expect_fingerprint=fp)
    edge_ids, _, _ = rsh.solve_graph_rank_sharded(g, initial_state=state)
    assert used, "capacity guard path was not exercised"
    assert np.array_equal(edge_ids, ref_ids)


def test_sharded_capacity_guard_checkpoints(tmp_path, monkeypatch):
    """ADVICE r4: the capacity-guard level loop must itself fire on_chunk
    periodically — a resume that spends many in-place sharded levels there
    would otherwise save nothing until the finish. Pin the cadence to 1 and
    the gather budget tiny, resume off an early checkpoint, and require (a)
    guard-loop saves with harvestable masks and (b) that resuming from the
    LAST guard-loop save still lands on the reference MST."""
    from distributed_ghs_implementation_tpu.graphs.generators import (
        road_grid_graph,
    )
    from distributed_ghs_implementation_tpu.parallel import rank_sharded as rsh
    from distributed_ghs_implementation_tpu.utils.checkpoint import (
        graph_fingerprint,
        load_checkpoint,
    )

    # High-diameter grid: many in-place guard levels run before the alive
    # count reaches zero, so mid-loop saves fire with count > 0. (An RMAT
    # graph at this scale finishes in one guard level, whose save lands
    # exactly when count hits 0 — indistinguishable from the finish hook.)
    g = road_grid_graph(40, 40, seed=9)
    ref_ids, _, _ = solve_graph(g, strategy="rank")
    p = str(tmp_path / "early.npz")
    fp = graph_fingerprint(g)

    class Stop(Exception):
        pass

    def dying_hook(level, fragment, mask_fn, count):
        save_checkpoint(p, fragment, mask_fn(), level, fingerprint=fp)
        raise Stop()

    with pytest.raises(Stop):
        rsh.solve_graph_rank_sharded(g, on_chunk=dying_hook)

    monkeypatch.setattr(rsh, "_FINISH_GATHER_MAX_SLOTS", 64)
    monkeypatch.setattr(rsh, "_GUARD_CHECKPOINT_EVERY", 1)
    state = load_checkpoint(p, expect_fingerprint=fp)
    saves = []
    p_guard = str(tmp_path / "guard.npz")

    def saving_hook(level, fragment, mask_fn, count):
        # Only guard-loop saves carry count > 0; the finish-stage hook
        # (count == 0) always fires and must not satisfy this test.
        if count > 0:
            saves.append(level)
            save_checkpoint(
                p_guard, fragment, mask_fn(), level, fingerprint=fp
            )

    edge_ids, _, _ = rsh.solve_graph_rank_sharded(
        g, initial_state=state, on_chunk=saving_hook
    )
    assert np.array_equal(edge_ids, ref_ids)
    assert len(saves) >= 1, "guard loop fired no periodic checkpoints"

    state2 = load_checkpoint(p_guard, expect_fingerprint=fp)
    edge_ids2, _, _ = rsh.solve_graph_rank_sharded(g, initial_state=state2)
    assert np.array_equal(edge_ids2, ref_ids)


def test_host_level1_malformed_vmin0_raises():
    """ADVICE r4: a vmin0 that is not the true per-vertex min incident rank
    can make the hook graph a cycle longer than 2; host_level1 must error
    loudly instead of spinning the host forever."""
    from distributed_ghs_implementation_tpu.models.rank_solver import host_level1

    # Three edges forming a directed 3-cycle of hooks: 0->1->2->0.
    vmin0 = np.array([0, 1, 2], dtype=np.int32)
    ra = np.array([0, 1, 2], dtype=np.int32)
    rb = np.array([1, 2, 0], dtype=np.int32)
    with pytest.raises(ValueError, match="did not converge"):
        host_level1(vmin0, ra, rb)


def test_instrumented_rank_strategy():
    from distributed_ghs_implementation_tpu.graphs.generators import road_grid_graph

    g = road_grid_graph(80, 80, seed=12)
    (edge_ids, fragment, levels), metrics = solve_graph_instrumented(
        g, strategy="rank"
    )
    ref_ids, _, _ = solve_graph(g, strategy="rank")
    assert np.array_equal(edge_ids, ref_ids)
    assert metrics.levels, "expected at least one chunk record"
    assert metrics.levels[-1].edges_alive_after == 0
    assert metrics.levels[-1].fragments_after == 1
    # fragment counts must be monotonically non-increasing across chunks
    seq = [m.fragments_before for m in metrics.levels] + [
        metrics.levels[-1].fragments_after
    ]
    assert all(a >= b for a, b in zip(seq, seq[1:]))


def test_checkpoint_every_stride_on_rank_path(tmp_path):
    """every=N on the rank strategy saves at every Nth chunk boundary (plus
    the final state)."""
    from distributed_ghs_implementation_tpu.graphs.generators import road_grid_graph
    from distributed_ghs_implementation_tpu.utils import checkpoint as cp

    g = road_grid_graph(70, 70, seed=4)
    saves = []
    orig = cp.save_checkpoint

    def spy(path, fragment, mst_ranks, level, **kw):
        saves.append(int(level))
        return orig(path, fragment, mst_ranks, level, **kw)

    cp.save_checkpoint = spy
    try:
        p2 = str(tmp_path / "stride.npz")
        cp.solve_graph_checkpointed(g, p2, every=100, strategy="rank")
        sparse_saves = list(saves)
    finally:
        cp.save_checkpoint = orig
    # With a huge stride only the count==0 boundary save plus the final
    # explicit save happen.
    assert len(sparse_saves) <= 2, sparse_saves


def test_host_level2_matches_device_head():
    """host_level2 (the road-family host precompute of level 2) must be a
    bit-exact replica of the device head's 2-level partition and MST
    marks, on both a grid and an RMAT graph."""
    import jax.numpy as jnp

    from distributed_ghs_implementation_tpu.graphs.generators import (
        rmat_graph,
        road_grid_graph,
    )
    from distributed_ghs_implementation_tpu.models import rank_solver as rs

    int32_max = np.iinfo(np.int32).max
    for g in (road_grid_graph(50, 50, seed=3), rmat_graph(10, 8, seed=4)):
        m_pad = rs._bucket_size(g.num_edges)
        n_pad = rs._bucket_size(g.num_nodes)
        vmin0 = np.full(n_pad, int32_max, np.int32)
        vmin0[: g.num_nodes] = g.first_ranks
        ra, rb = g.rank_endpoints(pad_to=m_pad)
        parent1 = rs.host_level1(vmin0, ra, rb)
        parent12, l2_ranks = rs.host_level2(parent1, ra, rb, g.num_edges)
        frag_dev, mst_dev, _fa, _fb, _stats = rs._rank_head(
            jnp.asarray(vmin0), jnp.asarray(ra), jnp.asarray(rb),
            jnp.asarray(parent1), compact_after=2,
        )
        assert np.array_equal(np.asarray(frag_dev), parent12)
        l1marks = np.zeros(m_pad, bool)
        has1 = vmin0 < int32_max
        l1marks[vmin0[has1]] = True
        l2_dev = np.nonzero(np.asarray(mst_dev) & ~l1marks)[0]
        l2_only = l2_ranks[~np.isin(l2_ranks, np.nonzero(l1marks)[0])]
        assert np.array_equal(np.sort(l2_dev), np.sort(l2_only))


def test_road_network_dead_end_prob():
    """dead_end_prob keeps exactly one (min-weight) incident edge at each
    dead-end cell and raises the degree-1 share."""
    from distributed_ghs_implementation_tpu.graphs.generators import (
        random_road_network,
    )

    g0 = random_road_network(60, 60, seed=7)
    g1 = random_road_network(60, 60, seed=7, dead_end_prob=0.35)
    d0 = g0.degrees()
    d1 = g1.degrees()
    share0 = (d0 == 1).mean()
    share1 = (d1 == 1).mean()
    assert share1 > share0 + 0.05, (share0, share1)
    assert g1.num_edges < g0.num_edges


@pytest.mark.parametrize("family_case", ["grid", "sparse"])
def test_solve_rank_l2_production_parity(tmp_path, family_case):
    """Both road families' production routing (host L1+L2, level-3 device
    entry) must be byte-identical to the staged path and survive a
    checkpoint round trip. The sparse staged reference uniquely uses
    compact_after=1 (no device level 2) — the L2 path must match it too."""
    from distributed_ghs_implementation_tpu.graphs.generators import (
        random_road_network,
        road_grid_graph,
    )
    from distributed_ghs_implementation_tpu.models import rank_solver as rs

    if family_case == "grid":
        g = road_grid_graph(60, 60, seed=11)
    else:
        g = random_road_network(
            55, 55, seed=11, axis_prob=0.7, diag_prob=0.2, dead_end_prob=0.2
        )
    assert rs._pick_family(g) == family_case
    assert rs.use_l2_path(family_case)
    # Staged reference (explicit, bypassing the new routing).
    vmin0, ra, rb, parent1 = rs.prepare_rank_arrays_full(g)
    mst_ref, frag_ref, _ = rs.solve_rank_staged(
        vmin0, ra, rb, **rs._family_params(family_case), parent1=parent1
    )
    ref_ids = rs.fetch_mst_edge_ids(g, mst_ref)
    # Production routing.
    ids, frag, _ = rs.solve_graph_rank(g)
    assert np.array_equal(ids, ref_ids)
    assert np.array_equal(
        np.unique(np.asarray(frag_ref)[: g.num_nodes]), np.unique(frag)
    )
    # Checkpointed solve routes through solve_rank_l2 and resumes.
    p = str(tmp_path / "l2.npz")
    ck_ids, _, _ = solve_graph_checkpointed(g, p, strategy="rank")
    assert np.array_equal(ck_ids, ref_ids)
    ck_ids2, _, _ = solve_graph_checkpointed(g, p, strategy="rank")
    assert np.array_equal(ck_ids2, ref_ids)


def test_filtered_head_l2_parity():
    """The dense filtered path with the host-precomputed prefix level 2
    (prepare_rank_arrays_filtered -> _filtered_head_l2) must be
    byte-identical to the device-head filtered path and the staged path."""
    from distributed_ghs_implementation_tpu.graphs.generators import rmat_graph
    from distributed_ghs_implementation_tpu.models import rank_solver as rs

    for seed in (3, 9):
        g = rmat_graph(10, 16, seed=seed)
        # Production gates the L2 prep off below _CENSUS_MIN_SPACE (the
        # speculative regime never consumes it); build the inputs directly
        # to pin the kernel itself at test width.
        vmin0, ra, rb, parent1 = rs.prepare_rank_arrays_full(g)
        n_pad, m_pad = vmin0.shape[0], ra.shape[0]
        prefix, _ = rs._prefix_plan(n_pad, m_pad)
        assert 2 * prefix <= m_pad, "filter split degenerate at test size"
        ra_h, rb_h = g.rank_endpoints(pad_to=m_pad)
        p1_np = np.asarray(parent1)
        p12_np, l2r = rs.host_level2(p1_np, ra_h, rb_h, prefix)
        import jax

        parent12 = jax.device_put(p12_np)
        l2_ranks = jax.device_put(rs._pad_l2_ranks(l2r, m_pad))
        mst_ref, _, _ = rs.solve_rank_filtered(vmin0, ra, rb, parent1=parent1)
        mst_l2, frag_l2, _ = rs.solve_rank_filtered(
            vmin0, ra, rb, parent1=parent1, parent12=parent12,
            l2_ranks=l2_ranks, l2_prefix=prefix,
        )
        assert np.array_equal(np.asarray(mst_ref), np.asarray(mst_l2))
        mst_st, _, _ = rs.solve_rank_staged(vmin0, ra, rb, parent1=parent1)
        assert np.array_equal(np.asarray(mst_st), np.asarray(mst_l2))


def test_speculative_l2_parity(monkeypatch):
    """The speculative program with the host mult-2-prefix L2 must accept
    and match the device-head speculative and the staged reference. The
    filter-scale floor is pinned down so the speculative regime engages at
    test width."""
    from distributed_ghs_implementation_tpu.graphs.generators import rmat_graph
    from distributed_ghs_implementation_tpu.models import rank_solver as rs

    monkeypatch.setattr(rs, "_FILTER_MIN_RANKS", 1024)
    for seed in (3, 9):
        g = rmat_graph(10, 16, seed=seed)
        vmin0, ra, rb, parent1, parent12, l2_ranks, prefix = (
            rs.prepare_rank_arrays_filtered(g)
        )
        assert parent12 is not None and parent1 is not None
        assert prefix == rs._prefix_size(vmin0.shape[0], ra.shape[0], 2)
        r_l2 = rs.solve_rank_filtered_speculative(
            vmin0, ra, rb, parent1=parent1, parent12=parent12,
            l2_ranks=l2_ranks, l2_prefix=prefix,
        )
        # A mismatched l2_prefix must fail loudly, never silently drop marks.
        with pytest.raises(ValueError, match="computed for prefix"):
            rs.solve_rank_filtered_speculative(
                vmin0, ra, rb, parent1=parent1, parent12=parent12,
                l2_ranks=l2_ranks, l2_prefix=prefix // 2,
            )
        r_dev = rs.solve_rank_filtered_speculative(
            vmin0, ra, rb, parent1=parent1
        )
        mst_st, _, _ = rs.solve_rank_staged(vmin0, ra, rb, parent1=parent1)
        # Pin acceptance so the parity checks can never go silently vacuous
        # under a future width retune.
        assert r_l2 is not None and r_dev is not None
        assert np.array_equal(np.asarray(r_l2[0]), np.asarray(r_dev[0]))
        assert np.array_equal(np.asarray(r_l2[0]), np.asarray(mst_st))


def test_production_solver_chunked_spec_regime(monkeypatch):
    """make_production_solver's chunked (on_chunk) form in the speculative
    regime must NOT consume the mult-2-prefix parent12 (the prefix
    comparison quarantines it) and must still land on the staged MST —
    pinning the receipt's 'quarantine is test-pinned' claim."""
    from distributed_ghs_implementation_tpu.graphs.generators import rmat_graph
    from distributed_ghs_implementation_tpu.models import rank_solver as rs

    monkeypatch.setattr(rs, "_FILTER_MIN_RANKS", 1024)
    g = rmat_graph(10, 16, seed=3)
    vmin0, ra, rb, parent1 = rs.prepare_rank_arrays_full(g)
    mst_ref, _, _ = rs.solve_rank_staged(vmin0, ra, rb, parent1=parent1)
    calls = []

    def hook(level, fragment, mst, count):
        calls.append(level)

    solve = rs.make_production_solver(g)
    mst, frag, _ = solve(on_chunk=hook)
    assert calls, "chunked form fired no on_chunk"
    assert np.array_equal(np.asarray(mst), np.asarray(mst_ref))


def test_broadcast_resume_state_single_process_passthrough():
    """Single-process runs skip the collective: state comes back unchanged."""
    from distributed_ghs_implementation_tpu.parallel import multihost

    state = (
        np.arange(6, dtype=np.int32),
        np.zeros(12, dtype=bool),
        3,
    )
    assert multihost.broadcast_resume_state(state) is state
    assert multihost.broadcast_resume_state(None) is None


def test_broadcast_resume_state_single_process_error():
    """error=True (the primary's pre-raise abort signal) returns None in a
    single-process run so the caller's re-raise proceeds — regression guard
    for the checkpoint abort discipline."""
    from distributed_ghs_implementation_tpu.parallel import multihost

    state = (np.arange(3, dtype=np.int32), np.ones(5, dtype=bool), 1)
    assert multihost.broadcast_resume_state(state, error=True) is None
    assert multihost.broadcast_resume_state(None, error=True) is None


def test_failure_report_protocol_nodes_on_failed_run():
    """The protocol table coexists with a failing verification: edge-state
    tallies, per-node rows, and the alive-edge diagnosis all populate."""
    import dataclasses

    from distributed_ghs_implementation_tpu.api import minimum_spanning_forest
    from distributed_ghs_implementation_tpu.protocol.runner import run_protocol
    from distributed_ghs_implementation_tpu.utils.diagnostics import failure_report
    from distributed_ghs_implementation_tpu.utils.verify import verify_result

    g = erdos_renyi_graph(20, 0.3, seed=23)
    nodes, _ = run_protocol(g)
    result = minimum_spanning_forest(g, backend="protocol")
    broken = dataclasses.replace(result, edge_ids=result.edge_ids[:-1])
    v = verify_result(broken)
    assert not v.ok
    report = failure_report(broken, v, nodes=nodes)
    proto = report["protocol"]
    assert proto["edge_state_totals"]["BRANCH"] == 2 * (g.num_nodes - 1)
    assert not proto["nodes_truncated"] and len(proto["nodes"]) == g.num_nodes
    halted_roots = [r for r in proto["nodes"] if r["halted"]]
    assert halted_roots, "a completed protocol run must have halted roots"
    assert all(r["messages_processed"] > 0 for r in proto["nodes"])
    assert report["verification"]["ok"] is False
    assert report["edges"]["alive_inter_fragment"] > 0
