"""Mesh construction helpers.

One mesh axis (``"edges"``) carries the edge partition. Multi-host runs reuse
the same axis: ``jax.distributed.initialize`` + the full device list makes the
combines ride ICI within a slice and DCN across hosts, replacing the
reference's mpiexec/SLURM rank layout (``README_MPI.md:78-92``).
"""

from __future__ import annotations

from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh

EDGE_AXIS = "edges"


def edge_mesh(devices: Sequence | None = None, num_devices: int | None = None) -> Mesh:
    """A 1-D mesh over ``devices`` (default: all) with the edge axis."""
    if devices is None:
        devices = jax.devices()
        if num_devices is not None:
            devices = devices[:num_devices]
    return Mesh(np.asarray(devices), (EDGE_AXIS,))


def shard_map_compat(fn, mesh, in_specs, out_specs):
    """``shard_map`` across JAX versions (moved out of experimental in 0.6+).

    Replication of the pmin-combined outputs isn't provable by the static
    checker through ``while_loop``, so the check is disabled.
    """
    try:
        from jax import shard_map as _sm  # jax >= 0.6

        return _sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=False)
    except (ImportError, TypeError):
        pass
    try:
        from jax.experimental.shard_map import shard_map as _sm_exp

        return _sm_exp(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=False)
    except TypeError:
        from jax.experimental.shard_map import shard_map as _sm_exp2

        return _sm_exp2(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs)
