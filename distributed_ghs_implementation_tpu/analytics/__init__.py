"""analytics/ — the multi-query front door over the serving stack.

One registry (:mod:`analytics.kinds`) maps each supported query *kind* —
``mst``, ``components``, ``k_msf``, ``bottleneck``, ``path_max`` — to its
solver entry, result schema, NetworkX oracle, verify adapter, and default
SLO class; thin wrappers (:mod:`analytics.solvers`) derive every kind from
the same GHS/Borůvka level loop the MST path runs. See ``docs/ANALYTICS.md``.
"""

from distributed_ghs_implementation_tpu.analytics.kinds import (  # noqa: F401
    KINDS,
    KindSpec,
    cache_token,
    get,
    known,
    parse_params,
)
