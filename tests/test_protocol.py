"""GHS protocol state machine on the deterministic event transport.

The protocol backend must agree *exactly* with the batched kernel — two
independent implementations of the same total order (weight, edge id) — and
stay correct under adversarial message latencies, where the reference's
thread/MPI versions lose MSTs to races (SURVEY.md: wrong 2/3 runs at 20
nodes).
"""

import numpy as np
import pytest

from distributed_ghs_implementation_tpu.api import minimum_spanning_forest
from distributed_ghs_implementation_tpu.graphs.edgelist import Graph
from distributed_ghs_implementation_tpu.graphs.generators import (
    erdos_renyi_graph,
    line_graph,
    readme_sample_graph,
    reference_random_graph,
    simple_test_graph,
)
from distributed_ghs_implementation_tpu.protocol import (
    EdgeState,
    SimTransport,
    run_protocol,
)
from distributed_ghs_implementation_tpu.utils.verify import verify_result


def test_readme_sample():
    r = minimum_spanning_forest(readme_sample_graph(), backend="protocol")
    assert r.total_weight == 20
    assert sorted(r.edges) == [(0, 1), (1, 2), (2, 3), (3, 4), (3, 5)]


def test_simple_fixture():
    r = minimum_spanning_forest(simple_test_graph(), backend="protocol")
    assert r.total_weight == 3


@pytest.mark.parametrize("seed", range(8))
def test_matches_batched_kernel_exactly(seed):
    g = erdos_renyi_graph(45, 0.15, seed=seed)
    rp = minimum_spanning_forest(g, backend="protocol")
    rd = minimum_spanning_forest(g, backend="device")
    assert np.array_equal(rp.edge_ids, rd.edge_ids)
    assert verify_result(rp).ok


def test_reference_20_node_config():
    """The config the reference gets wrong 2/3 of the time
    (ghs_implementation.py:793) — must verify every run here."""
    g = reference_random_graph(20, 0.3, 500)
    for _ in range(3):
        r = minimum_spanning_forest(g, backend="protocol")
        assert verify_result(r).ok


def test_reference_50_node_extrapolation_config():
    """The 50-node extrapolation of the reference's :793 config (same
    edge probability and seed). The reference's thread backend hit its 30 s
    timeout there and returned a wrong forest (52 edges, weight 89 vs the
    oracle's 82 — SURVEY.md §6); the protocol tier must return the exact
    MST, every run, with no timeout heuristics in the loop."""
    g = reference_random_graph(50, 0.3, 500)
    rd = minimum_spanning_forest(g, backend="device")
    for _ in range(3):
        r = minimum_spanning_forest(g, backend="protocol")
        assert verify_result(r).ok
        assert np.array_equal(r.edge_ids, rd.edge_ids)
        assert r.num_edges == 49  # a spanning tree, not a truncated forest


def test_determinism_exact_message_counts():
    g = erdos_renyi_graph(30, 0.2, seed=5)
    _, t1 = run_protocol(g)
    _, t2 = run_protocol(g)
    assert t1.messages_sent == t2.messages_sent
    assert t1.messages_deferred == t2.messages_deferred


def test_adversarial_latencies():
    """Skewed deterministic link delays reorder deliveries; the protocol's
    deferral rules (not luck) must keep the MST exact."""
    g = erdos_renyi_graph(35, 0.2, seed=9)
    expected = minimum_spanning_forest(g, backend="device")
    for a, b in [(1, 7), (5, 1), (3, 11)]:
        transport = SimTransport(latency=lambda s, d: a + ((s * 31 + d * 17) % b))
        nodes, _ = run_protocol(g, transport=transport)
        branch = {
            (min(v, e.neighbor), max(v, e.neighbor))
            for v, n in nodes.items()
            for e in n.edges.values()
            if e.state == EdgeState.BRANCH
        }
        assert branch == {tuple(e) for e in expected.edges}


def test_disconnected_and_isolated():
    g = Graph.from_edges(5, [(0, 1, 1), (1, 2, 2)])  # vertices 3, 4 isolated
    r = minimum_spanning_forest(g, backend="protocol")
    assert r.num_components == 3
    assert r.num_edges == 2


def test_high_diameter_line():
    r = minimum_spanning_forest(line_graph(64), backend="protocol")
    assert r.num_edges == 63


def test_message_complexity():
    """GHS bound: <= 5*n*log2(n) + 2*m messages (README.md:77-80 claims
    O(n log n + m) optimality — here it is enforced, not claimed)."""
    g = erdos_renyi_graph(60, 0.15, seed=3)
    _, t = run_protocol(g)
    n, m = g.num_nodes, g.num_edges
    assert t.messages_sent <= 5 * n * np.log2(n) + 2 * m


def test_ties_all_equal_weights():
    g = erdos_renyi_graph(30, 0.2, seed=4, weight_low=5, weight_high=5)
    r = minimum_spanning_forest(g, backend="protocol")
    assert verify_result(r).ok


def test_message_complexity_bound():
    """The reference claims O(n log n + m) message complexity
    (/root/reference/README.md:77-80) but never measures it; the protocol
    backend's transport counts messages, so assert the bound empirically
    across growing sizes (constant factor from classic GHS analysis: 5n log n
    + 10m covers wakeups, TEST/ACCEPT/REJECT, REPORT and CHANGEROOT)."""
    import math

    from distributed_ghs_implementation_tpu.graphs.generators import (
        gnm_random_graph,
    )
    from distributed_ghs_implementation_tpu.protocol.runner import run_protocol

    for n, m_target, seed in [(64, 256, 1), (128, 512, 2), (256, 1024, 3)]:
        g = gnm_random_graph(n, m_target, seed=seed)
        nodes, transport = run_protocol(g)
        bound = 5 * n * math.log2(n) + 10 * g.num_edges
        assert transport.messages_sent <= bound, (
            n, g.num_edges, transport.messages_sent, bound,
        )


def test_transport_livelock_guard_raises():
    """A node that defers forever must trip the max_events guard, not spin:
    the deterministic analog of the reference's requeue-cap hang."""

    class AlwaysDefer:
        def handle(self, msg):
            return False

    from distributed_ghs_implementation_tpu.protocol.messages import (
        Message,
        MessageType,
    )

    transport = SimTransport(max_events=1000)
    transport.send(0, 0, Message(MessageType.TEST, sender=0))
    with pytest.raises(RuntimeError, match="did not quiesce within 1000 events"):
        transport.run({0: AlwaysDefer()})
    assert transport.messages_deferred > 0
