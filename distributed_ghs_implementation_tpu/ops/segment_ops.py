"""Segment reductions: the minimum-outgoing-edge (MOE) search as dense array ops.

One GHS level's TEST/ACCEPT/REJECT probing plus the REPORT convergecast
(``/root/reference/ghs_implementation.py:235-353``) is, in batched form, a
single question per fragment: *what is the minimum-weight edge leaving me?*
That is two ``segment_min`` passes over the directed edge list keyed by the
source endpoint's fragment id — pass 1 finds the minimum weight, pass 2
tie-breaks among weight-achieving edges by global directed slot id. Because
slots are interleaved (``graphs/edgelist.py``), slot order is a total order on
*undirected* edges, which makes the per-fragment choice globally consistent —
the property that confines union-find hook cycles to mutual pairs.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp


def segment_min(data: jax.Array, segment_ids: jax.Array, num_segments: int) -> jax.Array:
    """Per-segment minimum; empty segments get the dtype's identity (max/+inf)."""
    return jax.ops.segment_min(data, segment_ids, num_segments=num_segments)


def weight_sentinel(dtype) -> jax.Array:
    dtype = jnp.dtype(dtype)
    if jnp.issubdtype(dtype, jnp.integer):
        return jnp.asarray(jnp.iinfo(dtype).max, dtype)
    return jnp.asarray(jnp.inf, dtype)


INT32_MAX = jnp.iinfo(jnp.int32).max


def fragment_moe(
    fragment: jax.Array,
    src: jax.Array,
    dst: jax.Array,
    w: jax.Array,
    *,
    axis_name: str | None = None,
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Per-fragment minimum outgoing edge over (optionally sharded) edge slots.

    Args:
      fragment: ``[n]`` int32, fragment id per vertex (always a root id).
      src, dst: ``[e]`` int32 directed slot endpoints (a local shard when
        ``axis_name`` is set).
      w: ``[e]`` weights (int32 or float32; sentinel = dtype max / +inf).
      axis_name: if set, combine per-fragment minima across this mesh axis with
        ``lax.pmin`` — the ICI replacement for the reference's MPI
        point-to-point REPORT convergecast.

    Returns:
      ``(has_moe[n], moe_w[n], moe_slot[n], moe_dst_frag[n])`` — whether each
      fragment has an outgoing edge, its weight, the *global* directed slot id
      chosen (INT32_MAX when none), and the fragment on the other end.
    """
    n = fragment.shape[0]
    e = src.shape[0]
    wmax = weight_sentinel(w.dtype)

    f_src = fragment[src]
    f_dst = fragment[dst]
    alive = f_src != f_dst

    # Pass 1: minimum outgoing weight per fragment.
    w_masked = jnp.where(alive, w, wmax)
    moe_w = segment_min(w_masked, f_src, n)
    if axis_name is not None:
        moe_w = jax.lax.pmin(moe_w, axis_name)

    # Pass 2: among weight-achieving edges, minimum global slot id.
    slot_ids = jnp.arange(e, dtype=jnp.int32)
    if axis_name is not None:
        shard = jax.lax.axis_index(axis_name).astype(jnp.int32)
        slot_ids = slot_ids + shard * e
    cand = alive & (w == moe_w[f_src])
    slot_masked = jnp.where(cand, slot_ids, INT32_MAX)
    local_moe_slot = segment_min(slot_masked, f_src, n)
    if axis_name is not None:
        moe_slot = jax.lax.pmin(local_moe_slot, axis_name)
    else:
        moe_slot = local_moe_slot
    has_moe = moe_slot < INT32_MAX

    # Pass 3: destination fragment of the winning slot. Single device: a plain
    # gather. Sharded: only the owner shard knows dst, so each shard proposes
    # its local winner's destination (or INT32_MAX) and a pmin selects it.
    if axis_name is None:
        safe = jnp.where(has_moe, moe_slot, 0)
        moe_dst_frag = jnp.where(has_moe, f_dst[safe], jnp.arange(n, dtype=jnp.int32))
    else:
        i_won = has_moe & (local_moe_slot == moe_slot)
        safe = jnp.where(i_won, local_moe_slot - slot_ids[0], 0)
        proposal = jnp.where(i_won, f_dst[safe], INT32_MAX)
        moe_dst_frag = jax.lax.pmin(proposal, axis_name)
        moe_dst_frag = jnp.where(has_moe, moe_dst_frag, jnp.arange(n, dtype=jnp.int32))
    return has_moe, moe_w, moe_slot, moe_dst_frag
