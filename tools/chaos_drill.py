#!/usr/bin/env python
"""Chaos drill CLI: fault matrix (lossy transport x induced solver faults x
torn checkpoint writes) vs the MST oracle.

    python tools/chaos_drill.py [--full] [--no-solver] [--output report.json]

Exit code 0 iff every case reaches oracle parity. The same drill is
reachable as ``python -m distributed_ghs_implementation_tpu chaos``; the
fast subset also runs inside tier-1 (``tests/test_resilience.py``).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from distributed_ghs_implementation_tpu.utils.chaos import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
