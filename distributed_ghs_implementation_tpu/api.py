"""Public API: the reference's driver surface, TPU-backed.

``GHSAlgorithm(num_nodes, edges).run() -> [(u, v), ...]`` mirrors the thread
driver (``/root/reference/ghs_implementation.py:416-490``) including the
``(min(u,v), max(u,v))`` edge normalization of its MST harvest
(``:481-490``), but dispatches to the batched Borůvka kernel instead of
spawning threads. ``backend`` selects the execution path:

  * ``"device"`` (default) — single-device JAX solve (TPU when present, else
    CPU); the replacement for the thread simulator (C2/C4/C6).
  * ``"sharded"`` — edges sharded over a ``jax.sharding.Mesh``; the
    replacement for the MPI backend (C3/C5/C7).
  * ``"protocol"`` — the message-level GHS state machine on the deterministic
    event-queue transport (protocol-parity backend, C1/C4/C5).
  * ``"host"`` — native single-core Kruskal over the precomputed rank order
    (byte-identical; the measured CPU baseline and a no-accelerator escape
    hatch — integer weights + native toolchain required).
"""

from __future__ import annotations

import dataclasses
import time
from typing import TYPE_CHECKING, Iterable, List, Optional, Tuple

import numpy as np

from distributed_ghs_implementation_tpu.graphs.edgelist import Graph

if TYPE_CHECKING:  # deferred: resilience imports stay off the cold path
    from distributed_ghs_implementation_tpu.utils.resilience import IncidentLog


@dataclasses.dataclass
class MSTResult:
    """Everything the reference reports about a run, in one place.

    The reference scatters this across console prints and JSON dumps
    (``ghs_implementation.py:766-776``, ``ghs_implementation_mpi.py:811-816``).
    """

    graph: Graph
    edge_ids: np.ndarray  # indices into graph.u/v/w
    num_levels: int
    wall_time_s: float
    backend: str
    num_components: int
    # Populated by supervised solves only: the structured attempt/fallback
    # record.
    incidents: Optional["IncidentLog"] = None

    @property
    def edges(self) -> List[Tuple[int, int]]:
        """MST edges as ``(min(u,v), max(u,v))`` pairs — the harvest format of
        ``ghs_implementation.py:481-490``."""
        return [
            (int(a), int(b))
            for a, b in zip(self.graph.u[self.edge_ids], self.graph.v[self.edge_ids])
        ]

    @property
    def weighted_edges(self) -> List[Tuple[int, int, float]]:
        cast = int if self.graph.is_integer_weighted else float
        return [
            (int(a), int(b), cast(c))
            for a, b, c in zip(
                self.graph.u[self.edge_ids],
                self.graph.v[self.edge_ids],
                self.graph.w[self.edge_ids],
            )
        ]

    @property
    def total_weight(self):
        w = self.graph.w[self.edge_ids].sum()
        return int(w) if self.graph.is_integer_weighted else float(w)

    @property
    def num_edges(self) -> int:
        return int(self.edge_ids.shape[0])

    @property
    def is_spanning_tree(self) -> bool:
        """n-1 edges over one component — the reference's edge-count check
        (``ghs_implementation_mpi.py:805-808``)."""
        return self.num_components == 1 and self.num_edges == self.graph.num_nodes - 1


def _solve(graph: Graph, backend: str) -> Tuple[np.ndarray, np.ndarray, int]:
    if backend == "device":
        from distributed_ghs_implementation_tpu.models.boruvka import solve_graph

        return solve_graph(graph)
    if backend == "sharded":
        try:
            from distributed_ghs_implementation_tpu.parallel.sharded import (
                solve_graph_sharded,
            )
        except ImportError as e:
            raise NotImplementedError("sharded backend unavailable") from e
        return solve_graph_sharded(graph)
    if backend == "protocol":
        try:
            from distributed_ghs_implementation_tpu.protocol.runner import (
                solve_graph_protocol,
            )
        except ImportError as e:
            raise NotImplementedError("protocol backend unavailable") from e
        return solve_graph_protocol(graph)
    if backend == "host":
        from distributed_ghs_implementation_tpu.models.rank_solver import (
            solve_graph_kruskal_host,
        )

        return solve_graph_kruskal_host(graph)
    raise ValueError(
        f"unknown backend {backend!r}; expected device|sharded|protocol|host"
    )


def minimum_spanning_forest(
    graph: Graph,
    *,
    backend: str = "device",
    supervised: bool = False,
    supervisor=None,
) -> MSTResult:
    """Compute the minimum spanning forest (tree per component) of ``graph``.

    ``supervised=True`` runs the solve under the self-healing supervisor
    (``utils.resilience``): watchdog deadline, bounded retry with backoff on
    transient device errors, and the ``sharded -> device -> stepped -> host``
    degradation ladder, starting at ``backend`` (backends outside the ladder,
    e.g. ``"protocol"``, enter at ``"device"``). The result's ``backend``
    then reads ``"supervised/<rung-that-succeeded>"`` and ``incidents``
    carries the structured attempt log. Pass a preconfigured
    ``utils.resilience.Supervisor`` as ``supervisor`` to control the policy
    (passing one implies ``supervised=True``).
    """
    t0 = time.perf_counter()
    incidents = None
    supervised = supervised or supervisor is not None
    if supervised:
        from distributed_ghs_implementation_tpu.utils.resilience import Supervisor

        sup = supervisor or Supervisor()
        edge_ids, fragment, levels, incidents = sup.solve(graph, entry=backend)
        backend_label = f"supervised/{incidents.final_rung or backend}"
    else:
        edge_ids, fragment, levels = _solve(graph, backend)
        backend_label = backend
    wall = time.perf_counter() - t0
    num_components = int(np.unique(fragment).size) if graph.num_nodes else 0
    return MSTResult(
        graph=graph,
        edge_ids=edge_ids,
        num_levels=levels,
        wall_time_s=wall,
        backend=backend_label,
        num_components=num_components,
        incidents=incidents,
    )


def minimum_spanning_forest_batch(
    graphs,
    *,
    backend: str = "device",
    policy=None,
    engine=None,
) -> List[MSTResult]:
    """Solve many independent graphs, coalescing same-bucket ones into
    single device dispatches (the ``batch/`` lane engine).

    Results are in input order and edge-for-edge identical to per-graph
    :func:`minimum_spanning_forest` — lane stacking is a disjoint union,
    and the global rank order makes each graph's MSF unique. Graphs too
    large to batch (``policy.admits`` is false) bypass to supervised
    single-graph solves, as do all graphs on non-``device`` backends
    (batching is a single-device dispatch optimization). Pass a
    ``batch.BatchPolicy`` to tune lane count/bucket ceilings, or a
    prebuilt ``batch.BatchEngine`` to share its queue and telemetry.
    """
    graphs = list(graphs)
    if backend != "device":
        return [minimum_spanning_forest(g, backend=backend) for g in graphs]
    if engine is None:
        from distributed_ghs_implementation_tpu.batch.engine import BatchEngine

        engine = BatchEngine(policy=policy)
    return engine.solve_many(graphs)


def minimum_spanning_tree(graph: Graph, *, backend: str = "device") -> MSTResult:
    """Like :func:`minimum_spanning_forest` but requires a connected graph."""
    result = minimum_spanning_forest(graph, backend=backend)
    if result.num_components > 1:
        raise ValueError(
            f"graph is disconnected ({result.num_components} components); "
            "use minimum_spanning_forest"
        )
    return result


class GHSAlgorithm:
    """Drop-in analog of the reference driver (``ghs_implementation.py:416-442``).

    >>> ghs = GHSAlgorithm(num_nodes=6, edges=[(0, 1, 1), ...])
    >>> mst_edges = ghs.run()          # [(u, v), ...]
    >>> ghs.result.total_weight
    """

    def __init__(
        self,
        num_nodes: int,
        edges: Iterable[Tuple[int, int, float]],
        *,
        backend: str = "device",
    ):
        self.graph = Graph.from_edges(num_nodes, edges)
        self.backend = backend
        self.result: Optional[MSTResult] = None

    def run(self, timeout: float | None = None) -> List[Tuple[int, int]]:
        """Compute the MST; returns normalized edge pairs.

        ``timeout`` is accepted for signature parity with
        ``ghs_implementation.py:442`` but unused — the solver terminates in at
        most ``ceil(log2 n)`` levels by construction, so there is nothing to
        time out (the reference needed it to escape its liveness bugs).
        """
        del timeout
        self.result = minimum_spanning_forest(self.graph, backend=self.backend)
        return self.result.edges

    def get_mst_weight(self):
        if self.result is None:
            raise RuntimeError("call run() first")
        return self.result.total_weight
