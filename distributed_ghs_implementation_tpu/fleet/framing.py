"""Length-prefixed JSON framing for the router <-> worker channels.

The single-process service speaks newline-delimited JSON (one request per
line, ``serve/service.py``); the fleet cannot: a worker's channel carries
*interleaved* responses written by concurrent request threads, and a torn
line would silently merge two frames. Each frame is therefore::

    <payload-byte-length>\\n<payload>\\n

— the reader knows exactly how many bytes belong to the frame before it
parses a single one, a short read is detected (not mis-parsed), and the
trailing newline keeps frames greppable in a captured channel dump. The
same framing runs over OS pipes (the single-host fleet) and TCP sockets
(``fleet/transport.py``) — a frame is a frame on either medium.

Error surface: :func:`read_frame` returns ``None`` only on a *clean* EOF
at a frame boundary (the peer closed in between frames — drain, or death)
and raises :class:`FrameError` on everything garbled: a non-numeric or
over-long length prefix, a length past ``max_bytes`` (a corrupt prefix
must not become a multi-gigabyte allocation — the reader sizes its buffer
from attacker/garbage-controlled bytes), a payload the stream could not
complete, or bytes that are not one JSON object. ``FrameError`` subclasses
``ValueError``, so callers that treated every framing problem as
peer-death (the router's reader catches ``(OSError, ValueError)``) keep
doing so unchanged — the typed error exists for callers that want to
*distinguish* a corrupt peer from a closed one (tests, the drills, the
dial-in hello validation). Writes must be serialized by the caller (the
transports hold a per-connection write lock).
"""

from __future__ import annotations

import json
from typing import IO, Optional

#: A frame larger than this is a protocol violation (a runaway edges_out
#: response, or garbage on the channel) — refuse to buffer it. Callers with
#: tighter expectations (the hello exchange is a few hundred bytes) pass
#: their own ``max_bytes``.
MAX_FRAME_BYTES = 256 * 1024 * 1024

#: The length prefix of MAX_FRAME_BYTES is 9 digits + newline; anything
#: longer is garbage, and an unbounded ``readline`` on a corrupt stream
#: would buffer until memory runs out.
_MAX_HEADER_BYTES = 20


class FrameError(ValueError):
    """A garbled frame: corrupt length prefix, oversize declaration,
    truncated payload, or non-JSON bytes. The channel can no longer be
    trusted to be frame-aligned — the only safe response is to drop it."""


def encode_frame(obj: dict) -> bytes:
    """``obj`` as one wire-ready frame (length prefix + payload + LF)."""
    payload = json.dumps(obj, separators=(",", ":")).encode("utf-8")
    return b"%d\n" % len(payload) + payload + b"\n"


def write_frame(stream: IO[bytes], obj: dict) -> None:
    """Serialize ``obj`` as one length-prefixed frame and flush."""
    stream.write(encode_frame(obj))
    stream.flush()


def read_frame(
    stream: IO[bytes], *, max_bytes: int = MAX_FRAME_BYTES
) -> Optional[dict]:
    """Read one frame; ``None`` on clean EOF, :class:`FrameError` on
    anything garbled (see module docstring for the contract)."""
    header = stream.readline(_MAX_HEADER_BYTES)
    if not header:
        return None
    if not header.endswith(b"\n"):
        raise FrameError(
            f"frame header not newline-terminated within "
            f"{_MAX_HEADER_BYTES} bytes: {header[:32]!r}"
        )
    try:
        n = int(header)
    except ValueError:
        raise FrameError(f"non-numeric frame length prefix: {header!r}") from None
    if n < 0 or n > max_bytes:
        raise FrameError(
            f"declared frame length {n} outside [0, {max_bytes}]"
        )
    payload = stream.read(n)
    if payload is None or len(payload) != n:
        raise FrameError(
            f"truncated frame: header promised {n} bytes, "
            f"got {0 if payload is None else len(payload)}"
        )
    stream.read(1)  # the trailing newline (EOF here still parsed a frame)
    try:
        obj = json.loads(payload)
    except ValueError:
        raise FrameError(
            f"frame payload is not valid JSON ({n} bytes)"
        ) from None
    if not isinstance(obj, dict):
        raise FrameError(f"frame payload is {type(obj).__name__}, not object")
    return obj
