"""Windowed batched MSF maintenance: one vmapped pass per update window.

``serve/dynamic.py`` proves the exchange rules one update at a time: every
insert walks a tree path (host BFS), every delete runs its own
``fragment_moe`` dispatch, and every structural change is an ``O(m)``
``np.insert``. At thousands of updates/sec that per-update walk is the
bottleneck, and it is also semantically awkward: a window containing
``insert(e) -> delete(e)`` applies both in arrival order when only the net
effect matters.

This module applies a whole window at once:

1. **Coalesce** (:func:`coalesce`) — last-write-wins per undirected edge.
   A window's worth of churn on the same edge collapses to its net op
   (``set`` to the final weight, or ``delete``); self-cancelling pairs
   vanish before any array is touched.
2. **Structural batch apply** — one vectorized rebuild of the canonical
   sorted arrays (``concatenate`` + ``lexsort``) instead of per-update
   splices.
3. **Cut pass** — deletions and weight *increases* first. Surviving tree
   edges whose weight did not increase are provably still in the MSF of
   that intermediate graph (cut property: every other edge got heavier or
   vanished), so their components seed a batched Borůvka
   (``fragment_moe`` + ``hook_and_compress`` rounds over all remaining
   edges) that finds every replacement edge for every broken cut in
   ``O(log n)`` vmapped rounds — not one MOE dispatch per deletion.
4. **Cycle pass** — insertions and weight *decreases*. The new MSF is a
   subset of (cut-pass MSF ∪ changed edges) — the classic insert-only
   sparsification — so one more seeded-Borůvka pass over that ``O(n)``-edge
   subgraph finishes the window exactly.

The result is *edge-for-edge* identical to a fresh solve (the ``(w, u, v)``
total order makes the MSF unique; property tests randomize whole update
streams against fresh solves). Escape hatches, test-pinned: ``sequential``
mode replays the coalesced window through the per-update exchange rules,
and ``resolve`` (also taken when a window exceeds the resolve threshold or
fails the forest check) hands the graph to a supervised full solve.

The Borůvka rounds run through one jitted kernel (:func:`_moe_round`) with
edge arrays padded to power-of-two buckets, so a long-lived stream
compiles a handful of shapes once — :func:`warm_window_kernels` lets
``batch/warmup.py`` pay that before traffic arrives.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Iterable, List, Optional, Sequence, Tuple, Union

import numpy as np

from distributed_ghs_implementation_tpu.models.boruvka import _next_pow2
from distributed_ghs_implementation_tpu.obs.events import BUS
from distributed_ghs_implementation_tpu.serve.dynamic import (
    DynamicMST,
    Update,
)

_MODES = ("batched", "sequential", "resolve")


# ----------------------------------------------------------------------
# Coalescing
# ----------------------------------------------------------------------
def coalesce(updates: Sequence[Union[Update, dict]]) -> List[Update]:
    """Collapse a window to its net per-edge effect (last write wins).

    The last update touching an undirected edge decides its final state:
    ``delete`` nets to a delete (a no-op when the edge never existed —
    which is how ``insert -> delete`` self-cancels), anything carrying a
    weight nets to a ``set`` (emitted as kind ``insert``, which the
    exchange rules already treat as reweight-if-present). Output order is
    canonical ``(u, v)``, so a window's net effect is independent of
    arrival order — the semantic fix for ``dynamic.py``'s
    arrival-order-sensitive same-edge pairs.
    """
    net: Dict[Tuple[int, int], Update] = {}
    for upd in updates:
        if not isinstance(upd, Update):
            upd = Update.from_dict(upd)
        a, b = (upd.u, upd.v) if upd.u < upd.v else (upd.v, upd.u)
        if upd.kind == "delete":
            net[(a, b)] = Update("delete", a, b)
        else:
            net[(a, b)] = Update("insert", a, b, upd.w)
    return [net[key] for key in sorted(net)]


def random_update_stream(
    rng,
    seed_graph,
    size: int,
    *,
    kinds: Sequence[str] = ("insert", "delete", "reweight"),
    max_w: int = 1000,
) -> List[Update]:
    """``size`` seeded mutations valid against ANY chain state grown from
    ``seed_graph``: inserts of fresh random pairs, deletes and reweights
    drawn from the SEED's edge set. Deleting an already-deleted edge is a
    defined no-op, so the stream is path-independent — ``bench.py
    --update-stream`` and ``tools/load_drill.py --update-heavy`` both
    publish windows of these without tracking the evolving edge set, and
    MUST share this generator so the gated bench workload and the drill
    workload cannot silently diverge. ``kinds`` weights the mix by
    repetition; weights draw from ``[1, max_w)``.
    """
    n = int(seed_graph.num_nodes)
    out: List[Update] = []
    for _ in range(size):
        kind = kinds[int(rng.integers(0, len(kinds)))]
        if kind == "insert":
            a, b = (int(x) for x in rng.integers(0, n, 2))
            while a == b:
                a, b = (int(x) for x in rng.integers(0, n, 2))
            out.append(Update("insert", min(a, b), max(a, b),
                              int(rng.integers(1, max_w))))
        else:
            j = int(rng.integers(0, seed_graph.num_edges))
            u, v = int(seed_graph.u[j]), int(seed_graph.v[j])
            if kind == "delete":
                out.append(Update("delete", u, v))
            else:
                out.append(Update("reweight", u, v,
                                  int(rng.integers(1, max_w))))
    return out


# ----------------------------------------------------------------------
# The jitted Borůvka round (padded shapes -> bounded compiles)
# ----------------------------------------------------------------------
_moe_round_jit = None


def _moe_round(fragment, src, dst, rank, ra, rb):
    """One batched Borůvka round: per-fragment MOE + hook-and-compress.

    Returns ``(has, moe_rank, new_fragment)`` — the chosen ranks are read
    out *before* the merge so the host loop can accumulate the window's
    replacement edges round by round.
    """
    global _moe_round_jit
    if _moe_round_jit is None:
        import jax

        from distributed_ghs_implementation_tpu.ops.segment_ops import (
            fragment_moe,
        )
        from distributed_ghs_implementation_tpu.ops.union_find import (
            hook_and_compress,
        )

        def round_fn(fragment, src, dst, rank, ra, rb):
            has, moe_rank, dstf = fragment_moe(fragment, src, dst, rank, ra, rb)
            new_fragment, _ = hook_and_compress(has, dstf, fragment)
            return has, moe_rank, new_fragment

        _moe_round_jit = jax.jit(round_fn)
    return _moe_round_jit(fragment, src, dst, rank, ra, rb)


def _seeded_boruvka(
    num_nodes: int,
    fragment0: np.ndarray,
    eu: np.ndarray,
    ev: np.ndarray,
    ew: np.ndarray,
) -> np.ndarray:
    """Exact MSF of the graph *contracted by* ``fragment0``, as positions
    into the given edge arrays.

    Classic Borůvka over the total order ``(w, u, v)``: every fragment
    hooks across its minimum outgoing edge each round, so the union of
    chosen edges across rounds is exactly ``MSF(G / fragment0)`` (ties are
    impossible — the order is total). Edge arrays are padded to
    power-of-two buckets so the jitted round compiles once per bucket.
    """
    import jax.numpy as jnp

    from distributed_ghs_implementation_tpu.ops.segment_ops import INT32_MAX

    m = int(eu.size)
    if m == 0:
        return np.zeros(0, dtype=np.int64)
    order = np.lexsort((ev, eu, ew))
    rank_of_edge = np.empty(m, dtype=np.int64)
    rank_of_edge[order] = np.arange(m)

    m_pad = _next_pow2(m)
    ra = np.zeros(m_pad, dtype=np.int32)
    rb = np.zeros(m_pad, dtype=np.int32)
    ra[:m] = eu[order]
    rb[:m] = ev[order]
    e_pad = 2 * m_pad
    src = np.zeros(e_pad, dtype=np.int32)
    dst = np.zeros(e_pad, dtype=np.int32)
    rank = np.full(e_pad, int(INT32_MAX), dtype=np.int32)
    src[:m], src[m_pad:m_pad + m] = eu, ev
    dst[:m], dst[m_pad:m_pad + m] = ev, eu
    rank[:m] = rank_of_edge
    rank[m_pad:m_pad + m] = rank_of_edge

    fragment = jnp.asarray(fragment0.astype(np.int32))
    src, dst = jnp.asarray(src), jnp.asarray(dst)
    rank = jnp.asarray(rank)
    ra, rb = jnp.asarray(ra), jnp.asarray(rb)
    chosen: set = set()
    for _ in range(max(1, num_nodes).bit_length() + 2):
        has, moe_rank, fragment = _moe_round(fragment, src, dst, rank, ra, rb)
        has_np = np.asarray(has)
        if not has_np.any():
            return order[np.fromiter(chosen, dtype=np.int64, count=len(chosen))]
        for r in np.unique(np.asarray(moe_rank)[has_np]):
            if r < m:  # guard the padding sentinel
                chosen.add(int(r))
    raise RuntimeError("windowed Borůvka did not converge")  # unreachable


def warm_window_kernels(num_nodes: int, num_edges: int) -> int:
    """Compile the window round for the padded buckets a stream of this
    size dispatches: the full-edge-set cut pass (``m`` edges) and the
    ``O(n)``-sized cycle pass. Returns the number of shapes touched —
    the calls run on inert all-sentinel slots, so each costs one compile
    (or nothing when the jit cache already holds the bucket).
    """
    import jax.numpy as jnp

    from distributed_ghs_implementation_tpu.ops.segment_ops import INT32_MAX

    n = max(1, int(num_nodes))
    m = max(1, int(num_edges))
    # The cycle pass runs over MSF ∪ changed edges — bounded by the TREE
    # size, which is min(n-1, m)-ish, not n. Capping at min(n, m) matters
    # for the sharded-stream shapes (n ≫ m, e.g. 70k nodes / 3k edges):
    # warming pow2(n)-wide rounds there would pay two giant compiles no
    # window ever dispatches. For the common n ≤ m streams the cap is a
    # no-op and the warmed set is unchanged.
    t = min(n, m)
    shapes = sorted({
        _next_pow2(m),
        # Slightly MORE than tree-size edges can enter the cycle pass, so
        # warm one bucket above next_pow2(t) too.
        _next_pow2(t),
        2 * _next_pow2(t),
    })
    for m_pad in shapes:
        fragment = jnp.arange(n, dtype=jnp.int32)
        zeros_e = jnp.zeros(2 * m_pad, jnp.int32)
        rank = jnp.full(2 * m_pad, int(INT32_MAX), jnp.int32)
        zeros_m = jnp.zeros(m_pad, jnp.int32)
        _moe_round(fragment, zeros_e, zeros_e, rank, zeros_m, zeros_m)
    return len(shapes)


# ----------------------------------------------------------------------
# The windowed session
# ----------------------------------------------------------------------
@dataclasses.dataclass
class WindowInfo:
    """What one committed window did to the forest (the notification
    payload): membership changes by ``(u, v, w)`` triple, the tree-weight
    delta, and how the window was answered."""

    mode: str
    applied: int
    coalesced_from: int
    entered: List[Tuple[int, int, float]]
    left: List[Tuple[int, int, float]]
    weight_delta: float


class WindowedMST(DynamicMST):
    """A :class:`~serve.dynamic.DynamicMST` whose unit of work is a window.

    ``window_mode`` pins the path: ``"batched"`` (the two-pass algorithm
    above — the default), ``"sequential"`` (coalesce, then the per-update
    exchange rules — the escape hatch that IS the old behavior), or
    ``"resolve"`` (structural apply + supervised full solve). A batched
    window larger than ``window_resolve_threshold`` net updates, or one
    that leaves the forest check failing, degrades to ``resolve`` on its
    own — same discipline as the per-update path.
    """

    def __init__(
        self,
        result,
        *,
        window_mode: str = "batched",
        window_resolve_threshold: Optional[int] = None,
        **kwargs,
    ):
        if window_mode not in _MODES:
            raise ValueError(
                f"unknown window_mode {window_mode!r}; expected {_MODES}"
            )
        super().__init__(result, **kwargs)
        self.window_mode = window_mode
        self._window_threshold = window_resolve_threshold

    # -- durable-state plumbing (stream/log.py snapshots) ----------------
    def state_arrays(self) -> dict:
        """The session's whole durable state as arrays — what a snapshot
        persists (``stream/log.py``) and :meth:`from_state` rebuilds."""
        return {
            "num_nodes": np.asarray(self._n, dtype=np.int64),
            "u": self._u.copy(),
            "v": self._v.copy(),
            "w": self._w.copy(),
            "in_tree": self._in_tree.copy(),
        }

    @classmethod
    def from_state(cls, state: dict, **kwargs) -> "WindowedMST":
        """Rebuild a session from snapshot arrays WITHOUT solving — the
        replay path's entry: the maintained forest is the persisted mask,
        so recovery never touches the solver."""
        from distributed_ghs_implementation_tpu.api import MSTResult
        from distributed_ghs_implementation_tpu.graphs.edgelist import Graph

        n = int(state["num_nodes"])
        in_tree = np.asarray(state["in_tree"], dtype=bool)
        graph = Graph(
            n,
            np.asarray(state["u"], dtype=np.int64),
            np.asarray(state["v"], dtype=np.int64),
            np.asarray(state["w"]),
        )
        result = MSTResult(
            graph=graph,
            edge_ids=np.nonzero(in_tree)[0],
            num_levels=0,
            wall_time_s=0.0,
            backend="stream/replay",
            num_components=n - int(in_tree.sum()),
        )
        return cls(result, **kwargs)

    # -- the window entry ------------------------------------------------
    def apply_window(
        self, updates: Iterable[Union[Update, dict]]
    ) -> Tuple[object, WindowInfo]:
        """Apply one update window; returns ``(MSTResult, WindowInfo)``."""
        import time

        batch = [
            u if isinstance(u, Update) else Update.from_dict(u) for u in updates
        ]
        self._validate(batch)
        net = coalesce(batch)
        if len(batch) > len(net):
            BUS.count("stream.window.coalesced", len(batch) - len(net))
        threshold = (
            self._window_threshold
            if self._window_threshold is not None
            else max(256, self._u.size // 4)
        )
        t0 = time.perf_counter()
        before_k, before_w = self._tree_snapshot()
        before_weight = self._tree_weight()
        with BUS.span(
            "stream.window.apply", cat="stream",
            updates=len(batch), net=len(net), nodes=self._n,
        ) as span:
            self._dirty = True
            mode = self.window_mode
            if mode == "batched" and len(net) > threshold:
                BUS.count("stream.window.over_threshold")
                mode = "resolve"
            if not net:
                mode = "noop"
                self._last_mode = "window"
            elif mode == "batched":
                self._apply_batched(net)
                if not self._forest_ok():
                    BUS.count("stream.window.verify_failed")
                    span.set(verify_failed=True)
                    mode = "resolve"
                    self._resolve([], t0)
                else:
                    self._last_mode = "window"
            elif mode == "sequential":
                for upd in net:
                    self._apply_one(upd)
                if not self._forest_ok():
                    BUS.count("stream.window.verify_failed")
                    mode = "resolve"
                    self._resolve([], t0)
                else:
                    self._last_mode = "window"
            else:  # resolve
                self._apply_structural(net)
                self._resolve([], t0)
            BUS.count(f"stream.window.{mode}")
            span.set(mode=mode)
            self._dirty = False
        after_k, after_w = self._tree_snapshot()
        info = WindowInfo(
            mode=mode,
            applied=len(net),
            coalesced_from=len(batch),
            entered=self._changed_triples(
                after_k, after_w, np.isin(after_k, before_k, invert=True)
            ),
            left=self._changed_triples(
                before_k, before_w, np.isin(before_k, after_k, invert=True)
            ),
            weight_delta=self._tree_weight() - before_weight,
        )
        return self.result(time.perf_counter() - t0), info

    # -- bookkeeping -----------------------------------------------------
    def _tree_snapshot(self) -> Tuple[np.ndarray, np.ndarray]:
        """``(keys, weights)`` of the current tree edges. The diff that
        feeds MST-change notifications is a vectorized set difference over
        these — a window touches O(window) edges, so Python-object work
        stays proportional to the change, not to the forest size."""
        idx = np.nonzero(self._in_tree)[0]
        keys = (
            self._u[idx].astype(np.int64) * self._n
            + self._v[idx].astype(np.int64)
        )
        return keys, self._w[idx].copy()

    def _changed_triples(
        self, keys: np.ndarray, ws: np.ndarray, mask: np.ndarray
    ) -> List[Tuple[int, int, float]]:
        """Materialize ``(u, v, w)`` triples for the masked (changed)
        edges, in ``(u, v)`` order — key order IS lexicographic order
        since ``v < n``."""
        keys, ws = keys[mask], ws[mask]
        order = np.argsort(keys, kind="stable")
        cast = int if ws.dtype.kind in "iu" else float
        return [
            (int(k // self._n), int(k % self._n), cast(w))
            for k, w in zip(keys[order], ws[order])
        ]

    def _tree_weight(self):
        w = self._w[self._in_tree].sum()
        return int(w) if self._w.dtype.kind in "iu" else float(w)

    # -- structural batch apply -----------------------------------------
    def _apply_structural(self, net: Sequence[Update]) -> dict:
        """Vectorized rebuild of the canonical arrays for a coalesced
        window. Returns the classification the cut/cycle passes need, in
        NEW index space: ``inserted`` / ``increased`` / ``decreased``
        boolean masks and ``w_before`` (the pre-window weight of every
        surviving edge; inserted slots hold the new weight).
        """
        for upd in net:
            if upd.kind != "delete":
                self._promote_weight_dtype(upd.w)
        m = self._u.size
        removed = np.zeros(m, dtype=bool)
        old_w = self._w.copy()
        new_w = self._w.copy()
        ins_u: List[int] = []
        ins_v: List[int] = []
        ins_w: List[float] = []
        for upd in net:
            idx = self._find(upd.u, upd.v)
            if upd.kind == "delete":
                if idx >= 0:
                    removed[idx] = True
            elif idx >= 0:
                new_w[idx] = upd.w
            else:
                ins_u.append(upd.u)
                ins_v.append(upd.v)
                ins_w.append(upd.w)

        keep = ~removed
        n_keep = int(keep.sum())
        u2 = np.concatenate([self._u[keep], np.asarray(ins_u, dtype=np.int64)])
        v2 = np.concatenate([self._v[keep], np.asarray(ins_v, dtype=np.int64)])
        w2 = np.concatenate(
            [new_w[keep], np.asarray(ins_w, dtype=new_w.dtype)]
        )
        wb2 = np.concatenate(
            [old_w[keep], np.asarray(ins_w, dtype=old_w.dtype)]
        )
        tree2 = np.concatenate(
            [self._in_tree[keep], np.zeros(len(ins_u), dtype=bool)]
        )
        inserted2 = np.concatenate(
            [np.zeros(n_keep, dtype=bool), np.ones(len(ins_u), dtype=bool)]
        )
        order = np.lexsort((v2, u2))
        self._u, self._v, self._w = u2[order], v2[order], w2[order]
        self._k = self._u * self._n + self._v
        self._in_tree = tree2[order]
        w_before = wb2[order]
        inserted = inserted2[order]
        return {
            "inserted": inserted,
            "increased": ~inserted & (self._w > w_before),
            "decreased": ~inserted & (self._w < w_before),
            "w_before": w_before,
        }

    # -- the batched two-pass algorithm ---------------------------------
    def _apply_batched(self, net: Sequence[Update]) -> None:
        from distributed_ghs_implementation_tpu.graphs.edgelist import (
            component_labels,
        )

        tree_before = self._in_tree.copy()
        info = self._apply_structural(net)
        inserted = info["inserted"]
        increased = info["increased"]
        decreased = info["decreased"]
        n, m = self._n, self._u.size
        if m == 0:
            self._in_tree = np.zeros(0, dtype=bool)
            return

        # Cut pass: the intermediate graph G_A applies only deletions and
        # weight increases (decreased edges stay at their OLD weight,
        # inserted edges are absent). Surviving non-increased tree edges
        # are provably still MSF(G_A) edges, so contract them and let the
        # seeded Borůvka find every replacement at once.
        kept = self._in_tree & ~increased
        tree_broken = (
            bool(tree_before.sum() > self._in_tree.sum())  # a tree edge died
            or bool((self._in_tree & increased).any())
        )
        w_a = self._w.copy()
        w_a[decreased] = info["w_before"][decreased]
        mask_a = ~inserted
        if tree_broken:
            if kept.any():
                fragment0 = component_labels(
                    n, self._u[kept], self._v[kept]
                ).astype(np.int32)
            else:
                fragment0 = np.arange(n, dtype=np.int32)
            idx_a = np.nonzero(mask_a)[0]
            chosen = _seeded_boruvka(
                n, fragment0, self._u[idx_a], self._v[idx_a], w_a[idx_a]
            )
            msf_a = kept.copy()
            msf_a[idx_a[chosen]] = True
        else:
            msf_a = self._in_tree.copy()

        # Cycle pass: insertions + decreases. MSF(G') ⊆ MSF(G_A) ∪ C, so
        # one more pass over that small subgraph (at FINAL weights)
        # finishes exactly.
        cyc = inserted | decreased
        if cyc.any():
            idx_s = np.nonzero(msf_a | cyc)[0]
            chosen = _seeded_boruvka(
                n,
                np.arange(n, dtype=np.int32),
                self._u[idx_s],
                self._v[idx_s],
                self._w[idx_s],
            )
            in_tree = np.zeros(m, dtype=bool)
            in_tree[idx_s[chosen]] = True
            self._in_tree = in_tree
        else:
            self._in_tree = msf_a
