"""Benchmark: MST throughput on RMAT graphs (BASELINE.json metric).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "edges/s", "vs_baseline": N, ...}

Baseline: the reference's best measured *correct* run — the 10-node/28-edge
thread-backend experiment at 0.41 s (BASELINE.md) ≈ 68 edges/s. Its 20-node
config is already wrong 2/3 of the time, so this is the fastest throughput the
reference demonstrably sustains.

Default config: RMAT scale-24 (16.8M vertices, ~252M undirected edges after
dedup) — the exact size BASELINE.json's metric names — solved on the real
TPU chip and verified for weight parity against the RECORDED SciPy oracle
weight (518,885,017 for seed 24; receipts in docs/BASELINE_RUNS.jsonl — the
live oracle at this scale costs ~15 min, the weight is deterministic per
seed, so the recorded value is the same check at zero cost). Unknown
(scale, seed, edge-factor) combinations fall back to the live SciPy oracle.

Accounting (round-5 contract): BOTH clocks are reported. ``value`` is the
solve-only throughput (arrays staged, Kruskal-style sort-excluded clock);
``prep_s`` and ``e2e_edges_per_sec`` put the host prep — rank construction,
first_ranks, the host level-1 partition, staging — back on the clock.
``e2e`` uses the best warm solve (XLA compile time is excluded from both
clocks; the persistent compile cache makes repeat processes warm).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

BASELINE_EDGES_PER_SEC = 68.0  # reference: 28 edges / 0.41 s (BASELINE.md)

SEED = 24  # ties the generator call and the recorded-weight keys together

# SciPy-oracle MSF weights, recorded in docs/BASELINE_RUNS.jsonl, keyed by
# (scale, edge_factor, seed) of rmat_graph. Deterministic per key — but only
# on the NATIVE generator path (the NumPy fallback is a different RNG
# stream), so the lookup is gated on native availability.
RECORDED_ORACLE_WEIGHTS = {
    (20, 16, SEED): 35_737_768,
    (22, 16, SEED): 136_591_056,
    (24, 16, SEED): 518_885_017,
    (25, 16, SEED): 1_008_877_972,
    (26, 16, SEED): 1_960_349_712,
}


def _pctl(samples, p: float) -> float:
    # The repo-wide nearest-rank rule (obs.events.quantile): bench
    # percentiles stay comparable with histogram and SLO-report quantiles.
    from distributed_ghs_implementation_tpu.obs.events import quantile

    return quantile(samples, p)


def run_batch_bench(args) -> int:
    """Batched-serving throughput + latency: graphs/sec over K lanes vs
    the sequential miss path, on same-bucket small graphs.

    This is the serving-fleet metric (ISSUE round 9): every graph here is
    a distinct cache miss, so the sequential baseline is one device
    dispatch per graph and the batched run is ``ceil(N / lanes)``
    dispatches through ``batch/``. Round 10 adds the latency contract:

    * **cold first query** (``cold_first_solve_s``) — the very first
      batched solve this process runs, compile included. With
      ``--warmup`` the bucket is AOT-precompiled first
      (``batch/warmup.py``), so this clock shows what a warmed serving
      process actually pays — the before/after pair is the warmup
      feature's headline number (docs/BENCH_NOTES.md "Cold vs warm").
    * **warm latency percentiles** (``warm_solve_p50_s`` / ``_p95_s``)
      over per-request sequential solves.
    * **pipelined vs synchronous** forming (``pipeline_graphs_per_sec``
      vs ``sync_batch_graphs_per_sec``), measured at a lane count that
      yields multiple batches so forming/execute overlap is exercised.

    Every batched result is checked edge-for-edge against its sequential
    counterpart, and the metrics land in the same ``ghs-bench-metrics-v1``
    schema so `tools/bench_gate.py` gates them against a committed
    baseline (``docs/BENCH_BASELINE_BATCH.json``).
    """
    import numpy as np

    from distributed_ghs_implementation_tpu.api import (
        minimum_spanning_forest,
        minimum_spanning_forest_batch,
    )
    from distributed_ghs_implementation_tpu.batch.engine import BatchEngine
    from distributed_ghs_implementation_tpu.batch.policy import BatchPolicy
    from distributed_ghs_implementation_tpu.batch.warmup import (
        WarmupPlan,
        bucket_of,
        run_warmup,
    )
    from distributed_ghs_implementation_tpu.graphs.generators import gnm_random_graph

    from distributed_ghs_implementation_tpu.ops.pallas_kernels import (
        kernel_choice,
    )

    resolved_kernel = kernel_choice(args.kernel)
    graphs = [
        gnm_random_graph(args.batch_nodes, args.batch_edges, seed=SEED * 1000 + i)
        for i in range(args.batch_graphs)
    ]
    engine = BatchEngine(policy=BatchPolicy(max_lanes=args.batch_lanes))

    warmup_s = None
    if args.warmup:
        t0 = time.perf_counter()
        report = run_warmup(
            WarmupPlan(
                buckets=(bucket_of(args.batch_nodes, args.batch_edges),),
                lanes=args.batch_lanes,
            )
        )
        warmup_s = time.perf_counter() - t0
        print(f"warmup: {report} in {warmup_s:.3f}s", file=sys.stderr)

    # Cold first query: the first batched solve this process runs — with
    # --warmup the compile already happened above, without it this clock
    # includes full XLA tracing+compilation (the cold-start spike).
    t0 = time.perf_counter()
    cold_first = engine.solve_many([graphs[0]])
    cold_first_solve_s = time.perf_counter() - t0
    print(
        f"cold first query ({'warmed' if args.warmup else 'no warmup'}): "
        f"{cold_first_solve_s:.3f}s",
        file=sys.stderr,
    )

    # Warm both paths: compiles and the per-graph cached rank order.
    seq = [minimum_spanning_forest(g) for g in graphs]
    minimum_spanning_forest_batch(graphs, engine=engine)

    seq_times, batch_times, per_solve = [], [], []
    for _ in range(args.repeats):
        t0 = time.perf_counter()
        for g in graphs:
            t1 = time.perf_counter()
            minimum_spanning_forest(g)
            per_solve.append(time.perf_counter() - t1)
        seq_times.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        batched = minimum_spanning_forest_batch(graphs, engine=engine)
        batch_times.append(time.perf_counter() - t0)

    for s, b in zip(seq, batched):
        if not np.array_equal(s.edge_ids, b.edge_ids):
            print("BATCH PARITY FAILED vs sequential solve", file=sys.stderr)
            return 1
    if not np.array_equal(seq[0].edge_ids, cold_first[0].edge_ids):
        print("BATCH PARITY FAILED on the cold first query", file=sys.stderr)
        return 1

    # Pipelined vs synchronous forming, at a lane count that yields >= 4
    # batches (64 graphs at 64 lanes is ONE batch — nothing to overlap).
    # This pair compares MEDIANS, not bests: on small shared machines the
    # synchronous path's wall time is strongly bimodal (scheduler jitter
    # between host stacking and the XLA thread pool), and best-of-N picks
    # its lucky tail while the pipelined path's whole point is removing
    # that jitter — the median is the serving-relevant central tendency.
    pipe_lanes = max(1, min(args.batch_lanes, args.batch_graphs // 4))
    pipe_engine = BatchEngine(
        # The floor exists for production policies; this pair MEASURES
        # pipelining, so force it on regardless of stack size.
        policy=BatchPolicy(
            max_lanes=pipe_lanes, pipeline_depth=2, pipeline_min_stack_elems=0
        )
    )
    sync_engine = BatchEngine(
        policy=BatchPolicy(max_lanes=pipe_lanes, pipeline_depth=1)
    )
    pipe_engine.solve_many(graphs)  # warm the pipe-lane bucket once
    pipe_times, sync_times = [], []
    for _ in range(max(args.repeats, 5)):
        t0 = time.perf_counter()
        sync_engine.solve_many(graphs)
        sync_times.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        pipe_engine.solve_many(graphs)
        pipe_times.append(time.perf_counter() - t0)

    # Level-kernel pair (gate-kernel-v1, docs/KERNELS.md): the SAME stacked
    # batch through the fused Pallas level kernels vs the pinned XLA path,
    # one dispatch each. Where the resolved kernel already IS xla (no TPU,
    # sticky fallback, or --kernel xla) the pair is the same program twice,
    # so the speedup pins at exactly 1.0 instead of publishing run-to-run
    # noise as a kernel effect — the gate then passes on the XLA path.
    from distributed_ghs_implementation_tpu.batch.lanes import (
        execute_stacked,
        stack_lanes,
    )

    # Re-resolve here: a sticky Pallas fallback tripped during the phases
    # above must pin this pair at 1.0 (XLA-vs-XLA is the same program
    # twice), not publish noise under a stale "pallas" label.
    resolved_kernel = kernel_choice(args.kernel)
    kernel_speedup = 1.0
    if resolved_kernel != "xla":
        stacked = stack_lanes(
            graphs[: args.batch_lanes], lanes=args.batch_lanes
        )
        execute_stacked(stacked, kernel="xla")  # warm both variants
        execute_stacked(stacked, kernel=resolved_kernel)
        t_xla, t_kern = [], []
        for _ in range(max(args.repeats, 3)):
            t0 = time.perf_counter()
            execute_stacked(stacked, kernel="xla")
            t_xla.append(time.perf_counter() - t0)
            t0 = time.perf_counter()
            execute_stacked(stacked, kernel=resolved_kernel)
            t_kern.append(time.perf_counter() - t0)
        kernel_speedup = min(t_xla) / min(t_kern)

    n = len(graphs)
    seq_gps = n / min(seq_times)
    batch_gps = n / min(batch_times)
    speedup = batch_gps / seq_gps
    pipe_gps = n / _pctl(pipe_times, 0.50)
    sync_gps = n / _pctl(sync_times, 0.50)
    total_weight = int(sum(r.total_weight for r in seq))
    out = {
        "metric": f"batched MST graphs/sec, gnm({args.batch_nodes},"
        f"{args.batch_edges}) x {n}, {args.batch_lanes} lanes",
        "value": round(batch_gps, 1),
        "unit": "graphs/s",
        "seq_graphs_per_sec": round(seq_gps, 1),
        "batch_speedup": round(speedup, 2),
        "cold_first_solve_s": round(cold_first_solve_s, 4),
        "warm_solve_p50_s": round(_pctl(per_solve, 0.50), 5),
        "warm_solve_p95_s": round(_pctl(per_solve, 0.95), 5),
        "pipeline_graphs_per_sec": round(pipe_gps, 1),
        "sync_batch_graphs_per_sec": round(sync_gps, 1),
        "pipeline_speedup": round(pipe_gps / sync_gps, 2),
        "pipeline_lanes": pipe_lanes,
        "kernel": resolved_kernel,
        "level_kernel_speedup": round(kernel_speedup, 3),
        "parity": "edge-exact vs sequential",
    }
    if warmup_s is not None:
        out["warmup_s"] = round(warmup_s, 3)
    print(json.dumps(out))
    if args.metrics_out:
        metrics = {
            "batch_graphs_per_sec": batch_gps,
            "seq_graphs_per_sec": seq_gps,
            "batch_speedup": speedup,
            "batch_solve_s": min(batch_times),
            "cold_first_solve_s": cold_first_solve_s,
            "warm_solve_p50_s": _pctl(per_solve, 0.50),
            "warm_solve_p95_s": _pctl(per_solve, 0.95),
            "pipeline_graphs_per_sec": pipe_gps,
            "sync_batch_graphs_per_sec": sync_gps,
            "pipeline_speedup": pipe_gps / sync_gps,
            "level_kernel_speedup": kernel_speedup,
            "mst_weight": total_weight,
        }
        if warmup_s is not None:
            metrics["warmup_s"] = warmup_s
        with open(args.metrics_out, "w") as f:
            json.dump(
                {
                    "schema": "ghs-bench-metrics-v1",
                    "config": {
                        "workload": f"batch-gnm({args.batch_nodes},"
                        f"{args.batch_edges})x{args.batch_graphs}"
                        f"-lanes{args.batch_lanes}",
                    },
                    "metrics": metrics,
                },
                f,
                indent=2,
            )
            f.write("\n")
    return 0


def run_update_stream_bench(args) -> int:
    """Streaming-maintenance throughput: windowed batched apply
    (``stream/window.py``) vs the sequential per-update exchange rules
    (``serve/dynamic.py``) on one sustained, seeded update stream.

    Both paths consume the IDENTICAL update list against the same seeded
    graph and must land on the same forest — which must also be
    edge-for-edge identical to a fresh solve of the final graph (the
    ``(w, u, v)`` order makes the MSF unique). The headline pair is
    ``window_updates_per_sec`` vs ``seq_updates_per_sec``; their ratio
    ``window_speedup`` gates as a throughput floor against
    ``docs/BENCH_BASELINE_STREAM_BENCH.json`` (``gate-stream-bench-v1``).
    The windowed target from ROADMAP item 4: >= 5x at window size >= 64.
    """
    import numpy as np

    from distributed_ghs_implementation_tpu.api import minimum_spanning_forest
    from distributed_ghs_implementation_tpu.graphs.generators import (
        gnm_random_graph,
    )
    from distributed_ghs_implementation_tpu.models.boruvka import solve_graph
    from distributed_ghs_implementation_tpu.serve.dynamic import DynamicMST
    from distributed_ghs_implementation_tpu.stream.window import (
        WindowedMST,
        random_update_stream,
        warm_window_kernels,
    )

    n, m = args.stream_nodes, args.stream_edges
    total, window = args.stream_updates, args.stream_window
    g = gnm_random_graph(n, m, seed=SEED)
    rng = np.random.default_rng(SEED)
    seed_result = minimum_spanning_forest(g)

    # One fixed update list both paths consume: the shared seeded
    # generator (also the load drill's published-window workload) —
    # path-independent, so sequential and windowed application see the
    # same stream.
    updates = random_update_stream(rng, g, total)

    t0 = time.perf_counter()
    # Warm both the grown shape and the seed shape: inserts/deletes
    # roughly cancel, so the measured windows dispatch near next_pow2(m),
    # not next_pow2(m + total) — an unwarmed bucket would put a jit trace
    # inside the timed loop.
    warm_window_kernels(n, m + total)
    warm_window_kernels(n, m)
    warmup_s = time.perf_counter() - t0
    print(f"window-kernel warmup: {warmup_s:.3f}s", file=sys.stderr)

    # Sequential per-update path (the round-8 serving behavior, measured
    # on DynamicMST itself — apply() never touches the windowed
    # machinery, so constructing a WindowedMST here would only mislabel
    # what is timed).
    seq = DynamicMST(seed_result, resolve_threshold=10**9)
    t0 = time.perf_counter()
    for upd in updates:
        seq.apply([upd])
    seq_s = time.perf_counter() - t0

    # Windowed batched path.
    win = WindowedMST(seed_result, resolve_threshold=10**9,
                      window_resolve_threshold=10**9)
    t0 = time.perf_counter()
    modes = {}
    for lo in range(0, total, window):
        _result, info = win.apply_window(updates[lo:lo + window])
        modes[info.mode] = modes.get(info.mode, 0) + 1
    window_s = time.perf_counter() - t0

    seq_result = seq.result()
    win_result = win.result()
    ids_ref, _, _ = solve_graph(win_result.graph)
    parity_ok = (
        np.array_equal(seq_result.graph.u, win_result.graph.u)
        and np.array_equal(seq_result.graph.v, win_result.graph.v)
        and np.array_equal(seq_result.graph.w, win_result.graph.w)
        and np.array_equal(
            np.sort(seq_result.edge_ids), np.sort(win_result.edge_ids)
        )
        and np.array_equal(np.sort(win_result.edge_ids), np.sort(ids_ref))
    )
    if not parity_ok:
        print("UPDATE-STREAM PARITY FAILED (windowed vs sequential vs "
              "fresh solve)", file=sys.stderr)
        return 1

    seq_ups = total / seq_s
    win_ups = total / window_s
    out = {
        "metric": f"streaming MSF maintenance, gnm({n},{m}), {total} updates"
        f" in windows of {window}",
        "value": round(win_ups, 1),
        "unit": "updates/s (windowed batched)",
        "seq_updates_per_sec": round(seq_ups, 1),
        "window_speedup": round(win_ups / seq_ups, 2),
        "window_size": window,
        "window_modes": modes,
        "warmup_s": round(warmup_s, 3),
        "parity": "edge-exact vs sequential AND fresh solve",
    }
    print(json.dumps(out))
    if args.metrics_out:
        metrics = {
            "window_updates_per_sec": win_ups,
            "seq_updates_per_sec": seq_ups,
            "window_speedup": win_ups / seq_ups,
            "window_apply_s": window_s,
            "seq_apply_s": seq_s,
            "warmup_s": warmup_s,
            "mst_weight": int(win_result.graph.w[win_result.edge_ids].sum()),
            "mst_edges": int(win_result.edge_ids.size),
        }
        with open(args.metrics_out, "w") as f:
            json.dump(
                {
                    "schema": "ghs-bench-metrics-v1",
                    "config": {
                        "workload": f"update-stream-gnm({n},{m})"
                        f"-u{total}w{window}-seed{SEED}",
                    },
                    "metrics": metrics,
                },
                f,
                indent=2,
            )
            f.write("\n")
    return 0


def run_fleet_tcp_bench(args) -> int:
    """Network-fleet transport metrics (``gate-fleet-tcp-v1``): router-hop
    latency over TCP sockets vs the round-12 subprocess pipes, plus the
    cross-host cache-miss forwarding counters, on jax-free echo workers.

    * **router_hop_{tcp,pipe}_{p50,p95}_s** — send-to-response wall time
      minus the worker's own service time, per request: the transport +
      framing + queueing overhead a ``--transport`` choice actually moves
      (workers answer canned content, so nothing solver-shaped pollutes
      the clock). Both sequential round trips and a concurrent burst feed
      the histogram — the burst is where TCP's coalesced pipelined writes
      earn their keep.
    * **forward_hit / forward_miss** — EXACT: a deterministic forwarding
      scenario (lane-steered oversize digests whose full-ring owner is a
      different worker) drives exactly ``--fleet-forward`` probes down
      each path. A changed count means the forwarding decision logic
      changed, never jitter.
    * **elastic churn** — one warm join + one drain-aware retire on a TCP
      echo fleet: the joiner must own its ring share and serve it, the
      retiree must exit 0 with its keyspace answerable by survivors, and
      the whole exchange must register zero worker deaths.
      ``elastic_scale_up`` / ``elastic_scale_down`` /
      ``elastic_unplanned_deaths`` gate EXACTLY (1/1/0);
      ``elastic_join_warm_s`` (spawn -> warmed hello -> ring entry) gates
      as a wall-time ceiling.

    Echo workers make this bench CI-cheap (~seconds, no jax import) while
    exercising the real router, real sockets, real framing, and the real
    forwarding machinery end to end.
    """
    from concurrent.futures import ThreadPoolExecutor

    from distributed_ghs_implementation_tpu.fleet.hashing import HashRing
    from distributed_ghs_implementation_tpu.fleet.router import (
        FleetConfig,
        FleetRouter,
    )
    from distributed_ghs_implementation_tpu.obs.events import BUS

    BUS.enable()
    workers = 3
    n_seq = args.fleet_requests
    n_burst = args.fleet_requests
    hops = {}
    for transport in ("pipe", "tcp"):
        BUS.clear()
        cfg = FleetConfig(
            workers=workers, test_echo=True, transport=transport,
            heartbeat_interval_s=0.25, ready_timeout_s=120.0,
            request_timeout_s=60.0,
        )
        with FleetRouter(cfg) as router:
            for i in range(16):  # warm: interpreter paths, first frames
                router.handle({"op": "solve", "digest": f"warm-{i}"})
            BUS.clear()
            for i in range(n_seq):
                resp = router.handle({"op": "solve", "digest": f"seq-{i}"})
                if not resp.get("ok"):
                    print(f"FLEET BENCH FAILED: {resp}", file=sys.stderr)
                    return 1
            # Concurrent burst: many requests in flight at once — the
            # regime where per-frame syscalls (pipe) vs coalesced writes
            # (tcp) diverge.
            with ThreadPoolExecutor(max_workers=8) as pool:
                burst = list(pool.map(
                    lambda i: router.handle(
                        {"op": "solve", "digest": f"burst-{i}"}
                    ),
                    range(n_burst),
                ))
            if not all(r.get("ok") for r in burst):
                print("FLEET BENCH FAILED: burst errors", file=sys.stderr)
                return 1
            hist = BUS.histograms().get("fleet.hop_s", {})
            if not hist.get("count"):
                print("FLEET BENCH FAILED: no hop samples", file=sys.stderr)
                return 1
            hops[transport] = hist

    # Forwarding scenario (deterministic): a 3-worker TCP fleet where
    # worker 0 owns the oversize lane subring and forwarding is ON (no
    # shared disk — the cross-host topology). Hits: a digest solved at its
    # full-ring owner, then re-requested oversize — the lane steers the
    # dispatch at worker 0, the router probes the owner-of-record first,
    # and the cached result comes back without a local solve. Misses: a
    # fresh oversize digest — the probe at the (never-asked) full-ring
    # owner misses and worker 0 solves locally. Digests are pre-screened
    # so every full-ring owner differs from worker 0; counters then gate
    # EXACTLY.
    BUS.clear()
    ring = HashRing(range(workers), replicas=64)
    k = args.fleet_forward
    hit_digests, miss_digests, i = [], [], 0
    while len(hit_digests) < k or len(miss_digests) < k:
        d = f"fwd-{i}"
        i += 1
        if ring.assign(d) == 0:
            continue
        if len(hit_digests) < k:
            hit_digests.append(d)
        else:
            miss_digests.append(d)
    oversize = {"num_nodes": 200_000, "edges": [[0, 1, 1]]}
    cfg = FleetConfig(
        workers=workers, test_echo=True, transport="tcp",
        sharded_lane_workers=1, forward_cache=True,
        heartbeat_interval_s=0.25, ready_timeout_s=120.0,
        request_timeout_s=60.0,
    )
    with FleetRouter(cfg) as router:
        for d in hit_digests:
            owner = router.handle({"op": "solve", "digest": d})
            fwd = router.handle({"op": "solve", "digest": d, **oversize})
            if not (fwd.get("ok") and fwd.get("cached")
                    and fwd.get("forwarded_from") == owner["worker"]):
                print(f"FORWARD HIT FAILED: {fwd}", file=sys.stderr)
                return 1
        for d in miss_digests:
            local = router.handle({"op": "solve", "digest": d, **oversize})
            if not (local.get("ok") and local.get("worker") == 0):
                print(f"FORWARD MISS FAILED: {local}", file=sys.stderr)
                return 1
    counters = BUS.counters()
    forward_hit = int(counters.get("fleet.forward.hit", 0))
    forward_miss = int(counters.get("fleet.forward.miss", 0))
    if forward_hit != k or forward_miss != k:
        print(
            f"FORWARD COUNTERS WRONG: hit {forward_hit} miss {forward_miss}"
            f" (expected {k}/{k})",
            file=sys.stderr,
        )
        return 1

    # Elastic churn (deterministic): one warm join, one drain-aware
    # retire, on a fresh TCP fleet. The joiner serves its own ring share
    # BEFORE the retire (proving ring entry was real, not cosmetic); the
    # retiree's keyspace stays answerable afterwards; and a planned
    # departure must never read as a death.
    BUS.clear()
    cfg = FleetConfig(
        workers=2, test_echo=True, transport="tcp",
        heartbeat_interval_s=0.25, ready_timeout_s=120.0,
        request_timeout_s=60.0,
    )
    with FleetRouter(cfg) as router:
        for i in range(8):
            router.handle({"op": "solve", "digest": f"pre-{i}"})
        joined = router.add_worker()
        ring3 = HashRing(range(3), replicas=cfg.ring_replicas)
        d_new = next(f"el-{i}" for i in range(1000)
                     if ring3.assign(f"el-{i}") == joined["worker"])
        served = router.handle({"op": "solve", "digest": d_new})
        if not (served.get("ok")
                and served.get("worker") == joined["worker"]):
            print(f"ELASTIC JOIN FAILED: {served}", file=sys.stderr)
            return 1
        retired = router.retire_worker(joined["worker"])
        if retired["exit_code"] != 0:
            print(f"ELASTIC RETIRE FAILED: {retired}", file=sys.stderr)
            return 1
        handoff = router.handle({"op": "solve", "digest": d_new})
        if not handoff.get("ok") or handoff.get("worker") == joined["worker"]:
            print(f"ELASTIC HANDOFF FAILED: {handoff}", file=sys.stderr)
            return 1
    counters = BUS.counters()
    elastic_up = int(counters.get("fleet.scale.up", 0))
    elastic_down = int(counters.get("fleet.scale.down", 0))
    elastic_deaths = int(counters.get("fleet.worker.dead", 0))
    if elastic_up != 1 or elastic_down != 1 or elastic_deaths != 0:
        print(
            f"ELASTIC COUNTERS WRONG: up {elastic_up} down {elastic_down} "
            f"deaths {elastic_deaths} (expected 1/1/0)",
            file=sys.stderr,
        )
        return 1

    out = {
        "metric": f"fleet router hop, {workers} echo workers, "
        f"{n_seq} sequential + {n_burst} burst requests",
        "value": round(hops["tcp"]["p50"] * 1e3, 3),
        "unit": "ms (tcp hop p50)",
        "router_hop_tcp_p50_s": round(hops["tcp"]["p50"], 6),
        "router_hop_tcp_p95_s": round(hops["tcp"]["p95"], 6),
        "router_hop_pipe_p50_s": round(hops["pipe"]["p50"], 6),
        "router_hop_pipe_p95_s": round(hops["pipe"]["p95"], 6),
        "forward_hit": forward_hit,
        "forward_miss": forward_miss,
        "elastic_join_warm_s": round(joined["warm_s"], 6),
        "elastic_scale_up": elastic_up,
        "elastic_scale_down": elastic_down,
    }
    print(json.dumps(out))
    if args.metrics_out:
        metrics = {
            "router_hop_tcp_p50_s": hops["tcp"]["p50"],
            "router_hop_tcp_p95_s": hops["tcp"]["p95"],
            "router_hop_pipe_p50_s": hops["pipe"]["p50"],
            "router_hop_pipe_p95_s": hops["pipe"]["p95"],
            "forward_hit": forward_hit,
            "forward_miss": forward_miss,
            "elastic_join_warm_s": joined["warm_s"],
            "elastic_scale_up": elastic_up,
            "elastic_scale_down": elastic_down,
            "elastic_unplanned_deaths": elastic_deaths,
            "fleet_requests": 2 * (n_seq + n_burst + 16),
        }
        with open(args.metrics_out, "w") as f:
            json.dump(
                {
                    "schema": "ghs-bench-metrics-v1",
                    "config": {"workload": "gate-fleet-tcp-v1"},
                    "metrics": metrics,
                },
                f,
                indent=2,
            )
            f.write("\n")
    return 0


def run_wire_bench(args) -> int:
    """Binary wire-plane metrics (``gate-wire-v1``): what the B-frame
    carrier (``fleet/framing.py``) buys at the front door, on the oversize
    deck the stream seeds use (70000x3000 by default).

    * **wire_binary_ingest_per_sec / wire_json_ingest_per_sec** — graphs
      per second through the full ingest path each carrier pays per
      request: ``read_frame`` off the wire bytes, :class:`Graph`
      reconstruction, content digest. JSON pays ``json.loads`` over a
      ``[[u,v,w],...]`` text list plus per-edge Python-object churn; the
      B-frame pays a crc32, a ~200-byte header parse, and three
      ``np.frombuffer`` views. The bench FAILS below **5x** — the ratio is
      the round's acceptance criterion, not a tolerance question. Parity
      is checked before anything is timed: both carriers must yield
      byte-identical digests and edge-exact arrays vs the source graph.
    * **wire_passthrough** — EXACT: every solve B-frame dispatched through
      a 3-worker all-binary TCP echo fleet must take the opaque
      passthrough path (``fleet.wire.passthrough == solve frames sent``,
      ``fleet.wire.fallback_json == 0``): the router read the header,
      never the edge sections.
    * **wire_mixed_passthrough / wire_mixed_fallback_json** — EXACT: the
      same deck through a mixed-build fleet (worker 0 spawned with
      ``GHS_FLEET_WIRE=0``, so its hello carries no binary capability)
      must split deterministically by ring owner — legacy-owned digests
      degrade to folded JSON per connection, everything else stays
      binary, and every response is still ``ok``.

    Echo workers keep this jax-free and CI-cheap while exercising the
    real framing, real sockets, and the real per-connection negotiation.
    """
    import io

    import numpy as np

    from distributed_ghs_implementation_tpu.fleet.framing import (
        encode_bframe,
        encode_frame,
        read_frame,
    )
    from distributed_ghs_implementation_tpu.fleet.hashing import HashRing
    from distributed_ghs_implementation_tpu.fleet.router import (
        FleetConfig,
        FleetRouter,
    )
    from distributed_ghs_implementation_tpu.graphs.edgelist import Graph
    from distributed_ghs_implementation_tpu.graphs.generators import (
        gnm_random_graph,
    )
    from distributed_ghs_implementation_tpu.obs.events import BUS

    n, m = args.wire_nodes, args.wire_edges
    deck = [
        gnm_random_graph(n, m, seed=SEED + i)
        for i in range(args.wire_graphs)
    ]

    # Pre-encode both carriers once: the clocks time INGEST only —
    # read_frame + Graph reconstruction + digest — the work the front
    # door repeats per request.
    json_frames = [
        encode_frame(
            {"op": "solve", "num_nodes": g.num_nodes,
             "edges": np.stack([g.u, g.v, g.w], axis=1).tolist()},
            crc=True,
        )
        for g in deck
    ]
    bin_frames = [
        encode_bframe({"op": "solve", **g.to_wire()}) for g in deck
    ]

    def _ingest_json(payload: bytes) -> Graph:
        req = read_frame(io.BytesIO(payload))
        return Graph.from_edges(req["num_nodes"], req["edges"])

    def _ingest_bin(payload: bytes) -> Graph:
        return Graph.from_wire(read_frame(io.BytesIO(payload)))

    # Parity before anything is timed: same digest (bit-identical — the
    # cache/store/stream identity), same edges, from either carrier.
    for g, jf, bf in zip(deck, json_frames, bin_frames):
        gj, gb = _ingest_json(jf), _ingest_bin(bf)
        if not (gj.digest() == gb.digest() == g.digest()):
            print("WIRE PARITY FAILED: digest mismatch", file=sys.stderr)
            return 1
        if not (np.array_equal(gb.u, g.u) and np.array_equal(gb.v, g.v)
                and np.array_equal(gb.w, g.w)
                and np.array_equal(gj.u, g.u)
                and np.array_equal(gj.w, g.w)):
            print("WIRE PARITY FAILED: edge arrays differ", file=sys.stderr)
            return 1

    def _ingest_clock(fn, frames) -> float:
        best = float("inf")
        for _ in range(max(1, args.repeats)):
            t0 = time.perf_counter()
            for payload in frames:
                fn(payload).digest()
            best = min(best, time.perf_counter() - t0)
        return len(frames) / best

    json_gps = _ingest_clock(_ingest_json, json_frames)
    bin_gps = _ingest_clock(_ingest_bin, bin_frames)
    speedup = bin_gps / json_gps
    if speedup < 5.0:
        print(
            f"WIRE BENCH FAILED: binary ingest {speedup:.1f}x JSON "
            f"(acceptance floor 5x)",
            file=sys.stderr,
        )
        return 1

    # All-binary fleet: every solve B-frame must dispatch opaquely.
    BUS.enable()
    BUS.clear()
    requests = [{"op": "solve", **g.to_wire()} for g in deck]
    cfg = FleetConfig(
        workers=3, test_echo=True, transport="tcp",
        heartbeat_interval_s=0.25, ready_timeout_s=120.0,
        request_timeout_s=60.0,
    )
    with FleetRouter(cfg) as router:
        for g, req in zip(deck, requests):
            resp = router.handle(dict(req))
            if not (resp.get("ok") and resp.get("digest") == g.digest()):
                print(f"WIRE FLEET FAILED: {resp}", file=sys.stderr)
                return 1
    counters = BUS.counters()
    passthrough = int(counters.get("fleet.wire.passthrough", 0))
    fallback = int(counters.get("fleet.wire.fallback_json", 0))
    if passthrough != len(deck) or fallback != 0:
        print(
            f"WIRE COUNTERS WRONG: passthrough {passthrough} fallback "
            f"{fallback} (expected {len(deck)}/0)",
            file=sys.stderr,
        )
        return 1

    # Mixed-build fleet: worker 0 is a legacy build (hello without the
    # binary capability), so exactly the ring share it owns degrades to
    # folded JSON — per connection, never an error. The split is
    # deterministic: seeded digests, deterministic ring.
    BUS.clear()
    ring = HashRing(range(3), replicas=cfg.ring_replicas)
    expect_fallback = sum(
        1 for g in deck if ring.assign(g.digest()) == 0
    )
    cfg_mixed = FleetConfig(
        workers=3, test_echo=True, transport="tcp",
        heartbeat_interval_s=0.25, ready_timeout_s=120.0,
        request_timeout_s=60.0,
        worker_env={0: {"GHS_FLEET_WIRE": "0"}},
    )
    with FleetRouter(cfg_mixed) as router:
        for g, req in zip(deck, requests):
            resp = router.handle(dict(req))
            if not (resp.get("ok") and resp.get("digest") == g.digest()):
                print(f"WIRE MIXED FLEET FAILED: {resp}", file=sys.stderr)
                return 1
    counters = BUS.counters()
    mixed_pass = int(counters.get("fleet.wire.passthrough", 0))
    mixed_fallback = int(counters.get("fleet.wire.fallback_json", 0))
    if (mixed_fallback != expect_fallback
            or mixed_pass != len(deck) - expect_fallback):
        print(
            f"WIRE MIXED COUNTERS WRONG: passthrough {mixed_pass} "
            f"fallback {mixed_fallback} (expected "
            f"{len(deck) - expect_fallback}/{expect_fallback})",
            file=sys.stderr,
        )
        return 1

    out = {
        "metric": f"binary wire ingest, gnm({n},{m}) x {len(deck)}",
        "value": round(speedup, 2),
        "unit": "x vs JSON ingest (graphs/sec)",
        "wire_binary_ingest_per_sec": round(bin_gps, 2),
        "wire_json_ingest_per_sec": round(json_gps, 2),
        "wire_passthrough": passthrough,
        "wire_fallback_json": fallback,
        "wire_mixed_passthrough": mixed_pass,
        "wire_mixed_fallback_json": mixed_fallback,
    }
    print(json.dumps(out))
    if args.metrics_out:
        metrics = {
            "wire_binary_ingest_per_sec": bin_gps,
            "wire_json_ingest_per_sec": json_gps,
            "wire_speedup": speedup,
            "wire_passthrough": passthrough,
            "wire_fallback_json": fallback,
            "wire_mixed_passthrough": mixed_pass,
            "wire_mixed_fallback_json": mixed_fallback,
            "wire_graphs": len(deck),
        }
        with open(args.metrics_out, "w") as f:
            json.dump(
                {
                    "schema": "ghs-bench-metrics-v1",
                    "config": {
                        "workload": "gate-wire-v1",
                        "deck": f"gnm({n},{m},seeds {SEED}..)"
                        f"x{len(deck)}",
                    },
                    "metrics": metrics,
                },
                f,
                indent=2,
            )
            f.write("\n")
    return 0


def run_verify_bench(args) -> int:
    """Certificate-checker overhead metrics (``gate-verify-bench-v1``):
    what one MST certificate costs, per engine, at interactive and bulk
    scale — the price list behind the ``verify=off|sample|full`` policy
    (``docs/VERIFICATION.md``).

    * **verify_overhead_p50_s** — p50 wall time of one inline certificate
      on the interactive-sized pool, default (auto) engine: the per-
      request tax a ``verify=full`` class pays.
    * **certify_np_p50_s / certify_xla_p50_s** — the same check on each
      engine explicitly (the NumPy engine is what the jax-free router
      runs on forwarded payloads; the XLA engine is the jitted path that
      cross-checks Pallas-routed solves).
    * **certify_bulk_s** — one certificate at RMAT-14 scale (the bulk
      class's inline cost).
    * **mutation_rejected** — EXACT: every adversarial mutation (swapped
      tree edge, duplicated edge id, dropped edge) must be rejected; a
      changed count means the checker's power regressed, never jitter.
    * **mst_weight** — EXACT, as everywhere.
    """
    import numpy as np

    from distributed_ghs_implementation_tpu.api import minimum_spanning_forest
    from distributed_ghs_implementation_tpu.graphs.generators import (
        gnm_random_graph,
        rmat_graph,
    )
    from distributed_ghs_implementation_tpu.obs.events import BUS, quantile
    from distributed_ghs_implementation_tpu.verify.certify import (
        certify_edge_ids,
        certify_result,
    )

    BUS.enable()
    small = [gnm_random_graph(256, 1024, seed=60 + i) for i in range(8)]
    bulk = rmat_graph(14, 8, seed=61)
    results = [
        minimum_spanning_forest(g, backend="host") for g in small
    ]
    bulk_result = minimum_spanning_forest(bulk, backend="host")

    # Warm both engines (the XLA engine's first call pays a jit compile
    # that serving pays once per scale bucket, not per request).
    for engine in ("np", "xla", "auto"):
        cert = certify_result(results[0], engine=engine)
        if not cert.ok:
            print(f"VERIFY BENCH FAILED: clean result rejected "
                  f"({engine}: {cert.reason})", file=sys.stderr)
            return 1

    timings = {"auto": [], "np": [], "xla": []}
    failed_clean = 0
    for _ in range(args.repeats):
        for engine in timings:
            for r in results:
                cert = certify_result(r, engine=engine)
                if not cert.ok:
                    failed_clean += 1
                timings[engine].append(cert.check_s)
    certify_result(bulk_result)  # warm the bulk shape's jit compile
    t0 = time.perf_counter()
    bulk_cert = certify_result(bulk_result)
    certify_bulk_s = time.perf_counter() - t0
    if not bulk_cert.ok:
        failed_clean += 1

    # Adversarial mutations: each must be rejected (exact count).
    rejected = 0
    mutations = 0
    for r in results:
        g = r.graph
        ids = np.asarray(r.edge_ids)
        in_tree = np.zeros(g.num_edges, dtype=bool)
        in_tree[ids] = True
        nt = np.nonzero(~in_tree)[0]
        order = np.argsort(g.w, kind="stable")
        rank = np.empty(g.num_edges, dtype=np.int64)
        rank[order] = np.arange(g.num_edges)
        cases = [
            np.concatenate([ids[1:], ids[:1]])[:-1],      # dropped edge
            np.concatenate([ids[:-1], ids[:1]]),          # duplicated id
        ]
        if nt.size:
            swapped = ids.copy()
            swapped[int(np.argmin(rank[ids]))] = int(nt[np.argmax(rank[nt])])
            cases.append(swapped)                         # heavier swap-in
        for bad in cases:
            mutations += 1
            if not certify_edge_ids(g, bad, engine="np").ok:
                rejected += 1
    if rejected != mutations:
        print(f"VERIFY BENCH FAILED: {mutations - rejected} adversarial "
              f"mutations ACCEPTED", file=sys.stderr)
        return 1

    weight = int(sum(r.total_weight for r in results)
                 + bulk_result.total_weight)
    out = {
        "metric": f"MST certificate, {len(small)} x gnm(256,1024) + "
        f"rmat-14, {args.repeats} repeats",
        "value": round(quantile(timings["auto"], 0.5) * 1e3, 3),
        "unit": "ms (auto-engine certify p50)",
        "verify_overhead_p50_s": round(quantile(timings["auto"], 0.5), 6),
        "certify_np_p50_s": round(quantile(timings["np"], 0.5), 6),
        "certify_xla_p50_s": round(quantile(timings["xla"], 0.5), 6),
        "certify_bulk_s": round(certify_bulk_s, 6),
        "mutation_rejected": rejected,
        "mst_weight": weight,
    }
    print(json.dumps(out))
    if args.metrics_out:
        metrics = {
            "verify_overhead_p50_s": quantile(timings["auto"], 0.5),
            "certify_np_p50_s": quantile(timings["np"], 0.5),
            "certify_xla_p50_s": quantile(timings["xla"], 0.5),
            "certify_bulk_s": certify_bulk_s,
            "mutation_rejected": rejected,
            "verify_failed_clean": failed_clean,
            "mst_weight": weight,
        }
        with open(args.metrics_out, "w") as f:
            json.dump(
                {
                    "schema": "ghs-bench-metrics-v1",
                    "config": {"workload": "gate-verify-bench-v1"},
                    "metrics": metrics,
                },
                f,
                indent=2,
            )
            f.write("\n")
    return 0 if failed_clean == 0 else 1


def run_kinds_bench(args) -> int:
    """Per-kind analytics latency (``gate-analytics-bench-v1``): what each
    query kind of the analytics front door (``docs/ANALYTICS.md``) costs
    through the full service path, cold and warm.

    Per kind, against a FRESH service (so no cross-kind cache sharing
    flatters the cold number):

    * **<kind>_solve_p50_s** — the miss path: the kind's own solve
      (``components`` solves the index-weighted twin; ``k_msf`` /
      ``bottleneck`` / ``path_max`` solve the MSF then reduce).
    * **<kind>_hit_p50_s** — the warm repeat: the per-kind cache entry, or
      the O(tree) host derivation off the shared MSF entry.

    ``mst_weight`` gates EXACT as everywhere; any non-ok or wrong-weight
    response fails the run (``wrong_results``).
    """
    from distributed_ghs_implementation_tpu.graphs.generators import (
        gnm_random_graph,
    )
    from distributed_ghs_implementation_tpu.obs.events import BUS, quantile
    from distributed_ghs_implementation_tpu.serve.service import MSTService
    from distributed_ghs_implementation_tpu.utils.verify import (
        networkx_mst_weight,
    )

    kinds = ("mst", "components", "k_msf", "bottleneck", "path_max")
    k_forest = 3
    BUS.enable()
    pool = [
        gnm_random_graph(args.batch_nodes, args.batch_edges, seed=70 + i)
        for i in range(8)
    ]
    oracle_weight = int(sum(networkx_mst_weight(g) for g in pool))

    def kind_request(g, kind: str) -> dict:
        req = {
            "op": "solve",
            "num_nodes": g.num_nodes,
            "edges": [
                [int(a), int(b), int(c)] for a, b, c in zip(g.u, g.v, g.w)
            ],
        }
        if kind != "mst":
            req["kind"] = kind
        if kind == "components":
            req["labels_out"] = True
        elif kind == "k_msf":
            req["k"] = k_forest
        elif kind == "path_max":
            req["u"], req["v"] = 0, g.num_nodes - 1
        return req

    # Warm the bucket's jit compile outside the clock — boot cost, not a
    # per-kind price (every kind rides the same level loop).
    MSTService(backend="device").handle(kind_request(pool[0], "mst"))

    solve_lat = {k: [] for k in kinds}
    hit_lat = {k: [] for k in kinds}
    wrong = 0
    for _ in range(args.repeats):
        for kind in kinds:
            svc = MSTService(backend="device")
            served = 0
            for sink in (solve_lat, hit_lat):
                for g in pool:
                    t0 = time.perf_counter()
                    resp = svc.handle(kind_request(g, kind))
                    sink[kind].append(time.perf_counter() - t0)
                    if not resp.get("ok"):
                        wrong += 1
                    elif kind == "mst":
                        served += int(resp["total_weight"])
            if kind == "mst" and served != 2 * oracle_weight:
                wrong += 1
    if wrong:
        print(f"KINDS BENCH FAILED: {wrong} wrong/non-ok responses",
              file=sys.stderr)

    metrics = {"mst_weight": oracle_weight, "wrong_results": wrong}
    for kind in kinds:
        metrics[f"{kind}_solve_p50_s"] = quantile(solve_lat[kind], 0.5)
        metrics[f"{kind}_hit_p50_s"] = quantile(hit_lat[kind], 0.5)
    out = {
        "metric": f"analytics kinds, {len(pool)} x gnm({args.batch_nodes},"
        f"{args.batch_edges}), {args.repeats} repeats",
        "value": round(metrics["mst_solve_p50_s"] * 1e3, 3),
        "unit": "ms (mst solve p50; per-kind keys in metrics)",
        **{
            name: (round(value, 6) if name.endswith("_s") else value)
            for name, value in metrics.items()
        },
    }
    print(json.dumps(out))
    if args.metrics_out:
        with open(args.metrics_out, "w") as f:
            json.dump(
                {
                    "schema": "ghs-bench-metrics-v1",
                    "config": {"workload": "gate-analytics-bench-v1"},
                    "metrics": metrics,
                },
                f,
                indent=2,
            )
            f.write("\n")
    return 0 if wrong == 0 else 1


def run_tuned_bench(args) -> int:
    """Tuned vs default selector (``gate-tune-v1``): warm solve p50 on
    the batch-lane and sharded (mesh) paths with a TuningRecord installed
    vs the bare probe heuristic, plus the deterministic record-consult
    count the gate pins exactly.

    The record comes from ``--tune-record`` (written by ``ghs tune``) or,
    absent that, a dry in-process search over exactly the buckets this
    bench drives — dry records pin ``xla`` winners on any backend, so the
    bench is deterministic everywhere (docs/KERNELS.md "Autotuning").
    ``tune_record_hits`` counts the measured-tier selections
    (``kernel.selected.measured``) the tuned phase made — one per batched
    dispatch (warm resident mesh re-solves reuse their staged programs
    without re-resolving) — so it gates exactly against
    ``docs/BENCH_BASELINE_TUNE.json``: a drop means the record stopped
    being consulted (a wiring regression, never jitter). Both phases'
    results are checked edge-for-edge against each other.
    """
    import tempfile

    import jax
    import numpy as np

    from distributed_ghs_implementation_tpu.batch import lanes as lanes_mod
    from distributed_ghs_implementation_tpu.graphs.generators import (
        gnm_random_graph,
    )
    from distributed_ghs_implementation_tpu.obs.events import BUS
    from distributed_ghs_implementation_tpu.tune import (
        load_and_install,
        save_record,
        search,
    )
    from distributed_ghs_implementation_tpu.tune.measure import mesh_bucket

    BUS.enable()
    BUS.clear()
    lanes = args.batch_lanes or 8
    graphs = [
        gnm_random_graph(args.batch_nodes, args.batch_edges, seed=SEED * 1000 + i)
        for i in range(lanes)
    ]
    n_pad, m_pad = lanes_mod.bucket_of(args.batch_nodes, args.batch_edges)
    buckets = [(n_pad, m_pad, lanes, "fused"), (n_pad, m_pad, 0, "fused")]

    use_mesh = jax.device_count() >= 2
    mesh_graph = None
    lane = None
    if use_mesh:
        from distributed_ghs_implementation_tpu.parallel.lane import ShardedLane

        mesh_graph = gnm_random_graph(
            args.sharded_nodes, args.sharded_edges, seed=SEED
        )
        buckets.append(
            mesh_bucket(
                args.sharded_nodes, args.sharded_edges, jax.device_count()
            )
        )
        lane = ShardedLane(kernel=args.kernel)

    def _warm_p50(fn):
        fn()  # warm (compile on the first phase, cache hit after)
        times = []
        for _ in range(args.repeats):
            t0 = time.perf_counter()
            fn()
            times.append(time.perf_counter() - t0)
        return _pctl(times, 0.50)

    def _batch():
        return lanes_mod.solve_lanes(
            graphs, lanes=lanes, mode="fused", kernel=None
        )

    # Phase 1 — default selector (no record installed anywhere).
    default_p50 = _warm_p50(_batch)
    default_ids = [r[0] for r in _batch()]
    mesh_default_p50 = None
    if use_mesh:
        mesh_default_p50 = _warm_p50(lambda: lane.solve(mesh_graph))

    # Phase 2 — the tuned selector: install, re-measure the same work.
    record_path = args.tune_record
    if not record_path:
        record = search(buckets, repeats=1, dry=True)
        record_path = os.path.join(
            tempfile.mkdtemp(prefix="ghs-tune-bench-"), "tuning.json"
        )
        save_record(record, record_path)
    installed = load_and_install(record_path)
    if installed < 1:
        print("TUNED BENCH FAILED: record installed 0 buckets",
              file=sys.stderr)
        return 1
    before = BUS.counters().get("kernel.selected.measured", 0)
    tuned_p50 = _warm_p50(_batch)
    tuned_ids = [r[0] for r in _batch()]
    mesh_tuned_p50 = None
    if use_mesh:
        mesh_tuned_p50 = _warm_p50(lambda: lane.solve(mesh_graph))
    tune_record_hits = int(
        BUS.counters().get("kernel.selected.measured", 0) - before
    )

    if not all(np.array_equal(a, b) for a, b in zip(default_ids, tuned_ids)):
        print("TUNED BENCH PARITY FAILED: tuned vs default edge ids",
              file=sys.stderr)
        return 1
    if tune_record_hits < 1:
        print("TUNED BENCH FAILED: the installed record was never "
              "consulted (kernel.selected.measured did not count)",
              file=sys.stderr)
        return 1

    total_weight = int(sum(
        int(g.w[ids].sum()) for g, ids in zip(graphs, tuned_ids)
    ))
    out = {
        "metric": f"tuned vs default selector, {lanes}-lane "
        f"gnm({args.batch_nodes},{args.batch_edges})"
        + (f" + mesh gnm({args.sharded_nodes},{args.sharded_edges})"
           if use_mesh else ""),
        "value": round(default_p50 / tuned_p50, 3),
        "unit": "x (batch warm p50, default/tuned)",
        "default_warm_p50_s": round(default_p50, 4),
        "tuned_warm_p50_s": round(tuned_p50, 4),
        "tune_record_hits": tune_record_hits,
        "tuned_entries": installed,
        "record": record_path,
    }
    if use_mesh:
        out["mesh_default_warm_p50_s"] = round(mesh_default_p50, 4)
        out["mesh_tuned_warm_p50_s"] = round(mesh_tuned_p50, 4)
    print(json.dumps(out))
    if args.metrics_out:
        metrics = {
            "default_warm_p50_s": default_p50,
            "tuned_warm_p50_s": tuned_p50,
            "tune_record_hits": tune_record_hits,
            "tuned_entries": installed,
            "mst_weight": total_weight,
        }
        if use_mesh:
            metrics["mesh_default_warm_p50_s"] = mesh_default_p50
            metrics["mesh_tuned_warm_p50_s"] = mesh_tuned_p50
        with open(args.metrics_out, "w") as f:
            json.dump(
                {
                    "schema": "ghs-bench-metrics-v1",
                    "config": {
                        "workload": f"tuned-{lanes}lane-gnm"
                        f"({args.batch_nodes},{args.batch_edges})-seed{SEED}"
                        f"-{jax.device_count()}dev-r{args.repeats}",
                    },
                    "metrics": metrics,
                },
                f,
                indent=2,
            )
            f.write("\n")
    return 0


def run_sharded_bench(args) -> int:
    """Oversize-lane serving metrics: cold staging vs warm device-resident
    re-solve on the mesh (``parallel/lane.py``), plus the donated-buffer
    incremental-update path.

    The pair that matters is ``resolve_cold_s`` (host prep + staging +
    dispatch) vs ``resolve_warm_s`` (dispatch-only on a resident graph —
    the repeat-solve path the serving scheduler hits after routing an
    oversize miss); ``reshard_skipped`` counts the dispatches that reused
    the pre-partitioned device arrays, and is DETERMINISTIC (one per warm
    repeat + one per donated update), so it gates exactly. Metrics land in
    the ``ghs-bench-metrics-v1`` schema and gate against
    ``docs/BENCH_BASELINE_SHARDED.json`` (``gate-sharded-v1``).
    """
    import numpy as np

    from distributed_ghs_implementation_tpu.api import minimum_spanning_forest
    from distributed_ghs_implementation_tpu.graphs.edgelist import Graph
    from distributed_ghs_implementation_tpu.graphs.generators import (
        gnm_random_graph,
    )
    from distributed_ghs_implementation_tpu.obs.events import BUS
    from distributed_ghs_implementation_tpu.parallel.lane import ShardedLane

    BUS.enable()
    BUS.clear()
    lane = ShardedLane(kernel=args.kernel)
    g = gnm_random_graph(
        args.sharded_nodes, args.sharded_edges, seed=SEED
    )

    t0 = time.perf_counter()
    lane.precompile(g.num_nodes, g.num_edges)
    warmup_s = time.perf_counter() - t0
    print(
        f"mesh warmup ({lane.n_dev} device(s)): {warmup_s:.3f}s",
        file=sys.stderr,
    )

    t0 = time.perf_counter()
    ids_cold, _, levels = lane.solve(g)
    resolve_cold_s = time.perf_counter() - t0

    warm_times = []
    for _ in range(args.repeats):
        t0 = time.perf_counter()
        ids_warm, _, _ = lane.solve(g)
        warm_times.append(time.perf_counter() - t0)
    resolve_warm_s = min(warm_times)

    # Donated incremental update: a top-weight true insert (one changed
    # rank slot — the scatter regime), then the dispatch-only re-solve.
    existing = {(int(a), int(b)) for a, b in zip(g.u, g.v)}
    ins_v = next(x for x in range(1, g.num_nodes) if (0, x) not in existing)
    g2 = Graph.from_arrays(
        g.num_nodes,
        np.concatenate([g.u, [0]]),
        np.concatenate([g.v, [ins_v]]),
        np.concatenate([g.w, [int(g.w.max()) + 1]]),
    )
    t0 = time.perf_counter()
    ids_upd, _, _ = lane.update(g.digest(), g2)
    update_donated_s = time.perf_counter() - t0

    ref = minimum_spanning_forest(g, backend="device")
    ref2 = minimum_spanning_forest(g2, backend="device")
    if not (
        np.array_equal(ids_cold, ref.edge_ids)
        and np.array_equal(ids_warm, ref.edge_ids)
        and np.array_equal(ids_upd, ref2.edge_ids)
    ):
        print("SHARDED LANE PARITY FAILED vs device solve", file=sys.stderr)
        return 1

    counters = BUS.counters()
    reshard_skipped = int(counters.get("lane.reshard.skipped", 0))
    update_donated = int(counters.get("lane.update.donated", 0))

    # Level-kernel pair (gate-kernel-v1, docs/KERNELS.md): warm resident
    # re-solves on a second lane pinned to XLA vs this lane's resolved
    # kernel. Runs LAST — after the exact-gated counters are read (the
    # extra lane's resharding bookkeeping must not perturb them) and with
    # this lane's residency evicted first: two device-resident copies of
    # an oversize graph is exactly what the lane's LRU exists to prevent.
    # Where the lane already resolved xla (no TPU, sticky fallback) the
    # pair would be the same program twice — pin the speedup at exactly
    # 1.0 instead of re-measuring noise, the fallback-routing contract.
    kernel_speedup = 1.0
    if lane.kernel != "xla":
        for digest in lane.resident_digests():
            lane.evict(digest)
        lane_xla = ShardedLane(kernel="xla")
        lane_xla.solve(g)  # stage + warm the resident XLA program
        xla_times = []
        for _ in range(args.repeats):
            t0 = time.perf_counter()
            ids_xla, _, _ = lane_xla.solve(g)
            xla_times.append(time.perf_counter() - t0)
        if not np.array_equal(ids_xla, ref.edge_ids):
            print("KERNEL PARITY FAILED: pallas vs xla lane", file=sys.stderr)
            return 1
        kernel_speedup = min(xla_times) / resolve_warm_s
    out = {
        "metric": f"sharded-lane oversize serving, gnm({g.num_nodes},"
        f"{g.num_edges}) on {lane.n_dev} device(s)",
        "value": round(g.num_edges / resolve_warm_s, 1),
        "unit": "edges/s (warm resident re-solve)",
        "warmup_s": round(warmup_s, 3),
        "resolve_cold_s": round(resolve_cold_s, 3),
        "resolve_warm_s": round(resolve_warm_s, 3),
        "update_donated_s": round(update_donated_s, 3),
        "reshard_skipped": reshard_skipped,
        "update_donated": update_donated,
        "levels": int(levels),
        "kernel": lane.kernel,
        "level_kernel_speedup": round(kernel_speedup, 3),
        "parity": "edge-exact vs device solve (incl. updated graph)",
    }
    print(json.dumps(out))
    if args.metrics_out:
        metrics = {
            "warmup_s": warmup_s,
            "resolve_cold_s": resolve_cold_s,
            "resolve_warm_s": resolve_warm_s,
            "warm_edges_per_sec": g.num_edges / resolve_warm_s,
            "update_donated_s": update_donated_s,
            "reshard_skipped": reshard_skipped,
            "update_donated": update_donated,
            "levels": int(levels),
            "level_kernel_speedup": kernel_speedup,
            "mst_weight": int(g.w[ids_cold].sum()),
        }
        with open(args.metrics_out, "w") as f:
            json.dump(
                {
                    "schema": "ghs-bench-metrics-v1",
                    "config": {
                        "workload": f"sharded-lane-gnm({args.sharded_nodes},"
                        f"{args.sharded_edges})-seed{SEED}"
                        f"-{lane.n_dev}dev-r{args.repeats}",
                    },
                    "metrics": metrics,
                },
                f,
                indent=2,
            )
            f.write("\n")
    return 0


def run_stream_sharded_bench(args) -> int:
    """Durable sharded streaming (gate-stream-sharded-v1): window
    throughput on a MESH-RESIDENT oversize stream, plus the crash-rebuild
    leg — a fresh process re-staging the snapshot and replaying the WAL
    into the lane's donated slots with zero fresh solves.

    One oversize-by-node-bucket seed (past the lane-engine admission
    ceiling, so it routes like a billion-edge graph while solving in
    bench time) is solved cold on the mesh, subscribed as a durable
    stream fused to the lane, and driven through K published windows —
    each commit coalesces via ``stream/window.py`` and migrates the
    resident CSR slots through ``ShardedLane.refresh_resident``
    (``window_commits_per_sec`` / ``window_updates_per_sec``). Then the
    manager and lane are thrown away and a fresh pair rebuilds the head
    from snapshot + WAL alone (``replay_rebuild_s``): the snapshot
    re-stages exactly once (``residency_restored`` gates exact), every
    window re-scatters, no solver is even attached, and the rebuilt head
    must be edge-exact against a fresh oracle solve. Warm head solves on
    both sides stay dispatch-only (``reshard_skipped`` gates exact).
    """
    import tempfile

    import numpy as np

    from distributed_ghs_implementation_tpu.api import minimum_spanning_forest
    from distributed_ghs_implementation_tpu.obs.events import BUS
    from distributed_ghs_implementation_tpu.graphs.generators import (
        gnm_random_graph,
    )
    from distributed_ghs_implementation_tpu.parallel.lane import ShardedLane
    from distributed_ghs_implementation_tpu.stream.session import StreamManager
    from distributed_ghs_implementation_tpu.stream.window import (
        random_update_stream,
        warm_window_kernels,
    )

    BUS.enable()
    BUS.clear()
    n, m = args.stream_sharded_nodes, args.stream_sharded_edges
    windows, per_window = args.stream_sharded_windows, args.stream_window
    g = gnm_random_graph(n, m, seed=SEED)
    rng = np.random.default_rng(SEED)

    lane = ShardedLane(kernel=args.kernel)
    t0 = time.perf_counter()
    lane.precompile(n, m)
    warm_window_kernels(n, m)
    warm_window_kernels(n, m + windows * per_window)
    warmup_s = time.perf_counter() - t0
    print(
        f"mesh + window warmup ({lane.n_dev} device(s)): {warmup_s:.3f}s",
        file=sys.stderr,
    )

    t0 = time.perf_counter()
    seed_result = lane.solve_result(g)
    seed_solve_s = time.perf_counter() - t0

    with tempfile.TemporaryDirectory(prefix="ghs-stream-sharded-") as root:
        # snapshot_every deliberately does NOT divide the window count:
        # the rebuild leg must find WAL entries past the last snapshot
        # (replay_windows gates exact), not a fully-snapshotted stream.
        mgr = StreamManager(root=root, snapshot_every=3, lane=lane)
        session = mgr.subscribe(digest=g.digest(), result=seed_result)
        if not session.sharded:
            print("STREAM NOT SHARDED: seed did not route to the mesh lane",
                  file=sys.stderr)
            return 1

        head = session.head
        t0 = time.perf_counter()
        for _ in range(windows):
            window = random_update_stream(rng, g, per_window)
            head = mgr.publish(session.id, head, window)["digest"]
        window_commit_s = time.perf_counter() - t0

        # Warm head solve: dispatch-only on the residency the commits
        # maintained (reshard_skipped counts it).
        head_graph = session.mst.result().graph
        t0 = time.perf_counter()
        ids_live, _, _ = lane.solve(head_graph)
        head_warm_solve_s = time.perf_counter() - t0

        # Crash-rebuild leg: fresh lane + manager, NO solver attached —
        # the rebuild is snapshot re-stage + WAL re-scatter or nothing.
        stream_id = session.id
        del mgr, session
        lane2 = ShardedLane(kernel=args.kernel)
        mgr2 = StreamManager(root=root, snapshot_every=3, lane=lane2)
        t0 = time.perf_counter()
        recovered = mgr2.recover(stream_id)
        replay_rebuild_s = time.perf_counter() - t0
        if recovered is None or recovered.head != head:
            print("REPLAY REBUILD FAILED: recovered head diverged",
                  file=sys.stderr)
            return 1
        rebuilt = recovered.mst.result()
        t0 = time.perf_counter()
        ids_replay, _, _ = lane2.solve(rebuilt.graph)
        replay_warm_solve_s = time.perf_counter() - t0

    ref = minimum_spanning_forest(rebuilt.graph, backend="device")
    if not (
        np.array_equal(np.sort(ids_live), np.sort(ref.edge_ids))
        and np.array_equal(np.sort(ids_replay), np.sort(ref.edge_ids))
        and np.array_equal(np.sort(rebuilt.edge_ids), np.sort(ref.edge_ids))
    ):
        print("STREAM-SHARDED PARITY FAILED vs fresh oracle solve",
              file=sys.stderr)
        return 1

    counters = BUS.counters()
    migrated = int(
        counters.get("stream.lane.migrated", 0)
        + counters.get("stream.lane.restaged", 0)
    )
    commits_per_sec = windows / window_commit_s
    out = {
        "metric": f"durable sharded streaming, gnm({n},{m}) on "
        f"{lane.n_dev} device(s), {windows} windows of {per_window}",
        "value": round(commits_per_sec, 2),
        "unit": "window commits/s (mesh-resident, durable)",
        "warmup_s": round(warmup_s, 3),
        "seed_solve_s": round(seed_solve_s, 3),
        "window_commits_per_sec": round(commits_per_sec, 2),
        "window_updates_per_sec": round(
            windows * per_window / window_commit_s, 1
        ),
        "head_warm_solve_s": round(head_warm_solve_s, 3),
        "replay_rebuild_s": round(replay_rebuild_s, 3),
        "replay_warm_solve_s": round(replay_warm_solve_s, 3),
        "residency_migrated": migrated,
        "residency_restored": int(counters.get("lane.resident.restored", 0)),
        "replay_windows": int(counters.get("stream.replay.windows", 0)),
        "replay_fresh_solves": int(
            counters.get("stream.replay.fresh_solve", 0)
        ),
        "reshard_skipped": int(counters.get("lane.reshard.skipped", 0)),
        "kernel": lane.kernel,
        "parity": "edge-exact vs fresh oracle solve (live AND rebuilt head)",
    }
    print(json.dumps(out))
    if args.metrics_out:
        metrics = {
            "warmup_s": warmup_s,
            "seed_solve_s": seed_solve_s,
            "window_commit_s": window_commit_s,
            "window_commits_per_sec": commits_per_sec,
            "window_updates_per_sec": windows * per_window / window_commit_s,
            "head_warm_solve_s": head_warm_solve_s,
            "replay_rebuild_s": replay_rebuild_s,
            "replay_warm_solve_s": replay_warm_solve_s,
            "residency_migrated": migrated,
            "residency_restored": int(
                counters.get("lane.resident.restored", 0)
            ),
            "replay_windows": int(counters.get("stream.replay.windows", 0)),
            "replay_fresh_solves": int(
                counters.get("stream.replay.fresh_solve", 0)
            ),
            "reshard_skipped": int(counters.get("lane.reshard.skipped", 0)),
            "mst_weight": int(
                rebuilt.graph.w[np.asarray(rebuilt.edge_ids)].sum()
            ),
            "mst_edges": int(np.asarray(rebuilt.edge_ids).size),
        }
        with open(args.metrics_out, "w") as f:
            json.dump(
                {
                    "schema": "ghs-bench-metrics-v1",
                    "config": {
                        "workload": "gate-stream-sharded-v1",
                        "shape": f"gnm({n},{m})-seed{SEED}"
                        f"-{lane.n_dev}dev-w{windows}x{per_window}",
                    },
                    "metrics": metrics,
                },
                f,
                indent=2,
            )
            f.write("\n")
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--scale", type=int, default=24, help="RMAT scale (2^scale vertices)")
    p.add_argument("--edge-factor", type=int, default=16)
    p.add_argument("--repeats", type=int, default=3)
    p.add_argument("--backend", default="device", choices=["device", "sharded"])
    p.add_argument("--no-verify", action="store_true")
    p.add_argument(
        "--metrics-out",
        help="also write the run's metrics in the bench-gate schema "
        "(tools/bench_gate.py compares such files across runs)",
    )
    p.add_argument(
        "--batch-lanes", type=int, default=0,
        help="measure batched small-graph serving throughput at this lane "
        "count instead of the RMAT bench (0 = RMAT bench)",
    )
    p.add_argument("--batch-graphs", type=int, default=64,
                   help="graphs in the batched workload")
    p.add_argument("--batch-nodes", type=int, default=128)
    p.add_argument("--batch-edges", type=int, default=480)
    p.add_argument(
        "--warmup", action="store_true",
        help="AOT-precompile the batch bucket before the cold-first-query "
        "clock (batch/warmup.py) — the cold/warm comparison pair for "
        "cold_first_solve_s (batch mode only)",
    )
    p.add_argument(
        "--sharded-lane", action="store_true",
        help="measure the oversize sharded-lane serving path (cold staging "
        "vs warm device-resident re-solve, donated updates) instead of the "
        "RMAT bench; set XLA_FLAGS=--xla_force_host_platform_device_count=8 "
        "for the CI dryrun mesh",
    )
    p.add_argument("--sharded-nodes", type=int, default=70_000,
                   help="oversize workload nodes for --sharded-lane")
    p.add_argument("--sharded-edges", type=int, default=140_000)
    p.add_argument(
        "--tuned", action="store_true",
        help="measure the tuned vs default kernel selector instead of the "
        "RMAT bench: warm solve p50 on the batch-lane (and, with >= 2 "
        "devices, mesh) paths with a TuningRecord installed, plus the "
        "exact record-consult count gate-tune-v1 pins "
        "(docs/BENCH_BASELINE_TUNE.json, docs/KERNELS.md \"Autotuning\")",
    )
    p.add_argument(
        "--tune-record", default=None, metavar="PATH",
        help="with --tuned: install this ghs-tuning-v1 record (from `ghs "
        "tune`) instead of running a dry in-process search",
    )
    p.add_argument(
        "--fleet-tcp", action="store_true",
        help="measure network-fleet transport overhead instead of the RMAT "
        "bench: router-hop p50/p95 over TCP sockets vs subprocess pipes on "
        "echo workers, plus EXACT cache-miss forwarding counters "
        "(gate-fleet-tcp-v1, docs/FLEET.md); jax-free and CI-cheap",
    )
    p.add_argument("--fleet-requests", type=int, default=200,
                   help="round trips per transport in --fleet-tcp (each "
                   "runs once sequentially and once in a concurrent burst)")
    p.add_argument("--fleet-forward", type=int, default=6,
                   help="forwarding hits AND misses driven in --fleet-tcp "
                   "(fleet.forward.hit/miss then gate exactly)")
    p.add_argument(
        "--wire", action="store_true",
        help="measure the binary wire plane instead of the RMAT bench: "
        "B-frame vs JSON ingest throughput (graphs/sec, FAILS below 5x), "
        "digest/edge parity, and EXACT opaque-passthrough counters "
        "through all-binary and mixed-build TCP echo fleets "
        "(gate-wire-v1, docs/FLEET.md \"Binary wire\"); jax-free",
    )
    p.add_argument("--wire-nodes", type=int, default=70_000,
                   help="deck graph nodes for --wire (the oversize bucket)")
    p.add_argument("--wire-edges", type=int, default=3_000)
    p.add_argument("--wire-graphs", type=int, default=16,
                   help="graphs in the --wire ingest/fleet deck")
    p.add_argument(
        "--update-stream", action="store_true",
        help="measure streaming MSF maintenance: windowed batched apply "
        "(stream/window.py) vs the sequential per-update path, edge-exact "
        "parity enforced (gate-stream-bench-v1)",
    )
    p.add_argument("--stream-nodes", type=int, default=1024)
    p.add_argument("--stream-edges", type=int, default=4096)
    p.add_argument("--stream-updates", type=int, default=256,
                   help="updates in the measured stream")
    p.add_argument("--stream-window", type=int, default=64,
                   help="updates per committed window (the batching unit)")
    p.add_argument(
        "--stream-sharded", action="store_true",
        help="measure durable sharded streaming (gate-stream-sharded-v1): "
        "window commits on a mesh-resident oversize stream fused to the "
        "sharded lane, then the crash-rebuild leg — snapshot re-stage + "
        "WAL re-scatter with zero fresh solves, edge-exact vs a fresh "
        "oracle; set XLA_FLAGS=--xla_force_host_platform_device_count=8 "
        "for the CI dryrun mesh",
    )
    p.add_argument("--stream-sharded-nodes", type=int, default=70_000,
                   help="stream seed nodes for --stream-sharded (oversize "
                   "by node bucket: routes to the mesh lane)")
    p.add_argument("--stream-sharded-edges", type=int, default=3_000)
    p.add_argument("--stream-sharded-windows", type=int, default=8,
                   help="published windows in --stream-sharded (each of "
                   "--stream-window updates)")
    p.add_argument(
        "--verify", action="store_true",
        help="certificate-checker overhead bench (gate-verify-bench-v1): "
        "per-engine certify p50 at interactive + bulk scale, adversarial "
        "mutation rejection exact (docs/VERIFICATION.md). Unrelated to "
        "--no-verify, which skips the RMAT run's oracle check",
    )
    p.add_argument(
        "--kinds", action="store_true",
        help="per-kind analytics latency bench (gate-analytics-bench-v1): "
        "p50 of each query kind (mst, components, k_msf, bottleneck, "
        "path_max) through the service, cold (the kind's own solve) and "
        "warm (per-kind cache / O(tree) derive) — docs/ANALYTICS.md",
    )
    p.add_argument(
        "--kernel", choices=["auto", "pallas", "xla"], default=None,
        help="per-level solver kernel (docs/KERNELS.md): 'pallas' = fused "
        "Pallas TPU kernels, 'xla' = the plain two-step path, 'auto' "
        "(default) = Pallas on TPU where the capability probe passes. The "
        "lane (--batch-lanes) and sharded (--sharded-lane) workloads also "
        "report level_kernel_speedup — the resolved-kernel vs XLA pair "
        "gate-kernel-v1 enforces (pinned 1.0 where the resolved kernel IS "
        "xla, so the gate passes on the fallback path)",
    )
    args = p.parse_args(argv)
    if args.kernel:
        from distributed_ghs_implementation_tpu.ops.pallas_kernels import (
            set_default_kernel,
        )

        set_default_kernel(args.kernel)
    if args.verify:
        return run_verify_bench(args)
    if args.kinds:
        return run_kinds_bench(args)
    if args.fleet_tcp:
        return run_fleet_tcp_bench(args)
    if args.wire:
        return run_wire_bench(args)
    if args.update_stream:
        return run_update_stream_bench(args)
    if args.stream_sharded:
        return run_stream_sharded_bench(args)
    if args.tuned:
        return run_tuned_bench(args)
    if args.sharded_lane:
        return run_sharded_bench(args)
    if args.batch_lanes:
        return run_batch_bench(args)

    from distributed_ghs_implementation_tpu.api import minimum_spanning_forest
    from distributed_ghs_implementation_tpu.graphs.generators import rmat_graph
    from distributed_ghs_implementation_tpu.utils.verify import verify_result

    t0 = time.perf_counter()
    g = rmat_graph(args.scale, args.edge_factor, seed=SEED)
    print(
        f"generated RMAT-{args.scale}: {g.num_nodes:,} nodes, {g.num_edges:,} edges "
        f"in {time.perf_counter() - t0:.1f}s",
        file=sys.stderr,
    )

    # Device-resident timing of the kernel that is also the one verified:
    # arrays staged once, each repeat is solve + scalar sync. prep_s is the
    # full host-side cost of getting there from the cold graph.
    times = []
    prep_s = None
    if args.backend == "device":
        import numpy as np

        from distributed_ghs_implementation_tpu.api import MSTResult
        from distributed_ghs_implementation_tpu.models.rank_solver import (
            make_production_solver,
        )

        # make_production_solver is the single routing source shared with
        # solve_graph_rank: the bench measures the kernel production runs.
        t0 = time.perf_counter()
        solve = make_production_solver(g)
        prep_s = time.perf_counter() - t0
        print(f"host prep (ranks + first_ranks + L1/L2 + staging): "
              f"{prep_s:.1f}s", file=sys.stderr)
        t0 = time.perf_counter()
        mst, fragment, levels = solve()
        _ = np.asarray(mst.ravel()[0])  # warm + sync
        cold_first_solve_s = time.perf_counter() - t0  # compile included
        for _ in range(args.repeats):
            t0 = time.perf_counter()
            mst, fragment, levels = solve()
            _ = np.asarray(mst.ravel()[0])
            times.append(time.perf_counter() - t0)
        # Wrap the timed kernel's own output for verification below.
        ranks = np.nonzero(np.asarray(mst))[0]
        edge_ids = np.sort(g.edge_id_of_rank(ranks))
        fragment = np.asarray(fragment)[: g.num_nodes]
        result = MSTResult(
            graph=g,
            edge_ids=edge_ids,
            num_levels=int(levels),
            wall_time_s=min(times),
            backend="device/rank",
            num_components=int(np.unique(fragment).size),
        )
    else:
        result = minimum_spanning_forest(g, backend=args.backend)
        cold_first_solve_s = result.wall_time_s  # compile included
        for _ in range(args.repeats):
            r = minimum_spanning_forest(g, backend=args.backend)
            times.append(r.wall_time_s)
    best = min(times)
    print(f"solve times: {[f'{t:.3f}' for t in times]} "
          f"(cold first: {cold_first_solve_s:.3f})", file=sys.stderr)

    # Recorded weights apply only to graphs from the native generator RNG
    # stream (the graph carries the tag); on a toolchain-less host the
    # NumPy-stream graph differs, so fall back to the live oracle.
    recorded = (
        RECORDED_ORACLE_WEIGHTS.get((args.scale, args.edge_factor, SEED))
        if g.__dict__.get("generator_path") == "rmat-native"
        else None
    )
    if not args.no_verify:
        # Recorded weight when known; otherwise the live auto oracle (the
        # native Kruskal pass — fast enough at any bench scale).
        v = verify_result(result, oracle="auto", expected_weight=recorded)
        if not v.ok:
            print(f"VERIFICATION FAILED: {v}", file=sys.stderr)
            print(
                json.dumps(
                    {
                        "metric": f"MST edges/sec on RMAT-{args.scale} (VERIFY FAILED)",
                        "value": 0.0,
                        "unit": "edges/s",
                        "vs_baseline": 0.0,
                    }
                )
            )
            return 1
        print(
            f"verified: weight {v.actual_weight} = {v.oracle} oracle",
            file=sys.stderr,
        )

    edges_per_sec = g.num_edges / best
    verified = "weight-verified" if not args.no_verify else "unverified"
    out = {
        "metric": f"MST edges/sec on RMAT-{args.scale} ({g.num_nodes} nodes, {g.num_edges} edges, {verified}, solve-only)",
        "value": round(edges_per_sec, 1),
        "unit": "edges/s",
        "vs_baseline": round(edges_per_sec / BASELINE_EDGES_PER_SEC, 1),
        "solve_s": round(best, 3),
        "cold_first_solve_s": round(cold_first_solve_s, 3),
        "solve_p50_s": round(_pctl(times, 0.50), 3),
        "solve_p95_s": round(_pctl(times, 0.95), 3),
    }
    if prep_s is not None:
        out["prep_s"] = round(prep_s, 3)
        out["e2e_edges_per_sec"] = round(g.num_edges / (prep_s + best), 1)
    print(json.dumps(out))
    if args.metrics_out:
        gate_metrics = {
            "solve_s": best,
            "cold_first_solve_s": cold_first_solve_s,
            "solve_p50_s": _pctl(times, 0.50),
            "solve_p95_s": _pctl(times, 0.95),
            "edges_per_sec": edges_per_sec,
            "levels": int(result.num_levels),
            "mst_weight": int(result.total_weight),
            "mst_edges": int(result.num_edges),
        }
        if prep_s is not None:
            gate_metrics["prep_s"] = prep_s
            gate_metrics["e2e_edges_per_sec"] = g.num_edges / (prep_s + best)
        with open(args.metrics_out, "w") as f:
            json.dump(
                {
                    "schema": "ghs-bench-metrics-v1",
                    "config": {
                        "workload": f"rmat-{args.scale}x{args.edge_factor}"
                        f"-seed{SEED}-{args.backend}",
                    },
                    "metrics": gate_metrics,
                },
                f,
                indent=2,
            )
            f.write("\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
