"""Shared ``sys.path`` bootstrap for ``tools/`` scripts.

Every script here is run as a file (``python tools/<script>.py``), so the
repo root is not importable until someone puts it on ``sys.path``. That
someone used to be four copy-pasted ``sys.path.insert`` preambles; it is
now this module — scripts just ``import _bootstrap`` (the script's own
directory, ``tools/``, is ``sys.path[0]`` when run as a file, so the
import always resolves).
"""

import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)
