"""Edge-sharded MST solve: the multi-chip replacement for the MPI backend.

Layout: directed slots are block-sharded over the mesh's ``edges`` axis (shard
``k`` owns global slots ``[k*e_local, (k+1)*e_local)`` — the contiguity the
global tie-break ids in ``ops.segment_ops`` rely on); ``fragment`` is
replicated and every device runs the identical hook-and-compress update, so no
collective is needed for the merge itself. Per level the only cross-chip
traffic is three n-sized ``lax.pmin``s (min weight, winning slot, winner's
destination fragment) — the ICI analog of the reference's REPORT convergecast
+ CHANGEROOT walk (``/root/reference/ghs_implementation_mpi.py:493-647``).
"""

from __future__ import annotations

import functools
import math
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_ghs_implementation_tpu.graphs.edgelist import Graph
from distributed_ghs_implementation_tpu.models.boruvka import (
    BoruvkaState,
    _bucket_size,
    _max_levels,
    boruvka_level,
)
from distributed_ghs_implementation_tpu.obs.events import BUS
from distributed_ghs_implementation_tpu.parallel.mesh import (
    EDGE_AXIS,
    edge_mesh,
    shard_map_compat,
)


def _stage(arr, sharding: NamedSharding) -> jax.Array:
    """Host->mesh staging that also works across processes.

    ``jax.device_put`` of host-local numpy onto a sharding that spans
    non-addressable (cross-process) devices is not portable; in multi-process
    runs each process instead contributes only its addressable shards via
    ``make_array_from_callback`` (every host holds the full graph, so the
    callback just slices it).
    """
    if jax.process_count() > 1:
        arr = np.asarray(arr)
        return jax.make_array_from_callback(arr.shape, sharding, lambda idx: arr[idx])
    return jax.device_put(jnp.asarray(arr), sharding)


@functools.lru_cache(maxsize=32)
def make_sharded_solver(mesh: Mesh, num_nodes: int):
    """Build a jitted sharded solver ``(src, dst, rank, ra, rb) ->
    (mst_ranks, fragment, levels)`` for ``mesh``, starting from the identity
    partition over ``num_nodes`` vertices. Slot and rank counts must divide
    evenly by mesh size (pad with inert entries first)."""

    def shard_fn(src, dst, rank, ra, rb):
        m_local = ra.shape[0]
        state = BoruvkaState(
            fragment=jnp.arange(num_nodes, dtype=jnp.int32),
            mst_ranks=jnp.zeros(m_local, dtype=bool),
            level=jnp.zeros((), jnp.int32),
            progress=jnp.ones((), bool),
        )
        max_levels = _max_levels(num_nodes)

        # Unrolled level 0: fragment == iota, skip the relabel gathers.
        state = boruvka_level(
            state, src, dst, rank, ra, rb, axis_name=EDGE_AXIS, identity_fragment=True
        )

        def cond(s):
            return s.progress & (s.level < max_levels)

        def body(s):
            return boruvka_level(s, src, dst, rank, ra, rb, axis_name=EDGE_AXIS)

        final = jax.lax.while_loop(cond, body, state)
        return final.mst_ranks, final.fragment, final.level

    mapped = shard_map_compat(
        shard_fn,
        mesh,
        in_specs=(
            P(EDGE_AXIS),
            P(EDGE_AXIS),
            P(EDGE_AXIS),
            P(EDGE_AXIS),
            P(EDGE_AXIS),
        ),
        out_specs=(P(EDGE_AXIS), P(), P()),
    )
    return jax.jit(mapped)


@functools.lru_cache(maxsize=32)
def make_sharded_ell_solver(mesh: Mesh, num_nodes: int):
    """ELL kernel over a vertex-sharded mesh: each device owns a row slice of
    every degree bucket (hubs spread evenly because buckets group by degree),
    vertex state is replicated, and the only per-level communication is ONE
    n-sized ``lax.pmin`` merging per-vertex minima — the flat edge-sharded
    path needs three. Solver signature: ``(buckets, ra, rb) -> (mst_ranks,
    fragment, levels)`` with ``buckets`` a tuple of ``(verts, dst, rank)``
    whose leading axes divide by mesh size."""
    from distributed_ghs_implementation_tpu.models.boruvka import ell_solve_loop

    def shard_fn(buckets, ra, rb):
        return ell_solve_loop(
            buckets, ra, rb, num_nodes=num_nodes, axis_name=EDGE_AXIS
        )

    # shard_map needs the bucket tuple's specs spelled per leaf; wrap once per
    # bucket count (jit then caches per array-shape signature as usual).
    bucket_spec = (P(EDGE_AXIS), P(EDGE_AXIS, None), P(EDGE_AXIS, None))
    wrapped = {}

    def call(buckets, ra, rb):
        k = len(buckets)
        if k not in wrapped:
            specs = tuple(bucket_spec for _ in range(k))
            wrapped[k] = jax.jit(
                shard_map_compat(
                    shard_fn,
                    mesh,
                    in_specs=(specs, P(), P()),
                    out_specs=(P(), P(), P()),
                )
            )
        return wrapped[k](buckets, ra, rb)

    return call


def solve_graph_sharded_ell(
    graph: Graph, *, mesh: Mesh | None = None
) -> Tuple[np.ndarray, np.ndarray, int]:
    """ELL strategy on a mesh; mirrors ``solve_graph_sharded``'s contract."""
    if mesh is None:
        mesh = edge_mesh()
    n_dev = int(mesh.devices.size)
    n = graph.num_nodes
    if n == 0 or graph.num_edges == 0:
        return np.zeros(0, dtype=np.int64), np.arange(n, dtype=np.int32), 0

    n_pad = _bucket_size(n)
    m_pad = _bucket_size(graph.num_edges)
    ra_np, rb_np = graph.rank_endpoints(pad_to=m_pad)

    int32_max = np.iinfo(np.int32).max
    with BUS.span("parallel.stage", cat="parallel", strategy="ell", devices=n_dev):
        buckets = []
        for verts, dstb, rankb in graph.ell_buckets:
            vb, w = dstb.shape
            vb_pad = int(math.ceil(vb / n_dev) * n_dev)
            if vb_pad > vb:
                pad = vb_pad - vb
                verts = np.concatenate([verts, np.zeros(pad, dtype=np.int32)])
                dstb = np.vstack([dstb, np.zeros((pad, w), dtype=np.int32)])
                rankb = np.vstack(
                    [rankb, np.full((pad, w), int32_max, dtype=np.int32)]
                )
            row_sharding = NamedSharding(mesh, P(EDGE_AXIS, None))
            vert_sharding = NamedSharding(mesh, P(EDGE_AXIS))
            buckets.append(
                (
                    _stage(verts, vert_sharding),
                    _stage(dstb, row_sharding),
                    _stage(rankb, row_sharding),
                )
            )
        rep = NamedSharding(mesh, P())
        ra = _stage(ra_np, rep)
        rb = _stage(rb_np, rep)

    solver = make_sharded_ell_solver(mesh, n_pad)
    with BUS.span(
        "parallel.sharded.solve", cat="parallel", strategy="ell", devices=n_dev
    ):
        mst_ranks, fragment, levels = solver(tuple(buckets), ra, rb)
    ranks = np.nonzero(np.asarray(mst_ranks))[0]
    edge_ids = np.sort(graph.edge_id_of_rank(ranks))
    return edge_ids, np.asarray(fragment)[:n], int(levels)


def solve_graph_sharded(
    graph: Graph,
    *,
    mesh: Mesh | None = None,
    bucket_shapes: bool = True,
    strategy: str = "auto",
) -> Tuple[np.ndarray, np.ndarray, int]:
    """Host entry mirroring ``models.boruvka.solve_graph`` on a device mesh.

    ``strategy``: ``"rank"`` = rank-space solver (the fast path — sharded
    head + all-gathered compact finish, ``parallel/rank_sharded.py``);
    ``"flat"`` = edge-sharded flat kernel; ``"ell"`` = vertex-sharded ELL
    kernel; ``"auto"`` = rank at scale (any process count), below the scale
    threshold flat (single-process) or ELL (multi-process).
    """
    from distributed_ghs_implementation_tpu.models.boruvka import (
        ELL_AUTO_EDGE_THRESHOLD,
    )

    if strategy not in ("auto", "rank", "flat", "ell"):
        raise ValueError(
            f"unknown strategy {strategy!r}; expected auto|rank|flat|ell"
        )
    if jax.process_count() > 1:
        # The flat kernel's slot-sharded output is partially non-addressable
        # per process; rank (packed all-gather harvest) and ELL (replicated
        # outputs) both harvest everywhere. Auto keeps the fast path on pods.
        if strategy == "flat":
            raise ValueError(
                "strategy='flat' is single-process only (slot-sharded "
                "outputs are not harvestable across processes); use 'rank', "
                "'ell' or 'auto'"
            )
        if strategy == "auto":
            strategy = (
                "rank" if graph.num_edges >= ELL_AUTO_EDGE_THRESHOLD else "ell"
            )
    if strategy == "auto":
        strategy = "rank" if graph.num_edges >= ELL_AUTO_EDGE_THRESHOLD else "flat"
    if strategy == "rank":
        from distributed_ghs_implementation_tpu.parallel.rank_sharded import (
            solve_graph_rank_sharded,
        )

        return solve_graph_rank_sharded(graph, mesh=mesh)
    if strategy == "ell":
        return solve_graph_sharded_ell(graph, mesh=mesh)
    if mesh is None:
        mesh = edge_mesh()
    n_dev = mesh.devices.size
    n = graph.num_nodes
    if n == 0 or graph.num_edges == 0:
        return np.zeros(0, dtype=np.int64), np.arange(n, dtype=np.int32), 0
    n_pad = _bucket_size(n) if bucket_shapes else n
    e2 = 2 * graph.num_edges
    e_pad = _bucket_size(e2) if bucket_shapes else e2
    # Both the slot axis and the rank axis (e_pad // 2) must divide by mesh size.
    e_pad = int(math.ceil(e_pad / (2 * n_dev)) * 2 * n_dev)
    src_np, dst_np, rank_np, ra_np, rb_np = graph.rank_arrays(
        pad_edges_to=e_pad, pad_ranks_to=e_pad // 2
    )

    solver = make_sharded_solver(mesh, n_pad)
    n_dev_i = int(n_dev)
    with BUS.span(
        "parallel.stage", cat="parallel", strategy="flat", devices=n_dev_i
    ):
        edge_sharding = NamedSharding(mesh, P(EDGE_AXIS))
        src = _stage(src_np, edge_sharding)
        dst = _stage(dst_np, edge_sharding)
        rank = _stage(rank_np, edge_sharding)
        ra = _stage(ra_np, edge_sharding)
        rb = _stage(rb_np, edge_sharding)
    with BUS.span(
        "parallel.sharded.solve", cat="parallel", strategy="flat",
        devices=n_dev_i,
    ):
        mst_ranks, fragment, levels = solver(src, dst, rank, ra, rb)
    ranks = np.nonzero(np.asarray(mst_ranks))[0]
    edge_ids = np.sort(graph.edge_id_of_rank(ranks))
    return edge_ids, np.asarray(fragment)[:n], int(levels)
