"""Unified observability: structured event bus + exporters.

>>> from distributed_ghs_implementation_tpu.obs import BUS
>>> with BUS.span("solver.solve", cat="solver", nodes=1000):
...     ...
>>> BUS.count("protocol.messages_sent", 42)
>>> from distributed_ghs_implementation_tpu.obs.export import write_chrome_trace
>>> write_chrome_trace(BUS, "/tmp/trace.json")  # open in ui.perfetto.dev

See ``docs/OBSERVABILITY.md`` for the event taxonomy and workflows.
"""

from distributed_ghs_implementation_tpu.obs import tracing  # noqa: F401
from distributed_ghs_implementation_tpu.obs.events import (  # noqa: F401
    BUS,
    NULL_SPAN,
    EventBus,
    get_bus,
    merge_hists,
)
from distributed_ghs_implementation_tpu.obs.export import (  # noqa: F401
    merge_trace_files,
    read_events_jsonl,
    render_stats,
    snapshot_from_jsonl,
    to_chrome_trace,
    write_chrome_trace,
    write_events_jsonl,
    write_merged_trace,
)
from distributed_ghs_implementation_tpu.obs.pulse import (  # noqa: F401
    FleetPulse,
    pulse_report,
    write_prometheus,
)
from distributed_ghs_implementation_tpu.obs.slo import (  # noqa: F401
    ClassStats,
    current_class,
    gate_metrics,
    summarize_bus,
    summarize_jsonl,
    tagged_class,
)
