"""Subscription sessions: long-lived subscribed graphs with MST-change
notifications per committed window.

A **stream** is a digest chain rooted at one solved seed graph: every
committed window re-keys the maintained forest under the updated graph's
content digest, exactly like ``serve``'s update sessions — which is what
lets the fleet router pin a stream to a worker with the *existing*
update-session digest-chain machinery (the ``publish`` response carries
``digest``/``prev_digest`` and the router follows the rename).

The protocol is pull-based, which is what survives failover cleanly:

* ``subscribe`` — pin a stream to a seed digest (creating it, joining it,
  or *recovering* it from the durable log when this process has never seen
  it — the restarted-worker path). Returns the stream id, current head
  digest, and head sequence number.
* ``publish`` — commit one update window against the current head:
  coalesce, batched apply (``stream/window.py``), WAL append + periodic
  snapshot (``stream/log.py``), then buffer one notification. A publish
  against a stale head fails with the current head attached
  (:class:`StaleDigest`) so a client that raced a failover re-syncs
  instead of forking the chain.
* ``poll`` — drain notifications after a client-held sequence number.
  Sequence numbers are the window commit order, so "no gap, no duplicate"
  is checkable by the subscriber: after a worker death, the next worker
  replays snapshot+WAL, regenerates the same notifications (windowed
  apply is deterministic), and the subscriber's ``after_seq`` cursor
  continues exactly where it left off — without one fresh solve
  (``stream.replay.*`` counters + the scheduler's fresh-solve counter are
  the receipts the kill drill asserts on).

**Sharded streams.** With a ``ShardedLane`` attached, a stream whose
graph is oversize for the lane engine (the scheduler's ``sharded_lane``
route) keeps its head **device-resident on the mesh**: the session pins
the residency for its lifetime (the lane-LRU eviction race — pressure
from unrelated oversize traffic must not donate a streamed graph's slots
away mid-window), every committed window migrates the residency along
the digest chain through the donated padded-slot scatter
(``refresh_resident`` — the pin re-keys with it), and a window that
degrades to a full re-solve migrates FIRST (``pre_resolve``) so the mesh
solve dispatches on already-scattered slots. The durability contract
extends to residency: snapshots carry a ``sharded`` marker, and
``recover`` re-stages the snapshot state (``ensure_resident`` — a
``device_put``, never a solve) then lets each replayed window re-scatter
into the slots, so a killed-and-restarted lane worker rebuilds
device-resident state with zero fresh solves
(``stream.replay.residency_restored``). Post-window sharded heads
additionally ride the async NumPy certify engine under the standard
``verify=off|sample|full`` policy (class ``stream_sharded``).
"""

from __future__ import annotations

import collections
import contextlib
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from distributed_ghs_implementation_tpu.obs import tracing
from distributed_ghs_implementation_tpu.obs.events import BUS
from distributed_ghs_implementation_tpu.obs.slo import current_class
from distributed_ghs_implementation_tpu.stream.log import (
    ChainBreak,
    UpdateLog,
    list_streams,
)
from distributed_ghs_implementation_tpu.stream.window import WindowedMST

#: Notifications retained per stream (ring): a poller further behind than
#: this sees ``truncated`` and must re-subscribe.
_NOTIFY_CAP = 4096

#: Live stream sessions retained per process (LRU, mirrors the service's
#: ``max_sessions`` bound on update handles): an evicted stream with a
#: durable log transparently recovers on its next verb; without one the
#: client re-subscribes by digest.
_MAX_STREAMS = 32

#: Stream ids are a digest prefix — long enough to be collision-safe at
#: any realistic stream count, short enough for directory names.
_ID_LEN = 16


def _notification(seq: int, prev_digest: str, digest: str, info) -> dict:
    """The MST-change payload a subscriber polls — built here for BOTH the
    live publish and the replay loop, so a recovered ring regenerates
    byte-for-byte (the failover contract: subscribers must not see a
    different shape after a worker kill)."""
    return {
        "seq": int(seq),
        "digest": digest,
        "prev_digest": prev_digest,
        "entered": [list(t) for t in info.entered],
        "left": [list(t) for t in info.left],
        "weight_delta": info.weight_delta,
        "mode": info.mode,
        "applied": info.applied,
    }


class StaleDigest(KeyError):
    """Publish against a superseded head; carries the current head."""

    def __init__(self, stream_id: str, head: str, seq: int):
        super().__init__(stream_id)
        self.stream_id = stream_id
        self.head = head
        self.seq = seq

    def __str__(self) -> str:
        return (
            f"stale digest for stream {self.stream_id}: "
            f"head is {self.head} at seq {self.seq}"
        )


class StreamSession:
    """One live stream: the windowed session + its notification ring."""

    __slots__ = (
        "id", "mst", "head", "seq", "notifications", "lock", "log",
        "sharded",
    )

    def __init__(
        self,
        stream_id: str,
        mst: WindowedMST,
        head: str,
        seq: int = 0,
        log: Optional[UpdateLog] = None,
        sharded: bool = False,
    ):
        self.id = stream_id
        self.mst = mst
        self.head = head
        self.seq = seq
        self.notifications: "collections.deque[dict]" = collections.deque(
            maxlen=_NOTIFY_CAP
        )
        self.lock = threading.Lock()
        self.log = log
        # This stream's head lives device-resident on the mesh lane
        # (pinned for the session's life; see the manager's residency
        # maintenance). Reset to False exactly once when the pin is
        # released — the flag doubles as the unpin idempotency guard.
        self.sharded = sharded


class StreamManager:
    """All of one process's streams: create, commit, poll, recover."""

    def __init__(
        self,
        *,
        root: Optional[str] = None,
        snapshot_every: int = 8,
        backend: str = "device",
        resolve_threshold: Optional[int] = None,
        window_mode: str = "batched",
        solver=None,
        interactive_gate=None,
        max_streams: int = _MAX_STREAMS,
        lane=None,
        verifier=None,
    ):
        if snapshot_every < 1:
            raise ValueError(
                f"snapshot_every must be >= 1, got {snapshot_every}"
            )
        if max_streams < 1:
            raise ValueError(f"max_streams must be >= 1, got {max_streams}")
        self.root = root
        self.snapshot_every = snapshot_every
        self.backend = backend
        self.resolve_threshold = resolve_threshold
        self.window_mode = window_mode
        self.max_streams = max_streams
        self._solver = solver
        self._gate = interactive_gate
        # ``lane`` (parallel.lane.ShardedLane) turns oversize streams into
        # mesh-resident sessions (module docstring); ``verifier``
        # (verify.policy.ResultVerifier) audits their post-window heads.
        self._lane = lane
        self._verifier = verifier
        self._streams: "collections.OrderedDict[str, StreamSession]" = (
            collections.OrderedDict()
        )
        self._by_head: Dict[str, str] = {}  # head digest -> stream id
        self._lock = threading.Lock()

    def __len__(self) -> int:
        with self._lock:
            return len(self._streams)

    # -- construction helpers ------------------------------------------
    def _make_mst(self, result=None, state=None) -> WindowedMST:
        kwargs = dict(
            window_mode=self.window_mode,
            resolve_threshold=self.resolve_threshold,
            backend=self.backend,
            solver=self._solver,
        )
        if state is not None:
            return WindowedMST.from_state(state, **kwargs)
        return WindowedMST(result, **kwargs)

    # -- sharded residency helpers --------------------------------------
    def _lane_wants(self, graph) -> bool:
        """Is this stream's graph one the mesh lane serves — oversize for
        the lane engine, inside the lane's rank envelope? Mirrors the
        scheduler's routing rule (``BatchPolicy.route``), so stream
        residency and solve routing agree on where a graph lives."""
        if self._lane is None:
            return False
        from distributed_ghs_implementation_tpu.batch.warmup import (
            bucket_of,
            warmable_single,
        )

        if warmable_single(*bucket_of(graph.num_nodes, graph.num_edges)):
            return False
        return self._lane.admits(graph)

    def _session_state(self, session: StreamSession) -> dict:
        state = session.mst.state_arrays()
        if session.sharded:
            # The durability contract extends to residency: the snapshot
            # records that this head lives device-resident on the mesh,
            # so a restarted lane worker re-stages BEFORE replaying
            # (replayed windows then re-scatter into the slots) instead
            # of deciding from scratch mid-recovery.
            state["sharded"] = np.asarray(True)
        return state

    def _attach_lane(self, session: StreamSession) -> None:
        """Arm the resolve escape hatch for a mesh-resident stream: when a
        window degrades to a full re-solve, migrate the head's residency
        onto the resolve graph FIRST, so the injected solver's oversize
        route lands dispatch-only on already-scattered slots instead of
        cold-staging the m-sized arrays mid-publish."""
        lane = self._lane

        def pre_resolve(graph) -> None:
            if not lane.refresh_resident(session.head, graph):
                lane.ensure_resident(graph)

        session.mst._pre_resolve = pre_resolve

    def _unpin(self, session: StreamSession) -> None:
        """Release a sharded session's residency pin exactly once (drop,
        manager-LRU eviction, or losing a registration race)."""
        if session.sharded and self._lane is not None:
            self._lane.unpin(session.head)
        session.sharded = False

    def _maintain_residency(
        self, session: StreamSession, prev: str, graph
    ) -> None:
        """Post-commit mesh maintenance (inside the session lock): scatter
        the committed window's changed rank slots into the resident
        per-shard buffers (donated), re-keying the residency — and the
        session's pin — along the digest chain. A drop (padded-shape
        change) on a sharded session re-stages, so 'the stream head is
        device-resident' survives every outcome; non-sharded sessions
        keep the best-effort migration (a no-op unless the head happened
        to be resident)."""
        migrated = self._lane.refresh_resident(prev, graph)
        if migrated:
            if session.sharded:
                BUS.count("stream.lane.migrated")
        elif session.sharded:
            self._lane.ensure_resident(graph, digest=session.head)
            BUS.count("stream.lane.restaged")

    def _audit_sharded(self, session: StreamSession, result) -> None:
        """Route a post-window (or post-replay) sharded head through the
        async NumPy certify engine under the standard off|sample|full
        policy — counted in ``verify.*`` like every other audit. The
        one-shot solve path audits at response time; these heads never
        pass through it, so without this class they would be invisible
        to verification."""
        if session.sharded and self._verifier is not None:
            self._verifier.audit(result, cls="stream_sharded", key=None)

    def _register(self, session: StreamSession) -> StreamSession:
        with self._lock:
            existing = self._streams.get(session.id)
            if existing is not None:
                self._streams.move_to_end(session.id)
                return existing  # a concurrent subscribe/recover won
            self._streams[session.id] = session
            self._by_head[session.head] = session.id
            # Bounded like the service's update-session LRU: a stream's
            # arrays + notification ring must not accumulate for the life
            # of the process. The durable log (when configured) makes
            # eviction transparent — the next verb recovers it.
            while len(self._streams) > self.max_streams:
                _sid, _evicted = self._streams.popitem(last=False)
                # Sweep every digest mapping to the evicted id, not just
                # its current head: a publish racing this eviction may
                # have moved ``session.head`` (under the session lock)
                # before its ``_move_head`` got here.
                for head in [
                    h for h, s in self._by_head.items() if s == _sid
                ]:
                    del self._by_head[head]
                # An evicted sharded stream releases its residency pin:
                # the head stays resident only as long as LRU pressure
                # allows, and recovery re-stages (without solving) if it
                # was lost in between.
                self._unpin(_evicted)
                BUS.count("stream.evicted")
            return session

    def _drop(self, session: StreamSession) -> None:
        with self._lock:
            if self._streams.get(session.id) is session:
                del self._streams[session.id]
            if self._by_head.get(session.head) == session.id:
                del self._by_head[session.head]
        self._unpin(session)

    def _move_head(self, session: StreamSession, prev: str) -> None:
        with self._lock:
            if self._by_head.get(prev) == session.id:
                del self._by_head[prev]
            # Only map the new head for a session still registered: a
            # publish whose session was LRU-evicted mid-flight must not
            # re-insert a digest mapping nothing will ever clean up
            # (subscribe-by-digest would chase a dead id forever).
            if self._streams.get(session.id) is session:
                self._by_head[session.head] = session.id

    # -- the verbs ------------------------------------------------------
    def subscribe(
        self,
        *,
        digest: Optional[str] = None,
        stream: Optional[str] = None,
        result=None,
    ) -> StreamSession:
        """Create, join, or recover a stream.

        ``stream`` resumes a known stream id (recovering from the log when
        this process has never seen it). ``digest`` joins the stream whose
        head (or seed) is that digest; creating a new stream additionally
        needs ``result`` — the solved seed the caller fetched from its
        session/store. Raises ``KeyError`` when nothing matches.
        """
        if stream is not None:
            session = self._get_or_recover(stream)
            if session is None:
                raise KeyError(f"unknown stream {stream!r}")
            BUS.count("stream.subscribe")
            return session
        if digest is None:
            raise ValueError("subscribe needs a digest or a stream id")
        with self._lock:
            sid = self._by_head.get(digest)
            session = self._streams.get(sid) if sid else None
        if session is None:
            # A stream seeded from this digest may exist on disk (the
            # process restarted): its id is derived from the seed digest.
            session = self._get_or_recover(digest[:_ID_LEN])
        if session is None:
            session = self._recover_by_head(digest)
        if session is None:
            if result is None:
                raise KeyError(
                    f"no stream for digest {digest!r} (solve the graph "
                    f"first, or pass its stream id)"
                )
            session = self._create(digest, result)
        BUS.count("stream.subscribe")
        return session

    def _recover_by_head(self, digest: str) -> Optional[StreamSession]:
        """Subscribe-by-digest fallback for an EVICTED stream addressed by
        its current (mid-chain) head: log dirs are keyed by the SEED
        digest, so scan the recoverable streams for one whose durable head
        is ``digest`` and recover that. Without this, a re-subscribe after
        manager-LRU eviction would silently fork a fresh seq-0 stream —
        pollers whose cursors sit at the old sequence would never see
        another notification (nor a ``truncated`` marker)."""
        if self.root is None:
            return None
        for sid in list_streams(self.root):
            with self._lock:
                if sid in self._streams:
                    # Resident heads were already checked via _by_head: a
                    # resident stream with this durable head would have
                    # matched there, so this digest is historical for it.
                    continue
            head = UpdateLog(self.root, sid)._durable_head()
            if head is not None and head[1] == digest:
                session = self.recover(sid)
                if session is not None:
                    return session
        return None

    def _create(self, digest: str, result) -> StreamSession:
        mst = self._make_mst(result=result)
        sharded = self._lane_wants(result.graph)
        session = StreamSession(
            digest[:_ID_LEN], mst, digest, 0, None, sharded=sharded
        )
        if self.root is not None:
            session.log = UpdateLog(self.root, digest[:_ID_LEN])
            # The creation snapshot (seq 0) is what makes the stream
            # replayable from its very first window.
            session.log.snapshot(
                self._session_state(session), seq=0, digest=digest
            )
        if sharded:
            # The seed rode the mesh (the scheduler's oversize route), so
            # its slots are usually still resident — pin them for the
            # session's life: eviction pressure from unrelated traffic
            # must not donate the stream's buffers away mid-window. A
            # seed that lost residency between solve and subscribe
            # re-stages here WITHOUT solving.
            self._lane.ensure_resident(result.graph, digest=digest, pin=True)
            self._attach_lane(session)
        BUS.count("stream.created")
        registered = self._register(session)
        if registered is not session:
            self._unpin(session)  # a concurrent subscribe won the race
        return registered

    def publish(
        self,
        stream_id: str,
        digest: str,
        updates: list,
        *,
        on_commit=None,
    ) -> dict:
        """Commit one window; returns the response fields (incl. the new
        :class:`MSTResult` under ``"result"`` and the notification).

        ``on_commit(result, prev_digest, new_digest)``, when given, runs
        INSIDE the session lock after the commit point — commits on one
        stream are seq-ordered, so per-head cache/residency maintenance
        hooked here cannot interleave out of order the way doing it after
        ``publish`` returns would (a later window's eviction racing ahead
        of an earlier window's insert re-plants a dead chain ancestor)."""
        session = self._get_or_recover(stream_id)
        if session is None:
            raise KeyError(f"unknown stream {stream_id!r}")
        gate = self._gate() if self._gate is not None else contextlib.nullcontext()
        # The stream front door: a publish arriving through a traced
        # serve/fleet request joins that trace; a direct publish (tests,
        # embedded use) mints its own root.
        with session.lock, gate, tracing.front_door(current_class()):
            if digest != session.head:
                BUS.count("stream.publish.stale")
                raise StaleDigest(session.id, session.head, session.seq)
            cls = current_class()
            span_args = dict(
                stream=session.id, seq=session.seq + 1, updates=len(updates),
            )
            if cls is not None:
                span_args["cls"] = cls
            t0 = time.perf_counter()
            with BUS.span("stream.window", cat="stream", **span_args) as span:
                try:
                    result, info = session.mst.apply_window(updates)
                except Exception:
                    if session.mst.dirty:
                        # Failed mid-mutation — a forest no client has
                        # seen. Drop the session: the next verb recovers
                        # the clean pre-window state from the durable log
                        # (same discipline as serve.sessions.poisoned).
                        self._drop(session)
                        BUS.count("stream.poisoned")
                    raise
                span.set(mode=info.mode, net=info.applied)
                # Captured INSIDE the window span: the WAL rides this
                # window's span id, so a replay of the entry parents to
                # the publish that committed it (same trace, new spans).
                publish_trace = tracing.wire_context()
            new_digest = result.graph.digest()
            seq = session.seq + 1
            notification = _notification(seq, session.head, new_digest, info)
            if session.log is not None:
                # The WAL append is the commit point: nothing a poller can
                # observe (ring, head, seq) moves until the window is
                # durable, so a failed append + client retry cannot yield
                # two notifications for one sequence number. The arrays
                # already hold the window the log refused, so the session
                # is dropped alongside the error — recovery rebuilds the
                # clean pre-window state and the retry applies to it.
                try:
                    session.log.append(
                        seq=seq, prev_digest=session.head, digest=new_digest,
                        updates=[u if isinstance(u, dict) else u.__dict__
                                 for u in updates],
                        trace=publish_trace,
                    )
                except ChainBreak as e:
                    # Another process sharing this stream root (a fleet
                    # worker the router re-pinned traffic to) committed
                    # past our resident head — a fork the in-memory
                    # staleness check above cannot see. Drop the stale
                    # resident copy (the next verb replays the durable
                    # log) and bounce the client with the durable head,
                    # the same re-sync contract as any stale publish.
                    self._drop(session)
                    BUS.count("stream.publish.stale")
                    raise StaleDigest(
                        session.id,
                        e.digest if e.digest is not None else session.head,
                        e.seq if e.seq is not None else session.seq,
                    ) from e
                except Exception:
                    self._drop(session)
                    BUS.count("stream.poisoned")
                    raise
            session.notifications.append(notification)
            prev = session.head
            session.head = new_digest
            session.seq = seq
            self._move_head(session, prev)
            if self._lane is not None and prev != new_digest:
                # Mesh maintenance rides the commit point: the coalesced
                # window's changed rank slots scatter into the resident
                # per-shard buffers (donated) and residency + pin re-key
                # to the new head — seq-ordered under the session lock,
                # like every other per-head side effect here.
                self._maintain_residency(session, prev, result.graph)
            if session.log is not None and seq % self.snapshot_every == 0:
                try:
                    session.log.snapshot(
                        self._session_state(session), seq=seq,
                        digest=new_digest,
                        notifications=list(session.notifications),
                    )
                except (OSError, TimeoutError):
                    # Past the commit point a snapshot is compaction, not
                    # durability — the WAL already holds the window, so a
                    # failed write must not error a committed publish.
                    BUS.count("stream.log.snapshot_failed")
            if on_commit is not None:
                on_commit(result, prev, new_digest)
            self._audit_sharded(session, result)
            BUS.count("stream.window.committed")
            BUS.count("stream.notify")
            return {
                "stream": session.id,
                "digest": new_digest,
                "prev_digest": prev,
                "seq": seq,
                "mode": info.mode,
                "applied": info.applied,
                "coalesced_from": info.coalesced_from,
                "notification": notification,
                "result": result,
                "wall_s": time.perf_counter() - t0,
            }

    def poll(self, stream_id: str, after_seq: int = 0) -> dict:
        """Notifications with ``seq > after_seq`` (+ the current head)."""
        session = self._get_or_recover(stream_id)
        if session is None:
            raise KeyError(f"unknown stream {stream_id!r}")
        with session.lock:
            notes = [
                n for n in session.notifications if n["seq"] > after_seq
            ]
            earliest = (
                session.notifications[0]["seq"]
                if session.notifications else session.seq + 1
            )
            out = {
                "stream": session.id,
                "digest": session.head,
                "seq": session.seq,
                "notifications": notes,
            }
            # The ring dropped windows the poller still needs: it must
            # re-subscribe (or re-solve) rather than silently skip.
            if after_seq + 1 < earliest and after_seq < session.seq:
                out["truncated"] = earliest
            BUS.count("stream.poll")
            return out

    # -- recovery --------------------------------------------------------
    def _get_or_recover(self, stream_id: str) -> Optional[StreamSession]:
        with self._lock:
            session = self._streams.get(stream_id)
            if session is not None:
                self._streams.move_to_end(stream_id)
                return session
        return self.recover(stream_id)

    def recover(self, stream_id: str) -> Optional[StreamSession]:
        """Rebuild a stream from its durable log: snapshot + WAL replay.

        Every replayed window goes through the same batched apply as a
        live publish — deterministic, so the digests must re-derive
        exactly (a divergence stops replay at the last agreeing window,
        ``stream.replay.diverged``) and the notification ring regenerates
        byte-for-byte. No step touches the solver.
        """
        if self.root is None:
            return None
        log = UpdateLog(self.root, stream_id)
        state, entries, _notes = log.load()
        if state is None:
            return None
        with BUS.span(
            "stream.replay", cat="stream", stream=stream_id,
            windows=len(entries),
        ) as span:
            mst = self._make_mst(state=state)
            head = mst.result().graph.digest()
            if head != state["digest"]:
                # The arrays are the truth; a stored-digest mismatch means
                # the snapshot generation predates a weight-dtype change
                # or was tampered with — surface it, then trust the arrays.
                BUS.count("stream.replay.digest_mismatch")
            session = StreamSession(
                stream_id, mst, head, state["seq"], log
            )
            session.sharded = self._lane_wants(mst.result().graph)
            if state.get("sharded") and not session.sharded:
                # The snapshot says this head lived mesh-resident but this
                # process cannot re-stage it (no lane, or the graph left
                # the lane's envelope) — replay still rebuilds the forest;
                # only the residency contract degrades, visibly.
                BUS.count("stream.replay.residency_unavailable")
            if session.sharded:
                # Re-stage the snapshot state (a device_put, never a
                # solve), pinned; each replayed window below then
                # re-scatters into the slots through the same donated
                # path a live publish uses, so residency — and the pin —
                # re-key along the replayed chain.
                self._lane.ensure_resident(
                    mst.result().graph, digest=head, pin=True
                )
                self._attach_lane(session)
            # Ring continuity across the snapshot point: the persisted
            # notifications preload, replayed windows append after them.
            for note in state.get("notifications", []):
                session.notifications.append(note)
            replayed = 0
            diverged = False
            # WAL entries chain from the snapshot's STORED digest (that is
            # what log.load() validated) — chaining on the recomputed head
            # would silently drop every post-snapshot window whenever the
            # digest_mismatch path above fired.
            chain = state["digest"]
            for entry in entries:
                if entry["prev"] != chain:
                    BUS.count("stream.replay.diverged")
                    diverged = True
                    break
                # Replay continues the ORIGINAL publish's trace (the WAL
                # entry journaled its wire context): the re-applied
                # window is a fresh child span under the publish that
                # committed it — same trace_id, across processes and
                # restarts.
                with tracing.activated(
                    tracing.from_wire(entry.get("trace"))
                ), BUS.span(
                    "stream.replay.window", cat="stream",
                    stream=stream_id, seq=entry["seq"],
                ):
                    result, info = mst.apply_window(entry["updates"])
                new_digest = result.graph.digest()
                if new_digest != entry["digest"]:
                    BUS.count("stream.replay.diverged")
                    diverged = True
                    break
                session.notifications.append(
                    _notification(entry["seq"], entry["prev"], new_digest, info)
                )
                prev_head = session.head
                chain = session.head = new_digest
                session.seq = entry["seq"]
                replayed += 1
                if session.sharded:
                    # Replayed windows re-scatter into the re-staged
                    # slots — the donated update path, not a solve — and
                    # the residency digest re-keys along the chain
                    # exactly as the live publishes did.
                    self._maintain_residency(session, prev_head, result.graph)
            # Round 19: verify the REBUILT head against the journaled
            # expectation. On a clean replay the two agree by construction
            # (every applied window's recomputed digest was checked); a
            # disagreement means the arrays were evolved through state we
            # cannot vouch for — corrupt snapshot arrays, a mangled WAL
            # update that still parsed, or a divergence that left the
            # arrays one window past the last verified head. Replay alone
            # would serve that forest with full confidence; instead fall
            # back to ONE fresh solve of the rebuilt graph, so the served
            # forest is re-derived from the edges actually recovered
            # (``stream.replay.fresh_solve`` — the zero-fresh-solve
            # failover contract is scoped to clean replays, and this is
            # not one).
            rebuilt = mst.result().graph.digest()
            # A fully-verified replay CURES a seed-digest mismatch: when
            # every WAL window re-derived its journaled digest from the
            # arrays, the final state is journal-verified even though the
            # seed was not (the legacy weight-dtype-change case).
            seed_uncured = (
                head != state["digest"] and not (replayed and not diverged)
            )
            if self._solver is not None and (
                diverged or seed_uncured or rebuilt != session.head
            ):
                BUS.count("stream.replay.fresh_solve")
                fresh = self._solver(mst.result().graph)
                session.mst = self._make_mst(result=fresh)
                prev_head = session.head
                session.head = fresh.graph.digest()
                if session.sharded:
                    # The re-derived head supersedes the replayed one:
                    # carry the pin over and make sure the served head is
                    # the resident one (the solver's oversize route
                    # usually staged it already).
                    self._lane.move_pins(prev_head, session.head)
                    self._lane.ensure_resident(
                        fresh.graph, digest=session.head
                    )
                    self._attach_lane(session)
            if session.sharded:
                BUS.count("stream.replay.residency_restored")
                self._audit_sharded(session, session.mst.result())
            span.set(replayed=replayed, head_seq=session.seq)
            BUS.count("stream.replay.streams")
            if replayed:
                BUS.count("stream.replay.windows", replayed)
            registered = self._register(session)
            if registered is not session:
                self._unpin(session)  # a concurrent recover won the race
            return registered

    # -- introspection ---------------------------------------------------
    def heads(self) -> Dict[str, str]:
        with self._lock:
            return {s.id: s.head for s in self._streams.values()}

    def stats(self) -> dict:
        with self._lock:
            out = {
                "streams": len(self._streams),
                "sharded": sum(
                    1 for s in self._streams.values() if s.sharded
                ),
                "root": self.root,
                "snapshot_every": self.snapshot_every,
                "heads": {
                    s.id: {"seq": s.seq, "digest": s.head}
                    for s in self._streams.values()
                },
            }
        if self.root is not None:
            # Durable streams outnumber live ones (LRU eviction, worker
            # restarts): report what is recoverable from disk, not just
            # what is resident.
            out["recoverable"] = list_streams(self.root)
        return out


def poll_gap_check(seen: List[int], head_seq: int, start_seq: int = 0) -> dict:
    """Subscriber-side integrity: ``seen`` window sequences vs the head.

    Returns ``{"gaps": N, "dups": N}`` — both must be zero for the
    no-lost-no-duplicated-notification contract (drills assert exactly
    this after a worker kill). ``start_seq`` is the sequence the
    subscriber JOINED at (the ``seq`` its subscribe response carried):
    a mid-chain joiner only owes the windows after it, so pre-join
    sequences are not gaps.
    """
    counts = collections.Counter(seen)
    dups = sum(c - 1 for c in counts.values())
    gaps = sum(
        1 for s in range(start_seq + 1, head_seq + 1) if s not in counts
    )
    return {"gaps": gaps, "dups": dups}
