"""Incremental MST maintenance (serve/dynamic.py) vs the networkx oracle:
randomized insert/delete/reweight streams with EVERY intermediate forest
checked — weight parity against networkx, exact edge-set parity against a
fresh solve (the (w, u, v) order makes the MSF unique). Long streams are
``slow``; tier-1 keeps the 100-node ones."""

import numpy as np
import pytest

from distributed_ghs_implementation_tpu.api import minimum_spanning_forest
from distributed_ghs_implementation_tpu.graphs.edgelist import Graph
from distributed_ghs_implementation_tpu.models.boruvka import solve_graph
from distributed_ghs_implementation_tpu.serve.dynamic import DynamicMST, Update


def _random_graph(rng, n, m, wmax=50):
    return Graph.from_arrays(
        n,
        rng.integers(0, n, m),
        rng.integers(0, n, m),
        rng.integers(1, wmax + 1, m),
    )


def _nx_msf_weight(graph: Graph) -> float:
    import networkx as nx

    return nx.minimum_spanning_tree(graph.to_networkx()).size(weight="weight")


def _random_update(rng, dyn: DynamicMST, n: int, wmax=50) -> Update:
    kind = str(rng.choice(["insert", "delete", "reweight"]))
    if kind in ("delete", "reweight") and dyn._u.size:
        i = int(rng.integers(0, dyn._u.size))
        a, b = int(dyn._u[i]), int(dyn._v[i])
        if kind == "delete":
            return Update("delete", a, b)
        return Update("reweight", a, b, int(rng.integers(1, wmax + 1)))
    a, b = (int(x) for x in rng.integers(0, n, 2))
    while a == b:
        a, b = (int(x) for x in rng.integers(0, n, 2))
    return Update("insert", a, b, int(rng.integers(1, wmax + 1)))


def _check_exact(dyn_result, context=""):
    """The maintained forest must be byte-identical to a fresh solve."""
    ids_ref, frag_ref, _ = solve_graph(dyn_result.graph)
    assert np.array_equal(np.sort(dyn_result.edge_ids), np.sort(ids_ref)), context
    assert dyn_result.num_components == int(np.unique(frag_ref).size), context


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_randomized_stream_100_nodes(seed):
    rng = np.random.default_rng(100 + seed)
    n = 100
    g = _random_graph(rng, n, 300)
    dyn = DynamicMST(minimum_spanning_forest(g), resolve_threshold=10**9)
    for step in range(30):
        upd = _random_update(rng, dyn, n)
        result = dyn.apply([upd])
        assert dyn.last_mode == "incremental"
        assert abs(
            float(result.total_weight) - _nx_msf_weight(result.graph)
        ) < 1e-9, (seed, step, upd)
        if step % 10 == 0:  # exact parity is the expensive check — sample it
            _check_exact(result, (seed, step, upd))
    _check_exact(dyn.result(), (seed, "final"))


@pytest.mark.slow
@pytest.mark.parametrize("seed", [0, 1])
def test_randomized_stream_1k_nodes_long(seed):
    rng = np.random.default_rng(1000 + seed)
    n = 1000
    g = _random_graph(rng, n, 4000, wmax=200)
    dyn = DynamicMST(minimum_spanning_forest(g), resolve_threshold=10**9)
    for step in range(120):
        upd = _random_update(rng, dyn, n, wmax=200)
        result = dyn.apply([upd])
        assert dyn.last_mode == "incremental"
        assert abs(
            float(result.total_weight) - _nx_msf_weight(result.graph)
        ) < 1e-9, (seed, step, upd)
    _check_exact(dyn.result(), (seed, "final"))


def test_mixed_batches_and_duplicate_edges():
    rng = np.random.default_rng(7)
    n = 120
    g = _random_graph(rng, n, 400)
    dyn = DynamicMST(minimum_spanning_forest(g), resolve_threshold=10**9)
    for _ in range(6):
        batch = [_random_update(rng, dyn, n) for _ in range(8)]
        result = dyn.apply(batch)
        assert abs(
            float(result.total_weight) - _nx_msf_weight(result.graph)
        ) < 1e-9
    _check_exact(dyn.result())


def test_insert_joins_components_delete_splits():
    # Two disjoint triangles.
    g = Graph.from_edges(6, [
        (0, 1, 1), (1, 2, 2), (0, 2, 3),
        (3, 4, 1), (4, 5, 2), (3, 5, 3),
    ])
    dyn = DynamicMST(minimum_spanning_forest(g))
    assert dyn.num_components == 2
    r = dyn.apply([Update("insert", 2, 3, 10)])
    assert dyn.last_mode == "incremental"
    assert r.num_components == 1
    assert r.total_weight == 1 + 2 + 1 + 2 + 10
    # Deleting the bridge splits again — no replacement exists.
    r = dyn.apply([Update("delete", 2, 3)])
    assert r.num_components == 2
    assert r.total_weight == 6
    _check_exact(r)


def test_delete_tree_edge_picks_minimum_replacement():
    # A 4-cycle with a chord: deleting a tree edge must pull in the cheapest
    # crossing edge, not just any.
    g = Graph.from_edges(4, [
        (0, 1, 1), (1, 2, 2), (2, 3, 1), (0, 3, 10), (1, 3, 5),
    ])
    dyn = DynamicMST(minimum_spanning_forest(g))
    r = dyn.apply([Update("delete", 1, 2)])
    assert dyn.last_mode == "incremental"
    assert r.total_weight == 1 + 1 + 5  # (1,3) chosen over (0,3)
    _check_exact(r)


def test_reweight_directions():
    g = Graph.from_edges(4, [(0, 1, 1), (1, 2, 2), (2, 3, 3), (0, 3, 9)])
    dyn = DynamicMST(minimum_spanning_forest(g))
    # Up-weighting a tree edge past the non-tree alternative swaps them.
    r = dyn.apply([Update("reweight", 2, 3, 20)])
    assert r.total_weight == 1 + 2 + 9
    # Down-weighting a (now) non-tree edge swaps back.
    r = dyn.apply([Update("reweight", 2, 3, 3)])
    assert r.total_weight == 1 + 2 + 3
    # No-op directions change nothing.
    r = dyn.apply([
        Update("reweight", 0, 1, 1),   # tree edge, same weight
        Update("reweight", 0, 3, 11),  # non-tree edge heavier
    ])
    assert r.total_weight == 1 + 2 + 3
    _check_exact(r)


def test_insert_existing_edge_is_reweight_and_delete_missing_is_noop():
    g = Graph.from_edges(3, [(0, 1, 5), (1, 2, 6), (0, 2, 7)])
    dyn = DynamicMST(minimum_spanning_forest(g))
    r = dyn.apply([Update("insert", 0, 2, 1)])  # exists: reweight to 1
    assert r.total_weight == 1 + 5
    r = dyn.apply([Update("delete", 0, 1)])
    before = r.total_weight
    r = dyn.apply([Update("delete", 0, 1)])  # now absent: no-op
    assert r.total_weight == before
    _check_exact(r)


def test_float_weight_promotes_dtype():
    g = Graph.from_edges(3, [(0, 1, 5), (1, 2, 6), (0, 2, 7)])
    dyn = DynamicMST(minimum_spanning_forest(g))
    r = dyn.apply([Update("insert", 0, 2, 5.5)])
    assert r.graph.w.dtype.kind == "f"
    assert abs(float(r.total_weight) - _nx_msf_weight(r.graph)) < 1e-9


def test_oversized_batch_falls_back_to_supervised_resolve():
    from distributed_ghs_implementation_tpu.obs.events import BUS

    BUS.enable()
    BUS.clear()
    rng = np.random.default_rng(11)
    n = 80
    g = _random_graph(rng, n, 240)
    dyn = DynamicMST(minimum_spanning_forest(g), resolve_threshold=4)
    batch = [_random_update(rng, dyn, n) for _ in range(12)]
    result = dyn.apply(batch)
    assert dyn.last_mode == "resolve"
    assert result.backend == "serve/resolve"
    assert BUS.counters()["serve.dynamic.resolve"] == 1
    assert BUS.counters().get("serve.dynamic.incremental", 0) == 0
    assert abs(float(result.total_weight) - _nx_msf_weight(result.graph)) < 1e-9
    _check_exact(result)
    BUS.clear()


def test_verification_failure_triggers_resolve(monkeypatch):
    from distributed_ghs_implementation_tpu.obs.events import BUS

    BUS.enable()
    BUS.clear()
    g = Graph.from_edges(4, [(0, 1, 1), (1, 2, 2), (2, 3, 3), (0, 3, 9)])
    dyn = DynamicMST(minimum_spanning_forest(g), resolve_threshold=10**9)
    monkeypatch.setattr(dyn, "_forest_ok", lambda: False)
    result = dyn.apply([Update("reweight", 0, 1, 2)])
    assert dyn.last_mode == "resolve"
    assert BUS.counters()["serve.dynamic.verify_failed"] == 1
    assert result.total_weight == 2 + 2 + 3
    BUS.clear()


def test_forest_check_rejects_cyclic_and_nonmaximal_masks():
    g = Graph.from_edges(4, [(0, 1, 1), (1, 2, 2), (0, 2, 3), (2, 3, 4)])
    dyn = DynamicMST(minimum_spanning_forest(g))
    assert dyn._forest_ok()
    # Cycle on {0,1,2} leaving node 3 unspanned: same edge count as a
    # spanning tree (t == n - k_graph), so only the tree-subgraph component
    # check catches it.
    dyn._in_tree = np.array([True, True, True, False])
    assert not dyn._forest_ok()
    # Non-maximal: too few edges for the graph's connectivity.
    dyn._in_tree = np.array([True, True, False, False])
    assert not dyn._forest_ok()


def test_validation_rejects_bad_updates():
    g = Graph.from_edges(3, [(0, 1, 5), (1, 2, 6)])
    dyn = DynamicMST(minimum_spanning_forest(g))
    with pytest.raises(ValueError, match="unknown update kind"):
        dyn.apply([Update("frobnicate", 0, 1, 2)])
    with pytest.raises(ValueError, match="out of range"):
        dyn.apply([Update("insert", 0, 99, 2)])
    with pytest.raises(ValueError, match="self-loop"):
        dyn.apply([Update("insert", 1, 1, 2)])
    with pytest.raises(ValueError, match="requires a weight"):
        dyn.apply([Update("insert", 0, 2)])
    with pytest.raises(ValueError, match="non-numeric weight"):
        dyn.apply([Update("insert", 0, 2, "abc")])
    with pytest.raises(ValueError, match="non-finite weight"):
        dyn.apply([Update("insert", 0, 2, float("nan"))])
    with pytest.raises(ValueError, match="non-finite weight"):
        dyn.apply([Update("reweight", 0, 1, float("inf"))])
    # Validation failures happen before any mutation: not dirty, still usable.
    assert not dyn.dirty
    r = dyn.apply([Update("insert", 0, 2, 4)])
    assert r.total_weight == 5 + 4
