"""Correctness oracles: NetworkX (exact, small), native Kruskal (fast,
large), SciPy (fallback).

The reference's gate is NetworkX MST comparison
(``/root/reference/ghs_implementation.py:746-756``, ``check_mst.py:9``).
We keep it — weight parity everywhere, exact edge sets only where the MST is
unique — and add two large-scale oracles: a native Kruskal pass over the
precomputed rank order (r5; measured 6.6 s at RMAT-22 vs csgraph's
~80 s — fast enough to live-verify every bench run) with
``scipy.sparse.csgraph.minimum_spanning_tree`` as the float-weight /
no-toolchain fallback. Because MST *weight* is unique even when edge sets
are not, weight parity is the sound cross-implementation check (the
insight the reference half-applies at ``ghs_implementation.py:753-756``).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

from distributed_ghs_implementation_tpu.graphs.edgelist import Graph


def networkx_mst_weight(graph: Graph) -> float:
    """Total minimum-spanning-forest weight per NetworkX (the reference oracle)."""
    import networkx as nx

    g = graph.to_networkx()
    mst = nx.minimum_spanning_tree(g)
    return sum(d["weight"] for _, _, d in mst.edges(data=True))


def networkx_mst_edges(graph: Graph) -> set:
    """Normalized NetworkX MST edge set — only meaningful when the MST is unique."""
    import networkx as nx

    mst = nx.minimum_spanning_tree(graph.to_networkx())
    return {(min(a, b), max(a, b)) for a, b in mst.edges()}


def native_mst_weight(graph: Graph) -> Optional[float]:
    """MSF weight via one native Kruskal pass over the precomputed
    (weight, edge id) rank order — the fastest oracle at scale (measured
    6.6 s at 64M edges vs SciPy csgraph's ~80 s; scales ~linearly, so
    ~27 s at RMAT-24's 260M vs csgraph's 890 s). Exact for integer
    weights, and the pass VALIDATES the order it is handed (the solver
    shares it — see ``kruskal_msf_native``). Returns ``None`` when
    unavailable (no toolchain, float weights) and falls back to SciPy on
    a corrupt order — callers fall back to SciPy either way."""
    if not graph.is_integer_weighted or graph.num_edges == 0:
        return None
    try:
        from distributed_ghs_implementation_tpu.graphs import native

        if not native.native_available():
            return None
        total, _count = native.kruskal_msf_native(
            graph.num_nodes, graph._rank_order, graph.u, graph.v, graph.w
        )
        return float(total)
    except Exception:  # noqa: BLE001 — any native issue -> fallback
        return None


def scipy_mst_weight(graph: Graph) -> float:
    """MSF weight via ``scipy.sparse.csgraph`` — C-speed oracle for big graphs.

    ``csgraph`` treats zero matrix entries as absent edges and ``coo_matrix``
    sums duplicate coordinates, so edges are deduped (min weight) and shifted
    positive first; the shift is subtracted back out per forest edge (a uniform
    shift never changes which edges form the MSF).
    """
    from scipy.sparse import coo_matrix
    from scipy.sparse.csgraph import minimum_spanning_tree as sp_mst

    n = graph.num_nodes
    u, v, w = graph.u, graph.v, graph.w.astype(np.float64)
    if u.size:
        # Dedup (u, v) keeping min weight — Graph normally guarantees this,
        # but dedup=False constructions can reach here.
        order = np.lexsort((w, v, u))
        u, v, w = u[order], v[order], w[order]
        first = np.ones(u.size, dtype=bool)
        first[1:] = (u[1:] != u[:-1]) | (v[1:] != v[:-1])
        u, v, w = u[first], v[first], w[first]
    shift = 1.0 - min(0.0, float(w.min()) if w.size else 0.0)
    m = coo_matrix((w + shift, (u, v)), shape=(n, n))
    t = sp_mst(m)
    return float(t.sum() - shift * t.nnz)


@dataclasses.dataclass
class Verification:
    ok: bool
    expected_weight: float
    actual_weight: float
    expected_edges: int
    actual_edges: int
    oracle: str

    def __bool__(self) -> bool:
        return self.ok


def verify_result(
    result,
    *,
    oracle: str = "auto",
    atol: float = 1e-6,
    expected_weight: float | None = None,
) -> Verification:
    """Check an :class:`~distributed_ghs_implementation_tpu.api.MSTResult`.

    Checks (a) weight parity with the oracle, (b) edge count ``n - c`` for
    ``c`` components — together these imply an exact minimum spanning forest.
    ``oracle="auto"`` uses NetworkX below 200k edges and the native Kruskal
    pass above (SciPy when native is unavailable or weights are float).

    ``expected_weight`` short-circuits the oracle computation with a
    previously recorded oracle weight (``oracle`` is reported as
    ``"recorded"``) — the SciPy oracle at RMAT-24+ costs 15+ minutes, and
    the weights are deterministic per (generator, scale, seed), so a
    recorded weight is the same check at zero cost. Recorded weights live
    in ``docs/BASELINE_RUNS.jsonl``.
    """
    graph: Graph = result.graph
    if expected_weight is not None:
        expected = float(expected_weight)
        oracle = "recorded"
    else:
        if oracle == "auto":
            oracle = "networkx" if graph.num_edges <= 200_000 else "native"
        if oracle == "native":
            expected = native_mst_weight(graph)
            if expected is None:  # no toolchain / float weights
                oracle = "scipy"
        if oracle == "networkx":
            expected = networkx_mst_weight(graph)
        elif oracle == "scipy":
            expected = scipy_mst_weight(graph)
        elif oracle != "native":
            raise ValueError(f"unknown oracle {oracle!r}")
    actual = result.total_weight
    expected_edges = graph.num_nodes - result.num_components
    ok = abs(float(expected) - float(actual)) <= atol and result.num_edges == expected_edges
    return Verification(
        ok=ok,
        expected_weight=float(expected),
        actual_weight=float(actual),
        expected_edges=expected_edges,
        actual_edges=result.num_edges,
        oracle=oracle,
    )
