"""High-rate graph mutation: windowed batched maintenance, a durable
update log with snapshot+replay recovery, and MST-change subscription
sessions (docs/STREAMING.md).

Three pillars, one per module:

* :mod:`stream.window` — coalesce an update window (last-write-wins per
  edge) and apply the whole window in two batched passes built on the
  solver's own ``fragment_moe`` / ``hook_and_compress`` primitives,
  instead of ``serve/dynamic.py``'s one-exchange-rule-per-update walk.
* :mod:`stream.log` — persist every committed window through the
  checkpoint layer (snapshot every K windows + JSONL delta log with
  torn-tail skip and ``.bak`` generation fallback), so a restarted worker
  replays to the current digest without a single fresh solve.
* :mod:`stream.session` — long-lived subscribed graphs: a digest-chained
  stream per seed graph, MST-change notifications (edges entered/left the
  forest, weight delta) per committed window, pull-based ``poll`` with
  gapless/duplicate-free sequence numbers that survive worker failover
  via log replay.
"""

from distributed_ghs_implementation_tpu.stream.log import UpdateLog
from distributed_ghs_implementation_tpu.stream.session import (
    StreamManager,
    StreamSession,
)
from distributed_ghs_implementation_tpu.stream.window import (
    WindowedMST,
    coalesce,
    random_update_stream,
)

__all__ = [
    "UpdateLog",
    "StreamManager",
    "StreamSession",
    "WindowedMST",
    "coalesce",
    "random_update_stream",
]
