"""CLI surface: generate / run / verify / experiments round-trips."""

import json
import os


from distributed_ghs_implementation_tpu.cli import main


def test_generate_run_verify_roundtrip(tmp_path):
    gdir = str(tmp_path / "g")
    assert main(["generate", "--nodes", "9", "--edge-prob", "0.5",
                 "--seed", "3", "--output-dir", gdir]) == 0
    assert os.path.exists(os.path.join(gdir, "graph_metadata.json"))
    assert os.path.exists(os.path.join(gdir, "node_0.json"))

    out = str(tmp_path / "res.json")
    assert main(["run", "--graph-dir", gdir, "--output", out, "--verify"]) == 0
    with open(out) as f:
        res = json.load(f)
    assert res["num_edges_in_mst"] == res["num_nodes"] - 1
    assert res["num_components"] == 1

    assert main(["verify", "--graph-dir", gdir, "--result", out]) == 0


def test_generate_npz_and_run(tmp_path):
    gdir = str(tmp_path)
    assert main(["generate", "--kind", "gnm", "--nodes", "128", "--edges", "512",
                 "--seed", "1", "--output-dir", gdir, "--npz"]) == 0
    npz = os.path.join(gdir, "graph.npz")
    assert os.path.exists(npz)
    assert main(["run", "--graph-dir", npz, "--verify"]) == 0


def test_run_all_backends_agree(tmp_path):
    gdir = str(tmp_path / "g")
    main(["generate", "--nodes", "12", "--edge-prob", "0.4",
          "--seed", "8", "--output-dir", gdir])
    weights = {}
    for backend in ["device", "sharded", "protocol"]:
        out = str(tmp_path / f"{backend}.json")
        assert main(["run", "--graph-dir", gdir, "--backend", backend,
                     "--output", out, "--verify"]) == 0
        with open(out) as f:
            weights[backend] = json.load(f)["total_weight"]
    assert len(set(weights.values())) == 1


def test_simple_test_fixture_generation(tmp_path):
    """create_simple_test.py parity (C14)."""
    gdir = str(tmp_path / "t")
    assert main(["generate", "--kind", "simple-test", "--output-dir", gdir]) == 0
    out = str(tmp_path / "res.json")
    assert main(["run", "--graph-dir", gdir, "--output", out]) == 0
    with open(out) as f:
        assert json.load(f)["total_weight"] == 3


def test_experiments_suite(tmp_path):
    out = str(tmp_path / "exp.json")
    assert main(["experiments", "--output", out]) == 0
    with open(out) as f:
        records = json.load(f)
    assert len(records) == 6
    assert all(r["is_correct"] for r in records)
    # The reference's own problem config (20 nodes, seed 500) must pass.
    r6 = records[-1]
    assert r6["num_nodes"] == 20 and r6["is_correct"]


def test_visualization(tmp_path):
    gdir = str(tmp_path / "g")
    main(["generate", "--nodes", "7", "--edge-prob", "0.6",
          "--seed", "2", "--output-dir", gdir, "--visualize"])
    assert os.path.exists(os.path.join(gdir, "input_graph.png"))
    out = str(tmp_path / "res.json")
    main(["run", "--graph-dir", gdir, "--output", out, "--visualize"])
    assert os.path.exists(str(tmp_path / "res.png"))
