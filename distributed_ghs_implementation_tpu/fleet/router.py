"""Fleet front-end: digest-routed dispatch over N worker processes.

The router is the serving tier's availability layer. It owns no solver —
every query is forwarded over a framed channel (``fleet/transport.py``:
subprocess pipes on one host, TCP sockets across hosts) to one of N worker
processes (``fleet/worker.py``), each a full single-process serving stack.
What the router adds is exactly what one process cannot have:

* **Cache-affine routing** — ``Graph.digest()`` consistent-hashes onto the
  ring (``fleet/hashing.py``), so repeats of a graph land on the worker
  whose result cache, update sessions, and compiled buckets are already
  warm, and worker death moves only the dead worker's keyspace share.
  Updates re-key content-addressed, so the router pins each *session
  digest* to the worker holding the materialized session and follows the
  chain as responses rename it.
* **Cache-miss forwarding** — across hosts there is no shared disk store,
  so whenever routing must deviate from the worker that last served a
  digest (lane steering, failover, ring rejoin), the router first asks the
  digest's owner-of-record with a tiny ``cached_only`` probe and only
  lets the dispatch target solve locally on a miss (``fleet.forward.hit``
  / ``fleet.forward.miss``) — consistent-hash affinity keeps paying off
  even where ``disk_dir`` cannot follow.
* **Admission control** — per-worker bounded in-flight queues
  (``queue_depth``). A full queue sheds requests whose ``slo_class`` is in
  ``shed_classes`` (``{"ok": false, "shed": true}``, counted
  ``fleet.shed``); every other class blocks — backpressure, not loss.
* **Health-checked failover** — a heartbeat thread pings every worker; a
  worker silent past its **lease** (``lease_s``, default
  ``heartbeat_interval_s * heartbeat_miss_threshold``), or whose channel
  reaches EOF (pipe closed, TCP connection lost), is declared dead. Its
  accepted-but-unanswered requests are **re-queued** onto surviving
  workers by the same digest key (``fleet.requeue``) — idempotent, because
  results are content-addressed and every worker computes the identical
  forest. The dead worker restarts (spawned) or is re-dialed (remote) with
  capped exponential backoff and rejoins the ring when it says hello.
* **Graceful drain** — :meth:`FleetRouter.shutdown` stops admitting, sends
  every worker a drain frame, and waits for in-flight responses to flush
  before the processes exit 0.
* **Elastic pool primitives** — :meth:`FleetRouter.add_worker` grows the
  pool by one *warm* worker (spawned with the fleet's warmup flags; it
  enters the hash ring only after a hello whose ``warmed`` capability is
  confirmed — a cold worker can never be routed interactive traffic;
  ``fleet.scale.up``, ``fleet.join.warm_s``) and
  :meth:`FleetRouter.retire_worker` shrinks it by one (victim = lowest
  forwarding-affinity worker unless pinned; removed from the ring first,
  in-flight accepted work drains, pinned update/stream sessions migrate to
  their ring inheritors — who replay from the shared disk store / stream
  WAL exactly like failover — then a drain frame and exit 0;
  ``fleet.scale.down``). A worker in graceful drain is exempt from lease
  expiry: the heartbeat loop skips draining workers entirely, so a slow
  drain can never be mistaken for a death and re-queued mid-flush.
  ``fleet/autoscaler.py`` drives both primitives from the obs bus.

Telemetry (router-process bus): ``fleet.request`` spans carry ``cls`` /
``worker`` / ``ok`` — ``obs.slo`` joins them into per-class AND per-worker
SLO breakdowns — plus ``fleet.dispatch`` / ``fleet.requeue`` /
``fleet.shed`` / ``fleet.worker.dead`` / ``fleet.worker.restart`` /
``fleet.heartbeat.miss`` / ``fleet.lease.expired`` /
``fleet.forward.hit`` / ``fleet.forward.miss`` counters and the
``fleet.hop_s[.<cls>]`` histograms (send-to-response minus in-worker
service time — the transport + queueing overhead a ``--transport`` choice
actually changes). See ``docs/FLEET.md``.
"""

from __future__ import annotations

import collections
import dataclasses
import hashlib
import os
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional, Tuple

from distributed_ghs_implementation_tpu.fleet.framing import (
    SECTIONS_KEY,
    fold_sections,
)
from distributed_ghs_implementation_tpu.fleet.hashing import HashRing
from distributed_ghs_implementation_tpu.fleet.transport import (
    ChaosState,
    ChaosTransport,
    HelloError,
    PipeTransport,
    Transport,
    WorkerListener,
    check_hello,
    connect_to_worker,
    new_conn_token,
)
from distributed_ghs_implementation_tpu.obs import tracing
from distributed_ghs_implementation_tpu.obs.events import BUS
from distributed_ghs_implementation_tpu.obs.slo import sanitize_class

_SESSION_MAP_CAP = 4096  # digest -> worker pins retained (LRU)
_FORWARD_MAP_CAP = 4096  # digest -> last-serving worker (LRU)
# The forwarding probe is an OPTIMIZATION riding ahead of a correct local
# solve: on a busy owner it must give up fast (miss and move on), never
# queue behind slow solves for the full control-plane timeout.
_FORWARD_PROBE_TIMEOUT_S = 2.0
_FORWARD_PROBE_SLOT_TIMEOUT_S = 0.25


@dataclasses.dataclass(frozen=True)
class FleetConfig:
    """Fleet topology + failover policy (defaults suit tests/drills; the
    ``serve --fleet`` CLI maps its flags onto this)."""

    workers: int = 2
    backend: str = "device"
    batch_lanes: int = 0
    batch_wait_s: Optional[float] = None
    store_capacity: int = 128
    disk_dir: Optional[str] = None  # SHARED persistent store (flock'd writes)
    max_concurrent: int = 2
    max_sessions: int = 32
    resolve_threshold: Optional[int] = None
    worker_threads: int = 4
    warmup_buckets: Optional[str] = None
    warmup_replay: Optional[str] = None
    compile_cache_dir: Optional[str] = None
    no_compile_cache: bool = False
    tune_record: Optional[str] = None  # ghs-tuning-v1 record (all workers)
    queue_depth: int = 64
    shed_classes: Tuple[str, ...] = ()
    # Oversize routing: the first K worker slots own a mesh-sharded solve
    # lane (spawned with --sharded-lane; -1 = every worker). Oversize
    # solves consistent-hash onto the LANE ring so they land on a
    # mesh-owning worker; 0 leaves oversize on the normal ring (bypass).
    sharded_lane_workers: int = 0
    warmup_mesh_buckets: Optional[str] = None  # passed to lane workers
    warmup_stream_buckets: Optional[str] = None  # window-kernel warm (all)
    # Durable stream layer: a SHARED directory (like disk_dir) holding
    # every stream's snapshot + update log, so whichever worker inherits a
    # stream's keyspace after a death replays it instead of re-solving
    # (stream/log.py, docs/STREAMING.md).
    stream_dir: Optional[str] = None
    stream_snapshot_every: int = 8
    # -- transport (round 16, docs/FLEET.md "Network transport") -------
    # "pipe": subprocess stdin/stdout (single host). "tcp": length-prefixed
    # frames over sockets with coalesced pipelined writes — spawned workers
    # dial into the router's listener with a tokened hello; with
    # remote_workers set, the router instead dials each listed
    # host:port (externally started `fleet.worker --listen` processes,
    # possibly on other machines / pod slices).
    transport: str = "pipe"
    listen_host: str = "127.0.0.1"
    remote_workers: Tuple[str, ...] = ()
    # Cross-host cache-miss forwarding: before a solve lands on a worker
    # that is NOT the digest's owner-of-record, probe the owner with a
    # cached_only frame and return its cached result on a hit. None = auto:
    # on for TCP fleets without a shared disk store (the topology where a
    # deviating dispatch would otherwise re-solve), off elsewhere.
    forward_cache: Optional[bool] = None
    # -- verification (round 19, docs/VERIFICATION.md) -------------------
    # verify: the per-class off|sample|full policy spec every spawned
    # real-service worker boots with (--verify). verify_forward: certify
    # cross-host forward.hit payloads AT THE ROUTER before serving them —
    # mandatory by default: a forwarded result crosses an extra process
    # and an extra link that the owning worker's own verification never
    # saw. verify_responses: certify EVERY verifiable solve response
    # (request carried edges, response carried mst_edges) and re-dispatch
    # once on a failed certificate — the net the corruption drill arms
    # fleet.chaos.payload against.
    verify: Optional[str] = None
    verify_forward: bool = True
    verify_responses: bool = False
    # -- survivability (round 18, docs/FLEET.md "Router survivability") --
    # Durable accepted-work journal (fleet/journal.py): every accept is
    # fsynced before dispatch, answers/pins/ring/scale changes follow, so
    # a router crash loses NOTHING acknowledged — a restarted router with
    # the same journal_dir re-adopts live --listen workers warm,
    # re-spawns dead ones, rebuilds pins/affinity, and re-queues orphaned
    # accepts by digest. None = the pre-round-18 in-memory-only router.
    journal_dir: Optional[str] = None
    journal_checkpoint_every: int = 512
    # Transport chaos layer (fleet/transport.py ChaosTransport): wrap
    # every worker channel in the fault-injectable wrapper so drills can
    # drive seeded partitions / latency / frame corruption per worker.
    chaos: bool = False
    chaos_seed: int = 0
    # Worker lease: silence (no pong, no frames) longer than this declares
    # the worker dead even while its connection stays open. None derives
    # heartbeat_interval_s * heartbeat_miss_threshold. A dead process is
    # caught instantly by channel EOF; the lease exists for WEDGED
    # processes and half-dead network paths, so the default errs generous —
    # a false-positive kill under load-spike GIL starvation costs more
    # than slow detection.
    lease_s: Optional[float] = None
    pipelined_io: bool = True  # coalesce TCP frame writes (transport.py)
    heartbeat_interval_s: float = 0.25
    heartbeat_miss_threshold: int = 20
    restart_backoff_base_s: float = 0.05
    restart_backoff_cap_s: float = 2.0
    # Restart jitter: each backoff is scaled by (1 - restart_jitter * u)
    # with u in [0,1) derived deterministically from (seed, worker,
    # attempt), so a MASS death (or mass scale-up rejoin) fans restarts
    # out over the backoff window instead of stampeding the shared disk
    # store and compile cache in lockstep — while staying reproducible
    # under a seed and never exceeding the documented cap.
    restart_jitter: float = 0.5
    restart_jitter_seed: int = 0
    max_restarts: int = 8  # per worker slot, cumulative
    request_timeout_s: float = 300.0
    ready_timeout_s: float = 120.0
    ring_replicas: int = 64
    obs_dir: Optional[str] = None  # per-worker JSONL exports on drain
    test_echo: bool = False  # spawn jax-free echo workers (tests)
    worker_env: Optional[Dict[int, Dict[str, str]]] = None  # incarnation 0 only

    @property
    def effective_lease_s(self) -> float:
        if self.lease_s is not None:
            return self.lease_s
        return self.heartbeat_interval_s * self.heartbeat_miss_threshold

    @property
    def forward_enabled(self) -> bool:
        if self.forward_cache is not None:
            return self.forward_cache
        return self.transport == "tcp" and not self.disk_dir


#: Default admission-ceiling BUCKETS mirrored from ``batch.policy
#: .BatchPolicy`` (max_bucket_nodes / max_bucket_edges) — mirrored, not
#: imported, because the policy module pulls in jax and the router must
#: stay importable without it (echo-worker tests); a drift guard in
#: tests/test_lane.py pins these to the real policy defaults.
_OVERSIZE_NODE_BUCKET = 1 << 16
_OVERSIZE_EDGE_BUCKET = 1 << 17


def _next_pow2(x: int) -> int:
    return 1 << max(0, (x - 1)).bit_length()


def _request_oversize(request: dict) -> bool:
    """Would this solve bypass the lane engine (oversize)? Judged from the
    raw request so the router can steer it at a mesh-owning worker without
    building a Graph twice. Binary requests declare ``num_edges`` in the
    B-frame header, so the judgment never touches the edge sections —
    part of the O(header) passthrough contract. ``graph_path`` solves
    (size unknown without I/O) and updates (session-pinned anyway) route
    normally."""
    if request.get("op") != "solve":
        return False
    if "edges" in request:
        m_raw = len(request["edges"])
    elif SECTIONS_KEY in request and "num_edges" in request:
        m_raw = int(request["num_edges"])
    else:
        return False
    n = _next_pow2(max(1, int(request.get("num_nodes", 0))))
    m = _next_pow2(max(1, m_raw))
    return n > _OVERSIZE_NODE_BUCKET or m > _OVERSIZE_EDGE_BUCKET


class _Pending:
    """One accepted request: survives its worker by being re-dispatched."""

    __slots__ = ("request", "key", "cls", "event", "response", "worker_id",
                 "requeues", "lane", "sent_at", "trace")

    def __init__(
        self,
        request: dict,
        key: Optional[str],
        cls: Optional[str],
        lane: bool = False,
    ):
        self.request = request
        self.key = key
        self.cls = cls
        self.event = threading.Event()
        self.response: Optional[dict] = None
        self.worker_id: Optional[int] = None
        self.requeues = 0
        self.lane = lane  # prefers a mesh-owning worker (oversize solve)
        self.sent_at: Optional[float] = None  # hop-latency clock start
        # Wire trace context (obs/tracing.py) captured at dispatch time —
        # failover re-dispatch happens on the monitor thread, where the
        # contextvar from handle() is gone; this is how the re-queued
        # attempt keeps the original trace_id.
        self.trace: Optional[dict] = None


class _Worker:
    """One worker slot: a stable ring identity across process incarnations
    (spawned) or connections (remote)."""

    def __init__(self, worker_id: int, queue_depth: int,
                 addr: Optional[str] = None):
        self.id = worker_id
        self.lock = threading.Lock()  # channel writes + pending map
        self.proc: Optional[subprocess.Popen] = None
        self.transport: Optional[Transport] = None
        self.addr = addr  # remote endpoint (None for spawned workers)
        self.conn_token: Optional[str] = None  # per-incarnation dial-in auth
        self.alive = False
        self.ready = threading.Event()
        self.incarnation = -1
        self.pending: Dict[int, _Pending] = {}
        self.slots = threading.BoundedSemaphore(queue_depth)
        self.last_pong = 0.0
        self.restarts = 0
        self.caps: Dict[str, object] = {}  # from the hello frame
        self.lane_advertised = False  # caps["lane"]
        # Elastic lifecycle: ``draining`` = mid-retire (off the ring,
        # flushing in-flight work — exempt from lease expiry); ``retired``
        # = gone on purpose (never restarted, never counted dead).
        self.draining = False
        self.retired = False


class FleetRouter:
    """Digest-routed, health-checked front end over worker processes.

    :meth:`handle` is request/response-compatible with
    :class:`serve.service.MSTService.handle`, so ``serve_loop``, the load
    drill, and tests drive either interchangeably.
    """

    def __init__(self, config: Optional[FleetConfig] = None):
        self.config = config or FleetConfig()
        if self.config.transport not in ("pipe", "tcp"):
            raise ValueError(
                f"transport must be 'pipe' or 'tcp', got "
                f"{self.config.transport!r}"
            )
        if self.config.remote_workers and self.config.transport != "tcp":
            raise ValueError("remote_workers requires transport='tcp'")
        n = len(self.config.remote_workers) or self.config.workers
        if n < 1:
            raise ValueError(f"workers must be >= 1, got {n}")
        # Durable journal: load BEFORE building slots — a journal from a
        # crashed predecessor may know about workers the static config
        # does not (elastic scale-ups), and those slots must exist so the
        # restarted pool matches the pool the autoscaler had built.
        self._journal = None
        self._journal_state = None
        if self.config.journal_dir:
            from distributed_ghs_implementation_tpu.fleet.journal import (
                RouterJournal,
            )

            self._journal = RouterJournal(
                self.config.journal_dir,
                checkpoint_every=self.config.journal_checkpoint_every,
            )
            self._journal_state = self._journal.load()
        self._workers = [
            _Worker(
                i, self.config.queue_depth,
                addr=(self.config.remote_workers[i]
                      if self.config.remote_workers else None),
            )
            for i in range(n)
        ]
        if self._journal_state is not None and self._journal_state.members:
            for wid in sorted(self._journal_state.members):
                member = self._journal_state.members[wid]
                while wid >= len(self._workers):
                    self._workers.append(_Worker(
                        len(self._workers), self.config.queue_depth,
                        addr=member.get("addr"),
                    ))
                w = self._workers[wid]
                if member.get("addr") and w.addr is None:
                    w.addr = member["addr"]
                if member.get("retired"):
                    # A planned departure stays departed across a router
                    # restart — resurrecting it would undo a scale-down.
                    w.retired = True
                    w.alive = False
        self._ring = HashRing(replicas=self.config.ring_replicas)
        # Mesh-owning worker slots (config-derived — stable across
        # incarnations): oversize solves hash onto this subring.
        k = self.config.sharded_lane_workers
        # -1 = every worker, including slots a journal restored beyond n.
        self._lane_ids = set(range(
            len(self._workers) if k == -1 else max(0, min(k, n))
        ))
        if self._journal_state is not None:
            for wid, member in self._journal_state.members.items():
                if member.get("lane") is None or wid >= len(self._workers):
                    continue
                # Restore the lane subring the crashed router had built —
                # it is capability-derived for dialed standbys, so config
                # alone would mis-place them (a -1 config would drag a
                # lane-less standby onto the oversize ring; a k-bounded
                # one would drop a lane-capable standby off it).
                if member["lane"]:
                    self._lane_ids.add(wid)
                else:
                    self._lane_ids.discard(wid)
        self._lane_ring = HashRing(replicas=self.config.ring_replicas)
        self._ring_lock = threading.Lock()
        # Chaos layer: one standing fault-flag object per worker slot,
        # shared across its transport incarnations (a partition outlives
        # a re-dial). Empty unless config.chaos.
        self._chaos: Dict[int, ChaosState] = {}
        self._sessions: Dict[str, int] = {}  # update-session digest -> worker
        # digest -> worker that LAST answered it ok (the forwarding hop's
        # owner-of-record; survives ring changes that move ownership).
        self._last_served: "collections.OrderedDict[str, int]" = (
            collections.OrderedDict()
        )
        self._next_id = 0
        self._id_lock = threading.Lock()
        self._rr = 0  # round-robin cursor for keyless ops
        self._closed = False
        self._started = False
        self._heartbeat: Optional[threading.Thread] = None
        self._listener: Optional[WorkerListener] = None
        self._hello_rejections: List[str] = []  # surfaced on ready timeout
        # Serializes pool mutations (add_worker / retire_worker): scale
        # operations are deliberately one-at-a-time — the hysteresis the
        # autoscaler's determinism rests on.
        self._pool_lock = threading.Lock()
        self.last_scale_decision: Optional[dict] = (
            dict(self._journal_state.last_scale)
            if self._journal_state is not None
            and self._journal_state.last_scale else None
        )

    # -- lifecycle -----------------------------------------------------
    def start(self) -> "FleetRouter":
        if self._started:
            return self
        self._started = True
        if self.config.transport == "tcp" and not self.config.remote_workers:
            self._listener = WorkerListener(
                self._on_dial_in,
                host=self.config.listen_host,
                on_reject=self._on_hello_reject,
                pipelined=self.config.pipelined_io,
            )
        for w in self._workers:
            if w.retired:
                continue  # journal-restored planned departures stay gone
            if w.addr is not None:
                threading.Thread(
                    target=self._connect_remote, args=(w,),
                    name=f"fleet-dial-{w.id}", daemon=True,
                ).start()
            else:
                self._spawn(w)
        deadline = time.monotonic() + self.config.ready_timeout_s
        for w in self._workers:
            if w.retired:
                continue
            if not w.ready.wait(max(0.0, deadline - time.monotonic())):
                rejections = "; ".join(self._hello_rejections[-3:])
                self.shutdown(drain=False)
                raise TimeoutError(
                    f"worker {w.id} not ready within "
                    f"{self.config.ready_timeout_s}s"
                    + (f" (hello rejected: {rejections})" if rejections else "")
                )
        now = time.monotonic()
        with self._ring_lock:
            for w in self._workers:
                if w.retired:
                    continue
                w.alive = True
                w.last_pong = now
                self._ring.add(w.id)
                if w.id in self._lane_ids:
                    self._lane_ring.add(w.id)
        for w in self._workers:
            if not w.retired:
                self._journal_ring("add", w)
        self._adopt_journal_state()
        self._heartbeat = threading.Thread(
            target=self._heartbeat_loop, name="fleet-heartbeat", daemon=True
        )
        self._heartbeat.start()
        return self

    # -- journal hooks (no-ops without a journal_dir) -------------------
    def _journal_ring(self, action: str, w: _Worker) -> None:
        if self._journal is not None:
            try:
                self._journal.ring(
                    action, w.id, addr=w.addr,
                    lane=w.id in self._lane_ids,
                )
            except (OSError, TimeoutError):
                BUS.count("fleet.router.journal.ring_failed")

    def _journal_answer(self, jid, *, ok, worker=None, digest=None) -> None:
        if self._journal is None or jid is None:
            return
        try:
            self._journal.answer(jid, ok=ok, worker=worker, digest=digest)
        except (OSError, TimeoutError):
            # A failed answer append degrades to a spurious (idempotent)
            # replay after a crash, never to a lost query.
            BUS.count("fleet.router.journal.answer_failed")

    def _adopt_journal_state(self) -> None:
        """Restart-with-warm-re-adoption: restore session pins and the
        forwarding affinity map from the journal (live workers only — a
        pin on a slot that did not come back would route at a corpse),
        then re-queue every accepted-but-unanswered entry by digest on a
        background thread (idempotent: results are content-addressed, so
        an answer the crashed router never delivered is recomputed or
        cache-hit, never double-committed)."""
        state = self._journal_state
        if state is None or not state.had_state:
            return
        self._journal_state = None  # one-shot: adoption happens at boot
        with self._ring_lock:
            for digest, wid in state.pins.items():
                if wid < len(self._workers) and self._workers[wid].alive:
                    self._sessions[digest] = wid
            for digest, wid in state.served.items():
                if wid < len(self._workers) and self._workers[wid].alive:
                    self._last_served[digest] = wid
        for w in self._workers:
            if not w.alive:
                continue
            if w.addr is not None:
                # A --listen worker that outlived the crashed router: the
                # re-dial found its caches and sessions warm.
                BUS.count("fleet.router.restart.readopted")
            else:
                BUS.count("fleet.router.restart.respawned")
        orphans = state.unanswered
        BUS.instant(
            "fleet.router.restart", cat="fleet",
            orphans=len(orphans), pins=len(state.pins),
            served=len(state.served), dropped=state.dropped,
        )
        if orphans:
            threading.Thread(
                target=self._replay_orphans, args=(list(orphans.values()),),
                name="fleet-journal-replay", daemon=True,
            ).start()

    def _replay_orphans(self, orphans: List[dict]) -> None:
        """Answer the crashed router's accepted-but-unanswered ledger.
        The original clients are gone (they died with the old router's
        sockets), so the *answer* here is the durable journal record: the
        query was accepted, it got executed, nothing was lost — and a
        client that retries the same content-addressed request gets a
        warm cache hit."""
        for entry in orphans:
            if self._closed:
                return
            BUS.count("fleet.router.restart.requeued")
            p = _Pending(
                entry.get("req") or {}, entry.get("key"), entry.get("cls"),
                lane=bool(entry.get("lane")),
            )
            p.trace = entry.get("trace")
            # Replay re-dispatch continues the ORIGINAL trace: the accept
            # record journaled the wire context, so the replayed hop shows
            # up as a fresh child span under the crashed router's request.
            with tracing.activated(tracing.from_wire(p.trace)), \
                    BUS.span("fleet.replay.request", cat="fleet",
                             op=(entry.get("req") or {}).get("op")):
                err = self._dispatch(p, allow_shed=False)
                if err is not None:
                    self._journal_answer(entry.get("jid"), ok=False)
                    continue
                if not p.event.wait(self.config.request_timeout_s):
                    self._forget(p)
                    self._journal_answer(entry.get("jid"), ok=False)
                    continue
                resp = p.response or {}
                self._journal_answer(
                    entry.get("jid"), ok=bool(resp.get("ok")),
                    worker=p.worker_id, digest=resp.get("digest"),
                )
                if resp.get("ok"):
                    BUS.count("fleet.router.restart.replayed")

    def __enter__(self) -> "FleetRouter":
        return self.start()

    def __exit__(self, *exc) -> bool:
        self.shutdown()
        return False

    def shutdown(self, *, drain: bool = True, timeout_s: float = 30.0) -> None:
        """Stop admitting, drain every worker, reap the processes.

        ``drain=True`` sends the drain frame and waits: in-flight requests
        finish and flush before the workers exit 0 (remote workers exit
        too — shutdown drains the whole fleet it was configured with).
        ``drain=False`` kills.
        """
        self._closed = True
        if self._heartbeat is not None:
            self._heartbeat.join(timeout=2.0)
        for w in self._workers:
            with w.lock:
                transport = w.transport
                proc = w.proc
                if drain and transport is not None and not transport.closed:
                    try:
                        transport.send({"drain": True})
                    except OSError:
                        pass
                elif not drain and proc is not None and proc.poll() is None:
                    proc.kill()
        deadline = time.monotonic() + timeout_s
        for w in self._workers:
            proc = w.proc
            if proc is None:
                # Remote worker: wait for its reader to see the post-drain
                # close (bye + EOF), bounded by the shutdown deadline.
                if drain and w.transport is not None:
                    t_deadline = max(0.1, deadline - time.monotonic())
                    t_end = time.monotonic() + t_deadline
                    while (time.monotonic() < t_end
                           and not w.transport.closed):
                        time.sleep(0.02)
                continue
            try:
                proc.wait(timeout=max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=5.0)
        for w in self._workers:
            if w.transport is not None:
                w.transport.close()
        if self._listener is not None:
            self._listener.close()

    # -- spawning ------------------------------------------------------
    def _worker_argv(self, w: _Worker) -> List[str]:
        cfg = self.config
        argv = [
            sys.executable, "-m",
            "distributed_ghs_implementation_tpu.fleet.worker",
            "--worker-id", str(w.id),
            "--backend", cfg.backend,
            "--batch-lanes", str(cfg.batch_lanes),
            "--store-capacity", str(cfg.store_capacity),
            "--max-concurrent", str(cfg.max_concurrent),
            "--max-sessions", str(cfg.max_sessions),
            "--threads", str(cfg.worker_threads),
        ]
        if self._listener is not None:
            argv += ["--connect", self._listener.address,
                     "--conn-token", w.conn_token]
        if cfg.batch_wait_s is not None:
            argv += ["--batch-wait", str(cfg.batch_wait_s)]
        if cfg.disk_dir:
            argv += ["--disk-cache", cfg.disk_dir]
        if cfg.stream_dir:
            argv += ["--stream-dir", cfg.stream_dir,
                     "--stream-snapshot-every",
                     str(cfg.stream_snapshot_every)]
        if cfg.resolve_threshold is not None:
            argv += ["--resolve-threshold", str(cfg.resolve_threshold)]
        if cfg.warmup_buckets:
            argv += ["--warmup-buckets", cfg.warmup_buckets]
        if cfg.warmup_replay:
            argv += ["--warmup-replay", cfg.warmup_replay]
        if cfg.warmup_stream_buckets:
            argv += ["--warmup-stream-buckets", cfg.warmup_stream_buckets]
        if w.id in self._lane_ids:
            argv += ["--sharded-lane", "-1"]
            if cfg.warmup_mesh_buckets:
                argv += ["--warmup-mesh-buckets", cfg.warmup_mesh_buckets]
        if cfg.verify:
            argv += ["--verify", cfg.verify]
        if cfg.compile_cache_dir:
            argv += ["--compile-cache-dir", cfg.compile_cache_dir]
        if cfg.no_compile_cache:
            argv += ["--no-compile-cache"]
        if cfg.tune_record:
            argv += ["--tune-record", cfg.tune_record]
        if cfg.obs_dir:
            os.makedirs(cfg.obs_dir, exist_ok=True)
            argv += ["--obs-jsonl", os.path.join(
                cfg.obs_dir, f"worker{w.id}.{w.incarnation}.jsonl"
            )]
        if cfg.test_echo:
            argv += ["--test-echo"]
        return argv

    def _spawn(self, w: _Worker) -> None:
        env = dict(os.environ)
        # The worker runs `-m distributed_ghs_implementation_tpu.fleet.worker`;
        # make the package importable no matter the caller's cwd.
        pkg_parent = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)
        )))
        env["PYTHONPATH"] = pkg_parent + os.pathsep + env.get("PYTHONPATH", "")
        extra = (self.config.worker_env or {}).get(w.id)
        if extra and w.incarnation < 0:
            # Incarnation 0 only: a crash-fault env inherited by restarts
            # would kill every incarnation and the fleet could never heal.
            env.update(extra)
        tcp = self._listener is not None
        with w.lock:
            w.incarnation += 1
            incarnation = w.incarnation
            # A fresh token per incarnation: a limping previous incarnation
            # (or a stranger on the port) cannot register into this slot.
            w.conn_token = new_conn_token() if tcp else None
            w.ready.clear()
            w.slots = threading.BoundedSemaphore(self.config.queue_depth)
            argv = self._worker_argv(w)
            if tcp:
                # The framed channel is the socket the worker dials back;
                # stdin/stdout stay free (stderr inherits for logs).
                w.transport = None
                w.proc = subprocess.Popen(
                    argv, stdin=subprocess.DEVNULL, env=env
                )
            else:
                w.proc = subprocess.Popen(
                    argv, stdin=subprocess.PIPE, stdout=subprocess.PIPE,
                    env=env,
                )
                w.transport = self._wrap_transport(
                    w, PipeTransport(w.proc.stdin, w.proc.stdout)
                )
        if not tcp:
            threading.Thread(
                target=self._reader,
                args=(w, incarnation, w.transport),
                name=f"fleet-reader-{w.id}.{incarnation}",
                daemon=True,
            ).start()
        # tcp: the reader starts when the worker's dial-in hello arrives
        # (_on_dial_in); until then the slot has no channel.

    # -- chaos wrapping ------------------------------------------------
    def _wrap_transport(self, w: _Worker, transport: Transport) -> Transport:
        """With ``config.chaos``, wrap a freshly established channel in
        the fault-injectable layer, bound to the worker's STANDING flag
        object — a partition set on the slot applies to every future
        incarnation until healed."""
        if not self.config.chaos:
            return transport
        state = self._chaos.get(w.id)
        if state is None:
            state = self._chaos[w.id] = ChaosState(
                seed=self.config.chaos_seed, name=str(w.id)
            )
        return ChaosTransport(transport, state)

    def partition_worker(self, worker_id: int, *, mode: str = "oneway") -> None:
        """Partition one worker's link (drills; needs ``config.chaos``).

        ``oneway``: router→worker frames vanish while worker→router still
        flows — the nastiest shape, because the worker looks alive (its
        in-flight responses keep arriving) right up until silence expires
        the lease. ``sym``: both directions drop. Unlike
        :meth:`close_worker_connection` the socket stays OPEN — detection
        must come from the lease, not EOF."""
        if not self.config.chaos:
            raise RuntimeError("partition_worker needs FleetConfig(chaos=True)")
        if mode not in ("oneway", "sym"):
            raise ValueError(f"mode must be 'oneway' or 'sym', got {mode!r}")
        state = self._chaos.setdefault(worker_id, ChaosState(
            seed=self.config.chaos_seed, name=str(worker_id)
        ))
        state.drop_send = True
        state.drop_recv = mode == "sym"
        BUS.count("fleet.chaos.partition")
        BUS.instant("fleet.chaos.partition", cat="fleet",
                    worker=worker_id, mode=mode)

    def heal_partition(self, worker_id: int) -> None:
        """Heal a drill partition: frames flow again and the redial loop's
        next knock completes — a warm rejoin, never a cold restart."""
        state = self._chaos.get(worker_id)
        if state is None:
            return
        state.drop_send = False
        state.drop_recv = False
        BUS.count("fleet.chaos.heal")
        BUS.instant("fleet.chaos.heal", cat="fleet", worker=worker_id)

    def set_worker_latency(
        self, worker_id: int, latency_s: float, jitter_s: float = 0.0
    ) -> None:
        """Add seeded latency/jitter to one worker's outbound frames."""
        if not self.config.chaos:
            raise RuntimeError(
                "set_worker_latency needs FleetConfig(chaos=True)"
            )
        state = self._chaos.setdefault(worker_id, ChaosState(
            seed=self.config.chaos_seed, name=str(worker_id)
        ))
        state.latency_s = float(latency_s)
        state.jitter_s = float(jitter_s)

    # -- connection establishment (tcp) --------------------------------
    def _on_hello_reject(self, reason: str) -> None:
        BUS.count("fleet.hello.rejected")
        BUS.instant("fleet.hello.reject", cat="fleet", reason=reason[:200])
        self._hello_rejections.append(reason)
        del self._hello_rejections[:-16]  # keep the tail only

    def _on_dial_in(self, hello: dict, transport: Transport) -> None:
        """Listener callback: attach a validated dial-in to its slot."""
        wid = int(hello["worker"])
        if not 0 <= wid < len(self._workers):
            raise HelloError(f"hello for unknown worker slot {wid}")
        w = self._workers[wid]
        with w.lock:
            if self._closed:
                raise HelloError("router is shutting down")
            if hello.get("token") != w.conn_token:
                raise HelloError(
                    f"stale or foreign dial-in token for worker {wid} "
                    f"(incarnation {w.incarnation})"
                )
            if w.transport is not None and not w.transport.closed:
                raise HelloError(f"worker {wid} already connected")
            transport = self._wrap_transport(w, transport)
            w.transport = transport
            incarnation = w.incarnation
        self._register_hello(w, hello)
        threading.Thread(
            target=self._reader,
            args=(w, incarnation, transport),
            name=f"fleet-reader-{w.id}.{incarnation}",
            daemon=True,
        ).start()

    def _connect_remote(self, w: _Worker) -> None:
        """Dial one externally started worker (``--listen``) until it
        answers with a valid hello or the ready timeout passes."""
        deadline = time.monotonic() + self.config.ready_timeout_s
        while not self._closed and time.monotonic() < deadline:
            state = self._chaos.get(w.id)
            if state is not None and state.partitioned:
                # A partitioned endpoint cannot complete a dial either —
                # the redial loop keeps knocking until the drill heals it.
                time.sleep(0.1)
                continue
            try:
                hello, transport = connect_to_worker(
                    w.addr, pipelined=self.config.pipelined_io
                )
            except HelloError as e:
                self._on_hello_reject(str(e))
                return  # incompatible peer: retrying cannot fix a version
            except OSError:
                time.sleep(0.2)
                continue
            if int(hello.get("worker", -1)) != w.id:
                # A misconfigured endpoint (two --listen workers started
                # with the same --worker-id, or the wrong port listed):
                # registering it anyway would mis-attribute every
                # response's `worker` field, the per-worker SLO breakdown,
                # and the session pins — silently. Redialing the same
                # endpoint cannot fix a config error, so fail loud.
                self._on_hello_reject(
                    f"worker at {w.addr} says --worker-id "
                    f"{hello.get('worker')}, but this slot is {w.id} — "
                    f"fix the --worker-id/--fleet-workers pairing"
                )
                transport.close(flush=False)
                return
            with w.lock:
                w.incarnation += 1
                incarnation = w.incarnation
                transport = self._wrap_transport(w, transport)
                w.transport = transport
            self._register_hello(w, hello)
            threading.Thread(
                target=self._reader,
                args=(w, incarnation, transport),
                name=f"fleet-reader-{w.id}.{incarnation}",
                daemon=True,
            ).start()
            return

    def _register_hello(self, w: _Worker, hello: dict) -> None:
        w.caps = dict(hello.get("caps") or {})
        w.lane_advertised = bool(w.caps.get("lane"))
        if w.caps.get("crc") and w.transport is not None:
            # The worker parses checksummed frames: emit them. The worker
            # side flips on by echo — its first checksummed inbound frame
            # (fleet/transport.py, "CRC negotiation").
            w.transport.enable_crc()
        if w.caps.get("wire") and w.transport is not None:
            # The worker parses B-frames: section-bearing payloads pass
            # through binary. Workers flip on by the same echo rule.
            w.transport.enable_wire()
        w.last_pong = time.monotonic()
        w.ready.set()

    # -- the channel reader (one per incarnation) ----------------------
    def _reader(self, w: _Worker, incarnation: int, transport: Transport) -> None:
        while True:
            frame = transport.recv()
            if frame is None:
                break
            if "ready" in frame:
                # Pipe mode: the hello arrives in-band as the first frame.
                try:
                    hello = check_hello(frame)
                except HelloError as e:
                    self._on_hello_reject(str(e))
                    break  # incompatible peer: drop the channel
                self._register_hello(w, hello)
                continue
            if "pong" in frame:
                w.last_pong = time.monotonic()
                continue
            if "bye" in frame:
                continue
            rid = frame.get("id")
            resp = frame.get("resp")
            if rid is None or not isinstance(resp, dict):
                continue
            w.last_pong = time.monotonic()  # a response proves liveness too
            with w.lock:
                pending = w.pending.pop(rid, None)
            if pending is None:
                # A response for a request we already re-queued elsewhere
                # (the worker was declared dead but limped on). Results are
                # content-addressed, so the duplicate is discardable.
                BUS.count("fleet.duplicate.response")
                continue
            self._release_slot(w)
            self._record_hop(pending, frame.get("t"))
            if resp.get("ok") and resp.get("op") in (
                "update", "publish", "subscribe"
            ):
                # update/publish rename the pinned digest along the chain;
                # subscribe pins the head it returned (no predecessor).
                self._note_session(
                    resp.get("digest"), w.id, prev=resp.get("prev_digest")
                )
            if resp.get("ok") and resp.get("digest"):
                # Forwarding's owner-of-record: this worker now holds the
                # digest's result warm, wherever the ring says it *should*
                # live.
                self._note_served(str(resp["digest"]), w.id)
            pending.response = resp
            pending.worker_id = w.id
            pending.event.set()
        transport.close(flush=False)  # channel already dead: never wait on it
        self._on_death(w, incarnation)

    @staticmethod
    def _release_slot(w: _Worker) -> None:
        try:
            w.slots.release()
        except ValueError:
            pass  # slot already reclaimed by a respawn's fresh semaphore

    @staticmethod
    def _record_hop(p: _Pending, service_s) -> None:
        """Hop latency = send-to-response wall time minus the worker's own
        service time: what the transport, framing, queueing, and router
        bookkeeping cost this request — the number a pipe-vs-TCP choice
        moves, tracked per class so the SLO report can carry it."""
        if p.sent_at is None:
            return
        try:
            service = float(service_s or 0.0)
        except (TypeError, ValueError):
            service = 0.0
        hop = max(0.0, time.monotonic() - p.sent_at - service)
        BUS.record("fleet.hop_s", hop)
        if p.cls:
            BUS.record(f"fleet.hop_s.{p.cls}", hop)

    def _note_session(
        self, digest: Optional[str], worker_id: int, prev: Optional[str]
    ) -> None:
        if not digest:
            return
        with self._ring_lock:
            if prev:
                self._sessions.pop(prev, None)
            self._sessions[digest] = worker_id
            while len(self._sessions) > _SESSION_MAP_CAP:
                self._sessions.pop(next(iter(self._sessions)))
        if self._journal is not None:
            try:
                self._journal.pin(digest, worker_id, prev=prev)
            except (OSError, TimeoutError):
                # A lost pin degrades to one post-restart ring-routed hop
                # (the session worker answers `no session` / stale and the
                # chain re-syncs) — never to a lost query.
                BUS.count("fleet.router.journal.pin_failed")

    def _note_served(self, digest: str, worker_id: int) -> None:
        with self._ring_lock:
            self._last_served[digest] = worker_id
            self._last_served.move_to_end(digest)
            while len(self._last_served) > _FORWARD_MAP_CAP:
                self._last_served.popitem(last=False)

    # -- health --------------------------------------------------------
    def _heartbeat_loop(self) -> None:
        cfg = self.config
        lease_s = cfg.effective_lease_s
        seq = 0
        while not self._closed:
            time.sleep(cfg.heartbeat_interval_s)
            for w in self._workers:
                if self._closed:
                    return
                if not (w.alive and w.ready.is_set()):
                    continue
                if w.draining:
                    # A worker in graceful drain stops reading its channel
                    # on purpose — silence is the PROTOCOL there, not a
                    # wedge. Lease expiry on a draining worker would
                    # declare it dead mid-flush and re-queue work it is
                    # about to answer (duplicate solves, a spurious
                    # fleet.worker.dead in a planned scale-down), so the
                    # heartbeat skips it entirely; retire_worker owns its
                    # deadline.
                    continue
                age = time.monotonic() - w.last_pong
                if age > lease_s:
                    # The channel is still open but the worker went silent
                    # past its lease: a wedged process, or a half-dead
                    # network path TCP keepalive hasn't noticed.
                    BUS.count("fleet.heartbeat.miss")
                    if w.transport is not None and w.transport.kind == "tcp":
                        BUS.count("fleet.lease.expired")
                    self._on_death(w, w.incarnation)
                    continue
                seq += 1
                try:
                    with w.lock:
                        if w.transport is not None:
                            w.transport.send({"ping": seq})
                except OSError:
                    self._on_death(w, w.incarnation)

    def _on_death(self, w: _Worker, incarnation: int) -> None:
        """Declare one incarnation dead exactly once: fail over its pending
        requests, drop its ring share + session pins, schedule a restart.
        A *retiring* worker's channel EOF lands here too — that exit is on
        purpose (drain frame sent, responses flushed), so it closes the
        slot quietly: no death counter, no kill, no restart."""
        with self._ring_lock:
            if w.incarnation != incarnation or not w.alive:
                return
            w.alive = False
            w.ready.clear()
            retiring = w.draining
            if retiring:
                w.retired = True
            self._ring.remove(w.id)
            if w.id in self._lane_ids:
                self._lane_ring.remove(w.id)
            for digest in [
                d for d, wid in self._sessions.items() if wid == w.id
            ]:
                del self._sessions[digest]
            for digest in [
                d for d, wid in self._last_served.items() if wid == w.id
            ]:
                # Its warm copies died with it (memory) or became
                # unreachable (its host-local disk): stop forwarding there.
                del self._last_served[digest]
        self._journal_ring("retire" if retiring else "remove", w)
        with w.lock:
            orphans = list(w.pending.values())
            w.pending.clear()
            proc = w.proc
            transport = w.transport
        if transport is not None:
            # flush=False: this is the death path — waiting on a wedged
            # peer's full TCP window here would stall the heartbeat thread
            # (and every other worker's failover) for the flush timeout.
            transport.close(flush=False)
        if retiring:
            # Planned exit: retire_worker() owns the reap and the
            # fleet.scale.down accounting. Anything still pending (the
            # drain deadline fired with work in flight) re-queues onto
            # survivors — retirement must uphold zero-loss like any other
            # departure.
            if orphans and not self._closed:
                self._redispatch(orphans)
            elif orphans:
                for p in orphans:
                    p.response = {
                        "ok": False, "error": "fleet shutting down",
                        "op": p.request.get("op"),
                    }
                    p.event.set()
            return
        if not self._closed:  # drained workers EOF on purpose: not a death
            BUS.count("fleet.worker.dead")
            BUS.instant("fleet.worker.death", cat="fleet", worker=w.id,
                        incarnation=incarnation, orphans=len(orphans))
        if proc is not None and proc.poll() is None and not self._closed:
            # During shutdown the channel closes BEFORE the process exits
            # (a TCP worker tears its socket down, then flushes obs and
            # returns 0) — killing here would turn every graceful drain
            # into a SIGKILL. shutdown() owns the reap (and the kill, past
            # its deadline); outside shutdown a dead channel means the
            # incarnation is done: make sure the process is too.
            try:
                proc.kill()
            except OSError:
                pass
        if orphans and not self._closed:
            threading.Thread(
                target=self._redispatch, args=(orphans,),
                name=f"fleet-requeue-{w.id}", daemon=True,
            ).start()
        elif orphans:
            for p in orphans:  # shutting down: answer rather than hang
                p.response = {
                    "ok": False, "error": "fleet shutting down",
                    "op": p.request.get("op"),
                }
                p.event.set()
        if not self._closed:
            threading.Thread(
                target=self._restart, args=(w,),
                name=f"fleet-restart-{w.id}", daemon=True,
            ).start()

    def _redispatch(self, orphans: List[_Pending]) -> None:
        for p in orphans:
            p.requeues += 1
            BUS.count("fleet.requeue")
            # Failover continues the original trace: re-activate the wire
            # context captured at first dispatch, so the requeue span (and
            # the second worker's spans under it) keep the trace_id while
            # parenting to the attempt that lost its worker.
            with tracing.activated(tracing.from_wire(p.trace)), \
                    BUS.span("fleet.requeue.dispatch", cat="fleet",
                             requeues=p.requeues):
                err = self._dispatch(p, allow_shed=False)
            if err is not None:
                p.response = err
                p.event.set()

    def _backoff_s(self, worker_id: int, attempt: int) -> float:
        """The jittered restart backoff for one (worker, attempt) pair.

        Capped exponential, then scaled DOWN by a deterministic per-pair
        jitter: ``sha256(seed:worker:attempt)`` -> u in [0,1), backoff *=
        (1 - restart_jitter * u). Scaling down (never up) keeps the cap a
        real ceiling while desynchronizing a mass death's restart wave —
        N workers that died together stop hammering the shared disk store
        and compile cache at the same instant. Fully reproducible under
        ``restart_jitter_seed`` (the property the jitter test pins)."""
        cfg = self.config
        backoff = min(
            cfg.restart_backoff_base_s * (2 ** attempt),
            cfg.restart_backoff_cap_s,
        )
        if cfg.restart_jitter <= 0:
            return backoff
        token = f"{cfg.restart_jitter_seed}:{worker_id}:{attempt}"
        u = int.from_bytes(
            hashlib.sha256(token.encode("utf-8")).digest()[:8], "big"
        ) / float(1 << 64)
        return backoff * (1.0 - cfg.restart_jitter * u)

    def _restart(self, w: _Worker) -> None:
        cfg = self.config
        while not self._closed:
            if w.retired:
                return  # a planned departure is never restarted
            if w.restarts >= cfg.max_restarts:
                BUS.count("fleet.worker.abandoned")
                # The slot is gone for good — it must leave pool_size(),
                # or the autoscaler would forever count phantom capacity
                # and refuse to scale up past a crash-looped worker
                # ("already at max" while real capacity is below it).
                with self._ring_lock:
                    w.retired = True
                self._journal_ring("retire", w)
                return
            backoff = self._backoff_s(w.id, w.restarts)
            w.restarts += 1
            time.sleep(backoff)
            if self._closed:
                return
            if w.addr is not None:
                # Remote worker: re-dial. The process (and its caches) may
                # have survived a mere connection loss — the hello-led
                # reconnect is then a warm rejoin, not a cold restart.
                self._connect_remote(w)
            else:
                try:
                    self._spawn(w)
                except OSError:
                    continue
            if w.ready.wait(cfg.ready_timeout_s):
                with self._ring_lock:
                    w.alive = True
                    w.last_pong = time.monotonic()
                    self._ring.add(w.id)
                    if w.id in self._lane_ids:
                        self._lane_ring.add(w.id)
                self._journal_ring("add", w)
                BUS.count("fleet.worker.restart")
                BUS.instant("fleet.worker.rejoin", cat="fleet", worker=w.id,
                            incarnation=w.incarnation, backoff_s=backoff)
                return
            with w.lock:
                if w.proc is not None and w.proc.poll() is None:
                    w.proc.kill()
                if w.transport is not None:
                    w.transport.close()

    # -- elastic pool (fleet/autoscaler.py drives these) ---------------
    def pool_size(self) -> int:
        """Worker slots currently in (or rejoining) the pool: everything
        not retired and not mid-drain. A slot whose process is restarting
        still counts — the pool's *intent* is N workers; the autoscaler
        must not scale up just because a restart is in flight."""
        return sum(
            1 for w in self._workers if not w.retired and not w.draining
        )

    def queue_depths(self) -> Dict[int, int]:
        """Live per-worker in-flight depth (the autoscaler's queue-pressure
        signal; draining/retired slots excluded — their depth is drain
        progress, not demand)."""
        return {
            w.id: len(w.pending)
            for w in self._workers
            if w.alive and not w.draining
        }

    def note_scale_decision(self, decision: dict) -> None:
        """Record the latest scale decision (the stats op reports it, so an
        operator can see WHY the fleet is its current size). With a
        journal, the decision — wall-clock stamped — is durable too: a
        restarted router hands it back to its autoscaler, whose cooldown
        then spans the crash instead of resetting (a crash-loop must not
        double-scale a fleet that just scaled)."""
        decision = dict(decision)
        decision.setdefault("at", time.time())
        self.last_scale_decision = decision
        if self._journal is not None:
            try:
                self._journal.scale(decision)
            except (OSError, TimeoutError):
                BUS.count("fleet.router.journal.scale_failed")

    def add_worker(
        self, *, addr: Optional[str] = None,
        timeout_s: Optional[float] = None,
    ) -> dict:
        """Grow the pool by one WARM worker; returns ``{"worker", "warm_s"}``.

        The joiner is spawned with the fleet's full flag set — shared disk
        store, persistent compile cache, warmup buckets — so it pre-seeds
        and precompiles before saying hello, and it enters the hash ring
        only once its hello's ``warmed`` capability is confirmed: scale-up
        can never route interactive traffic at a cold worker. The warm
        join wall time lands on ``fleet.join.warm_s`` (the elastic gate
        bounds its p95) and the join counts ``fleet.scale.up``.

        ``addr`` instead DIALS an externally started ``--listen`` worker
        (an operator bringing standby capacity into a remote fleet: the
        same warm gate applies — that worker's service, caches, and warmup
        already exist, which is the whole point of a standby).
        """
        if self.config.remote_workers and addr is None:
            raise ValueError(
                "add_worker spawns processes; growing a --fleet-workers "
                "remote topology needs the standby's endpoint: "
                "add_worker(addr='host:port')"
            )
        if addr is not None and self.config.transport != "tcp":
            raise ValueError("dialing a remote joiner needs transport='tcp'")
        if self._closed or not self._started:
            raise RuntimeError("router is not running")
        with self._pool_lock:
            t0 = time.monotonic()
            w = _Worker(len(self._workers), self.config.queue_depth,
                        addr=addr)
            if self.config.sharded_lane_workers == -1 and addr is None:
                # "-1 = every worker" includes workers that join later.
                self._lane_ids.add(w.id)
            self._workers.append(w)
            if addr is not None:
                threading.Thread(
                    target=self._connect_remote, args=(w,),
                    name=f"fleet-dial-{w.id}", daemon=True,
                ).start()
            else:
                self._spawn(w)
            deadline = timeout_s or self.config.ready_timeout_s
            if not w.ready.wait(deadline):
                self._abandon_join(w)
                BUS.count("fleet.scale.failed")
                rejections = "; ".join(self._hello_rejections[-3:])
                raise TimeoutError(
                    f"joining worker {w.id} not ready within {deadline}s"
                    + (f" (hello rejected: {rejections})" if rejections
                       else "")
                )
            if not w.caps.get("warmed", False):
                self._abandon_join(w)
                BUS.count("fleet.join.cold_rejected")
                raise RuntimeError(
                    f"joining worker {w.id} said hello without the "
                    f"'warmed' capability — a cold joiner would serve "
                    f"cold p99s, refusing ring entry"
                )
            warm_s = time.monotonic() - t0
            with self._ring_lock:
                w.alive = True
                w.last_pong = time.monotonic()
                self._ring.add(w.id)
                if addr is not None and w.lane_advertised:
                    # A dialed standby declares its own lane capability.
                    self._lane_ids.add(w.id)
                if w.id in self._lane_ids:
                    self._lane_ring.add(w.id)
            self._journal_ring("add", w)
            BUS.count("fleet.scale.up")
            BUS.record("fleet.join.warm_s", warm_s)
            BUS.instant("fleet.join", cat="fleet", worker=w.id,
                        warm_s=round(warm_s, 4),
                        warmup=w.caps.get("warmup"))
            return {"worker": w.id, "warm_s": warm_s}

    def _abandon_join(self, w: _Worker) -> None:
        """A join that never got warm: close the slot without it ever
        having owned keyspace (it was never on the ring)."""
        with self._ring_lock:
            w.retired = True
            w.draining = False
            w.alive = False
            w.ready.clear()
        self._journal_ring("retire", w)
        with w.lock:
            proc, transport = w.proc, w.transport
        if proc is not None and proc.poll() is None:
            try:
                proc.kill()
            except OSError:
                pass
        if transport is not None:
            transport.close(flush=False)

    def retire_worker(
        self, worker_id: Optional[int] = None, *, timeout_s: float = 30.0
    ) -> dict:
        """Drain one worker out of the pool (scale-down); returns
        ``{"worker", "sessions_moved", "exit_code"}``.

        Victim (when not pinned by ``worker_id``): the live worker with the
        fewest ``_last_served`` affinity entries — the one whose warm
        result cache the fleet would miss least; ties retire the youngest
        slot (a recent joiner before a long-warmed original). Sequence:

        1. off the ring immediately (its keyspace hands off with bounded
           movement; no NEW work routes at it) and marked ``draining`` —
           the heartbeat loop now ignores it, so a slow drain cannot trip
           ``fleet.lease.expired`` and re-queue work mid-flush;
        2. in-flight accepted work drains (bounded by ``timeout_s``;
           whatever outlives the deadline re-queues onto survivors at EOF
           — zero loss either way);
        3. pinned update/stream session digests unpin — their ring
           inheritors recover state exactly like failover does: disk-store
           reads for results, snapshot+WAL replay for streams (zero fresh
           solves, the contract the elastic drill gates);
        4. a drain frame: the worker stops reading, flushes every
           response, exports its obs JSONL, and exits 0.
        """
        with self._pool_lock:
            with self._ring_lock:
                live = [
                    w for w in self._workers
                    if w.alive and w.ready.is_set()
                    and not w.draining and not w.retired
                ]
                if worker_id is not None:
                    w = self._workers[worker_id]
                    if w.retired or w.draining or not w.alive:
                        raise ValueError(
                            f"worker {worker_id} is not live "
                            f"(retired={w.retired}, draining={w.draining})"
                        )
                else:
                    if not live:
                        raise ValueError("no live worker to retire")
                    affinity: Dict[int, int] = {}
                    for wid in self._last_served.values():
                        affinity[wid] = affinity.get(wid, 0) + 1
                    w = min(
                        live,
                        key=lambda c: (affinity.get(c.id, 0), -c.id),
                    )
                if len(live) <= 1:
                    raise ValueError("cannot retire the last live worker")
                w.draining = True
                self._ring.remove(w.id)
                self._lane_ring.remove(w.id)
                for digest in [
                    d for d, wid in self._last_served.items()
                    if wid == w.id
                ]:
                    # Its in-memory warm copies leave with it; survivors
                    # fall back to the shared disk store (or a forward
                    # miss + local solve across hosts).
                    del self._last_served[digest]
            deadline = time.monotonic() + timeout_s
            flushed = True
            while time.monotonic() < deadline:
                with w.lock:
                    if not w.pending:
                        break
                time.sleep(0.02)
            else:
                flushed = False  # EOF re-queue covers what's left
            with self._ring_lock:
                moved = [
                    d for d, wid in self._sessions.items() if wid == w.id
                ]
                for d in moved:
                    del self._sessions[d]
            with w.lock:
                transport = w.transport
                proc = w.proc
            if transport is not None and not transport.closed:
                try:
                    transport.send({"drain": True})
                except OSError:
                    pass
            # The reap gets a grace floor beyond the flush deadline: work
            # that outlived timeout_s re-queues at EOF anyway, but a drain
            # that is ALMOST done should exit 0, not eat a SIGKILL at the
            # buzzer.
            exit_code = None
            if proc is not None:
                try:
                    proc.wait(timeout=max(10.0, deadline - time.monotonic()))
                except subprocess.TimeoutExpired:
                    proc.kill()
                    proc.wait(timeout=5.0)
                exit_code = proc.returncode
            elif transport is not None:
                t_end = max(time.monotonic() + 10.0, deadline)
                while time.monotonic() < t_end and not transport.closed:
                    time.sleep(0.02)
            with self._ring_lock:
                # The reader's EOF normally lands in _on_death and marks
                # these; make retirement unconditional even if the reader
                # thread lost the race.
                w.alive = False
                w.retired = True
                w.ready.clear()
            if transport is not None:
                transport.close(flush=False)
            self._journal_ring("retire", w)
            BUS.count("fleet.scale.down")
            BUS.instant(
                "fleet.retire", cat="fleet", worker=w.id,
                sessions_moved=len(moved), flushed=flushed,
                exit_code=exit_code,
            )
            return {
                "worker": w.id,
                "sessions_moved": len(moved),
                "exit_code": exit_code,
            }

    # -- routing + dispatch --------------------------------------------
    def _routing_key(self, request: dict) -> Optional[str]:
        op = request.get("op")
        if op == "update":
            return request.get("digest")
        if op in ("subscribe", "publish", "poll"):
            # Stream ops ride the update-session digest-chain pinning: the
            # head digest (session-pinned, renamed by every publish
            # response) keeps a stream on the worker whose windowed
            # session is live; the stream id is the stable fallback so
            # polls without a head still hash consistently.
            return request.get("digest") or request.get("stream")
        if op == "solve":
            if "digest" in request:
                return str(request["digest"])  # client-side hint
            if "graph_path" in request:
                return str(request["graph_path"])  # stable path identity
            if "edges" in request:
                from distributed_ghs_implementation_tpu.graphs.edgelist import (
                    Graph,
                )

                return Graph.from_edges(
                    int(request["num_nodes"]), request["edges"]
                ).digest()
            if SECTIONS_KEY in request and "num_nodes" in request:
                # Binary solve without a digest hint: the one routing
                # case that must decode sections. ``to_wire()`` always
                # stamps the digest into the header, so a well-formed
                # binary client never lands here.
                from distributed_ghs_implementation_tpu.graphs.edgelist import (
                    Graph,
                )

                return Graph.from_wire(request).digest()
        return None

    def _route(
        self, key: Optional[str], *, lane: bool = False, count: bool = True
    ) -> Optional[_Worker]:
        """``count=False`` is the side-effect-free peek the forwarding
        probe uses to learn the prospective target — the lane-routing
        counters must reflect dispatches only (``fleet.route
        .lane_fallback`` is documented as the all-lane-workers-down
        signal; a probe pre-pass must not double it)."""
        with self._ring_lock:
            if key is not None:
                wid = self._sessions.get(key)
                if wid is not None and self._workers[wid].alive:
                    return self._workers[wid]
                if lane:
                    # Oversize: prefer a mesh-owning worker (cache
                    # affinity within the lane subring). All lane workers
                    # down -> fall through to the full ring: a bypass
                    # solve is slow, never wrong.
                    try:
                        wid = self._lane_ring.assign(key)
                        if count:
                            BUS.count("fleet.route.sharded_lane")
                        return self._workers[wid]
                    except LookupError:
                        if count:
                            BUS.count("fleet.route.lane_fallback")
                try:
                    return self._workers[self._ring.assign(key)]
                except LookupError:
                    return None
            live = [
                w for w in self._workers
                if w.alive and w.ready.is_set() and not w.draining
            ]
            if not live:
                return None
            self._rr += 1
            return live[self._rr % len(live)]

    def _dispatch(
        self, p: _Pending, *, allow_shed: bool = True
    ) -> Optional[dict]:
        """Queue ``p`` on the worker owning its key. Returns ``None`` once
        accepted (a response will land on ``p.event``) or a terminal
        error/shed response dict."""
        cfg = self.config
        # The trace context to put on the wire: the caller's active one
        # (handle()'s attempt span), else the context ``p`` was first
        # dispatched under — the monitor thread's failover re-dispatch
        # path, where the contextvar is long gone.
        wire = tracing.wire_context()
        if wire is None:
            wire = p.trace
        deadline = time.monotonic() + cfg.request_timeout_s
        while True:
            if self._closed:
                return {"ok": False, "op": p.request.get("op"),
                        "error": "fleet shutting down"}
            w = self._route(p.key, lane=p.lane)
            if w is None:
                if time.monotonic() >= deadline:
                    BUS.count("fleet.unroutable")
                    return {"ok": False, "op": p.request.get("op"),
                            "error": "no live workers"}
                time.sleep(0.02)  # workers restarting; the ring will refill
                continue
            incarnation = w.incarnation
            if not w.slots.acquire(blocking=False):
                if allow_shed and p.cls in cfg.shed_classes:
                    BUS.count("fleet.shed")
                    return {"ok": False, "op": p.request.get("op"),
                            "shed": True, "worker": w.id,
                            "error": f"shed: worker {w.id} queue full"}
                # Backpressure: wait briefly, then re-check liveness (a
                # worker dying with a full queue must not wedge us here).
                if not w.slots.acquire(timeout=0.05):
                    if time.monotonic() >= deadline:
                        return {"ok": False, "op": p.request.get("op"),
                                "error": f"admission timeout on worker {w.id}"}
                    continue
            rid = None
            try:
                with w.lock:
                    if (not w.alive or w.incarnation != incarnation
                            or w.transport is None):
                        raise OSError("worker died during dispatch")
                    with self._id_lock:
                        self._next_id += 1
                        rid = self._next_id
                    w.pending[rid] = p
                    p.sent_at = time.monotonic()
                    frame = {"id": rid, "req": p.request}
                    if wire is not None and w.caps.get("trace"):
                        # Gated on the hello capability: a legacy worker
                        # without caps.trace gets the untraced frame shape
                        # it has always parsed.
                        frame["trace"] = wire
                    w.transport.send(frame)
            except OSError:
                if rid is not None:
                    with w.lock:
                        w.pending.pop(rid, None)
                self._release_slot(w)
                self._on_death(w, incarnation)
                continue
            BUS.count("fleet.dispatch")
            if SECTIONS_KEY in p.request:
                # Binary payload: the transport passed the sections
                # through opaquely (caps.wire peers — the O(header) hop)
                # or folded them to classic JSON for a legacy worker.
                BUS.count(
                    "fleet.wire.passthrough" if w.caps.get("wire")
                    else "fleet.wire.fallback_json"
                )
            BUS.sample(f"fleet.queue.depth.{w.id}", len(w.pending))
            return None

    # -- payload verification (round 19, docs/VERIFICATION.md) ----------
    @staticmethod
    def _certify_solve_response(request: dict, response: dict):
        """Certify a solve response against the request it answers —
        ``None`` when the pair carries no verifiable claim (echo fleets,
        digest-only requests, responses without ``mst_edges``), else the
        :class:`verify.certify.Certificate`. NumPy engine: the router is
        jax-free by design and the claim arrives as plain JSON anyway.
        Binary requests certify too — folding the edge sections here is
        deliberate: certification is the one router path that is ABOUT
        the edges, so it pays to decode them (forwarded hits only,
        never the passthrough dispatch)."""
        if request.get("op") != "solve" or "num_nodes" not in request:
            return None
        if "edges" not in request:
            if SECTIONS_KEY not in request:
                return None
            request = fold_sections(request)
            if "edges" not in request:
                return None
        if not isinstance(response.get("mst_edges"), list):
            return None
        from distributed_ghs_implementation_tpu.verify.certify import (
            Certificate,
            certify_claim,
        )

        # Analytics kinds certify with their own adapters (the request's
        # kind travels on the forwarding probe, so a forwarded hit is
        # verified kind-correctly). A path_max response's edge payload IS
        # the owner's MST, so it certifies as an mst claim.
        kind = str(request.get("kind", "mst"))
        if kind == "path_max":
            kind = "mst"
        try:
            return certify_claim(
                request["num_nodes"], request["edges"],
                response["mst_edges"],
                total_weight=response.get("total_weight"), engine="np",
                kind=kind,
                k=request.get("k"),
                num_components=response.get("num_components"),
                bottleneck_weight=response.get("bottleneck_weight"),
            )
        except Exception as e:  # noqa: BLE001 — a crash here would turn
            # the designed reject-and-re-solve path into an unhandled
            # error on exactly the adversarial payloads it exists for.
            return Certificate(
                ok=False, reason="malformed_claim",
                detail=f"{type(e).__name__}: {e}", engine="np",
            )

    # -- cache-miss forwarding -----------------------------------------
    def _forward_probe(
        self, request: dict, key: Optional[str], cls: Optional[str],
        lane: bool,
    ) -> Tuple[Optional[dict], bool]:
        """The cross-host affinity hop: when a solve is about to land on a
        worker that is NOT the digest's owner-of-record, ask the owner
        first with a tiny ``cached_only`` frame (digest + backend — never
        the edge list). A hit returns the owner's cached result without
        any local solve (``fleet.forward.hit``); a miss falls through to
        the normal dispatch, which solves locally
        (``fleet.forward.miss``). Returns ``(response_or_None,
        rejected)``: when the request carries its edge list the probe asks
        for the owner's MST edges too and the hit payload is CERTIFIED
        before it is served (``verify_forward``, mandatory by default) — a
        failed certificate drops the poisoned forwarding affinity, counts
        ``fleet.forward.rejected`` + ``verify.failed``, and reports
        ``rejected=True`` so the caller counts the local re-solve as
        ``verify.corrected``."""
        if key is None or request.get("op") != "solve":
            return None, False
        if request.get("cached_only"):
            return None, False  # already a probe: no recursion
        target = self._route(key, lane=lane, count=False)  # peek only
        if target is None:
            return None, False
        with self._ring_lock:
            owner = self._last_served.get(key)
            if owner is None and lane:
                # No serving history: the lane steered this dispatch away
                # from the full-ring owner — the worker affinity WOULD have
                # chosen. Ask it (the literal "ask the digest-owner
                # first"); on the first-ever solve this is a recorded miss.
                try:
                    owner = self._ring.assign(key)
                except LookupError:
                    owner = None
        if owner is None or owner == target.id:
            return None, False
        ow = self._workers[owner]
        if not (ow.alive and ow.ready.is_set() and not ow.draining):
            return None, False  # a draining owner is leaving: don't queue on it
        probe = {"op": "solve", "digest": key, "cached_only": True}
        # The query kind (and its parameters) must travel on the probe:
        # per-kind cache keys mean the owner's mst entry says nothing
        # about its components entry, and a kind-blind probe would serve
        # an MST answer to a components query (docs/ANALYTICS.md).
        kind = str(request.get("kind", "mst"))
        if kind != "mst":
            probe["kind"] = kind
            for param in ("k", "u", "v", "labels_out"):
                if param in request:
                    probe[param] = request[param]
        verifiable = (
            self.config.verify_forward
            and "edges" in request and "num_nodes" in request
        )
        if verifiable:
            # The certificate needs the claimed edge set; the probe is no
            # longer "tiny" for verifiable requests, but the response was
            # always the full result — this only sizes the hit payload.
            probe["edges_out"] = True
        if "backend" in request:
            probe["backend"] = request["backend"]
        with BUS.span("fleet.forward.probe", cat="fleet", owner=owner):
            resp = self._request_worker(
                ow, probe,
                timeout_s=min(_FORWARD_PROBE_TIMEOUT_S,
                              self.config.request_timeout_s),
                # A saturated owner (no free admission slot) is a miss,
                # not something to wait out: the probe must not queue
                # behind slow solves or starve real requests of the
                # owner's slots.
                slot_timeout_s=_FORWARD_PROBE_SLOT_TIMEOUT_S,
            )
        if resp and resp.get("ok"):
            if verifiable:
                cert = self._certify_solve_response(request, resp)
                if cert is not None and not cert.ok:
                    # The owner's payload is wrong (corrupted cache, bad
                    # link, lying peer): never serve it. Drop the
                    # affinity so the next query doesn't re-probe the
                    # same poison, and fall through to a local solve.
                    BUS.count("verify.failed")
                    BUS.count("fleet.forward.rejected")
                    BUS.instant(
                        "fleet.forward.reject", cat="fleet",
                        worker=owner, reason=cert.reason,
                    )
                    with self._ring_lock:
                        if self._last_served.get(key) == owner:
                            del self._last_served[key]
                    return None, True
                if cert is not None:
                    BUS.count("fleet.forward.verified")
            BUS.count("fleet.forward.hit")
            out = dict(resp)
            if verifiable and not request.get("edges_out"):
                out.pop("mst_edges", None)  # the probe asked, not the client
            out["forwarded_from"] = owner
            out.setdefault("worker", owner)
            if cls is not None:
                out.setdefault("slo_class", cls)
            return out, False
        BUS.count("fleet.forward.miss")
        return None, False

    # -- the service surface -------------------------------------------
    def handle(self, request: dict) -> dict:
        """One request, same contract as ``MSTService.handle``."""
        op = request.get("op")
        if op == "stats":
            return self._stats()
        if op == "shutdown":
            return {"ok": True, "op": "shutdown"}
        cls = sanitize_class(request.get("slo_class"))
        span_args = {"op": str(op)}
        if cls is not None:
            span_args["cls"] = cls
        # The fleet front door: mint (or join) the request's trace context
        # before the root span opens, so every span below — here and on
        # whichever workers the request visits — shares one trace_id.
        with tracing.front_door(cls), \
                BUS.span("fleet.request", cat="fleet", **span_args) as span:
            BUS.count("fleet.requests")
            try:
                key = self._routing_key(request)
            except Exception as e:  # noqa: BLE001 — bad request, not a crash
                BUS.count("fleet.errors")
                return {"ok": False, "op": op,
                        "error": f"{type(e).__name__}: {e}"}
            # lane preference only exists in a fleet that HAS lane
            # workers — otherwise every oversize request would probe the
            # empty lane ring and pollute the lane_fallback counter
            # (documented as the all-lane-workers-down signal).
            lane = bool(self._lane_ids) and _request_oversize(request)
            jid = None
            if self._journal is not None:
                # The accept ack is GATED on the durable append: dispatch
                # happens only after the journal fsync returns, so a
                # router crash can never lose an acknowledged query. A
                # journal that cannot append refuses the work — accepting
                # without durability would be the round-12 router again.
                try:
                    # Binary payloads journal in their folded JSON form:
                    # the journal is JSONL by schema, and a successor
                    # router must be able to re-dispatch the replayed
                    # request at ANY worker, caps.wire or not.
                    jid = self._journal.accept(
                        fold_sections(request), key=key, cls=cls, lane=lane,
                        trace=tracing.wire_context(),
                    )
                except (OSError, TimeoutError) as e:
                    BUS.count("fleet.errors")
                    span.set(ok=False)
                    err = {"ok": False, "op": op,
                           "error": f"journal append failed: {e}"}
                    if self._closed:
                        # The append lost the race with crash(): the
                        # query was never acknowledged — clients retry on
                        # the successor like any crash-window request.
                        err["router_crashed"] = True
                    return err
            corrected = False  # a verification rejection forced a re-solve
            if self.config.forward_enabled:
                forwarded, rejected = self._forward_probe(
                    request, key, cls, lane
                )
                corrected = rejected
                if forwarded is not None:
                    span.set(ok=True, worker=forwarded.get("worker"),
                             forwarded=True)
                    self._journal_answer(
                        jid, ok=True, worker=forwarded.get("worker"),
                        digest=forwarded.get("digest"),
                    )
                    return forwarded
            for attempt in (0, 1):
                p = _Pending(request, key, cls, lane=lane)
                # One attempt = dispatch + wait + certify, under its own
                # span: the worker-side spans parent to THIS attempt (the
                # wire context is captured inside it), so the merge can
                # price the transport hop as attempt-duration minus the
                # worker's in-span service time.
                with BUS.span(
                    "fleet.attempt", cat="fleet", attempt=attempt
                ) as aspan:
                    p.trace = tracing.wire_context()
                    err = self._dispatch(p)
                    if err is not None:
                        span.set(ok=False, shed=bool(err.get("shed")))
                        if not err.get("shed"):
                            BUS.count("fleet.errors")
                        if cls is not None:
                            err.setdefault("slo_class", cls)
                        if not err.get("router_crashed"):
                            # A crashed router never acknowledged failure —
                            # those accepts stay unanswered so the restart
                            # replays them.
                            self._journal_answer(jid, ok=False)
                        return err
                    if not p.event.wait(self.config.request_timeout_s):
                        BUS.count("fleet.timeout")
                        span.set(ok=False)
                        self._forget(p)
                        self._journal_answer(jid, ok=False)
                        return {"ok": False, "op": op,
                                "error": "request timed out in the fleet"}
                    response = dict(p.response)
                    aspan.set(worker=p.worker_id)
                    if (
                        attempt == 0
                        and self.config.verify_responses
                        and response.get("ok")
                    ):
                        # Round 19: certify verifiable solve responses
                        # before they leave the router — the
                        # fleet.chaos.payload net. ONE re-dispatch on
                        # failure: the worker's own copy is good
                        # (in-flight corruption) or the worker's own
                        # verification corrects it (cache corruption). The
                        # replacement is re-certified below before it
                        # earns the corrected counter — a second
                        # consecutive bad answer is systemic and is
                        # refused, never served.
                        cert = self._certify_solve_response(
                            request, response
                        )
                        if cert is not None and not cert.ok:
                            BUS.count("verify.failed")
                            BUS.count("fleet.response.rejected")
                            BUS.instant(
                                "fleet.response.reject", cat="fleet",
                                worker=p.worker_id, reason=cert.reason,
                            )
                            corrected = True
                            continue
                    break
            if corrected and response.get("ok"):
                # The replacement must EARN the corrected counter: when
                # it is verifiable, re-certify it — a second consecutive
                # bad answer (systemic corruption) is refused loudly, not
                # served while the counters read "corrected".
                recheck = self._certify_solve_response(request, response)
                if recheck is not None and not recheck.ok:
                    BUS.count("verify.failed")
                    BUS.count("verify.unrecoverable")
                    span.set(ok=False)
                    self._journal_answer(jid, ok=False)
                    err = {
                        "ok": False, "op": op,
                        "error": "result failed verification even after "
                                 f"re-dispatch ({recheck.reason}: "
                                 f"{recheck.detail}) — refusing to serve",
                    }
                    if cls is not None:
                        err["slo_class"] = cls
                    return err
                BUS.count("verify.corrected")
            span.set(ok=bool(response.get("ok")), worker=p.worker_id,
                     requeues=p.requeues)
            if not response.get("router_crashed"):
                self._journal_answer(
                    jid, ok=bool(response.get("ok")), worker=p.worker_id,
                    digest=response.get("digest"),
                )
            response.setdefault("worker", p.worker_id)
            if p.requeues:
                response.setdefault("requeued", p.requeues)
            if cls is not None:
                response.setdefault("slo_class", cls)
            return response

    def _forget(self, p: _Pending) -> None:
        """Drop a timed-out pending from whichever worker holds it."""
        for w in self._workers:
            with w.lock:
                stale = [rid for rid, q in w.pending.items() if q is p]
                for rid in stale:
                    del w.pending[rid]
            for _ in stale:
                self._release_slot(w)
            if stale:
                return

    def _request_worker(
        self, w: _Worker, request: dict, timeout_s: float = 10.0,
        slot_timeout_s: Optional[float] = None,
    ) -> Optional[dict]:
        """A control-plane request pinned to one worker (stats fan-out,
        forwarding probes). ``slot_timeout_s`` bounds the admission-slot
        wait separately (probes give up fast on a saturated worker)."""
        p = _Pending(request, None, None)
        if not w.slots.acquire(
            timeout=timeout_s if slot_timeout_s is None else slot_timeout_s
        ):
            return None
        try:
            with w.lock:
                if not w.alive or w.transport is None:
                    self._release_slot(w)
                    return None
                with self._id_lock:
                    self._next_id += 1
                    rid = self._next_id
                w.pending[rid] = p
                p.sent_at = time.monotonic()
                frame = {"id": rid, "req": request}
                wire = tracing.wire_context()
                if wire is not None and w.caps.get("trace"):
                    frame["trace"] = wire
                w.transport.send(frame)
        except OSError:
            self._release_slot(w)
            return None
        if not p.event.wait(timeout_s):
            self._forget(p)
            return None
        return p.response

    def _stats(self) -> dict:
        counters: Dict[str, float] = {}
        workers_out = {}
        for w in self._workers:
            info = {
                "alive": w.alive,
                "incarnation": w.incarnation,
                "restarts": w.restarts,
                "pending": len(w.pending),
                "lane": w.id in self._lane_ids,
                "caps": dict(w.caps),
                # The elastic pool's operator view: is this slot serving
                # warm, leaving, or gone?
                "warmed": bool(w.caps.get("warmed")),
                "draining": w.draining,
                "retired": w.retired,
            }
            if w.addr is not None:
                info["addr"] = w.addr
            if w.transport is not None:
                info["transport"] = w.transport.kind
                info["channel_writes"] = w.transport.writes
                info["channel_frames"] = w.transport.frames
            if w.alive and w.ready.is_set() and not w.draining:
                # Draining workers stop reading mid-retire: a stats
                # fan-out at them would hang until the control timeout.
                resp = self._request_worker(w, {"op": "stats"})
                if resp and resp.get("ok"):
                    info["stats"] = {
                        k: v for k, v in resp.items()
                        if k not in ("ok", "op")
                    }
                    for name, value in (resp.get("counters") or {}).items():
                        counters[name] = counters.get(name, 0) + value
            workers_out[str(w.id)] = info
        fleet_counters = {
            name: value for name, value in BUS.counters().items()
            if name.startswith("fleet.")
        }
        hop = {
            name: summary
            for name, summary in BUS.histograms().items()
            if name.startswith("fleet.hop_s")
        }
        out = {
            "ok": True,
            "op": "stats",
            "counters": counters,  # summed across live workers
            "fleet": fleet_counters,
            "workers": workers_out,
            "ring": sorted(self._ring.members()),
            "sessions": len(self._sessions),
            "transport": self.config.transport,
            "forward_cache": self.config.forward_enabled,
            # The live pool, as the autoscaler sees it: slot counts by
            # lifecycle state plus the last scale decision and its reason
            # string — "why is the fleet this size" in one stanza.
            "pool": {
                "size": self.pool_size(),
                "alive": sum(1 for w in self._workers if w.alive),
                "draining": [w.id for w in self._workers if w.draining
                             and not w.retired],
                "retired": [w.id for w in self._workers if w.retired],
                "warmed": [w.id for w in self._workers
                           if w.alive and bool(w.caps.get("warmed"))],
                "last_scale": self.last_scale_decision,
            },
        }
        if hop:
            out["router_hop_s"] = hop
        join = BUS.histograms().get("fleet.join.warm_s")
        if join and join.get("count"):
            out["join_warm_s"] = join
        if self._journal is not None:
            unanswered, next_jid = self._journal.status()
            out["journal"] = {
                "dir": self.config.journal_dir,
                "accepted": next_jid - 1,
                "unanswered": unanswered,
            }
        return out

    # -- chaos/drill surface -------------------------------------------
    def crash(self) -> None:
        """Simulate abrupt router-process death (drills). Everything a
        real crash would do to the *world* happens — channels hard-close
        without drain (``--listen`` workers return to accept with their
        caches warm), in-flight callers get an error, NOTHING more is
        journaled (a dead process appends nothing) — while the test
        process survives to boot the successor: a new
        :class:`FleetRouter` on the same ``journal_dir`` re-adopts the
        live workers and replays the orphaned accepts."""
        BUS.count("fleet.router.crash")
        self._closed = True
        if self._journal is not None:
            # Synchronous: an in-flight accept finishes its durable
            # append before this returns (its owner got a real ack);
            # everything after raises OSError — a dead process appends
            # nothing, and a late append would collide with the
            # successor's sequence numbers. The reference itself stays
            # set: nulling it would race request threads between their
            # None-check and the call (AttributeError instead of the
            # caught OSError -> router_crashed error the clients retry
            # on).
            self._journal.close()
        if self._listener is not None:
            self._listener.close()
        for w in self._workers:
            with w.lock:
                orphans = list(w.pending.values())
                w.pending.clear()
                transport = w.transport
            for p in orphans:
                p.response = {
                    "ok": False, "op": p.request.get("op"),
                    "error": "router crashed", "router_crashed": True,
                }
                p.event.set()
            if transport is not None:
                transport.close(flush=False)

    def kill_worker(self, worker_id: int) -> None:
        """SIGKILL one worker mid-traffic (drills). Failover is automatic.
        Remote workers have no process handle here — their connection is
        hard-closed instead (the same death signal a network partition
        gives)."""
        w = self._workers[worker_id]
        with w.lock:
            proc = w.proc
            transport = w.transport
        if proc is not None and proc.poll() is None:
            proc.kill()
        elif transport is not None:
            transport.close(flush=False)
        # The reader sees EOF and runs the death path; nothing else to do.

    def close_worker_connection(self, worker_id: int) -> None:
        """Hard-close one worker's channel WITHOUT killing the process
        (drills: a network partition / socket reset, distinct from a
        crash). The reader sees EOF, pending requests re-queue onto
        survivors, and the restart path re-establishes the channel."""
        w = self._workers[worker_id]
        with w.lock:
            transport = w.transport
        if transport is not None:
            transport.close(flush=False)  # a partition does not flush

    def arm_worker_fault(
        self, worker_id: int, *, site: str = "fleet.worker.crash",
        times: int = 1, kind: str = "raise", value: float = 0.0,
    ) -> bool:
        """Arm the fault registry INSIDE one worker process (kill drills:
        ``fleet.worker.crash`` makes it die in place of its ``times``-th
        next request — deterministic, mid-traffic, no response flushed)."""
        w = self._workers[worker_id]
        try:
            with w.lock:
                if not w.alive or w.transport is None:
                    return False
                w.transport.send({
                    "arm": {"site": site, "times": times, "kind": kind,
                            "value": value},
                })
            return True
        except OSError:
            return False
