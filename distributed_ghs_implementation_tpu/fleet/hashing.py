"""Consistent-hash ring: ``Graph.digest()`` -> worker id.

Why consistent hashing and not ``hash(key) % N``: worker death (and
restart-rejoin) must move only the dead worker's share of the keyspace.
With modulo routing, removing one of three workers reassigns ~2/3 of all
digests — every surviving worker's warm result cache, materialized update
sessions, and AOT-compiled buckets turn cold at exactly the moment the
fleet is degraded. On the ring, keys owned by survivors stay put (the
bounded-movement property ``tests/test_fleet.py`` pins).

Determinism is load-bearing: ring points are sha256 of ``"{member}#{i}"``
— no process-seeded ``hash()`` — so the digest->worker mapping is identical
across router restarts and across machines. A restarted fleet re-routes
every digest to the worker whose shared-disk-store entries and compile
cache it warmed last time.

Churn-safe by construction: :meth:`HashRing.add` is idempotent (a member
already on the ring gains no duplicate points — an autoscaler join racing
a restart rejoin cannot double a worker's keyspace share) and
:meth:`HashRing.remove` of an absent member is a no-op (a retire racing a
death-path removal cannot corrupt the point list). The elastic fleet
(``fleet/autoscaler.py``) adds and removes members continuously, so both
properties are pinned by the churn tests in ``tests/test_fleet.py``.
"""

from __future__ import annotations

import bisect
import hashlib
from typing import Iterable, List, Tuple


def _point(token: str) -> int:
    return int.from_bytes(
        hashlib.sha256(token.encode("utf-8")).digest()[:8], "big"
    )


class HashRing:
    """Sorted-point consistent-hash ring over small member ids."""

    def __init__(self, members: Iterable[int] = (), replicas: int = 64):
        if replicas < 1:
            raise ValueError(f"replicas must be >= 1, got {replicas}")
        self.replicas = replicas
        self._points: List[Tuple[int, int]] = []  # (point, member), sorted
        for m in members:
            self.add(m)

    def __len__(self) -> int:
        return len({m for _, m in self._points})

    def members(self) -> set:
        return {m for _, m in self._points}

    def add(self, member: int) -> None:
        if any(m == member for _, m in self._points):
            return  # idempotent under churn: never duplicate ring points
        for i in range(self.replicas):
            bisect.insort(self._points, (_point(f"{member}#{i}"), member))

    def remove(self, member: int) -> None:
        self._points = [p for p in self._points if p[1] != member]

    def assign(self, key: str) -> int:
        """The member owning ``key`` (first ring point clockwise of its
        hash). Raises ``LookupError`` on an empty ring — the caller decides
        whether that means *wait* (workers restarting) or *fail*."""
        if not self._points:
            raise LookupError("hash ring is empty (no live workers)")
        h = _point(key)
        i = bisect.bisect_right(self._points, (h, -1))
        if i == len(self._points):
            i = 0  # wrap
        return self._points[i][1]
