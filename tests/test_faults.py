"""Lossy transport + reliable delivery layer (``protocol/faults.py``).

The property under test is the one the reference's liveness heuristics
destroyed (PAPER.md): under message drop, duplication, and adversarial
reorder, the protocol must still reach exact quiescence with the oracle MST
— because the reliable sublayer restores the FIFO-reliable-link assumption
GHS is proved against. Everything is seeded and event-driven: no sleeps, no
wall clock, bit-identical replays.
"""

import numpy as np
import pytest

from distributed_ghs_implementation_tpu.graphs.generators import (
    erdos_renyi_graph,
    line_graph,
    simple_test_graph,
)
from distributed_ghs_implementation_tpu.models.boruvka import solve_graph
from distributed_ghs_implementation_tpu.protocol import (
    FaultSpec,
    FaultyTransport,
    Message,
    MessageType,
    ReliableTransport,
)
from distributed_ghs_implementation_tpu.protocol.runner import solve_graph_protocol


class _Recorder:
    """Transport-level stub node: records deliveries, never defers."""

    def __init__(self):
        self.seen = []

    def handle(self, msg):
        self.seen.append(msg)
        return True


def _blast(transport, n=200):
    """Send n distinct messages 0->1 and drain; returns delivered payloads."""
    nodes = {0: _Recorder(), 1: _Recorder()}
    for i in range(n):
        transport.send(0, 1, Message(MessageType.TEST, sender=0, fragment=i))
    transport.run(nodes)
    return [m.fragment for m in nodes[1].seen]


def test_fault_spec_validates():
    with pytest.raises(ValueError, match="probability"):
        FaultSpec(drop=1.5)
    with pytest.raises(ValueError, match="max_jitter"):
        FaultSpec(max_jitter=0)
    with pytest.raises(ValueError, match="severs"):
        ReliableTransport(FaultSpec(drop=1.0))


def test_faulty_transport_is_deterministic():
    """Same spec, same sends -> identical losses, duplicates, and order."""
    spec = FaultSpec(drop=0.3, duplicate=0.2, reorder=0.4, seed=5)
    runs = []
    for _ in range(2):
        t = FaultyTransport(spec)
        runs.append((_blast(t), t.dropped, t.duplicated, t.jittered))
    assert runs[0] == runs[1]
    delivered, dropped, duplicated, _ = runs[0]
    assert dropped > 0 and duplicated > 0
    # The raw channel really loses and repeats traffic (no reliability here).
    assert len(delivered) == 200 - dropped + duplicated


def test_faulty_transport_clean_spec_is_simtransport():
    delivered = _blast(FaultyTransport(FaultSpec()))
    assert delivered == list(range(200))


def test_reliable_layer_exactly_once_in_order():
    """20% drop + duplicates + reorder: every message once, in send order."""
    spec = FaultSpec(drop=0.2, duplicate=0.2, reorder=0.5, seed=9)
    t = ReliableTransport(spec)
    delivered = _blast(t)
    assert delivered == list(range(200))
    assert t.dropped > 0 and t.retransmits > 0 and t.dup_suppressed > 0


def test_reliable_clean_channel_never_retransmits():
    """Ack RTT (2 ticks) beats the 8-tick RTO: zero spurious retransmits."""
    t = ReliableTransport(FaultSpec())
    assert _blast(t) == list(range(200))
    assert t.retransmits == 0 and t.dropped == 0


def test_reliable_max_retries_gives_up_loudly():
    t = ReliableTransport(FaultSpec(drop=0.95, seed=3), max_retries=3)
    with pytest.raises(RuntimeError, match="gave up"):
        _blast(t, n=50)


@pytest.mark.parametrize("seed", range(4))
def test_protocol_oracle_parity_under_worst_spec(seed):
    """The acceptance bar: drop<=20%, dup<=10%, adversarial reorder -> the
    protocol quiesces with the exact device-kernel MST (weight-unique by
    rank order, so edge-id equality is the strongest possible check)."""
    g = erdos_renyi_graph(40, 0.12, seed=seed)
    ref_ids, ref_frag, _ = solve_graph(g)
    t = ReliableTransport(FaultSpec(drop=0.2, duplicate=0.1, reorder=0.3, seed=seed + 7))
    edge_ids, fragment, _levels = solve_graph_protocol(g, transport=t)
    assert np.array_equal(edge_ids, ref_ids)
    # Fragment *labels* are backend-specific; component structure must agree.
    assert np.unique(fragment).size == np.unique(ref_frag).size
    assert t.dropped > 0  # the scenario was not vacuous


def test_protocol_parity_asymmetric_latency_and_faults():
    """Faults on top of asymmetric link latencies (delivery races)."""
    g = line_graph(24)
    ref_ids, _, _ = solve_graph(g)
    t = ReliableTransport(
        FaultSpec(drop=0.3, duplicate=0.2, reorder=0.5, seed=99),
        latency=lambda s, d: 1 if s < d else 4,
    )
    edge_ids, _, _ = solve_graph_protocol(g, transport=t)
    assert np.array_equal(edge_ids, ref_ids)


def test_protocol_parity_simple_fixture_all_fault_kinds():
    g = simple_test_graph()
    expected = float(solve_graph(g)[0].shape[0])
    for spec in (
        FaultSpec(drop=0.25, seed=1),
        FaultSpec(duplicate=0.5, seed=2),
        FaultSpec(reorder=0.8, max_jitter=32, seed=3),
    ):
        t = ReliableTransport(spec)
        edge_ids, _, _ = solve_graph_protocol(g, transport=t)
        assert float(edge_ids.shape[0]) == expected


def test_reliable_stats_report_ack_latency():
    """The reliable sublayer measures first-send -> first-ack latency in sim
    ticks: bounded below by one round trip, inflated by drops (a retransmit
    must age the sample past the RTO)."""
    clean = ReliableTransport(FaultSpec())
    _blast(clean)
    lat = clean.stats["ack_latency_ticks"]
    assert lat["count"] == 200
    assert lat["mean"] == lat["max"] == 2  # symmetric 1-tick links: RTT 2

    lossy = ReliableTransport(FaultSpec(drop=0.3, seed=21))
    _blast(lossy)
    lossy_lat = lossy.stats["ack_latency_ticks"]
    assert lossy_lat["count"] == 200  # reliability: every send eventually acks
    assert lossy_lat["max"] >= 8  # a dropped DATA waits out at least one RTO


def test_reliable_runs_are_replayable():
    """(graph, spec) fully determines the run — stats and result identical."""
    g = erdos_renyi_graph(30, 0.15, seed=2)
    outs = []
    for _ in range(2):
        t = ReliableTransport(FaultSpec(drop=0.15, duplicate=0.1, reorder=0.2, seed=4))
        ids, _, _ = solve_graph_protocol(g, transport=t)
        outs.append((ids.tolist(), t.stats))
    assert outs[0] == outs[1]
