"""Fit random_road_network's parameters to a USA-road degree histogram.

VERDICT r4 item 8. Provenance, stated honestly: the two quantities of
USA-road (DIMACS ``USA-road-d.USA``) robustly known offline are
n = 23,947,347 nodes and 58,333,344 arcs => mean degree 2.436. The full
degree histogram needs the .gr file, which is not obtainable here (the
reader ``graphs/io.py:read_dimacs`` is tested and ready for it). With only
the mean known, the least-presumptive target is the MAXIMUM-ENTROPY
distribution on the road-degree support {1..5} with that mean (real road
graphs put >99% of mass on degrees <= 4-5, with genuine dead-end mass —
cul-de-sacs — at degree 1). When the real file is available, pass
``--dimacs path.gr`` and the fit targets its actual histogram instead.

Search: coarse grid over (hole_prob, axis_prob, diag_prob,
dead_end_prob) on a small lattice (the degree distribution is
size-independent), L1 distance on degree shares 0..6+ plus a mean-degree
penalty. Prints the best parameters and both histograms; ``--full`` then
builds the 23.9M-node instance with the fitted parameters, solves it on
the attached chip, verifies against the SciPy oracle, and prints a
config-5 receipt line for docs/BASELINE_RUNS.jsonl.
"""

from __future__ import annotations

import argparse
import itertools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

USA_NODES = 23_947_347
USA_ARCS = 58_333_344
USA_MEAN_DEGREE = USA_ARCS / USA_NODES  # 2.436


def maxent_target(mean: float, support=(1, 2, 3, 4, 5)) -> dict:
    """Max-entropy histogram p(d) ~ r^d on the support with the given mean
    (solve for r by bisection)."""
    d = np.asarray(support, dtype=float)

    def m(r):
        p = r ** d
        p /= p.sum()
        return float((p * d).sum())

    lo, hi = 1e-6, 1e6
    for _ in range(200):
        mid = (lo * hi) ** 0.5
        if m(mid) < mean:
            lo = mid
        else:
            hi = mid
    p = lo ** d
    p /= p.sum()
    return {int(k): float(v) for k, v in zip(support, p)}


def degree_shares(g, max_bin: int = 6) -> dict:
    deg = g.degrees()
    shares = {}
    for d in range(0, max_bin):
        shares[d] = float((deg == d).mean())
    shares[max_bin] = float((deg >= max_bin).mean())
    return shares


def fit(target: dict, *, lattice: int = 400, seed: int = 5):
    from distributed_ghs_implementation_tpu.graphs.generators import (
        random_road_network,
    )

    tvec = {d: target.get(d, 0.0) for d in range(0, 7)}
    tmean = sum(d * p for d, p in target.items())
    best = None
    grid = itertools.product(
        [0.04, 0.08, 0.12],          # hole_prob
        [0.45, 0.53, 0.61, 0.70],    # axis_prob
        [0.04, 0.12, 0.20],          # diag_prob
        [0.0, 0.1, 0.2, 0.3, 0.4],   # dead_end_prob
    )
    for hp, ap, dp, de in grid:
        g = random_road_network(
            lattice, lattice, seed=seed, hole_prob=hp, axis_prob=ap,
            diag_prob=dp, dead_end_prob=de,
        )
        s = degree_shares(g)
        mean = 2.0 * g.num_edges / g.num_nodes
        l1 = sum(abs(s[d] - tvec[d]) for d in range(0, 7))
        score = l1 + 2.0 * abs(mean - tmean)
        if best is None or score < best[0]:
            best = (score, dict(hole_prob=hp, axis_prob=ap, diag_prob=dp,
                                dead_end_prob=de), s, mean, l1)
    return best


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--dimacs", help="real USA-road .gr file (preferred target)")
    p.add_argument("--full", action="store_true",
                   help="run the fitted config at USA-road scale on the chip")
    p.add_argument("--lattice", type=int, default=400)
    args = p.parse_args()

    if args.dimacs:
        from distributed_ghs_implementation_tpu.graphs.io import read_dimacs

        g_real = read_dimacs(args.dimacs)
        target = degree_shares(g_real)
        target = {d: v for d, v in target.items() if d >= 1}
        tsrc = f"measured from {args.dimacs}"
    else:
        target = maxent_target(USA_MEAN_DEGREE)
        tsrc = ("max-entropy on {1..5} with the known mean 2.436 "
                "(full histogram needs the unobtainable .gr; see docstring)")

    score, params, achieved, mean, l1 = fit(target, lattice=args.lattice)
    out = {
        "target_source": tsrc,
        "target": {str(k): round(v, 4) for k, v in sorted(target.items())},
        "fitted_params": params,
        "achieved_shares": {str(k): round(v, 4) for k, v in achieved.items()},
        "achieved_mean_degree": round(mean, 3),
        "target_mean_degree": round(sum(d * v for d, v in target.items()), 3),
        "l1_distance": round(l1, 4),
    }
    print(json.dumps(out, indent=2), file=sys.stderr)

    if args.full:
        from distributed_ghs_implementation_tpu.api import (
            minimum_spanning_forest,
        )
        from distributed_ghs_implementation_tpu.graphs.generators import (
            random_road_network,
        )
        from distributed_ghs_implementation_tpu.models.rank_solver import (
            _pick_family,
        )
        from distributed_ghs_implementation_tpu.utils.verify import (
            verify_result,
        )

        rows, cols = 4864, 4924  # ~23.95M cells ~= USA-road's node count
        t0 = time.perf_counter()
        g = random_road_network(rows, cols, seed=8, **params)
        gen_s = time.perf_counter() - t0
        fam = _pick_family(g)
        r = minimum_spanning_forest(g)   # warm/compile
        r = minimum_spanning_forest(g)
        t0 = time.perf_counter()
        v = verify_result(r, oracle="scipy")
        oracle_s = time.perf_counter() - t0
        receipt = {
            "config": "config-5 USA-road stand-in, histogram-matched (r5)",
            "round": 5,
            "nodes": g.num_nodes, "edges": g.num_edges,
            "mean_degree": round(2.0 * g.num_edges / g.num_nodes, 3),
            "degree_shares": {str(k): round(x, 4)
                              for k, x in degree_shares(g).items()},
            "fitted_params": params,
            "family_policy": fam,
            "solve_s": round(r.wall_time_s, 2),
            "levels": r.num_levels,
            "gen_s": round(gen_s, 1), "oracle_s": round(oracle_s, 1),
            "weight": int(v.actual_weight), "verified": bool(v.ok),
            "note": ("degree histogram matched beyond mean degree: target = "
                     + tsrc),
        }
        print(json.dumps(receipt))
        return 0 if v.ok else 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
