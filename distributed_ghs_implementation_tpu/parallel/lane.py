"""ShardedLane: the mesh-sharded solve lane behind the serving scheduler.

Round 1-5 built the multichip rank-sharded solver
(``parallel/rank_sharded.py`` — the RMAT-26 / 1.05B-edge certification);
rounds 8-12 built the serving stack. They never met: ``serve``/``batch``
only drive the single-device solver, and oversize admissions just bypass
the lane engine onto the semaphore path. This module is the join — a
solve lane owning a mesh (real devices or the 8-device CPU dryrun) that
the scheduler routes oversize misses to, with the two levers that make
routing them worthwhile rather than merely possible:

* **Pre-partitioned residency** — a bounded LRU of device-resident
  graphs: the m-sized rank-endpoint arrays (``ra``/``rb``) are staged
  ONCE with ``jax.device_put`` onto the exact block sharding the solver's
  ``in_specs`` declare (``P(EDGE_AXIS)``), and the n-sized level-1 state
  rides replicated beside them. A repeat solve on a resident graph is
  dispatch-only: no host pass, no transfer, no resharding — inputs
  already match ``in_axis_resources``, so XLA moves nothing
  (``lane.reshard.skipped`` counts exactly these).
* **Donated incremental updates** — an edge insert/delete/reweight on a
  resident graph shifts a contiguous rank interval of ``ra``/``rb``.
  Instead of re-staging the full m-sized arrays from host, the changed
  slots are scattered into the resident buffers by a jitted update whose
  input buffers are DONATED on accelerators (``donate_argnums`` — the
  old device allocation is consumed in place, the SNIPPETS donation
  pattern), and the entry re-keys under the new content digest. Updates
  that dirty more than ``max_update_frac`` of the rank space fall back
  to a full restage (``lane.restage``) — the scatter would cost more
  than the transfer it avoids.

Compile accounting: the sharded programs compile under plain ``jit``
(per shape), outside the lane engine's AOT executable cache — so the
lane keeps its own first-dispatch ledger per program shape and lands the
events on the shared ``compile.*`` taxonomy: a shape first dispatched
during :meth:`ShardedLane.precompile` counts ``compile.warmup``; one
first dispatched by live traffic counts ``compile.miss``; every repeat
is ``compile.hit``. "Zero request-time compiles on the oversize path"
is therefore the same assertable property the warm path has
(``tools/serve_drill.py --sharded-smoke``).

Priority: solves accept a ``yield_fn`` called between device dispatches
(head / in-place guard levels / finish — the stepped-solve boundaries).
The serving scheduler passes its two-class gate's checkpoint there, so a
bulk mesh solve pauses between levels while interactive small-graph
traffic is pending instead of starving it (``serve/scheduler.py``).

Exactness: the lane runs the PLAIN (non-filtered) rank-sharded program —
head (levels 1-2), capacity-guard in-place levels, compact/all-gather
finish — which is edge-for-edge identical to every other backend on any
graph (the filtered split is a perf specialization the residency
contract deliberately skips: its prefix arrays would double the resident
footprint). Harvest is the single-process chunked fetch; multi-process
serving fronts each process with its own lane.
"""

from __future__ import annotations

import collections
import dataclasses
import functools
import math
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_ghs_implementation_tpu.api import MSTResult
from distributed_ghs_implementation_tpu.graphs.edgelist import Graph
from distributed_ghs_implementation_tpu.models.boruvka import (
    _bucket_size,
    _max_levels,
    _next_pow2,
)
from distributed_ghs_implementation_tpu.models.rank_solver import (
    _INT32_RANK_LIMIT,
    fetch_mst_edge_ids,
    host_level1,
)
from distributed_ghs_implementation_tpu.obs.events import BUS
from distributed_ghs_implementation_tpu.parallel.mesh import EDGE_AXIS, edge_mesh
from distributed_ghs_implementation_tpu.parallel.rank_sharded import (
    _FINISH_GATHER_MAX_SLOTS,
    make_rank_sharded_finish,
    make_rank_sharded_head,
    make_rank_sharded_level,
)
from distributed_ghs_implementation_tpu.parallel.sharded import _stage

_INT32_MAX = np.iinfo(np.int32).max

#: Default resident-graph LRU capacity. Each entry pins ~2 int32 arrays of
#: m_pad on device plus 2 of n_pad replicated per device and 2 host-side
#: m_pad copies — size to HBM, not request rate (docs/SHARDED_LANE.md).
DEFAULT_CAPACITY = 4

#: Updates dirtying more than this fraction of the rank space restage in
#: full: past it the padded scatter (index transfer + gather-scatter
#: dispatch) loses to one contiguous host->device copy.
DEFAULT_MAX_UPDATE_FRAC = 0.5

# First-dispatch ledger: one entry per compiled program shape, process
# wide (the jit caches underneath are process-wide too). Guarded because
# the scheduler may drive lanes from concurrent request threads.
_SEEN_SHAPES: set = set()
_SEEN_LOCK = threading.Lock()


def _note_dispatch(shape_key: tuple, phase: str) -> None:
    """Land a lane dispatch on the ``compile.*`` taxonomy: the first time a
    program shape is dispatched in this process it compiles (jit caches by
    shape), so first-seen counts as ``compile.warmup`` or ``compile.miss``
    by who paid; repeats are ``compile.hit``."""
    with _SEEN_LOCK:
        first = shape_key not in _SEEN_SHAPES
        if first:
            _SEEN_SHAPES.add(shape_key)
    if first:
        BUS.count("lane.compile")
        BUS.count("compile.warmup" if phase == "warmup" else "compile.miss")
    else:
        BUS.count("compile.hit")


def _reset_shape_ledger() -> None:
    """Tests simulate a process restart (pairs with clearing jit caches)."""
    with _SEEN_LOCK:
        _SEEN_SHAPES.clear()


@functools.lru_cache(maxsize=16)
def _make_scatter_update(mesh: Mesh, donate: bool):
    """Jitted in-place slot scatter for resident rank arrays.

    ``arr`` stays on its block sharding; ``idx`` is padded to a power-of-
    two bucket with the out-of-range sentinel (``mode="drop"`` discards
    the pads), so compiles are bounded by log2 of the changed-slot count.
    With ``donate`` (accelerators, no concurrent reader of the buffer)
    the resident allocation is consumed in place; the non-donating
    variant leaves the old buffers valid for an in-flight solve still
    holding them.
    """
    blk = NamedSharding(mesh, P(EDGE_AXIS))

    def upd(arr, idx, vals):
        return arr.at[idx].set(vals, mode="drop")

    kwargs = {}
    if donate and jax.default_backend() in ("tpu", "gpu"):
        kwargs["donate_argnums"] = (0,)  # donation no-ops on CPU anyway
    return jax.jit(upd, out_shardings=blk, **kwargs)


@dataclasses.dataclass
class ResidentGraph:
    """One device-resident graph: staged arrays pre-partitioned to the
    mesh layout, plus the host-side rank endpoints updates diff against."""

    digest: str
    num_nodes: int
    num_edges: int
    n_pad: int
    m_pad: int
    vmin0: jax.Array  # replicated, n_pad
    parent1: jax.Array  # replicated, n_pad
    ra: jax.Array  # block-sharded over EDGE_AXIS, m_pad
    rb: jax.Array  # block-sharded over EDGE_AXIS, m_pad
    ra_np: np.ndarray  # host copies: the delta diff base for updates
    rb_np: np.ndarray


class ShardedLane:
    """Mesh-owning solve lane with a bounded device-resident graph LRU.

    The serving-facing surface mirrors the lane engine's contract
    (``batch/engine.py``): :meth:`solve_result` /: meth:`update_result`
    return :class:`api.MSTResult`; ``admits`` is the routing predicate the
    scheduler consults. One device batch in flight at a time
    (``_dispatch`` lock) — the mesh is a single shared resource.
    """

    def __init__(
        self,
        mesh: Optional[Mesh] = None,
        *,
        capacity: int = DEFAULT_CAPACITY,
        max_update_frac: float = DEFAULT_MAX_UPDATE_FRAC,
        max_in_flight: int = 2,
        kernel: Optional[str] = None,
    ):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        if not 0.0 <= max_update_frac <= 1.0:
            raise ValueError(
                f"max_update_frac must be in [0, 1], got {max_update_frac}"
            )
        if max_in_flight < 1:
            raise ValueError(
                f"max_in_flight must be >= 1, got {max_in_flight}"
            )
        self.mesh = mesh if mesh is not None else edge_mesh()
        self.n_dev = int(self.mesh.devices.size)
        # Level-kernel variant for every program this lane dispatches
        # (head / in-place levels / finish) — resolved ONCE at construction
        # so warmup and every later solve compile the same variant
        # (docs/KERNELS.md). A Pallas failure mid-solve repins this to
        # "xla" (see the fallback in solve()) so later dispatches and the
        # retry resolve together — degraded to request-time XLA compiles
        # on first touch, never a failed solve.
        from distributed_ghs_implementation_tpu.ops.pallas_kernels import (
            kernel_choice,
        )

        # The raw request is kept so per-bucket dispatch can re-resolve
        # through the measured-auto tier (an installed TuningRecord's
        # "mesh" entries, keyed (n_pad, m_pad, n_dev, "mesh")); self.kernel
        # stays the construction-time resolution for stats and the repin.
        self._kernel_request = kernel
        self.kernel = kernel_choice(kernel)
        self.capacity = capacity
        self.max_update_frac = max_update_frac
        self._lru: "collections.OrderedDict[str, ResidentGraph]" = (
            collections.OrderedDict()
        )
        self._lock = threading.Lock()  # LRU + in-use bookkeeping
        self._dispatch = threading.Lock()  # one mesh solve in flight
        # Admission bound on lane work as a whole: dispatch is serialized,
        # but COLD STAGING happens before the dispatch lock — without this
        # semaphore, K concurrent distinct oversize misses would stage K
        # sets of m-sized device arrays at once (the LRU bounds retained
        # entries, not in-flight stagings).
        self._admit = threading.BoundedSemaphore(max_in_flight)
        # digest -> count of solves currently holding the entry's device
        # buffers (between LRU lookup and dispatch completion): an entry
        # with readers must never be DONATED out from under them.
        self._in_use: Dict[str, int] = {}
        # digest -> pin refcount: entries pinned by an open stream session
        # are not LRU-evictable (the eviction race — pressure from
        # unrelated oversize traffic must not free a streamed graph's
        # buffers mid-window). Keyed by digest, independent of residency:
        # a pin on a not-yet-staged digest is legal and arms the moment
        # the entry lands; refresh_resident moves pins along the digest
        # chain so a session's claim follows its head.
        self._pinned: Dict[str, int] = {}

    # ------------------------------------------------------------------
    # Routing predicate
    # ------------------------------------------------------------------
    def pad_shape(self, num_nodes: int, num_edges: int) -> Tuple[int, int]:
        """The padded ``(n_pad, m_pad)`` a graph stages at on this mesh —
        bucket sizes, with the rank width rounded up so every shard block
        is byte-aligned for the bit-packed harvest."""
        n_pad = _bucket_size(max(1, num_nodes))
        unit = 8 * self.n_dev
        m_pad = int(math.ceil(_bucket_size(max(1, num_edges)) / unit) * unit)
        return n_pad, m_pad

    def admits(self, graph: Graph) -> bool:
        """Can this graph run on the lane's plain sharded program? (The
        2^31+ rank regime needs the split-key program — route those
        through ``solve_graph_rank_sharded`` directly.)"""
        n_pad, m_pad = self.pad_shape(graph.num_nodes, graph.num_edges)
        return n_pad < _INT32_RANK_LIMIT and m_pad < _INT32_RANK_LIMIT

    # ------------------------------------------------------------------
    # Residency
    # ------------------------------------------------------------------
    def resident_digests(self) -> List[str]:
        with self._lock:
            return list(self._lru)

    def _get_resident(
        self, digest: str, *, checkout: bool = False
    ) -> Optional[ResidentGraph]:
        with self._lock:
            res = self._lru.get(digest)
            if res is not None:
                self._lru.move_to_end(digest)
                if checkout:
                    self._in_use[digest] = self._in_use.get(digest, 0) + 1
            return res

    def _put_resident(
        self, res: ResidentGraph, *, checkout: bool = False
    ) -> None:
        with self._lock:
            self._lru[res.digest] = res
            self._lru.move_to_end(res.digest)
            if checkout:
                self._in_use[res.digest] = (
                    self._in_use.get(res.digest, 0) + 1
                )
            while len(self._lru) > self.capacity:
                victim = next(
                    (d for d in self._lru if not self._pinned.get(d)), None
                )
                if victim is None:
                    # Every entry is pinned by an open stream session.
                    # Running over capacity beats freeing a pinned graph's
                    # buffers out from under a mid-window commit; capacity
                    # recovers on the next unpin (the counter makes the
                    # overflow visible so operators size capacity to the
                    # live stream count).
                    BUS.count("lane.resident.pin_overflow")
                    break
                self._lru.pop(victim)  # dropping refs frees HBM
                BUS.count("lane.resident.evict")

    def _release(self, digest: str) -> None:
        with self._lock:
            n = self._in_use.get(digest, 0) - 1
            if n <= 0:
                self._in_use.pop(digest, None)
            else:
                self._in_use[digest] = n

    def _pop_resident(self, digest: str) -> Tuple[Optional[ResidentGraph], bool]:
        """Remove ``digest``'s entry; also reports whether any in-flight
        solve still holds its device buffers (a busy entry's buffers must
        not be donated — the non-donating scatter leaves them valid)."""
        with self._lock:
            return (
                self._lru.pop(digest, None),
                self._in_use.get(digest, 0) > 0,
            )

    def evict(self, digest: str) -> bool:
        """Drop a resident graph from the LRU (its device buffers free once
        no in-flight dispatch holds a checkout). Returns whether it was
        resident. The next solve of that digest restages from the host.
        Explicit eviction overrides pins — it is the correctness purge
        (failed certificate, invalidated entry), not capacity pressure."""
        res, _ = self._pop_resident(digest)
        return res is not None

    # ------------------------------------------------------------------
    # Stream pinning (stream/session.py holds these for its sessions)
    # ------------------------------------------------------------------
    def pin(self, digest: str) -> bool:
        """Pin ``digest`` against LRU eviction (refcounted). An open
        stream session's head must stay device-resident across eviction
        pressure from unrelated traffic — donating its slots away
        mid-window would scatter the next commit into freed buffers.
        Returns whether the digest is currently resident."""
        with self._lock:
            self._pinned[digest] = self._pinned.get(digest, 0) + 1
            return digest in self._lru

    def unpin(self, digest: str) -> None:
        with self._lock:
            n = self._pinned.get(digest, 0) - 1
            if n <= 0:
                self._pinned.pop(digest, None)
            else:
                self._pinned[digest] = n

    def pin_count(self, digest: str) -> int:
        with self._lock:
            return self._pinned.get(digest, 0)

    def move_pins(self, old_digest: str, new_digest: str) -> None:
        """Re-key pin refcounts along the digest chain (a stream commit):
        the session that pinned the old head now answers for the new one.
        ``refresh_resident`` calls this on every outcome path, so pins
        follow the chain even when the residency itself was dropped."""
        if old_digest == new_digest:
            return
        with self._lock:
            n = self._pinned.pop(old_digest, 0)
            if n:
                self._pinned[new_digest] = (
                    self._pinned.get(new_digest, 0) + n
                )

    def ensure_resident(
        self,
        graph: Graph,
        *,
        digest: Optional[str] = None,
        pin: bool = False,
    ) -> bool:
        """Stage ``graph`` into the resident LRU WITHOUT solving — the
        stream-replay rebuild path: a restarted lane worker re-stages the
        snapshot state and lets the replayed windows re-scatter into the
        slots (``refresh_resident``), so recovery never pays a mesh
        solve. Idempotent when the digest is already resident (beyond the
        optional pin). Returns whether the graph is resident on return;
        graphs the lane cannot serve (empty, or past the rank envelope)
        return ``False`` without pinning."""
        if graph.num_nodes == 0 or graph.num_edges == 0:
            return False
        if not self.admits(graph):
            return False
        digest = digest if digest is not None else graph.digest()
        if pin:
            self.pin(digest)
        if self._get_resident(digest) is not None:
            return True
        with self._admit:
            if self._get_resident(digest) is None:
                self._put_resident(self._stage_resident(graph, digest))
                BUS.count("lane.resident.restored")
        return True

    def _stage_resident(
        self,
        graph: Graph,
        digest: str,
        pad_shape: Optional[Tuple[int, int]] = None,
    ) -> ResidentGraph:
        """Cold path: host level-1 prep + one staging pass onto the mesh
        layout the solver's ``in_specs`` declare. Everything a warm
        re-solve or donated update later skips happens here. ``pad_shape``
        overrides the graph's own padded shape (warmup stages a small
        inert graph at the TARGET bucket's shapes)."""
        n = graph.num_nodes
        n_pad, m_pad = pad_shape or self.pad_shape(n, graph.num_edges)
        with BUS.span(
            "lane.stage", cat="lane", nodes=n, edges=graph.num_edges,
            n_pad=n_pad, m_pad=m_pad, devices=self.n_dev,
        ):
            ra_np, rb_np = graph.rank_endpoints(pad_to=m_pad)
            vmin0_np = np.full(n_pad, _INT32_MAX, dtype=np.int32)
            vmin0_np[:n] = graph.first_ranks
            parent1_np = host_level1(vmin0_np, ra_np, rb_np)
            rep = NamedSharding(self.mesh, P())
            blk = NamedSharding(self.mesh, P(EDGE_AXIS))
            return ResidentGraph(
                digest=digest,
                num_nodes=n,
                num_edges=graph.num_edges,
                n_pad=n_pad,
                m_pad=m_pad,
                vmin0=_stage(vmin0_np, rep),
                parent1=_stage(parent1_np, rep),
                ra=_stage(ra_np, blk),
                rb=_stage(rb_np, blk),
                ra_np=ra_np,
                rb_np=rb_np,
            )

    # ------------------------------------------------------------------
    # Solve
    # ------------------------------------------------------------------
    def solve(
        self,
        graph: Graph,
        *,
        yield_fn: Optional[Callable[[], None]] = None,
        phase: str = "request",
    ) -> Tuple[np.ndarray, np.ndarray, int]:
        """Solve on the mesh; ``(edge_ids, fragment, levels)`` — the
        ``models.boruvka.solve_graph`` contract, edge-for-edge identical
        to every other backend. Resident graphs re-solve dispatch-only."""
        n = graph.num_nodes
        if n == 0 or graph.num_edges == 0:
            return np.zeros(0, dtype=np.int64), np.arange(n, dtype=np.int32), 0
        if not self.admits(graph):
            raise ValueError(
                "graph exceeds the lane's int32 rank envelope; use "
                "solve_graph_rank_sharded(rank64=True)"
            )
        digest = graph.digest()
        with self._admit:  # bounds stage+solve in flight, not just dispatch
            res = self._get_resident(digest, checkout=True)
            resident_hit = res is not None
            if resident_hit:
                BUS.count("lane.resident.hit")
                BUS.count("lane.reshard.skipped")
            else:
                BUS.count("lane.resident.miss")
                res = self._stage_resident(graph, digest)
                self._put_resident(res, checkout=True)
            try:
                try:
                    return self._dispatch_solve(
                        res, graph, yield_fn=yield_fn, phase=phase,
                        resident=resident_hit,
                    )
                except ValueError:
                    raise  # caller/geometry errors are never kernel faults
                except Exception as ex:  # noqa: BLE001 — kernel fallback
                    if self.kernel != "pallas":
                        raise
                    # Speculative-kernel discipline (docs/KERNELS.md): a
                    # Pallas compile/dispatch failure in the mesh programs
                    # trips the sticky process-wide fallback, repins this
                    # lane to XLA (every later dispatch — and warmup —
                    # resolves the same variant), and the SAME resident
                    # graph re-dispatches: the staged arrays are intact
                    # (the solve programs never donate them), so the
                    # retry is exact and the oversize query never fails.
                    from distributed_ghs_implementation_tpu.ops.pallas_kernels import (  # noqa: E501
                        disable_pallas,
                    )

                    disable_pallas(
                        f"sharded lane: {type(ex).__name__}: {ex}"
                    )
                    self.kernel = "xla"
                    return self._dispatch_solve(
                        res, graph, yield_fn=yield_fn, phase=phase,
                        resident=resident_hit,
                    )
            finally:
                # The checkout pins the entry's buffers against donation
                # by a concurrent refresh for the dispatch's duration.
                self._release(digest)

    def _bucket_kernel(self, n_pad: int, m_pad: int) -> str:
        """Per-bucket kernel resolution at dispatch: an installed
        TuningRecord's ``mesh`` entry — keyed ``(n_pad, m_pad, n_dev,
        "mesh")`` — can pin this bucket's measured winner; otherwise this
        resolves exactly like construction did. The sticky
        ``disable_pallas`` fallback (tripped by this lane's own repin too)
        outranks any measured Pallas winner inside ``kernel_choice``."""
        from distributed_ghs_implementation_tpu.ops.pallas_kernels import (
            kernel_choice,
        )

        return kernel_choice(
            self._kernel_request, bucket=(n_pad, m_pad, self.n_dev, "mesh")
        )

    def _dispatch_solve(
        self,
        res: ResidentGraph,
        graph: Graph,
        *,
        yield_fn: Optional[Callable[[], None]] = None,
        phase: str = "request",
        resident: bool = True,
    ) -> Tuple[np.ndarray, np.ndarray, int]:
        """The plain rank-sharded program over staged arrays: head (levels
        1-2) -> capacity-guard in-place levels -> compact/all-gather
        finish. ``yield_fn`` runs between dispatches — the stepped-solve
        boundaries the priority gate hooks."""
        mesh = self.mesh
        n_pad, m_pad = res.n_pad, res.m_pad
        kern = self._bucket_kernel(n_pad, m_pad)

        def checkpoint():
            if yield_fn is not None:
                yield_fn()

        with self._dispatch, BUS.span(
            "lane.solve", cat="lane", nodes=graph.num_nodes,
            edges=graph.num_edges, devices=self.n_dev, resident=resident,
        ) as span:
            _note_dispatch(
                ("head", n_pad, m_pad, self.n_dev, kern, mesh), phase
            )
            head = make_rank_sharded_head(mesh, kern)
            fragment, mst, fa, fb, stats = head(
                res.vmin0, res.parent1, res.ra, res.rb
            )
            lv, total, cmax = (int(x) for x in jax.device_get(stats))
            checkpoint()
            while (
                total > 0
                and self.n_dev * _bucket_size(cmax) > _FINISH_GATHER_MAX_SLOTS
            ):
                _note_dispatch(
                    ("level", n_pad, m_pad, self.n_dev, kern, mesh),
                    phase,
                )
                level_fn = make_rank_sharded_level(mesh, kernel=kern)
                fragment, mst, fa, fb, lstats = level_fn(fragment, mst, fa, fb)
                total, cmax, progressed = (
                    int(x) for x in jax.device_get(lstats)
                )
                lv += 1
                if not progressed:
                    break  # isolated remainder (disconnected pads)
                checkpoint()
            if total > 0:
                fs_local = self._finish_width(m_pad, cmax)
                max_levels = _max_levels(n_pad)
                _note_dispatch(
                    ("finish", n_pad, m_pad, fs_local, max_levels,
                     self.n_dev, kern, mesh),
                    phase,
                )
                finish = make_rank_sharded_finish(
                    mesh, fs_local, max_levels, kernel=kern
                )
                fragment, mst, extra = finish(fragment, mst, fa, fb)
                lv += int(extra)
            checkpoint()
            edge_ids = fetch_mst_edge_ids(graph, mst)
            span.set(levels=lv)
        return edge_ids, np.asarray(fragment)[: graph.num_nodes], lv

    def _finish_width(self, m_pad: int, cmax: int) -> int:
        """The finish program's compact width — pinned to the full
        shard-width bucket (capped by the gather budget) so every graph in
        a shape bucket shares ONE finish shape: :meth:`precompile` covers
        it deterministically, and no survivor set can overflow it below
        the cap. Only past the gather budget (m_pad > 2^25-class graphs,
        where the capacity-guard levels run first anyway) does the width
        fall back to the measured survivor bucket — one extra compile
        that is noise next to a solve at that scale."""
        spec = min(
            max(_bucket_size(m_pad // self.n_dev), 1024),
            _FINISH_GATHER_MAX_SLOTS // self.n_dev,
        )
        if cmax <= spec:
            return spec
        BUS.count("lane.finish.overflow")
        return max(_bucket_size(cmax), 1024)

    # ------------------------------------------------------------------
    # Donated incremental update
    # ------------------------------------------------------------------
    def refresh_resident(self, old_digest: str, new_graph: Graph) -> bool:
        """Migrate ``old_digest``'s device residency to ``new_graph``
        (the incremental-update path): the changed rank slots are
        scattered into the resident ``ra``/``rb`` buffers — DONATED on
        accelerators, so the update mutates the existing device
        allocation instead of re-staging the m-sized arrays from host —
        and the entry re-keys under the new content digest. No solve runs;
        the next solve on the new digest is dispatch-only.

        Returns ``True`` when residency now covers ``new_graph``. An
        update that changes the padded shape drops the stale entry
        (``lane.update.dropped`` — the next solve stages cold); one that
        dirties more than ``max_update_frac`` of the rank space restages
        in full (``lane.restage``) — past that the padded scatter loses
        to one contiguous host->device copy.
        """
        n = new_graph.num_nodes
        n_pad, m_pad = self.pad_shape(n, new_graph.num_edges)
        digest = new_graph.digest()
        # Pins re-key along the chain on EVERY outcome — dropped included:
        # the stream session's claim follows its head digest, and a
        # dropped residency re-stages under the new head already pinned.
        self.move_pins(old_digest, digest)
        res, busy = self._pop_resident(old_digest)
        if res is None:
            return False
        if (res.n_pad, res.m_pad) != (n_pad, m_pad) or res.num_nodes != n:
            BUS.count("lane.update.dropped")
            return False

        new_ra, new_rb = new_graph.rank_endpoints(pad_to=m_pad)
        changed = np.nonzero((new_ra != res.ra_np) | (new_rb != res.rb_np))[0]
        frac = changed.size / max(1, m_pad)
        BUS.record("lane.update.changed_frac", frac)
        if frac > self.max_update_frac:
            BUS.count("lane.restage")
            with self._admit:
                self._put_resident(self._stage_resident(new_graph, digest))
            return True

        with BUS.span(
            "lane.update", cat="lane", changed=int(changed.size),
            m_pad=m_pad, devices=self.n_dev,
        ):
            if changed.size:
                # Donate only when no in-flight solve still holds the
                # popped entry's buffers — a busy entry's solve would
                # otherwise dispatch on deleted device arrays. The
                # non-donating variant leaves the old buffers valid (the
                # reader's ref keeps them alive until it lands).
                scatter = _make_scatter_update(self.mesh, not busy)
                # 1024-slot floor: single-edge deltas share one scatter
                # shape per bucket, which precompile() warms — wider
                # deltas pay one pow2-width compile each, truthfully
                # counted compile.miss (docs/SHARDED_LANE.md).
                bucket = max(1024, _next_pow2(int(changed.size)))
                _note_dispatch(
                    ("scatter", m_pad, bucket, not busy, self.n_dev,
                     self.mesh),
                    "request",
                )
                idx = np.full(bucket, m_pad, dtype=np.int32)  # pads dropped
                idx[: changed.size] = changed
                vra = np.zeros(bucket, dtype=np.int32)
                vrb = np.zeros(bucket, dtype=np.int32)
                vra[: changed.size] = new_ra[changed]
                vrb[: changed.size] = new_rb[changed]
                with self._dispatch:
                    ra = scatter(res.ra, idx, vra)
                    rb = scatter(res.rb, idx, vrb)
            else:
                ra, rb = res.ra, res.rb
            # The n-sized level-1 state re-derives on host (two O(n)-ish
            # passes) and restages replicated — small next to the m-sized
            # transfer the scatter just avoided.
            vmin0_np = np.full(n_pad, _INT32_MAX, dtype=np.int32)
            vmin0_np[:n] = new_graph.first_ranks
            parent1_np = host_level1(vmin0_np, new_ra, new_rb)
            rep = NamedSharding(self.mesh, P())
            fresh = ResidentGraph(
                digest=digest,
                num_nodes=n,
                num_edges=new_graph.num_edges,
                n_pad=n_pad,
                m_pad=m_pad,
                vmin0=_stage(vmin0_np, rep),
                parent1=_stage(parent1_np, rep),
                ra=ra,
                rb=rb,
                ra_np=new_ra,
                rb_np=new_rb,
            )
        self._put_resident(fresh)
        BUS.count("lane.update.donated")
        return True

    def update(
        self,
        old_digest: str,
        new_graph: Graph,
        *,
        yield_fn: Optional[Callable[[], None]] = None,
    ) -> Tuple[np.ndarray, np.ndarray, int]:
        """Re-solve ``new_graph`` reusing ``old_digest``'s residency
        through the donated-buffer refresh, then the normal (now
        dispatch-only) solve path."""
        self.refresh_resident(old_digest, new_graph)
        return self.solve(new_graph, yield_fn=yield_fn)

    # ------------------------------------------------------------------
    # MSTResult surface (what the serving scheduler consumes)
    # ------------------------------------------------------------------
    def solve_result(
        self, graph: Graph, *, yield_fn: Optional[Callable[[], None]] = None
    ) -> MSTResult:
        t0 = time.perf_counter()
        edge_ids, fragment, levels = self.solve(graph, yield_fn=yield_fn)
        return self._wrap(graph, edge_ids, fragment, levels, t0)

    def update_result(
        self,
        old_digest: str,
        new_graph: Graph,
        *,
        yield_fn: Optional[Callable[[], None]] = None,
    ) -> MSTResult:
        t0 = time.perf_counter()
        edge_ids, fragment, levels = self.update(
            old_digest, new_graph, yield_fn=yield_fn
        )
        return self._wrap(new_graph, edge_ids, fragment, levels, t0)

    @staticmethod
    def _wrap(graph, edge_ids, fragment, levels, t0) -> MSTResult:
        return MSTResult(
            graph=graph,
            edge_ids=edge_ids,
            num_levels=levels,
            wall_time_s=time.perf_counter() - t0,
            backend="sharded_lane",
            num_components=(
                int(np.unique(fragment).size) if graph.num_nodes else 0
            ),
        )

    # ------------------------------------------------------------------
    # Warmup
    # ------------------------------------------------------------------
    def precompile(self, num_nodes: int, num_edges: int) -> dict:
        """Warm one mesh-shaped bucket ahead of traffic: solve an inert
        high-diameter graph padded into the bucket through the exact
        request path, so the head/finish programs compile now (counted
        ``compile.warmup``) and the bucket's first real query hits the jit
        cache. The warm graph is never put in the LRU — warming must not
        consume residency capacity. Returns a small report dict.

        Coverage is deterministic below the gather budget:
        :meth:`_finish_width` pins the finish's compact width per shape
        bucket, so the warm graph and every real graph in the bucket
        share ONE finish program. Only past the budget (``m_pad > 2^25``-
        class graphs) does the width fall back to the measured survivor
        bucket and possibly pay one request-time compile
        (docs/SHARDED_LANE.md "Warmup coverage").
        """
        n_pad, m_pad = self.pad_shape(num_nodes, num_edges)
        # The warm graph must SURVIVE the head with alive edges or the
        # finish program stays cold (a monotone-weight path chains all its
        # level-1 hooks and merges completely). A path whose weights cycle
        # [1, 100, 1, 50] pairs up locally instead: after levels 1-2 the
        # fragments are short runs with the 100-edges still crossing, so
        # the finish compiles on the warmup clock.
        k = int(min(num_nodes, 32))
        if k < 2 or num_edges < k - 1:
            k = max(2, min(num_nodes, num_edges + 1))
        cycle = (1, 100, 1, 50)
        warm = Graph.from_edges(
            num_nodes,
            [(i, i + 1, cycle[i % 4] * (i + 1)) for i in range(k - 1)],
        )
        # Staged at the TARGET bucket's padded shapes — the compile keys
        # are the padded array shapes, not the warm graph's own sizes.
        res = self._stage_resident(
            warm, warm.digest(), pad_shape=(n_pad, m_pad)
        )
        try:
            self._dispatch_solve(res, warm, phase="warmup", resident=False)
        except ValueError:
            raise  # caller/geometry errors are never kernel faults
        except Exception as ex:  # noqa: BLE001 — kernel fallback
            if self.kernel != "pallas":
                raise
            # Same repin as solve(): a Pallas failure during mesh warmup
            # must degrade the lane to XLA, not kill boot (docs/KERNELS.md).
            from distributed_ghs_implementation_tpu.ops.pallas_kernels import (
                disable_pallas,
            )

            disable_pallas(
                f"sharded lane warmup: {type(ex).__name__}: {ex}"
            )
            self.kernel = "xla"
            self._dispatch_solve(res, warm, phase="warmup", resident=False)
        # Warm the donated-update scatter at its floor width too: a
        # single-edge update on this bucket then compiles nothing. The
        # warm entry is being discarded, so donation consuming its
        # buffers is fine.
        scatter = _make_scatter_update(self.mesh, True)
        _note_dispatch(
            ("scatter", m_pad, 1024, True, self.n_dev, self.mesh), "warmup"
        )
        idx = np.full(1024, m_pad, dtype=np.int32)  # all pads: a no-op write
        with self._dispatch:
            scatter(res.ra, idx, np.zeros(1024, dtype=np.int32))
        return {
            "bucket": (n_pad, m_pad),
            "devices": self.n_dev,
            "kernel": self.kernel,
        }
