"""The verify/ layer: certificate checker vs the NetworkX oracle (seeded
random + RMAT graphs), adversarial mutations rejected with the RIGHT
reason, engine agreement (NumPy vs jitted XLA), the off|sample|full
policy, the async auditor, and the service-level transparent correction
path."""

import numpy as np
import networkx as nx
import pytest

from distributed_ghs_implementation_tpu.api import minimum_spanning_forest
from distributed_ghs_implementation_tpu.graphs.generators import (
    gnm_random_graph,
    line_graph,
    rmat_graph,
)
from distributed_ghs_implementation_tpu.obs.events import BUS
from distributed_ghs_implementation_tpu.verify.certify import (
    certify_claim,
    certify_edge_ids,
    certify_result,
)
from distributed_ghs_implementation_tpu.verify.policy import (
    AsyncAuditor,
    VerifyPolicy,
)


@pytest.fixture(autouse=True)
def _clean_bus():
    BUS.enable()
    BUS.clear()
    yield
    BUS.clear()


def _ranks(g):
    order = np.argsort(g.w, kind="stable")
    rank = np.empty(g.num_edges, dtype=np.int64)
    rank[order] = np.arange(g.num_edges)
    return rank


def _edges_of(g):
    return [[int(a), int(b), int(c)] for a, b, c in zip(g.u, g.v, g.w)]


# ----------------------------------------------------------------------
# Oracle parity: a passing certificate == the NetworkX-exact MSF
# ----------------------------------------------------------------------
@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_certificate_accepts_true_msf_and_matches_oracle(seed):
    g = gnm_random_graph(180, 560, seed=seed)
    r = minimum_spanning_forest(g, backend="host")
    cert = certify_result(r, engine="np")
    assert cert.ok and cert.reason is None
    # The oracle cross-check: certificate acceptance must coincide with
    # NetworkX weight parity (MSF weight is unique).
    oracle = nx.minimum_spanning_tree(g.to_networkx())
    assert r.total_weight == sum(
        d["weight"] for _, _, d in oracle.edges(data=True)
    )


@pytest.mark.parametrize("scale", [8, 10])
def test_certificate_on_rmat_graphs_both_engines(scale):
    g = rmat_graph(scale, 8, seed=scale)
    r = minimum_spanning_forest(g, backend="host")
    for engine in ("np", "xla"):
        cert = certify_result(r, engine=engine)
        assert cert.ok, (engine, cert.summary())
        assert cert.graph_components == r.num_components


def test_certificate_deep_path_graph():
    # A line graph's MST is the graph itself: maximum depth per vertex —
    # the pointer-doubling depth build and log-depth lifting must hold.
    g = line_graph(4096)
    r = minimum_spanning_forest(g, backend="host")
    for engine in ("np", "xla"):
        assert certify_result(r, engine=engine).ok


def test_empty_and_edgeless_graphs():
    from distributed_ghs_implementation_tpu.graphs.edgelist import Graph

    g = Graph.from_edges(5, [])
    cert = certify_edge_ids(g, np.zeros(0, dtype=np.int64), engine="np")
    assert cert.ok and cert.graph_components == 5


# ----------------------------------------------------------------------
# Adversarial mutations: rejected, each with the RIGHT reason
# ----------------------------------------------------------------------
def _swap_for_heavier(g, ids):
    """Replace one tree edge with a heavier non-tree edge closing the
    same cycle: still a spanning forest, no longer minimal."""
    rank = _ranks(g)
    in_tree = np.zeros(g.num_edges, dtype=bool)
    in_tree[ids] = True
    T = nx.Graph()
    T.add_nodes_from(range(g.num_nodes))
    for i in ids:
        T.add_edge(int(g.u[i]), int(g.v[i]), eid=int(i))
    for e in np.nonzero(~in_tree)[0]:
        a, b = int(g.u[e]), int(g.v[e])
        if not nx.has_path(T, a, b):
            continue
        path = nx.shortest_path(T, a, b)
        on_path = [T[x][y]["eid"] for x, y in zip(path, path[1:])]
        drop = max(on_path, key=lambda i: rank[i])
        if rank[e] > rank[drop]:
            out = ids.copy()
            out[np.nonzero(ids == drop)[0][0]] = e
            return out
    return None


@pytest.mark.parametrize("seed", [5, 6, 7])
def test_swapped_heavier_edge_rejected_as_not_minimal(seed):
    g = gnm_random_graph(150, 520, seed=seed)
    r = minimum_spanning_forest(g, backend="host")
    ids = np.asarray(r.edge_ids).copy()
    mutated = _swap_for_heavier(g, ids)
    assert mutated is not None
    for engine in ("np", "xla"):
        cert = certify_edge_ids(g, mutated, engine=engine)
        assert not cert.ok and cert.reason == "not_minimal", cert.summary()
        assert cert.violations >= 1


def test_duplicate_edge_rejected_as_bad_edge_ids():
    g = gnm_random_graph(80, 220, seed=9)
    r = minimum_spanning_forest(g, backend="host")
    ids = np.asarray(r.edge_ids).copy()
    ids[0] = ids[1]
    cert = certify_edge_ids(g, ids, engine="np")
    assert cert.reason == "bad_edge_ids"
    out_of_range = np.asarray(r.edge_ids).copy()
    out_of_range[0] = g.num_edges + 3
    assert certify_edge_ids(g, out_of_range).reason == "bad_edge_ids"


def test_dropped_component_rejected_as_not_spanning():
    # Two disjoint communities; drop every tree edge of the second.
    a = gnm_random_graph(40, 90, seed=11)
    edges = _edges_of(a)
    edges += [[40 + int(u), 40 + int(v), int(w) + 1]
              for u, v, w in _edges_of(gnm_random_graph(30, 70, seed=12))]
    from distributed_ghs_implementation_tpu.graphs.edgelist import Graph

    g = Graph.from_edges(70, edges)
    r = minimum_spanning_forest(g, backend="host")
    ids = np.asarray(r.edge_ids)
    keep = ids[(g.u[ids] < 40) & (g.v[ids] < 40)]
    assert keep.size < ids.size
    cert = certify_edge_ids(g, keep, engine="np")
    assert cert.reason == "not_spanning", cert.summary()


def test_extra_edge_rejected_as_cycle():
    g = gnm_random_graph(80, 220, seed=13)
    r = minimum_spanning_forest(g, backend="host")
    ids = np.asarray(r.edge_ids)
    in_tree = np.zeros(g.num_edges, dtype=bool)
    in_tree[ids] = True
    extra = np.nonzero(~in_tree)[0][:1]
    cert = certify_edge_ids(g, np.concatenate([ids, extra]), engine="np")
    assert cert.reason == "cycle", cert.summary()


def test_metadata_mismatch_rejected():
    g = gnm_random_graph(60, 160, seed=14)
    r = minimum_spanning_forest(g, backend="host")
    cert = certify_edge_ids(
        g, r.edge_ids, engine="np",
        expect_components=r.num_components + 1,
    )
    assert cert.reason == "metadata_mismatch"


def test_claim_form_unknown_edge_and_weight_mismatch():
    g = gnm_random_graph(64, 180, seed=15)
    r = minimum_spanning_forest(g, backend="host")
    edges = _edges_of(g)
    mst_edges = [[int(a), int(b)] for a, b in r.edges]
    assert certify_claim(
        64, edges, mst_edges, total_weight=r.total_weight
    ).ok
    assert certify_claim(
        64, edges, mst_edges, total_weight=r.total_weight + 1
    ).reason == "weight_mismatch"
    assert certify_claim(
        64, edges, [[0, 0]] + mst_edges[1:]
    ).reason == "unknown_edge"
    not_an_edge = mst_edges[:]
    # A vertex pair that is (virtually certainly) not an input edge.
    pairs = {(int(a), int(b)) for a, b in zip(g.u, g.v)}
    for u in range(64):
        for v in range(u + 1, 64):
            if (u, v) not in pairs:
                not_an_edge[0] = [u, v]
                break
        else:
            continue
        break
    assert certify_claim(64, edges, not_an_edge).reason in (
        "unknown_edge", "cycle", "not_minimal", "not_spanning",
    )


# ----------------------------------------------------------------------
# Engine agreement
# ----------------------------------------------------------------------
def test_engines_agree_verdict_for_verdict():
    for seed in range(6):
        g = gnm_random_graph(100, 300, seed=40 + seed)
        r = minimum_spanning_forest(g, backend="host")
        ids = np.asarray(r.edge_ids).copy()
        cases = [ids]
        mutated = _swap_for_heavier(g, ids)
        if mutated is not None:
            cases.append(mutated)
        for case in cases:
            a = certify_edge_ids(g, case, engine="np")
            b = certify_edge_ids(g, case, engine="xla")
            assert (a.ok, a.reason, a.violations) == (
                b.ok, b.reason, b.violations
            )


# ----------------------------------------------------------------------
# Policy + auditor
# ----------------------------------------------------------------------
def test_policy_parse_specs():
    p = VerifyPolicy.parse("full")
    assert p.default == "full" and p.enabled
    p = VerifyPolicy.parse("bulk=full,interactive=sample,default=off")
    assert p.mode_for("bulk") == "full"
    assert p.mode_for("interactive") == "sample"
    assert p.mode_for("anything") == "off"
    p = VerifyPolicy.parse("sample:4")
    assert p.default == "sample" and p.sample_every == 4
    assert not VerifyPolicy.parse(None).enabled
    assert not VerifyPolicy.parse("off").enabled
    with pytest.raises(ValueError):
        VerifyPolicy.parse("bogus-mode")
    assert VerifyPolicy.parse(p) is p  # pass-through


def test_policy_sampling_is_deterministic_per_class():
    p = VerifyPolicy.parse("sample:3")
    hits = [p.should_sample("a") for _ in range(7)]
    assert hits == [True, False, False, True, False, False, True]
    # Independent counters per class.
    assert p.should_sample("b") is True


def test_auditor_failure_callback_and_counters():
    g = gnm_random_graph(60, 150, seed=21)
    r = minimum_spanning_forest(g, backend="host")
    bad = minimum_spanning_forest(g, backend="host")
    bad.edge_ids[0] = bad.edge_ids[1]
    failures = []
    auditor = AsyncAuditor(
        engine="np",
        on_failure=lambda result, cert, cls, key: failures.append(
            (cert.reason, cls, key)
        ),
    )
    assert auditor.submit(r, cls="x", key="k1")
    assert auditor.submit(bad, cls="y", key="k2")
    assert auditor.flush()
    counters = BUS.counters()
    assert counters.get("verify.audit.ok") == 1
    assert counters.get("verify.audit.failed") == 1
    assert failures == [("bad_edge_ids", "y", "k2")]


# ----------------------------------------------------------------------
# Service-level transparent correction (the serving contract)
# ----------------------------------------------------------------------
def test_service_corrects_corrupted_cached_result():
    from distributed_ghs_implementation_tpu.serve.service import MSTService

    svc = MSTService(verify="full", backend="host")
    g = gnm_random_graph(64, 180, seed=7)
    req = {"op": "solve", "num_nodes": g.num_nodes,
           "edges": _edges_of(g), "slo_class": "bulk"}
    first = svc.handle(req)
    assert first["ok"] and first["verified"] == "full"
    # Corrupt the cached result in place — the miscompiled-kernel /
    # flipped-RAM stand-in nothing below a certificate can see.
    key = next(iter(svc.store._mem))
    svc.store._mem[key].edge_ids[0] = svc.store._mem[key].edge_ids[1]
    second = svc.handle(req)
    assert second["ok"] and second["total_weight"] == first["total_weight"]
    counters = BUS.counters()
    assert counters.get("verify.failed") == 1
    assert counters.get("verify.corrected") == 1
    assert counters.get("serve.store.invalidated") == 1


def test_service_off_mode_never_checks():
    from distributed_ghs_implementation_tpu.serve.service import MSTService

    svc = MSTService(backend="host")  # no verify kwarg at all
    assert svc.verifier is None
    g = gnm_random_graph(48, 120, seed=8)
    resp = svc.handle({"op": "solve", "num_nodes": g.num_nodes,
                       "edges": _edges_of(g)})
    assert resp["ok"] and "verified" not in resp
    assert "verify.checks" not in BUS.counters()


def test_stream_replay_divergence_falls_back_to_fresh_solve(tmp_path):
    """A WAL window whose updates were tampered (legacy line, no crc)
    diverges replay: the recovered session must be rebuilt by ONE fresh
    solve instead of serving the unvouched-for maintained forest."""
    import json

    from distributed_ghs_implementation_tpu.stream.log import UpdateLog
    from distributed_ghs_implementation_tpu.stream.session import (
        StreamManager,
    )

    root = str(tmp_path)
    solves = []

    def solver(graph):
        solves.append(graph.num_edges)
        return minimum_spanning_forest(graph, backend="host")

    mgr = StreamManager(root=root, snapshot_every=100, backend="host",
                        solver=solver)
    g = gnm_random_graph(64, 180, seed=31)
    seed_result = minimum_spanning_forest(g, backend="host")
    session = mgr.subscribe(digest=g.digest(), result=seed_result)
    mgr.publish(session.id, session.head,
                [{"kind": "insert", "u": 0, "v": 63, "w": 1}])
    # Tamper the committed window's updates on disk (drop the crc so the
    # line still parses — the legacy-corruption shape the chain digest
    # check must catch).
    log = UpdateLog(root, session.id)
    with open(log.wal_path) as f:
        lines = [json.loads(ln) for ln in f.read().splitlines() if ln]
    lines[-1]["updates"] = [{"kind": "insert", "u": 0, "v": 62, "w": 2}]
    for ln in lines:
        ln.pop("crc", None)
    with open(log.wal_path, "w") as f:
        for ln in lines:
            f.write(json.dumps(ln) + "\n")
    BUS.clear()
    solves.clear()
    fresh_mgr = StreamManager(root=root, snapshot_every=100, backend="host",
                              solver=solver)
    recovered = fresh_mgr.recover(session.id)
    assert recovered is not None
    counters = BUS.counters()
    assert counters.get("stream.replay.diverged") == 1
    assert counters.get("stream.replay.fresh_solve") == 1
    assert len(solves) == 1  # exactly ONE corrective solve
    # The fallback session serves a certified-fresh forest for whatever
    # graph the durable log actually rebuilt.
    assert certify_result(recovered.mst.result(), engine="np").ok
