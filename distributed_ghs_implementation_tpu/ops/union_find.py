"""Hook-and-compress union-find: fragment merging as parallel pointer ops.

The reference merges fragments with a CONNECT/INITIATE/CHANGEROOT message walk
(``/root/reference/ghs_implementation.py:155-199,355-387``) and fights
symmetric-merge races with dedup lists and sleeps
(``ghs_implementation_mpi.py:217-230``). In the batched formulation each
fragment *hooks* onto the fragment across its minimum outgoing edge; because
every fragment picks its MOE by a shared total order (weight, then undirected
edge id — see ``segment_ops``), the hook graph's only cycles are mutual pairs,
which are broken deterministically (smaller id becomes the root). Pointer
jumping then compresses every tree to a star in ``O(log depth)`` parallel
steps — the reference's sequential CHANGEROOT root walk, made log-depth (the
high-diameter answer demanded by SURVEY.md §5's long-context analog).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def break_symmetric_hooks(parent: jax.Array) -> jax.Array:
    """Resolve mutual hooks ``f <-> g``: the smaller id becomes a self-root.

    This is the deterministic replacement for the reference's symmetric-CONNECT
    merge negotiation (``ghs_implementation_mpi.py:232-287``, where a
    ``(fragment_id, rank)`` priority decides the initiator).
    """
    ids = jnp.arange(parent.shape[0], dtype=parent.dtype)
    mutual = parent[parent] == ids
    return jnp.where(mutual & (ids < parent), ids, parent)


def pointer_jump(parent: jax.Array, *, num_iters: int | None = None) -> jax.Array:
    """Compress a hook forest to stars: ``parent[f]`` becomes f's root.

    Runs to fixpoint with early exit — hook chains are usually O(1) deep, so
    this typically costs 2-4 n-sized gathers instead of the worst-case
    ``ceil(log2 n)`` (each jump doubles pointer reach, so the bound holds for
    any forest). Pass ``num_iters`` to force a fixed-trip loop instead.
    """
    if num_iters is not None:

        def body(_, p):
            return p[p]

        return jax.lax.fori_loop(0, num_iters, body, parent)

    def cond(state):
        p, changed = state
        return changed

    def step(state):
        p, _ = state
        p2 = p[p]
        return p2, jnp.any(p2 != p)

    out, _ = jax.lax.while_loop(cond, step, (parent, jnp.ones((), bool)))
    return out


def hook_and_compress(
    has_moe: jax.Array,
    moe_dst_frag: jax.Array,
    fragment: jax.Array,
    *,
    kernel: str = "xla",
) -> tuple[jax.Array, jax.Array]:
    """One merge round: hook every active fragment, compress, relabel vertices.

    Returns ``(new_fragment, parent_star)``: the relabeled per-vertex fragment
    array, and the compressed old-root -> new-root map (useful for relabeling
    other root-id-valued arrays). Fragments with no outgoing edge (isolated
    components — the root-termination case, ``ghs_implementation.py:316-320``)
    self-hook and are left untouched.

    ``kernel="pallas"`` routes through the fused Pallas kernel
    (``ops.pallas_kernels.fused_hook_compress``): symmetric break, bounded
    pointer jumping, and the relabel gather run in one VMEM-resident pass
    with no intermediate parent arrays in HBM. Geometries past the VMEM
    guard take this XLA form regardless; results are identical either way.
    """
    n = fragment.shape[0]
    if kernel == "pallas":
        from distributed_ghs_implementation_tpu.ops import pallas_kernels as pk

        if pk.hook_shape_ok(n):
            return pk.fused_hook_compress(has_moe, moe_dst_frag, fragment)
    ids = jnp.arange(n, dtype=fragment.dtype)
    parent = jnp.where(has_moe, moe_dst_frag, ids)
    parent = break_symmetric_hooks(parent)
    parent = pointer_jump(parent)
    return parent[fragment], parent
