"""Candidate enumeration: the autotuner's per-bucket search space.

A **candidate** is a kernel variant plus the geometry it traces under:
``("xla", default)`` — the always-valid reference — or ``("pallas", g)``
for every :class:`~..ops.pallas_kernels.KernelGeometry` in the knob grid
that passes the same trace-time guards the request path applies. Guards
are *hard validity filters*: a geometry whose fragment table cannot be
VMEM-resident for the bucket, or whose flat slots are off the 128-lane
grid, is not "slow", it is not a Pallas candidate at all (the wrapper
would silently route to the XLA form, so measuring it would measure the
wrong thing).

Which knobs vary depends on the bucket's mode, because each solver path
touches a different kernel:

* lane buckets (``fused`` / ``vmap``) and mesh buckets run the flat iota
  solve — ``fused_gather_key`` (``flat_block_rows``) and
  ``fused_hook_compress`` (``hook_max_nodes``);
* ``ell`` buckets run the degree-bucketed search — ``ell_block_elems``
  and ``hook_max_nodes``.

Enumeration is pure and deterministic (sorted grids, no clocks, no
randomness): two hosts with the same bucket list derive the same
candidate lists, which is half of what makes ``cli tune --dry`` byte-
reproducible (the other half is the CPU pin in ``tune/measure.py``).
"""

from __future__ import annotations

import dataclasses
from typing import List, Tuple

from distributed_ghs_implementation_tpu.ops.pallas_kernels import (
    DEFAULT_GEOMETRY,
    KernelGeometry,
    flat_shape_ok,
)

#: The knob grids. Small on purpose: the search multiplies per bucket,
#: and each Pallas candidate costs a parity solve before it may be timed.
FLAT_ROW_CHOICES: Tuple[int, ...] = (128, 256, 512)
ELL_BLOCK_CHOICES: Tuple[int, ...] = (1 << 14, 1 << 15, 1 << 16)
HOOK_NODE_CHOICES: Tuple[int, ...] = (1 << 18, 1 << 19)

#: Bucket modes the tuner understands. ``mesh`` is the sharded lane's
#: per-bucket key space (lanes field carries the device count).
VALID_MODES = ("fused", "vmap", "ell", "mesh")


@dataclasses.dataclass(frozen=True)
class Candidate:
    """One point of the search space: a kernel and its trace geometry."""

    kernel: str  # "pallas" | "xla"
    geometry: KernelGeometry = DEFAULT_GEOMETRY

    def label(self) -> str:
        """Stable human/obs label (also the dedup key in records)."""
        if self.kernel == "xla":
            return "xla"
        g = self.geometry
        return (
            f"pallas/ell{g.ell_block_elems}"
            f"/flat{g.flat_block_rows}/hook{g.hook_max_nodes}"
        )

    def to_json(self) -> dict:
        return {"kernel": self.kernel, "geometry": self.geometry.to_json()}


def _bucket_extent(
    n_pad: int, m_pad: int, lanes: int, mode: str
) -> Tuple[int, int]:
    """``(total_nodes, total_slots)`` the kernels actually see for a
    bucket — fused lanes stack block-diagonally into one big graph, vmap
    and mesh keep per-lane / per-device shapes."""
    k = max(1, lanes)
    if mode == "fused":
        return k * n_pad, k * 2 * m_pad
    return n_pad, 2 * m_pad


def candidate_valid(
    geom: KernelGeometry, n_pad: int, m_pad: int, lanes: int, mode: str
) -> bool:
    """Would a Pallas trace under ``geom`` actually take the fused path
    for this bucket? The request path's own guards, applied up front."""
    total_nodes, total_slots = _bucket_extent(n_pad, m_pad, lanes, mode)
    if mode == "ell":
        # ELL row geometry is data-dependent (degree buckets); the table
        # residency bound is the shape-independent hard gate.
        return 0 < total_nodes <= geom.table_max_elems
    return flat_shape_ok(total_nodes, total_slots, geom)


def raw_space_size(mode: str) -> int:
    """Grid size before validity filtering (the denominator for
    ``tune.search.rejected`` accounting)."""
    if mode == "ell":
        return 1 + len(ELL_BLOCK_CHOICES) * len(HOOK_NODE_CHOICES)
    return 1 + len(FLAT_ROW_CHOICES) * len(HOOK_NODE_CHOICES)


def enumerate_candidates(
    n_pad: int, m_pad: int, lanes: int, mode: str
) -> List[Candidate]:
    """The valid candidates for one solver bucket, deterministic order:
    the XLA reference first, then the Pallas grid (sorted knob order)."""
    if mode not in VALID_MODES:
        raise ValueError(
            f"unknown tune bucket mode {mode!r}; expected one of "
            f"{VALID_MODES}"
        )
    if n_pad < 1 or m_pad < 1 or lanes < 0:
        raise ValueError(
            f"bad tune bucket ({n_pad}, {m_pad}, {lanes}, {mode!r}): "
            "sizes must be positive, lanes non-negative"
        )
    out: List[Candidate] = [Candidate("xla")]
    if mode == "ell":
        for ell in ELL_BLOCK_CHOICES:
            for hook in HOOK_NODE_CHOICES:
                geom = KernelGeometry(
                    ell_block_elems=ell, hook_max_nodes=hook
                )
                if candidate_valid(geom, n_pad, m_pad, lanes, mode):
                    out.append(Candidate("pallas", geom))
        return out
    for rows in FLAT_ROW_CHOICES:
        for hook in HOOK_NODE_CHOICES:
            geom = KernelGeometry(flat_block_rows=rows, hook_max_nodes=hook)
            if candidate_valid(geom, n_pad, m_pad, lanes, mode):
                out.append(Candidate("pallas", geom))
    return out
