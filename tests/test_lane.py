"""Sharded big-graph lane (parallel/lane.py) + oversize serve routing.

The round-13 acceptance in code: an oversize query through the serving
stack executes on the mesh (8-virtual-device dryrun here) edge-for-edge
equal to the single-device solver; a repeat solve / incremental update on
a resident graph performs no host re-staging or resharding (asserted via
the ``lane.*`` obs counters); and interactive traffic is protected from
bulk solves by the scheduler's two-class priority gate.
"""

import threading
import time

import numpy as np
import pytest

from distributed_ghs_implementation_tpu.api import minimum_spanning_forest
from distributed_ghs_implementation_tpu.graphs.edgelist import Graph
from distributed_ghs_implementation_tpu.graphs.generators import (
    gnm_random_graph,
)
from distributed_ghs_implementation_tpu.obs.events import BUS
from distributed_ghs_implementation_tpu.parallel.lane import (
    ShardedLane,
    _reset_shape_ledger,
)
from distributed_ghs_implementation_tpu.utils.verify import verify_result

# Oversize by NODE bucket (2^16 < 70000's bucket) with few edges: routes
# like a billion-edge graph, solves in test time.
OVERSIZE_NODES = 70_000
OVERSIZE_EDGES = 3_000


def _edges(g):
    return [[int(a), int(b), int(c)] for a, b, c in zip(g.u, g.v, g.w)]


def _oversize_graph(seed):
    return gnm_random_graph(OVERSIZE_NODES, OVERSIZE_EDGES, seed=seed)


@pytest.fixture(autouse=True)
def _bus():
    BUS.enable()
    BUS.clear()
    yield


def _lane_solve_spans():
    return sum(1 for e in BUS.events() if e[1] == "lane.solve")


# ----------------------------------------------------------------------
# Parity
# ----------------------------------------------------------------------
@pytest.mark.parametrize("seed", range(3))
def test_lane_matches_device_exactly(seed):
    lane = ShardedLane()
    g = gnm_random_graph(300, 900, seed=seed)
    ids, frag, lv = lane.solve(g)
    ref = minimum_spanning_forest(g, backend="device")
    assert np.array_equal(ids, ref.edge_ids)
    assert np.unique(frag).size == ref.num_components
    assert verify_result(ref, oracle="scipy").ok


def test_lane_disconnected_and_trivial():
    lane = ShardedLane()
    g = Graph.from_edges(9, [(0, 1, 1), (1, 2, 2), (3, 4, 1), (4, 5, 5)])
    ids, frag, _ = lane.solve(g)
    assert len(ids) == 4
    assert np.unique(frag).size == 5
    ids0, frag0, lv0 = lane.solve(Graph.from_edges(3, []))
    assert ids0.size == 0 and frag0.size == 3 and lv0 == 0


def test_lane_oversize_parity():
    lane = ShardedLane()
    g = _oversize_graph(5)
    ids, _, _ = lane.solve(g)
    ref = minimum_spanning_forest(g, backend="device")
    assert np.array_equal(ids, ref.edge_ids)


# ----------------------------------------------------------------------
# Residency: warm re-solve is dispatch-only
# ----------------------------------------------------------------------
def test_warm_resolve_skips_restaging():
    lane = ShardedLane()
    g = gnm_random_graph(400, 1600, seed=3)
    ids1, _, _ = lane.solve(g)
    stage_spans = sum(1 for e in BUS.events() if e[1] == "lane.stage")
    ids2, _, _ = lane.solve(g)
    assert np.array_equal(ids1, ids2)
    c = BUS.counters()
    assert c.get("lane.resident.hit") == 1
    assert c.get("lane.resident.miss") == 1
    assert c.get("lane.reshard.skipped") == 1
    # No second lane.stage span: the m-sized arrays were not re-staged.
    assert sum(1 for e in BUS.events() if e[1] == "lane.stage") == stage_spans


def test_residency_lru_bounded():
    lane = ShardedLane(capacity=2)
    graphs = [gnm_random_graph(200, 600, seed=s) for s in range(3)]
    for g in graphs:
        lane.solve(g)
    assert len(lane.resident_digests()) == 2
    assert BUS.counters().get("lane.resident.evict") == 1
    # The evicted (oldest) graph restages on its next solve.
    lane.solve(graphs[0])
    assert BUS.counters().get("lane.resident.miss") == 4


# ----------------------------------------------------------------------
# Donated incremental updates
# ----------------------------------------------------------------------
def test_update_donated_reweight_parity():
    lane = ShardedLane()
    g = gnm_random_graph(400, 1600, seed=7)
    lane.solve(g)
    edges = _edges(g)
    edges[10][2] += 1  # small rank shift: the donated-scatter regime
    g2 = Graph.from_edges(g.num_nodes, edges)
    ids, _, _ = lane.update(g.digest(), g2)
    ref = minimum_spanning_forest(g2, backend="device")
    assert np.array_equal(ids, ref.edge_ids)
    c = BUS.counters()
    assert c.get("lane.update.donated") == 1
    assert c.get("lane.restage") is None
    # The refresh + solve path never re-staged the m-sized arrays.
    assert c.get("lane.reshard.skipped") == 1  # the post-refresh solve
    assert lane.resident_digests() == [g2.digest()]


def test_update_delete_and_heavy_insert_parity():
    lane = ShardedLane()
    g = gnm_random_graph(400, 1600, seed=8)
    lane.solve(g)
    # Heavy insert: lands at the top of the rank order, shifting nothing.
    edges = _edges(g) + [[0, 399, 10_000]]
    g2 = Graph.from_edges(g.num_nodes, edges)
    ids, _, _ = lane.update(g.digest(), g2)
    assert np.array_equal(
        ids, minimum_spanning_forest(g2, backend="device").edge_ids
    )
    # Delete the edge again (same bucket, small shift).
    g3 = Graph.from_edges(g.num_nodes, _edges(g2)[:-1])
    ids3, _, _ = lane.update(g2.digest(), g3)
    assert np.array_equal(
        ids3, minimum_spanning_forest(g3, backend="device").edge_ids
    )
    assert BUS.counters().get("lane.update.donated") == 2


def test_update_wide_delta_restages_exactly():
    """Reversing the weight order moves (almost) every rank slot: past
    max_update_frac the refresh restages in full — still exact, counted
    ``lane.restage``."""
    lane = ShardedLane()
    g = gnm_random_graph(400, 1600, seed=9)
    lane.solve(g)
    top = int(g.w.max()) + 1
    edges = [[u, v, top - w] for u, v, w in _edges(g)]  # rank order reversed
    g2 = Graph.from_edges(g.num_nodes, edges)
    ids, _, _ = lane.update(g.digest(), g2)
    assert np.array_equal(
        ids, minimum_spanning_forest(g2, backend="device").edge_ids
    )
    c = BUS.counters()
    assert c.get("lane.restage") == 1
    assert c.get("lane.update.donated") is None


def test_update_bucket_change_drops_residency():
    lane = ShardedLane()
    g = gnm_random_graph(100, 300, seed=4)
    lane.solve(g)
    # Enough inserts to cross the edge bucket: residency is dropped, the
    # next solve stages cold (and is still exact).
    extra = [[i, i + 50, 1000 + i] for i in range(40)]
    g2 = Graph.from_edges(g.num_nodes, _edges(g) + extra)
    assert lane.pad_shape(g2.num_nodes, g2.num_edges) != lane.pad_shape(
        g.num_nodes, g.num_edges
    )
    ids, _, _ = lane.update(g.digest(), g2)
    assert np.array_equal(
        ids, minimum_spanning_forest(g2, backend="device").edge_ids
    )
    assert BUS.counters().get("lane.update.dropped") == 1


def test_refresh_while_entry_in_use_keeps_old_buffers_valid():
    """A refresh racing an in-flight solve must not donate the buffers
    that solve still holds: with the entry checked out, the non-donating
    scatter runs and the old device arrays stay readable."""
    lane = ShardedLane()
    g = gnm_random_graph(300, 900, seed=13)
    lane.solve(g)
    digest = g.digest()
    res = lane._get_resident(digest, checkout=True)  # simulated reader
    try:
        edges = _edges(g)
        edges[5][2] += 1
        g2 = Graph.from_edges(g.num_nodes, edges)
        assert lane.refresh_resident(digest, g2)
        # The reader's buffers were not consumed.
        assert np.asarray(res.ra).shape[0] == res.m_pad
        ids, _, _ = lane.solve(g2)
        assert np.array_equal(
            ids, minimum_spanning_forest(g2, backend="device").edge_ids
        )
    finally:
        lane._release(digest)
    assert not lane._in_use


def test_concurrent_distinct_solves_are_admission_bounded():
    """Distinct oversize misses must queue on the lane's admission bound
    (staging included), and all land exactly."""
    lane = ShardedLane(max_in_flight=2)
    graphs = [gnm_random_graph(250, 800, seed=40 + s) for s in range(4)]
    results = [None] * len(graphs)

    def solve_one(i):
        results[i] = lane.solve(graphs[i])[0]

    threads = [
        threading.Thread(target=solve_one, args=(i,))
        for i in range(len(graphs))
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(60)
    for g, ids in zip(graphs, results):
        assert ids is not None
        assert np.array_equal(
            ids, minimum_spanning_forest(g, backend="device").edge_ids
        )
    assert not lane._in_use  # every checkout released


# ----------------------------------------------------------------------
# Warmup: zero request-time compiles on the oversize path
# ----------------------------------------------------------------------
def test_precompile_covers_request_shapes():
    _reset_shape_ledger()
    lane = ShardedLane()
    lane.precompile(2000, 6000)
    miss0 = BUS.counters().get("compile.miss", 0)
    g = gnm_random_graph(2000, 6000, seed=11)
    ids, _, _ = lane.solve(g)
    assert np.array_equal(
        ids, minimum_spanning_forest(g, backend="device").edge_ids
    )
    assert BUS.counters().get("compile.miss", 0) == miss0
    assert BUS.counters().get("compile.warmup", 0) >= 1


def test_warmup_plan_mesh_buckets():
    from distributed_ghs_implementation_tpu.batch.warmup import (
        WarmupPlan,
        merge_plans,
        parse_mesh_bucket_list,
        plan_from_flags,
        run_warmup,
    )

    assert parse_mesh_bucket_list("70000x140000, 500x1500,70000x140000") == [
        (70000, 140000), (500, 1500),
    ]
    plan = plan_from_flags(mesh_buckets="500x1500")
    assert plan.mesh_buckets == ((500, 1500),)
    merged = merge_plans(
        WarmupPlan(buckets=((64, 256),), lanes=4),
        WarmupPlan(mesh_buckets=((500, 1500),)),
    )
    assert merged.buckets == ((64, 256),)
    assert merged.mesh_buckets == ((500, 1500),)
    # Without a lane the mesh buckets are declared-but-unreachable.
    report = run_warmup(WarmupPlan(mesh_buckets=((500, 1500),)))
    assert report["mesh_skipped"] == 1 and report["mesh_warmed"] == 0
    report = run_warmup(
        WarmupPlan(mesh_buckets=((500, 1500),)), lane=ShardedLane()
    )
    assert report["mesh_warmed"] == 1


# ----------------------------------------------------------------------
# Scheduler routing + the store contract
# ----------------------------------------------------------------------
def test_scheduler_routes_oversize_to_lane_and_caches():
    from distributed_ghs_implementation_tpu.serve.service import MSTService

    svc = MSTService(batch_lanes=4, sharded_lane=True)
    g = _oversize_graph(21)
    req = {"op": "solve", "num_nodes": g.num_nodes, "edges": _edges(g),
           "slo_class": "oversize"}
    r1 = svc.handle(req)
    assert r1["ok"] and r1["backend"] == "sharded_lane"
    assert r1["source"] == "solved"
    assert r1["total_weight"] == minimum_spanning_forest(g).total_weight
    assert BUS.counters().get("serve.route.sharded_lane") == 1
    # Route arg on the serve.solve span (bypass vs sharded_lane).
    routes = [
        e[6]["route"] for e in BUS.events()
        if e[1] == "serve.solve" and e[6] and "route" in e[6]
    ]
    assert routes == ["sharded_lane"]

    # Satellite (serve/store.py): the sharded result is cached under the
    # same Graph.digest() contract — the second query is a store hit with
    # NO second mesh dispatch.
    spans = _lane_solve_spans()
    r2 = svc.handle(req)
    assert r2["cached"] is True and r2["source"] == "cache"
    assert _lane_solve_spans() == spans


def test_sharded_result_disk_cache_round_trip(tmp_path):
    """Oversize miss -> sharded solve -> a RESTARTED service (fresh memory,
    shared disk store) answers the repeat from disk, zero mesh dispatches."""
    from distributed_ghs_implementation_tpu.serve.service import MSTService

    disk = str(tmp_path / "store")
    g = _oversize_graph(22)
    req = {"op": "solve", "num_nodes": g.num_nodes, "edges": _edges(g)}
    svc1 = MSTService(sharded_lane=True, disk_dir=disk)
    r1 = svc1.handle(req)
    assert r1["ok"] and r1["backend"] == "sharded_lane"

    svc2 = MSTService(sharded_lane=True, disk_dir=disk)
    spans = _lane_solve_spans()
    r2 = svc2.handle(req)
    assert r2["ok"] and r2["cached"] is True
    assert r2["total_weight"] == r1["total_weight"]
    assert _lane_solve_spans() == spans
    assert BUS.counters().get("serve.store.disk_hit", 0) >= 1


def test_service_update_migrates_residency():
    from distributed_ghs_implementation_tpu.serve.service import MSTService

    svc = MSTService(sharded_lane=True)
    g = _oversize_graph(23)
    r1 = svc.handle(
        {"op": "solve", "num_nodes": g.num_nodes, "edges": _edges(g)}
    )
    assert r1["ok"]
    assert svc.sharded_lane.resident_digests() == [r1["digest"]]
    up = svc.handle({
        "op": "update", "digest": r1["digest"],
        "updates": [{"kind": "insert", "u": 0, "v": 1, "w": 10_000}],
    })
    assert up["ok"]
    # Residency followed the digest chain without a mesh solve.
    assert svc.sharded_lane.resident_digests() == [up["digest"]]


def test_scheduler_bypass_without_lane():
    from distributed_ghs_implementation_tpu.serve.service import MSTService

    svc = MSTService()
    g = _oversize_graph(24)
    r = svc.handle(
        {"op": "solve", "num_nodes": g.num_nodes, "edges": _edges(g)}
    )
    assert r["ok"] and r["backend"].startswith("supervised/")
    assert BUS.counters().get("serve.route.bypass") == 1
    routes = [
        e[6]["route"] for e in BUS.events()
        if e[1] == "serve.solve" and e[6] and "route" in e[6]
    ]
    assert routes == ["bypass"]


def test_solve_batch_peels_oversize_to_lane():
    from distributed_ghs_implementation_tpu.serve.service import MSTService

    svc = MSTService(batch_lanes=4, sharded_lane=True)
    small = [gnm_random_graph(128, 400, seed=s) for s in range(3)]
    big = _oversize_graph(25)
    results = svc.scheduler.solve_batch(small + [big])
    assert [r.backend for r, _ in results[:3]] == ["batch/fused"] * 3
    assert results[3][0].backend == "sharded_lane"
    for g, (r, _) in zip(small + [big], results):
        assert np.array_equal(
            r.edge_ids, minimum_spanning_forest(g).edge_ids
        )


# ----------------------------------------------------------------------
# Two-class priority gate
# ----------------------------------------------------------------------
def test_priority_gate_bulk_yields_to_interactive():
    from distributed_ghs_implementation_tpu.serve.scheduler import PriorityGate

    gate = PriorityGate(max_pause_s=5.0)
    order = []
    release = threading.Event()

    def interactive_work():
        with gate.interactive():
            release.wait(2.0)
            order.append("interactive")

    t = threading.Thread(target=interactive_work)
    t.start()
    time.sleep(0.05)  # the interactive solve is pending now

    def bulk_work():
        gate.checkpoint()  # must pause until interactive lands
        order.append("bulk")

    b = threading.Thread(target=bulk_work)
    b.start()
    time.sleep(0.1)
    assert order == []  # bulk is paused at the checkpoint
    release.set()
    t.join(5)
    b.join(5)
    assert order == ["interactive", "bulk"]
    assert BUS.counters().get("serve.gate.yields", 0) >= 1


def test_priority_gate_pause_is_bounded():
    from distributed_ghs_implementation_tpu.serve.scheduler import PriorityGate

    gate = PriorityGate(max_pause_s=0.2)
    hang = threading.Event()

    def hung_interactive():
        with gate.interactive():
            hang.wait(5.0)  # a pending interactive solve that never finishes

    t = threading.Thread(target=hung_interactive)
    t.start()
    time.sleep(0.05)
    try:
        t0 = time.monotonic()
        gate.checkpoint()
        assert 0.15 <= time.monotonic() - t0 < 2.0  # bounded, not deadlocked
    finally:
        hang.set()
        t.join(5)


def test_priority_gate_checkpoint_skips_own_registration():
    """A bulk solve reached from INSIDE an interactive context (a stream
    window's resolve escape hatch routing to the sharded lane) must not
    wait out its own pending registration at every checkpoint — while
    still yielding to other threads' interactive work."""
    from distributed_ghs_implementation_tpu.serve.scheduler import PriorityGate

    gate = PriorityGate(max_pause_s=5.0)
    with gate.interactive():
        t0 = time.monotonic()
        gate.checkpoint()  # own registration: must not stall max_pause_s
        assert time.monotonic() - t0 < 1.0
    # Another thread's interactive work still pauses a bulk checkpoint —
    # and its exit releases the checkpoint, not max_pause_s expiry. Runs
    # OUTSIDE the interactive block above: an open registration on this
    # thread is not exempt for the bulk thread and would pin the
    # checkpoint to the full max_pause_s.
    release = threading.Event()
    entered = threading.Event()

    def other():
        with gate.interactive():
            entered.set()
            release.wait(5.0)

    t = threading.Thread(target=other)
    t.start()
    assert entered.wait(5.0)
    gate2 = threading.Event()

    def bulk():
        gate.checkpoint()
        gate2.set()

    b = threading.Thread(target=bulk)
    b.start()
    time.sleep(0.1)
    assert not gate2.is_set()  # the other thread's pending still gates
    t_release = time.monotonic()
    release.set()
    t.join(5)
    b.join(5)
    assert gate2.is_set()
    # Released by the interactive exit (50ms poll + margin), far below
    # the 5s max_pause ceiling a vacuous wait-out would take.
    assert time.monotonic() - t_release < 2.0


# ----------------------------------------------------------------------
# Fleet: oversize digests land on mesh-owning workers
# ----------------------------------------------------------------------
def test_router_oversize_constants_match_policy():
    """Drift guard: the router's jax-free mirror of the admission ceiling
    must equal the real BatchPolicy defaults."""
    from distributed_ghs_implementation_tpu.batch.policy import BatchPolicy
    from distributed_ghs_implementation_tpu.fleet import router

    policy = BatchPolicy()
    assert router._OVERSIZE_NODE_BUCKET == policy.max_bucket_nodes
    assert router._OVERSIZE_EDGE_BUCKET == policy.max_bucket_edges


def test_router_request_oversize_predicate():
    from distributed_ghs_implementation_tpu.fleet.router import (
        _request_oversize,
    )

    assert _request_oversize(
        {"op": "solve", "num_nodes": 70_000, "edges": [[0, 1, 1]]}
    )
    assert not _request_oversize(
        {"op": "solve", "num_nodes": 128, "edges": [[0, 1, 1]]}
    )
    assert not _request_oversize({"op": "update", "digest": "x"})
    assert not _request_oversize({"op": "solve", "graph_path": "g.npz"})


def test_fleet_routes_oversize_to_lane_workers():
    """Echo fleet: worker 0 owns the lane; every oversize digest must land
    there while small digests spread over the full ring."""
    from distributed_ghs_implementation_tpu.fleet.router import (
        FleetConfig,
        FleetRouter,
    )

    config = FleetConfig(
        workers=3, test_echo=True, sharded_lane_workers=1,
        ready_timeout_s=30.0,
    )
    with FleetRouter(config) as router:
        stats = router.handle({"op": "stats"})
        assert stats["workers"]["0"]["lane"] is True
        assert stats["workers"]["1"]["lane"] is False
        oversize_workers = set()
        for i in range(6):
            r = router.handle({
                "op": "solve", "num_nodes": 70_000,
                "edges": [[0, i + 1, i + 1]],
            })
            assert r["ok"]
            oversize_workers.add(r["worker"])
        assert oversize_workers == {0}
        small_workers = set()
        for i in range(24):
            r = router.handle({
                "op": "solve", "num_nodes": 16, "edges": [[0, i % 15 + 1, i]],
            })
            assert r["ok"]
            small_workers.add(r["worker"])
        assert len(small_workers) > 1  # the full ring still spreads
    assert BUS.counters().get("fleet.route.sharded_lane", 0) >= 6
