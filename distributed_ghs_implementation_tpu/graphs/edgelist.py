"""Dense edge-list graph container feeding the batched MST kernel.

The reference keeps graphs as NetworkX objects plus per-vertex adjacency dicts
(``/root/reference/ghs_implementation.py:417-429``,
``ghs_implementation_mpi.py:74-92``). Here the canonical form is three NumPy
arrays ``(u, v, w)`` of undirected edges, from which we derive the *interleaved
directed layout* the kernel consumes: undirected edge ``e = (a, b, w)`` becomes
directed slots ``2e = a->b`` and ``2e+1 = b->a``. The interleaving makes the
global directed-slot order agree with undirected-edge order, so per-fragment
minimum-outgoing-edge tie-breaking by directed slot id is a *total order on
undirected edges* — the property that guarantees Borůvka hooking only ever
forms 2-cycles (deterministic, race-free merges; contrast the reference's
symmetric-CONNECT dedup workarounds at ``ghs_implementation_mpi.py:217-230``).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Iterable, Tuple

import numpy as np

# Weights are int64 on the host for exactness; the device kernel picks int32 or
# float32 per graph (int weights below 2**31 stay exact end to end).
_INT_DTYPES = (np.int8, np.int16, np.int32, np.int64, np.uint8, np.uint16, np.uint32)


@dataclasses.dataclass(frozen=True)
class Graph:
    """An undirected weighted graph as dense arrays.

    Attributes:
      num_nodes: vertex count ``n``; vertices are ``0..n-1``.
      u, v, w: parallel arrays of undirected edges (``u[i] < v[i]`` after
        canonicalization). ``w`` is int64 or float64 on the host.
    """

    num_nodes: int
    u: np.ndarray
    v: np.ndarray
    w: np.ndarray

    @property
    def num_edges(self) -> int:
        return int(self.u.shape[0])

    @property
    def is_integer_weighted(self) -> bool:
        return self.w.dtype.kind in "iu"

    @property
    def total_weight(self):
        return self.w.sum()

    # ------------------------------------------------------------------
    # Construction
    # ------------------------------------------------------------------
    @staticmethod
    def from_edges(
        num_nodes: int,
        edges: Iterable[Tuple[int, int, float]] | np.ndarray,
        *,
        dedup: bool = True,
    ) -> "Graph":
        """Build from an iterable of ``(u, v, weight)`` triples.

        Self-loops are dropped; parallel edges keep the minimum weight when
        ``dedup`` (an MST never uses the heavier duplicate). Mirrors the edge
        list accepted by the reference driver
        (``ghs_implementation.py:416-429``).

        Generator input streams through bounded chunks instead of one
        ``list(edges)`` materialization — peak host memory is one chunk of
        Python triples plus the arrays, not the whole deck twice. Chunked
        conversion keeps the single-pass dtype semantics (any float triple
        upcasts the whole array, exactly as one ``np.asarray`` would), so
        digests are unchanged vs the materializing path (tested).
        """
        if isinstance(edges, np.ndarray):
            arr = edges
        elif isinstance(edges, (list, tuple)):
            arr = np.asarray(edges)
        else:
            import itertools

            it = iter(edges)
            blocks = []
            while True:
                block = list(itertools.islice(it, 65536))
                if not block:
                    break
                blocks.append(np.asarray(block))
            arr = (
                blocks[0]
                if len(blocks) == 1
                else np.concatenate(blocks)
                if blocks
                else np.empty((0, 3))
            )
        if arr.size == 0:
            e = np.zeros(0, dtype=np.int64)
            return Graph(int(num_nodes), e, e.copy(), np.zeros(0, dtype=np.int64))
        if arr.ndim != 2 or arr.shape[1] != 3:
            raise ValueError(f"edges must be (m, 3) triples, got shape {arr.shape}")
        u = arr[:, 0].astype(np.int64)
        v = arr[:, 1].astype(np.int64)
        wcol = arr[:, 2]
        if np.all(wcol == np.floor(wcol)):
            w = wcol.astype(np.int64)
        else:
            w = wcol.astype(np.float64)
        return Graph.from_arrays(num_nodes, u, v, w, dedup=dedup)

    @staticmethod
    def from_arrays(
        num_nodes: int,
        u: np.ndarray,
        v: np.ndarray,
        w: np.ndarray,
        *,
        dedup: bool = True,
    ) -> "Graph":
        """Build from parallel arrays; canonicalizes, drops loops, dedups."""
        num_nodes = int(num_nodes)
        u = np.asarray(u)
        v = np.asarray(v)
        w = np.asarray(w)
        if (
            min(u.min(initial=0), v.min(initial=0)) < 0
            or max(u.max(initial=-1), v.max(initial=-1)) >= num_nodes
        ):
            raise ValueError("edge endpoint out of range")
        lo = np.minimum(u, v).astype(np.int64)
        hi = np.maximum(u, v).astype(np.int64)
        keep = lo != hi  # drop self-loops
        lo, hi, w = lo[keep], hi[keep], w[keep]
        if dedup and lo.size:
            # Keep min weight per (lo, hi) pair: stable sort by (lo, hi, w).
            order = np.lexsort((w, hi, lo))
            lo, hi, w = lo[order], hi[order], w[order]
            first = np.ones(lo.size, dtype=bool)
            first[1:] = (lo[1:] != lo[:-1]) | (hi[1:] != hi[:-1])
            lo, hi, w = lo[first], hi[first], w[first]
        if w.dtype.kind in "iu":
            w = w.astype(np.int64)
        else:
            w = w.astype(np.float64)
        return Graph(num_nodes, lo, hi, w)

    @staticmethod
    def from_networkx(g) -> "Graph":
        """Convert a ``networkx.Graph`` with ``weight`` edge attributes."""
        edges = [(a, b, d.get("weight", 1)) for a, b, d in g.edges(data=True)]
        return Graph.from_edges(g.number_of_nodes(), edges)

    # ------------------------------------------------------------------
    # Identity
    # ------------------------------------------------------------------
    @functools.cached_property
    def _digest(self) -> str:
        import hashlib

        h = hashlib.sha256()
        h.update(np.int64(self.num_nodes).tobytes())
        h.update(self.w.dtype.char.encode())  # int 5 vs float 5.0 differ
        h.update(np.ascontiguousarray(self.u).tobytes())
        h.update(np.ascontiguousarray(self.v).tobytes())
        h.update(np.ascontiguousarray(self.w).tobytes())
        return h.hexdigest()

    def digest(self) -> str:
        """Stable content hash over ``(num_nodes, u, v, w)`` — hex sha256.

        Construction canonicalizes edges (``u < v``, sorted, deduped), so any
        two :class:`Graph` instances describing the same weighted edge set
        share a digest regardless of input order. This is the ONE identity
        both the serve result cache (``serve/store.py``) and checkpoint
        fingerprints (``utils/checkpoint.py``) key on; computed once per
        instance (cached).
        """
        return self._digest

    def digest_words(self) -> np.ndarray:
        """:meth:`digest` as four int64 words — the array form checkpoint
        fingerprints and disk-cache entries embed (one decode, one place)."""
        return np.frombuffer(bytes.fromhex(self._digest), dtype=np.int64).copy()

    # ------------------------------------------------------------------
    # Binary wire codec (fleet/framing.py B-frames, docs/FLEET.md)
    # ------------------------------------------------------------------
    def to_wire(self) -> dict:
        """This graph as a binary request fragment: ``num_nodes`` /
        ``num_edges`` / ``digest`` as plain JSON fields (everything a
        router needs — routing key, oversize bucket — stays in the
        B-frame *header*) plus ``u``/``v``/``w`` as raw little-endian
        sections. The canonical arrays go onto the wire as-is, so the
        receiver's :meth:`from_wire` digest is byte-identical to ours."""
        from distributed_ghs_implementation_tpu.fleet.framing import (
            SECTIONS_KEY,
            WireSections,
        )

        return {
            "num_nodes": self.num_nodes,
            "num_edges": self.num_edges,
            "digest": self.digest(),
            SECTIONS_KEY: WireSections()
            .add("u", self.u)
            .add("v", self.v)
            .add("w", self.w),
        }

    @staticmethod
    def from_wire(payload: dict) -> "Graph":
        """Rebuild from a binary request fragment — ``np.frombuffer``
        views over the received frame buffer, zero copies, zero
        per-edge Python objects.

        The arrays are trusted to be canonical only after a vectorized
        check (in-range, ``u < v``, strictly lexsorted — what
        :meth:`from_arrays` would produce); a non-canonical sender falls
        back through :meth:`from_arrays` so the digest always names the
        canonical form, exactly as the JSON ``edges`` path does. The
        fast-path arrays are read-only views; every consumer treats
        ``Graph`` arrays as immutable already (staging copies to
        device)."""
        from distributed_ghs_implementation_tpu.fleet.framing import (
            SECTIONS_KEY,
        )

        secs = payload.get(SECTIONS_KEY)
        if secs is None or not all(n in secs for n in ("u", "v", "w")):
            raise ValueError(
                "binary graph payload needs u/v/w sections "
                f"(got {getattr(secs, 'names', None)})"
            )
        num_nodes = int(payload["num_nodes"])
        u, v, w = secs.array("u"), secs.array("v"), secs.array("w")
        if u.dtype != np.int64 or v.dtype != np.int64:
            raise ValueError(
                f"endpoint sections must be int64, got {u.dtype}/{v.dtype}"
            )
        if w.dtype not in (np.dtype(np.int64), np.dtype(np.float64)):
            raise ValueError(f"weight section must be i8/f8, got {w.dtype}")
        if not (u.shape == v.shape == w.shape):
            raise ValueError(
                f"section lengths disagree: {u.size}/{v.size}/{w.size}"
            )
        m = u.size
        canonical = m == 0 or (
            int(u.min()) >= 0
            and int(v.max()) < num_nodes
            and bool(np.all(u < v))
            and bool(
                np.all(
                    (u[1:] > u[:-1]) | ((u[1:] == u[:-1]) & (v[1:] > v[:-1]))
                )
            )
        )
        if canonical:
            if m == 0:
                e = np.zeros(0, dtype=np.int64)
                return Graph(num_nodes, e, e.copy(), w.astype(w.dtype))
            return Graph(num_nodes, u, v, w)
        return Graph.from_arrays(num_nodes, u, v, w)

    # ------------------------------------------------------------------
    # Views
    # ------------------------------------------------------------------
    def edge_triples(self) -> list:
        """Edges as ``[(u, v, w), ...]`` with Python scalars."""
        return [
            (int(a), int(b), (int(c) if self.is_integer_weighted else float(c)))
            for a, b, c in zip(self.u, self.v, self.w)
        ]

    def to_networkx(self):
        import networkx as nx

        g = nx.Graph()
        g.add_nodes_from(range(self.num_nodes))
        g.add_weighted_edges_from(self.edge_triples())
        return g

    def device_weight_dtype(self) -> np.dtype:
        """Pick the on-device weight dtype (int32 when exact, else float32)."""
        if self.is_integer_weighted and (
            self.w.size == 0
            or (self.w.min() > np.iinfo(np.int32).min and self.w.max() < np.iinfo(np.int32).max)
        ):
            return np.dtype(np.int32)
        return np.dtype(np.float32)

    def directed_arrays(
        self, *, pad_to: int | None = None
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Interleaved directed layout ``(src, dst, w)`` of length ``2m``.

        Slot ``2e`` is ``u[e]->v[e]``, slot ``2e+1`` is ``v[e]->u[e]``; the
        undirected id of slot ``s`` is ``s >> 1``. Optionally right-pads to
        ``pad_to`` slots with inert self-edges of sentinel weight so sharded
        runs get equal per-device shapes without recompilation.
        """
        m = self.num_edges
        n2 = 2 * m
        wd = self.device_weight_dtype()
        sentinel = np.iinfo(wd).max if wd.kind == "i" else np.inf
        size = n2 if pad_to is None else int(pad_to)
        if size < n2:
            raise ValueError(f"pad_to={pad_to} < 2*m={n2}")
        src = np.zeros(size, dtype=np.int32)
        dst = np.zeros(size, dtype=np.int32)
        w = np.full(size, sentinel, dtype=wd)
        src[0:n2:2] = self.u
        dst[0:n2:2] = self.v
        src[1:n2:2] = self.v
        dst[1:n2:2] = self.u
        w[0:n2:2] = self.w.astype(wd)
        w[1:n2:2] = self.w.astype(wd)
        # Padding rows are self-edges (src == dst == 0): never outgoing, inert.
        return src, dst, w

    def rank_arrays(
        self, *, pad_edges_to: int | None = None, pad_ranks_to: int | None = None
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
        """Rank-based device layout: ``(src, dst, rank, ra, rb)``.

        ``rank[e]`` (over undirected edges) is the position of edge ``e`` in
        the total order ``(weight, edge id)`` — ascending, all-distinct. The
        device kernel selects each fragment's minimum outgoing edge with ONE
        ``segment_min`` over ranks (weights never reach the device; any weight
        dtype collapses to int32 ranks on the host). ``ra[r], rb[r]`` are the
        endpoints of the rank-``r`` edge, for recovering the far-side fragment
        with n-sized gathers. ``src/dst`` are directed slots carrying
        ``rank[slot >> 1]`` in ``rank``; pads are inert (self-edges, sentinel
        rank). Use :meth:`edge_id_of_rank` to map chosen ranks back to edges.
        """
        m = self.num_edges
        order = self._rank_order  # sort by (w, edge id)
        rank_of_edge = np.empty(m, dtype=np.int64)
        rank_of_edge[order] = np.arange(m)
        e2 = 2 * m
        e_size = e2 if pad_edges_to is None else int(pad_edges_to)
        m_size = m if pad_ranks_to is None else int(pad_ranks_to)
        if e_size < e2 or m_size < m:
            raise ValueError("pad sizes smaller than graph")
        src = np.zeros(e_size, dtype=np.int32)
        dst = np.zeros(e_size, dtype=np.int32)
        rank = np.full(e_size, np.iinfo(np.int32).max, dtype=np.int32)
        src[0:e2:2] = self.u
        dst[0:e2:2] = self.v
        src[1:e2:2] = self.v
        dst[1:e2:2] = self.u
        rank[0:e2:2] = rank_of_edge
        rank[1:e2:2] = rank_of_edge
        ra, rb = self.rank_endpoints(pad_to=m_size)
        return src, dst, rank, ra, rb

    def rank_endpoints(self, *, pad_to: int | None = None) -> Tuple[np.ndarray, np.ndarray]:
        """``(ra, rb)``: endpoints of the rank-``r`` edge, indexed by rank,
        optionally right-padded with zeros (inert — pads are never chosen).

        These arrays sit on prep's pre-transfer critical path (the big
        host->device stagings cannot start before they exist), so the native
        path fuses gather + int32 cast + pad into one pass."""
        m = self.num_edges
        size = m if pad_to is None else int(pad_to)
        if size < m:
            raise ValueError("pad_to smaller than edge count")
        order = self._rank_order
        if m:
            try:
                from distributed_ghs_implementation_tpu.graphs import native

                if native.native_available():
                    return native.rank_endpoints_i32_native(
                        order, self.u, self.v, size
                    )
            except Exception:  # noqa: BLE001 — any native issue -> fallback
                pass
        ra = np.zeros(size, dtype=np.int32)
        rb = np.zeros(size, dtype=np.int32)
        ra[:m] = self.u[order]
        rb[:m] = self.v[order]
        return ra, rb

    @functools.cached_property
    def _rank_order(self) -> np.ndarray:
        """Edge ids sorted by ``(weight, edge id)`` — computed once per graph.

        Integer weights take a native stable counting sort (O(m + range); the
        lexsort here is the single biggest host-prep cost at RMAT-24 scale);
        everything else falls back to NumPy lexsort.
        """
        if self.is_integer_weighted and self.num_edges:
            try:
                from distributed_ghs_implementation_tpu.graphs import native

                order = native.rank_order_counting_native(self.w)
                if order is not None:
                    return order
            except Exception:  # noqa: BLE001 — any native issue -> fallback
                pass
        # Stable argsort by weight == lexsort by (weight, edge id), at about
        # half the cost (single key) — matters for float weights, which skip
        # the native counting sort.
        return np.argsort(self.w, kind="stable")

    @functools.cached_property
    def first_ranks(self) -> np.ndarray:
        """Per-vertex minimum incident rank (INT32_MAX when isolated).

        This is GHS/Boruvka level 1 precomputed: at the identity partition
        every incident edge is outgoing, so each vertex's minimum outgoing
        edge is simply its minimum-rank incident edge — one O(m) host pass
        instead of an edge-sized device reduction.
        """
        int32_max = np.iinfo(np.int32).max
        m = self.num_edges
        order = self._rank_order
        ra = self.u[order]
        rb = self.v[order]
        try:
            from distributed_ghs_implementation_tpu.graphs import native

            if native.native_available():
                return native.first_rank_native(self.num_nodes, ra, rb)
        except Exception:  # noqa: BLE001
            pass
        # NumPy fallback: first occurrence of each vertex in rank-interleaved
        # endpoint order is its minimum incident rank.
        arr = np.empty(2 * m, dtype=np.int64)
        arr[0::2] = ra
        arr[1::2] = rb
        verts, first_pos = np.unique(arr, return_index=True)
        out = np.full(self.num_nodes, int32_max, dtype=np.int32)
        out[verts] = (first_pos // 2).astype(np.int32)
        return out

    @functools.cached_property
    def first_ranks64(self) -> np.ndarray:
        """:attr:`first_ranks` with int64 ranks and an INT64_MAX isolated
        sentinel — for the sharded ``rank64`` path, whose rank space can
        exceed 2^31 (ranks are positions in the (weight, edge id) order, so
        they outgrow int32 long before vertex ids do)."""
        int64_max = np.iinfo(np.int64).max
        m = self.num_edges
        order = self._rank_order
        ra = self.u[order]
        rb = self.v[order]
        try:
            from distributed_ghs_implementation_tpu.graphs import native

            if native.native_available():
                return native.first_rank64_native(self.num_nodes, ra, rb)
        except Exception:  # noqa: BLE001
            pass
        arr = np.empty(2 * m, dtype=np.int64)
        arr[0::2] = ra
        arr[1::2] = rb
        verts, first_pos = np.unique(arr, return_index=True)
        out = np.full(self.num_nodes, int64_max, dtype=np.int64)
        out[verts] = first_pos // 2
        return out

    @functools.cached_property
    def ell_buckets(self):
        """Degree-bucketed ELL layout for the dense-reduction kernel.

        Directed adjacency (CSR order) split by degree class ``(W/2, W]`` into
        2-D blocks of width ``W`` (powers of two): per bucket,
        ``(verts[Vb], dst[Vb, W], rank[Vb, W])`` with inert padding (self
        destination, sentinel rank) and ``Vb`` padded to a power of two
        (pad rows use vertex 0 with all-sentinel ranks — harmless under the
        scatter-min that collects per-vertex minima). Rows within a vertex are
        in rank order. On TPU this turns the per-vertex minimum-outgoing-edge
        search into a dense row ``min`` — measured ~2x over the flat
        scatter-based ``segment_min`` (scatter costs ~8 ns/element on v5e vs
        ~2 ns/element for gathers; the dense reduce is ~free).
        """
        n, m = self.num_nodes, self.num_edges
        int32_max = np.iinfo(np.int32).max
        order = self._rank_order
        rank_of_edge = np.empty(m, dtype=np.int64)
        rank_of_edge[order] = np.arange(m)
        # Directed slots in CSR order with rows sorted by rank. Native path
        # (counting sort + parallel row sorts) when available — the NumPy
        # lexsort over 2m slots takes minutes at RMAT-22+ scale.
        try:
            from distributed_ghs_implementation_tpu.graphs import native

            if not native.native_available():
                raise RuntimeError
            indptr, dd, dr = native.build_rank_csr_native(
                n, self.u, self.v, rank_of_edge
            )
        except RuntimeError:
            ds = np.concatenate([self.u, self.v])
            dd = np.concatenate([self.v, self.u])
            dr = np.concatenate([rank_of_edge, rank_of_edge])
            o2 = np.lexsort((dr, ds))
            dd, dr = dd[o2], dr[o2]
            ds = ds[o2]
            indptr = np.zeros(n + 1, dtype=np.int64)
            np.add.at(indptr, ds + 1, 1)
            np.cumsum(indptr, out=indptr)
        deg = np.diff(indptr)

        def pow2(x: int) -> int:
            return 1 << max(0, int(x - 1).bit_length())

        buckets = []
        w = 1
        max_deg = int(deg.max()) if n else 0
        while w <= max(1, pow2(max_deg)):
            lo = (w >> 1) + 1 if w > 1 else 1
            sel = (deg >= lo) & (deg <= w)
            w_next = w << 1
            if sel.any():
                verts = np.nonzero(sel)[0].astype(np.int64)
                vb = len(verts)
                vb_pad = pow2(vb)
                pos = indptr[verts][:, None] + np.arange(w)[None, :]
                valid = np.arange(w)[None, :] < deg[verts][:, None]
                pos = np.where(valid, pos, 0)
                dstb = np.where(valid, dd[pos], verts[:, None]).astype(np.int32)
                rankb = np.where(valid, dr[pos], int32_max).astype(np.int32)
                if vb_pad > vb:
                    pad = vb_pad - vb
                    verts = np.concatenate([verts, np.zeros(pad, dtype=np.int64)])
                    dstb = np.vstack([dstb, np.zeros((pad, w), dtype=np.int32)])
                    rankb = np.vstack(
                        [rankb, np.full((pad, w), int32_max, dtype=np.int32)]
                    )
                buckets.append((verts.astype(np.int32), dstb, rankb))
            w = w_next
        return buckets

    def edge_id_of_rank(self, ranks: np.ndarray) -> np.ndarray:
        """Map ranks (as produced by :meth:`rank_arrays`) back to edge indices."""
        return self._rank_order[ranks]

    def csr(self) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """CSR adjacency over directed slots: ``(indptr, dst, w)`` sorted by src."""
        src, dst, w = self.directed_arrays()
        order = np.argsort(src, kind="stable")
        src, dst, w = src[order], dst[order], w[order]
        indptr = np.zeros(self.num_nodes + 1, dtype=np.int64)
        np.add.at(indptr, src + 1, 1)
        np.cumsum(indptr, out=indptr)
        return indptr, dst, w

    def degrees(self) -> np.ndarray:
        deg = np.zeros(self.num_nodes, dtype=np.int64)
        np.add.at(deg, self.u, 1)
        np.add.at(deg, self.v, 1)
        return deg


def component_labels(num_nodes: int, u: np.ndarray, v: np.ndarray) -> np.ndarray:
    """Component label per vertex of the (undirected) edge list — one
    C-speed ``scipy.sparse.csgraph`` pass. Shared by the generators'
    connectivity repair and the failure diagnostics (a Python union-find
    here would crawl at bench-scale edge counts)."""
    from scipy.sparse import coo_matrix
    from scipy.sparse.csgraph import connected_components

    adj = coo_matrix(
        (np.ones(u.size, dtype=np.int8), (u, v)),
        shape=(num_nodes, num_nodes),
    )
    _, labels = connected_components(adj, directed=False)
    return labels.astype(np.int64)
